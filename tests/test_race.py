"""Race-detector tests (framework extension — the reference has no
sanitizer, SURVEY.md §5; we verify the fused kernels' signal protocols
with the interpreter's vector-clock detector)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import NamedSharding, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.ops.common import comm_params, resolve_interpret
from triton_dist_tpu.testing.race import race_check, races_were_found

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow


def test_fused_ops_race_free(mesh8, key):
    """AG-GEMM + GEMM-RS signal protocols pass the race detector."""
    from triton_dist_tpu.ops.allgather_gemm import (
        create_ag_gemm_context, ag_gemm)

    a = jax.device_put(jax.random.normal(key, (16, 32), jnp.float32),
                       NamedSharding(mesh8, P("tp")))
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (32, 64), jnp.float32),
        NamedSharding(mesh8, P(None, "tp")))
    with race_check():
        out = ag_gemm(a, b, create_ag_gemm_context(mesh8, "tp"),
                      impl="pallas")
        jax.block_until_ready(out)


def _racy_kernel(x_ref, o_ref, send_sem, recv_sem, *, axis, world):
    """Deliberately broken: writes into the peer WITHOUT the peer waiting
    on the recv semaphore before reading — a missing-wait race."""
    me = lax.axis_index(axis)
    right = lax.rem(me + 1, world)
    dl.barrier_all(axis)
    dl.remote_copy(x_ref.at[:], o_ref.at[:], right, send_sem, recv_sem,
                   axis=axis).start()
    # BUG: read o_ref before waiting for the incoming DMA.
    o_ref[:] = o_ref[:] * 1.0
    dl.remote_copy(x_ref.at[:], o_ref.at[:], me, send_sem, recv_sem,
                   axis=axis).wait_recv()
    dl.remote_copy(x_ref.at[:], o_ref.at[:], right, send_sem, recv_sem,
                   axis=axis).wait_send()


def test_detector_catches_missing_wait(mesh8):
    world = 8
    kernel = functools.partial(_racy_kernel, axis="tp", world=world)

    def body(xs):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(xs.shape, xs.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.DMA,
                            pltpu.SemaphoreType.DMA],
            compiler_params=comm_params(collective_id=9, world=world),
            interpret=resolve_interpret(None),
        )(xs)

    x = jax.device_put(jnp.ones((16, 128), jnp.float32),
                       NamedSharding(mesh8, P("tp")))
    with pytest.raises(AssertionError, match="race"):
        with race_check():
            out = jax.shard_map(body, mesh=mesh8, in_specs=P("tp"),
                                out_specs=P("tp"), check_vma=False)(x)
            jax.block_until_ready(out)
    assert races_were_found()
