"""Model-level sequence parallelism (DenseLLM mode="sp").

The reference's SP story stops at layer wrappers (SpFlashDecodeLayer,
AG-attention kernels, test_sp_decode_attn.py); here the whole model
runs sequence-parallel — (B, S, H) activations with S sharded, ring
attention prefill, distributed split-KV flash decode over the
sequence-sharded cache — and must agree with the head-sharded TP paths
it coexists with:

  * prefill logits == the xla full-attention golden;
  * Engine greedy serving (sp prefill + sp decode) == plain serving;
  * training in mode="sp" (+ remat) == xla-mode losses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow

from triton_dist_tpu.models import (
    DenseLLM, Engine, KVCacheManager, ModelConfig, make_train_step)


def _cfg(dtype=jnp.float32):
    return ModelConfig(
        hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        vocab_size=64, max_position_embeddings=64, dtype=dtype)


@pytest.fixture()
def sp_setup(devices):
    mesh = Mesh(np.array(devices).reshape(1, 8), ("tp", "sp"))
    cfg = _cfg()
    model = DenseLLM(cfg, mesh=mesh, axis="tp", sp_axis="sp",
                     impl="pallas", fwd_mode="sp")
    params = model.init(jax.random.PRNGKey(0))
    return mesh, cfg, model, params


def test_sp_prefill_matches_golden(sp_setup):
    mesh, cfg, model, params = sp_setup
    b, s = 2, 32
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                             cfg.vocab_size, jnp.int32)
    kv_sp = KVCacheManager(cfg.num_hidden_layers, b, 64,
                           cfg.num_key_value_heads, cfg.head_dim,
                           mesh=mesh, axis="sp", seq_shard=True,
                           dtype=cfg.dtype)
    kv_tp = KVCacheManager(cfg.num_hidden_layers, b, 64,
                           cfg.num_key_value_heads, cfg.head_dim,
                           mesh=mesh, axis="tp", dtype=cfg.dtype)
    lo_sp, caches = jax.jit(
        lambda p, i, c: model.forward(p, i, c, 0, mode="sp"))(
        params, ids, kv_sp.init())
    lo_x, _ = jax.jit(
        lambda p, i, c: model.forward(p, i, c, 0, mode="xla"))(
        params, ids, kv_tp.init())
    np.testing.assert_allclose(np.asarray(lo_sp), np.asarray(lo_x),
                               rtol=2e-4, atol=2e-4)
    # The sp cache now holds the prefix: a decode step must work on it.
    tok = jnp.argmax(lo_sp[:, -1], -1).astype(jnp.int32)[:, None]
    lo_d, _ = jax.jit(
        lambda p, i, c: model.forward(p, i, c, s, mode="sp"))(
        params, tok, caches)
    assert bool(jnp.isfinite(lo_d).all())


def test_sp_serve_matches_plain(sp_setup):
    """Greedy generation through sp prefill + sp decode equals the
    head-sharded engine's tokens on the same weights."""
    mesh, cfg, model, params = sp_setup
    b, s, gen = 2, 16, 6
    ids = jax.random.randint(jax.random.PRNGKey(2), (b, s), 0,
                             cfg.vocab_size, jnp.int32)
    eng_sp = Engine(model, batch=b, max_seq=64, prefill_mode="sp",
                    decode_mode="sp")
    eng_tp = Engine(model, batch=b, max_seq=64, prefill_mode="xla",
                    decode_mode="xla_ar")
    out_sp = np.asarray(eng_sp.serve(params, ids, gen))
    out_tp = np.asarray(eng_tp.serve(params, ids, gen))
    np.testing.assert_array_equal(out_sp, out_tp)


def test_sp_paged_serving_matches(sp_setup):
    """Engine(paged=True): prefill scatters into allocated pages,
    decode runs the paged distributed flash decode — greedy tokens
    equal both the contiguous sp engine and the plain engine. A second
    serve() call reuses freed slots (admission per call)."""
    mesh, cfg, model, params = sp_setup
    b, s, gen = 2, 16, 6
    ids = jax.random.randint(jax.random.PRNGKey(4), (b, s), 0,
                             cfg.vocab_size, jnp.int32)
    eng_pg = Engine(model, batch=b, max_seq=64, prefill_mode="sp",
                    decode_mode="sp", paged=True, page_size=4)
    eng_sp = Engine(model, batch=b, max_seq=64, prefill_mode="sp",
                    decode_mode="sp")
    eng_tp = Engine(model, batch=b, max_seq=64, prefill_mode="xla",
                    decode_mode="xla_ar")
    out_pg = np.asarray(eng_pg.serve(params, ids, gen))
    np.testing.assert_array_equal(out_pg,
                                  np.asarray(eng_sp.serve(params, ids, gen)))
    np.testing.assert_array_equal(out_pg,
                                  np.asarray(eng_tp.serve(params, ids, gen)))
    # Second call: rows were owned; the engine frees + re-admits.
    np.testing.assert_array_equal(np.asarray(eng_pg.serve(params, ids, gen)),
                                  out_pg)


def test_sp_chunked_prefill_matches_single_shot(sp_setup):
    """Chunked prefill (cache-aware ring: q_offset/kv_len) must produce
    the same final logits and caches as the single-shot prefill."""
    mesh, cfg, model, params = sp_setup
    b, s = 2, 32
    ids = jax.random.randint(jax.random.PRNGKey(9), (b, s), 0,
                             cfg.vocab_size, jnp.int32)
    kv = KVCacheManager(cfg.num_hidden_layers, b, 64,
                        cfg.num_key_value_heads, cfg.head_dim, mesh=mesh,
                        axis="sp", seq_shard=True, dtype=cfg.dtype)
    lo_full, caches_full = model.forward(params, ids, kv.init(), 0,
                                         mode="sp")
    # two chunks of 16
    lo_a, caches = model.forward(params, ids[:, :16], kv.init(), 0,
                                 mode="sp")
    lo_b, caches = model.forward(params, ids[:, 16:], caches, 16,
                                 mode="sp")
    np.testing.assert_allclose(np.asarray(lo_a),
                               np.asarray(lo_full[:, :16]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(lo_b),
                               np.asarray(lo_full[:, 16:]),
                               rtol=2e-4, atol=2e-4)
    for (ka, va), (kf, vf) in zip(caches, caches_full):
        np.testing.assert_allclose(np.asarray(ka)[:, :s],
                                   np.asarray(kf)[:, :s],
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(va)[:, :s],
                                   np.asarray(vf)[:, :s],
                                   rtol=1e-5, atol=1e-5)
    # decode continues identically from either cache
    tok = ids[:, -1:]
    dec_a, _ = model.forward(params, tok, caches, s, mode="sp")
    dec_b, _ = model.forward(params, tok, caches_full, s, mode="sp")
    np.testing.assert_allclose(np.asarray(dec_a), np.asarray(dec_b),
                               rtol=2e-4, atol=2e-4)


def test_sp_engine_rejects_mixed_modes(sp_setup):
    mesh, cfg, model, params = sp_setup
    with pytest.raises(AssertionError, match="prefill and decode"):
        Engine(model, batch=2, max_seq=64, prefill_mode="sp",
               decode_mode="gemm_ar")


def test_sp_2d_tp_x_sp(devices):
    """2-D tp×sp: heads shard over tp inside the sequence ring
    (SpAttentionContext.head_axis); prefill logits, greedy serving,
    and training all agree with the 1-axis paths."""
    mesh = Mesh(np.array(devices).reshape(2, 4), ("tp", "sp"))
    cfg = _cfg()
    model = DenseLLM(cfg, mesh=mesh, axis="tp", sp_axis="sp",
                     impl="pallas", fwd_mode="sp")
    params = model.init(jax.random.PRNGKey(0))
    b, s = 2, 16
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                             cfg.vocab_size, jnp.int32)

    kv_sp = KVCacheManager(cfg.num_hidden_layers, b, 64,
                           cfg.num_key_value_heads, cfg.head_dim,
                           mesh=mesh, axis="sp", seq_shard=True,
                           dtype=cfg.dtype)
    kv_tp = KVCacheManager(cfg.num_hidden_layers, b, 64,
                           cfg.num_key_value_heads, cfg.head_dim,
                           mesh=mesh, axis="tp", dtype=cfg.dtype)
    lo_sp, _ = jax.jit(
        lambda p, i, c: model.forward(p, i, c, 0, mode="sp"))(
        params, ids, kv_sp.init())
    lo_x, _ = jax.jit(
        lambda p, i, c: model.forward(p, i, c, 0, mode="xla"))(
        params, ids, kv_tp.init())
    np.testing.assert_allclose(np.asarray(lo_sp), np.asarray(lo_x),
                               rtol=2e-4, atol=2e-4)

    eng_sp = Engine(model, batch=b, max_seq=64, prefill_mode="sp",
                    decode_mode="sp")
    eng_tp = Engine(model, batch=b, max_seq=64, prefill_mode="xla",
                    decode_mode="xla_ar")
    out_tp = np.asarray(eng_tp.serve(params, ids, 5))
    np.testing.assert_array_equal(
        np.asarray(eng_sp.serve(params, ids, 5)), out_tp)
    # Paged serving composes with the 2-D grid too (head-replicated
    # pools; the head gather folds into the cache-layout constraint).
    eng_pg = Engine(model, batch=b, max_seq=64, prefill_mode="sp",
                    decode_mode="sp", paged=True, page_size=4)
    np.testing.assert_array_equal(
        np.asarray(eng_pg.serve(params, ids, 5)), out_tp)

    losses = {}
    for mode in ("xla", "sp"):
        step, init_opt = make_train_step(model, mode=mode, donate=False)
        p, o = params, init_opt(params)
        seq = []
        for _ in range(2):
            p, o, m = step(p, o, {"input_ids": ids})
            seq.append(float(m["loss"]))
        losses[mode] = seq
    np.testing.assert_allclose(losses["sp"], losses["xla"], rtol=2e-4)


def test_sp_training(sp_setup):
    """mode="sp" trains (ring attention differentiates natively) with
    the same losses as the xla-mode step, including under remat."""
    mesh, cfg, model, params = sp_setup
    batch = {"input_ids": jax.random.randint(
        jax.random.PRNGKey(3), (2, 16), 0, cfg.vocab_size, jnp.int32)}

    losses = {}
    for mode, remat in (("xla", False), ("sp", False), ("sp", True)):
        step, init_opt = make_train_step(model, mode=mode, remat=remat,
                                         donate=False)
        p, o = params, init_opt(params)
        seq = []
        for _ in range(3):
            p, o, m = step(p, o, batch)
            seq.append(float(m["loss"]))
            assert np.isfinite(seq[-1])
        assert seq[-1] < seq[0], (mode, remat, seq)
        losses[(mode, remat)] = seq
    np.testing.assert_allclose(losses[("sp", False)], losses[("xla", False)],
                               rtol=2e-4)
    np.testing.assert_allclose(losses[("sp", True)], losses[("sp", False)],
                               rtol=1e-6)


def _moe_cfg():
    return ModelConfig(
        hidden_size=32, intermediate_size=0, moe_intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, vocab_size=64,
        max_position_embeddings=64, dtype=jnp.float32, num_experts=8,
        num_experts_per_tok=2)


def test_moe_sp_forward_matches_tp(devices):
    """Qwen3MoE model-level SP (row-local MoE FFN, zero FFN
    collectives): prefill logits and greedy serving equal the
    head-sharded tp paths on the same weights."""
    from triton_dist_tpu.models import Qwen3MoE
    mesh = Mesh(np.array(devices).reshape(1, 8), ("tp", "sp"))
    cfg = _moe_cfg()
    model = Qwen3MoE(cfg, mesh=mesh, axis="tp", sp_axis="sp",
                     impl="pallas", fwd_mode="sp")
    params = model.init(jax.random.PRNGKey(0))
    b, s = 2, 16
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                             cfg.vocab_size, jnp.int32)
    kv_sp = KVCacheManager(cfg.num_hidden_layers, b, 64,
                           cfg.num_key_value_heads, cfg.head_dim,
                           mesh=mesh, axis="sp", seq_shard=True,
                           dtype=cfg.dtype)
    kv_tp = KVCacheManager(cfg.num_hidden_layers, b, 64,
                           cfg.num_key_value_heads, cfg.head_dim,
                           mesh=mesh, axis="tp", dtype=cfg.dtype)
    lo_sp, caches = model.forward(params, ids, kv_sp.init(), 0, mode="sp")
    lo_x, _ = model.forward(params, ids, kv_tp.init(), 0, mode="xla")
    np.testing.assert_allclose(np.asarray(lo_sp), np.asarray(lo_x),
                               rtol=2e-4, atol=2e-4)
    # decode over the seq-sharded cache
    tok = jnp.argmax(lo_sp[:, -1], -1).astype(jnp.int32)[:, None]
    dec_sp, _ = model.forward(params, tok, caches, s, mode="sp")
    assert bool(jnp.isfinite(dec_sp).all())


def test_moe_sp_serving_matches_plain(devices):
    from triton_dist_tpu.models import Qwen3MoE
    mesh = Mesh(np.array(devices).reshape(1, 8), ("tp", "sp"))
    cfg = _moe_cfg()
    model = Qwen3MoE(cfg, mesh=mesh, axis="tp", sp_axis="sp",
                     impl="pallas", fwd_mode="sp")
    params = model.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0,
                             cfg.vocab_size, jnp.int32)
    eng_sp = Engine(model, batch=2, max_seq=64, prefill_mode="sp",
                    decode_mode="sp")
    eng_tp = Engine(model, batch=2, max_seq=64, prefill_mode="xla",
                    decode_mode="xla_ar")
    np.testing.assert_array_equal(
        np.asarray(eng_sp.serve(params, ids, 6)),
        np.asarray(eng_tp.serve(params, ids, 6)))
