"""Serving roundtrip test (reference model_server/chat demo, SURVEY §2.7)."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
from triton_dist_tpu.serving import ChatClient, ModelServer

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow


def test_server_client_roundtrip(mesh8, key):
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=32, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = model.init(key)
    eng = Engine(model, batch=1, max_seq=16, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    srv = ModelServer(eng, params, port=0).start()
    try:
        client = ChatClient(srv.host, srv.port)
        resp = client.generate_ids([[1, 2, 3]], gen_len=4)
        assert "tokens" in resp and len(resp["tokens"][0]) == 4
        assert resp["latency_ms"] > 0
        # server result must equal a direct engine call
        direct = eng.serve(params, jnp.asarray([[1, 2, 3]], jnp.int32), 4)
        np.testing.assert_array_equal(np.asarray(resp["tokens"]),
                                      np.asarray(direct)[:, 3:])
        # malformed request → error response, server stays alive
        bad = client.generate_ids("nonsense", gen_len=1)
        assert "error" in bad
        ok = client.generate_ids([[5]], gen_len=2)
        assert "tokens" in ok
        client.close()
    finally:
        srv.stop()


def test_server_streams_oversized_batches(mesh8, key):
    """More prompts than engine rows route through serve_stream and
    match solo generations (continuous batching behind the protocol)."""
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=32, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = model.init(key)
    eng = Engine(model, batch=2, max_seq=16, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    srv = ModelServer(eng, params, port=0).start()
    prompts = [[1, 2], [3, 4, 5], [6], [7, 8]]
    try:
        client = ChatClient(srv.host, srv.port)
        resp = client.generate_ids(prompts, gen_len=3)
        assert len(resp["tokens"]) == len(prompts)
        solo = Engine(model, batch=1, max_seq=16, prefill_mode="xla_ar",
                      decode_mode="gemm_ar")
        for prompt, row in zip(prompts, resp["tokens"]):
            want = np.asarray(solo.serve(
                params, jnp.asarray([prompt], jnp.int32), 3))[0]
            np.testing.assert_array_equal(np.asarray(row),
                                          want[len(prompt):])
        client.close()
    finally:
        srv.stop()


def test_server_concurrent_clients(mesh8, key):
    """Two clients in flight at once: the ThreadingTCPServer accepts
    both, the generation lock serializes engine access, and each client
    gets exactly its own answer (reference model_server is likewise a
    threaded socket server)."""
    import threading

    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=32, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = model.init(key)
    eng = Engine(model, batch=1, max_seq=16, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    srv = ModelServer(eng, params, port=0).start()
    results: dict[int, dict] = {}
    prompts = {0: [1, 2, 3], 1: [7, 8]}
    try:
        def worker(i):
            c = ChatClient(srv.host, srv.port)
            results[i] = c.generate_ids([prompts[i]], gen_len=3)
            c.close()

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in prompts]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        for i, prompt in prompts.items():
            assert "tokens" in results[i], results[i]
            direct = np.asarray(eng.serve(
                params, jnp.asarray([prompt], jnp.int32), 3))[0]
            np.testing.assert_array_equal(
                np.asarray(results[i]["tokens"][0]),
                direct[len(prompt):])
    finally:
        srv.stop()


def test_server_ragged_prompts(mesh8, key):
    """Variable-length prompt rows route through serve_ragged and match
    solo generations (greedy)."""
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=32, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = model.init(key)
    eng = Engine(model, batch=2, max_seq=16, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    srv = ModelServer(eng, params, port=0).start()
    try:
        client = ChatClient(srv.host, srv.port)
        resp = client.generate_ids([[1, 2, 3, 4], [9]], gen_len=3)
        assert len(resp["tokens"]) == 2
        solo = Engine(model, batch=1, max_seq=16, prefill_mode="xla_ar",
                      decode_mode="gemm_ar")
        for row, prompt in zip(resp["tokens"], [[1, 2, 3, 4], [9]]):
            direct = np.asarray(solo.serve(
                params, jnp.asarray([prompt], jnp.int32), 3))[0]
            np.testing.assert_array_equal(np.asarray(row),
                                          direct[len(prompt):])
        client.close()
    finally:
        srv.stop()
