"""SP attention tests: distributed flash-decode (split-KV + cross-rank
combine) and sequence-parallel prefill attention (AG-KV and ring),
vs a full-attention golden.

Mirrors the reference's test_sp_decode_attn.py /
test_sp_ag_attention_{intra,inter}_node.py (SURVEY.md §4) on the
single-process 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow

from triton_dist_tpu.ops.flash_decode import (
    create_flash_decode_context, gqa_fwd_batch_decode,
    gqa_fwd_batch_decode_paged)
from triton_dist_tpu.ops.sp_attention import (
    create_sp_attention_context, sp_ag_attention, zigzag_reorder,
    zigzag_restore)


def attention_golden(q, k, v, causal, q_offset=0):
    """Brute-force fp32 GQA attention. q: (B, Sq, Hq, D), k/v: (B, T, Hkv, D).
    Query i is at absolute position q_offset + i."""
    b, sq, hq, d = q.shape
    t, hkv = k.shape[1], k.shape[2]
    g = hq // hkv
    qf = np.asarray(q, np.float32).reshape(b, sq, hkv, g, d)
    kf = np.asarray(k, np.float32)
    vf = np.asarray(v, np.float32)
    scores = np.einsum("bskgd,btkd->bkgst", qf, kf) / np.sqrt(d)
    if causal:
        mask = (q_offset + np.arange(sq))[:, None] >= np.arange(t)[None, :]
        scores = np.where(mask[None, None, None], scores, -np.inf)
    scores -= scores.max(-1, keepdims=True)
    p = np.exp(scores)
    p /= p.sum(-1, keepdims=True)
    out = np.einsum("bkgst,btkd->bskgd", p, vf)
    return out.reshape(b, sq, hq, d)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_flash_decode(mesh8, impl, key):
    b, hq, hkv, d, t = 2, 8, 4, 32, 64
    kv_len = 41  # partial cache: spans rank 0..5 of the 8-way shard
    q = jax.random.normal(key, (b, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, hkv, d), jnp.float32)
    ctx = create_flash_decode_context(mesh8, "tp")
    ks = jax.device_put(k, NamedSharding(mesh8, P(None, "tp")))
    vs = jax.device_put(v, NamedSharding(mesh8, P(None, "tp")))
    out = gqa_fwd_batch_decode(q, ks, vs, jnp.int32(kv_len), ctx, impl=impl)
    ref = attention_golden(q[:, None], k[:, :kv_len], v[:, :kv_len],
                           causal=False)[:, 0]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_flash_decode_single_rank_kv(mesh8, key):
    """kv_len entirely inside rank 0's shard — other ranks contribute 0."""
    b, hq, hkv, d, t = 1, 4, 2, 16, 64
    kv_len = 5
    q = jax.random.normal(key, (b, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(3), (b, t, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(4), (b, t, hkv, d), jnp.float32)
    ctx = create_flash_decode_context(mesh8, "tp")
    ks = jax.device_put(k, NamedSharding(mesh8, P(None, "tp")))
    vs = jax.device_put(v, NamedSharding(mesh8, P(None, "tp")))
    out = gqa_fwd_batch_decode(q, ks, vs, jnp.int32(kv_len), ctx,
                               impl="xla")
    ref = attention_golden(q[:, None], k[:, :kv_len], v[:, :kv_len],
                           causal=False)[:, 0]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("kv_len", [41, 128, 3])
def test_flash_decode_tiled(mesh8, kv_len, key):
    """Tiled split-KV variant (KV streamed from HBM in t_blk tiles with
    online softmax) vs the dense golden — VERDICT r1 item 2 gate."""
    b, hq, hkv, d, t = 2, 8, 4, 32, 128   # t_loc = 16 per rank
    q = jax.random.normal(key, (b, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, hkv, d), jnp.float32)
    ctx = create_flash_decode_context(mesh8, "tp", variant="tiled", t_blk=8)
    ks = jax.device_put(k, NamedSharding(mesh8, P(None, "tp")))
    vs = jax.device_put(v, NamedSharding(mesh8, P(None, "tp")))
    out = gqa_fwd_batch_decode(q, ks, vs, jnp.int32(kv_len), ctx,
                               impl="pallas")
    ref = attention_golden(q[:, None], k[:, :kv_len], v[:, :kv_len],
                           causal=False)[:, 0]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_flash_decode_paged(mesh8, key):
    """Paged pool + block_table indirection matches the dense golden
    (reference block_table paged decode, flash_decode.py:136,:203)."""
    w, b, hq, hkv, d = 8, 2, 8, 4, 32
    page, n_pages = 8, 4                  # t_loc = 32/rank, t = 256
    t = w * page * n_pages
    kv_len = 177
    rng = np.random.default_rng(0)
    k = rng.standard_normal((b, t, hkv, d), np.float32)
    v = rng.standard_normal((b, t, hkv, d), np.float32)
    q = jax.random.normal(key, (b, hq, d), jnp.float32)

    # Scatter each device's slice into a shuffled local pool.
    p_loc = b * n_pages + 3               # a few spare slots
    pool_k = np.zeros((w * p_loc, page, hkv, d), np.float32)
    pool_v = np.zeros((w * p_loc, page, hkv, d), np.float32)
    table = np.zeros((w, b, n_pages), np.int32)
    for r in range(w):
        slots = rng.permutation(p_loc)[:b * n_pages].reshape(b, n_pages)
        for bi in range(b):
            for pi in range(n_pages):
                lo = r * page * n_pages + pi * page
                pool_k[r * p_loc + slots[bi, pi]] = k[bi, lo:lo + page]
                pool_v[r * p_loc + slots[bi, pi]] = v[bi, lo:lo + page]
        table[r] = slots

    ctx = create_flash_decode_context(mesh8, "tp")
    sh = NamedSharding(mesh8, P("tp"))
    out = gqa_fwd_batch_decode_paged(
        q, jax.device_put(jnp.asarray(pool_k), sh),
        jax.device_put(jnp.asarray(pool_v), sh),
        jax.device_put(jnp.asarray(table), sh), jnp.int32(kv_len), ctx)
    ref = attention_golden(q[:, None], k[:, :kv_len], v[:, :kv_len],
                           causal=False)[:, 0]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("impl", ["xla", "ring", "pallas", "ag_pallas"])
@pytest.mark.parametrize("causal", [True, False])
def test_sp_prefill_attention(mesh8, impl, causal, key):
    b, s, hq, hkv, d = 2, 64, 4, 2, 16
    q = jax.random.normal(key, (b, s, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(5), (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(6), (b, s, hkv, d), jnp.float32)
    ctx = create_sp_attention_context(mesh8, "tp", causal=causal)
    sh = NamedSharding(mesh8, P(None, "tp"))
    out = sp_ag_attention(jax.device_put(q, sh), jax.device_put(k, sh),
                          jax.device_put(v, sh), ctx, impl=impl)
    ref = attention_golden(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=3e-4, atol=3e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_sp_ulysses_attention(mesh8, causal, key):
    """All-to-all head parallelism (absent in the reference): exact match
    with the dense golden — no online-softmax merging error at all."""
    b, s, hq, hkv, d = 2, 64, 16, 8, 16
    q = jax.random.normal(key, (b, s, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(5), (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(6), (b, s, hkv, d), jnp.float32)
    ctx = create_sp_attention_context(mesh8, "tp", causal=causal)
    sh = NamedSharding(mesh8, P(None, "tp"))
    out = sp_ag_attention(jax.device_put(q, sh), jax.device_put(k, sh),
                          jax.device_put(v, sh), ctx, impl="ulysses")
    ref = attention_golden(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=3e-4, atol=3e-4)


def test_sp_ulysses_rejects_indivisible_heads(mesh8, key):
    b, s, hq, hkv, d = 1, 64, 4, 2, 16
    q = jax.random.normal(key, (b, s, hq, d), jnp.float32)
    k = jax.random.normal(key, (b, s, hkv, d), jnp.float32)
    ctx = create_sp_attention_context(mesh8, "tp")
    sh = NamedSharding(mesh8, P(None, "tp"))
    with pytest.raises(AssertionError, match="divisible"):
        sp_ag_attention(jax.device_put(q, sh), jax.device_put(k, sh),
                        jax.device_put(k, sh), ctx, impl="ulysses")


@pytest.mark.parametrize("causal", [True, False])
def test_sp_fused_multi_tile(mesh8, causal, key):
    """Fused kernel with several KV subtiles and q tiles per chunk
    (n_sub=2, n_q=2) — exercises the double-buffered subtile DMA loop."""
    from triton_dist_tpu.ops.sp_attention import sp_ag_attention_fused
    b, s, hq, hkv, d = 1, 256, 4, 2, 16
    q = jax.random.normal(key, (b, s, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(8), (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(9), (b, s, hkv, d), jnp.float32)
    ctx = create_sp_attention_context(mesh8, "tp", causal=causal)
    sh = NamedSharding(mesh8, P(None, "tp"))
    out = sp_ag_attention_fused(jax.device_put(q, sh),
                                jax.device_put(k, sh),
                                jax.device_put(v, sh), ctx,
                                sq_blk=16, t_sub=16)
    ref = attention_golden(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=3e-4, atol=3e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_sp_fused_q_groups(mesh8, causal, key):
    """Tiny vmem_budget forces MULTIPLE resident q-groups: group 0
    drives the ring, later groups replay the landed workspace with no
    further communication — results must equal the golden exactly as in
    the single-group case."""
    import dataclasses as _dc
    from triton_dist_tpu.ops.sp_attention import sp_ag_attention_fused
    b, s, hq, hkv, d = 1, 256, 4, 2, 16
    q = jax.random.normal(key, (b, s, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(8), (b, s, hkv, d),
                          jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(9), (b, s, hkv, d),
                          jnp.float32)
    ctx = _dc.replace(
        create_sp_attention_context(mesh8, "tp", causal=causal),
        vmem_budget=20 * 1024)   # n_res = 1 of 4 slabs → 4 groups
    sh = NamedSharding(mesh8, P(None, "tp"))
    out = sp_ag_attention_fused(jax.device_put(q, sh),
                                jax.device_put(k, sh),
                                jax.device_put(v, sh), ctx,
                                sq_blk=16, t_sub=16)
    ref = attention_golden(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=3e-4, atol=3e-4)


def test_zigzag_roundtrip(key):
    x = jax.random.normal(key, (2, 32, 3), jnp.float32)
    z = zigzag_reorder(x, world=4)
    r = zigzag_restore(z, world=4)
    np.testing.assert_array_equal(np.asarray(r), np.asarray(x))
    assert not np.array_equal(np.asarray(z), np.asarray(x))


def test_zigzag_balances_causal_work():
    """The point of the zigzag layout (reference intra-node schedule):
    pairing chunk r with chunk 2w-1-r equalizes causal attention work
    (sum of key positions attended) across shards."""
    w, s = 4, 64
    c = s // (2 * w)
    # derive each shard's positions from the actual implementation
    layout = np.asarray(zigzag_reorder(jnp.arange(s)[None], world=w,
                                       seq_axis=1))[0]
    shards = layout.reshape(w, 2 * c)
    work = [int((shards[r] + 1).sum()) for r in range(w)]
    assert len(set(work)) == 1, f"unbalanced shard work: {work}"
    # contiguous sharding is maximally unbalanced by contrast
    contig = [sum(p + 1 for p in range(r * 2 * c, (r + 1) * 2 * c))
              for r in range(w)]
    assert len(set(contig)) == w


def test_sp_flash_decode_layer_e2e(mesh8, key):
    """SpFlashDecodeLayer: append tokens one-by-one into the
    sequence-sharded cache, decode at each step, match dense attention
    over the live prefix (reference sp_flash_decode_layer.py)."""
    from triton_dist_tpu.layers.sp_flash_decode import SpFlashDecodeLayer
    b, hq, hkv, d, t = 2, 8, 2, 16, 16
    layer = SpFlashDecodeLayer(b, t, hkv, d, mesh=mesh8, axis="tp",
                               dtype=jnp.float32, impl="pallas")
    cache = layer.init_cache()
    ks = jax.random.normal(key, (b, t, hkv, d), jnp.float32)
    vs = jax.random.normal(jax.random.PRNGKey(7), (b, t, hkv, d),
                           jnp.float32)
    q = jax.random.normal(jax.random.PRNGKey(8), (b, hq, d), jnp.float32)

    for pos in range(t):
        cache = layer.append(cache, ks[:, pos:pos + 1], vs[:, pos:pos + 1],
                             pos)
        if pos in (3, t - 1):
            got = layer(q, cache, jnp.int32(pos + 1))
            ref = attention_golden(q[:, None], ks[:, :pos + 1],
                                   vs[:, :pos + 1], causal=False
                                   )[:, 0]
            np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-3,
                                       atol=2e-3, err_msg=f"pos {pos}")


def test_sp_attention_layer_wrapper(mesh8, key):
    """SpAttentionLayer binds ctx+impl; matches the functional entry."""
    from triton_dist_tpu.layers.sp_flash_decode import SpAttentionLayer
    b, s, hq, hkv, d = 1, 64, 4, 2, 16
    q = jax.random.normal(key, (b, s, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d),
                          jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d),
                          jnp.float32)
    sh = NamedSharding(mesh8, P(None, "tp"))
    layer = SpAttentionLayer(mesh=mesh8, axis="tp", causal=True,
                             impl="ring")
    got = layer(jax.device_put(q, sh), jax.device_put(k, sh),
                jax.device_put(v, sh))
    ref = attention_golden(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-4, atol=3e-4)
