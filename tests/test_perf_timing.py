"""Regression tests for the r5 noise-robust timing path
(``perf_func_chained``'s non-tunneled branch) that root-caused the
"2.845x same-matmul XLA baseline split" (VERDICT r4 weak-1/next-2,
diagnosis in docs/perf.md): on the 1-core bench host a SINGLE sub-ms
timing window under background load spread 3-4.4x, so the two world=1
XLA baselines — measured in different child processes minutes apart —
could disagree by 2.8x with no compiler asymmetry at all.

The fix escalates the chain until a window carries >= 20 ms of signal
and takes the min of 5 windows. Reference analog: the reference's
perf_func also uses warmup + many-iteration loops around CUDA events
(/root/reference/python/triton_dist/utils.py:274)."""

import time

import jax
import jax.numpy as jnp
import pytest

from triton_dist_tpu.runtime.utils import perf_func_chained


def test_min_of_windows_rejects_transient_load():
    """A load burst confined to the first ~150 ms must not inflate the
    result: min-of-5 windows picks the clean later windows. Under the
    pre-r5 single-window behavior this test fails (the one window eats
    the whole burst)."""
    base = jnp.ones((8, 8), jnp.float32)

    t_start = time.perf_counter()

    def step(x):
        # ~0.4 ms of real work per step...
        te = time.perf_counter() + 4e-4
        while time.perf_counter() < te:
            pass
        # ...plus a 10 ms "background preemption" per step, but only
        # during the first 150 ms (a bursty neighbor, not constant).
        if time.perf_counter() - t_start < 0.15:
            time.sleep(10e-3)
        return x + 1.0

    ms = perf_func_chained(step, base, (2, 6))
    # Clean-step cost is ~0.4 ms (+ small jax overhead); the burst
    # would push a burst-covered window to >10 ms/step.
    assert ms < 3.0, f"min-of-windows failed to reject the burst: {ms} ms"


def test_window_escalation_reaches_signal_floor():
    """Sub-20-ms initial windows must escalate the chain: 6 steps of a
    ~50 us computation is ~0.3 ms of signal, far below the floor; the
    returned per-step time must still be sane (not dominated by the
    per-call dispatch jitter a one-shot 6-step window sees)."""
    base = jnp.ones((64, 64), jnp.bfloat16)

    @jax.jit
    def step(x):
        return (x @ x).astype(jnp.bfloat16)

    ms = perf_func_chained(step, base, (2, 6))
    assert 0.0 < ms < 5.0


@pytest.mark.slow
def test_world1_xla_baseline_pair_agreement():
    """The bench's two world=1 XLA baselines are the same matmul behind
    the same fold; with windowed min-of-5 timing they must agree within
    the bench's 1.5x anomaly gate (plus slack for CI neighbors). This
    is the in-CI replica of bench.py::_finalize_checks' cross-part
    gate."""
    import importlib.util
    import pathlib

    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from triton_dist_tpu.ops.allgather_gemm import (
        create_ag_gemm_context, ag_gemm)
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)

    root = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location("bench", root / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    m, k, nn = 64, 128, 128
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (m, k), jnp.float32).astype(jnp.bfloat16)
    b = jax.random.normal(key, (k, nn), jnp.float32).astype(jnp.bfloat16)

    ctx_ag = create_ag_gemm_context(mesh, "tp", interpret=None)
    ctx_rs = create_gemm_rs_context(mesh, "tp", interpret=None)
    a_ag = jax.device_put(a, NamedSharding(mesh, P("tp")))
    b_ag = jax.device_put(b, NamedSharding(mesh, P(None, "tp")))
    a_rs = jax.device_put(a, NamedSharding(mesh, P(None, "tp")))
    b_rs = jax.device_put(b, NamedSharding(mesh, P("tp")))

    t_ag = perf_func_chained(
        bench._args_step(
            lambda x, bb: bench._chain_fold(
                ag_gemm(x, bb, ctx_ag, impl="xla"), m, k), b_ag),
        a_ag, (8, 24))
    t_rs = perf_func_chained(
        bench._args_step(
            lambda x, bb: bench._chain_fold(
                gemm_rs(x, bb, ctx_rs, impl="xla"), m, k), b_rs),
        a_rs, (8, 24))
    ratio = max(t_ag, t_rs) / min(t_ag, t_rs)
    assert ratio < 1.6, (t_ag, t_rs, ratio)
