"""Regression tests for the r5 noise-robust timing path
(``perf_func_chained``'s non-tunneled branch) that root-caused the
"2.845x same-matmul XLA baseline split" (VERDICT r4 weak-1/next-2,
diagnosis in docs/perf.md): on the 1-core bench host a SINGLE sub-ms
timing window under background load spread 3-4.4x, so the two world=1
XLA baselines — measured in different child processes minutes apart —
could disagree by 2.8x with no compiler asymmetry at all.

The fix escalates the chain until a window carries >= 20 ms of signal
and takes the min of 5 windows. Reference analog: the reference's
perf_func also uses warmup + many-iteration loops around CUDA events
(/root/reference/python/triton_dist/utils.py:274)."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.runtime.utils import perf_func_chained


def test_min_of_windows_rejects_transient_load():
    """A load burst confined to the first ~150 ms must not inflate the
    result: min-of-5 windows picks the clean later windows. Under the
    pre-r5 single-window behavior this test fails (the one window eats
    the whole burst)."""
    base = jnp.ones((8, 8), jnp.float32)

    t_start = time.perf_counter()

    def step(x):
        # ~0.4 ms of real work per step...
        te = time.perf_counter() + 4e-4
        while time.perf_counter() < te:
            pass
        # ...plus a 10 ms "background preemption" per step, but only
        # during the first 150 ms (a bursty neighbor, not constant).
        if time.perf_counter() - t_start < 0.15:
            time.sleep(10e-3)
        return x + 1.0

    ms = perf_func_chained(step, base, (2, 6))
    # Clean-step cost is ~0.4 ms (+ small jax overhead); the burst
    # would push a burst-covered window to >10 ms/step.
    assert ms < 3.0, f"min-of-windows failed to reject the burst: {ms} ms"


def test_window_escalation_reaches_signal_floor():
    """Sub-20-ms initial windows must escalate the chain: 6 steps of a
    ~50 us computation is ~0.3 ms of signal, far below the floor; the
    returned per-step time must still be sane (not dominated by the
    per-call dispatch jitter a one-shot 6-step window sees)."""
    base = jnp.ones((64, 64), jnp.bfloat16)

    @jax.jit
    def step(x):
        return (x @ x).astype(jnp.bfloat16)

    ms = perf_func_chained(step, base, (2, 6))
    assert 0.0 < ms < 5.0


def test_chain_tie_is_exactly_zero_even_for_inf_nan_carry():
    """The tie term must be EXACTLY zero whatever the previous output
    held — 0*inf = nan would otherwise poison every later iteration of
    the sweep."""
    from triton_dist_tpu.runtime.utils import _chain_tie

    x = jnp.concatenate([jnp.arange(10, dtype=jnp.bfloat16),
                         jnp.asarray([-0.0, jnp.inf], jnp.bfloat16)]
                        ).reshape(3, 4)
    for bad in (jnp.float32(jnp.inf), jnp.float32(jnp.nan),
                jnp.float32(-jnp.inf), jnp.bfloat16(3.5)):
        tied = _chain_tie((x, jnp.arange(3)), bad)
        got, want = np.asarray(tied[0]), np.asarray(x)
        # bitwise equality, so -0.0 vs +0.0 is caught
        assert (got.view(np.uint16) == want.view(np.uint16)).all(), bad
        assert tied[1].dtype == jnp.int32  # non-float leaves untouched


def test_perturbed_runner_single_readback_per_window(monkeypatch):
    """On a tunneled device, a chained runner must cost ONE readback per
    timing window, not one per iteration — per-read roundtrip jitter is
    what made the round-5 on-chip autotune sweep rank a 0.89 ms ag_gemm
    config above the 0.52 ms default."""
    from triton_dist_tpu.runtime import utils

    reads = [0]
    real_mat = utils._materialize_small

    def counting_mat(tree):
        reads[0] += 1
        real_mat(tree)

    monkeypatch.setattr(utils, "_tunneled_device", lambda: True)
    monkeypatch.setattr(utils, "_materialize_small", counting_mat)

    calls = [0]
    x = jnp.ones((16, 16), jnp.float32)

    @jax.jit
    def op(v):
        return v * 2.0

    def fn(v):
        calls[0] += 1
        return op(v)

    runner = utils.make_perturbed_runner(fn, x)
    assert runner.chained
    _, ms = utils.perf_func(runner, iters=4, warmup_iters=1,
                            return_output=False)
    assert ms > 0.0
    # warmup read (1) + one read per run() window; every fn call would
    # have been read under the old per-iteration behavior. Worst case:
    # 5 escalation stages x (5 slope samples x 2 runs) reads.
    assert calls[0] > reads[0], (calls[0], reads[0])
    assert reads[0] <= 1 + 10 * 5, reads[0]


def test_perturbed_runner_downgrades_without_float_leaves(monkeypatch):
    """Integer-only inputs/outputs cannot form a chain — the runner must
    NOT advertise chained=True (perf_func would then skip the
    per-iteration readbacks that force lazy-tunnel execution), and
    perf_func(iters=1) must not divide by zero on the chained path."""
    from triton_dist_tpu.runtime import utils

    ints = jnp.arange(8)
    r_int = utils.make_perturbed_runner(lambda v: v + 1, ints)
    assert not r_int.chained

    # Float input but int output: first call downgrades, before
    # perf_func (which reads .chained after warmup) consults it.
    r_mixed = utils.make_perturbed_runner(
        lambda v: jnp.argsort(v), jnp.ones((8,), jnp.float32))
    assert r_mixed.chained
    r_mixed()
    assert not r_mixed.chained

    # iters=1 on the chained tunnel path: n1 == n2 would divide by zero.
    monkeypatch.setattr(utils, "_tunneled_device", lambda: True)
    r = utils.make_perturbed_runner(lambda v: v * 2.0,
                                    jnp.ones((4,), jnp.float32))
    _, ms = utils.perf_func(r, iters=1, warmup_iters=1,
                            return_output=False)
    assert ms > 0.0


def test_perturbed_runner_values_match_unchained(monkeypatch):
    """Chaining must not change computed values: iteration i's output
    equals fn(perturb_input(x, i)) bit-for-bit (the tie adds exact
    zero)."""
    from triton_dist_tpu.runtime import utils

    x = jnp.linspace(-2.0, 7.0, 64, dtype=jnp.bfloat16).reshape(8, 8)

    def fn(v):
        return (v @ v).astype(jnp.bfloat16)

    runner = utils.make_perturbed_runner(fn, x)
    for i in range(1, 4):
        got = runner()
        want = fn(utils.perturb_input(x, i))
        assert (np.asarray(got) == np.asarray(want)).all(), i


@pytest.mark.slow
def test_world1_xla_baseline_pair_agreement():
    """The bench's two world=1 XLA baselines are the same matmul behind
    the same fold; with windowed min-of-5 timing they must agree within
    the bench's 1.5x anomaly gate (plus slack for CI neighbors). This
    is the in-CI replica of bench.py::_finalize_checks' cross-part
    gate."""
    import importlib.util
    import pathlib

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from triton_dist_tpu.ops.allgather_gemm import (
        create_ag_gemm_context, ag_gemm)
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)

    root = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location("bench", root / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    m, k, nn = 64, 128, 128
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (m, k), jnp.float32).astype(jnp.bfloat16)
    b = jax.random.normal(key, (k, nn), jnp.float32).astype(jnp.bfloat16)

    ctx_ag = create_ag_gemm_context(mesh, "tp", interpret=None)
    ctx_rs = create_gemm_rs_context(mesh, "tp", interpret=None)
    a_ag = jax.device_put(a, NamedSharding(mesh, P("tp")))
    b_ag = jax.device_put(b, NamedSharding(mesh, P(None, "tp")))
    a_rs = jax.device_put(a, NamedSharding(mesh, P(None, "tp")))
    b_rs = jax.device_put(b, NamedSharding(mesh, P("tp")))

    t_ag = perf_func_chained(
        bench._args_step(
            lambda x, bb: bench._chain_fold(
                ag_gemm(x, bb, ctx_ag, impl="xla"), m, k), b_ag),
        a_ag, (8, 24))
    t_rs = perf_func_chained(
        bench._args_step(
            lambda x, bb: bench._chain_fold(
                gemm_rs(x, bb, ctx_rs, impl="xla"), m, k), b_rs),
        a_rs, (8, 24))
    ratio = max(t_ag, t_rs) / min(t_ag, t_rs)
    assert ratio < 1.6, (t_ag, t_rs, ratio)
