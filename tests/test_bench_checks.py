"""Unit tests for bench.py's self-consistency machinery (VERDICT r3
next-1/2): the arithmetic recheck, baseline cross-check, headline
selection, and the shared chain fold. These run the bench's CODE, not
its measurements — the orchestration end-to-end is validated by the
TDT_BENCH_CPU run (and the chip run by the driver)."""

import contextlib
import importlib.util
import io
import json
import pathlib

import jax.numpy as jnp

_ROOT = pathlib.Path(__file__).resolve().parent.parent


def _load_bench():
    spec = importlib.util.spec_from_file_location("bench", _ROOT / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench = _load_bench()


def test_finalize_checks_consistent():
    ex = {"n_devices": 1, "timing_selfcheck": {"calib_ms": 1.0},
          "ag_gemm_flops": 2.0 * 2048 * 4096 * 4096,
          "ag_gemm_pallas_ms": 1.0, "ag_gemm_xla_ms": 1.1,
          "ag_gemm_tflops": round(2.0 * 2048 * 4096 * 4096
                                  / 1e-3 / 1e12, 2),
          "gemm_rs_xla_ms": 1.2}
    bench._finalize_checks(ex)
    assert ex["arith_ok"], ex["arith_bad"]
    assert ex["baseline_anomaly"] is None
    assert ex["baseline_xla_ratio"] == round(1.2 / 1.1, 3)


def test_finalize_checks_catches_2x_tflops():
    """The r3 notes' exact failure: ms and TFLOPS apart by 2x."""
    flops = 2.0 * 2048 * 4096 * 4096
    true_tflops = flops / (0.634e-3) / 1e12
    ex = {"n_devices": 1, "ag_gemm_flops": flops,
          "ag_gemm_pallas_ms": 0.634,
          "ag_gemm_tflops": round(true_tflops / 2, 2)}  # the 2x lie
    bench._finalize_checks(ex)
    assert not ex["arith_ok"]
    assert ex["arith_bad"][0]["key"] == "ag_gemm_tflops"


def test_finalize_checks_flags_baseline_split():
    """The r3 anomaly: same-shape XLA baselines 3.5x apart."""
    ex = {"n_devices": 1, "ag_gemm_xla_ms": 0.913,
          "gemm_rs_xla_ms": 3.226,
          "timing_selfcheck": {"calib_ms": 0.9}}
    bench._finalize_checks(ex)
    assert ex["baseline_anomaly"] is not None
    assert any("same matmul" in a for a in ex["baseline_anomaly"])
    assert any("gemm_rs_xla_ms" in a for a in ex["baseline_anomaly"])


def test_select_result_fallback_order():
    assert bench._select_result({})["value"] is None
    ex = {"tp_mlp_fused_ms": 2.0, "tp_mlp_vs_xla": 1.1}
    r = bench._select_result(ex)
    assert r["metric"] == "tp_mlp_fused_ms" and r["vs_baseline"] == 1.1
    ex["ag_gemm_tflops"] = 100.0
    assert bench._select_result(ex)["metric"] == "ag_gemm_tflops"


def test_chain_fold_shapes():
    m, k = 64, 32
    # slice path (output at least (m, k))
    big = jnp.ones((64, 48), jnp.float32)
    assert bench._chain_fold(big, m, k).shape == (m, k)
    # tile path (RS output: (m/w, n))
    small = jnp.ones((8, 48), jnp.float32)
    out = bench._chain_fold(small, m, k)
    assert out.shape == (m, k) and out.dtype == jnp.bfloat16


def test_probe_failure_exits_zero_with_prior(tmp_path, monkeypatch):
    """A wedged tunnel must yield rc=0 + a JSON line carrying the prior
    checkpoint: full table under extras.prior_run, the prior headline
    surfaced under the DISTINCT prior_value field + a "(prior)"-labeled
    metric, and the top-level value staying null — a label-blind
    consumer reading metric/value must never mistake a stale number for
    a fresh run (ADVICE r5 low re-tightened the old promote-into-value
    contract)."""
    prior = tmp_path / "progress.json"
    prior.write_text(json.dumps(
        {"last_done": "ag_gemm", "ts": 0,
         "extras": {"ag_gemm_tflops": 123.0}}))
    # Drive main() in-process with the subprocess probe forced to fail
    # (hermetic stand-in for the wedged tunnel). The scan list is
    # pinned to the planted file so the repo's own live checkpoints
    # can't shadow it.
    mod = _load_bench()
    mod._probe_backend_subprocess = lambda *_a, **_k: False
    mod._fallback_scan_paths = lambda: [str(prior)]
    monkeypatch.setenv("TDT_BENCH_PROGRESS", str(prior))
    monkeypatch.delenv("TDT_BENCH_CPU", raising=False)
    monkeypatch.delenv("TDT_BENCH_ONLY", raising=False)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        mod.main()
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert out["value"] is None                  # this run measured 0
    assert out["prior_value"] == 123.0           # prior, labeled as such
    assert out["metric"] == "ag_gemm_tflops (prior)"
    assert out["from_prior_run"]["path"] == "progress.json"
    assert out["extras"]["probe_failed"] is True
    assert out["extras"]["prior_run"]["ag_gemm_tflops"] == 123.0
    assert "prior_run_age_s" in out["extras"]


def test_probe_failure_prior_ranking(tmp_path, monkeypatch):
    """The fallback picks the NEWEST checkpoint that carries measured
    metrics: a wedged run's fresh-but-empty init checkpoint must not
    mask an older run with real evidence, and among runs WITH evidence
    recency wins (review r5a-1/r5b-1)."""
    old_good = tmp_path / "old_good.json"
    old_good.write_text(json.dumps(
        {"ts": 1000.0, "extras": {"ag_gemm_tflops": 1.0,
                                  "ag_gemm_pallas_ms": 2.0}}))
    new_good = tmp_path / "new_good.json"
    new_good.write_text(json.dumps(
        {"ts": 2000.0, "extras": {"tp_mlp_fused_ms": 3.0}}))
    fresh_empty = tmp_path / "fresh_empty.json"
    fresh_empty.write_text(json.dumps(
        {"ts": 3000.0, "extras": {"checkpoint_after": "init"}}))
    mod = _load_bench()
    mod._probe_backend_subprocess = lambda *_a, **_k: False
    mod._fallback_scan_paths = lambda: [str(old_good), str(new_good),
                                        str(fresh_empty)]
    monkeypatch.delenv("TDT_BENCH_CPU", raising=False)
    monkeypatch.delenv("TDT_BENCH_ONLY", raising=False)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        mod.main()
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    # new_good wins: newest among metric-bearing; fresh_empty loses
    # despite being newest overall.
    assert out["extras"]["prior_run"] == {"tp_mlp_fused_ms": 3.0}
    assert out["extras"]["prior_run_n_measured"] == 1
    assert out["value"] is None and out["prior_value"] == 3.0
    assert out["metric"] == "tp_mlp_fused_ms (prior)"
    assert "from_prior_run" in out


def test_probe_failure_prior_ranking_prefers_tpu(tmp_path, monkeypatch):
    """Device-kind-aware fallback (VERDICT r5 fact 1): a NEWER CPU
    checkpoint must not outrank the same morning's TPU run —
    BENCH_r05.json shipped a CPU checkpoint while TPU evidence existed
    because the score was (has_measured, ts) only."""
    tpu_run = tmp_path / "tpu_run.json"
    tpu_run.write_text(json.dumps(
        {"ts": 1000.0, "extras": {"device_kind": "TPU v5 lite",
                                  "ag_gemm_tflops": 133.0}}))
    cpu_newer = tmp_path / "cpu_newer.json"
    cpu_newer.write_text(json.dumps(
        {"ts": 2000.0, "extras": {"device_kind": "cpu",
                                  "ag_gemm_tflops": 0.01}}))
    mod = _load_bench()
    mod._probe_backend_subprocess = lambda *_a, **_k: False
    mod._fallback_scan_paths = lambda: [str(tpu_run), str(cpu_newer)]
    monkeypatch.delenv("TDT_BENCH_CPU", raising=False)
    monkeypatch.delenv("TDT_BENCH_ONLY", raising=False)
    monkeypatch.delenv("TDT_BENCH_PARTS", raising=False)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        mod.main()
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert out["prior_value"] == 133.0          # the TPU run won
    assert out["extras"]["prior_run_device_kind"] == "TPU v5 lite"
    assert out["from_prior_run"]["path"] == "tpu_run.json"
    # among same-kind checkpoints recency still wins
    tpu_newer = tmp_path / "tpu_newer.json"
    tpu_newer.write_text(json.dumps(
        {"ts": 3000.0, "extras": {"device_kind": "TPU v5 lite",
                                  "ag_gemm_tflops": 140.0}}))
    mod._fallback_scan_paths = lambda: [str(tpu_run), str(cpu_newer),
                                        str(tpu_newer)]
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        mod.main()
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert out["prior_value"] == 140.0


# -- tools/bench_ops.py --regress (the quick-tier CI smoke) ----------------

def _floors_file(tmp_path):
    path = tmp_path / "BASELINE.json"
    path.write_text(json.dumps({"regression_floors": {
        "tpu": {"ag_gemm_vs_xla": 0.7, "gemm_rs_vs_xla": 0.78},
        "cpu": {"ag_gemm_vs_xla": 0.001}}}))
    return str(path)


def test_regress_passes_and_fails(tmp_path):
    from triton_dist_tpu.tools.bench_ops import (check_regression,
                                                 load_floors)
    floors = load_floors(_floors_file(tmp_path), "tpu")
    ok = {"ag_gemm_vs_xla": 1.5, "gemm_rs_vs_xla": 0.78,
          "baseline_anomaly": None}
    assert check_regression(ok, floors) == []
    bad = dict(ok, ag_gemm_vs_xla=0.5)
    fails = check_regression(bad, floors)
    assert any("ag_gemm_vs_xla" in f for f in fails)
    # a missing metric fails too — the end-to-end assertion
    missing = {"ag_gemm_vs_xla": 1.5}
    assert any("missing" in f for f in check_regression(missing, floors))


def test_regress_flags_baseline_anomaly(tmp_path):
    """baseline_anomaly is machine-checked: when the same-matmul XLA
    baselines disagree, every vs_xla ratio is untrustworthy and the
    gate must fail regardless of the ratios themselves."""
    from triton_dist_tpu.tools.bench_ops import (check_regression,
                                                 load_floors)
    floors = load_floors(_floors_file(tmp_path), "tpu")
    ex = {"ag_gemm_vs_xla": 1.5, "gemm_rs_vs_xla": 1.0,
          "baseline_anomaly": ["ag vs rs: 2.37x apart"]}
    fails = check_regression(ex, floors)
    assert any("anomaly" in f for f in fails)


def test_regress_cli_end_to_end(tmp_path, capsys):
    """The harness runs end to end from a bench checkpoint file — the
    CPU-only smoke wiring (relaxed cpu floors, exit code contract)."""
    from triton_dist_tpu.tools import bench_ops
    baseline = _floors_file(tmp_path)
    ckpt = tmp_path / "ckpt.json"
    ckpt.write_text(json.dumps(
        {"ts": 1, "extras": {"device_kind": "cpu",
                             "ag_gemm_vs_xla": 0.4,
                             "baseline_anomaly": None}}))
    rc = bench_ops.main(["--regress", "--from", str(ckpt),
                         "--baseline", baseline])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0 and report["tier"] == "cpu"
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(
        {"extras": {"device_kind": "TPU v5 lite",
                    "ag_gemm_vs_xla": 0.2, "gemm_rs_vs_xla": 0.9,
                    "baseline_anomaly": None}}))
    rc = bench_ops.main(["--regress", "--from", str(bad),
                        "--baseline", baseline])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert report["tier"] == "tpu" and report["failures"]


def test_regress_live_sweep_filters_unswept_floors(tmp_path, monkeypatch,
                                                   capsys):
    """Live-sweep mode checks only the floors its sweeps can produce
    (bench.py-only metrics like tp_mlp_vs_xla apply to --from
    checkpoints) — otherwise the missing-key-fails contract would make
    the live TPU gate structurally unpassable (review finding)."""
    from triton_dist_tpu.tools import bench_ops
    baseline = tmp_path / "BASELINE.json"
    baseline.write_text(json.dumps({"regression_floors": {
        "tpu": {"ag_gemm_vs_xla": 0.7, "tp_mlp_vs_xla": 0.45}}}))
    monkeypatch.setattr(bench_ops, "_init_mesh", lambda: (None, 1))
    monkeypatch.setattr(bench_ops, "_is_tpu", lambda: True)
    monkeypatch.setattr(
        bench_ops, "_extras_from_sweep",
        lambda *a: {"ag_gemm_vs_xla": 1.5, "gemm_rs_vs_xla": 1.0,
                    "flash_decode_vs_xla": 1.0, "baseline_anomaly": None})
    rc = bench_ops.main(["--regress", "--baseline", str(baseline)])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert report["floors_skipped_not_swept"] == ["tp_mlp_vs_xla"]
    assert "tp_mlp_vs_xla" not in report["floors"]


def test_repo_baseline_floors_wellformed():
    """The checked-in BASELINE.json floor file parses and carries both
    tiers with the keys the bench actually emits."""
    from triton_dist_tpu.tools.bench_ops import load_floors
    path = str(_ROOT / "BASELINE.json")
    tpu = load_floors(path, "tpu")
    cpu = load_floors(path, "cpu")
    assert {"ag_gemm_vs_xla", "gemm_rs_vs_xla"} <= set(tpu)
    assert all(isinstance(v, (int, float)) for v in tpu.values())
    # cpu KERNEL floors are the end-to-end smoke: near-zero by design
    # (interpret-mode ratios price the interpreter, not the kernels)
    assert all(v <= 0.01 for k, v in cpu.items()
               if k.endswith("_vs_xla"))
    # ... but the scheduler ratio is kernel-independent (both paths run
    # the same xla model), so its floor is the ISSUE 5 acceptance bar:
    # 8 concurrent clients >= 2x the serialized-lock server.
    assert cpu.get("serving_sched_vs_serial", 0) >= 2.0


def test_regress_gates_serving_ratio(tmp_path):
    """serving_sched_vs_serial is machine-checked like the kernel
    ratios: below-floor (a scheduler regressed toward serialized
    behavior) or missing (the serving probe never ran) both fail."""
    from triton_dist_tpu.tools.bench_ops import (check_regression,
                                                 load_floors)
    path = tmp_path / "BASELINE.json"
    path.write_text(json.dumps({"regression_floors": {
        "cpu": {"ag_gemm_vs_xla": 0.001,
                "serving_sched_vs_serial": 2.0}}}))
    floors = load_floors(str(path), "cpu")
    ok = {"ag_gemm_vs_xla": 1.0, "serving_sched_vs_serial": 40.0,
          "baseline_anomaly": None}
    assert check_regression(ok, floors) == []
    bad = dict(ok, serving_sched_vs_serial=1.1)
    assert any("serving_sched_vs_serial" in f
               for f in check_regression(bad, floors))
    gone = {k: v for k, v in ok.items()
            if k != "serving_sched_vs_serial"}
    assert any("serving_sched_vs_serial" in f and "missing" in f
               for f in check_regression(gone, floors))


def test_mega_serving_wellformed_gate():
    """ISSUE 11 satellite: once the serving_mega part ran, its
    serving_mega_vs_plain ratio must exist and be a positive number —
    a run silently dropping the mega-in-scheduler evidence fails; a
    run that never measured serving_mega passes untouched."""
    from triton_dist_tpu.tools.bench_ops import (
        check_mega_serving_wellformed)
    assert check_mega_serving_wellformed({}) == []      # part didn't run
    ok = {"serving_mega_tokens_per_s": 100.0,
          "serving_mega_vs_plain": 0.97}
    assert check_mega_serving_wellformed(ok) == []
    for bad_val in (None, "fast", True, 0.0, -1.0):
        bad = {"serving_mega_tokens_per_s": 100.0,
               "serving_mega_vs_plain": bad_val}
        fails = check_mega_serving_wellformed(bad)
        assert fails and "serving_mega_vs_plain" in fails[0], bad_val
    gone = {"serving_mega_tokens_per_s": 100.0}
    assert check_mega_serving_wellformed(gone)


def test_spec_serving_wellformed_gate():
    """ISSUE 13 satellite: once the serving_spec part ran, its
    serving_spec_vs_plain ratio AND a [0, 1] accept rate must exist —
    a run silently dropping either would let a drafter regression
    hide behind a stale floor pass; a run that never measured
    serving_spec passes untouched."""
    from triton_dist_tpu.tools.bench_ops import (
        check_spec_serving_wellformed)
    assert check_spec_serving_wellformed({}) == []      # part didn't run
    ok = {"serving_spec_tokens_per_s": 100.0,
          "serving_spec_vs_plain": 1.62,
          "serving_spec_accept_rate": 0.44}
    assert check_spec_serving_wellformed(ok) == []
    for bad_val in (None, "fast", True, 0.0, -1.0):
        bad = dict(ok, serving_spec_vs_plain=bad_val)
        fails = check_spec_serving_wellformed(bad)
        assert fails and "serving_spec_vs_plain" in fails[0], bad_val
    for bad_rate in (None, "hi", True, -0.1, 1.5):
        bad = dict(ok, serving_spec_accept_rate=bad_rate)
        fails = check_spec_serving_wellformed(bad)
        assert fails and "serving_spec_accept_rate" in fails[0], \
            bad_rate
    gone = {"serving_spec_tokens_per_s": 100.0}
    assert len(check_spec_serving_wellformed(gone)) == 2


def test_fleet_wellformed_gate():
    """ISSUE 14 satellite: once the serving_fleet part ran, its
    fleet-vs-single ratio must exist and be positive, its per-replica
    rows must name >= 2 distinct replicas, no replica may have been
    down, every replica must have RETIRED rows in the timed window
    (a dead-pump replica still answers health from handler threads),
    and neither timed leg may have request errors — a fanout
    half-landing on a dead replica would publish a fleet tokens/s
    that is really a single-replica number. A run that never measured
    serving_fleet passes untouched."""
    from triton_dist_tpu.tools.bench_ops import check_fleet_wellformed
    assert check_fleet_wellformed({}) == []             # part didn't run
    ok = {"serving_fleet_tokens_per_s": 1200.0,
          "serving_fleet_vs_single": 0.84,
          "serving_fleet_replica_ids": ["r0", "r1"],
          "serving_fleet_down_replicas": 0,
          "serving_fleet_replica_retired": [8, 8],
          "serving_fleet_error_count": 0,
          "serving_fleet_single_error_count": 0}
    assert check_fleet_wellformed(ok) == []
    for bad_val in (None, "fast", True, 0.0, -1.0):
        fails = check_fleet_wellformed(
            dict(ok, serving_fleet_vs_single=bad_val))
        assert fails and "serving_fleet_vs_single" in fails[0], bad_val
    for bad_ids in (None, [], ["r0"], ["r0", "r0"], "r0,r1"):
        fails = check_fleet_wellformed(
            dict(ok, serving_fleet_replica_ids=bad_ids))
        assert fails and "replica_ids" in fails[0], bad_ids
    fails = check_fleet_wellformed(
        dict(ok, serving_fleet_down_replicas=1))
    assert fails and "down" in fails[0]
    fails = check_fleet_wellformed(
        dict(ok, serving_fleet_down_replicas=None))
    assert fails and "down_replicas" in fails[0]
    # The dead-pump case: replica r1 answered health (not down) but
    # retired nothing in the window — must fail.
    for bad_ret in (None, [8], [8, 0], [8, True], [8, "x"]):
        fails = check_fleet_wellformed(
            dict(ok, serving_fleet_replica_retired=bad_ret))
        assert fails and "replica_retired" in fails[0], bad_ret
    # Errored requests in either timed leg fail too.
    for key in ("serving_fleet_error_count",
                "serving_fleet_single_error_count"):
        fails = check_fleet_wellformed(dict(ok, **{key: 2}))
        assert fails and key in fails[0]
        fails = check_fleet_wellformed(dict(ok, **{key: None}))
        assert fails and key in fails[0]
    gone = {"serving_fleet_tokens_per_s": 1200.0}
    assert len(check_fleet_wellformed(gone)) == 6


def test_regress_gates_fleet(tmp_path):
    """serving_fleet rides the full --regress path: a well-formed run
    above the cpu floor passes; a down replica or a below-floor ratio
    fails."""
    import pathlib
    from triton_dist_tpu.tools.bench_ops import run_regress
    base = {"metric": "x", "extras": {
        "ag_gemm_vs_xla": 1.0, "gemm_rs_vs_xla": 1.0,
        "flash_decode_vs_xla": 1.0, "serving_sched_vs_serial": 50.0,
        "serving_prefix_ttft_vs_cold": 6.0,
        "serving_mega_vs_plain": 1.0, "serving_spec_vs_plain": 1.6,
        "serving_router_vs_direct": 0.9,
        "serving_history_on_vs_off": 0.97,
        "serving_disagg_vs_unified": 0.31,
        "serving_fleet_vs_single": 0.84,
        "serving_fleet_tokens_per_s": 1200.0,
        "serving_fleet_replica_ids": ["r0", "r1"],
        "serving_fleet_down_replicas": 0,
        "serving_fleet_replica_retired": [8, 8],
        "serving_fleet_error_count": 0,
        "serving_fleet_single_error_count": 0,
        "baseline_anomaly": None}}
    repo_baseline = str(pathlib.Path(__file__).resolve().parents[1]
                        / "BASELINE.json")
    p = tmp_path / "ok.json"
    p.write_text(json.dumps(base))
    assert run_regress(repo_baseline, str(p), "cpu") == 0
    bad = json.loads(json.dumps(base))
    bad["extras"]["serving_fleet_down_replicas"] = 1
    p2 = tmp_path / "down.json"
    p2.write_text(json.dumps(bad))
    assert run_regress(repo_baseline, str(p2), "cpu") == 1
    low = json.loads(json.dumps(base))
    low["extras"]["serving_fleet_vs_single"] = 0.1
    p3 = tmp_path / "low.json"
    p3.write_text(json.dumps(low))
    assert run_regress(repo_baseline, str(p3), "cpu") == 1


def test_bench_parts_typo_fails_before_checkpoint(tmp_path, monkeypatch):
    """A typo'd TDT_BENCH_PARTS must SystemExit before the checkpoint
    clear — prior evidence survives (review r5a-2)."""
    import pytest

    progress = tmp_path / "progress.json"
    progress.write_text(json.dumps(
        {"ts": 1.0, "extras": {"ag_gemm_tflops": 9.0}}))
    mod = _load_bench()
    monkeypatch.setenv("TDT_BENCH_PROGRESS", str(progress))
    monkeypatch.setenv("TDT_BENCH_PARTS", "ag_gemm,flash_deocde")
    with pytest.raises(SystemExit):
        mod.main()
    assert json.loads(progress.read_text())["extras"] == {
        "ag_gemm_tflops": 9.0}


def test_check_serving_wellformed_requires_rolling_keys():
    """ISSUE 8 satellite: --regress fails a serving bench run whose
    extras lack rolling-window TTFT/TPOT percentiles."""
    from triton_dist_tpu.tools import bench_ops
    # Kernel-only runs pass untouched.
    assert bench_ops.check_serving_wellformed({"ag_gemm_vs_xla": 1.0}) == []
    ex = {"serving_tokens_per_s": 100.0,
          "serving_rolling_ttft_p50_ms": 1.2,
          "serving_rolling_ttft_p99_ms": 3.4,
          "serving_rolling_tpot_p50_ms": 0.5,
          "serving_rolling_tpot_p99_ms": 0.9}
    assert bench_ops.check_serving_wellformed(ex) == []
    bad = dict(ex)
    bad["serving_rolling_tpot_p99_ms"] = None
    del bad["serving_rolling_ttft_p50_ms"]
    fails = bench_ops.check_serving_wellformed(bad)
    assert len(fails) == 2
    assert any("serving_rolling_ttft_p50_ms" in f for f in fails)
    assert any("serving_rolling_tpot_p99_ms" in f for f in fails)
    # The recorded TDT_SLO=0 opt-out is not a missing-metric failure.
    assert bench_ops.check_serving_wellformed(
        {"serving_tokens_per_s": 50.0,
         "serving_rolling_disabled": True}) == []


def test_regress_from_file_gates_serving_rolling(tmp_path):
    """run_regress picks the wellformedness check up end to end."""
    import json as _json
    from triton_dist_tpu.tools import bench_ops
    baseline = tmp_path / "BASELINE.json"
    baseline.write_text(_json.dumps(
        {"regression_floors": {"cpu": {}}}))
    art = tmp_path / "bench.json"
    art.write_text(_json.dumps(
        {"extras": {"serving_tokens_per_s": 50.0}}))
    rc = bench_ops.run_regress(str(baseline), str(art), "cpu")
    assert rc == 1
    ok = tmp_path / "bench_ok.json"
    ok.write_text(_json.dumps({"extras": {
        "serving_tokens_per_s": 50.0,
        "serving_rolling_ttft_p50_ms": 1.0,
        "serving_rolling_ttft_p99_ms": 2.0,
        "serving_rolling_tpot_p50_ms": 0.3,
        "serving_rolling_tpot_p99_ms": 0.6}}))
    assert bench_ops.run_regress(str(baseline), str(ok), "cpu") == 0
