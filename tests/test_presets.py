"""Model presets: the reference's benchmark menu as named configs
(e2e_dense.md Qwen3-8B/32B rows, mega_triton_kernel.md, Qwen3-MoE)."""

import jax.numpy as jnp
import pytest

from triton_dist_tpu.models import AutoLLM, presets
from triton_dist_tpu.parallel.plan import plan_parallelism


@pytest.mark.parametrize("name,lo,hi", [
    ("qwen3-0.6b", 0.55e9, 0.65e9),
    ("qwen3-8b", 8.0e9, 8.4e9),
    ("qwen3-32b", 32.4e9, 33.2e9),
    ("qwen3-30b-a3b", 30.0e9, 31.0e9),
])
def test_param_counts_match_model_names(name, lo, hi):
    cfg = presets.PRESETS[name]()
    n = presets.param_count(cfg)
    assert lo <= n <= hi, (name, n)


def test_presets_bench_dims_agree():
    """The bench's layer_8b/layer_32b parts use per-chip TP8 slices of
    exactly these architectures."""
    c8, c32 = presets.qwen3_8b(), presets.qwen3_32b()
    assert (c8.hidden_size, c8.intermediate_size) == (4096, 12288)
    assert (c32.hidden_size, c32.intermediate_size) == (5120, 25600)
    assert c8.intermediate_size % 8 == c32.intermediate_size % 8 == 0
    assert c8.num_key_value_heads == c32.num_key_value_heads == 8


def test_plan_parallelism_on_presets():
    """tdt-plan consumes the presets directly: the 32B model must ask
    for more TP than the 8B at the same chip count, and the MoE preset
    must spread experts over EP."""
    p8 = plan_parallelism(presets.qwen3_8b(), n_chips=8)
    p32 = plan_parallelism(presets.qwen3_32b(), n_chips=8)
    assert p8.tp <= p32.tp
    pm = plan_parallelism(presets.qwen3_30b_a3b(), n_chips=8)
    assert pm.ep > 1


def test_autollm_builds_scaled_preset(mesh8):
    """A depth/width-scaled 30B-A3B still builds + runs through AutoLLM
    (full-size would not fit CI; the architecture selection logic —
    MoE dispatch, qk-norm, head shapes — is what this covers)."""
    import dataclasses
    import jax

    cfg = dataclasses.replace(
        presets.qwen3_30b_a3b(), hidden_size=64, num_hidden_layers=2,
        num_attention_heads=8, num_key_value_heads=8, head_dim=8,
        moe_intermediate_size=32, num_experts=8, num_experts_per_tok=2,
        vocab_size=128, max_position_embeddings=32, dtype=jnp.float32)
    model = AutoLLM.build(cfg, mesh=mesh8, axis="tp", impl="xla")
    assert type(model).__name__ == "Qwen3MoE"
    params = model.init(jax.random.PRNGKey(0))
    tok = jnp.ones((1, 4), jnp.int32)
    from triton_dist_tpu.models.kv_cache import KVCacheManager
    kv = KVCacheManager(2, 1, 16, 8, 8, mesh=mesh8, axis="tp",
                        dtype=cfg.dtype)
    logits, _ = model.forward(params, tok, kv.init(), 0, mode="xla_ar")
    assert logits.shape == (1, 4, 128)
