"""The `parallel` facade (strategy-grouped re-exports) resolves and its
groupings are consistent — answers VERDICT r2's padded-file note with a
contract test."""


def test_facade_exports_resolve():
    import triton_dist_tpu.parallel as par
    for name in par.__all__:
        assert getattr(par, name) is not None, name


def test_strategy_groupings():
    from triton_dist_tpu import parallel as par
    assert par.TPAttn in par.TP_LAYERS and par.TPMLP in par.TP_LAYERS
    assert par.EPAll2AllLayer in par.EP_LAYERS
    assert par.SpFlashDecodeLayer in par.SP_LAYERS
    assert par.CommOp in par.PP_LAYERS
    # no layer appears in two strategy groups
    groups = [par.TP_LAYERS, par.EP_LAYERS, par.SP_LAYERS, par.PP_LAYERS]
    seen = set()
    for g in groups:
        for cls in g:
            assert cls not in seen, cls
            seen.add(cls)
