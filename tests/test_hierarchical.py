"""Two-level (ICI+DCN) collective tests on a 4x2 mesh (reference
test_reduce_scatter.py 2D paths, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow

from triton_dist_tpu.ops.hierarchical import (
    all_gather_2d, all_gather_nd, all_reduce_2d, all_reduce_nd,
    create_hier_context, reduce_scatter_2d, reduce_scatter_nd)


@pytest.fixture()
def mesh2d(devices):
    return Mesh(np.array(devices).reshape(2, 4), ("dcn", "ici"))


def test_all_gather_2d(mesh2d, key):
    ctx = create_hier_context(mesh2d)
    x = jax.random.normal(key, (16, 32), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh2d, P(("dcn", "ici"))))
    out = all_gather_2d(xs, ctx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_reduce_scatter_2d(mesh2d, key):
    ctx = create_hier_context(mesh2d)
    x = jax.random.normal(key, (16, 8), jnp.float32)
    out = reduce_scatter_2d(x, ctx)
    # every device contributed the same replicated x → sum = 8 * x
    np.testing.assert_allclose(np.asarray(out), 8 * np.asarray(x),
                               rtol=1e-5)


def test_all_reduce_2d(mesh2d, key):
    ctx = create_hier_context(mesh2d)
    x = jax.random.normal(key, (16, 8), jnp.float32)
    out = all_reduce_2d(x, ctx)
    np.testing.assert_allclose(np.asarray(out), 8 * np.asarray(x),
                               rtol=1e-5)


@pytest.fixture()
def mesh3d(devices):
    # 3-level ladder: two ICI dimensions + DCN (reference 3d multinode
    # variants, low_latency_allgather.py:617-780)
    return Mesh(np.array(devices).reshape(2, 2, 2), ("dcn", "iciy", "icix"))


AXES3 = ("icix", "iciy", "dcn")  # fastest → slowest


def test_all_gather_3d(mesh3d, key):
    x = jax.random.normal(key, (16, 32), jnp.float32)
    xs = jax.device_put(
        x, NamedSharding(mesh3d, P(("dcn", "iciy", "icix"))))
    out = all_gather_nd(xs, mesh3d, AXES3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_reduce_scatter_3d(mesh3d, key):
    x = jax.random.normal(key, (16, 8), jnp.float32)
    out = reduce_scatter_nd(x, mesh3d, AXES3)
    np.testing.assert_allclose(np.asarray(out), 8 * np.asarray(x),
                               rtol=1e-5)


def test_all_reduce_3d_matches_flat(mesh3d, key):
    x = jax.random.normal(key, (8, 8), jnp.float32)

    def flat(xs):
        return jax.lax.psum(xs, ("dcn", "iciy", "icix"))
    ref = jax.shard_map(flat, mesh=mesh3d, in_specs=P(), out_specs=P(),
                        check_vma=False)(x)
    out = all_reduce_nd(x, mesh3d, AXES3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_nd_matches_2d(mesh2d, key):
    """The n-level schedule at n=2 must reproduce the 2-level op."""
    ctx = create_hier_context(mesh2d)
    x = jax.random.normal(key, (16, 8), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(all_reduce_nd(x, mesh2d, ("ici", "dcn"))),
        np.asarray(all_reduce_2d(x, ctx)), rtol=1e-5)


def test_all_reduce_2d_matches_flat(mesh2d, key):
    """2-level AR must equal a flat psum over both axes."""
    ctx = create_hier_context(mesh2d)
    x = jax.random.normal(key, (8, 8), jnp.float32)

    def flat(xs):
        return jax.lax.psum(xs, ("dcn", "ici"))
    ref = jax.shard_map(flat, mesh=mesh2d, in_specs=P(), out_specs=P(),
                        check_vma=False)(x)
    out = all_reduce_2d(x, ctx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_all_to_all_2d_matches_flat(mesh2d, key):
    """Two-level EP dispatch a2a must be the same permutation as a flat
    all_to_all over both axes (bit-equal), batching the DCN hop."""
    ctx = create_hier_context(mesh2d)
    w = 8
    rows, f_dim = 4, 16
    x = jax.random.normal(key, (w * w * rows, f_dim), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh2d, P(("dcn", "ici"))))

    from triton_dist_tpu.ops.hierarchical import all_to_all_2d
    got = all_to_all_2d(xs, ctx)

    def flat(v):
        return jax.lax.all_to_all(v, ("dcn", "ici"), split_axis=0,
                                  concat_axis=0, tiled=True)
    ref = jax.shard_map(flat, mesh=mesh2d, in_specs=P(("dcn", "ici")),
                        out_specs=P(("dcn", "ici")), check_vma=False)(xs)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_all_to_all_2d_3dim_payload(mesh2d, key):
    """Payloads with trailing dims beyond 2-D also roundtrip."""
    ctx = create_hier_context(mesh2d)
    x = jax.random.normal(key, (8 * 8 * 2, 4, 8), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh2d, P(("dcn", "ici"))))
    from triton_dist_tpu.ops.hierarchical import all_to_all_2d
    out = all_to_all_2d(all_to_all_2d(xs, ctx), ctx)
    # a2a is an involution for symmetric chunk layouts
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
