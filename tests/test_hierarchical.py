"""Two-level (ICI+DCN) collective tests on a 4x2 mesh (reference
test_reduce_scatter.py 2D paths, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.ops.hierarchical import (
    all_gather_2d, all_reduce_2d, create_hier_context, reduce_scatter_2d)


@pytest.fixture()
def mesh2d(devices):
    return Mesh(np.array(devices).reshape(2, 4), ("dcn", "ici"))


def test_all_gather_2d(mesh2d, key):
    ctx = create_hier_context(mesh2d)
    x = jax.random.normal(key, (16, 32), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh2d, P(("dcn", "ici"))))
    out = all_gather_2d(xs, ctx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_reduce_scatter_2d(mesh2d, key):
    ctx = create_hier_context(mesh2d)
    x = jax.random.normal(key, (16, 8), jnp.float32)
    out = reduce_scatter_2d(x, ctx)
    # every device contributed the same replicated x → sum = 8 * x
    np.testing.assert_allclose(np.asarray(out), 8 * np.asarray(x),
                               rtol=1e-5)


def test_all_reduce_2d(mesh2d, key):
    ctx = create_hier_context(mesh2d)
    x = jax.random.normal(key, (16, 8), jnp.float32)
    out = all_reduce_2d(x, ctx)
    np.testing.assert_allclose(np.asarray(out), 8 * np.asarray(x),
                               rtol=1e-5)


def test_all_reduce_2d_matches_flat(mesh2d, key):
    """2-level AR must equal a flat psum over both axes."""
    ctx = create_hier_context(mesh2d)
    x = jax.random.normal(key, (8, 8), jnp.float32)

    def flat(xs):
        return jax.lax.psum(xs, ("dcn", "ici"))
    ref = jax.shard_map(flat, mesh=mesh2d, in_specs=P(), out_specs=P(),
                        check_vma=False)(x)
    out = all_reduce_2d(x, ctx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)
