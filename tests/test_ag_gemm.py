"""Fused AG-GEMM / GEMM-RS / GEMM-AR tests vs XLA goldens (reference
analogs: test_ag_gemm.py:72-197, test_gemm_rs.py, test_gemm_ar.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.ops.allgather_gemm import ag_gemm, create_ag_gemm_context
from triton_dist_tpu.ops.gemm_reduce_scatter import (
    create_gemm_rs_context, gemm_ar, gemm_rs)
from triton_dist_tpu.runtime.utils import assert_allclose

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow

WORLD = 8
M, K, N = 64, 32, 256   # per-device: (8, 32) x (32, 32)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_ag_gemm(mesh8, key, dtype):
    ka, kb = jax.random.split(key)
    a = (jax.random.normal(ka, (M, K)) / 4).astype(dtype)
    b = (jax.random.normal(kb, (K, N)) / 4).astype(dtype)
    ctx = create_ag_gemm_context(mesh8)
    got = ag_gemm(a, b, ctx, impl="pallas")
    ref = ag_gemm(a, b, ctx, impl="xla")
    assert got.shape == (M, N)
    tol = 1e-5 if dtype == jnp.float32 else 5e-2
    assert_allclose(got, ref, rtol=tol, atol=tol)
    # analytic golden
    full = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
    assert_allclose(got, full, rtol=2e-2, atol=2e-1)


def test_ag_gemm_return_gathered(mesh8, key):
    ka, kb = jax.random.split(key)
    a = (jax.random.normal(ka, (M, K)) / 4).astype(jnp.float32)
    b = (jax.random.normal(kb, (K, N)) / 4).astype(jnp.float32)
    ctx = create_ag_gemm_context(mesh8, return_gathered=True)
    c, ag = ag_gemm(a, b, ctx, impl="pallas")
    assert c.shape == (M, N)
    ag = np.asarray(ag).reshape(WORLD, M, K)
    for d in range(WORLD):
        assert np.array_equal(ag[d], np.asarray(a)), f"device {d}"


@pytest.mark.parametrize("dtype", [jnp.float32])
def test_gemm_rs(mesh8, key, dtype):
    ka, kb = jax.random.split(key)
    a = (jax.random.normal(ka, (M, K)) / 4).astype(dtype)   # col-sharded
    b = (jax.random.normal(kb, (K, N)) / 4).astype(dtype)   # row-sharded
    ctx = create_gemm_rs_context(mesh8)
    got = gemm_rs(a, b, ctx, impl="pallas")
    ref = gemm_rs(a, b, ctx, impl="xla")
    assert got.shape == (M, N)
    assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    full = np.asarray(a, np.float64) @ np.asarray(b, np.float64)
    assert_allclose(got, full, rtol=1e-3, atol=1e-3)


def test_gemm_ar(mesh8, key):
    ka, kb = jax.random.split(key)
    a = (jax.random.normal(ka, (M, K)) / 4).astype(jnp.float32)
    b = (jax.random.normal(kb, (K, N)) / 4).astype(jnp.float32)
    ctx = create_gemm_rs_context(mesh8)
    got = gemm_ar(a, b, ctx, impl="pallas")
    assert got.shape == (M, N)
    full = np.asarray(a, np.float64) @ np.asarray(b, np.float64)
    assert_allclose(got, full, rtol=1e-3, atol=1e-3)


def test_ag_gemm_hbm_variant(mesh8, key):
    """HBM-resident tiled kernel matches the golden (large-shape path)."""
    from triton_dist_tpu.ops.allgather_gemm import ag_gemm_multi
    m, k, n = 32, 256, 256
    a = jax.device_put(jax.random.normal(key, (m, k), jnp.float32),
                       jax.sharding.NamedSharding(
                           mesh8, jax.sharding.PartitionSpec("tp")))
    b1 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.float32),
        jax.sharding.NamedSharding(
            mesh8, jax.sharding.PartitionSpec(None, "tp")))
    b2 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(2), (k, n // 2), jnp.float32),
        jax.sharding.NamedSharding(
            mesh8, jax.sharding.PartitionSpec(None, "tp")))
    ctx = create_ag_gemm_context(mesh8, "tp")
    ctx.variant = "hbm"
    ctx.block_k = 64
    ctx.block_m = 4
    outs = ag_gemm_multi(a, [b1, b2], ctx, impl="pallas")
    golds = ag_gemm_multi(a, [b1, b2], ctx, impl="xla")
    for o, g in zip(outs, golds):
        np.testing.assert_allclose(np.asarray(o), np.asarray(g),
                                   rtol=1e-4, atol=1e-4)


def test_ag_gemm_hbm_kt_variant(mesh8, key):
    """k-tiled fallback kernel (huge-K path) matches the golden."""
    from triton_dist_tpu.ops.allgather_gemm import ag_gemm_multi
    m, k, n = 32, 256, 256
    a = jax.device_put(jax.random.normal(key, (m, k), jnp.float32),
                       NamedSharding(mesh8, P("tp")))
    b1 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.float32),
        NamedSharding(mesh8, P(None, "tp")))
    ctx = create_ag_gemm_context(mesh8, "tp")
    ctx.variant = "hbm_kt"
    ctx.block_k = 64
    ctx.block_m = 4
    outs = ag_gemm_multi(a, [b1], ctx, impl="pallas")
    golds = ag_gemm_multi(a, [b1], ctx, impl="xla")
    for o, g in zip(outs, golds):
        np.testing.assert_allclose(np.asarray(o), np.asarray(g),
                                   rtol=1e-4, atol=1e-4)


def test_gemm_ar_hbm_variant(mesh8, key):
    """N-blocked hbm GEMM-AR (ring-AG epilogue over the HBM output)
    matches the replicated golden (VERDICT r2 weak 8: decode GEMM-AR at
    production widths must not need VMEM residency)."""
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_ar)
    m, k, n = 64, 128, 256
    ctx = create_gemm_rs_context(mesh8, "tp")
    ctx.variant = "hbm"
    ctx.block_m, ctx.block_n = 8, 128
    a = jax.random.normal(key, (m, k), jnp.float32) / 4
    b = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.float32) / 4
    a_s = jax.device_put(a, NamedSharding(mesh8, P(None, "tp")))
    b_s = jax.device_put(b, NamedSharding(mesh8, P("tp")))
    out = gemm_ar(a_s, b_s, ctx, impl="pallas")
    assert out.shape == (m, n)
    full = np.asarray(a, np.float64) @ np.asarray(b, np.float64)
    np.testing.assert_allclose(np.asarray(out), full, rtol=1e-3, atol=1e-3)


def test_gemm_rs_hbm_kt_variant(mesh8, key):
    """k-tiled GEMM-RS fallback matches the xla golden."""
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)
    m, k, n = 64, 128, 256
    ctx = create_gemm_rs_context(mesh8, "tp")
    ctx.variant = "hbm_kt"
    ctx.block_m, ctx.block_k = 8, 8
    a = jax.random.normal(key, (m, k), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.float32)
    a_s = jax.device_put(a, NamedSharding(mesh8, P(None, "tp")))
    b_s = jax.device_put(b, NamedSharding(mesh8, P("tp")))
    out = gemm_rs(a_s, b_s, ctx, impl="pallas")
    ref = gemm_rs(a_s, b_s, create_gemm_rs_context(mesh8, "tp"),
                  impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ag_gemm_jit_grad_composes(mesh8, key):
    """The fused op must compose under jit; the XLA impl must also be
    differentiable (training use beyond the reference's inference-only
    scope)."""
    ka, kb = jax.random.split(key)
    a = (jax.random.normal(ka, (M, K)) / 4).astype(jnp.float32)
    b = (jax.random.normal(kb, (K, N)) / 4).astype(jnp.float32)
    ctx = create_ag_gemm_context(mesh8)

    @jax.jit
    def f(a, b):
        return ag_gemm(a, b, ctx, impl="pallas").sum()

    @jax.jit
    def g(a, b):
        return ag_gemm(a, b, ctx, impl="xla").sum()

    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-2)
    da = jax.grad(lambda a, b: ag_gemm(a, b, ctx, impl="xla").sum(),
                  argnums=0)(a, b)
    assert da.shape == a.shape


def test_gemm_rs_hbm_variant(mesh8, key):
    """HBM-streaming GEMM-RS (tiled K/M loops, travelling partials in
    HBM) matches the xla golden."""
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)
    m, k, n = 64, 128, 256
    ctx = create_gemm_rs_context(mesh8, "tp")
    ctx.variant = "hbm"
    ctx.block_m, ctx.block_k = 8, 8
    a = jax.random.normal(key, (m, k), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.float32)
    a_s = jax.device_put(a, NamedSharding(mesh8, P(None, "tp")))
    b_s = jax.device_put(b, NamedSharding(mesh8, P("tp")))
    out = gemm_rs(a_s, b_s, ctx, impl="pallas")
    ref = gemm_rs(a_s, b_s, create_gemm_rs_context(mesh8, "tp"),
                  impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ag_gemm_autotune_caches(mesh8, key):
    """Autotune sweeps the config table on the first eager call and
    caches the winner by shape (VERDICT r1 item 5)."""
    from triton_dist_tpu.ops import allgather_gemm as agm
    m, k, n = 32, 64, 128
    ctx = agm.create_ag_gemm_context(mesh8, "tp")
    ctx.autotune = True
    a = jax.random.normal(key, (m, k), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.float32)
    a_s = jax.device_put(a, NamedSharding(mesh8, P("tp")))
    b_s = jax.device_put(b, NamedSharding(mesh8, P(None, "tp")))
    agm._TUNED.clear()
    out = agm.ag_gemm(a_s, b_s, ctx, impl="pallas")
    key_ = (m, k, n // 8, "float32", 8)
    assert key_ in agm._TUNED, agm._TUNED
    cfg = agm._TUNED[key_]
    assert cfg["variant"] in ("vmem", "hbm")
    ref = agm.ag_gemm(a_s, b_s, agm.create_ag_gemm_context(mesh8, "tp"),
                      impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    # jitted call reuses the cache (no eager sweep possible inside trace)
    out2 = jax.jit(lambda x, w: agm.ag_gemm(x, w, ctx))(a_s, b_s)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_gemm_rs_configs_table():
    from triton_dist_tpu.ops.gemm_reduce_scatter import gemm_rs_configs
    cfgs = gemm_rs_configs(2048, 2048, 4096, 4096, 2, 1)
    # too big for vmem; N-blocked hbm configs ranked before the k-tiled
    # fallback
    assert all(c["variant"] in ("hbm", "hbm_kt") for c in cfgs)
    assert cfgs[0]["variant"] == "hbm"
    assert len(cfgs) >= 1
    cfgs2 = gemm_rs_configs(2048, 2048, 4096, 1024, 2, 1)
    assert len(cfgs2) >= 2  # smaller N admits several tilings
    small = gemm_rs_configs(64, 8, 16, 32, 4, 8)
    assert small[0]["variant"] == "vmem"


def test_aggressive_blocks_reach_kernel_unclamped(mesh8, key, monkeypatch):
    """Blocks with a footprint between the soft vmem_budget and
    HARD_FOOTPRINT_CAP must be HONORED — this is how the config table's
    aggressive tier reaches Mosaic at all (review r5i finding 1: a
    soft-budget clamp silently rewrote every swept aggressive config
    back to the budget kernel, so the tier benchmarked duplicates).
    Blocks beyond the hard cap must still be clamped to an in-budget
    config (BENCH_r02: an uncompilable config never reaches the
    compiler). Budgets are shrunk so 'aggressive' stays tiny in
    interpret mode."""
    import triton_dist_tpu.ops.allgather_gemm as agm

    seen = []
    seen_kt = []
    orig = agm._ag_gemm_hbm_nb_kernel
    orig_kt = agm._ag_gemm_hbm_kernel

    def spy(*a, **kw):
        seen.append((kw["m_blk"], kw["n_blk"]))
        return orig(*a, **kw)

    def spy_kt(*a, **kw):
        seen_kt.append((kw["m_blk"], kw["k_blk"]))
        return orig_kt(*a, **kw)

    monkeypatch.setattr(agm, "_ag_gemm_hbm_nb_kernel", spy)
    monkeypatch.setattr(agm, "_ag_gemm_hbm_kernel", spy_kt)

    m, k, n = 64, 32, 256
    a = (jax.random.normal(key, (m, k)) / 4).astype(jnp.float32)
    b = (jax.random.normal(jax.random.PRNGKey(1), (k, n)) / 4
         ).astype(jnp.float32)
    a_s = jax.device_put(a, NamedSharding(mesh8, P("tp")))
    b_s = jax.device_put(b, NamedSharding(mesh8, P(None, "tp")))
    golden = np.asarray(a, np.float64) @ np.asarray(b, np.float64)

    # rows=8, n_loc=32, fp(8, 32) = 4*(2*8*32 + 2*32*32 + 2*8*32) = 12 KB
    ctx = create_ag_gemm_context(mesh8)
    ctx.variant = "hbm"
    ctx.block_m, ctx.block_n = 8, 32
    ctx.vmem_budget = 8 * 1024          # over-budget...
    assert agm._hbm_footprint(8, 32, k, 4) > ctx.vmem_budget

    # Without trust_blocks (default path), the soft-budget clamp holds:
    # no in-budget hbm config exists, so the entry degrades to hbm_kt.
    out = agm.ag_gemm(a_s, b_s, ctx, impl="pallas")
    np.testing.assert_allclose(np.asarray(out), golden, rtol=1e-3,
                               atol=1e-3)
    assert not seen and seen_kt, "default path honored over-budget blocks"

    # With trust_blocks (how the sweep and tuned winners run), blocks up
    # to HARD_FOOTPRINT_CAP are honored.
    ctx.trust_blocks = True
    out = agm.ag_gemm(a_s, b_s, ctx, impl="pallas")
    np.testing.assert_allclose(np.asarray(out), golden, rtol=1e-3,
                               atol=1e-3)
    assert seen and seen[-1] == (8, 32), "aggressive blocks were clamped"

    # ...but over the hard cap: no in-budget NB config exists at this
    # shrunken budget, so the entry degrades to the k-tiled kernel with
    # SHAPE-CLAMPED blocks (the unclamped 128/256 table fallback used
    # to reach the kernel with block_k > K here: k_tiles = 0 ->
    # ZeroDivisionError in the ring schedule).
    monkeypatch.setattr(agm, "HARD_FOOTPRINT_CAP", 10 * 1024)
    n_nb = len(seen)
    out = agm.ag_gemm(a_s, b_s, ctx, impl="pallas")
    np.testing.assert_allclose(np.asarray(out), golden, rtol=1e-3,
                               atol=1e-3)
    assert len(seen) == n_nb, "over-cap blocks still ran the NB kernel"
    rows = m // 8
    assert seen_kt and seen_kt[-1][0] <= rows and seen_kt[-1][1] <= k, \
        seen_kt


def test_gemm_ar_infeasible_config_degrades(mesh8, key):
    """When no resident-B-panel config fits the VMEM budget, GEMM-AR must
    degrade to the XLA path rather than fall through to the
    full-residency vmem kernel (code-review r3: BENCH_r02-class crash)."""
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_ar)
    m, k, n = 64, 128, 256
    ctx = create_gemm_rs_context(mesh8, "tp")
    ctx.vmem_budget = 1024     # nothing fits -> hbm -> hbm_kt -> xla
    a = jax.random.normal(key, (m, k), jnp.float32) / 4
    b = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.float32) / 4
    a_s = jax.device_put(a, NamedSharding(mesh8, P(None, "tp")))
    b_s = jax.device_put(b, NamedSharding(mesh8, P("tp")))
    out = gemm_ar(a_s, b_s, ctx, impl="pallas")
    full = np.asarray(a, np.float64) @ np.asarray(b, np.float64)
    np.testing.assert_allclose(np.asarray(out), full, rtol=1e-3, atol=1e-3)


class TestAgSwiglu:
    """Fused AG + dual-GEMM + SwiGLU (beyond-reference fusion; the
    reference's TP_MLP runs AG-GEMM then a separate silu-mul,
    tp_mlp.py:147-270)."""

    @staticmethod
    def _golden(a, wg, wu):
        ag = np.asarray(a, np.float32)
        g = ag @ np.asarray(wg, np.float32)
        u = ag @ np.asarray(wu, np.float32)
        return (g / (1 + np.exp(-g))) * u

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_fallback_shape(self, mesh8, key, dtype):
        """Small shards route through the composed fallback."""
        from triton_dist_tpu.ops.allgather_gemm import ag_swiglu
        ka, kg, ku = jax.random.split(key, 3)
        a = (jax.random.normal(ka, (M, K)) / 4).astype(dtype)
        wg = (jax.random.normal(kg, (K, N)) / 4).astype(dtype)
        wu = (jax.random.normal(ku, (K, N)) / 4).astype(dtype)
        ctx = create_ag_gemm_context(mesh8)
        got = ag_swiglu(a, wg, wu, ctx, impl="pallas")
        ref = ag_swiglu(a, wg, wu, ctx, impl="xla")
        assert got.shape == (M, N)
        tol = 1e-5 if dtype == jnp.float32 else 5e-2
        assert_allclose(got, ref, rtol=tol, atol=tol)
        assert_allclose(got, self._golden(a, wg, wu), rtol=2e-2, atol=2e-1)

    def test_kernel_shape(self, mesh8, key):
        """128-divisible shards engage the single fused kernel."""
        from triton_dist_tpu.ops.allgather_gemm import ag_swiglu
        m, k, n = 1024, 64, 1024          # rows=128, n_loc=128
        ka, kg, ku = jax.random.split(key, 3)
        a = (jax.random.normal(ka, (m, k)) / 4).astype(jnp.float32)
        wg = (jax.random.normal(kg, (k, n)) / 4).astype(jnp.float32)
        wu = (jax.random.normal(ku, (k, n)) / 4).astype(jnp.float32)
        ctx = create_ag_gemm_context(mesh8)
        got = ag_swiglu(a, wg, wu, ctx, impl="pallas")
        assert got.shape == (m, n)
        assert_allclose(got, self._golden(a, wg, wu), rtol=1e-3,
                        atol=1e-3)

    def test_grad_parity(self, mesh8, key):
        """VJP grads equal the differentiable composition's."""
        from triton_dist_tpu.ops import autodiff as ad
        ka, kg, ku, kd = jax.random.split(key, 4)
        a = (jax.random.normal(ka, (M, K)) / 4).astype(jnp.float32)
        wg = (jax.random.normal(kg, (K, N)) / 4).astype(jnp.float32)
        wu = (jax.random.normal(ku, (K, N)) / 4).astype(jnp.float32)
        ctx = create_ag_gemm_context(mesh8)

        def fused(a, wg, wu):
            return jnp.sum(ad.ag_swiglu(a, wg, wu, ctx, "pallas") ** 2)

        def composed(a, wg, wu):
            g, u = ad.ag_gemm_multi(a, [wg, wu], ctx, "pallas")
            act = jax.nn.silu(g.astype(jnp.float32)).astype(a.dtype) * u
            return jnp.sum(act.astype(jnp.float32) ** 2)

        gf = jax.grad(fused, argnums=(0, 1, 2))(a, wg, wu)
        gc = jax.grad(composed, argnums=(0, 1, 2))(a, wg, wu)
        for x, y, name in zip(gf, gc, ("da", "dwg", "dwu")):
            assert_allclose(x, y, rtol=2e-3, atol=2e-3)


def test_ag_swiglu_autotune_sweep(mesh8, key):
    """Eager sweep + winner application end-to-end in interpret mode:
    numerics must match the XLA golden and a winner must be cached."""
    import dataclasses as dc
    from triton_dist_tpu.ops import allgather_gemm as agm

    m, k, n = 1024, 128, 2048
    ka, kg, ku = jax.random.split(key, 3)
    a = jax.device_put((jax.random.normal(ka, (m, k)) / 4
                        ).astype(jnp.bfloat16),
                       NamedSharding(mesh8, P("tp")))
    wg = jax.device_put((jax.random.normal(kg, (k, n)) / 4
                         ).astype(jnp.bfloat16),
                        NamedSharding(mesh8, P(None, "tp")))
    wu = jax.device_put((jax.random.normal(ku, (k, n)) / 4
                         ).astype(jnp.bfloat16),
                        NamedSharding(mesh8, P(None, "tp")))
    ctx = dc.replace(agm.create_ag_gemm_context(mesh8), autotune=True)
    got = agm.ag_swiglu(a, wg, wu, ctx, impl="pallas")
    ref = agm.ag_swiglu(a, wg, wu, dc.replace(ctx, autotune=False),
                        impl="xla")
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-2)
    assert any(kk[-1] == "swiglu" for kk in agm._TUNED), agm._TUNED
