"""Bidirectional-ring fused GEMM schedules (ISSUE 2 tentpole).

Numerics of every fused variant against the XLA golden at world sizes
1/2/4 plus the odd world 3, in BOTH ring-direction modes — the
unidirectional schedule (``ring_dirs=1``, the round-5 proven-on-chip
fallback, selectable via ``TDT_RING_DIRS=1``) must stay byte-identical
in behavior, and the bidirectional schedule (``ring_dirs=2``, the
default) must match it exactly. Plus the pure-python ring-schedule
protocol properties (permutation + arrival monotonicity) that hold
independent of Pallas, and the per-op overlap gauges.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.ops.common import (
    resolve_ring_dirs, ring_chunk_schedule, ring_hop_counts)

#: Interpret-mode kernel numerics -> full tier (like test_ag_gemm.py).
pytestmark = pytest.mark.slow


@pytest.fixture(autouse=True)
def _barrier_compat_04x():
    """jax 0.4.x cannot lower ``get_barrier_semaphore`` for the cpu
    (interpret) platform. The ring kernels under test order their data
    through per-(direction, chunk) DMA semaphores — every remote write
    targets a disjoint chunk slot and every read waits its recv
    semaphore — so stubbing the barrier is sound FOR THESE KERNELS
    (NOT in general: see the note on ``language.barrier_all``). On a
    current jax the real barrier runs."""
    if getattr(pltpu, "InterpretParams", None) is not None:
        yield
        return
    orig = dl.barrier_all
    dl.barrier_all = lambda *a, **k: None
    try:
        yield
    finally:
        dl.barrier_all = orig


def _mesh(world):
    return Mesh(np.array(jax.devices()[:world]), ("tp",))


def _sharded(a, mesh, spec):
    return jax.device_put(a, NamedSharding(mesh, spec))


# ---------------------------------------------------------------------------
# Protocol properties (pure python/jnp — no kernels)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("world", [1, 2, 3, 4, 5, 8])
@pytest.mark.parametrize("dirs", [1, 2])
def test_ring_schedule_is_permutation(world, dirs):
    """Every rank consumes every chunk exactly once, starting with its
    own; hop counts cover all w-1 travelling chunks."""
    n_fwd, n_bwd = ring_hop_counts(world, dirs)
    assert n_fwd + n_bwd == max(world - 1, 0)
    for me in range(world):
        chunks, offs = [], {0: [], 1: []}
        for s in range(world):
            c, is_bwd, off = ring_chunk_schedule(me, s, world, dirs)
            chunks.append(int(c))
            offs[int(is_bwd)].append(int(off))
        assert chunks[0] == me
        assert sorted(chunks) == list(range(world)), (me, chunks)
        # offsets stay within each direction's hop budget
        assert all(o <= n_fwd for o in offs[0])
        assert all(o <= n_bwd for o in offs[1])


@pytest.mark.parametrize("world", [2, 3, 4, 5, 8])
def test_ring_schedule_arrival_monotone(world):
    """A chunk at hop offset o+1 is consumed at a strictly later
    schedule position than offset o (per direction) — the
    happens-before every ``advance`` wait relies on: the hop that
    delivers position s's chunk was started at an earlier position on
    the sending rank, which runs the same schedule."""
    for dirs in (1, 2):
        for me in range(world):
            pos = {0: {}, 1: {}}
            for s in range(world):
                _, is_bwd, off = ring_chunk_schedule(me, s, world, dirs)
                pos[int(is_bwd)][int(off)] = s
            for d in (0, 1):
                offsets = sorted(pos[d])
                positions = [pos[d][o] for o in offsets]
                assert positions == sorted(positions), (dirs, me, pos)


def test_resolve_ring_dirs_env(monkeypatch):
    monkeypatch.delenv("TDT_RING_DIRS", raising=False)
    assert resolve_ring_dirs(0) == 2          # default: bidirectional
    assert resolve_ring_dirs(1) == 1          # explicit ctx wins
    monkeypatch.setenv("TDT_RING_DIRS", "1")  # proven-fallback switch
    assert resolve_ring_dirs(0) == 1
    assert resolve_ring_dirs(2) == 2          # ctx still wins over env
    monkeypatch.setenv("TDT_RING_DIRS", "3")
    with pytest.raises(ValueError):
        resolve_ring_dirs(0)
    with pytest.raises(ValueError):
        resolve_ring_dirs(7)


# ---------------------------------------------------------------------------
# Kernel numerics vs the XLA golden (interpret mode)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("world", [1, 2, 3, 4])
@pytest.mark.parametrize("dirs", [1, 2])
def test_ag_gemm_ring_dirs_exact(world, dirs, key):
    """vmem and N-blocked hbm variants are numerics-EXACT vs the XLA
    golden (full-K dots — same per-row reduction); the k-tiled fallback
    matches to accumulation tolerance."""
    from triton_dist_tpu.ops import allgather_gemm as agm
    mesh = _mesh(world)
    m, k, n = 16 * world, 32, 64 * world
    a = (jax.random.normal(key, (m, k)) / 4).astype(jnp.float32)
    b = (jax.random.normal(jax.random.PRNGKey(1), (k, n)) / 4
         ).astype(jnp.float32)
    a_s = _sharded(a, mesh, P("tp"))
    b_s = _sharded(b, mesh, P(None, "tp"))
    golden = np.asarray(a, np.float64) @ np.asarray(b, np.float64)

    ctx = agm.create_ag_gemm_context(mesh)
    ctx.ring_dirs = dirs
    ref = agm.ag_gemm(a_s, b_s, ctx, impl="xla")
    out = agm.ag_gemm(a_s, b_s, ctx, impl="pallas")
    assert np.array_equal(np.asarray(out), np.asarray(ref)), "vmem"
    np.testing.assert_allclose(np.asarray(out), golden, rtol=1e-3,
                               atol=1e-3)

    ctx2 = agm.create_ag_gemm_context(mesh)
    ctx2.ring_dirs = dirs
    ctx2.variant = "hbm"
    ctx2.block_m, ctx2.block_n = 4, 32
    out2 = agm.ag_gemm(a_s, b_s, ctx2, impl="pallas")
    assert np.array_equal(np.asarray(out2), np.asarray(ref)), "hbm"

    ctx3 = agm.create_ag_gemm_context(mesh)
    ctx3.ring_dirs = dirs
    ctx3.variant = "hbm_kt"
    ctx3.block_m, ctx3.block_k = 4, 8
    out3 = agm.ag_gemm(a_s, b_s, ctx3, impl="pallas")
    np.testing.assert_allclose(np.asarray(out3), golden, rtol=1e-3,
                               atol=1e-3)


@pytest.mark.parametrize("world", [1, 2, 3, 4])
@pytest.mark.parametrize("dirs", [1, 2])
def test_gemm_rs_ring_dirs(world, dirs, key):
    """Bidirectional column-halved RS matches the golden at every world
    (ring summation order differs from psum only at float tolerance)."""
    from triton_dist_tpu.ops import gemm_reduce_scatter as grs
    mesh = _mesh(world)
    m, k, n = 16 * world, 32 * world, 256
    a = (jax.random.normal(key, (m, k)) / 4).astype(jnp.float32)
    b = (jax.random.normal(jax.random.PRNGKey(1), (k, n)) / 4
         ).astype(jnp.float32)
    a_s = _sharded(a, mesh, P(None, "tp"))
    b_s = _sharded(b, mesh, P("tp"))
    golden = np.asarray(a, np.float64) @ np.asarray(b, np.float64)

    ctx = grs.create_gemm_rs_context(mesh)
    ctx.ring_dirs = dirs
    out = grs.gemm_rs(a_s, b_s, ctx, impl="pallas")
    assert out.shape == (m, n)
    np.testing.assert_allclose(np.asarray(out), golden, rtol=1e-4,
                               atol=1e-4)
    ar = grs.gemm_ar(a_s, b_s, ctx, impl="pallas")
    np.testing.assert_allclose(np.asarray(ar), golden, rtol=1e-4,
                               atol=1e-4)

    ctx2 = grs.create_gemm_rs_context(mesh)
    ctx2.ring_dirs = dirs
    ctx2.variant = "hbm"
    ctx2.block_m, ctx2.block_n = max(m // world // 2, 4), 64
    out2 = grs.gemm_rs(a_s, b_s, ctx2, impl="pallas")
    np.testing.assert_allclose(np.asarray(out2), golden, rtol=1e-4,
                               atol=1e-4)
    ar2 = grs.gemm_ar(a_s, b_s, ctx2, impl="pallas")
    np.testing.assert_allclose(np.asarray(ar2), golden, rtol=1e-4,
                               atol=1e-4)


@pytest.mark.parametrize("dirs", [1, 2])
def test_ag_swiglu_bias_epilogue(dirs, key):
    """The fused AG-SwiGLU kernel with the bias epilogue (both ring
    modes) matches the analytic golden and its own XLA impl."""
    from triton_dist_tpu.ops import allgather_gemm as agm
    world = 4
    mesh = _mesh(world)
    m, k, n = 256 * world, 64, 256 * world   # rows/n_loc = 256 (kernel)
    ks = jax.random.split(key, 5)
    a = (jax.random.normal(ks[0], (m, k)) / 4).astype(jnp.float32)
    wg = (jax.random.normal(ks[1], (k, n)) / 4).astype(jnp.float32)
    wu = (jax.random.normal(ks[2], (k, n)) / 4).astype(jnp.float32)
    bg = (jax.random.normal(ks[3], (n,)) / 4).astype(jnp.float32)
    bu = (jax.random.normal(ks[4], (n,)) / 4).astype(jnp.float32)

    ag = np.asarray(a, np.float32)
    g = ag @ np.asarray(wg, np.float32) + np.asarray(bg, np.float32)
    u = ag @ np.asarray(wu, np.float32) + np.asarray(bu, np.float32)
    golden = (g / (1 + np.exp(-g))) * u

    ctx = agm.create_ag_gemm_context(mesh)
    ctx.ring_dirs = dirs
    got = agm.ag_swiglu(a, wg, wu, ctx, impl="pallas",
                        b_gate=bg, b_up=bu)
    np.testing.assert_allclose(np.asarray(got), golden, rtol=1e-3,
                               atol=1e-3)
    ref = agm.ag_swiglu(a, wg, wu, ctx, impl="xla", b_gate=bg, b_up=bu)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    with pytest.raises(ValueError):
        agm.ag_swiglu(a, wg, wu, ctx, impl="pallas", b_gate=bg)


def test_tp_mlp_bias_fused_matches_xla(key):
    """TPMLP(use_bias=True): the fused path (bias + SwiGLU inside the
    AG-GEMM consumer loop, down-bias after the reduce) matches the xla
    golden in both layouts."""
    from triton_dist_tpu.layers.tp_mlp import TPMLP
    mesh = _mesh(4)
    mlp = TPMLP(64, 1024, mesh=mesh, axis="tp", dtype=jnp.float32,
                use_bias=True)
    params = mlp.init(key)
    assert {"b_gate", "b_up", "b_down"} <= set(params)
    ks = jax.random.split(key, 3)
    params["b_gate"] = _sharded(
        (jax.random.normal(ks[0], (1024,)) / 4).astype(jnp.float32),
        mesh, P("tp"))
    params["b_up"] = _sharded(
        (jax.random.normal(ks[1], (1024,)) / 4).astype(jnp.float32),
        mesh, P("tp"))
    params["b_down"] = _sharded(
        (jax.random.normal(ks[2], (64,)) / 4).astype(jnp.float32),
        mesh, P())
    x = _sharded((jax.random.normal(jax.random.PRNGKey(1), (1024, 64))
                  / 4).astype(jnp.float32), mesh, P("tp"))
    np.testing.assert_allclose(
        np.asarray(mlp(params, x, mode="ag_rs")),
        np.asarray(mlp(params, x, mode="xla")), rtol=2e-3, atol=2e-3)
    xr = _sharded((jax.random.normal(jax.random.PRNGKey(2), (64, 64))
                   / 4).astype(jnp.float32), mesh, P())
    np.testing.assert_allclose(
        np.asarray(mlp(params, xr, mode="gemm_ar")),
        np.asarray(mlp(params, xr, mode="xla_ar")), rtol=2e-3, atol=2e-3)


def test_overlap_gauges_in_snapshot(key):
    """comms.<op>.overlap_pct gauges land in the obs snapshot when the
    fused ops dispatch (the north-star metric stops reading a
    hardcoded 0)."""
    from triton_dist_tpu import obs
    from triton_dist_tpu.ops import allgather_gemm as agm
    from triton_dist_tpu.ops import gemm_reduce_scatter as grs
    mesh = _mesh(4)
    obs.disable()
    obs.enable()
    try:
        m, k, n = 64, 128, 256
        a = (jax.random.normal(key, (m, k)) / 4).astype(jnp.float32)
        b = (jax.random.normal(jax.random.PRNGKey(1), (k, n)) / 4
             ).astype(jnp.float32)
        agm.ag_gemm(_sharded(a, mesh, P("tp")),
                    _sharded(b, mesh, P(None, "tp")),
                    agm.create_ag_gemm_context(mesh), impl="pallas")
        grs.gemm_rs(_sharded(a, mesh, P(None, "tp")),
                    _sharded(b, mesh, P("tp")),
                    grs.create_gemm_rs_context(mesh), impl="pallas")
        gauges = obs.snapshot()["gauges"]
        assert 0.0 <= gauges["comms.ag_gemm.overlap_pct"] <= 100.0
        assert 0.0 <= gauges["comms.gemm_rs.overlap_pct"] <= 100.0
        assert "comms.ag_gemm.exposed_comm_ms" in gauges
    finally:
        obs.disable()
