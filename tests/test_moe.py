"""MoE / EP tests: routing utils, LL all-to-all, EP dispatch/combine,
grouped GEMM, MoE-RS, and the TP-MoE layer vs a dense golden.

Mirrors the reference's test spine (SURVEY.md §4): correctness vs a
brute-force golden on an 8-device mesh — test_all_to_all.py,
test_ep_a2a.py, test_ag_moe.py, test_moe_reduce_rs.py, test_tp_moe.py
collapsed into one single-process suite.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.ops.moe_utils import (
    topk_routing, dispatch_layout, scatter_to_slabs)
from triton_dist_tpu.ops.all_to_all import (
    create_all_to_all_context, fast_all_to_all)
from triton_dist_tpu.ops.group_gemm import (
    grouped_matmul, grouped_expert_ffn, create_ag_group_gemm_context,
    ag_group_gemm)
from triton_dist_tpu.ops.moe_reduce_rs import (
    create_moe_rs_context, moe_reduce_rs)
from triton_dist_tpu.layers.ep_a2a import EPAll2AllLayer
from triton_dist_tpu.layers.ep_moe import EPMoE
from triton_dist_tpu.layers.tp_moe import TPMoE

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow


def dense_moe_golden(x, w_router, w_gate, w_up, w_down, topk,
                     norm_topk_prob=True):
    """Brute-force per-token MoE (fp32): the NCCL-golden analog."""
    x32 = np.asarray(x, np.float32)
    logits = x32 @ np.asarray(w_router, np.float32)
    e = logits.shape[-1]
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    idx = np.argsort(-probs, axis=-1, kind="stable")[:, :topk]
    w = np.take_along_axis(probs, idx, axis=-1)
    if norm_topk_prob:
        w /= w.sum(-1, keepdims=True)
    out = np.zeros_like(x32)
    for t in range(x.shape[0]):
        for k in range(topk):
            ex = idx[t, k]
            g = x32[t] @ np.asarray(w_gate[ex], np.float32)
            u = x32[t] @ np.asarray(w_up[ex], np.float32)
            act = (g / (1 + np.exp(-g))) * u
            out[t] += w[t, k] * (act @ np.asarray(w_down[ex], np.float32))
    return out


def test_topk_routing():
    logits = jnp.array([[1.0, 3.0, 2.0, -1.0]])
    w, idx = topk_routing(logits, 2)
    assert idx.tolist() == [[1, 2]]
    np.testing.assert_allclose(np.asarray(w).sum(), 1.0, rtol=1e-6)


def test_dispatch_layout_positions():
    idx = jnp.array([[0, 3], [1, 3], [0, 2]], jnp.int32)  # E=4, world=2
    meta = dispatch_layout(idx, num_experts=4, world=2, capacity=4)
    # dest = expert // 2
    assert meta["dest"].tolist() == [[0, 1], [0, 1], [0, 1]]
    # positions are unique per destination and dense from 0
    assert meta["send_counts"].tolist() == [3, 3]
    for r in range(2):
        pos = np.asarray(meta["pos"])[np.asarray(meta["dest"]) == r]
        assert sorted(pos.tolist()) == [0, 1, 2]
    assert bool(np.all(np.asarray(meta["valid"])))


def test_dispatch_layout_capacity_drop():
    idx = jnp.zeros((5, 1), jnp.int32)  # all to rank 0
    meta = dispatch_layout(idx, num_experts=2, world=2, capacity=3)
    assert int(meta["send_counts"][0]) == 3
    assert int(np.asarray(meta["valid"]).sum()) == 3


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_fast_all_to_all(mesh8, impl):
    world, cap, h = 8, 16, 128
    ctx = create_all_to_all_context(mesh8, "tp", capacity=cap)
    key = jax.random.PRNGKey(0)
    buf = jax.random.normal(key, (world * world, cap, h), jnp.float32)
    counts = jax.random.randint(jax.random.PRNGKey(1), (world * world,),
                                0, cap + 1, jnp.int32)
    sharded = jax.device_put(buf, NamedSharding(mesh8, P("tp")))
    counts = jax.device_put(counts, NamedSharding(mesh8, P("tp")))

    recv, rcounts = fast_all_to_all(sharded, counts, ctx, impl=impl)
    recv = np.asarray(recv).reshape(world, world, cap, h)
    rcounts = np.asarray(rcounts).reshape(world, world)
    sent = np.asarray(buf).reshape(world, world, cap, h)
    scounts = np.asarray(counts).reshape(world, world)
    for dst in range(world):
        for src in range(world):
            assert rcounts[dst, src] == scounts[src, dst]
            n = rcounts[dst, src]
            # only live rows are defined
            np.testing.assert_array_equal(recv[dst, src, :n],
                                          sent[src, dst, :n])


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_fast_all_to_all_fp8(mesh8, impl):
    # Reference headline config class: fp8 tokens + per-row scales
    # (README.md:97; low_latency_all_to_all.py scale channel).
    from triton_dist_tpu.ops.all_to_all import fast_all_to_all_fp8
    world, cap, h = 8, 16, 128
    ctx = create_all_to_all_context(mesh8, "tp", capacity=cap)
    buf = jax.random.normal(jax.random.PRNGKey(2),
                            (world * world, cap, h), jnp.bfloat16)
    # Mixed magnitudes stress the per-row scale (1e-3 .. 1e3).
    mags = 10.0 ** jax.random.uniform(jax.random.PRNGKey(3),
                                      (world * world, cap, 1),
                                      minval=-3, maxval=3)
    buf = (buf.astype(jnp.float32) * mags).astype(jnp.bfloat16)
    counts = jax.random.randint(jax.random.PRNGKey(4), (world * world,),
                                0, cap + 1, jnp.int32)
    sharded = jax.device_put(buf, NamedSharding(mesh8, P("tp")))
    counts_s = jax.device_put(counts, NamedSharding(mesh8, P("tp")))

    recv, rcounts = fast_all_to_all_fp8(sharded, counts_s, ctx, impl=impl)
    assert recv.dtype == jnp.bfloat16
    recv = np.asarray(recv, np.float32).reshape(world, world, cap, h)
    rcounts = np.asarray(rcounts).reshape(world, world)
    sent = np.asarray(buf, np.float32).reshape(world, world, cap, h)
    scounts = np.asarray(counts).reshape(world, world)
    for dst in range(world):
        for src in range(world):
            assert rcounts[dst, src] == scounts[src, dst]
            n = rcounts[dst, src]
            if n == 0:
                continue
            got, want = recv[dst, src, :n], sent[src, dst, :n]
            # fp8 e4m3 relative error ~2^-3 worst case per element;
            # row-scaled so tolerance is relative to the row max.
            row_max = np.abs(want).max(axis=-1, keepdims=True) + 1e-9
            assert np.max(np.abs(got - want) / row_max) < 0.07


def test_fp8_quantize_roundtrip():
    from triton_dist_tpu.ops.all_to_all import (
        dequantize_fp8_rows, quantize_fp8_rows)
    x = jnp.array([[0.0, 0.0, 0.0], [1.0, -448.0, 2.0],
                   [1e-4, 2e-4, -3e-4]], jnp.float32)
    q, s = quantize_fp8_rows(x)
    assert q.dtype == jnp.float8_e4m3fn and s.shape == (3,)
    back = dequantize_fp8_rows(q, s, jnp.float32)
    assert np.allclose(np.asarray(back[0]), 0.0)          # zero row exact
    rel = np.abs(np.asarray(back) - np.asarray(x)) / (
        np.abs(np.asarray(x)).max(axis=-1, keepdims=True) + 1e-12)
    assert rel.max() < 0.07


def test_moe_align_block_size_native_matches_numpy():
    from triton_dist_tpu.ops import moe_utils as mu
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 4, size=37).astype(np.int32)
    assert mu._moe_native() is not None, "C++ moe_align failed to build"
    native = mu.moe_align_block_size(ids, 4, 8)
    # force the numpy fallback path
    saved = mu._MOE_LIB
    mu._MOE_LIB = None
    try:
        pyver = mu.moe_align_block_size(ids, 4, 8)
    finally:
        mu._MOE_LIB = saved
    for k in native:
        np.testing.assert_array_equal(native[k], pyver[k], err_msg=k)
    # invariants: order sorts ids stably; offsets tile-aligned
    sorted_ids = ids[native["sorted_order"]]
    assert (np.diff(sorted_ids) >= 0).all()
    assert (native["padded_offsets"] % 8 == 0).all()
    assert len(native["block_expert"]) == sum(
        -(-c // 8) for c in native["expert_counts"])


def test_grouped_matmul_matches_loop(key):
    t, kdim, n, e = 32, 16, 24, 4
    x = jax.random.normal(key, (t, kdim), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(7), (e, kdim, n), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(8), (t,), 0, e, jnp.int32)
    out = grouped_matmul(x, w, ids, e)
    ref = np.stack([np.asarray(x[i]) @ np.asarray(w[int(ids[i])])
                    for i in range(t)])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_grouped_expert_ffn_sentinel_masked(key):
    t, h, i, e = 16, 8, 12, 3
    x = jax.random.normal(key, (t, h), jnp.float32)
    wg = jax.random.normal(jax.random.PRNGKey(1), (e, h, i), jnp.float32)
    wu = jax.random.normal(jax.random.PRNGKey(2), (e, h, i), jnp.float32)
    wd = jax.random.normal(jax.random.PRNGKey(3), (e, i, h), jnp.float32)
    ids = jnp.concatenate([jnp.zeros((8,), jnp.int32),
                           jnp.full((8,), e, jnp.int32)])  # half invalid
    out = grouped_expert_ffn(x, wg, wu, wd, ids, e)
    # valid rows match a manual swiglu through expert 0
    g = np.asarray(x[:8]) @ np.asarray(wg[0])
    u = np.asarray(x[:8]) @ np.asarray(wu[0])
    ref = ((g / (1 + np.exp(-g))) * u) @ np.asarray(wd[0])
    np.testing.assert_allclose(np.asarray(out[:8]), ref, rtol=1e-4,
                               atol=1e-4)


@pytest.mark.parametrize("impl", ["xla", "ring", "auto"])
def test_ag_group_gemm(mesh8, impl, key):
    world, rows, kdim, n, e = 8, 4, 16, 256, 4
    m = world * rows
    x = jax.random.normal(key, (m, kdim), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(5), (e, kdim, n), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(6), (m,), 0, e, jnp.int32)
    xs = jax.device_put(x, NamedSharding(mesh8, P("tp")))
    ws = jax.device_put(w, NamedSharding(mesh8, P(None, None, "tp")))
    ids_s = jax.device_put(ids, NamedSharding(mesh8, P("tp")))
    ctx = create_ag_group_gemm_context(mesh8, "tp")
    out = ag_group_gemm(xs, ws, ids_s, e, ctx, impl=impl)
    ref = np.stack([np.asarray(x[i]) @ np.asarray(w[int(ids[i])])
                    for i in range(m)])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("impl", ["xla", "ring", "fused", "auto"])
def test_moe_reduce_rs(mesh8, impl, key):
    world, rows, i, h, e, topk = 8, 4, 32, 16, 4, 2
    t = world * rows
    act = jax.random.normal(key, (t * topk, i), jnp.float32)
    wd = jax.random.normal(jax.random.PRNGKey(2), (e, i, h), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(3), (t * topk,), 0, e,
                             jnp.int32)
    wts = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(4), (t, topk)), axis=-1)
    ctx = create_moe_rs_context(mesh8, "tp", num_experts=e, topk=topk)
    act_s = jax.device_put(act, NamedSharding(mesh8, P(None, "tp")))
    wd_s = jax.device_put(wd, NamedSharding(mesh8, P(None, "tp", None)))
    out = moe_reduce_rs(act_s, wd_s, ids, wts, ctx, impl=impl)
    # golden: full-I down-proj, weighted reduce (no sharding)
    pair = np.stack([np.asarray(act[i_]) @ np.asarray(wd[int(ids[i_])])
                     for i_ in range(t * topk)]).reshape(t, topk, h)
    ref = (pair * np.asarray(wts)[..., None]).sum(1)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_ep_dispatch_combine_roundtrip(mesh8, impl, key):
    """Identity expert: combine(dispatch(x)) == x (weights sum to 1)."""
    world, rows, h, e, topk = 8, 8, 128, 16, 2
    t = world * rows
    layer = EPAll2AllLayer(max_tokens=rows, hidden=h, topk=topk,
                           num_experts=e, mesh=mesh8, axis="tp",
                           dtype=jnp.float32, impl=impl)
    x = jax.random.normal(key, (t, h), jnp.float32)
    idx = jax.random.randint(jax.random.PRNGKey(1), (t, topk), 0, e,
                             jnp.int32)
    wts = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(2), (t, topk)), axis=-1)
    xs = jax.device_put(x, NamedSharding(mesh8, P("tp")))
    idx_s = jax.device_put(idx, NamedSharding(mesh8, P("tp")))
    wts_s = jax.device_put(wts, NamedSharding(mesh8, P("tp")))

    tokens, local_expert, handle = layer.dispatch(xs, idx_s)
    assert tokens.shape == (world * world * layer.capacity, h)
    out = layer.combine(tokens, wts_s, handle)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-5,
                               atol=1e-5)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_ep_dispatch_fp8_wire(mesh8, impl, key):
    """wire_dtype='fp8': identity-expert roundtrip within fp8 tolerance
    (reference LL-a2a fp8 config, README.md:97)."""
    world, rows, h, e, topk = 8, 8, 128, 16, 2
    t = world * rows
    layer = EPAll2AllLayer(max_tokens=rows, hidden=h, topk=topk,
                           num_experts=e, mesh=mesh8, axis="tp",
                           dtype=jnp.bfloat16, impl=impl,
                           wire_dtype="fp8")
    x = jax.random.normal(key, (t, h), jnp.bfloat16)
    idx = jax.random.randint(jax.random.PRNGKey(1), (t, topk), 0, e,
                             jnp.int32)
    wts = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(2), (t, topk)), axis=-1
    ).astype(jnp.bfloat16)
    xs = jax.device_put(x, NamedSharding(mesh8, P("tp")))
    idx_s = jax.device_put(idx, NamedSharding(mesh8, P("tp")))
    wts_s = jax.device_put(wts, NamedSharding(mesh8, P("tp")))

    tokens, _, handle = layer.dispatch(xs, idx_s)
    out = layer.combine(tokens, wts_s, handle)
    want = np.asarray(x, np.float32)
    got = np.asarray(out, np.float32)
    denom = np.abs(want).max(axis=-1, keepdims=True) + 1e-9
    assert np.max(np.abs(got - want) / denom) < 0.1


def test_ep_moe_vs_dense(mesh8, key):
    """Full EP MoE: dispatch → grouped expert FFN (per-rank expert shard)
    → combine, vs the brute-force dense golden."""
    world, rows, h, i, e, topk = 8, 4, 16, 24, 16, 2
    t = world * rows
    epr = e // world
    x = jax.random.normal(key, (t, h), jnp.float32) * 0.5
    wr = jax.random.normal(jax.random.PRNGKey(1), (h, e), jnp.float32)
    wg = jax.random.normal(jax.random.PRNGKey(2), (e, h, i), jnp.float32)
    wu = jax.random.normal(jax.random.PRNGKey(3), (e, h, i), jnp.float32)
    wd = jax.random.normal(jax.random.PRNGKey(4), (e, i, h), jnp.float32)

    logits = x @ wr
    wts, idx = topk_routing(logits, topk)

    layer = EPAll2AllLayer(max_tokens=rows, hidden=h, topk=topk,
                           num_experts=e, mesh=mesh8, axis="tp",
                           dtype=jnp.float32, impl="xla")
    sh = lambda a, spec: jax.device_put(a, NamedSharding(mesh8, spec))
    tokens, local_expert, handle = layer.dispatch(sh(x, P("tp")),
                                                  sh(idx, P("tp")))

    # Expert compute per rank on its expert shard (E/world experts).
    from jax import shard_map
    from triton_dist_tpu.ops.group_gemm import grouped_expert_ffn as ffn

    def local_ffn(tok, le, g, u, d):
        return ffn(tok, g, u, d, le, epr)
    out_tok = jax.shard_map(
        local_ffn, mesh=mesh8,
        in_specs=(P("tp"), P("tp"), P("tp"), P("tp"), P("tp")),
        out_specs=P("tp"), check_vma=False)(
        tokens, local_expert,
        sh(wg, P("tp")), sh(wu, P("tp")), sh(wd, P("tp")))

    out = layer.combine(out_tok, sh(wts, P("tp")), handle)
    ref = dense_moe_golden(x, wr, wg, wu, wd, topk)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("mode", ["xla", "ag_rs"])
def test_tp_moe_vs_dense(mesh8, mode, key):
    world, rows, h, i, e, topk = 8, 4, 16, 32, 4, 2
    t = world * rows
    layer = TPMoE(hidden_size=h, intermediate_size=i, num_experts=e,
                  topk=topk, mesh=mesh8, axis="tp", dtype=jnp.float32)
    params = layer.init(key)
    full = {k: np.asarray(jax.device_get(v)) for k, v in params.items()}
    x = jax.random.normal(jax.random.PRNGKey(9), (t, h), jnp.float32) * 0.5
    xs = jax.device_put(x, NamedSharding(mesh8, P("tp")))
    out = layer(params, xs, mode=mode)
    ref = dense_moe_golden(x, full["w_router"], full["w_gate"],
                           full["w_up"], full["w_down"], topk)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_ep_moe_layer_vs_dense(mesh8, impl, key):
    """EPMoE layer (router → dispatch → per-rank experts → combine) vs
    the brute-force dense golden — VERDICT r1 item 4 gate."""
    world, rows, h, i, e, topk = 8, 4, 16, 24, 16, 2
    t = world * rows
    layer = EPMoE(h, i, e, topk, mesh=mesh8, axis="tp",
                  dtype=jnp.float32, impl=impl)
    params = layer.init(key)
    x = jax.random.normal(jax.random.PRNGKey(7), (t, h), jnp.float32) * 0.5
    xs = jax.device_put(x, NamedSharding(mesh8, P("tp")))
    out = layer(params, xs)
    ref = dense_moe_golden(
        x, params["w_router"], params["w_gate"], params["w_up"],
        params["w_down"], topk)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-3)


def test_ep_moe_layer_matches_tp_moe(mesh8, key):
    """EP and TP parallelizations of the same MoE weights agree."""
    world, rows, h, i, e, topk = 8, 4, 16, 24, 16, 2
    t = world * rows
    ep = EPMoE(h, i, e, topk, mesh=mesh8, axis="tp", dtype=jnp.float32)
    tp = TPMoE(h, i, e, topk, mesh=mesh8, axis="tp", dtype=jnp.float32)
    ep_params = ep.init(key)
    tp_params = tp.shard_params(
        {k: np.asarray(v) for k, v in ep_params.items()})
    x = jax.random.normal(jax.random.PRNGKey(8), (t, h), jnp.float32) * 0.5
    xs = jax.device_put(x, NamedSharding(mesh8, P("tp")))
    out_ep = ep(ep_params, xs)
    out_tp = tp(tp_params, xs, mode="ag_rs")
    np.testing.assert_allclose(np.asarray(out_ep), np.asarray(out_tp),
                               rtol=1e-3, atol=1e-3)


def test_ag_group_gemm_fused_kernel(mesh8, key):
    """ONE-Pallas-kernel AG + grouped GEMM over the tile-aligned schedule
    matches the xla golden (VERDICT r2 next 7; reference fused
    producer/consumer allgather_group_gemm.py:608)."""
    from triton_dist_tpu.ops.group_gemm import (
        create_ag_group_gemm_context, ag_group_gemm)
    world, n_exp = 8, 4
    m, k, n = world * 16, 64, world * 32
    rng = np.random.RandomState(3)
    x = jax.device_put(jnp.asarray(rng.randn(m, k) / 4, jnp.float32),
                       NamedSharding(mesh8, P("tp")))
    w = jax.device_put(
        jnp.asarray(rng.randn(n_exp, k, n) / 4, jnp.float32),
        NamedSharding(mesh8, P(None, None, "tp")))
    eid = jax.device_put(
        jnp.asarray(rng.randint(0, n_exp, m), jnp.int32),
        NamedSharding(mesh8, P("tp")))
    ctx = create_ag_group_gemm_context(mesh8, "tp")
    ctx.block_m, ctx.block_n = 8, 32
    got = ag_group_gemm(x, w, eid, n_exp, ctx, impl="fused")
    gold = ag_group_gemm(x, w, eid, n_exp, ctx, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(gold),
                               rtol=1e-4, atol=1e-4)


def test_align_tokens_for_tiles_schedule():
    """Every tile of the aligned layout touches exactly one expert and
    dest maps rows back losslessly."""
    from triton_dist_tpu.ops.group_gemm import align_tokens_for_tiles
    rng = np.random.RandomState(0)
    m, k, e, blk = 50, 8, 4, 8
    tokens = jnp.asarray(rng.randn(m, k), jnp.float32)
    ids = jnp.asarray(rng.randint(0, e, m), jnp.int32)
    padded, tile_e, dest = align_tokens_for_tiles(tokens, ids, e, blk)
    padded, tile_e, dest = map(np.asarray, (padded, tile_e, dest))
    # round trip
    np.testing.assert_allclose(padded[dest], np.asarray(tokens))
    # one expert per tile: every live row's tile expert matches its id
    for r in range(m):
        t = dest[r] // blk
        assert tile_e[t] == int(ids[r]), (r, t)


def test_moe_reduce_rs_fused_kernel(mesh8, key):
    """Single-kernel MoE down-proj + topk-reduce + ring RS matches the
    xla golden (VERDICT r2 next 7; reference fused producer/reducer
    moe_reduce_rs.py:167-546)."""
    from triton_dist_tpu.ops.moe_reduce_rs import (
        create_moe_rs_context, moe_reduce_rs)
    world, n_exp, topk = 8, 4, 2
    t, inter, hid = world * 8, 128, 256
    rng = np.random.RandomState(5)
    ctx = create_moe_rs_context(mesh8, "tp", num_experts=n_exp, topk=topk)
    ctx.block_m, ctx.block_h = 8, 64
    act = jax.device_put(
        jnp.asarray(rng.randn(t * topk, inter) / 4, jnp.float32),
        NamedSharding(mesh8, P(None, "tp")))
    wdown = jax.device_put(
        jnp.asarray(rng.randn(n_exp, inter, hid) / 4, jnp.float32),
        NamedSharding(mesh8, P(None, "tp")))
    eid = jnp.asarray(rng.randint(0, n_exp, t * topk), jnp.int32)
    wts = jnp.asarray(
        np.abs(rng.randn(t, topk)) / topk, jnp.float32)
    got = moe_reduce_rs(act, wdown, eid, wts, ctx, impl="fused")
    gold = moe_reduce_rs(act, wdown, eid, wts, ctx, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(gold),
                               rtol=2e-3, atol=2e-3)
