"""Collective building-block tests vs jax.lax goldens (reference analogs:
test_fast_allgather.py, test_reduce_scatter.py, test_allreduce.py —
SURVEY.md §7 stage 2 gate)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.ops.allgather import (
    AllGatherMethod, all_gather, create_allgather_context,
    get_auto_all_gather_method)
from triton_dist_tpu.ops.allreduce import (
    AllReduceMethod, all_reduce, create_allreduce_context)
from triton_dist_tpu.ops.reduce_scatter import (
    ReduceScatterMethod, create_reduce_scatter_context, reduce_scatter)
from triton_dist_tpu.runtime.utils import assert_allclose, bitwise_equal

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow

WORLD = 8


def _mk(key, shape, dtype):
    return (jax.random.normal(key, shape) * 4).astype(dtype)


@pytest.mark.parametrize("method", [AllGatherMethod.RING_1D,
                                    AllGatherMethod.RING_BIDIR,
                                    AllGatherMethod.FULL_MESH_PUSH])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_all_gather(mesh8, key, method, dtype):
    x = _mk(key, (WORLD * 16, 128), dtype)
    ctx = create_allgather_context(mesh8, method=method)
    got = all_gather(x, ctx, impl="pallas", stacked=True)
    ref = all_gather(x, ctx, impl="xla", stacked=True)
    # pure data movement → bitwise
    assert bitwise_equal(got, ref)
    # every device's copy equals the concatenated input
    got = np.asarray(got).reshape(WORLD, WORLD * 16, 128)
    for d in range(WORLD):
        assert np.array_equal(got[d], np.asarray(x)), f"device {d}"


def test_all_gather_auto_method():
    assert get_auto_all_gather_method(2, 1 << 30) == \
        AllGatherMethod.FULL_MESH_PUSH
    assert get_auto_all_gather_method(8, 1 << 10) == \
        AllGatherMethod.FULL_MESH_PUSH
    assert get_auto_all_gather_method(8, 1 << 30) == \
        AllGatherMethod.RING_BIDIR


@pytest.mark.parametrize("method", [AllGatherMethod.RING_1D,
                                    AllGatherMethod.RING_BIDIR,
                                    AllGatherMethod.FULL_MESH_PUSH])
@pytest.mark.parametrize("shape,dtype", [
    ((WORLD * 8, 128), jnp.float32),    # one (8,128) f32 tile per rank
    ((WORLD * 16, 128), jnp.bfloat16),  # one (16,128) bf16 tile per rank
])
def test_all_gather_small_msg(mesh8, key, method, shape, dtype):
    """Latency-class payloads — one minimum TPU tile per rank (4 KB) —
    must stay correct on every method (reference test_ag_small_msg.py:
    the LL-allgather family's domain; here the same kernels serve both
    regimes and AUTO picks FULL_MESH_PUSH below the perf-model
    crossover)."""
    x = _mk(key, shape, dtype)
    ctx = create_allgather_context(mesh8, method=method)
    got = all_gather(x, ctx, impl="pallas", stacked=True)
    ref = all_gather(x, ctx, impl="xla", stacked=True)
    assert bitwise_equal(got, ref)


@pytest.mark.parametrize("method", [ReduceScatterMethod.RING,
                                    ReduceScatterMethod.ONE_SHOT])
@pytest.mark.parametrize("dtype", [jnp.float32])
def test_reduce_scatter(mesh8, key, method, dtype):
    x = _mk(key, (WORLD, WORLD * 8, 128), dtype)
    ctx = create_reduce_scatter_context(mesh8, method=method)
    got = reduce_scatter(x, ctx, impl="pallas")
    ref = np.asarray(x, np.float64).sum(axis=0)
    assert got.shape == (WORLD * 8, 128)
    assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    # xla impl agrees with the analytic golden too
    xla = reduce_scatter(x, ctx, impl="xla")
    assert_allclose(xla, ref, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("method", [AllReduceMethod.ONE_SHOT,
                                    AllReduceMethod.TWO_SHOT,
                                    AllReduceMethod.RECURSIVE_DOUBLING])
def test_all_reduce(mesh8, key, method):
    x = _mk(key, (WORLD, 32, 128), jnp.float32)
    ctx = create_allreduce_context(mesh8, method=method)
    got = all_reduce(x, ctx, impl="pallas", stacked=True)
    ref = np.asarray(x, np.float64).sum(axis=0)
    got = np.asarray(got)
    assert got.shape == (WORLD, 32, 128)
    for d in range(WORLD):
        assert_allclose(got[d], ref, rtol=1e-4, atol=1e-4)


def test_all_reduce_straggler(mesh8, key):
    """Correctness must hold under an injected straggler (reference
    straggler_option allreduce.py:137). pl.delay is a TPU-only primitive;
    in interpret mode the option must at least be accepted."""
    x = _mk(key, (WORLD, 16, 128), jnp.float32)
    try:
        ctx = create_allreduce_context(
            mesh8, method=AllReduceMethod.ONE_SHOT,
            straggler_option=(3, 1000))
        got = all_reduce(x, ctx, impl="pallas")
    except Exception:
        pytest.skip("pl.delay unsupported in interpret mode")
    assert_allclose(got, np.asarray(x, np.float64).sum(axis=0),
                    rtol=1e-4, atol=1e-4)


def test_all_reduce_jit_composes(mesh8, key):
    """Ops must compose under jit with surrounding computation."""
    x = _mk(key, (WORLD, 16, 128), jnp.float32)
    ctx = create_allreduce_context(mesh8, method=AllReduceMethod.ONE_SHOT)

    @jax.jit
    def f(x):
        return all_reduce(x * 2.0, ctx, impl="pallas") + 1.0

    got = f(x)
    ref = np.asarray(x, np.float64).sum(axis=0) * 2 + 1
    assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast(mesh8, key, root):
    """Root-push broadcast (reference LL-AG broadcast variants,
    low_latency_allgather.py:48-210): every device ends with the root's
    chunk."""
    from triton_dist_tpu.ops.allgather import (
        create_allgather_context, broadcast)
    x = _mk(key, (WORLD * 16, 128), jnp.float32)
    ctx = create_allgather_context(mesh8, "tp")
    got = broadcast(x, root=root, ctx=ctx, impl="pallas")
    expect = np.asarray(x).reshape(WORLD, 16, 128)[root]
    np.testing.assert_allclose(np.asarray(got), expect)
    gold = broadcast(x, root=root, ctx=ctx, impl="xla")
    np.testing.assert_allclose(np.asarray(gold), expect)


def test_all_reduce_recursive_doubling_odd_rows(mesh8, key):
    """RECURSIVE_DOUBLING has no row-divisibility requirement (unlike
    TWO_SHOT) — odd M exercises the full-buffer exchange."""
    x = _mk(key, (WORLD, 24, 128), jnp.float32)
    ctx = create_allreduce_context(
        mesh8, method=AllReduceMethod.RECURSIVE_DOUBLING)
    got = all_reduce(x, ctx, impl="pallas")
    assert_allclose(got, np.asarray(x, np.float64).sum(axis=0),
                    rtol=1e-4, atol=1e-4)
