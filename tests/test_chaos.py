"""Chaos-harness injector verification (testing/chaos.py, ISSUE 15).

Quick tier. Each injector is pinned to EXACTLY the failure signature
and FleetView/breaker transition it claims, against a live fleet —
so the router tests (tests/test_router.py) and the ``serving_router``
bench can trust the faults they inject:

- ``kill_replica``: new connections refuse, in-flight clients see a
  DEAD SOCKET (never a polite error reply), FleetView degrades the
  victim live → stale → down on an injected clock while its sibling
  stays fresh;
- ``wedge_pump``: requests stall (client timeout) while the replica
  KEEPS answering the health verb — the failure class liveness
  checks cannot catch (the router's dispatch deadline/breaker does);
  releasing the wedge restores service;
- ``ChaosProxy`` blackhole / drop / delay: scrapes through the proxy
  fail (hang-to-timeout, instant close, reply past the deadline) →
  stale → down, and flipping back to ``forward`` recovers to live —
  without ever touching the replica behind it;
- ``ChaosProxy.sever``: a mid-request connection cut surfaces as a
  socket error on the client side.
"""

import socket
import threading
import time

import jax.numpy as jnp
import pytest

from triton_dist_tpu.obs.fleet import FleetView
from triton_dist_tpu.serving import ChatClient, ModelServer
from triton_dist_tpu.testing import chaos


class _FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def tiny(request):
    import jax
    import numpy as np
    from jax.sharding import Mesh
    from triton_dist_tpu.models import DenseLLM, ModelConfig
    mesh = Mesh(np.array(jax.devices()), ("tp",))
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="xla")
    return model, model.init(jax.random.PRNGKey(0))


def _server(tiny, rid, **kw):
    from triton_dist_tpu.models import Engine
    model, params = tiny
    eng = Engine(model, batch=2, max_seq=64, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    return ModelServer(eng, params, port=0, registry="private",
                       replica_id=rid, **kw).start()


def _wait(pred, timeout=30.0, what="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# kill_replica
# ---------------------------------------------------------------------------

def test_kill_refuses_new_connections_and_transitions_down(tiny):
    """The claimed FleetView transition: live → (kill) → stale →
    down by age, sibling fresh throughout; and the killed listener
    refuses new connections outright."""
    s0 = _server(tiny, "chaos-a")
    s1 = _server(tiny, "chaos-b")
    eps = [(s0.host, s0.port), (s1.host, s1.port)]
    try:
        clock = _FakeClock()
        view = FleetView(eps, stale_s_=5.0, down_s_=20.0, clock=clock)
        assert [r["status"] for r in view.poll()] == ["live", "live"]

        chaos.kill_replica(s1)
        with pytest.raises(OSError):
            socket.create_connection(eps[1], timeout=2.0)

        clock.t += 1.0
        rows = view.poll()
        assert rows[0]["status"] == "live"
        assert rows[1]["status"] == "stale"
        clock.t += 25.0
        rows = view.poll()
        assert rows[0]["status"] == "live"
        assert rows[1]["status"] == "down"
        # live traffic still lands on the survivor
        c = ChatClient(s0.host, s0.port, timeout=60)
        assert "tokens" in c.generate_ids([[1, 2]], gen_len=2)
        c.close()
    finally:
        s0.stop()
        s1.stop()


def test_kill_severs_inflight_connection_abruptly(tiny):
    """A client mid-generation on the victim sees a DEAD SOCKET
    (ConnectionError/OSError) — not a structured error reply: a
    killed process sends nothing. This is what lets the router treat
    the kill as a transport failure and re-dispatch."""
    srv = _server(tiny, "chaos-kill")
    try:
        got: dict = {}

        def bg():
            c = ChatClient(srv.host, srv.port, timeout=60)
            try:
                got["resp"] = c.generate_ids([[1, 2, 3]], gen_len=60)
            except OSError as e:
                got["err"] = e
            finally:
                c.close()

        th = threading.Thread(target=bg, daemon=True)
        th.start()
        _wait(lambda: srv.scheduler.inflight() >= 1,
              what="request in flight")
        chaos.kill_replica(srv)
        th.join(timeout=60)
        assert not th.is_alive()
        assert "err" in got, got     # dead socket, not an error reply
        assert isinstance(got["err"], OSError)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# wedge_pump
# ---------------------------------------------------------------------------

def test_wedge_stalls_requests_health_stays_live(tiny):
    """The wedge's claimed signature: in-flight requests STALL
    (client timeout) while the health verb keeps answering — the
    replica looks alive to liveness checks while serving nothing.
    Release restores service."""
    srv = _server(tiny, "chaos-wedge")
    try:
        c = ChatClient(srv.host, srv.port, timeout=60)
        # Warm the compile OUTSIDE the wedge so the stall below is
        # the wedge, not a cold jit.
        assert "tokens" in c.generate_ids([[1, 2]], gen_len=2)
        with chaos.wedge_pump(srv.scheduler) as w:
            raw = ChatClient(srv.host, srv.port, retry_shed=False)
            with pytest.raises(TimeoutError):
                raw.generate_ids([[3, 4]], gen_len=2, timeout=1.0)
            raw.close()
            assert w.fired.is_set()      # provably wedged, not idle
            # Health still answers — from the handler threads.
            h = c.health()
            assert h["replica_id"] == "chaos-wedge"
            assert srv.scheduler.inflight() >= 1
        # Released: the stalled request finishes server-side; new
        # requests serve normally again.
        _wait(lambda: srv.scheduler.inflight() == 0,
              what="wedge drained")
        assert "tokens" in c.generate_ids([[5, 6]], gen_len=2)
        c.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# ChaosProxy: blackhole / drop / delay / sever
# ---------------------------------------------------------------------------

@pytest.fixture()
def proxied(tiny):
    srv = _server(tiny, "chaos-proxy")
    proxy = chaos.ChaosProxy((srv.host, srv.port))
    yield srv, proxy
    proxy.stop()
    srv.stop()


def test_proxy_forward_is_transparent(proxied):
    srv, proxy = proxied
    c = ChatClient(*proxy.endpoint, timeout=60)
    resp = c.generate_ids([[1, 2]], gen_len=2)
    assert "tokens" in resp
    assert c.health()["replica_id"] == "chaos-proxy"
    c.close()


def test_blackhole_scrape_times_out_stale_then_down_then_recovers(
        proxied):
    """Blackhole: the scrape hangs to its timeout (connection
    accepted, nothing answers) → stale → down by age; forward mode
    recovers to live. The replica itself is never touched."""
    srv, proxy = proxied
    clock = _FakeClock()
    view = FleetView([proxy.endpoint], timeout_s=0.3, stale_s_=5.0,
                     down_s_=20.0, clock=clock)
    (row,) = view.poll()
    assert row["status"] == "live"

    proxy.set_mode("blackhole")
    clock.t += 1.0
    (row,) = view.poll()
    assert row["status"] == "stale"
    assert row["health"] is not None     # last-good retained
    clock.t += 25.0
    (row,) = view.poll()
    assert row["status"] == "down"

    proxy.set_mode("forward")
    (row,) = view.poll()
    assert row["status"] == "live"       # recovered


def test_drop_mode_fails_connections_fast(proxied):
    srv, proxy = proxied
    proxy.set_mode("drop")
    clock = _FakeClock()
    view = FleetView([proxy.endpoint], timeout_s=1.0, stale_s_=5.0,
                     down_s_=20.0, clock=clock)
    t0 = time.monotonic()
    (row,) = view.poll()
    assert row["status"] == "stale"      # never-scraped, scrape died
    assert row["error"]
    assert time.monotonic() - t0 < 5.0   # fast failure, not a hang


def test_delay_pushes_health_past_the_scrape_deadline(proxied):
    """Delay: the reply arrives LATER than the scrape timeout — the
    injector that drives health responses past the stale/down
    thresholds without killing anything; dropping the delay below
    the deadline recovers."""
    srv, proxy = proxied
    clock = _FakeClock()
    view = FleetView([proxy.endpoint], timeout_s=0.3, stale_s_=5.0,
                     down_s_=20.0, clock=clock)
    assert view.poll()[0]["status"] == "live"

    proxy.set_mode("forward", delay_s=1.0)   # > scrape timeout
    clock.t += 1.0
    (row,) = view.poll()
    assert row["status"] == "stale"

    proxy.set_mode("forward", delay_s=0.0)
    (row,) = view.poll()
    assert row["status"] == "live"


def test_sever_cuts_live_connections_mid_request(proxied):
    """A severed proxied connection surfaces as a socket-level error
    on the client — the mid-request connection-kill injector."""
    srv, proxy = proxied
    c = ChatClient(*proxy.endpoint, timeout=60)
    assert "tokens" in c.generate_ids([[1, 2]], gen_len=2)

    got: dict = {}

    def bg():
        try:
            got["resp"] = c.generate_ids([[1, 2, 3]], gen_len=60)
        except OSError as e:
            got["err"] = e

    th = threading.Thread(target=bg, daemon=True)
    th.start()
    _wait(lambda: srv.scheduler.inflight() >= 1,
          what="request in flight")
    assert proxy.sever() >= 1
    th.join(timeout=60)
    assert not th.is_alive()
    assert "err" in got, got
    c.close()


def test_proxy_rejects_unknown_mode(proxied):
    _, proxy = proxied
    with pytest.raises(ValueError, match="unknown chaos mode"):
        proxy.set_mode("explode")
