"""Sliding-window SLO engine (obs/slo.py, ISSUE 8).

Quick tier, pure Python: every clock is injected, so window rotation,
subwindow expiry, empty-window reads, burn-rate arithmetic, and the
fast/slow multi-window agreement rules are tested without sleeping.
The flight-recorder arming test drives a fault-injected latency spike
through a real tracker with tracing on and checks the dump is a valid
Perfetto artifact written exactly once per breach episode.

The live-scheduler integration (a real request breaching a tiny
threshold through ``{"cmd": "metrics"}``) lives in
tests/test_scheduler.py next to the other server scenarios.
"""

import json

import pytest

from triton_dist_tpu import obs
from triton_dist_tpu.obs import flight, slo, trace
from triton_dist_tpu.obs.exposition import histogram_quantile


class Clock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _wh(ck, window=60.0, subs=12, retain=10):
    return slo.WindowedHistogram(window_s_=window, subwindows_=subs,
                                 retain_windows=retain, clock=ck)


# ---------------------------------------------------------------------------
# WindowedHistogram: rotation, expiry, empty reads.
# ---------------------------------------------------------------------------

def test_window_rotation_keeps_trailing_window():
    ck = Clock()
    w = _wh(ck)
    for _ in range(10):
        w.observe(4.0)
    ck.advance(30.0)                      # still inside the 60 s window
    assert w.snapshot()["count"] == 10
    ck.advance(40.0)                      # 70 s: out of the fast window
    assert w.snapshot()["count"] == 0
    # ... but still inside the retained slow span.
    assert w.snapshot(over_s=600.0)["count"] == 10


def test_subwindow_expiry_prunes_the_ring():
    ck = Clock()
    w = _wh(ck)
    w.observe(1.0)
    ck.advance(60.0 * 10 + 5.0)           # past the full retained span
    assert w.snapshot(over_s=600.0)["count"] == 0
    w.observe(2.0)                        # triggers expiry of the old slot
    assert len(w._slots) == 1


def test_empty_window_reads():
    ck = Clock()
    w = _wh(ck)
    assert w.snapshot()["count"] == 0
    assert w.quantile(0.99) is None
    assert slo.violating_fraction(w.snapshot(), 5.0) == 0.0


def test_rolling_quantile_tracks_recent_samples_only():
    ck = Clock()
    w = _wh(ck)
    for _ in range(100):
        w.observe(2.0)                    # old regime
    ck.advance(120.0)                     # old regime leaves the window
    for _ in range(10):
        w.observe(400.0)                  # new regime
    p50 = w.quantile(0.50)
    assert 250.0 < p50 <= 500.0, p50      # sees only the regression
    # The cumulative view would have said ~2 ms: that is the bug this
    # module exists to fix.


# ---------------------------------------------------------------------------
# Burn-rate arithmetic.
# ---------------------------------------------------------------------------

def test_violating_fraction_interpolates():
    h = {"buckets": [10.0, 20.0], "counts": [5, 5, 0], "count": 10}
    assert slo.violating_fraction(h, 15.0) == pytest.approx(0.25)
    assert slo.violating_fraction(h, 10.0) == pytest.approx(0.5)
    assert slo.violating_fraction(h, 0.0) == pytest.approx(1.0)


def test_violating_fraction_overflow_needs_proof():
    # Overflow samples are provably above the top finite edge — they
    # count against thresholds at/below it, never above it (no
    # manufactured false positives).
    h = {"buckets": [10.0, 20.0], "counts": [0, 0, 4], "count": 4}
    assert slo.violating_fraction(h, 20.0) == pytest.approx(1.0)
    assert slo.violating_fraction(h, 50.0) == 0.0


def test_burn_rate_fast_slow_agreement_breaches():
    ck = Clock(1000.0)
    t = slo.SLOTracker(targets=[slo.SLOTarget("ttft", 0.9, 10.0)],
                       clock=ck)
    for _ in range(50):
        t.observe("ttft", 100.0)          # fresh spike, no history
    r = t.evaluate(force=True)
    b = r["burn"]["ttft_p90"]
    assert b["fast"] == pytest.approx(10.0)
    assert b["slow"] == pytest.approx(10.0)
    assert b["breached"]
    assert r["new_breaches"] == ["ttft_p90"]


def test_burn_rate_slow_window_vetoes_fresh_blip():
    """Fast window screaming + slow window diluted by a long good
    history = no breach (the single-blip veto)."""
    ck = Clock()
    t = slo.SLOTracker(targets=[slo.SLOTarget("ttft", 0.9, 10.0)],
                       clock=ck)
    for i in range(500):                  # 500 good samples over ~8 min
        t.observe("ttft", 1.0)
        ck.advance(1.0)
    for _ in range(10):                   # small fresh spike
        t.observe("ttft", 100.0)
    r = t.evaluate(force=True)
    b = r["burn"]["ttft_p90"]
    assert b["fast"] > 1.0                # fast window sees the spike
    assert b["slow"] < 1.0                # diluted over the history
    assert not b["breached"]


def test_burn_rate_fast_window_vetoes_stale_spike():
    """An old spike that has left the fast window cannot breach, no
    matter how bad the slow window still looks."""
    ck = Clock()
    t = slo.SLOTracker(targets=[slo.SLOTarget("ttft", 0.99, 10.0)],
                       clock=ck)
    for _ in range(20):
        t.observe("ttft", 100.0)          # spike at t=0
    ck.advance(300.0)                     # 5 min later...
    for _ in range(50):
        t.observe("ttft", 1.0)            # ...recent traffic is clean
    r = t.evaluate(force=True)
    b = r["burn"]["ttft_p99"]
    assert b["fast"] == pytest.approx(0.0)
    assert b["slow"] > 1.0
    assert not b["breached"]


def test_sparse_traffic_single_blip_cannot_breach(monkeypatch):
    """Review hardening: with only the blip itself in BOTH windows,
    fast and slow agree trivially and the multiwindow veto is void —
    the slow-window sample floor (TDT_SLO_MIN_SAMPLES) restores
    'a single slow request cannot page anyone'."""
    ck = Clock(1000.0)
    t = slo.SLOTracker(targets=[slo.SLOTarget("ttft", 0.99, 10.0)],
                       clock=ck)
    t.observe("ttft", 600.0)              # one slow request, no traffic
    b = t.evaluate(force=True)["burn"]["ttft_p99"]
    assert b["fast"] > 1.0 and b["slow"] > 1.0
    assert not b["breached"]              # sample floor vetoes
    # The floor is a knob: a deployment that wants single-sample
    # sensitivity can have it.
    monkeypatch.setenv("TDT_SLO_MIN_SAMPLES", "1")
    assert t.evaluate(force=True)["burn"]["ttft_p99"]["breached"]


def test_reset_windows_starts_fresh_epoch():
    """bench.py's warmup/timed split: reset_windows drops every
    retained subwindow so the next scrape prices only post-reset
    traffic."""
    ck = Clock()
    t = slo.SLOTracker(targets=[], clock=ck)
    for _ in range(5):
        t.observe("ttft", 100.0)
    assert t.quantile("ttft", 0.5) is not None
    t.reset_windows()
    assert t.quantile("ttft", 0.5) is None
    t.observe("ttft", 2.0)
    assert t.quantile("ttft", 0.5) < 100.0


def test_evaluate_rate_limit_and_force():
    ck = Clock()
    t = slo.SLOTracker(targets=[], clock=ck)
    assert t.evaluate() is not None
    assert t.evaluate() is None           # < EVAL_INTERVAL_S later
    assert t.evaluate(force=True) is not None
    ck.advance(2.0)
    assert t.evaluate() is not None


# ---------------------------------------------------------------------------
# Breach → flight recorder, exactly once per episode.
# ---------------------------------------------------------------------------

def test_breach_arms_flight_recorder_once_and_dump_validates(tmp_path,
                                                             monkeypatch):
    monkeypatch.setenv("TDT_TRACE_DIR", str(tmp_path))
    trace.enable()
    reg = obs.Registry()
    obs.enable(reg)
    try:
        trace.instant("serving.fake_event", "serving")
        ck = Clock(1000.0)
        t = slo.SLOTracker(
            targets=[slo.SLOTarget("ttft", 0.9, 10.0)], clock=ck)
        for _ in range(50):
            t.observe("ttft", 500.0)      # the injected latency spike
        r1 = t.evaluate(force=True)
        assert r1["burn"]["ttft_p90"]["breached"]
        rec = flight.last_record()
        assert rec is not None and rec["count"] == 1
        assert rec["reason"] == "slo_ttft_p90"
        # Sustained breach: later evaluations do NOT dump again.
        ck.advance(5.0)
        t.observe("ttft", 500.0)
        r2 = t.evaluate(force=True)
        assert r2["burn"]["ttft_p90"]["breached"]
        assert not r2["new_breaches"]
        assert flight.last_record()["count"] == 1
        assert reg.snapshot()["counters"]["serving.slo_breaches"] == 1
        # The dump is a valid Perfetto artifact.
        with open(rec["path"]) as f:
            chrome = json.load(f)
        from triton_dist_tpu.tools import trace_export
        errors, _ = trace_export.validate(chrome)
        assert errors == [], errors
        names = [ev.get("name") for ev in chrome["traceEvents"]]
        assert "serving.slo_breach.ttft_p90" in names
    finally:
        obs.disable()


def test_recovery_rearms_the_breach_transition(tmp_path, monkeypatch):
    monkeypatch.setenv("TDT_TRACE_DIR", str(tmp_path))
    reg = obs.Registry()
    obs.enable(reg)
    try:
        ck = Clock()
        t = slo.SLOTracker(
            targets=[slo.SLOTarget("ttft", 0.9, 10.0,
                                   burn_threshold=1.0)], clock=ck)
        for _ in range(50):
            t.observe("ttft", 500.0)
        assert t.evaluate(force=True)["new_breaches"]
        # Full recovery: the spike ages out of BOTH windows.
        ck.advance(601.0)
        for _ in range(50):
            t.observe("ttft", 1.0)
        assert not t.evaluate(force=True)["burn"]["ttft_p90"]["breached"]
        # A second regression is a NEW transition.
        for _ in range(50):
            t.observe("ttft", 500.0)
        assert t.evaluate(force=True)["new_breaches"] == ["ttft_p90"]
        assert reg.snapshot()["counters"]["serving.slo_breaches"] == 2
    finally:
        obs.disable()


# ---------------------------------------------------------------------------
# Targets, defaults, gauges.
# ---------------------------------------------------------------------------

def test_default_targets_env_overrides(monkeypatch):
    monkeypatch.setenv("TDT_SLO_TTFT_P99_MS", "123")
    monkeypatch.setenv("TDT_SLO_TPOT_P99_MS", "0")   # disables it
    targets = {t.metric: t for t in slo.default_targets()}
    assert targets["ttft"].threshold_ms == 123.0
    assert "tpot" not in targets
    assert "queue_wait" in targets


def test_slo_target_validation():
    with pytest.raises(ValueError):
        slo.SLOTarget("nope", 0.99, 10.0)
    with pytest.raises(ValueError):
        slo.SLOTarget("ttft", 1.5, 10.0)
    with pytest.raises(ValueError):
        slo.SLOTarget("ttft", 0.99, 0.0)
    assert slo.SLOTarget("ttft", 0.999, 5.0).name == "ttft_p99_9"


def test_evaluate_sets_rolling_and_burn_gauges():
    reg = obs.Registry()
    obs.enable(reg)
    try:
        ck = Clock()
        t = slo.SLOTracker(targets=[slo.SLOTarget("ttft", 0.99, 60000.0)],
                           clock=ck)
        for m in slo.METRICS:
            for _ in range(8):
                t.observe(m, 5.0)
        t.evaluate(force=True)
        g = reg.snapshot()["gauges"]
        for name in slo.gauge_catalog([slo.SLOTarget("ttft", 0.99,
                                                     60000.0)]):
            assert name in g, name
        assert g["serving.slo_burn.ttft_p99"] == 0.0
        assert g["serving.rolling.ttft_n"] == 8
        assert 2.5 < g["serving.rolling.ttft_p50_ms"] <= 5.0
    finally:
        obs.disable()


def test_quantile_clips_to_top_edge_in_overflow():
    """The rolling windows never track min/max — the +Inf tail must
    still yield a usable (flagged) number (obs.histogram_quantile
    overflow handling, ISSUE 8 satellite)."""
    ck = Clock()
    w = _wh(ck)
    top = slo.SLO_MS_BUCKETS[-1]
    w.observe(top * 10)
    v, clipped = histogram_quantile(w.snapshot(), 0.5, detail=True)
    assert v == top and clipped
