"""Fleet observability plane (obs/fleet.py, ISSUE 14).

Quick tier. Covered here:

- snapshot merge math BY KIND: counters sum, additive gauges sum /
  point-in-time gauges max, histograms merge bucket-wise — the fleet
  p99 interpolates the SUMMED buckets and is property-checked against
  a numpy percentile golden over the concatenated raw samples (and
  shown to differ from naively aggregated per-replica percentiles);
- staleness transitions live → stale → down → recovered with an
  injected clock and scrape function, a mid-scrape death degrading
  one replica while the other stays fresh — never an exception;
- ``placement_score`` ranking: queue depth, occupancy headroom, burn/
  breach and breaker penalties, the loaded-below-idle acceptance case;
- the two-live-``ModelServer`` acceptance scenario: both replicas
  healthy with correct fleet-summed counters and bucket-merged p99,
  private per-replica registries (``obs.scoped_registry``), kill one
  → stale → down while the other's signals stay fresh;
- the cheap ``{"cmd": "health"}`` verb (schema, monotonic seq,
  replica_id stamping into metrics snapshots and flight-dump
  filenames);
- fleet Prometheus exposition (``replica`` labels, fleet rollup);
- ``tools/fleet_top.py`` pure ``render()`` + ``--once`` against live
  servers; ``tools/report.py``'s fleet section;
- ``obs.scoped_registry`` thread isolation.
"""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu import obs
from triton_dist_tpu.obs import fleet
from triton_dist_tpu.obs.fleet import (
    FleetView, merge_fleet_snapshots, placement_score,
    render_prometheus_fleet, replica_health)
from triton_dist_tpu.obs.registry import Histogram, Registry


# ---------------------------------------------------------------------------
# Merge math.
# ---------------------------------------------------------------------------

def _hist_snapshot(samples, buckets=(1.0, 2.0, 5.0, 10.0, 50.0)):
    h = Histogram("serving.ttft_ms", threading.Lock(), buckets)
    for s in samples:
        h.observe(s)
    return h.to_dict()


def test_merged_p99_matches_numpy_golden_property():
    """Property check over random per-replica sample sets, two
    invariants per seed:

    1. EXACT: the merged quantile equals the quantile of one
       histogram built from the concatenated raw samples — merging
       bucket arrays must be indistinguishable from having observed
       the union on one replica (any per-replica-percentile
       aggregation breaks this on skewed splits);
    2. GOLDEN: on dense tails the merged p99 lands within two bucket
       widths of ``np.percentile`` over the concatenated samples
       (bucket interpolation + order-statistic convention are the
       only slack)."""
    buckets = tuple(float(b) for b in np.linspace(1, 200, 40))
    width = buckets[1] - buckets[0]
    rng = np.random.default_rng(7)
    for trial in range(10):
        a = rng.uniform(1, 60, size=rng.integers(300, 2000))
        b = rng.uniform(1, 190, size=rng.integers(300, 2000))
        snap_a = {"histograms": {"h": _hist_snapshot(a, buckets)}}
        snap_b = {"histograms": {"h": _hist_snapshot(b, buckets)}}
        merged = merge_fleet_snapshots({"ra": snap_a, "rb": snap_b})
        h = merged["histograms"]["h"]
        assert h["count"] == len(a) + len(b)
        np.testing.assert_array_equal(
            h["counts"],
            np.asarray(snap_a["histograms"]["h"]["counts"])
            + np.asarray(snap_b["histograms"]["h"]["counts"]))
        union = _hist_snapshot(np.concatenate([a, b]), buckets)
        for q in (0.5, 0.9, 0.99):
            got = obs.histogram_quantile(h, q)
            assert got == pytest.approx(
                obs.histogram_quantile(union, q)), (trial, q)
        got = obs.histogram_quantile(h, 0.99)
        want = np.percentile(np.concatenate([a, b]), 99)
        assert abs(got - want) <= 2 * width + 1e-9, (trial, got, want)


def test_merged_p99_is_not_per_replica_aggregate():
    """A skewed split where naive per-replica aggregation is wrong:
    one replica holds the slow tail, the other the fast bulk. The
    bucket-sum p99 tracks the combined distribution; the mean of
    per-replica p99s does not."""
    buckets = tuple(float(b) for b in np.linspace(1, 101, 51))
    fast = np.full(990, 3.0)        # bulk, replica A
    slow = np.full(10, 95.0)        # tail, replica B
    snap_a = {"histograms": {"h": _hist_snapshot(fast, buckets)}}
    snap_b = {"histograms": {"h": _hist_snapshot(slow, buckets)}}
    merged = merge_fleet_snapshots({"a": snap_a, "b": snap_b})
    got = obs.histogram_quantile(merged["histograms"]["h"], 0.99)
    want = np.percentile(np.concatenate([fast, slow]), 99)
    width = buckets[1] - buckets[0]
    assert abs(got - want) <= width + 1e-9
    p99_a = obs.histogram_quantile(snap_a["histograms"]["h"], 0.99)
    p99_b = obs.histogram_quantile(snap_b["histograms"]["h"], 0.99)
    mean_of_p99 = (p99_a + p99_b) / 2
    assert abs(mean_of_p99 - want) > 5 * width   # the wrong arithmetic


def test_merge_counters_sum_gauges_by_kind():
    a = {"counters": {"serving.admitted": 3, "serving.retired": 2},
         "gauges": {"serving.queue_depth": 4.0,
                    "serving.batch_occupancy": 2.0,
                    "serving.rolling.ttft_p99_ms": 80.0},
         "histograms": {}}
    b = {"counters": {"serving.admitted": 5},
         "gauges": {"serving.queue_depth": 1.0,
                    "serving.batch_occupancy": 3.0,
                    "serving.rolling.ttft_p99_ms": 120.0},
         "histograms": {}}
    m = merge_fleet_snapshots({"r0": a, "r1": b})
    assert m["counters"]["serving.admitted"] == 8
    assert m["counters"]["serving.retired"] == 2
    # Additive gauges SUM (fleet queue depth is a total)…
    assert m["gauges"]["serving.queue_depth"] == 5.0
    assert m["gauges"]["serving.batch_occupancy"] == 5.0
    # …point-in-time gauges keep the max (merge_snapshots semantics).
    assert m["gauges"]["serving.rolling.ttft_p99_ms"] == 120.0
    # Per-replica values retained verbatim.
    assert m["replicas"] == ["r0", "r1"]
    assert m["per_replica"]["r0"]["gauges"][
        "serving.queue_depth"] == 4.0
    assert m["per_replica"]["r1"]["counters"]["serving.admitted"] == 5


# ---------------------------------------------------------------------------
# placement_score.
# ---------------------------------------------------------------------------

def _health(queue=0, occ=0, batch=4, burn=0.0, breached=False,
            breakers=0):
    return {"queue_depth": queue, "batch_occupancy": occ,
            "batch": batch,
            "slo": {"ttft_p99": {"burn": burn, "burn_slow": burn,
                                 "breached": breached}},
            "breakers": {"open": breakers, "not_closed": {}}}


def test_placement_score_ranks_loaded_below_idle():
    idle = _health(queue=0, occ=0)
    loaded = _health(queue=6, occ=4)        # injected queue depth
    assert placement_score(idle) > placement_score(loaded)


def test_placement_score_penalties():
    base = placement_score(_health())
    assert placement_score(_health(occ=2)) < base          # headroom
    assert placement_score(_health(burn=3.0)) < base       # burn > 1
    assert placement_score(_health(burn=0.5)) == base      # sustainable
    assert placement_score(_health(breached=True)) < \
        placement_score(_health(burn=3.0))                 # breach worst
    assert placement_score(_health(breakers=1)) < base
    assert placement_score(None) == float("-inf")
    assert placement_score({}) == float("-inf") or \
        placement_score({}) <= placement_score(_health())


# ---------------------------------------------------------------------------
# Staleness transitions (injected clock + scrape).
# ---------------------------------------------------------------------------

class _FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_staleness_live_stale_down_recovered():
    clock = _FakeClock()
    answers = {}        # endpoint -> response dict or error dict

    def scrape(endpoints, req):
        return [answers[ep] for ep in endpoints]

    ok = {"health": {"replica_id": "r0", "seq": 1, "uptime_s": 1.0}}
    dead = {"error": "connection refused", "type": "ConnectionError"}
    view = FleetView(["127.0.0.1:1"], stale_s_=5.0, down_s_=20.0,
                     clock=clock, scrape=scrape)
    ep = view.endpoints[0]

    answers[ep] = ok
    (row,) = view.poll()
    assert row["status"] == "live" and row["replica_id"] == "r0"
    assert row["score"] is not None

    # Scrape fails: immediately not-live, last-good health RETAINED
    # with its age reported — never an exception.
    clock.t += 2.0
    answers[ep] = dead
    (row,) = view.poll()
    assert row["status"] == "stale"
    assert row["health"]["replica_id"] == "r0"   # last good, kept
    assert row["age_s"] == pytest.approx(2.0)
    assert row["error"]

    # Still failing past down_s: down, excluded from placement.
    clock.t += 25.0
    (row,) = view.poll()
    assert row["status"] == "down"
    assert row["score"] is None
    assert view.placement() == []

    # A later good scrape recovers it to live.
    answers[ep] = {"health": {"replica_id": "r0", "seq": 9,
                              "uptime_s": 30.0}}
    (row,) = view.poll()
    assert row["status"] == "live" and row["seq"] == 9

    # A SUCCESSFUL but old scrape also degrades by age (no poll ran).
    clock.t += 6.0
    (row,) = view.replicas()
    assert row["status"] == "stale"


def test_one_replica_dies_other_stays_fresh():
    clock = _FakeClock()
    state = {"b_alive": True}

    def scrape(endpoints, req):
        out = []
        for ep in endpoints:
            if ep[1] == 2 and not state["b_alive"]:
                out.append({"error": "timed out",
                            "type": "TimeoutError"})
            else:
                out.append({"health": {
                    "replica_id": f"r{ep[1]}", "seq": 1,
                    "uptime_s": 1.0, "queue_depth": 0,
                    "batch_occupancy": 0, "batch": 4}})
        return out

    view = FleetView(["127.0.0.1:1", "127.0.0.1:2"], stale_s_=5.0,
                     down_s_=20.0, clock=clock, scrape=scrape)
    rows = view.poll()
    assert [r["status"] for r in rows] == ["live", "live"]
    state["b_alive"] = False
    clock.t += 3.0
    rows = view.poll()
    assert [r["status"] for r in rows] == ["live", "stale"]
    assert rows[0]["age_s"] < 1.0              # fresh
    clock.t += 30.0
    rows = view.poll()
    assert [r["status"] for r in rows] == ["live", "down"]
    # Placement only offers the live replica.
    assert [rid for rid, _ in view.placement()] == ["r1"]


def test_duplicate_replica_ids_do_not_collapse_in_merge():
    """Two replicas (mis)configured with one replica_id must not
    alias in the metrics merge — their counters would silently
    halve; the view disambiguates by endpoint instead."""
    clock = _FakeClock()

    def scrape(endpoints, req):
        return [{"metrics": {"replica_id": "same",
                             "counters": {"serving.admitted": 2},
                             "gauges": {}, "histograms": {}}}
                for _ in endpoints]

    view = FleetView(["127.0.0.1:1", "127.0.0.1:2"], clock=clock,
                     scrape=scrape)
    merged = view.scrape_metrics()
    assert merged["counters"]["serving.admitted"] == 4
    assert len(merged["replicas"]) == 2


def test_fleetview_validates_config():
    with pytest.raises(ValueError):
        FleetView([])
    with pytest.raises(ValueError):
        FleetView(["127.0.0.1:1", "127.0.0.1:1"])
    with pytest.raises(ValueError):
        FleetView(["127.0.0.1:1"], stale_s_=10.0, down_s_=5.0)
    with pytest.raises(ValueError):
        fleet.parse_endpoint("no-port")


# ---------------------------------------------------------------------------
# Prometheus exposition with replica labels.
# ---------------------------------------------------------------------------

def test_render_prometheus_fleet_labels():
    a = {"counters": {"serving.admitted": 3},
         "gauges": {"serving.queue_depth": 2.0},
         "histograms": {"serving.ttft_ms": _hist_snapshot([1.5, 3.0])}}
    b = {"counters": {"serving.admitted": 4},
         "gauges": {"serving.queue_depth": 1.0},
         "histograms": {"serving.ttft_ms": _hist_snapshot([8.0])}}
    text = render_prometheus_fleet({"h:1": a, "h:2": b})
    assert 'tdt_serving_admitted_total{replica="fleet"} 7' in text
    assert 'tdt_serving_admitted_total{replica="h:1"} 3' in text
    assert 'tdt_serving_admitted_total{replica="h:2"} 4' in text
    # Additive gauge rollup sums.
    assert 'tdt_serving_queue_depth{replica="fleet"} 3' in text
    # Histograms: fleet rollup only, cumulative buckets.
    assert 'tdt_serving_ttft_ms_bucket{replica="fleet",le="+Inf"} 3' \
        in text
    assert '{replica="h:1",le=' not in text
    # One TYPE line per metric (samples grouped per the format spec).
    assert text.count("# TYPE tdt_serving_admitted_total counter") == 1


# ---------------------------------------------------------------------------
# replica_health + scoped registries (no server needed).
# ---------------------------------------------------------------------------

def test_replica_health_reads_registry_lock_free():
    reg = Registry()
    reg.gauge("serving.queue_depth").set(3)
    reg.gauge("serving.batch_occupancy").set(2)
    reg.gauge("serving.rolling.ttft_p99_ms").set(42.5)
    reg.gauge("serving.slo_burn.ttft_p99").set(1.5)
    reg.gauge("serving.slo_burn.ttft_p99_slow").set(1.2)
    reg.gauge("serving.slo_breached.ttft_p99").set(1.0)
    reg.gauge("resilience.gemm_rs.breaker_state").set(1)
    reg.gauge("resilience.breakers_open").set(1)
    reg.counter("serving.admitted").inc(5)
    h = replica_health("rX", 3, 0.0, registry=reg,
                       clock=lambda: 12.0)
    assert h["replica_id"] == "rX" and h["seq"] == 3
    assert h["uptime_s"] == pytest.approx(12.0)
    assert h["queue_depth"] == 3 and h["batch_occupancy"] == 2
    assert h["rolling"]["ttft_p99_ms"] == 42.5
    assert h["slo"]["ttft_p99"] == {"burn": 1.5, "burn_slow": 1.2,
                                    "breached": True}
    assert h["breakers"]["open"] == 1
    assert h["breakers"]["not_closed"] == {"gemm_rs": 1}
    assert h["counters"]["serving.admitted"] == 5
    # The loaded replica scores below an idle one built the same way.
    idle = replica_health("rY", 1, 0.0, registry=Registry())
    assert placement_score(idle) > placement_score(h)


def test_scoped_registry_routes_per_thread():
    reg_a, reg_b = Registry(), Registry()
    ready = threading.Barrier(2)

    def work(reg, n):
        with obs.scoped_registry(reg):
            ready.wait(5)
            for _ in range(n):
                obs.counter("t.x").inc()

    ta = threading.Thread(target=work, args=(reg_a, 3))
    tb = threading.Thread(target=work, args=(reg_b, 5))
    ta.start(); tb.start(); ta.join(5); tb.join(5)
    assert reg_a.snapshot()["counters"]["t.x"] == 3
    assert reg_b.snapshot()["counters"]["t.x"] == 5
    # The global registry saw nothing, and this thread is unscoped.
    assert "t.x" not in obs.snapshot().get("counters", {})
    # Nested scopes restore the outer one.
    with obs.scoped_registry(reg_a):
        with obs.scoped_registry(reg_b):
            obs.counter("t.y").inc()
        obs.counter("t.y").inc()
    assert reg_a.snapshot()["counters"]["t.y"] == 1
    assert reg_b.snapshot()["counters"]["t.y"] == 1


# ---------------------------------------------------------------------------
# Live two-replica acceptance scenario.
# ---------------------------------------------------------------------------

@pytest.fixture()
def tiny(mesh8, key):
    from triton_dist_tpu.models import DenseLLM, ModelConfig
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    return model, model.init(key)


def _server(model, params, rid):
    from triton_dist_tpu.models import Engine
    from triton_dist_tpu.serving import ModelServer
    eng = Engine(model, batch=2, max_seq=64, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    return ModelServer(eng, params, port=0, registry="private",
                       replica_id=rid).start()


def test_two_live_replicas_fleet_view(tiny):
    """The ISSUE 14 acceptance scenario: two live ModelServer replicas
    in one process (private registries), FleetView reports both
    healthy with correct fleet-summed counters and bucket-merged p99;
    killing one flips it stale → down within the configured ages
    while the other's signals stay fresh — no exception, no stale
    value presented as current."""
    from triton_dist_tpu.serving.client import fanout
    model, params = tiny
    s0 = _server(model, params, "rep-a")
    s1 = _server(model, params, "rep-b")
    eps = [(s0.host, s0.port), (s1.host, s1.port)]
    try:
        # Round-robin traffic: 4 requests → 2 per replica.
        outs = fanout(endpoints=eps,
                      requests=[{"prompt_ids": [[i + 1, i + 2]],
                                 "gen_len": 3} for i in range(4)])
        assert all("tokens" in o for o in outs), outs

        clock = _FakeClock()
        view = FleetView(eps, stale_s_=5.0, down_s_=20.0, clock=clock)
        rows = view.poll()
        assert [r["status"] for r in rows] == ["live", "live"]
        assert sorted(r["replica_id"] for r in rows) == \
            ["rep-a", "rep-b"]
        for r in rows:
            assert r["health"]["counters"]["serving.retired"] == 2
            assert r["seq"] >= 1

        merged = view.scrape_metrics(evaluate=True)
        # Fleet-summed counters: each replica retired exactly 2 rows
        # in its OWN registry — a shared registry would double-count.
        assert merged["counters"]["serving.retired"] == 4
        assert merged["counters"]["serving.admitted"] == 4
        # Bucket-merged TTFT: fleet count is the sum of both replicas'
        # and the p99 interpolates the summed buckets.
        h = merged["histograms"]["serving.ttft_ms"]
        assert h["count"] == 4
        per = merged["per_replica"]
        assert sorted(per) == ["rep-a", "rep-b"]
        assert view.fleet_quantile("serving.ttft_ms", 0.99) is not None
        # TPOT merges bucket-wise too (the cumulative sibling
        # histogram the scheduler now feeds).
        assert merged["histograms"]["serving.tpot_ms"]["count"] == 4

        # Kill replica b: the next poll degrades it to stale (last
        # good health retained, age reported), then to down past the
        # configured age — while replica a stays fresh throughout.
        s1.stop()
        clock.t += 1.0
        rows = view.poll()
        assert rows[0]["status"] == "live"
        assert rows[1]["status"] == "stale"
        assert rows[1]["health"]["replica_id"] == "rep-b"
        assert rows[1]["age_s"] >= 1.0
        clock.t += 25.0
        rows = view.poll()
        assert rows[0]["status"] == "live"
        assert rows[1]["status"] == "down"
        # The down replica leaves placement AND the metrics merge.
        assert [rid for rid, _ in view.placement()] == ["rep-a"]
        merged = view.scrape_metrics()
        assert merged["replicas"] == ["rep-a"]
        assert merged["counters"]["serving.retired"] == 2
    finally:
        s0.stop()
        s1.stop()


def test_health_verb_and_replica_stamping(tiny):
    from triton_dist_tpu.serving import ChatClient
    model, params = tiny
    srv = _server(model, params, "stamp-me")
    try:
        c = ChatClient(srv.host, srv.port)
        assert "tokens" in c.generate_ids([[1, 2, 3]], gen_len=2)
        h1 = c.health()
        h2 = c.health()
        assert h1["replica_id"] == "stamp-me"
        assert h2["seq"] > h1["seq"]            # monotonic
        assert h1["uptime_s"] >= 0
        assert h1["batch"] == 2 and h1["max_waiting"] >= 1
        assert h1["decode_path"] in ("plain", "mega", "auto")
        assert "rolling" in h1 and "slo" in h1 and "breakers" in h1
        # Metrics snapshots carry the id (merged snapshots from
        # same-host replicas can't alias).
        m = c.request({"cmd": "metrics"})["metrics"]
        assert m["replica_id"] == "stamp-me"
        # The cumulative TPOT histogram exists for the fleet merge.
        assert "serving.tpot_ms" in m["histograms"]
        # Flight-dump filenames carry the replica id.
        d = c.dump_trace()
        assert "stamp-me" in d["dumped"]
        c.close()
    finally:
        srv.stop()


def test_health_verb_serialized_path(tiny):
    """The health verb works on a scheduler-less server too (no SLO
    tracker to read — the dict is just sparser)."""
    from triton_dist_tpu.models import Engine
    from triton_dist_tpu.serving import ChatClient, ModelServer
    model, params = tiny
    eng = Engine(model, batch=1, max_seq=64, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    srv = ModelServer(eng, params, port=0, scheduler=False).start()
    try:
        c = ChatClient(srv.host, srv.port)
        h = c.health()
        assert h["replica_id"] == f"{srv.host}:{srv.port}"
        assert h["seq"] == 1
        c.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# fleet_top + report rendering.
# ---------------------------------------------------------------------------

def _row(rid, status, age=0.1, queue=0, occ=0, score=0.5, err=None):
    return {"endpoint": rid, "replica_id": rid, "status": status,
            "age_s": age, "seq": 1, "error": err, "score": score,
            "health": {"queue_depth": queue, "batch_occupancy": occ,
                       "batch": 4,
                       "rolling": {"ttft_p50_ms": 10.0,
                                   "ttft_p99_ms": 40.0},
                       "slo": {"ttft_p99": {"breached": queue > 4}}}}


def test_fleet_top_render_pure():
    from triton_dist_tpu.tools import fleet_top
    merged = merge_fleet_snapshots(
        {"r0": {"histograms":
                {"serving.ttft_ms": _hist_snapshot([2.0, 9.0])},
                "counters": {"serving.retired": 2}},
         "r1": {"histograms":
                {"serving.ttft_ms": _hist_snapshot([4.0])},
                "counters": {"serving.retired": 1}}})
    screen = fleet_top.render({
        "replicas": [_row("h:1", "live", queue=6),
                     _row("h:2", "stale", age=7.2),
                     _row("h:3", "down", score=None,
                          err="connection refused")],
        "merged": merged})
    assert "1 live / 1 stale / 1 down" in screen
    assert "h:1" in screen and "stale" in screen and "down" in screen
    assert "7.2" in screen                  # stale age visible
    assert "bucket-merged, n 3" in screen   # fleet rollup line
    assert "connection refused" in screen
    # Pure render: no replicas → friendly empty screen.
    assert "(no replicas)" in fleet_top.render({"replicas": []})


def test_fleet_top_once_live(tiny, capsys):
    from triton_dist_tpu.tools import fleet_top
    model, params = tiny
    s0 = _server(model, params, "ft-a")
    s1 = _server(model, params, "ft-b")
    try:
        rc = fleet_top.main(
            ["--endpoints",
             f"{s0.host}:{s0.port},{s1.host}:{s1.port}", "--once"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "2 replica(s) (2 live" in out
        assert "ft-a" in out and "ft-b" in out
    finally:
        s0.stop()
        s1.stop()


def test_report_fleet_section():
    from triton_dist_tpu.tools.report import render_fleet, \
        render_telemetry
    merged = merge_fleet_snapshots(
        {"r0": {"gauges": {"serving.queue_depth": 2.0},
                "counters": {"serving.admitted": 3,
                             "serving.retired": 3},
                "histograms": {"serving.ttft_ms":
                               _hist_snapshot([1.5, 3.0])}},
         "r1": {"gauges": {"serving.queue_depth": 0.0},
                "counters": {"serving.admitted": 1,
                             "serving.retired": 1},
                "histograms": {"serving.ttft_ms":
                               _hist_snapshot([9.0])}}})
    md = render_fleet(merged)
    assert "#### fleet" in md
    assert "replicas: r0, r1" in md
    assert "| r0 | 2 |" in md
    assert "bucket-merged" in md
    assert render_fleet(None) == ""
    # Rides inside render_telemetry under the "fleet" key.
    full = render_telemetry({"counters": {}, "gauges": {},
                             "histograms": {}, "fleet": merged})
    assert "#### fleet" in full
