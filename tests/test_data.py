"""Token-shard loader (tools/data.py + csrc/dataio).

What must hold: native and Python batching are bit-identical (same
splitmix64 Fisher-Yates, same gathers); epochs are deterministic in
(seed, epoch) and cover every chunk exactly once; bad chunk ids fail
loudly on both paths.
"""

from __future__ import annotations

import numpy as np
import pytest

from triton_dist_tpu.tools import data as D


@pytest.fixture()
def shard(tmp_path):
    ids = np.arange(1000, dtype=np.int32) * 3 % 50021
    return D.pack_tokens(ids, str(tmp_path / "corpus.bin")), ids


def test_pack_and_shapes(shard):
    path, ids = shard
    ds = D.TokenDataset(path, batch=4, seq=64)
    assert ds.n_chunks == len(ids) // 64
    b = next(ds.batches(seed=1))
    assert b.shape == (4, 64) and b.dtype == np.int32


def test_epoch_covers_all_chunks_once(shard):
    path, _ = shard
    ds = D.TokenDataset(path, batch=3, seq=64)
    perm = ds.epoch_perm(seed=7, epoch=0)
    assert sorted(perm.tolist()) == list(range(ds.n_chunks))
    # Different epochs / seeds give different orders, deterministically.
    assert (perm == ds.epoch_perm(seed=7, epoch=0)).all()
    assert not (perm == ds.epoch_perm(seed=7, epoch=1)).all()
    assert not (perm == ds.epoch_perm(seed=8, epoch=0)).all()


def test_native_python_parity(shard):
    path, _ = shard
    if not D.have_native():
        pytest.skip("no native toolchain")
    nat = D.TokenDataset(path, batch=4, seq=32)
    py = D.TokenDataset(path, batch=4, seq=32)
    py._lib = None
    it_n, it_p = nat.batches(seed=3), py.batches(seed=3)
    for _ in range(3 * nat.n_chunks // 4):  # cross several epochs
        np.testing.assert_array_equal(next(it_n), next(it_p))


def test_gather_content_and_bounds(shard):
    path, ids = shard
    ds = D.TokenDataset(path, batch=2, seq=100)
    got = ds.gather(np.array([2, 0], np.int32))
    np.testing.assert_array_equal(got[0], ids[200:300])
    np.testing.assert_array_equal(got[1], ids[:100])
    for backend_py in (False, True):
        d2 = D.TokenDataset(path, batch=2, seq=100)
        if backend_py:
            d2._lib = None
        with pytest.raises(IndexError):
            d2.gather(np.array([ds.n_chunks], np.int32))


def test_batches_start_offset_resumes_stream(shard):
    """start_batch=N fast-forwards to exactly the batches a run that
    consumed N batches would see next (the finetune --resume contract)."""
    path, _ = shard
    full = D.TokenDataset(path, batch=3, seq=32).batches(seed=5)
    ref = [next(full) for _ in range(8)]
    resumed = D.TokenDataset(path, batch=3, seq=32).batches(
        seed=5, start_batch=3)
    for want in ref[3:]:
        np.testing.assert_array_equal(next(resumed), want)
