"""Tools tests: autotuner, perf models, profiler, AOT export (reference
L9 coverage; the reference has no dedicated tool tests — we add them,
SURVEY.md §4 notes CI gaps)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.tools import (
    aot_compile_spaces, aot_export, aot_load, autotune,
    estimate_all_gather_time_ms, estimate_all_reduce_time_ms,
    estimate_gemm_sol_time_ms, get_chip_spec, group_profile,
    load_artifact, overlap_efficiency, save_artifacts, trace_files)
from triton_dist_tpu.tools import autotuner


def test_autotune_picks_fastest():
    import time

    def make_fn(sleep_ms):
        def fn():
            time.sleep(sleep_ms / 1e3)
            return None
        return fn

    res = autotune(make_fn, [{"sleep_ms": 5}, {"sleep_ms": 0.1},
                             {"sleep_ms": 3}], iters=3, warmup_iters=1)
    assert res.config == {"sleep_ms": 0.1}
    assert len(res.all_ms) == 3


def test_autotune_cache():
    autotuner.clear_cache()
    calls = []

    def make_fn(v):
        calls.append(v)
        return lambda: None

    r1 = autotune(make_fn, [{"v": 1}, {"v": 2}], key="k", iters=1,
                  warmup_iters=1)
    n = len(calls)
    r2 = autotune(make_fn, [{"v": 1}, {"v": 2}], key="k", iters=1,
                  warmup_iters=1)
    assert len(calls) == n and r1 == r2


def test_autotune_disk_cache(tmp_path, monkeypatch):
    """A sweep persisted to disk is served without re-running configs in
    a fresh process (simulated by clearing the in-memory cache)."""
    monkeypatch.setenv("TDT_AUTOTUNE_CACHE", str(tmp_path / "tune.json"))
    autotuner.clear_cache()
    calls = []

    def make_fn(v):
        calls.append(v)
        return lambda: None

    cfgs = [{"v": 1}, {"v": 2}]
    r1 = autotune(make_fn, cfgs, key="dk", iters=1, warmup_iters=1)
    n = len(calls)
    autotuner.clear_cache()  # "new process"
    r2 = autotune(make_fn, cfgs, key="dk", iters=1, warmup_iters=1)
    assert len(calls) == n, "disk hit must not re-run configs"
    assert r1.config == r2.config
    # corrupt file degrades to a re-sweep, not an error
    (tmp_path / "tune.json").write_text("{not json")
    autotuner.clear_cache()
    r3 = autotune(make_fn, cfgs, key="dk", iters=1, warmup_iters=1)
    assert len(calls) > n and r3.config in cfgs


def test_autotune_disk_cache_stale_config_resweeps(tmp_path, monkeypatch):
    """A persisted winner absent from the current candidate list (config
    table changed, e.g. a tightened VMEM filter) must NOT be served."""
    monkeypatch.setenv("TDT_AUTOTUNE_CACHE", str(tmp_path / "t.json"))
    autotuner.clear_cache()
    calls = []

    def make_fn(v):
        calls.append(v)
        return lambda: None

    autotune(make_fn, [{"v": 1}, {"v": 2}], key="sk", iters=1,
             warmup_iters=1)
    n = len(calls)
    autotuner.clear_cache()
    r = autotune(make_fn, [{"v": 3}, {"v": 4}], key="sk", iters=1,
                 warmup_iters=1)
    assert len(calls) > n and r.config in ({"v": 3}, {"v": 4})


def test_autotune_disk_cache_failed_config_roundtrip(tmp_path, monkeypatch):
    """inf scores (failed configs) survive the JSON round trip as
    losers."""
    monkeypatch.setenv("TDT_AUTOTUNE_CACHE", str(tmp_path / "t.json"))
    autotuner.clear_cache()

    def make_fn(v):
        if v == 1:
            raise RuntimeError("boom")
        return lambda: None

    r1 = autotune(make_fn, [{"v": 1}, {"v": 2}], key="fk", iters=1,
                  warmup_iters=1)
    autotuner.clear_cache()
    r2 = autotune(make_fn, [{"v": 1}, {"v": 2}], key="fk", iters=1,
                  warmup_iters=1)
    assert r2.config == r1.config == {"v": 2}
    assert r2.all_ms[0] == float("inf")


def test_perf_model_monotonic():
    spec = get_chip_spec()
    t1 = estimate_gemm_sol_time_ms(1024, 1024, 1024, spec)
    t2 = estimate_gemm_sol_time_ms(2048, 2048, 2048, spec)
    assert 0 < t1 < t2
    a1 = estimate_all_gather_time_ms(1 << 20, 8, spec)
    a2 = estimate_all_gather_time_ms(1 << 22, 8, spec)
    assert 0 < a1 < a2
    assert estimate_all_reduce_time_ms(1 << 20, 8, spec) > 0
    assert overlap_efficiency(1.0, 1.0) == 2.0
    assert overlap_efficiency(2.0, 0.0) == 1.0


def test_cost_model_ranks_measured_winner():
    """The roofline cost model's ranking must be consistent with the
    round-5 measured hw_bench_headline.out winner: at the bench shape
    (2048, 4096, 4096) bf16 world=1 on TPU v5 lite, the hbm_kt
    (128, 256) config — the measured tuned winner — must survive
    pruning and rank first among the hbm_kt candidates; big-tile hbm
    configs (the measured best variant class) must rank above it."""
    from triton_dist_tpu.ops.allgather_gemm import ag_gemm_configs
    from triton_dist_tpu.ops.common import TUNED_VMEM_BUDGET
    from triton_dist_tpu.tools import perf_model as pm

    from triton_dist_tpu.ops.common import DEFAULT_VMEM_BUDGET
    spec = pm.CHIP_SPECS["v5 lite"]
    m = rows = 2048
    k = n_loc = 4096
    kt_target = {"variant": "hbm_kt", "block_m": 128, "block_k": 256}

    def cost(c):
        return pm.estimate_ag_gemm_cost(
            c, m=m, rows=rows, k=k, n_loc=n_loc, itemsize=2, world=1,
            spec=spec).total_ms

    # (a) Under the r5 sweep conditions (default-budget table — exactly
    # what produced the measured winner) the kt config is top of its
    # tier and stays reachable for the default-path clamps.
    dflt = ag_gemm_configs(m, rows, k, n_loc, 2, DEFAULT_VMEM_BUDGET)
    kts = [c for c in dflt if c["variant"] == "hbm_kt"]
    assert kt_target in kts
    assert min(kts, key=cost) == kt_target
    # (b) Absolute consistency with hw_bench_headline.out: the model's
    # prediction for the measured kt winner sits on its 0.892 ms, and
    # the hbm-NB class it measures as faster (0.515 ms) ranks faster.
    assert cost(kt_target) == pytest.approx(0.892, rel=0.25)
    full = ag_gemm_configs(m, rows, k, n_loc, 2, TUNED_VMEM_BUDGET,
                           tier_caps=False)
    best_hbm = min((c for c in full if c["variant"] == "hbm"), key=cost)
    assert cost(best_hbm) < cost(kt_target)

    # (c) The sweep's pruned table keeps an hbm_kt fallback and the
    # model's favorite, at >= 4x search-space reduction (acceptance).
    pruned, n_before = pm.prune_configs(
        full, cost, always_keep=lambda c: c["variant"] == "hbm_kt")
    assert any(c["variant"] == "hbm_kt" for c in pruned)
    assert best_hbm in pruned
    assert n_before >= 4 * len(pruned), (n_before, len(pruned))


def test_cost_model_prefers_big_tiles():
    """The measured round-5 hypothesis encoded: per-tile Mosaic
    overhead makes small tiles lose (docs/perf.md 'Why 135 TFLOPS')."""
    from triton_dist_tpu.tools import perf_model as pm
    spec = pm.CHIP_SPECS["v5 lite"]

    def cost(bm, bn):
        return pm.estimate_ag_gemm_cost(
            {"variant": "hbm", "block_m": bm, "block_n": bn},
            m=2048, rows=2048, k=4096, n_loc=4096, itemsize=2, world=1,
            spec=spec).total_ms

    assert cost(256, 1024) < cost(128, 512) < cost(128, 128)


def test_cost_model_overlap_pct():
    """Overlap accounting: no comm -> 100 (nothing exposed); a
    comm-dominated shape exposes most of its ring time; bidirectional
    halves the comm and can only improve the hidden fraction."""
    from triton_dist_tpu.tools import perf_model as pm
    spec = pm.CHIP_SPECS["v5 lite"]
    kw = dict(m=2048, rows=2048, k=4096, n_loc=4096, itemsize=2,
              spec=spec)
    c1 = pm.estimate_ag_gemm_cost({"variant": "vmem"}, world=1, **kw)
    assert c1.overlap_pct == 100.0 and c1.exposed_comm_ms == 0.0
    # world 8 of the same global shape: comm-heavier per-chip
    kw8 = dict(m=2048, rows=256, k=4096, n_loc=512, itemsize=2,
               spec=spec)
    uni = pm.estimate_ag_gemm_cost(
        {"variant": "hbm", "block_m": 256, "block_n": 512},
        world=8, ring_dirs=1, **kw8)
    bi = pm.estimate_ag_gemm_cost(
        {"variant": "hbm", "block_m": 256, "block_n": 512},
        world=8, ring_dirs=2, **kw8)
    assert 0.0 <= uni.overlap_pct <= 100.0
    assert bi.comm_ms < uni.comm_ms          # half the hops
    assert bi.total_ms <= uni.total_ms
    # breakdown is self-consistent
    assert bi.total_ms == pytest.approx(bi.compute_ms
                                        + bi.exposed_comm_ms)


def test_prune_configs_logs_counts():
    """record_prune lands the before/after pair in LAST_PRUNE and the
    obs gauges (the acceptance 'candidate count before/after logged')."""
    from triton_dist_tpu import obs
    from triton_dist_tpu.tools import autotuner
    obs.disable()
    obs.enable()
    try:
        autotuner.record_prune("ag_gemm", 16, 4)
        assert autotuner.LAST_PRUNE["ag_gemm"] == (16, 4)
        g = obs.snapshot()["gauges"]
        assert g["autotune.ag_gemm.candidates_before"] == 16.0
        assert g["autotune.ag_gemm.candidates_after"] == 4.0
    finally:
        obs.disable()


def test_group_profile_writes_trace(tmp_path):
    with group_profile("t1", str(tmp_path)):
        jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
    files = trace_files("t1", str(tmp_path))
    assert files, "no trace artifacts written"


def test_aot_export_roundtrip():
    def fn(x, y):
        return jnp.dot(x, y) + 1.0

    a = jnp.ones((8, 16), jnp.float32)
    b = jnp.ones((16, 4), jnp.float32)
    blob = aot_export(fn, (a, b))
    assert isinstance(blob, bytes) and len(blob) > 0
    loaded = aot_load(blob)
    np.testing.assert_allclose(np.asarray(loaded(a, b)),
                               np.asarray(fn(a, b)))


def test_aot_export_symbolic_dynamic_m():
    """One symbolic-M artifact serves multiple batch sizes (the
    reference's per-signature AOT spaces over M, compile_aot.py:61)."""
    from triton_dist_tpu.tools.aot import aot_export_symbolic
    w = jax.random.normal(jax.random.PRNGKey(0), (16, 8), jnp.float32)

    def fn(x):
        return x @ w

    blob = aot_export_symbolic(fn, [("m, 16", jnp.float32)])
    loaded = aot_load(blob)
    for m in (4, 32):
        x = jax.random.normal(jax.random.PRNGKey(m), (m, 16), jnp.float32)
        np.testing.assert_allclose(np.asarray(loaded(x)),
                                   np.asarray(x) @ np.asarray(w),
                                   rtol=1e-5, atol=1e-5)


def test_aot_compile_spaces(tmp_path):
    a = jnp.ones((4, 4), jnp.float32)

    @aot_compile_spaces({"square": (a,)})
    def f(x):
        return x * x

    arts = f.aot_artifacts()
    assert set(arts) == {"square"}
    paths = save_artifacts(arts, str(tmp_path))
    assert os.path.exists(paths[0])
    g = load_artifact(paths[0])
    np.testing.assert_allclose(np.asarray(g(a)), np.asarray(a * a))


def test_perf_model_auto_crossovers():
    """AUTO method selection turns on perf-model crossovers, not
    hardcoded byte thresholds (VERDICT r2 next 9; reference
    comm_perf_model.py:94-116, allreduce.py:1101-1127)."""
    from triton_dist_tpu.tools.perf_model import (
        CHIP_SPECS, estimate_all_gather_time_ms,
        estimate_full_mesh_push_time_ms)
    from triton_dist_tpu.ops.allgather import (
        AllGatherMethod, get_auto_all_gather_method)
    from triton_dist_tpu.ops.allreduce import (
        AllReduceMethod, get_auto_allreduce_method)

    spec = CHIP_SPECS["v5e"]
    # Latency-bound: one launch beats per-step ring overhead.
    assert get_auto_all_gather_method(8, 4 * 1024, spec) \
        is AllGatherMethod.FULL_MESH_PUSH
    # Bandwidth-bound: through-traffic sinks full-mesh; ring wins.
    assert get_auto_all_gather_method(8, 64 * 1024 * 1024, spec) \
        is AllGatherMethod.RING_BIDIR
    # The crossover exists and is monotone: find it by bisection and
    # check the model actually flips there.
    lo, hi = 4 * 1024, 64 * 1024 * 1024
    while hi - lo > 1024:
        mid = (lo + hi) // 2
        if (estimate_full_mesh_push_time_ms(mid, 8, spec)
                <= estimate_all_gather_time_ms(mid, 8, spec)):
            lo = mid
        else:
            hi = mid
    assert 16 * 1024 < hi < 16 * 1024 * 1024  # physically plausible

    assert get_auto_allreduce_method(8, 16 * 1024, spec) \
        is AllReduceMethod.ONE_SHOT
    assert get_auto_allreduce_method(8, 64 * 1024 * 1024, spec) \
        is AllReduceMethod.TWO_SHOT
    # w<=2 degenerates to the single-hop method regardless of size.
    assert get_auto_all_gather_method(2, 64 * 1024 * 1024, spec) \
        is AllGatherMethod.FULL_MESH_PUSH


def test_reduce_scatter_auto_crossover():
    from triton_dist_tpu.ops.reduce_scatter import (
        ReduceScatterMethod, create_reduce_scatter_context)
    import numpy as np
    import jax
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:8]), ("tp",))
    ctx = create_reduce_scatter_context(mesh, "tp")
    assert ctx.resolve_method(8 * 1024) is ReduceScatterMethod.ONE_SHOT
    assert ctx.resolve_method(64 * 1024 * 1024) is ReduceScatterMethod.RING


def test_autotune_isolates_failing_config():
    """A config that fails to compile/run scores inf instead of killing
    the sweep (aggressive-tier configs rely on this)."""
    from triton_dist_tpu.tools.autotuner import autotune, clear_cache
    clear_cache()

    def make_fn(ok):
        if not ok:
            def boom():
                raise RuntimeError("synthetic compile failure")
            return boom

        def fine():
            return jnp.ones((8,)).sum()
        return fine

    res = autotune(make_fn, [{"ok": False}, {"ok": True}],
                   key="isolate-test", iters=2, warmup_iters=1)
    assert res.config == {"ok": True}
    assert res.all_ms[0] == float("inf")

    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="every autotune config"):
        autotune(make_fn, [{"ok": False}], key="isolate-test-2",
                 iters=2, warmup_iters=1)


def test_disk_cache_device_kind_quarantine(tmp_path, monkeypatch):
    """A winner persisted under one device kind must NEVER be served
    under another (VERDICT r4 next-6): a CPU interpret-mode verdict
    (where ring beats fused by 100-300x of pure artifact) leaking onto
    TPU would silently pin the wrong impl on chip. The disk key is
    '{device_kind}::{key}'."""
    monkeypatch.setenv("TDT_AUTOTUNE_CACHE", str(tmp_path / "t.json"))
    autotuner.clear_cache()
    calls = []

    def make_fn(v):
        calls.append(v)
        return lambda: None

    cfgs = [{"v": 1}, {"v": 2}]
    # Plant a cpu-keyed winner by sweeping under the real (cpu) backend.
    r1 = autotune(make_fn, cfgs, key="quar", iters=1, warmup_iters=1)
    assert (tmp_path / "t.json").exists()
    import json
    keys = list(json.loads((tmp_path / "t.json").read_text()))
    assert all("::" in k for k in keys), keys

    # Same key looked up under a FAKE TPU platform: must miss.
    class _Dev:
        device_kind = "TPU v5 lite"

    class _FakeJax:
        @staticmethod
        def devices():
            return [_Dev()]
    real_jax = autotuner.jax
    monkeypatch.setattr(autotuner, "jax", _FakeJax)
    assert autotuner._disk_load("quar") is None
    # And back under the real platform it still hits.
    monkeypatch.setattr(autotuner, "jax", real_jax)
    hit = autotuner._disk_load("quar")
    assert hit is not None and hit.config == r1.config


def test_trace_fallback_multiprocess_refuses_disk(tmp_path, monkeypatch):
    """consult_disk_for_trace returns None on multi-process deployments
    even when a local cache hit exists (ADVICE r4-1: a per-host disk
    consult with no agreement step can bake MISMATCHED collective
    programs across ranks — a hang), and warns once."""
    import warnings

    monkeypatch.setenv("TDT_AUTOTUNE_CACHE", str(tmp_path / "t.json"))
    autotuner.clear_cache()
    autotuner._TRACE_FALLBACK_WARNED.clear()
    autotune(lambda v: (lambda: None), [{"v": 1}], key="mp", iters=1,
             warmup_iters=1)
    assert autotuner._disk_load("mp") is not None  # local hit exists

    class _FakeJax:
        @staticmethod
        def process_count():
            return 2

        @staticmethod
        def devices():
            return autotuner.jax.devices()
    real_jax = autotuner.jax
    monkeypatch.setattr(autotuner, "jax", _FakeJax)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert autotuner.consult_disk_for_trace("mp") is None
        assert autotuner.consult_disk_for_trace("mp") is None  # warn once
    assert len([x for x in w if "multi-process" in str(x.message)]) == 1
    monkeypatch.setattr(autotuner, "jax", real_jax)
    # Single-process: the same consult hits.
    autotuner._TRACE_FALLBACK_WARNED.clear()
    assert autotuner.consult_disk_for_trace("mp") is not None


def test_trace_fallback_miss_warns_once(tmp_path, monkeypatch):
    """A traced auto call with NO cached winner warns once that the
    program baked the default impl for its lifetime (ADVICE r4-4)."""
    import warnings

    monkeypatch.setenv("TDT_AUTOTUNE_CACHE", str(tmp_path / "t.json"))
    autotuner._TRACE_FALLBACK_WARNED.clear()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert autotuner.consult_disk_for_trace("missing_key") is None
        assert autotuner.consult_disk_for_trace("missing_key") is None
    msgs = [x for x in w if "baked" in str(x.message).lower()
            or "bakes" in str(x.message)]
    assert len(msgs) == 1


def test_top_render_dashboard_sections():
    """tools/top.py: the dashboard renders rolling SLOs, burn rates,
    occupancy/pool, live op ratios, and request waterfalls from a
    plain metrics snapshot (no server needed)."""
    from triton_dist_tpu.tools import top
    snap = {
        "gauges": {
            "serving.rolling.ttft_p50_ms": 12.5,
            "serving.rolling.ttft_p99_ms": 80.0,
            "serving.rolling.ttft_n": 42,
            "serving.slo_burn.ttft_p99": 0.2,
            "serving.slo_burn.ttft_p99_slow": 0.1,
            "serving.slo_breached.ttft_p99": 0,
            "serving.batch_occupancy": 3,
            "serving.queue_depth": 1,
            "kv.block_utilization": 0.75,
            "resilience.perfwatch.ag_gemm.live_ratio": 1.2,
            "trace.dropped_total": 7,
        },
        "counters": {"serving.admitted": 10, "serving.retired": 9},
        "requests": [{"rid": 4, "total_ms": 20.0,
                      "segments": {"queue_wait_ms": 1.0,
                                   "prefill_ms": 9.0,
                                   "decode_ms": 10.0},
                      "tokens": 5, "cached_tokens": 2}],
    }
    out = top.render(snap)
    assert "rolling latency" in out and "p50 12.500" in out
    assert "slo burn rates" in out and "ttft_p99" in out
    assert "BREACH" not in out
    assert "block utilization" in out and "0.750" in out
    assert "ag_gemm" in out and "1.200x" in out
    assert "rid 4" in out and "prefill 9" in out
    assert "TDT_TRACE_RING" in out
    snap["gauges"]["serving.slo_breached.ttft_p99"] = 1
    assert "BREACH" in top.render(snap)
    assert "(no serving metrics yet)" in top.render(
        {"gauges": {}, "counters": {}})


def test_top_and_report_render_device_time_section():
    """tools/top.py + tools/report.py: the device-time truth gauges
    (obs.devprof) render as their own section — measured per-op
    compute/comm, overlap + drift, unlabeled warning, last profile
    path (docs/observability.md "Device-time truth")."""
    from triton_dist_tpu.tools import report, top
    snap = {
        "gauges": {
            "device.ag_gemm.total_ms": 2.0,
            "device.ag_gemm.compute_ms": 1.2,
            "device.ag_gemm.comm_ms": 0.8,
            "device.step.total_ms": 5.0,
            "device.step.compute_ms": 4.0,
            "device.step.comm_ms": 0.0,
            "device.unlabeled_ms": 0.25,
            "comms.ag_gemm.overlap_pct_measured": 50.0,
            "comms.ag_gemm.exposed_comm_ms_measured": 0.4,
            "comms.ag_gemm.overlap_drift_pct": -40.0,
        },
        "counters": {"profile.captures": 3, "profile.parsed": 3},
        "devprof": {"last_profile": "/tmp/x/pump_1/host0",
                    "last_reason": "breach_slo_ttft_p99",
                    "ops": ["ag_gemm", "step"]},
    }
    out = top.render(snap)
    assert "device time (measured)" in out
    assert "ag_gemm" in out and "overlap 50" in out
    assert "drift -40" in out
    assert "step" in out
    assert "annotation-coverage" in out          # unlabeled warning
    assert "/tmp/x/pump_1/host0" in out
    md = report.render_devprof(snap, snap["devprof"])
    assert "#### device time (measured)" in md
    assert "comms.ag_gemm.overlap_drift_pct" in md and "-40" in md
    assert "profile.captures" in md
    assert "last_profile" in md and "breach_slo_ttft_p99" in md
    assert "annotation-coverage" in md           # unlabeled warning
    # The telemetry renderer routes device.*/profile.* rows into the
    # section instead of duplicating them in the scalar table.
    full = report.render_telemetry(snap)
    assert full.count("device.ag_gemm.total_ms") == 1
    # No devprof metrics at all → no section.
    assert report.render_devprof({"gauges": {}, "counters": {}}) == ""
