"""Export-lint CI gate: every smoke case must pass Pallas→Mosaic
lowering + verification for the TPU platform — on this CPU host, no
chip needed.

This closes the round-2 failure class for good: "127 CPU tests pass
because the interpreter doesn't enforce MXU constraints" (VERDICT r2) —
the interpret-mode suite cannot see Mosaic rejections like
multi-batch-dim ``tpu.matmul``, but ``jax.export(platforms=('tpu',))``
runs the real lowering and its verifier without executing anything
(tpu_smoke.py --export-lint; verified to catch the exact round-2
constructs)."""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


import pytest


@pytest.mark.parametrize("world", [1, 8])
def test_export_lint_all_cases(tmp_path, world):
    """world=1 lints the on-chip smoke variants; world=8 lints the
    multi-device ring/remote-DMA variants that NO other check compiles
    (the chip is a single device; the interpret suite never lowers)."""
    r = subprocess.run(
        [sys.executable, str(REPO / "tpu_smoke.py"), "--export-lint",
         "--world", str(world), "--log", str(tmp_path / "lint.log")],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    tail = "\n".join(r.stdout.splitlines()[-45:])
    assert r.returncode == 0, f"export-lint failures:\n{tail}"
    assert ", 0 failing" in r.stdout, tail


def test_export_lint_layer_bench_dims():
    """bench.py layer_8b/32b compositions (Qwen3 per-chip TP8 slices,
    prefill ag_rs M=2048 + decode gemm_ar M=128) pass the Mosaic
    verifier at the REAL dims the chip bench runs — K=5120 and odd
    N-widths never appear in the smoke shapes (round 4)."""
    import os
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import export as jexport
    from jax.sharding import Mesh
    from triton_dist_tpu.layers import TPAttn, precompute_rope_cache
    from triton_dist_tpu.layers.tp_mlp import TPMLP

    os.environ["TDT_FORCE_COMPILED"] = "1"
    try:
        mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
        for tag, h, nq, nkv, d, inter in (
                ("8b", 4096, 4, 1, 128, 1536),
                ("32b", 5120, 8, 1, 128, 3200)):
            attn = TPAttn(h, nq, nkv, d, mesh=mesh, axis="tp",
                          dtype=jnp.bfloat16)
            mlp = TPMLP(h, inter, mesh=mesh, axis="tp",
                        dtype=jnp.bfloat16)
            pa = attn.init(jax.random.PRNGKey(0))
            pm = mlp.init(jax.random.PRNGKey(1))
            rope = precompute_rope_cache(d, 512)
            for phase, b, s, mode in (("prefill", 16, 128, "ag_rs"),
                                      ("decode", 128, 1, "gemm_ar")):
                m = b * s
                pos = (jnp.tile(jnp.arange(s), (b, 1))
                       if phase == "prefill"
                       else jnp.full((b, 1), 256, jnp.int32))
                offset = jnp.int32(0 if phase == "prefill" else 256)
                cache = tuple(
                    jnp.zeros((b, 512, nkv, d), jnp.bfloat16)
                    for _ in range(2))
                x = jnp.zeros((m, h), jnp.bfloat16)

                def f(x, pa=pa, pm=pm, cache=cache, pos=pos,
                      offset=offset, mode=mode, attn=attn, mlp=mlp):
                    a_out, _ = attn(pa, x, pos, rope, cache, offset,
                                    mode=mode)
                    y = x + a_out
                    return y + mlp(pm, y, mode=mode)
                jexport.export(jax.jit(f), platforms=("tpu",))(x)
    finally:
        os.environ.pop("TDT_FORCE_COMPILED", None)
