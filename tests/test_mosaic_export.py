"""Export-lint CI gate: every smoke case must pass Pallas→Mosaic
lowering + verification for the TPU platform — on this CPU host, no
chip needed.

This closes the round-2 failure class for good: "127 CPU tests pass
because the interpreter doesn't enforce MXU constraints" (VERDICT r2) —
the interpret-mode suite cannot see Mosaic rejections like
multi-batch-dim ``tpu.matmul``, but ``jax.export(platforms=('tpu',))``
runs the real lowering and its verifier without executing anything
(tpu_smoke.py --export-lint; verified to catch the exact round-2
constructs)."""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


import pytest


@pytest.mark.parametrize("world", [1, 8])
def test_export_lint_all_cases(tmp_path, world):
    """world=1 lints the on-chip smoke variants; world=8 lints the
    multi-device ring/remote-DMA variants that NO other check compiles
    (the chip is a single device; the interpret suite never lowers)."""
    r = subprocess.run(
        [sys.executable, str(REPO / "tpu_smoke.py"), "--export-lint",
         "--world", str(world), "--log", str(tmp_path / "lint.log")],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    tail = "\n".join(r.stdout.splitlines()[-45:])
    assert r.returncode == 0, f"export-lint failures:\n{tail}"
    assert ", 0 failing" in r.stdout, tail
