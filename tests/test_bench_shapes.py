"""Bench-config interpret tests (VERDICT r3 next-9).

test_vmem_budget checks that the bench-shape configs FIT; these check
that they COMPUTE CORRECTLY: each fused op runs in interpret mode on the
world=8 mesh with the exact variant + block config its default path
resolves at the real bench.py shape (world=1, 2048x4096x4096 bf16), so
a schedule/config regression fails here in CI instead of on the chip
(reference analog: test/nvidia/test_ag_gemm.py:72-197's shape sweep).

Shapes are scaled (K, and N where it only multiplies work) to keep the
interpreter fast, but the BLOCK sizes — what the kernel schedule
actually tiles by — are pinned to the bench-resolved config, and the
per-rank row/column counts keep multiple blocks live per rank.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow

bf16 = jnp.bfloat16


def _mesh8():
    return Mesh(np.array(jax.devices()[:8]), ("tp",))


def _put(mesh, x, spec):
    return jax.device_put(x, NamedSharding(mesh, spec))


def _randn(shape, k=0, dtype=bf16):
    return jax.random.normal(jax.random.PRNGKey(k), shape,
                             jnp.float32).astype(dtype)


def test_ag_gemm_bench_config_numerics():
    from triton_dist_tpu.ops.allgather_gemm import (
        ag_gemm, ag_gemm_configs, create_ag_gemm_context)
    # The config the world=1 bench default path resolves (first feasible
    # table entry at m=2048, rows=2048, k=4096, n_tot_loc=4096).
    cfg = ag_gemm_configs(2048, 2048, 4096, 4096, 2)[0]
    assert cfg["variant"] in ("hbm", "hbm_kt"), cfg
    mesh = _mesh8()
    # Scaled run: keep block sizes; K shrinks (it only multiplies
    # interpreter work), per-rank rows/cols hold >= 1 block.
    k = 512
    m = max(2 * cfg.get("block_m", 128), 256) * 8
    n = 512 * 8
    ctx = create_ag_gemm_context(mesh, "tp", interpret=True)
    ctx = dataclasses.replace(ctx, **cfg)
    a = _put(mesh, _randn((m, k)), P("tp"))
    b = _put(mesh, _randn((k, n), k=1), P(None, "tp"))
    out = ag_gemm(a, b, ctx, impl="pallas")
    ref = ag_gemm(a, b, ctx, impl="xla")
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_gemm_rs_bench_config_numerics():
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs, gemm_rs_configs)
    cfg = gemm_rs_configs(2048, 2048, 4096, 4096, 2, 1)[0]
    assert cfg["variant"] in ("hbm", "hbm_kt"), cfg
    mesh = _mesh8()
    bm = cfg.get("block_m", 128)
    m = max(2 * bm, 256) * 8          # rows/rank >= 2 blocks
    k, n = 512 * 8, 512
    ctx = create_gemm_rs_context(mesh, "tp", interpret=True)
    keys = {f.name for f in dataclasses.fields(ctx)}
    ctx = dataclasses.replace(
        ctx, **{kk: v for kk, v in cfg.items() if kk in keys})
    a = _put(mesh, _randn((m, k)), P(None, "tp"))
    b = _put(mesh, _randn((k, n), k=1), P("tp"))
    out = gemm_rs(a, b, ctx, impl="pallas")
    ref = gemm_rs(a, b, ctx, impl="xla")
    # K = 4096 here: |out| ~ 128, so the bf16 output quantization step
    # is ~1.0 — atol covers two ulps at that magnitude (the pallas and
    # xla paths partition the contraction differently, and with the
    # 24 MB-budget default tiles a lone element can land two roundings
    # apart: observed 1/2^21 elements past one ulp).
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=2.0)


def test_ag_swiglu_bench_blocks_numerics():
    """The tp_mlp bench line rides ag_swiglu; same block-pinning check
    (golden: the xla shard_map MLP front half)."""
    from triton_dist_tpu.ops.allgather_gemm import (
        ag_swiglu, create_ag_gemm_context)
    mesh = _mesh8()
    m, k, n = 256 * 8, 512, 512 * 8
    ctx = create_ag_gemm_context(mesh, "tp", interpret=True)
    x = _put(mesh, _randn((m, k)), P("tp"))
    wg = _put(mesh, _randn((k, n), k=1), P(None, "tp"))
    wu = _put(mesh, _randn((k, n), k=2), P(None, "tp"))
    act = ag_swiglu(x, wg, wu, ctx, impl="pallas")

    def body(xs, g, u):
        from jax import lax
        ag = lax.all_gather(xs, "tp", tiled=True)
        gate = jnp.dot(ag, g, preferred_element_type=jnp.float32)
        up = jnp.dot(ag, u, preferred_element_type=jnp.float32)
        return (jax.nn.silu(gate) * up).astype(xs.dtype)
    from triton_dist_tpu.ops.common import nestable_shard_map
    ref = nestable_shard_map(
        body, mesh=mesh, in_specs=(P("tp"), P(None, "tp"), P(None, "tp")),
        out_specs=P(None, "tp"), check_vma=False)(x, wg, wu)
    np.testing.assert_allclose(np.asarray(act, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=3e-2, atol=3e-2)


@pytest.mark.parametrize("t_blk", [512, 1024])
def test_flash_decode_bench_tblk_numerics(t_blk):
    """The serving-shape flash-decode line's tiled variant at the bench
    t_blk values, world=8 (cross-rank LSE combine live)."""
    from triton_dist_tpu.ops.flash_decode import (
        create_flash_decode_context, gqa_fwd_batch_decode)
    mesh = _mesh8()
    b, hq, hkv, d, t = 2, 32, 8, 64, 8 * 2 * t_blk // 4
    ctx = create_flash_decode_context(mesh, "tp", variant="tiled",
                                      t_blk=t_blk // 4, interpret=True)
    q = _randn((b, hq, d))
    kc = _put(mesh, _randn((b, t, hkv, d), k=1), P(None, "tp"))
    vc = _put(mesh, _randn((b, t, hkv, d), k=2), P(None, "tp"))
    out = gqa_fwd_batch_decode(q, kc, vc, jnp.int32(t - 5), ctx,
                               impl="pallas")
    ref = gqa_fwd_batch_decode(q, kc, vc, jnp.int32(t - 5), ctx,
                               impl="xla")
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)
