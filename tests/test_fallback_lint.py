"""Quick-tier CI gate: every public op entry has a registered fallback.

The static check lives in tools/fallback_lint.py (docs/resilience.md
"Escape-hatch lint"); this test wires it into the quick tier so a new
op entry cannot merge without an XLA escape hatch.
"""

from triton_dist_tpu.tools import fallback_lint


def test_no_uncovered_op_entries():
    assert fallback_lint.missing_fallbacks() == []


def test_registry_covers_the_issue_ops():
    """The ops ISSUE 3 names explicitly must all be registered."""
    from triton_dist_tpu.resilience import registered_fallbacks
    # Importing via the lint populated the registry for every module.
    fallback_lint.missing_fallbacks()
    ops = set(registered_fallbacks())
    for required in ("ag_gemm", "gemm_rs", "gemm_ar", "allreduce",
                     "flash_decode", "flash_decode_paged", "all_to_all",
                     "moe_reduce_rs", "sp_attention"):
        assert required in ops, required
    for op, spec in registered_fallbacks().items():
        assert spec.fallback_impl == "xla", (op, spec)


def test_lint_main_exit_code():
    assert fallback_lint.main([]) == 0
