"""Engine sampling / generation-contract tests (reference
test_e2e_inference.py sampling paths + Engine.serve loop invariants,
engine.py:113-190)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
from triton_dist_tpu.models.engine import sample_token

#: Engine-integration tier (model-driven, ~2 min total) -> full tier only.
pytestmark = pytest.mark.slow


def _cfg():
    return ModelConfig(hidden_size=32, intermediate_size=64,
                       num_hidden_layers=1, num_attention_heads=8,
                       num_key_value_heads=8, head_dim=8, vocab_size=64,
                       max_position_embeddings=32, dtype=jnp.float32)


@pytest.fixture()
def model(mesh8):
    return DenseLLM(_cfg(), mesh=mesh8, axis="tp", impl="xla")


def test_greedy_sampling_is_argmax(key):
    logits = jax.random.normal(key, (3, 64), jnp.float32)
    tok = sample_token(logits, key, temperature=0.0, top_k=0)
    np.testing.assert_array_equal(np.asarray(tok),
                                  np.argmax(np.asarray(logits), axis=-1))


def test_topk_sampling_stays_in_topk(key):
    logits = jax.random.normal(key, (4, 64), jnp.float32)
    k = 5
    topk_sets = np.argsort(-np.asarray(logits), axis=-1)[:, :k]
    for i in range(20):
        tok = np.asarray(sample_token(logits, jax.random.PRNGKey(i),
                                      temperature=1.0, top_k=k))
        for b in range(4):
            assert tok[b] in topk_sets[b], (b, tok[b])


def test_top_p_nucleus_membership(key):
    """top_p samples stay inside the smallest prefix of the sorted
    distribution whose mass reaches p; p→0 degenerates to argmax."""
    logits = jax.random.normal(key, (4, 64), jnp.float32) * 3
    p = 0.6
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    order = np.argsort(-probs, axis=-1)
    nucleus = []
    for b in range(4):
        cum, keep = 0.0, set()
        for idx in order[b]:
            if cum >= p:
                break
            keep.add(int(idx))
            cum += probs[b, idx]
        nucleus.append(keep)
    for i in range(20):
        tok = np.asarray(sample_token(logits, jax.random.PRNGKey(i),
                                      temperature=1.0, top_p=p))
        for b in range(4):
            assert int(tok[b]) in nucleus[b], (b, int(tok[b]), nucleus[b])
    # p small enough (including exactly 0) keeps only the argmax
    for p0 in (1e-6, 0.0):
        tok = np.asarray(sample_token(logits, jax.random.PRNGKey(99),
                                      temperature=1.0, top_p=p0))
        np.testing.assert_array_equal(tok,
                                      np.argmax(np.asarray(logits), -1))
    # combined top_k + top_p stays inside BOTH filters
    for i in range(10):
        tok = np.asarray(sample_token(logits, jax.random.PRNGKey(i),
                                      temperature=1.0, top_k=5, top_p=p))
        for b in range(4):
            topk_set = set(np.argsort(-np.asarray(logits)[b])[:5])
            assert int(tok[b]) in (nucleus[b] & topk_set) or \
                int(tok[b]) in topk_set, (b, int(tok[b]))


def test_sampling_seeded_determinism(key):
    logits = jax.random.normal(key, (2, 64), jnp.float32)
    a = sample_token(logits, jax.random.PRNGKey(7), 0.8, 10)
    b = sample_token(logits, jax.random.PRNGKey(7), 0.8, 10)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_engine_seeded_generation_deterministic(model, key):
    params = model.init(key)
    ids = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    e1 = Engine(model, batch=2, max_seq=16, temperature=0.7, top_k=8,
                seed=11)
    e2 = Engine(model, batch=2, max_seq=16, temperature=0.7, top_k=8,
                seed=11)
    np.testing.assert_array_equal(np.asarray(e1.serve(params, ids, 5)),
                                  np.asarray(e2.serve(params, ids, 5)))


def test_engine_serve_shapes_and_prefix(model, key):
    """Output prepends the prompt unchanged; gen_len<=0 echoes it."""
    params = model.init(key)
    ids = jnp.asarray([[9, 8, 7]], jnp.int32)
    eng = Engine(model, batch=1, max_seq=16)
    out = eng.serve(params, ids, 4)
    assert out.shape == (1, 7)
    np.testing.assert_array_equal(np.asarray(out)[:, :3], np.asarray(ids))
    np.testing.assert_array_equal(np.asarray(eng.serve(params, ids, 0)),
                                  np.asarray(ids))


def test_engine_stop_tokens(model, key):
    """Rows that emit a stop token keep emitting it; output stays a
    (B, S+gen_len) rectangle; early-exit must not change the result."""
    params = model.init(key)
    ids = jnp.asarray([[1, 2, 3]], jnp.int32)
    eng = Engine(model, batch=1, max_seq=64)
    free = np.asarray(eng.serve(params, ids, 40))
    # pick the first generated token as the stop token: generation must
    # then be that token repeated for the whole gen window
    stop_tok = int(free[0, 3])
    eng2 = Engine(model, batch=1, max_seq=64)
    out = np.asarray(eng2.serve(params, ids, 40, stop_tokens=(stop_tok,)))
    assert out.shape == (1, 43)
    np.testing.assert_array_equal(out[0, 3:], np.full(40, stop_tok))


def test_engine_stop_token_rows_independent(model, key):
    """One row stopping must not stop the other row's generation."""
    params = model.init(key)
    ids = jnp.asarray([[1, 2, 3], [7, 8, 9]], jnp.int32)
    free = np.asarray(Engine(model, batch=2, max_seq=64)
                      .serve(params, ids, 6))
    stop_tok = int(free[0, 3])  # row 0's first token
    if stop_tok in free[1, 3:]:
        pytest.skip("stop token occurs in both rows for this seed")
    out = np.asarray(Engine(model, batch=2, max_seq=64)
                     .serve(params, ids, 6, stop_tokens=(stop_tok,)))
    np.testing.assert_array_equal(out[0, 3:], np.full(6, stop_tok))
    np.testing.assert_array_equal(out[1], free[1])


def test_engine_eos_from_config(mesh8, key):
    """With config.eos_token_id set, serve() stops on it by default."""
    import dataclasses
    cfg = dataclasses.replace(_cfg(), eos_token_id=5)
    m = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = m.init(key)
    ids = jnp.asarray([[9, 8, 7]], jnp.int32)
    free = np.asarray(Engine(m, batch=1, max_seq=64)
                      .serve(params, ids, 12, stop_tokens=()))
    out = np.asarray(Engine(m, batch=1, max_seq=64)
                     .serve(params, ids, 12))
    if 5 not in free[0, 3:]:
        np.testing.assert_array_equal(out, free)
    else:
        first = 3 + int(np.argmax(free[0, 3:] == 5))
        np.testing.assert_array_equal(out[0, :first + 1],
                                      free[0, :first + 1])
        np.testing.assert_array_equal(out[0, first:],
                                      np.full(out.shape[1] - first, 5))


def test_engine_serve_ragged_matches_solo(model, key):
    """Ragged batches (left-pad + kv_start mask + shifted rope) must
    generate exactly what each prompt generates served alone."""
    params = model.init(key)
    prompts = [[5, 9, 2, 7, 1], [3, 8]]
    outs = Engine(model, batch=2, max_seq=32).serve_ragged(
        params, prompts, gen_len=6)
    for i, p in enumerate(prompts):
        solo = np.asarray(Engine(model, batch=1, max_seq=32).serve(
            params, jnp.asarray([p], jnp.int32), 6))[0]
        np.testing.assert_array_equal(np.asarray(outs[i]), solo,
                                      err_msg=f"row {i}")


def test_engine_serve_ragged_equal_lengths_degenerates(model, key):
    """Equal-length prompts through serve_ragged == plain serve."""
    params = model.init(key)
    prompts = [[1, 2, 3], [4, 5, 6]]
    outs = Engine(model, batch=2, max_seq=32).serve_ragged(
        params, prompts, gen_len=4)
    plain = np.asarray(Engine(model, batch=2, max_seq=32).serve(
        params, jnp.asarray(prompts, jnp.int32), 4))
    np.testing.assert_array_equal(np.stack(outs), plain)


def test_engine_decode_profile_hook(model, key, tmp_path):
    """The decode profile window (reference engine.py:153-179) traces the
    first N steps and leaves generation unchanged."""
    params = model.init(key)
    ids = jnp.asarray([[9, 8, 7]], jnp.int32)
    # temperature > 0 locks the RNG-stream contract: profiling must not
    # consume extra PRNG splits vs an unprofiled serve.
    plain = np.asarray(Engine(model, batch=1, max_seq=16, temperature=0.7,
                              top_k=8, seed=3).serve(params, ids, 5))
    eng = Engine(model, batch=1, max_seq=16, temperature=0.7, top_k=8,
                 seed=3, profile_dir=str(tmp_path), profile_steps=2)
    prof = np.asarray(eng.serve(params, ids, 5))
    np.testing.assert_array_equal(plain, prof)
    from triton_dist_tpu.tools.profiler import trace_files
    assert trace_files("engine_decode", str(tmp_path)), "no trace written"


def test_engine_reuse_resets_cache(model, key):
    """Two serves from the same Engine must be independent (the KV cache
    resets between calls) — a stale cache would change the second run."""
    params = model.init(key)
    ids = jnp.asarray([[1, 2, 3]], jnp.int32)
    eng = Engine(model, batch=1, max_seq=16)
    first = np.asarray(eng.serve(params, ids, 4))
    second = np.asarray(eng.serve(params, ids, 4))
    np.testing.assert_array_equal(first, second)


def test_engine_batch_row_independence(model, key):
    """Greedy generation for a row must not depend on what else is in
    the batch (attention/cache leakage across rows)."""
    params = model.init(key)
    a = jnp.asarray([[1, 2, 3], [40, 50, 60]], jnp.int32)
    b = jnp.asarray([[1, 2, 3], [7, 8, 9]], jnp.int32)
    eng = Engine(model, batch=2, max_seq=16)
    out_a = np.asarray(eng.serve(params, a, 4))
    out_b = np.asarray(eng.serve(params, b, 4))
    np.testing.assert_array_equal(out_a[0], out_b[0])


def test_engine_ragged_stop_profile_combo(model, key, tmp_path):
    """All three serve features together keep the output contract."""
    params = model.init(key)
    prompts = [[5, 9, 2], [3]]
    eng = Engine(model, batch=2, max_seq=32,
                 profile_dir=str(tmp_path), profile_steps=2)
    free = eng.serve_ragged(params, prompts, gen_len=6)
    stop_tok = int(free[0][3])
    eng2 = Engine(model, batch=2, max_seq=32,
                  profile_dir=str(tmp_path), profile_steps=2)
    outs = eng2.serve_ragged(params, prompts, gen_len=6,
                             stop_tokens=(stop_tok,))
    assert len(outs) == 2
    assert len(outs[0]) == 3 + 6 and len(outs[1]) == 1 + 6
    # row 0 froze on its stop token
    gen0 = np.asarray(outs[0][3:])
    first = int(np.argmax(gen0 == stop_tok))
    assert (gen0[first:] == stop_tok).all()
