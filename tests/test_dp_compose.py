"""Fused ops composed under an outer data-parallel axis.

The reference delegates DP to torchrun replication (SURVEY.md §2.9 "DP:
not a subsystem"). Here DP is a mesh axis: the user wraps a step in
``shard_map(..., axis_names={"dp"})`` and every fused op nests inside it
— ``nestable_shard_map`` reuses the context mesh, making both axes
manual inside the op, so ``logical_device_id`` keeps the dp coordinate
and remote DMAs stay within the dp slice automatically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture()
def mesh_dp(devices):
    return Mesh(np.array(devices).reshape(2, 4), ("dp", "tp"))


def _dp_wrap(mesh, fn, in_specs, out_specs):
    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, axis_names={"dp"},
                                 check_vma=False))


# impl="pallas" under an outer dp axis requires compiled (TPU) mode: the
# interpreter's io_callback crashes XLA when nested in a manual region
# (see ops.common.resolve_interpret guard); tpu_smoke covers the
# compiled nesting path.
@pytest.mark.parametrize("impl", ["xla"])
def test_ag_gemm_under_dp(mesh_dp, key, impl):
    from triton_dist_tpu.ops.allgather_gemm import (
        ag_gemm, create_ag_gemm_context)
    ctx = create_ag_gemm_context(mesh_dp, "tp")
    m, k, n = 32, 32, 64
    k1, k2 = jax.random.split(key)
    x = jax.random.normal(k1, (m, k), jnp.float32)
    b = jax.random.normal(k2, (k, n), jnp.float32) / 8
    xs = jax.device_put(x, NamedSharding(mesh_dp, P(("dp", "tp"), None)))
    bs = jax.device_put(b, NamedSharding(mesh_dp, P(None, "tp")))

    f = _dp_wrap(mesh_dp, lambda a, w: ag_gemm(a, w, ctx, impl=impl),
                 (P("dp", None), P(None, None)), P("dp", None))
    out = f(xs, bs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) @ np.asarray(b),
                               rtol=2e-3, atol=2e-3)


# impl="pallas" under an outer dp axis requires compiled (TPU) mode: the
# interpreter's io_callback crashes XLA when nested in a manual region
# (see ops.common.resolve_interpret guard); tpu_smoke covers the
# compiled nesting path.
@pytest.mark.parametrize("impl", ["xla"])
def test_gemm_rs_under_dp(mesh_dp, key, impl):
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)
    ctx = create_gemm_rs_context(mesh_dp, "tp")
    m, k, n = 32, 32, 64
    k1, k2 = jax.random.split(key)
    x = jax.random.normal(k1, (m, k), jnp.float32)
    b = jax.random.normal(k2, (k, n), jnp.float32) / 8
    # within each dp slice: x cols sharded over tp, out rows sharded over tp
    xs = jax.device_put(x, NamedSharding(mesh_dp, P("dp", "tp")))
    bs = jax.device_put(b, NamedSharding(mesh_dp, P("tp", None)))

    f = _dp_wrap(mesh_dp, lambda a, w: gemm_rs(a, w, ctx, impl=impl),
                 (P("dp", None), P(None, None)), P("dp", None))
    out = f(xs, bs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) @ np.asarray(b),
                               rtol=2e-3, atol=2e-3)


# impl="pallas" under an outer dp axis requires compiled (TPU) mode: the
# interpreter's io_callback crashes XLA when nested in a manual region
# (see ops.common.resolve_interpret guard); tpu_smoke covers the
# compiled nesting path.
@pytest.mark.parametrize("impl", ["xla"])
def test_flash_decode_under_dp(mesh_dp, key, impl):
    """SP decode inside a dp slice: each dp group holds its own batch and
    combines split-KV partials across its own tp ranks only."""
    from triton_dist_tpu.ops.flash_decode import (
        create_flash_decode_context, gqa_fwd_batch_decode)
    ctx = create_flash_decode_context(mesh_dp, "tp")
    b, hq, hkv, d, t = 2, 8, 4, 16, 32
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, hq, d), jnp.float32)
    kk = jax.random.normal(ks[1], (b, t, hkv, d), jnp.float32)
    vv = jax.random.normal(ks[2], (b, t, hkv, d), jnp.float32)

    def golden(q, kk, vv):
        g = hq // hkv
        qh = q.reshape(b, hkv, g, d)
        s = np.einsum("bkgd,btkd->bkgt", qh, kk) / np.sqrt(d)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        return np.einsum("bkgt,btkd->bkgd", p, vv).reshape(b, hq, d)

    qs = jax.device_put(q, NamedSharding(mesh_dp, P("dp")))
    kvs = NamedSharding(mesh_dp, P("dp", "tp"))
    kks, vvs = jax.device_put(kk, kvs), jax.device_put(vv, kvs)

    f = _dp_wrap(
        mesh_dp,
        lambda q, kk, vv: gqa_fwd_batch_decode(
            q, kk, vv, jnp.int32(t), ctx, impl=impl),
        (P("dp"), P("dp", None), P("dp", None)), P("dp"))
    out = f(qs, kks, vvs)
    np.testing.assert_allclose(
        np.asarray(out),
        golden(np.asarray(q), np.asarray(kk), np.asarray(vv)),
        rtol=2e-3, atol=2e-3)


def test_pallas_under_dp_raises_on_interpreter(mesh_dp, key):
    """The interpret-mode nesting limitation must surface as a clear error,
    not an XLA process abort."""
    from triton_dist_tpu.ops.allgather_gemm import (
        ag_gemm, create_ag_gemm_context)
    ctx = create_ag_gemm_context(mesh_dp, "tp")
    x = jax.device_put(
        jax.random.normal(key, (32, 32), jnp.float32),
        NamedSharding(mesh_dp, P(("dp", "tp"), None)))
    b = jax.device_put(
        jax.random.normal(key, (32, 64), jnp.float32),
        NamedSharding(mesh_dp, P(None, "tp")))
    f = _dp_wrap(mesh_dp, lambda a, w: ag_gemm(a, w, ctx, impl="pallas"),
                 (P("dp", None), P(None, None)), P("dp", None))
    with pytest.raises(NotImplementedError, match="interpret-mode"):
        f(x, b)


@pytest.mark.parametrize("mode", ["ag_rs", "gemm_ar"])
def test_tp_mlp_under_dp(mesh_dp, key, mode):
    """A whole fused layer under dp: per-dp-slice batches through the
    AG-GEMM/GEMM-RS (or GEMM-AR) forward."""
    from triton_dist_tpu.layers.tp_mlp import TPMLP
    mlp = TPMLP(hidden_size=32, intermediate_size=64, mesh=mesh_dp,
                axis="tp", dtype=jnp.float32, impl="xla")
    params = mlp.init(key)
    x = jax.random.normal(jax.random.fold_in(key, 1), (16, 32), jnp.float32)
    # ag_rs wants row-sharded input; gemm_ar wants it replicated within
    # the slice — either way the batch dim carries dp outermost.
    xs = jax.device_put(x, NamedSharding(mesh_dp, P(("dp", "tp"), None))
                        if mode == "ag_rs"
                        else NamedSharding(mesh_dp, P("dp", None)))

    wg, wu, wd = (np.asarray(params[k], np.float64)
                  for k in ("w_gate", "w_up", "w_down"))
    xf = np.asarray(x, np.float64)

    def silu(v):
        return v / (1 + np.exp(-v))
    ref = (silu(xf @ wg) * (xf @ wu)) @ wd

    f = _dp_wrap(mesh_dp, lambda p, v: mlp(p, v, mode=mode),
                 (P(None, None), P("dp", None)), P("dp", None))
    out = f(params, xs)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=5e-2, atol=5e-2)
