"""Gradient parity for the fused-op custom VJPs (ops/autodiff.py).

Globally ``ag_gemm(a, b) == a @ b == gemm_rs(a, b)`` (only the
shardings differ), so ``jnp.dot`` under the same shardings is the
golden for both values and gradients. What these tests pin down:

  * each wrapper's grads equal the global-math grads (the custom VJP
    formulas — GEMM-RS backward = AG-GEMM, and vice versa — are right);
  * the multi-B form accumulates dA over all heads' cotangents;
  * gemm_ar (replicated output) gets comm-free local-dot grads.

Run with impl="pallas": on the CPU mesh the fused kernels execute in
interpret mode, so the backward really does go through the transpose
fused kernel, not an XLA fallback.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.ops import autodiff
from triton_dist_tpu.ops.allgather_gemm import create_ag_gemm_context
from triton_dist_tpu.ops.gemm_reduce_scatter import create_gemm_rs_context


def _rand(key, shape, mesh, spec):
    x = jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)
    return jax.device_put(x, NamedSharding(mesh, spec))


def _grad_pair(fused_loss, golden_loss, args):
    gf = jax.jit(jax.grad(fused_loss, argnums=tuple(range(len(args)))))
    gg = jax.jit(jax.grad(golden_loss, argnums=tuple(range(len(args)))))
    for a, b in zip(jax.tree.leaves(gf(*args)), jax.tree.leaves(gg(*args))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_ag_gemm_grads(mesh8, impl):
    ctx = create_ag_gemm_context(mesh8, "tp")
    a = _rand(0, (16, 32), mesh8, P("tp", None))
    b = _rand(1, (32, 16), mesh8, P(None, "tp"))
    # A non-uniform weighting makes wrong transposes show up in grads.
    w = jax.random.normal(jax.random.PRNGKey(2), (16, 16), jnp.float32)

    def fused(a, b):
        return jnp.sum(autodiff.ag_gemm(a, b, ctx, impl=impl) * w)

    def golden(a, b):
        return jnp.sum(jnp.dot(a, b) * w)

    np.testing.assert_allclose(
        np.asarray(jax.jit(fused)(a, b)), np.asarray(jax.jit(golden)(a, b)),
        rtol=2e-5)
    _grad_pair(fused, golden, (a, b))


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_ag_gemm_multi_grads(mesh8, impl):
    ctx = create_ag_gemm_context(mesh8, "tp")
    a = _rand(3, (16, 32), mesh8, P("tp", None))
    b1 = _rand(4, (32, 16), mesh8, P(None, "tp"))
    b2 = _rand(5, (32, 16), mesh8, P(None, "tp"))
    w1 = jax.random.normal(jax.random.PRNGKey(6), (16, 16), jnp.float32)
    w2 = jax.random.normal(jax.random.PRNGKey(7), (16, 16), jnp.float32)

    def fused(a, b1, b2):
        c1, c2 = autodiff.ag_gemm_multi(a, (b1, b2), ctx, impl)
        return jnp.sum(c1 * w1) + jnp.sum(c2 * w2)

    def golden(a, b1, b2):
        return jnp.sum(jnp.dot(a, b1) * w1) + jnp.sum(jnp.dot(a, b2) * w2)

    _grad_pair(fused, golden, (a, b1, b2))


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_gemm_rs_grads(mesh8, impl):
    ctx = create_gemm_rs_context(mesh8, "tp")
    a = _rand(8, (16, 32), mesh8, P(None, "tp"))
    b = _rand(9, (32, 16), mesh8, P("tp", None))
    w = jax.random.normal(jax.random.PRNGKey(10), (16, 16), jnp.float32)

    def fused(a, b):
        return jnp.sum(autodiff.gemm_rs(a, b, ctx, impl=impl) * w)

    def golden(a, b):
        return jnp.sum(jnp.dot(a, b) * w)

    _grad_pair(fused, golden, (a, b))


def test_ep_a2a_grads(mesh8, monkeypatch):
    """The a2a VJP (reverse exchange + live-count masking): EPMoE grads
    through the Pallas dispatch/combine equal an INDEPENDENT baseline.

    The baseline bypasses the custom VJP entirely (the layer is
    monkeypatched back to the raw op with impl="xla", where
    lax.all_to_all differentiates natively) — so a mathematically wrong
    adjoint cannot cancel out of both sides.
    """
    from triton_dist_tpu.layers import ep_a2a as ep_a2a_mod
    from triton_dist_tpu.layers.ep_moe import EPMoE
    from triton_dist_tpu.ops.all_to_all import fast_all_to_all as raw_a2a

    grads = {}
    for name, impl in (("native", "xla"), ("vjp", "pallas")):
        if name == "native":
            monkeypatch.setattr(ep_a2a_mod, "fast_all_to_all", raw_a2a)
        else:
            monkeypatch.undo()
        moe = EPMoE(hidden_size=32, intermediate_size=32, num_experts=8,
                    topk=2, mesh=mesh8, axis="tp", dtype=jnp.float32,
                    impl=impl)
        params = moe.init(jax.random.PRNGKey(0))
        x = _rand(14, (16, 32), mesh8, P("tp", None))

        def loss(p, x):
            return jnp.sum(moe(p, x) ** 2)

        v, g = jax.jit(jax.value_and_grad(loss))(params, x)
        assert bool(jnp.isfinite(v))
        grads[name] = jax.tree.map(np.asarray, g)
    for a, b in zip(jax.tree.leaves(grads["native"]),
                    jax.tree.leaves(grads["vjp"])):
        assert np.isfinite(a).all() and np.isfinite(b).all()
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_sp_attention_grads(mesh8):
    """Context-parallel training: the ring and ulysses SP attention
    impls differentiate natively (ppermute/all_to_all carry transpose
    rules; the fori_loop has static bounds) with grads equal to the
    AG-KV baseline — long-context training needs no custom VJP."""
    from triton_dist_tpu.ops.sp_attention import (
        create_sp_attention_context, sp_ag_attention)

    ctx = create_sp_attention_context(mesh8, "tp")
    b, s, hq, hkv, d = 2, 32, 8, 8, 16
    sh = P(None, "tp")
    q = _rand(20, (b, s, hq, d), mesh8, sh)
    k = _rand(21, (b, s, hkv, d), mesh8, sh)
    v = _rand(22, (b, s, hkv, d), mesh8, sh)

    def loss(impl):
        return jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(
                sp_ag_attention(q, k, v, ctx, impl=impl) ** 2),
            argnums=(0, 1, 2)))
    base = [np.asarray(t) for t in loss("xla")(q, k, v)]
    for impl in ("ring", "ulysses"):
        got = [np.asarray(t) for t in loss(impl)(q, k, v)]
        for a, g in zip(base, got):
            assert np.isfinite(g).all()
            np.testing.assert_allclose(a, g, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_gemm_ar_grads(mesh8, impl):
    ctx = create_gemm_rs_context(mesh8, "tp")
    a = _rand(11, (16, 32), mesh8, P(None, "tp"))
    b = _rand(12, (32, 16), mesh8, P("tp", None))
    w = jax.random.normal(jax.random.PRNGKey(13), (16, 16), jnp.float32)

    def fused(a, b):
        return jnp.sum(autodiff.gemm_ar(a, b, ctx, impl=impl) * w)

    def golden(a, b):
        return jnp.sum(jnp.dot(a, b) * w)

    _grad_pair(fused, golden, (a, b))
