"""Mega runtime tests (reference mega_triton_kernel/test/: per-op tests +
models/test_qwen3.py comparing the megakernel against torch references,
SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.mega import ModelBuilder, MegaQwen3, TaskGraph
from triton_dist_tpu.mega import native
from triton_dist_tpu.models import DenseLLM, ModelConfig
from triton_dist_tpu.models.kv_cache import KVCacheManager


# -- native scheduler --------------------------------------------------------

def _random_dag(rng, n):
    """Shared randomized-DAG builder for the native/python parity tests
    (diamonds, chains, fan-in/out)."""
    edges = []
    for dst in range(1, n):
        for src in rng.choice(dst, size=min(dst, 3), replace=False):
            if rng.rand() < 0.6:
                edges.append((int(src), dst))
    return np.asarray(edges or [(0, 1)], np.int32)


def test_native_lib_builds():
    assert native.have_native(), "C++ scheduler failed to build"


@pytest.mark.parametrize("policy", ["round_robin", "zigzag", "least_loaded"])
def test_schedule_native_matches_python(policy):
    costs = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5]
    a = native.schedule(11, 4, policy, costs=costs)
    b = native._schedule_py(11, 4, policy, costs=costs)
    np.testing.assert_array_equal(a, b)


def test_zigzag_pattern():
    out = native.schedule(8, 3, "zigzag")
    assert out.tolist() == [0, 1, 2, 2, 1, 0, 0, 1]


def test_toposort_and_cycles():
    edges = [(0, 2), (1, 2), (2, 3)]
    order = native.toposort(4, edges)
    assert order.tolist() == [0, 1, 2, 3]
    with pytest.raises(ValueError):
        native.toposort(2, [(0, 1), (1, 0)])
    py = native._toposort_py(4, np.asarray(edges, np.int32))
    np.testing.assert_array_equal(order, py)


def test_wavefronts():
    # diamond: 0 -> {1,2} -> 3
    n, wave = native.wavefronts(4, [(0, 1), (0, 2), (1, 3), (2, 3)])
    assert n == 3
    assert wave.tolist() == [0, 1, 1, 2]
    n2, wave2 = native._wavefronts_py(
        4, np.asarray([(0, 1), (0, 2), (1, 3), (2, 3)], np.int32))
    assert n2 == n and wave2.tolist() == wave.tolist()


# -- task graph --------------------------------------------------------------

def test_task_graph_executor():
    g = TaskGraph()
    g.add("mul", lambda a, b: a * b, ["x", "y"], ["xy"])
    g.add("add", lambda a, b: a + b, ["xy", "z"], ["out"])
    g.add("neg", lambda a: -a, ["x"], ["nx"])
    run = g.make_executor(["x", "y", "z"], ["out", "nx"])
    out, nx = run(jnp.float32(3), jnp.float32(4), jnp.float32(5))
    assert float(out) == 17.0 and float(nx) == -3.0
    assert g.edges().tolist() == [[0, 1]]
    assert "3 tasks" in g.summary()


def test_task_graph_ssa_violation():
    g = TaskGraph()
    g.add("a", lambda x: x, ["i"], ["o"])
    with pytest.raises(ValueError):
        g.add("b", lambda x: x, ["i"], ["o"])


def test_queue_assignment_costs():
    g = TaskGraph()
    for i in range(6):
        g.add("op", lambda x: x, ["i"], [f"o{i}"] if i else ["o0"],
              cost=i + 1) if False else None
    g2 = TaskGraph()
    for i in range(6):
        g2.add("op", lambda x: x, ["i"], [f"b{i}"], cost=i + 1)
    q = g2.queue_assignment(2, "least_loaded")
    assert len(q) == 6 and set(q.tolist()) <= {0, 1}


# -- qwen3 mega step ---------------------------------------------------------

@pytest.mark.slow
def test_mega_qwen3_matches_dense(mesh8, key):
    cfg = ModelConfig(hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=8, vocab_size=128,
                      max_position_embeddings=32, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = model.init(key)
    kv = KVCacheManager(cfg.num_hidden_layers, 2, 16,
                        cfg.num_key_value_heads, cfg.head_dim, mesh=mesh8,
                        axis="tp", dtype=cfg.dtype)
    caches = kv.init()
    token = jnp.array([[5], [7]], jnp.int32)

    ref, ref_caches = model.forward(params, token, caches, 0,
                                    mode="gemm_ar")
    mega = MegaQwen3(model, decode_mode="gemm_ar")
    out, new_caches = mega.step(params, token, caches, 0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)
    for (rk, rv), (nk, nv) in zip(ref_caches, new_caches):
        np.testing.assert_allclose(np.asarray(rk), np.asarray(nk))
        np.testing.assert_allclose(np.asarray(rv), np.asarray(nv))
    # graph structure sanity: tasks per layer + embed + final norm + head
    n_waves, _ = mega.graph.waves()
    # embed + final norm + lm head, plus 9 tasks per layer
    assert len(mega.graph.tasks) == 3 + 9 * cfg.num_hidden_layers
    assert n_waves >= 6


@pytest.mark.slow
def test_mega_decode_loop(mesh8, key):
    """Multi-step decode through the mega step matches DenseLLM decode."""
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=8, vocab_size=64,
                      max_position_embeddings=32, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = model.init(key)
    kv = KVCacheManager(1, 1, 8, 8, 8, mesh=mesh8, axis="tp",
                        dtype=cfg.dtype)
    mega = MegaQwen3(model, decode_mode="gemm_ar")

    c1 = kv.init()
    c2 = kv.init()
    tok = jnp.array([[3]], jnp.int32)
    t1 = t2 = tok
    for step in range(3):
        ref, c1 = model.forward(params, t1, c1, step, mode="gemm_ar")
        out, c2 = mega.step(params, t2, c2, step)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        t1 = jnp.argmax(ref[:, -1], -1).astype(jnp.int32)[:, None]
        t2 = jnp.argmax(out[:, -1], -1).astype(jnp.int32)[:, None]
        np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))


def test_native_python_parity_random_dags():
    """Native toposort/wavefronts must be bit-identical to the Python
    fallback on randomized DAGs (diamonds, chains, fan-in/out) — the
    scheduler correctness the reference gets from its device scoreboard
    is carried here by this parity invariant."""
    from triton_dist_tpu.mega.native import (
        _toposort_py, _wavefronts_py, have_native, toposort, wavefronts)
    if not have_native():
        pytest.skip("no native build")
    rng = np.random.RandomState(0)
    for trial in range(10):
        n = int(rng.randint(3, 40))
        edges = _random_dag(rng, n)
        np.testing.assert_array_equal(toposort(n, edges),
                                      _toposort_py(n, edges),
                                      err_msg=f"trial {trial}")
        nw, waves = wavefronts(n, edges)
        nw_py, waves_py = _wavefronts_py(n, edges)
        assert nw == nw_py, trial
        np.testing.assert_array_equal(waves, waves_py,
                                      err_msg=f"trial {trial}")
        # Wave numbers must respect every edge.
        for s, d in edges:
            assert waves[s] < waves[d], (trial, s, d)


def test_least_loaded_schedule_balances():
    """least_loaded must beat round_robin on skewed costs."""
    from triton_dist_tpu.mega.native import schedule
    costs = np.asarray([100, 1, 1, 1, 100, 1, 1, 1], np.int64)
    q_ll = schedule(8, 2, "least_loaded", costs=costs)
    loads = [int(costs[q_ll == i].sum()) for i in range(2)]
    q_rr = schedule(8, 2, "round_robin")
    loads_rr = [int(costs[q_rr == i].sum()) for i in range(2)]
    assert max(loads) <= max(loads_rr)
    assert max(loads) - min(loads) <= 2  # near-perfect balance here


def test_critical_path_schedule():
    """HEFT critical-path scheduling: makespan invariants + native/python
    parity on random DAGs."""
    from triton_dist_tpu.mega.native import (
        _schedule_critical_path_py, have_native, schedule_critical_path)
    # chain: makespan = sum of costs regardless of queues
    chain_edges = [(i, i + 1) for i in range(4)]
    costs = [2, 3, 1, 4, 5]
    _, span = schedule_critical_path(5, chain_edges, 4, costs=costs)
    assert span == sum(costs)
    # independent tasks: perfect balance
    assign, span = schedule_critical_path(8, np.empty((0, 2), np.int32),
                                          4, costs=[3] * 8)
    assert span == 6 and len(set(assign.tolist())) == 4
    # dependency-aware beats (or ties) cost-only least_loaded makespan
    # on a fan-out/fan-in diamond with a heavy critical path
    edges = [(0, 1), (0, 2), (0, 3), (1, 4), (2, 4), (3, 4)]
    c = [1, 10, 1, 1, 1]
    _, span_d = schedule_critical_path(5, edges, 2, costs=c)
    assert span_d == 12  # 0 → 1(heavy) → 4, others overlap
    # zero-cost tasks: rank ties must not schedule a child before its
    # zero-cost parent (tie-break is topo position, not raw id): the free
    # parent finishes at t=0 and both children overlap → span 4
    _, s0 = schedule_critical_path(3, [(2, 0), (2, 1)], 2,
                                   costs=[4, 4, 0])
    assert s0 == 4
    with pytest.raises(ValueError, match=">= 0"):
        schedule_critical_path(2, [(0, 1)], 1, costs=[-1, 1])
    if have_native():
        rng = np.random.RandomState(7)
        for trial in range(10):
            n = int(rng.randint(3, 40))
            edges = _random_dag(rng, n)
            cst = rng.randint(1, 20, size=n).astype(np.int64)
            a_n, s_n = schedule_critical_path(n, edges, 3, costs=cst)
            a_p, s_p = _schedule_critical_path_py(n, edges, 3, costs=cst)
            assert s_n == s_p, trial
            np.testing.assert_array_equal(a_n, a_p,
                                          err_msg=f"trial {trial}")


def test_task_graph_critical_path_policy():
    """TaskGraph exposes the dependency-aware policy + makespan model."""
    from triton_dist_tpu.mega.task_graph import TaskGraph
    g = TaskGraph()
    g.add("a", lambda x: x, ["in"], ["t0"], cost=4)
    g.add("b", lambda x: x, ["t0"], ["t1"], cost=2)
    g.add("c", lambda x: x, ["in"], ["t2"], cost=3)
    assign = g.queue_assignment(2, policy="critical_path")
    assert assign.shape == (3,)
    # chain a→b (6) dominates; c overlaps on the other queue
    assert g.makespan(2) == 6


def test_priority_order_is_topological():
    """The HEFT priority linearization must be a valid topo order on
    randomized DAGs (incl. zero-cost tasks), native == python."""
    from triton_dist_tpu.mega.native import (
        _priority_order_py, have_native, priority_order)
    rng = np.random.RandomState(3)
    for trial in range(10):
        n = int(rng.randint(3, 40))
        edges = _random_dag(rng, n)
        cst = rng.randint(0, 5, size=n).astype(np.int64)
        order = priority_order(n, edges, costs=cst)
        pos = np.empty(n, np.int64)
        pos[order] = np.arange(n)
        for s, d in edges:
            assert pos[s] < pos[d], (trial, s, d)
        if have_native():
            np.testing.assert_array_equal(
                order, _priority_order_py(n, edges, cst),
                err_msg=f"trial {trial}")


def test_priority_order_cycle():
    from triton_dist_tpu.mega.native import priority_order
    with pytest.raises(ValueError, match="cycle"):
        priority_order(2, [(0, 1), (1, 0)])


def test_executor_heft_order_matches_topo():
    """order_policy='heft' emits a different (critical-path-first)
    order but computes identical results — the runtime wiring of the
    scheduler (VERDICT r3 weak-4)."""
    g = TaskGraph()
    g.add("a", lambda x: x + 1.0, ["in"], ["t0"], cost=1)
    g.add("b", lambda x: x * 2.0, ["t0"], ["t1"], cost=5)
    g.add("c", lambda x: x - 3.0, ["in"], ["t2"], cost=1)
    g.add("d", lambda a, b: a + b, ["t1", "t2"], ["out"], cost=1)
    x = jnp.arange(4, dtype=jnp.float32)
    run_t = g.make_executor(["in"], ["out"], order_policy="topo")
    run_h = g.make_executor(["in"], ["out"], order_policy="heft")
    np.testing.assert_allclose(np.asarray(run_t(x)), np.asarray(run_h(x)))
    # heft prioritizes the heavy chain a→b over c
    order = g.priority_order().tolist()
    assert order.index(1) < order.index(2)


@pytest.mark.slow
def test_mega_qwen3_heft_matches_topo(mesh8, key):
    """MegaQwen3(order_policy='heft') is numerically identical to the
    default emission order (same graph, different linearization)."""
    cfg = ModelConfig(hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=8,
                      vocab_size=64, max_position_embeddings=16,
                      dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = model.init(key)
    kv = KVCacheManager(cfg.num_hidden_layers, 2, 16,
                        cfg.num_key_value_heads, cfg.head_dim,
                        mesh=mesh8, axis="tp", dtype=cfg.dtype)
    token = jnp.array([[5], [7]], jnp.int32)
    out_t, _ = MegaQwen3(model, decode_mode="gemm_ar").step(
        params, token, kv.init(), 0)
    out_h, _ = MegaQwen3(model, decode_mode="gemm_ar",
                         order_policy="heft").step(
        params, token, kv.init(), 0)
    np.testing.assert_allclose(np.asarray(out_t), np.asarray(out_h),
                               rtol=1e-5, atol=1e-5)


def test_heft_emission_inert_under_xla():
    """Pins the r5 demotion finding (docs/architecture.md "Mega
    scheduler"): topo- and heft-ordered emissions of the same graph
    compile to programs with IDENTICAL peak temp memory — XLA
    schedules the dataflow graph and normalizes instruction order
    away, so emission order is an observability knob, not a schedule
    input. If this ever fails, emission order has become meaningful
    and the scheduler's demotion should be revisited."""
    n = 128
    g = TaskGraph()
    for i in range(4):
        g.add("mm1", lambda x: x @ (jnp.ones((n, n)) * 0.01),
              ["x"], [f"t{i}"], cost=10 * (i + 1))
        g.add("mm2", lambda t: t @ (jnp.ones((n, n)) * 0.01),
              [f"t{i}"], [f"u{i}"], cost=5)
    g.add("sum", lambda *us: sum(jnp.sum(u) for u in us),
          [f"u{i}" for i in range(4)], ["out"], cost=1)
    assert g.order().tolist() != g.priority_order().tolist()
    x = jnp.ones((n, n), jnp.float32)
    temps = {}
    for pol in ("topo", "heft"):
        run = g.make_executor(["x"], ["out"], order_policy=pol)
        compiled = jax.jit(lambda x, run=run: run(x)).lower(x).compile()
        ma = compiled.memory_analysis()
        assert ma is not None, "memory_analysis unavailable: test is moot"
        temps[pol] = int(ma.temp_size_in_bytes)
    assert temps["topo"] >= 0
    assert temps["topo"] == temps["heft"], temps


def test_engine_use_mega_matches_plain(mesh8, key):
    """Engine(use_mega=True) greedy serving is token-identical to the
    plain jitted decode step (the mega program is the same dataflow;
    the chip measured it 1.49x faster — docs/perf.md)."""
    from triton_dist_tpu.models import Engine
    cfg = ModelConfig(hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=8, vocab_size=128,
                      max_position_embeddings=32, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = model.init(key)
    ids = jax.random.randint(jax.random.PRNGKey(3), (2, 4), 0,
                             cfg.vocab_size, jnp.int32)
    out_plain = Engine(model, batch=2, max_seq=16, prefill_mode="xla_ar",
                       decode_mode="gemm_ar").serve(params, ids, 3)
    out_mega = Engine(model, batch=2, max_seq=16, prefill_mode="xla_ar",
                      decode_mode="gemm_ar", use_mega=True
                      ).serve(params, ids, 3)
    np.testing.assert_array_equal(np.asarray(out_mega),
                                  np.asarray(out_plain))


def test_engine_decode_path_validation(mesh8):
    """The remaining ILLEGAL combos stay config ValueErrors (not
    asserts — they must survive ``python -O``): an unknown decode_path
    and a use_mega/decode_path contradiction. The old
    use_mega x (paged|sp|ragged) refusals are gone — those are real
    code paths now (ISSUE 11, tests/test_scheduler.py)."""
    from triton_dist_tpu.models import Engine
    cfg = ModelConfig(hidden_size=64, intermediate_size=128,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=8, vocab_size=128,
                      max_position_embeddings=32, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    with pytest.raises(ValueError, match="decode_path"):
        Engine(model, batch=2, max_seq=16, prefill_mode="xla_ar",
               decode_mode="gemm_ar", decode_path="turbo")
    with pytest.raises(ValueError, match="conflicting"):
        Engine(model, batch=2, max_seq=16, prefill_mode="xla_ar",
               decode_mode="gemm_ar", use_mega=True,
               decode_path="plain")
    # use_mega=True IS decode_path="mega" (legacy spelling).
    eng = Engine(model, batch=2, max_seq=16, prefill_mode="xla_ar",
                 decode_mode="gemm_ar", use_mega=True)
    assert eng.decode_path == "mega" and eng.use_mega


def test_engine_use_mega_serves_ragged_and_stream(mesh8, key):
    """ISSUE 11: the mega graph takes per-row kv_start/offset vectors,
    so ragged serving AND continuous batching run under use_mega —
    greedy outputs bit-identical to the plain decode path (the two
    refusals this test replaces are deleted)."""
    from triton_dist_tpu.models import Engine
    cfg = ModelConfig(hidden_size=64, intermediate_size=128,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=8, vocab_size=128,
                      max_position_embeddings=32, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = model.init(key)

    def eng(path):
        return Engine(model, batch=2, max_seq=32, prefill_mode="xla_ar",
                      decode_mode="gemm_ar", decode_path=path)

    prompts = [[1, 2, 3], [9, 8, 7, 6, 5]]
    rag_p = eng("plain").serve_ragged(params, prompts, gen_len=4)
    rag_m = eng("mega").serve_ragged(params, prompts, gen_len=4)
    for a, b in zip(rag_p, rag_m):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    st_p = eng("plain").serve_stream(params, prompts + [[4, 4], [5]],
                                     gen_len=3)
    st_m = eng("mega").serve_stream(params, prompts + [[4, 4], [5]],
                                    gen_len=3)
    assert st_p == st_m
