"""Fused ops at deliberately awkward shapes (VERDICT r2 #10: round-shape
tests at M=64/K=32 miss tile-clamp and tail bugs).

Every case uses dimensions that are NOT multiples of the preferred
128/256/512 tiles, so the divisor-clamping (`_pick_block_k`), config
fallback, and padding paths all execute. Goldens are the ops' own
``impl="xla"`` bodies (reference analog: per-shape sweep loops in
test/nvidia/test_ag_gemm.py:72-197).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.ops.allgather_gemm import (
    ag_gemm, ag_swiglu, create_ag_gemm_context)
from triton_dist_tpu.ops.gemm_reduce_scatter import (
    create_gemm_rs_context, gemm_ar, gemm_rs)
from triton_dist_tpu.runtime.utils import assert_allclose

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow

WORLD = 8


@pytest.mark.parametrize("variant", ["vmem", "hbm", "hbm_kt"])
@pytest.mark.parametrize("m,k,n", [(192, 96, 160), (24, 40, 48)])
def test_ag_gemm_odd(mesh8, key, variant, m, k, n):
    ka, kb = jax.random.split(key)
    a = (jax.random.normal(ka, (m, k)) / 4).astype(jnp.float32)
    b = (jax.random.normal(kb, (k, n)) / 4).astype(jnp.float32)
    ctx = dataclasses.replace(create_ag_gemm_context(mesh8),
                              variant=variant)
    got = ag_gemm(a, b, ctx, impl="pallas")
    ref = ag_gemm(a, b, ctx, impl="xla")
    assert got.shape == (m, n)
    assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    full = np.asarray(a, np.float64) @ np.asarray(b, np.float64)
    assert_allclose(got, full, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("variant", ["vmem", "hbm"])
def test_gemm_rs_odd(mesh8, key, variant):
    m, k, n = 136, 72, 104     # none 128-multiples; m/world = 17 rows
    ka, kb = jax.random.split(key)
    a = (jax.random.normal(ka, (m, k)) / 4).astype(jnp.float32)
    b = (jax.random.normal(kb, (k, n)) / 4).astype(jnp.float32)
    ctx = dataclasses.replace(create_gemm_rs_context(mesh8),
                              variant=variant)
    got = gemm_rs(a, b, ctx, impl="pallas")
    ref = gemm_rs(a, b, ctx, impl="xla")
    assert got.shape == (m, n)
    assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_gemm_ar_nondivisible_m(mesh8, key):
    # M=100 is not divisible by world=8: exercises the zero-pad + slice
    # path (the reference's tile-padded GEMM grids).
    m, k, n = 100, 48, 56
    ka, kb = jax.random.split(key)
    a = (jax.random.normal(ka, (m, k)) / 4).astype(jnp.float32)
    b = (jax.random.normal(kb, (k, n)) / 4).astype(jnp.float32)
    ctx = create_gemm_rs_context(mesh8)
    got = gemm_ar(a, b, ctx, impl="pallas")
    ref = gemm_ar(a, b, ctx, impl="xla")
    assert got.shape == (m, n)
    assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_ag_swiglu_odd(mesh8, key):
    m, h, inter = 48, 56, 80    # inter/world = 10 cols per shard
    ka, kg, ku = jax.random.split(key, 3)
    x = (jax.random.normal(ka, (m, h)) / 4).astype(jnp.float32)
    wg = (jax.random.normal(kg, (h, inter)) / 4).astype(jnp.float32)
    wu = (jax.random.normal(ku, (h, inter)) / 4).astype(jnp.float32)
    ctx = create_ag_gemm_context(mesh8)
    got = ag_swiglu(x, wg, wu, ctx, impl="pallas")
    ref = ag_swiglu(x, wg, wu, ctx, impl="xla")
    assert got.shape == ref.shape
    assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_flash_decode_partial_tail(mesh8, key):
    # kv_len lands mid-tile AND mid-rank: live tiles are a strict prefix
    # on early ranks, zero on late ranks (split-KV early-exit).
    from triton_dist_tpu.ops.flash_decode import (
        create_flash_decode_context, gqa_fwd_batch_decode)
    b, hq, hkv, d = 2, 8, 2, 64
    t_loc = 96                 # not a t_blk multiple after clamping
    ctx = dataclasses.replace(
        create_flash_decode_context(mesh8, axis="tp", variant="tiled"),
        t_blk=64)
    kq, kk, kv = jax.random.split(key, 3)
    q = (jax.random.normal(kq, (b, hq, d)) / 4).astype(jnp.bfloat16)
    k = (jax.random.normal(kk, (b, WORLD * t_loc, hkv, d)) / 4
         ).astype(jnp.bfloat16)
    v = (jax.random.normal(kv, (b, WORLD * t_loc, hkv, d)) / 4
         ).astype(jnp.bfloat16)
    kv_len = 3 * t_loc + 17    # rank 3 partial, ranks 4..7 empty
    got = gqa_fwd_batch_decode(q, k, v, kv_len, ctx)
    ctx_e = dataclasses.replace(ctx, variant="einsum")
    ref = gqa_fwd_batch_decode(q, k, v, kv_len, ctx_e)
    assert_allclose(got, ref, rtol=5e-2, atol=5e-2)


@pytest.mark.parametrize("method", ["one_shot", "two_shot",
                                    "recursive_doubling"])
def test_allreduce_odd_partials(mesh8, key, method):
    # (w, 136, 72): M=136 is not divisible by world=8, so TWO_SHOT must
    # fall back rather than mis-slice; the others take it directly.
    from triton_dist_tpu.ops.allreduce import (
        AllReduceMethod, create_allreduce_context, all_reduce)
    x = (jax.random.normal(key, (WORLD, 136, 72)) / 4).astype(jnp.float32)
    ctx = create_allreduce_context(mesh8, "tp",
                                   method=AllReduceMethod(method))
    got = all_reduce(x, ctx, impl="pallas")
    ref = all_reduce(x, ctx, impl="xla")
    assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_a2a_unaligned_capacity(mesh8, key):
    # capacity=12 has no sublane-aligned divisor -> chunk falls back to
    # the full slab; live-count masking still must hold.
    from triton_dist_tpu.ops.all_to_all import (
        create_all_to_all_context, fast_all_to_all)
    cap, h = 12, 128
    ctx = create_all_to_all_context(mesh8, "tp", capacity=cap)
    buf = jax.random.normal(key, (WORLD * WORLD, cap, h), jnp.float32)
    counts = jax.random.randint(jax.random.PRNGKey(1), (WORLD * WORLD,),
                                0, cap + 1, jnp.int32)
    bufs = jax.device_put(buf, NamedSharding(mesh8, P("tp")))
    counts_s = jax.device_put(counts, NamedSharding(mesh8, P("tp")))
    recv, rc = fast_all_to_all(bufs, counts_s, ctx, impl="pallas")
    ref, rc2 = fast_all_to_all(bufs, counts_s, ctx, impl="xla")
    recv = np.asarray(recv).reshape(WORLD, WORLD, cap, h)
    ref = np.asarray(ref).reshape(WORLD, WORLD, cap, h)
    rcn = np.asarray(rc).reshape(WORLD, WORLD)
    np.testing.assert_array_equal(np.asarray(rc), np.asarray(rc2))
    for dst in range(WORLD):
        for src in range(WORLD):
            n = rcn[dst, src]
            np.testing.assert_array_equal(recv[dst, src, :n],
                                          ref[dst, src, :n])


def test_hierarchical_nd_odd_payload(key):
    # 2x2x2 mesh with a (24, 40) payload — no 128-multiples anywhere.
    import numpy as _np
    from jax.sharding import Mesh
    from triton_dist_tpu.ops.hierarchical import (
        all_gather_nd, all_reduce_nd)
    devs = jax.devices()
    mesh = Mesh(_np.array(devs).reshape(2, 2, 2), ("x", "y", "z"))
    x = (jax.random.normal(key, (24, 40)) / 4).astype(jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("x")))
    ag = all_gather_nd(xs, mesh, ("x",))
    np.testing.assert_allclose(np.asarray(ag)[:24], np.asarray(x),
                               rtol=0, atol=0)
    # all_reduce_nd sums the per-device views of a replicated input
    # (in_specs=P(); see test_hierarchical.py) — replicated x sums to
    # 8*x. The odd (24, 40) payload stresses the RS-ladder slicing
    # (24 -> 12 -> 6 rows down the x/y rungs).
    ar = all_reduce_nd(x, mesh, ("x", "y", "z"))
    np.testing.assert_allclose(np.asarray(ar), 8 * np.asarray(x),
                               rtol=1e-5, atol=1e-5)


def test_flash_decode_per_row_lengths(mesh8, key):
    """Per-sequence kv lengths (reference kv_length_ptr + bid): each
    row masked to its own length must equal serving that row alone with
    a scalar length."""
    from triton_dist_tpu.ops.flash_decode import (
        create_flash_decode_context, gqa_fwd_batch_decode)
    b, hq, hkv, d, t_loc = 4, 8, 2, 64, 64
    t = WORLD * t_loc
    kq, kk, kv = jax.random.split(key, 3)
    q = (jax.random.normal(kq, (b, hq, d)) / 4).astype(jnp.bfloat16)
    k = (jax.random.normal(kk, (b, t, hkv, d)) / 4).astype(jnp.bfloat16)
    v = (jax.random.normal(kv, (b, t, hkv, d)) / 4).astype(jnp.bfloat16)
    lens = jnp.asarray([t, t // 2 + 3, 17, t_loc], jnp.int32)
    for variant in ("einsum", "tiled"):
        ctx = dataclasses.replace(
            create_flash_decode_context(mesh8, axis="tp",
                                        variant=variant), t_blk=32)
        got = gqa_fwd_batch_decode(q, k, v, lens, ctx)
        for r in range(b):
            ref = gqa_fwd_batch_decode(
                q[r:r + 1], k[r:r + 1], v[r:r + 1],
                jnp.int32(lens[r]), ctx)
            assert_allclose(got[r:r + 1], ref, rtol=4e-2, atol=4e-2)


def test_sp_attention_pallas_odd_block_shrink(mesh8, key):
    # s_loc=160 forces both sq_blk and t_sub to shrink (128 -> 32) via
    # the divisor loops; checks the clamped tiling end-to-end.
    from triton_dist_tpu.ops.sp_attention import (
        create_sp_attention_context, sp_ag_attention)
    b, s, hq, hkv, d = 1, WORLD * 160, 4, 2, 64
    ctx = create_sp_attention_context(mesh8, axis="tp", causal=True)
    kq, kk, kv = jax.random.split(key, 3)
    q = (jax.random.normal(kq, (b, s, hq, d)) / 4).astype(jnp.bfloat16)
    k = (jax.random.normal(kk, (b, s, hkv, d)) / 4).astype(jnp.bfloat16)
    v = (jax.random.normal(kv, (b, s, hkv, d)) / 4).astype(jnp.bfloat16)
    got = sp_ag_attention(q, k, v, ctx, impl="pallas")
    ref = sp_ag_attention(q, k, v, ctx, impl="xla")
    assert_allclose(got, ref, rtol=5e-2, atol=5e-2)
