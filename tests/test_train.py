"""Training-step contract tests (beyond-reference: the reference has no
training path at all — SURVEY §2.9 "DP: not a subsystem").

What must hold for the training step to be trusted:
  * loss falls over a few steps of overfitting one tiny batch (the
    gradients point somewhere useful);
  * remat=True is numerically identical to remat=False (checkpointing
    must not change the math, only the memory schedule);
  * masked positions contribute nothing (prompt-prefix masking);
  * the step composes over a dp×tp grid with the batch sharded over dp
    (XLA inserts the gradient all-reduce from shardings alone).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.models import DenseLLM, ModelConfig, make_train_step
from triton_dist_tpu.models.train import cross_entropy_loss

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow


def _tiny_cfg(world: int, dtype=jnp.float32, layers: int = 2):
    return ModelConfig(
        hidden_size=16 * world, intermediate_size=32 * world,
        num_hidden_layers=layers, num_attention_heads=world,
        num_key_value_heads=world, head_dim=16, vocab_size=64,
        max_position_embeddings=64, dtype=dtype)


def _batch(b, s, vocab, seed=0):
    ids = jax.random.randint(jax.random.PRNGKey(seed), (b, s), 0, vocab,
                             jnp.int32)
    return {"input_ids": ids}


def test_cross_entropy_masking():
    logits = jnp.zeros((1, 4, 8))
    labels = jnp.zeros((1, 4), jnp.int32)
    full = cross_entropy_loss(logits, labels)
    # Uniform logits: NLL = log V on every row, so any mask gives log V.
    half = cross_entropy_loss(logits, labels,
                              jnp.array([[1.0, 1.0, 0.0, 0.0]]))
    np.testing.assert_allclose(full, np.log(8.0), rtol=1e-6)
    np.testing.assert_allclose(half, np.log(8.0), rtol=1e-6)
    # A masked row with a huge wrong logit must not leak into the loss.
    bad = logits.at[0, 3, 1].set(100.0)
    np.testing.assert_allclose(
        cross_entropy_loss(bad, labels, jnp.array([[1.0, 1.0, 1.0, 0.0]])),
        np.log(8.0), rtol=1e-6)


def test_loss_decreases_tp(mesh8):
    model = DenseLLM(_tiny_cfg(8), mesh=mesh8, axis="tp", impl="xla",
                     fwd_mode="xla")
    params = model.init(jax.random.PRNGKey(0))
    step, init_opt = make_train_step(model)
    opt_state = init_opt(params)
    batch = _batch(2, 8, model.config.vocab_size)

    losses = []
    for _ in range(5):
        params, opt_state, m = step(params, opt_state, batch)
        losses.append(float(m["loss"]))
        assert np.isfinite(losses[-1])
        assert np.isfinite(float(m["grad_norm"])) and float(m["grad_norm"]) > 0
    # Overfitting one tiny batch: the last loss must beat the first.
    assert losses[-1] < losses[0], losses


def test_remat_matches_no_remat(mesh8):
    """Checkpointing changes the schedule, not the math."""
    model = DenseLLM(_tiny_cfg(8), mesh=mesh8, axis="tp", impl="xla",
                     fwd_mode="xla")
    params = model.init(jax.random.PRNGKey(1))
    batch = _batch(2, 8, model.config.vocab_size, seed=1)

    results = {}
    for remat in (False, True):
        step, init_opt = make_train_step(model, remat=remat, donate=False)
        p2, _, m = step(params, init_opt(params), batch)
        results[remat] = (m["loss"], jax.tree.map(np.asarray, p2))
    np.testing.assert_allclose(results[False][0], results[True][0],
                               rtol=1e-6)
    flat_a = jax.tree.leaves(results[False][1])
    flat_b = jax.tree.leaves(results[True][1])
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_loss_mask_freezes_masked_positions(mesh8):
    """With every position masked the gradients are exactly zero."""
    model = DenseLLM(_tiny_cfg(8), mesh=mesh8, axis="tp", impl="xla",
                     fwd_mode="xla")
    params = model.init(jax.random.PRNGKey(2))
    batch = _batch(2, 8, model.config.vocab_size, seed=2)
    batch["loss_mask"] = jnp.zeros((2, 8), jnp.float32)
    step, init_opt = make_train_step(model, donate=False)
    _, _, m = step(params, init_opt(params), batch)
    assert float(m["loss"]) == 0.0
    assert float(m["grad_norm"]) == 0.0


def test_dp_tp_grid(devices):
    """dp=2 × tp=4: batch sharded over dp, params sharded over tp.

    No dp-specific code exists in train.py — the gradient all-reduce
    over dp comes from XLA's sharding propagation (scaling-book recipe).
    """
    mesh = Mesh(np.array(devices).reshape(2, 4), ("dp", "tp"))
    model = DenseLLM(_tiny_cfg(4), mesh=mesh, axis="tp", impl="xla",
                     fwd_mode="xla")
    params = model.init(jax.random.PRNGKey(3))
    step, init_opt = make_train_step(model)
    opt_state = init_opt(params)
    batch = _batch(4, 8, model.config.vocab_size, seed=3)
    batch["input_ids"] = jax.device_put(
        batch["input_ids"], NamedSharding(mesh, P("dp")))

    losses = []
    for _ in range(3):
        params, opt_state, m = step(params, opt_state, batch)
        losses.append(float(m["loss"]))
        assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0], losses


def test_dp_equals_single_device_math(devices):
    """The dp=2 sharded step computes the same loss as unsharded."""
    mesh_dp = Mesh(np.array(devices).reshape(2, 4), ("dp", "tp"))
    mesh_tp = Mesh(np.array(devices[:4]), ("tp",))
    batch = _batch(4, 8, 64, seed=4)

    losses = {}
    for name, mesh in (("dp", mesh_dp), ("flat", mesh_tp)):
        model = DenseLLM(_tiny_cfg(4), mesh=mesh, axis="tp", impl="xla",
                         fwd_mode="xla")
        params = model.init(jax.random.PRNGKey(5))
        step, init_opt = make_train_step(model, donate=False)
        b = dict(batch)
        if name == "dp":
            b["input_ids"] = jax.device_put(
                b["input_ids"], NamedSharding(mesh, P("dp")))
        _, _, m = step(params, init_opt(params), b)
        losses[name] = float(m["loss"])
    np.testing.assert_allclose(losses["dp"], losses["flat"], rtol=1e-5)


def test_ep_moe_trains(mesh8):
    """mode="ep": Qwen3-MoE with expert parallelism trains through the
    Pallas a2a dispatch/combine (the a2a VJP is the reverse exchange)
    and computes the same losses as the TP-sharded xla path."""
    from triton_dist_tpu.models import Qwen3MoE

    cfg = ModelConfig(
        hidden_size=32, moe_intermediate_size=32, num_hidden_layers=1,
        num_attention_heads=8, num_key_value_heads=8, head_dim=16,
        vocab_size=64, max_position_embeddings=32, dtype=jnp.float32,
        num_experts=8, num_experts_per_tok=2, intermediate_size=0)
    batch = _batch(2, 8, 64, seed=8)
    losses = {}
    for name, kw, mode in (
            ("tp", {"moe_parallel": "tp"}, "xla"),
            ("ep", {"moe_parallel": "ep", "impl": "pallas"}, "ep")):
        model = Qwen3MoE(cfg, mesh=mesh8, axis="tp", **kw)
        params = model.init(jax.random.PRNGKey(0))
        step, init_opt = make_train_step(model, mode=mode)
        opt_state = init_opt(params)
        seq = []
        for _ in range(3):
            params, opt_state, m = step(params, opt_state, batch)
            seq.append(float(m["loss"]))
            assert np.isfinite(seq[-1])
            assert np.isfinite(float(m["grad_norm"]))
        assert seq[-1] < seq[0], (name, seq)
        losses[name] = seq
    # Same math, different parallelism: EP must track TP step for step.
    np.testing.assert_allclose(losses["ep"], losses["tp"], rtol=2e-4)


def test_checkpoint_resume_training(mesh8, tmp_path):
    """Save mid-training, restore into a fresh process-state, continue:
    the resumed run must reproduce the uninterrupted run's losses
    exactly (params AND optimizer moments round-trip via orbax)."""
    from triton_dist_tpu.models.checkpoint import load_params, save_params

    model = DenseLLM(_tiny_cfg(8), mesh=mesh8, axis="tp", impl="xla",
                     fwd_mode="xla")
    params = model.init(jax.random.PRNGKey(4))
    step, init_opt = make_train_step(model, donate=False)
    opt_state = init_opt(params)
    batch = _batch(2, 8, model.config.vocab_size, seed=5)

    for _ in range(2):
        params, opt_state, _ = step(params, opt_state, batch)
    save_params(str(tmp_path / "ckpt"), {"params": params,
                                         "opt_state": opt_state})

    uninterrupted = []
    p, o = params, opt_state
    for _ in range(2):
        p, o, m = step(p, o, batch)
        uninterrupted.append(float(m["loss"]))

    restored = load_params(str(tmp_path / "ckpt"),
                           like={"params": params, "opt_state": opt_state})
    resumed = []
    p, o = restored["params"], restored["opt_state"]
    for _ in range(2):
        p, o, m = step(p, o, batch)
        resumed.append(float(m["loss"]))
    assert resumed == uninterrupted, (resumed, uninterrupted)


def test_unknown_mode_rejected(mesh8):
    model = DenseLLM(_tiny_cfg(8), mesh=mesh8, axis="tp", impl="xla",
                     fwd_mode="xla")
    with pytest.raises(ValueError, match="differentiable"):
        make_train_step(model, mode="bogus")


def test_fused_mode_trains(mesh8):
    """mode="ag_rs": the training step runs through the fused Pallas
    kernels in BOTH directions (custom VJPs, ops/autodiff.py) and its
    math matches the xla-mode step."""
    batch = _batch(2, 8, 64, seed=6)
    losses = {}
    for mode, impl in (("xla", "xla"), ("ag_rs", "pallas"),
                       ("gemm_ar", "pallas")):
        model = DenseLLM(_tiny_cfg(8), mesh=mesh8, axis="tp", impl=impl,
                         fwd_mode=mode)
        params = model.init(jax.random.PRNGKey(7))
        step, init_opt = make_train_step(model, mode=mode)
        opt_state = init_opt(params)
        seq = []
        for _ in range(3):
            params, opt_state, m = step(params, opt_state, batch)
            seq.append(float(m["loss"]))
            assert np.isfinite(seq[-1])
        losses[mode] = seq
        assert seq[-1] < seq[0], (mode, seq)
    np.testing.assert_allclose(losses["ag_rs"], losses["xla"],
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(losses["gemm_ar"], losses["xla"],
                               rtol=2e-4, atol=2e-5)
