"""Event tracing + flight recorder (docs/observability.md "Tracing").

Quick tier, CPU-only: ring-buffer overwrite semantics, trace-ID
propagation through the server → engine → ops path, the Chrome
trace-event exporter/validator/merger, overlap reconstruction from
ring-schedule chunk events, and the fault-injected watchdog-trip
auto-dump (the ISSUE 4 acceptance scenarios).
"""

import json
import socket

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu import obs
from triton_dist_tpu.obs import flight, trace
from triton_dist_tpu.tools import trace_export


@pytest.fixture(autouse=True)
def _fresh_registry():
    obs.disable()
    yield
    obs.disable()


# ---------------------------------------------------------------------------
# Ring buffer semantics.
# ---------------------------------------------------------------------------

def test_disabled_tracing_is_noop():
    assert not trace.enabled()
    trace.instant("x")
    trace.begin("y")
    trace.end("y")
    with trace.span("z"):
        pass
    c = trace.collect()
    assert c["tracks"] == {} and c["events_total"] == 0


def test_ring_overwrites_oldest_and_counts_drops():
    trace.enable(capacity=4)
    for i in range(10):
        trace.instant(f"e{i}")
    c = trace.collect()
    assert c["events_total"] == 10
    assert c["dropped_total"] == 6          # oldest 6 overwritten
    (events,) = c["tracks"].values()
    names = [e[3] for e in events]
    assert names == ["e6", "e7", "e8", "e9"]   # newest window, in order
    assert trace.stats()["dropped_total"] == 6


def test_collect_last_s_window_trims_old_events():
    t = trace.enable()
    t.emit("i", "old", ts_us=t.now_us() - 100e6)    # 100 s ago
    trace.instant("new")
    all_names = [e[3] for evs in trace.collect()["tracks"].values()
                 for e in evs]
    assert set(all_names) == {"old", "new"}
    recent = [e[3] for evs in trace.collect(last_s=30)["tracks"].values()
              for e in evs]
    assert recent == ["new"]


def test_dead_thread_rings_are_bounded():
    """A server handling each connection on a fresh thread must not
    leak one ring per connection: finished threads' rings are pruned
    beyond a bounded tail (newest kept — they are flight-record
    history)."""
    import threading
    t = trace.enable()
    n = t.MAX_DEAD_RINGS + 20
    for i in range(n):
        th = threading.Thread(target=trace.instant, args=(f"c{i}",),
                              name=f"conn-{i}")
        th.start()
        th.join()
    trace.instant("live")
    with t._lock:
        rings = list(t._rings.values())
    assert len(rings) <= t.MAX_DEAD_RINGS + 2   # dead tail + this thread
    names = {e[3] for evs in trace.collect()["tracks"].values()
             for e in evs}
    assert "live" in names and f"c{n - 1}" in names   # newest kept
    assert f"c{0}" not in names                       # oldest pruned


def test_trace_id_binds_to_thread():
    trace.enable()
    assert trace.current_trace_id() is None
    with trace.bind("req-1"):
        assert trace.current_trace_id() == "req-1"
        trace.instant("inner")
        with trace.bind("req-2"):
            assert trace.current_trace_id() == "req-2"
        assert trace.current_trace_id() == "req-1"
    assert trace.current_trace_id() is None
    (events,) = trace.collect()["tracks"].values()
    assert events[0][5] == "req-1"          # trace_id slot


def test_span_emits_events_with_tracing_only():
    """The span contract extends PR 1's: with ONLY tracing enabled
    (metrics registry still the no-op default) spans emit B/E events
    and the metrics side stays empty."""
    trace.enable()
    assert not obs.enabled()
    with obs.span("engine.step"):
        pass
    assert obs.snapshot()["histograms"] == {}
    (events,) = trace.collect()["tracks"].values()
    phs = [(e[0], e[3], e[4]) for e in events]
    assert ("B", "engine.step", "engine") in phs
    assert ("E", "engine.step", "engine") in phs


def test_span_annotate_unavailable_warns_once_and_counts(monkeypatch):
    from triton_dist_tpu.obs import registry as registry_mod
    from triton_dist_tpu.tools import profiler

    def boom(label):
        raise ImportError("no xprof here")

    monkeypatch.setattr(profiler, "annotate", boom)
    monkeypatch.setattr(registry_mod, "_ANNOTATE_WARNED", False)
    obs.enable(obs.Registry())
    with pytest.warns(RuntimeWarning, match="annotate unavailable"):
        with obs.span("engine.step"):
            pass
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")            # second failure: no warning
        with obs.span("engine.step"):
            pass
    snap = obs.snapshot()
    assert snap["counters"]["obs.span.annotate_unavailable"] == 2
    # the span still recorded its histogram both times
    assert snap["histograms"]["engine.step_ms"]["count"] == 2


# ---------------------------------------------------------------------------
# Exporter + validator.
# ---------------------------------------------------------------------------

def _chrome_of_current():
    return trace_export.to_chrome(trace.collect(), pid=0)


def test_export_validate_roundtrip(tmp_path):
    trace.enable()
    with trace.bind("rt-1"):
        with trace.span("serving.request", "serving", args={"n": 1}):
            trace.instant("comms.ag_gemm", "op", args={"bytes": 64})
    chrome = _chrome_of_current()
    errors, warnings = trace_export.validate(chrome)
    assert errors == [] and warnings == []
    # args carry the trace id through export
    by_name = {e["name"]: e for e in chrome["traceEvents"]
               if e["ph"] != "M"}
    assert by_name["comms.ag_gemm"]["args"]["trace_id"] == "rt-1"
    # the CLI validates the written file (the CI wire)
    p = tmp_path / "dump.trace.json"
    trace_export.write_trace(chrome, str(p))
    assert trace_export.main(["--validate", str(p)]) == 0


def test_validate_catches_malformed_traces():
    bad = {"traceEvents": [
        {"ph": "E", "ts": 1.0, "pid": 0, "tid": 1, "name": "a"},
        {"ph": "B", "ts": 5.0, "pid": 0, "tid": 1, "name": "b"},
        {"ph": "i", "ts": 2.0, "pid": 0, "tid": 1, "name": "c"},
        {"ph": "X", "ts": 1.0, "dur": -4.0, "pid": 0, "tid": 2,
         "name": "d"},
        {"ph": "B", "ts": "NaN?", "pid": 0, "tid": 3, "name": "e"},
    ]}
    errors, warnings = trace_export.validate(bad)
    # an E whose B fell outside the recorded window is truncation,
    # not corruption: warning, like trailing unclosed begins
    assert any("no open B" in w for w in warnings)
    assert any("backwards" in e for e in errors)
    assert any("bad dur" in e for e in errors)
    assert any("non-numeric ts" in e for e in errors)
    assert any("unclosed B" in w for w in warnings)
    assert trace_export.validate({"nope": 1})[0]
    # mismatched B/E names on one track
    errors, _ = trace_export.validate({"traceEvents": [
        {"ph": "B", "ts": 1.0, "pid": 0, "tid": 1, "name": "a"},
        {"ph": "E", "ts": 2.0, "pid": 0, "tid": 1, "name": "z"},
    ]})
    assert any("closes B" in e for e in errors)


def test_unclosed_begin_is_warning_not_error():
    """A flight record of a hang legitimately ends mid-span: the
    unclosed B IS the postmortem's answer, so --validate must not
    reject it."""
    trace.enable()
    trace.begin("smoke.hung_case", "op")
    errors, warnings = trace_export.validate(_chrome_of_current())
    assert errors == []
    assert any("hung_case" in w for w in warnings)


def test_merge_chrome_keeps_hosts_distinct():
    a = {"traceEvents": [{"ph": "i", "ts": 1.0, "pid": 0, "tid": 1,
                          "name": "h0", "s": "t"}]}
    b = {"traceEvents": [{"ph": "i", "ts": 2.0, "pid": 0, "tid": 1,
                          "name": "h1", "s": "t"}]}
    merged = trace_export.merge_chrome([a, b])
    pids = {e["pid"] for e in merged["traceEvents"]}
    assert len(pids) == 2                       # collision re-based
    assert trace_export.validate(merged)[0] == []


# ---------------------------------------------------------------------------
# Overlap reconstruction from ring-schedule chunk events.
# ---------------------------------------------------------------------------

def test_ring_schedule_events_and_overlap_reconstruction():
    trace.enable()
    trace.ring_schedule_events("ag_gemm", world=4, dirs=2,
                               compute_ms=4.0, comm_ms=2.0)
    c = trace.collect()
    assert set(c["tracks"]) == {"comms.ag_gemm.compute",
                                "comms.ag_gemm.comm"}
    assert len(c["tracks"]["comms.ag_gemm.compute"]) == 4  # one/chunk
    assert len(c["tracks"]["comms.ag_gemm.comm"]) == 3     # w-1 hops
    chunks = {e[6]["chunk"] for e in c["tracks"]["comms.ag_gemm.compute"]}
    assert chunks == {0, 1, 2, 3}               # rank-rotated, complete
    ov = trace_export.compute_overlap(_chrome_of_current())
    assert set(ov) == {"ag_gemm"}
    r = ov["ag_gemm"]
    assert r["n_chunks"] == 4
    assert r["comm_ms"] == pytest.approx(2.0, rel=0.01)
    # per-chunk compute (1 ms) exceeds per-hop comm (0.67 ms): the
    # schedule hides everything, and the geometry shows it.
    assert r["overlap_pct"] == pytest.approx(100.0, abs=0.5)
    assert r["exposed_comm_ms"] == pytest.approx(0.0, abs=1e-6)


def test_overlap_exposed_when_comm_dominates():
    trace.enable()
    # comm 8 ms over 3 hops (2.67 ms each) vs 0.5 ms per chunk: most
    # of each hop sticks out past the tile loop it overlaps.
    trace.ring_schedule_events("gemm_rs", world=4, dirs=1,
                               compute_ms=2.0, comm_ms=8.0)
    r = trace_export.compute_overlap(_chrome_of_current())["gemm_rs"]
    # hops union to [0, 3.67 ms] of which compute covers [0, 2 ms]:
    # 1.67 ms exposed, ~55% hidden — the geometry, not the gauge.
    assert r["exposed_comm_ms"] == pytest.approx(1.667, rel=0.05)
    assert 40 < r["overlap_pct"] < 70


def test_overlap_is_computed_per_host_in_merged_traces():
    """SPMD hosts run near-simultaneously on wall-anchored clocks: in
    a merged trace, host B's compute slices must not mask host A's
    exposed comm — the interval arithmetic runs per (pid, op) and the
    per-op numbers sum the hosts."""
    trace.enable()
    trace.ring_schedule_events("gemm_rs", world=4, dirs=1,
                               compute_ms=2.0, comm_ms=8.0)
    host0 = _chrome_of_current()
    solo = trace_export.compute_overlap(host0)["gemm_rs"]
    # "host 1": same schedule, same wall-clock — covers nothing of
    # host 0's comm if keyed per host, everything if pooled.
    merged = trace_export.merge_chrome([host0, host0])
    both = trace_export.compute_overlap(merged)["gemm_rs"]
    assert both["n_hosts"] == 2
    assert both["exposed_comm_ms"] == pytest.approx(
        2 * solo["exposed_comm_ms"], rel=1e-6)
    assert both["overlap_pct"] == pytest.approx(solo["overlap_pct"],
                                               rel=1e-6)


def test_record_overlap_emits_schedule_with_tracing(mesh8):
    from triton_dist_tpu.ops.common import record_overlap
    from triton_dist_tpu.tools.perf_model import estimate_ag_gemm_cost
    trace.enable()
    cost = estimate_ag_gemm_cost({"variant": "vmem"}, m=64, rows=8,
                                 k=128, n_loc=32, itemsize=2, world=8,
                                 ring_dirs=2)
    record_overlap("ag_gemm", cost, world=8, dirs=2)
    c = trace.collect()
    assert "comms.ag_gemm.compute" in c["tracks"]
    assert len(c["tracks"]["comms.ag_gemm.comm"]) == 7


# ---------------------------------------------------------------------------
# Flight recorder.
# ---------------------------------------------------------------------------

def test_flight_dump_writes_valid_trace(tmp_path, monkeypatch):
    monkeypatch.setenv("TDT_TRACE_DIR", str(tmp_path / "fr"))
    assert flight.dump("nothing") is None       # tracing off → no dump
    trace.enable()
    trace.instant("resilience.x.failure", "resilience")
    path = flight.dump("unit_test")
    assert path and path.endswith(".trace.json")
    with open(path) as f:
        chrome = json.load(f)
    assert trace_export.validate(chrome)[0] == []
    assert chrome["metadata"]["reason"] == "unit_test"
    rec = flight.last_record()
    assert rec["path"] == path and rec["count"] == 1
    # the dump surfaced in metrics and in trace.stats()
    obs.enable(obs.Registry())
    flight.dump("unit_test")
    assert obs.snapshot()["counters"]["resilience.flight_dumps"] == 1
    assert trace.stats()["last_flight_record"] != path   # newer dump


def test_maybe_dump_rate_limits_per_reason():
    trace.enable()
    p1 = flight.maybe_dump("breaker_x")
    p2 = flight.maybe_dump("breaker_x")         # within MIN_INTERVAL_S
    p3 = flight.maybe_dump("watchdog_y")        # different reason
    assert p1 and p3 and p2 is None


def test_watchdog_trip_auto_dumps_flight_record(devices, monkeypatch):
    """ISSUE 4 acceptance: a fault-injected watchdog trip auto-dumps a
    flight record whose path appears in the report output next to the
    ``resilience.*`` counters."""
    from triton_dist_tpu.ops.p2p import create_p2p_context, pp_shift
    from triton_dist_tpu.testing import faults
    from triton_dist_tpu.tools.report import render_telemetry
    obs.enable(obs.Registry())
    trace.enable()
    mesh1 = Mesh(np.array(devices[:1]), ("tp",))
    xp = jnp.ones((1, 8, 128), jnp.float32)
    ctx = create_p2p_context(mesh1, "tp")
    with faults.inject("compile_timeout", op="pp_shift"):
        out = pp_shift(xp, ctx, impl="pallas")  # trips → falls back
    np.testing.assert_array_equal(np.asarray(out), np.asarray(xp))
    c = obs.snapshot()["counters"]
    assert c["resilience.pp_shift.watchdog_trips"] == 1
    assert c["resilience.flight_dumps"] == 1
    rec = flight.last_record()
    assert rec and "watchdog_pp_shift" in rec["path"]
    with open(rec["path"]) as f:
        chrome = json.load(f)
    assert trace_export.validate(chrome)[0] == []
    # the trip itself is on the recorded timeline (the fallback
    # instant fires AFTER the dump — by design, the record is the
    # window up to and including the failure — so it shows up in the
    # live tracer, not in this dump)
    names = {e.get("name") for e in chrome["traceEvents"]}
    assert "resilience.pp_shift.failure" in names
    live = {e[3] for evs in trace.collect()["tracks"].values()
            for e in evs}
    assert "resilience.pp_shift.fallback" in live
    # ... and the path renders in the report's Tracing section
    snap = obs.snapshot()
    snap["trace"] = trace.stats()
    text = render_telemetry(snap)
    assert "#### tracing" in text and rec["path"] in text
    assert "resilience.flight_dumps" in text


# ---------------------------------------------------------------------------
# End-to-end: server request → engine → ops under one trace ID.
# ---------------------------------------------------------------------------

def _tiny_engine(mesh8, key):
    from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=32, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = model.init(key)
    eng = Engine(model, batch=1, max_seq=16, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    return eng, params


def _send(host, port, payload: dict) -> dict:
    with socket.create_connection((host, port)) as s:
        f = s.makefile("rwb")
        f.write((json.dumps(payload) + "\n").encode())
        f.flush()
        return json.loads(f.readline())


def test_server_request_traced_end_to_end(mesh8, key):
    """ISSUE 4 acceptance, updated for the ISSUE 5 scheduler: serve one
    request with tracing on, dump via {"cmd": "dump_trace"}, and the
    exported Perfetto JSON validates and holds the request's serving
    span, its admit/retire instants, and its admission-side engine/op
    events under ONE trace ID. (The shared decode step serves many
    requests at once, so per-token spans are deliberately unbound —
    the per-request story is span + admit/retire + admission events;
    docs/observability.md "Trace-ID propagation".)"""
    from triton_dist_tpu.serving import ModelServer
    eng, params = _tiny_engine(mesh8, key)
    srv = ModelServer(eng, params, port=0).start()   # tracing default-on
    try:
        assert trace.enabled()
        gen = _send(srv.host, srv.port,
                    {"prompt_ids": [[1, 2, 3]], "gen_len": 3})
        assert "tokens" in gen
        tid = gen["trace_id"]
        assert tid
        # a client-chosen trace id is honored and echoed
        gen2 = _send(srv.host, srv.port,
                     {"prompt_ids": [[1, 2]], "gen_len": 2,
                      "trace_id": "client-chosen"})
        assert gen2["trace_id"] == "client-chosen"
        # window widened past the first-compile time so the request's
        # back-dated serve/prefill events stay inside it (also
        # exercises the protocol's "seconds" knob)
        resp = _send(srv.host, srv.port,
                     {"cmd": "dump_trace", "seconds": 600})
        path = resp["dumped"]
        assert path and resp["trace"]["events_total"] > 0
        with open(path) as f:
            chrome = json.load(f)
        errors, _ = trace_export.validate(chrome)
        assert errors == []
        cats = {e.get("cat") for e in chrome["traceEvents"]
                if e.get("args", {}).get("trace_id") == tid}
        # serving span + admit/retire, the admission's
        # engine.stream_admission, and (first compile ran under this
        # request's binding) the admission program's op instants
        assert {"serving", "engine", "op"} <= cats, cats
        names = {e["name"] for e in chrome["traceEvents"]
                 if e.get("args", {}).get("trace_id") == tid}
        assert "serving.request" in names
        assert "serving.admit" in names and "serving.retire" in names
        assert "engine.stream_admission" in names
        assert any(n.startswith("comms.") for n in names), names
        # the second request's admission events carry ITS id too
        names2 = {e["name"] for e in chrome["traceEvents"]
                  if e.get("args", {}).get("trace_id") == "client-chosen"}
        assert {"serving.admit", "serving.retire",
                "engine.stream_admission"} <= names2, names2
        # the shared decode loop shows up as stream-step spans
        b_names = {e["name"] for e in chrome["traceEvents"]
                   if e["ph"] == "B"}
        assert "engine.stream_step" in b_names
        # the metrics command surfaces tracing stats for report.py
        m = _send(srv.host, srv.port, {"cmd": "metrics"})
        assert m["metrics"]["trace"]["events_total"] > 0
    finally:
        srv.stop()


def test_server_tracing_opt_out(mesh8, key, monkeypatch):
    monkeypatch.setenv("TDT_TRACE", "0")
    from triton_dist_tpu.serving import ModelServer
    eng, params = _tiny_engine(mesh8, key)
    srv = ModelServer(eng, params, port=0).start()
    try:
        assert not trace.enabled()
        gen = _send(srv.host, srv.port,
                    {"prompt_ids": [[1, 2, 3]], "gen_len": 2})
        assert "tokens" in gen and "trace_id" not in gen
        resp = _send(srv.host, srv.port, {"cmd": "dump_trace"})
        assert "error" in resp
    finally:
        srv.stop()


def test_obs_enable_honors_tdt_trace_env(monkeypatch):
    monkeypatch.setenv("TDT_TRACE", "1")
    assert not trace.enabled()
    obs.enable()
    assert trace.enabled()
