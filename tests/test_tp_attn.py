"""TP_Attn layer vs single-device golden (reference test/nvidia/test_tp_attn.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.layers import TPAttn, precompute_rope_cache
from triton_dist_tpu.layers.tp_attn import _attention_core

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow

H = 64
NQ, NKV, D = 16, 8, 8
B, S, T = 2, 4, 8


def np_rms(x, w, eps=1e-6):
    var = np.mean(x.astype(np.float64) ** 2, -1, keepdims=True)
    return (x / np.sqrt(var + eps)) * w


def np_rope(x, cos, sin, pos):
    c = cos[pos][:, :, None, :]
    s = sin[pos][:, :, None, :]
    x1, x2 = np.split(x, 2, -1)
    return np.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], -1)


def golden(params, x, pos, rope, offset):
    """Full-array (no TP) cached GQA attention in numpy."""
    wq = np.asarray(params["w_q"], np.float64)
    wk = np.asarray(params["w_k"], np.float64)
    wv = np.asarray(params["w_v"], np.float64)
    wo = np.asarray(params["w_o"], np.float64)
    xf = np.asarray(x, np.float64)
    b, s = pos.shape
    q = (xf @ wq).reshape(b, s, NQ, D)
    k = (xf @ wk).reshape(b, s, NKV, D)
    v = (xf @ wv).reshape(b, s, NKV, D)
    q = np_rms(q, np.asarray(params["q_norm"], np.float64))
    k = np_rms(k, np.asarray(params["k_norm"], np.float64))
    cos, sin = (np.asarray(r, np.float64) for r in rope)
    q, k = np_rope(q, cos, sin, pos), np_rope(k, cos, sin, pos)
    # causal over the fresh segment only (offset=0 prefill)
    assert offset == 0
    scores = np.einsum("bsKgd,btKd->bKgst",
                       q.reshape(b, s, NKV, NQ // NKV, D), k) * D ** -0.5
    mask = np.tril(np.ones((s, s), bool))
    scores = np.where(mask[None, None, None], scores, -np.inf)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    out = np.einsum("bKgst,btKd->bsKgd", p, v).reshape(b, s, NQ * D)
    return out.reshape(b * s, -1) @ wo


@pytest.fixture()
def attn(mesh8):
    return TPAttn(H, NQ, NKV, D, mesh=mesh8, dtype=jnp.float32)


@pytest.fixture()
def setup(attn, key):
    params = attn.init(key)
    x = jax.random.normal(jax.random.PRNGKey(3), (B * S, H), jnp.float32)
    pos = jnp.tile(jnp.arange(S), (B, 1))
    rope = precompute_rope_cache(D, T)
    cache = (jnp.zeros((B, T, NKV, D), jnp.float32),
             jnp.zeros((B, T, NKV, D), jnp.float32))
    ref = golden(params, x, np.asarray(pos), rope, 0)
    return params, x, pos, rope, cache, ref


@pytest.mark.parametrize("mode", ["xla", "ag_rs", "xla_ar", "gemm_ar"])
def test_tp_attn_prefill(attn, setup, mode):
    params, x, pos, rope, cache, ref = setup
    out, (ck, cv) = attn(params, x, pos, rope, cache, 0, mode=mode)
    np.testing.assert_allclose(np.asarray(out, np.float64), ref,
                               rtol=2e-4, atol=2e-4)
    # cache got written at [0, S)
    assert not np.allclose(np.asarray(ck)[:, :S], 0)
    assert np.allclose(np.asarray(ck)[:, S:], 0)


def test_tp_attn_decode_matches_prefill(attn, setup):
    """Decode step at offset=S must equal prefilling S+1 tokens."""
    params, x, pos, rope, cache, _ = setup
    xs1 = jax.random.normal(jax.random.PRNGKey(9), (B, H), jnp.float32)

    # path A: prefill S then decode 1 (gemm_ar replicated decode layout)
    _, cache1 = attn(params, x, pos, rope, cache, 0, mode="xla")
    pos_d = jnp.full((B, 1), S)
    out_d, _ = attn(params, xs1, pos_d, rope, cache1, S, mode="gemm_ar")

    # path B: prefill S+1 at once
    x_all = jnp.concatenate([x.reshape(B, S, H),
                             xs1.reshape(B, 1, H)], axis=1).reshape(-1, H)
    pos_all = jnp.tile(jnp.arange(S + 1), (B, 1))
    cache0 = (jnp.zeros((B, T, NKV, D), jnp.float32),
              jnp.zeros((B, T, NKV, D), jnp.float32))
    # M = B*(S+1) = 10 doesn't divide the tp=8 axis -> replicated layout
    out_all, _ = attn(params, x_all, pos_all, rope, cache0, 0, mode="xla_ar")
    last = np.asarray(out_all).reshape(B, S + 1, H)[:, -1]
    np.testing.assert_allclose(np.asarray(out_d), last, rtol=2e-4, atol=2e-4)


def test_attention_core_gqa_grouping():
    """GQA must use the co-located KV head for each query group."""
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 2, 4, D), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 2, D), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 2, D), jnp.float32)
    ck = jnp.zeros((1, 4, 2, D), jnp.float32)
    z = jnp.zeros((1,), jnp.int32)
    out, _, _ = _attention_core(q, k, v, ck, ck, jnp.int32(0), z, groups=2)
    # head 0,1 share kv head 0; heads 2,3 share kv head 1.
    out2, _, _ = _attention_core(
        q[:, :, [2, 3, 0, 1]], k[:, :, [1, 0]], v[:, :, [1, 0]],
        ck, ck, jnp.int32(0), z, groups=2)
    np.testing.assert_allclose(np.asarray(out)[:, :, [2, 3, 0, 1]],
                               np.asarray(out2), rtol=1e-5, atol=1e-5)
