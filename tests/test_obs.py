"""Telemetry subsystem tests (docs/observability.md): registry
semantics, the zero-overhead no-op default, cross-host snapshot merge,
engine/server instrumentation end-to-end, and the Prometheus text
exposition golden."""

import json
import socket

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu import obs


@pytest.fixture(autouse=True)
def _fresh_registry():
    """Telemetry state is process-global; every test starts and ends
    disabled so no test leaks counts into another."""
    obs.disable()
    yield
    obs.disable()


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------

def test_counter_gauge_semantics():
    reg = obs.Registry()
    c = reg.counter("x.calls")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError, match="< 0"):
        c.inc(-1)
    g = reg.gauge("x.inflight")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value == 3.0
    assert reg.counter("x.calls") is c          # registered once
    with pytest.raises(ValueError, match="different"):
        reg.gauge("x.calls")                    # type conflict refused


def test_histogram_buckets_and_snapshot():
    reg = obs.Registry()
    h = reg.histogram("lat", buckets=(1.0, 10.0, 100.0))
    # upper bounds are inclusive: 10.0 lands in the le=10 bucket;
    # 500.0 in the implicit +Inf tail.
    for v in (0.5, 5.0, 50.0, 500.0, 10.0):
        h.observe(v)
    snap = reg.snapshot()["histograms"]["lat"]
    assert snap["counts"] == [1, 2, 1, 1]
    assert snap["count"] == 5 and snap["sum"] == 565.5
    assert snap["min"] == 0.5 and snap["max"] == 500.0
    with pytest.raises(ValueError, match="ascending"):
        reg.histogram("bad", buckets=(5.0, 1.0))
    json.dumps(reg.snapshot())                  # plain-dict contract


def test_default_registry_is_noop():
    """The disabled default: recording is swallowed, snapshots are
    empty, and span is a shared null context manager (no clock read on
    the decode hot path)."""
    assert not obs.enabled()
    obs.counter("n").inc()
    obs.histogram("h").observe(1.0)
    obs.gauge("g").set(2)
    assert obs.snapshot() == {"counters": {}, "gauges": {},
                              "histograms": {}}
    s1, s2 = obs.span("a"), obs.span("b")
    assert s1 is s2
    with s1:
        pass
    assert obs.snapshot()["histograms"] == {}


def test_enable_span_records():
    obs.enable()
    with obs.span("step"):
        pass
    h = obs.snapshot()["histograms"]["step_ms"]
    assert h["count"] == 1 and h["sum"] >= 0.0
    # enable() is idempotent: re-enabling keeps the counts
    obs.enable()
    assert obs.snapshot()["histograms"]["step_ms"]["count"] == 1


# ---------------------------------------------------------------------------
# Cross-host merge (the reference's rank-0 gather_object merge)
# ---------------------------------------------------------------------------

def test_merge_snapshots_across_fake_hosts():
    r0, r1 = obs.Registry(), obs.Registry()
    for i, r in enumerate((r0, r1)):
        r.counter("c").inc(1 + i)
        r.gauge("g").set(10 * (i + 1))
        r.histogram("h", buckets=(1.0, 2.0)).observe(0.5 + i)
    m = obs.merge_snapshots([r0.snapshot(), r1.snapshot()])
    assert m["counters"]["c"] == 3.0            # counters add
    assert m["gauges"]["g"] == 20.0             # gauges take max
    assert m["histograms"]["h"]["counts"] == [1, 1, 0]
    assert m["histograms"]["h"]["count"] == 2
    assert m["histograms"]["h"]["min"] == 0.5
    assert m["histograms"]["h"]["max"] == 1.5
    # mismatched bucket layouts refuse to merge silently
    r2 = obs.Registry()
    r2.histogram("h", buckets=(5.0,)).observe(1.0)
    with pytest.raises(ValueError, match="bucket"):
        obs.merge_snapshots([r0.snapshot(), r2.snapshot()])
    # single-process aggregate == local merge (the CPU tier-1 path)
    obs.enable(r0)
    assert obs.aggregate_across_hosts() == obs.merge_snapshots(
        [r0.snapshot()])


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------

def test_render_prometheus_golden():
    reg = obs.Registry()
    reg.counter("engine.serve_calls").inc(2)
    reg.gauge("server.inflight").set(1)
    h = reg.histogram("engine.decode_step_ms", buckets=(1.0, 5.0))
    for v in (0.5, 2.0, 9.0):
        h.observe(v)
    got = obs.render_prometheus(reg.snapshot())
    assert got == (
        "# TYPE tdt_engine_serve_calls_total counter\n"
        "tdt_engine_serve_calls_total 2\n"
        "# TYPE tdt_server_inflight gauge\n"
        "tdt_server_inflight 1\n"
        "# TYPE tdt_engine_decode_step_ms histogram\n"
        'tdt_engine_decode_step_ms_bucket{le="1"} 1\n'
        'tdt_engine_decode_step_ms_bucket{le="5"} 2\n'
        'tdt_engine_decode_step_ms_bucket{le="+Inf"} 3\n'
        "tdt_engine_decode_step_ms_sum 11.5\n"
        "tdt_engine_decode_step_ms_count 3\n")


def test_render_telemetry_table():
    from triton_dist_tpu.tools.report import render_telemetry
    reg = obs.Registry()
    reg.counter("comms.allgather.bytes").inc(4096)
    reg.histogram("engine.decode_step_ms", buckets=(1.0,)).observe(0.5)
    text = render_telemetry(reg.snapshot())
    assert "comms.allgather.bytes" in text and "4096" in text
    assert "engine.decode_step_ms" in text


# ---------------------------------------------------------------------------
# Engine + collective instrumentation
# ---------------------------------------------------------------------------

def _tiny_engine(mesh8, key, **kw):
    from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=32, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = model.init(key)
    eng = Engine(model, batch=1, max_seq=16, prefill_mode="xla_ar",
                 decode_mode="gemm_ar", **kw)
    return eng, params


def test_engine_decode_histogram_populated(mesh8, key):
    obs.enable()
    eng, params = _tiny_engine(mesh8, key)
    out = eng.serve(params, jnp.asarray([[1, 2, 3]], jnp.int32), 4,
                    stop_tokens=())
    assert out.shape == (1, 7)
    snap = obs.snapshot()
    assert snap["counters"]["engine.serve_calls"] == 1
    assert snap["counters"]["engine.decode_path.plain"] == 1
    assert snap["counters"]["engine.tokens_generated"] == 4
    assert snap["histograms"]["engine.decode_step_ms"]["count"] == 3
    assert snap["histograms"]["engine.prefill_ms"]["count"] == 1
    assert snap["histograms"]["engine.ttft_ms"]["count"] == 1
    assert snap["gauges"]["engine.tokens_per_s"] > 0
    # the gemm_ar decode route counted its collective payloads
    assert snap["counters"]["comms.gemm_ar.calls"] >= 1
    assert snap["counters"]["comms.gemm_ar.bytes"] > 0


def test_engine_disabled_records_nothing(mesh8, key):
    """Zero-overhead contract: with the default no-op registry a serve
    leaves the telemetry state bit-identical to empty (and tokens match
    an instrumented run — the instrumentation is observation-only)."""
    eng, params = _tiny_engine(mesh8, key)
    ids = jnp.asarray([[1, 2, 3]], jnp.int32)
    out_off = eng.serve(params, ids, 4, stop_tokens=())
    assert obs.snapshot() == {"counters": {}, "gauges": {},
                              "histograms": {}}
    obs.enable()
    eng2, params2 = _tiny_engine(mesh8, key)
    out_on = eng2.serve(params2, ids, 4, stop_tokens=())
    np.testing.assert_array_equal(np.asarray(out_off),
                                  np.asarray(out_on))


# ---------------------------------------------------------------------------
# Server metrics exposition round trip
# ---------------------------------------------------------------------------

def _send(host, port, payload: dict) -> dict:
    with socket.create_connection((host, port)) as s:
        f = s.makefile("rwb")
        f.write((json.dumps(payload) + "\n").encode())
        f.flush()
        return json.loads(f.readline())


def test_server_metrics_roundtrip(mesh8, key):
    from triton_dist_tpu.serving import ModelServer
    eng, params = _tiny_engine(mesh8, key)
    srv = ModelServer(eng, params, port=0).start()
    try:
        assert obs.enabled()        # construction enabled telemetry
        gen = _send(srv.host, srv.port,
                    {"prompt_ids": [[1, 2, 3]], "gen_len": 3})
        assert "tokens" in gen
        resp = _send(srv.host, srv.port, {"cmd": "metrics"})
        m = resp["metrics"]
        # at least one engine latency histogram (the scheduler's
        # shared decode loop spans engine.stream_step) ...
        assert m["histograms"]["engine.stream_step_ms"]["count"] >= 1
        assert m["histograms"]["server.request_ms"]["count"] == 1
        assert m["counters"]["server.requests"] == 1
        assert m["gauges"]["server.inflight"] == 0
        # ... and at least one collective byte counter (acceptance)
        comm_bytes = {k: v for k, v in m["counters"].items()
                      if k.startswith("comms.") and k.endswith(".bytes")
                      and v > 0}
        assert comm_bytes, m["counters"]
        prom = _send(srv.host, srv.port,
                     {"cmd": "metrics", "format": "prometheus"})
        assert "tdt_server_request_ms_count 1" in prom["prometheus"]
        bad = _send(srv.host, srv.port, {"cmd": "bogus"})
        assert "error" in bad
    finally:
        srv.stop()


def test_vmem_limit_bytes_deprecation():
    """testing.vmem's old VMEM_LIMIT_BYTES name (26 MB declared cap,
    colliding with ops.common's unrelated 64 MB scoped limit) warns and
    forwards to DECLARED_FOOTPRINT_CAP (ADVICE r5 low)."""
    from triton_dist_tpu.ops import common
    from triton_dist_tpu.testing import vmem
    assert vmem.DECLARED_FOOTPRINT_CAP == vmem.HARD_FOOTPRINT_CAP
    with pytest.warns(DeprecationWarning, match="DECLARED_FOOTPRINT_CAP"):
        old = vmem.VMEM_LIMIT_BYTES
    assert old == vmem.DECLARED_FOOTPRINT_CAP
    assert common.VMEM_LIMIT_BYTES != vmem.DECLARED_FOOTPRINT_CAP
    with pytest.raises(AttributeError):
        vmem.NOPE


def test_histogram_quantile_overflow_clips_to_top_edge():
    """ISSUE 8 satellite: a quantile landing in the +Inf bucket of a
    histogram with no recorded max (windowed deltas, rolling windows)
    reports the top finite edge flagged clipped=True — not None."""
    from triton_dist_tpu.obs import histogram_quantile
    h = {"buckets": [1.0, 2.0, 4.0], "counts": [1, 0, 0, 9],
         "count": 10, "sum": 100.0, "min": None, "max": None}
    v, clipped = histogram_quantile(h, 0.99, detail=True)
    assert v == 4.0 and clipped
    assert histogram_quantile(h, 0.99) == 4.0     # default: value only
    # A recorded max stays the honest (unclipped) overflow estimate.
    h2 = dict(h, max=37.5)
    v2, clipped2 = histogram_quantile(h2, 0.99, detail=True)
    assert v2 == 37.5 and not clipped2
    # Finite-bucket quantiles never flag.
    v3, clipped3 = histogram_quantile(h, 0.05, detail=True)
    assert v3 == pytest.approx(0.5) and not clipped3
    # Empty histograms still report None.
    assert histogram_quantile({"buckets": [1.0], "counts": [0, 0],
                               "count": 0}, 0.5) is None


def test_trace_stats_exports_drop_gauges():
    """ISSUE 8 satellite: ring drops + per-ring high water surface as
    obs gauges (not only inside trace.stats()), and report.py warns on
    nonzero drops."""
    from triton_dist_tpu.obs import trace
    reg = obs.Registry()
    obs.enable(reg)
    try:
        trace.enable(capacity=4)
        for i in range(10):                 # 6 overwrites
            trace.instant(f"e{i}", "op")
        st = trace.stats()
        assert st["dropped_total"] == 6
        assert st["ring_high_water"] == 4
        g = reg.snapshot()["gauges"]
        assert g["trace.dropped_total"] == 6
        assert g["trace.ring_high_water"] == 4
        from triton_dist_tpu.tools.report import render_tracing
        md = render_tracing(st)
        assert "ring_high_water" in md
        assert "TDT_TRACE_RING" in md and "⚠" in md
        # No warning when nothing dropped.
        assert "⚠" not in render_tracing(
            {"events_total": 3, "dropped_total": 0})
    finally:
        obs.disable()
