"""History plane: sampled series, trend math, early-warning detectors
(obs/history.py, ISSUE 16).

Quick tier — everything here is either pure math over point lists,
a thread-free sampler driven with injected timestamps, or a short
live-scheduler scenario on the xla-impl tiny model:

- trend math (slope / ema / window_stats / eta_to) against numpy
  goldens, including the no-crossing, negative-slope, and len<2
  degenerate cases ISSUE 17's autoscaler will lean on;
- ring-buffer semantics (wraparound, trailing-window trim,
  stride-downsample keeping the newest point) and sparkline units;
- the detector grammar (``metric>thr[@window]``), the fire-once
  latch, and the step detector's both-halves-populated guard;
- the sampler contract: gauges stored as values, counters as
  per-second rates (first sample skipped), a firing detector emits
  the ``history.warning`` counters + trace instant and a flight dump
  that EMBEDS the trailing series (the injectable provider satellite);
- ``{"cmd": "history"}`` through a live ModelServer + ChatClient, and
  the Perfetto counter-track export (library + CLI ``--history``);
- the acceptance scenario: under ramped load the step detector fires
  and produces a validated flight dump with attached series STRICTLY
  BEFORE the SLO breach dump;
- dashboards: ``top.py`` / ``fleet_top.py`` sparkline panels (pure
  render + live ``--once``), the fleet_top cached-merge contract
  (off-tick refreshes issue ZERO extra history scrapes), poll-fed
  FleetView health history, and ``report.py``'s history section;
- ``bench_ops.check_history_wellformed`` shape gate.
"""

import json
import time

import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
from triton_dist_tpu.obs import flight, trace
from triton_dist_tpu.obs.history import (DetectorSpec, HistorySampler,
                                         Series, SeriesStore,
                                         StepChange, SustainedSlope,
                                         downsample, ema, eta_to,
                                         make_detector, parse_detectors,
                                         slope, sparkline, window_stats)
from triton_dist_tpu.obs.registry import Registry
from triton_dist_tpu.serving import ChatClient, ModelServer, fanout

# ---------------------------------------------------------------------------
# Trend math vs numpy goldens.
# ---------------------------------------------------------------------------

_RAGGED = [(0.0, 1.0), (0.5, 2.2), (1.1, 2.9), (1.7, 4.5), (2.3, 4.9)]


def _np_slope(points):
    t = np.array([p[0] for p in points])
    v = np.array([p[1] for p in points])
    return float(np.polyfit(t, v, 1)[0])


def test_slope_matches_numpy_polyfit():
    assert slope(_RAGGED) == pytest.approx(_np_slope(_RAGGED))
    falling = [(t, 10.0 - 3.0 * t) for t in (0.0, 0.7, 1.3, 2.0)]
    s = slope(falling)
    assert s == pytest.approx(_np_slope(falling))
    assert s < 0


def test_slope_degenerate_cases():
    assert slope([]) is None
    assert slope([(1.0, 5.0)]) is None                # len < 2: no data
    assert slope([(1.0, 5.0), (1.0, 9.0)]) is None    # zero time variance


def test_ema_golden_and_alpha_validation():
    pts = [(0.0, 1.0), (1.0, 2.0), (2.0, 3.0)]
    # s = .5*2 + .5*1 = 1.5 ; s = .5*3 + .5*1.5 = 2.25
    assert ema(pts, alpha=0.5) == pytest.approx(2.25)
    assert ema([], alpha=0.5) is None
    for bad in (0.0, -0.1, 1.5):
        with pytest.raises(ValueError):
            ema(pts, alpha=bad)


def test_window_stats():
    assert window_stats([]) == {"n": 0}
    st = window_stats(_RAGGED)
    vals = [v for _, v in _RAGGED]
    assert st["n"] == len(vals)
    assert st["min"] == min(vals) and st["max"] == max(vals)
    assert st["avg"] == pytest.approx(sum(vals) / len(vals))
    assert st["last"] == vals[-1]
    assert st["span_s"] == pytest.approx(2.3)


def test_eta_to_forecasts_vs_numpy():
    rising = [(0.0, 0.0), (1.0, 1.0), (2.0, 2.0)]
    # Crossing ahead: (thr - last) / fitted slope.
    want = (5.0 - 2.0) / _np_slope(rising)
    assert eta_to(rising, 5.0) == pytest.approx(want)
    # Moving AWAY from the threshold (it is behind us): no crossing.
    assert eta_to(rising, -1.0) is None
    # Negative slope falling toward a lower threshold.
    falling = [(0.0, 10.0), (1.0, 8.0), (2.0, 6.0)]
    want = (2.0 - 6.0) / _np_slope(falling)
    assert eta_to(falling, 2.0) == pytest.approx(want)
    # Negative slope, threshold above: moving away, no crossing.
    assert eta_to(falling, 20.0) is None
    # Already sitting ON the threshold.
    assert eta_to(rising, 2.0) == 0.0
    # Flat never crosses; len<2 is no-data.
    assert eta_to([(0.0, 3.0), (1.0, 3.0)], 9.0) is None
    assert eta_to([(0.0, 3.0)], 9.0) is None


# ---------------------------------------------------------------------------
# Ring buffers, downsampling, sparklines.
# ---------------------------------------------------------------------------

def test_series_ring_wraparound():
    s = Series("q", maxlen=4)
    for i in range(6):
        s.append(float(i), float(i * 10))
    assert len(s) == 4
    assert s.total == 6
    assert s.last() == (5.0, 50.0)
    # Oldest-first, only the newest maxlen survive the wrap.
    assert s.points() == [(2.0, 20.0), (3.0, 30.0), (4.0, 40.0),
                          (5.0, 50.0)]
    # Trailing-window trim anchored at an explicit now.
    assert s.points(last_s=1.5, now=5.0) == [(4.0, 40.0), (5.0, 50.0)]
    assert s.values(last_s=1.5, now=5.0) == [40.0, 50.0]
    with pytest.raises(ValueError):
        Series("bad", maxlen=1)


def test_downsample_keeps_newest():
    pts = [(float(i), float(i)) for i in range(10)]
    out = downsample(pts, 3)
    assert len(out) <= 3
    assert out[-1] == pts[-1]                 # right edge always kept
    assert out == sorted(out)                 # still oldest-first
    assert downsample(pts, None) == pts
    assert downsample(pts, 100) == pts
    assert downsample(pts, 0) == []


def test_sparkline_units():
    assert sparkline([]) == ""
    assert sparkline([None, None]) == ""      # None-filtered → no data
    assert sparkline([3.0, 3.0, 3.0]) == "▄▄▄"   # flat ≠ empty
    assert sparkline(range(8)) == "▁▂▃▄▅▆▇█"
    assert len(sparkline(range(100), width=12)) == 12
    # Bucket-averaged width reduction keeps the ramp monotone.
    w = sparkline(range(64), width=8)
    assert list(w) == sorted(w)


def test_store_snapshot_filter_window_downsample():
    store = SeriesStore(maxlen=16)
    for i in range(10):
        store.record("a", float(i), float(i))
        store.record("b", float(i), 1.0)
    store.add_warning({"detector": "slope", "metric": "a"})
    store.add_warning({"detector": "step", "metric": "b"})
    snap = store.snapshot(series=["a"], max_points=4)
    assert set(snap["series"]) == {"a"}
    assert len(snap["series"]["a"]["points"]) <= 4
    assert snap["series"]["a"]["points"][-1] == [9.0, 9.0]
    assert snap["series"]["a"]["n"] == 10
    assert snap["maxlen"] == 16 and "epoch" in snap
    # Warnings are newest-first.
    assert [w["detector"] for w in snap["warnings"]] == ["step",
                                                         "slope"]
    # last_s trims relative to each series' newest point.
    snap2 = store.snapshot(last_s=2.0)
    assert len(snap2["series"]["a"]["points"]) == 3


# ---------------------------------------------------------------------------
# Detector grammar + the fire-once latch.
# ---------------------------------------------------------------------------

def test_detector_spec_validation():
    with pytest.raises(ValueError):
        DetectorSpec("nope", "m", ">", 1.0)
    with pytest.raises(ValueError):
        DetectorSpec("slope", "m", ">=", 1.0)
    with pytest.raises(ValueError):
        DetectorSpec("slope", "m", ">", 1.0, window_s=0.0)


def test_parse_detectors_grammar():
    specs = parse_detectors(
        "serving.queue_depth>0.5@30; kv.blocks_free<2", "slope")
    assert [s.metric for s in specs] == ["serving.queue_depth",
                                         "kv.blocks_free"]
    assert specs[0].op == ">" and specs[0].threshold == 0.5
    assert specs[0].window_s == 30.0
    assert specs[1].op == "<" and specs[1].window_s == 30.0  # default
    assert parse_detectors("", "slope") == []
    assert parse_detectors("  ;  ", "step") == []
    for bad in ("queue_depth", ">1.0", "m>abc", "m>1@xx"):
        with pytest.raises(ValueError):
            parse_detectors(bad, "slope")
    assert isinstance(make_detector(specs[0]), SustainedSlope)
    assert isinstance(
        make_detector(DetectorSpec("step", "m", ">", 1.0)), StepChange)


def test_sustained_slope_fires_once_then_rearms():
    det = make_detector(DetectorSpec("slope", "q", ">", 0.5,
                                     window_s=2.0))
    ramp = [(t * 0.5, t * 0.5 * 2.0) for t in range(5)]  # slope 2.0
    d = det.check(ramp, now=2.0)
    assert d is not None
    assert d["detector"] == "slope" and d["metric"] == "q"
    assert d["slope_per_s"] == pytest.approx(2.0)
    # Still over threshold: latched, no second fire.
    assert det.check(ramp, now=2.0) is None
    # Condition clears (flat window) → re-arms...
    flat = [(t * 0.5, 7.0) for t in range(5)]
    assert det.check(flat, now=2.0) is None
    # ... and a new sustained excursion fires again.
    assert det.check(ramp, now=2.0) is not None
    # Too few points / half-covered window: never fires.
    assert det.check(ramp[:2], now=2.0) is None
    fresh = make_detector(DetectorSpec("slope", "q", ">", 0.5,
                                       window_s=10.0))
    assert fresh.check(ramp, now=2.0) is None   # span 2 < 0.5*10


def test_step_change_needs_both_halves():
    det = make_detector(DetectorSpec("step", "q", ">", 2.0,
                                     window_s=1.0))
    # A series that APPEARS mid-window (late half only) cannot
    # instant-fire on its first samples.
    late_only = [(0.6, 5.0), (0.7, 5.0), (0.8, 5.0), (0.9, 5.0)]
    assert det.check(late_only, now=1.0) is None
    # Both halves populated and the level shift exceeds the threshold.
    pts = [(0.1, 0.0), (0.3, 0.0), (0.7, 5.0), (0.9, 5.0)]
    d = det.check(pts, now=1.0)
    assert d is not None and d["delta"] == pytest.approx(5.0)
    assert det.check(pts, now=1.0) is None     # latched
    # Shift below threshold clears the latch.
    small = [(0.1, 0.0), (0.3, 0.0), (0.7, 1.0), (0.9, 1.0)]
    assert det.check(small, now=1.0) is None
    assert det.check(pts, now=1.0) is not None  # re-armed, fires again


# ---------------------------------------------------------------------------
# The sampler: values vs rates, detector wiring, flight provider.
# ---------------------------------------------------------------------------

def _sampler(reg, **kw):
    kw.setdefault("thread", False)
    kw.setdefault("install_flight_provider", False)
    kw.setdefault("tick_s", 0.05)
    return HistorySampler(registry=reg, **kw)


def test_sampler_gauges_as_values_counters_as_rates():
    reg = Registry()
    reg.gauge("serving.queue_depth").set(5.0)
    reg.counter("serving.admitted").inc(10.0)
    smp = _sampler(reg, maxlen=32)
    smp.sample_once(now=100.0)
    # Gauge recorded as a value; the FIRST counter sample is skipped
    # (no previous tick to rate against).
    q = smp.store.get("serving.queue_depth")
    assert q is not None and q.last() == (100.0, 5.0)
    assert smp.store.get("serving.admitted") is None
    reg.counter("serving.admitted").inc(20.0)
    reg.gauge("serving.queue_depth").set(7.0)
    smp.sample_once(now=102.0)
    adm = smp.store.get("serving.admitted")
    assert adm.last() == (102.0, pytest.approx(10.0))   # 20 / 2 s
    assert smp.store.get("serving.queue_depth").last() == (102.0, 7.0)
    # Bookkeeping: tick counter + series-count gauge in the SAME
    # registry the sampler peeks.
    assert reg.counter("history.ticks").value == 2
    assert reg.gauge("history.series").value == len(smp.store)
    assert smp.snapshot()["tick_s"] == 0.05


def test_sampler_detector_fire_emits_warning_and_embedding_dump(
        monkeypatch, tmp_path):
    """A firing detector bumps the history.warning counters, records
    the excerpt, and the flight dump it triggers EMBEDS the trailing
    series (the injectable-provider satellite) as metadata AND as
    Perfetto counter tracks — and the artifact validates."""
    trace.enable()
    reg = Registry()
    det = make_detector(DetectorSpec("step", "g", ">", 2.0,
                                     window_s=1.0))
    smp = _sampler(reg, detectors=[det], install_flight_provider=True)
    try:
        for i, (now, v) in enumerate([(0.0, 0.0), (0.2, 0.0),
                                      (0.4, 0.0), (0.6, 5.0),
                                      (0.8, 5.0), (1.0, 5.0)]):
            reg.gauge("g").set(v)
            smp.sample_once(now=now)
        assert reg.counter("history.warnings").value == 1
        assert reg.counter("history.warning.step").value == 1
        (w,) = smp.store.warnings()
        assert w["detector"] == "step" and w["metric"] == "g"
        rec = flight.last_record()
        assert rec is not None and rec["reason"] == "history_step_g"
        with open(rec["path"]) as f:
            chrome = json.load(f)
        hist = chrome["metadata"]["history"]
        assert "g" in hist["series"] and hist["series"]["g"]["points"]
        cs = [e for e in chrome["traceEvents"] if e.get("ph") == "C"]
        assert cs and any(e["name"] == "g" for e in cs)
        from triton_dist_tpu.tools import trace_export
        errors, _ = trace_export.validate(chrome)
        assert errors == [], errors
    finally:
        smp.close()
    assert flight.history_provider() is None   # close uninstalls


def test_flight_provider_last_installer_wins():
    reg = Registry()
    a = _sampler(reg, install_flight_provider=True)
    assert flight.history_provider() == a.dump_payload
    b = _sampler(reg, install_flight_provider=True)
    assert flight.history_provider() == b.dump_payload
    a.close()                                  # not ours anymore: kept
    assert flight.history_provider() == b.dump_payload
    b.close()
    assert flight.history_provider() is None


def test_from_env_contract(monkeypatch):
    assert HistorySampler.from_env(registry=Registry()) is None
    monkeypatch.setenv("TDT_HISTORY", "1")
    monkeypatch.setenv("TDT_HISTORY_TICK_S", "0.05")
    monkeypatch.setenv("TDT_HISTORY_SLOPE", "serving.queue_depth>0.5@5")
    monkeypatch.setenv("TDT_HISTORY_STEP", "g>2@1")
    smp = HistorySampler.from_env(registry=Registry())
    try:
        assert smp is not None and smp.tick_s == 0.05
        assert [(d.kind, d.spec.metric) for d in smp.detectors] == \
            [("slope", "serving.queue_depth"), ("step", "g")]
    finally:
        smp.close()


def test_scheduler_ctor_injection_paths(tiny, monkeypatch):
    from triton_dist_tpu.models import Engine
    from triton_dist_tpu.serving import Scheduler
    model, params = tiny

    def _eng():
        return Engine(model, batch=2, max_seq=64,
                      prefill_mode="xla_ar", decode_mode="gemm_ar")

    # Default env-off: no sampler, no thread (zero-overhead contract).
    assert Scheduler(_eng(), params).history is None
    # Explicit opt-out even with the env set.
    monkeypatch.setenv("TDT_HISTORY", "1")
    assert Scheduler(_eng(), params, history_sampler=False) \
        .history is None
    # Injected instance is used verbatim.
    mine = _sampler(Registry())
    assert Scheduler(_eng(), params, history_sampler=mine) \
        .history is mine
    mine.close()
    # Env-on default path builds one.
    sched = Scheduler(_eng(), params)
    assert sched.history is not None
    sched.history.close()


# ---------------------------------------------------------------------------
# Perfetto counter-track export (library + CLI).
# ---------------------------------------------------------------------------

def _hist_snap():
    return {"epoch": 1000.0, "maxlen": 8,
            "series": {"q": {"points": [[1.0, 2.0], [2.0, 3.0]],
                             "n": 2},
                       "a": {"points": [[1.5, 7.0]], "n": 1}},
            "warnings": []}


def test_history_counter_events_and_validate():
    from triton_dist_tpu.tools import trace_export
    evs = trace_export.history_counter_events(_hist_snap(), pid=3)
    # Series-sorted; wall-anchored micros: (t + epoch) * 1e6.
    assert [e["name"] for e in evs] == ["a", "q", "q"]
    assert all(e["ph"] == "C" and e["pid"] == 3 and
               e["cat"] == "history" for e in evs)
    assert evs[0]["ts"] == pytest.approx(1001.5e6)
    assert evs[0]["args"] == {"value": 7.0}
    # Interleaved C events are exempt from the per-track monotonic
    # check (several series share a tid by design)...
    chrome = {"traceEvents": evs}
    errors, _ = trace_export.validate(chrome)
    assert errors == []
    # ... but non-numeric / empty args are schema errors.
    for bad_args in ({}, {"value": "x"}, {"value": True}, None):
        bad = {"traceEvents": [{"ph": "C", "ts": 1.0, "name": "q",
                                "args": bad_args}]}
        errors, _ = trace_export.validate(bad)
        assert errors, bad_args


def test_trace_export_cli_history_overlay(tmp_path, capsys):
    from triton_dist_tpu.tools import trace_export
    src = tmp_path / "in.trace.json"
    src.write_text(json.dumps({"traceEvents": [
        {"ph": "X", "ts": 1.0, "dur": 2.0, "pid": 0, "tid": 1,
         "name": "step", "cat": "serving"}]}))
    hist = tmp_path / "hist.json"
    # A saved {"cmd": "history"} reply — the wrapper is unwrapped.
    hist.write_text(json.dumps({"history": _hist_snap()}))
    out = tmp_path / "out.trace.json"
    rc = trace_export.main([str(src), "--out", str(out),
                            "--history", str(hist)])
    assert rc == 0
    merged = json.loads(out.read_text())
    assert merged["metadata"]["history_series"] == 2
    cs = [e for e in merged["traceEvents"] if e.get("ph") == "C"]
    assert len(cs) == 3
    # --history without --out, and a snapshot with no series: errors.
    with pytest.raises(SystemExit):
        trace_export.main([str(src), "--history", str(hist)])
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"history": {"series": {}}}))
    with pytest.raises(SystemExit):
        trace_export.main([str(src), "--out", str(out),
                           "--history", str(empty)])


# ---------------------------------------------------------------------------
# Live server: the {"cmd": "history"} verb + the acceptance scenario.
# ---------------------------------------------------------------------------

@pytest.fixture()
def tiny(mesh8, key):
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    return model, model.init(key)


def _engine(model, batch=2, max_seq=64):
    return Engine(model, batch=batch, max_seq=max_seq,
                  prefill_mode="xla_ar", decode_mode="gemm_ar")


def _wait_until(pred, timeout=60.0, what="condition"):
    t0 = time.monotonic()
    while not pred():
        assert time.monotonic() - t0 < timeout, f"timed out on {what}"
        time.sleep(0.02)


def test_history_verb_null_without_sampler(tiny):
    model, params = tiny
    srv = ModelServer(_engine(model), params, port=0,
                      registry="private").start()
    try:
        c = ChatClient(srv.host, srv.port, timeout=180)
        assert c.request({"cmd": "history"}) == {"history": None}
        c.close()
    finally:
        srv.stop()


def test_history_verb_live_roundtrip(tiny, monkeypatch):
    """TDT_HISTORY=1 at construction: the sampler rides the pump's
    registry and the verb round-trips a downsampled snapshot."""
    monkeypatch.setenv("TDT_HISTORY", "1")
    monkeypatch.setenv("TDT_HISTORY_TICK_S", "0.05")
    model, params = tiny
    srv = ModelServer(_engine(model), params, port=0,
                      registry="private").start()
    try:
        c = ChatClient(srv.host, srv.port, timeout=180)
        c.generate_ids([[1, 2, 3]], gen_len=3)

        def _series():
            return c.request({"cmd": "history"})["history"]["series"]

        _wait_until(lambda: "serving.queue_depth" in _series(),
                    what="sampled queue_depth series")
        h = c.request({"cmd": "history", "max_points": 2,
                       "series": ["serving.queue_depth"]})["history"]
        assert h["tick_s"] == 0.05
        assert set(h["series"]) == {"serving.queue_depth"}
        assert 1 <= len(h["series"]["serving.queue_depth"]["points"]) \
            <= 2
        c.close()
    finally:
        srv.stop()


def test_early_warning_precedes_breach_live(tiny, monkeypatch):
    """Acceptance: under ramped load the step detector fires
    ``history.warning`` and dumps a flight record with the attached
    series STRICTLY BEFORE the SLO breach — the warning lands while
    ``serving.slo_breaches`` is still untouched, because the breach's
    slow window hasn't met its sample floor yet. The warning dump then
    validates as a Perfetto artifact with embedded counter tracks."""
    monkeypatch.setenv("TDT_SLO_TTFT_P99_MS", "0.001")
    monkeypatch.setenv("TDT_HISTORY", "1")
    monkeypatch.setenv("TDT_HISTORY_TICK_S", "0.05")
    monkeypatch.setenv("TDT_HISTORY_STEP",
                       "serving.queue_depth>1.5@1")
    model, params = tiny
    srv = ModelServer(_engine(model), params, port=0).start()
    try:
        assert trace.enabled()
        c = ChatClient(srv.host, srv.port, timeout=180)
        m0 = c.request({"cmd": "metrics",
                        "evaluate": False})["metrics"]["counters"]
        b0 = m0.get("serving.slo_breaches", 0)
        w0 = m0.get("history.warnings", 0)
        # Phase 1 — calm baseline: two serial requests, then idle long
        # enough for the sampler to record queue_depth == 0 into what
        # will become the detector window's EARLY half.
        for i in range(2):
            c.generate_ids([[1 + i, 2, 3]], gen_len=2)
        time.sleep(0.6)
        # Phase 2 — the ramp: 7 concurrent long generations through a
        # 2-row batch. Queue depth steps 0 → ~5; the step detector
        # fires mid-flood. TOTAL slow-window samples stay at 9 — below
        # the breach floor (TDT_SLO_MIN_SAMPLES = 10) — so the SLO
        # breach CANNOT fire yet: the warning is strictly earlier by
        # construction, not by a race.
        outs = fanout(srv.host, srv.port,
                      [{"prompt_ids": [[1 + i, 2, 3]], "gen_len": 48}
                       for i in range(7)], timeout=180)
        assert all("tokens" in o for o in outs), outs
        m1 = c.request({"cmd": "metrics",
                        "evaluate": False})["metrics"]["counters"]
        assert m1.get("history.warnings", 0) >= w0 + 1
        assert m1.get("serving.slo_breaches", 0) == b0   # not yet
        warn_rec = flight.last_record()
        assert warn_rec is not None
        assert warn_rec["reason"] == "history_step_serving.queue_depth"
        # Phase 3 — three more violating requests clear the sample
        # floor; the metrics scrape (evaluate defaults True) forces
        # the breach and its own dump.
        for i in range(3):
            c.generate_ids([[9 + i, 2]], gen_len=2)
        m2 = c.request({"cmd": "metrics"})["metrics"]
        c.close()
        assert m2["counters"]["serving.slo_breaches"] == b0 + 1
        breach_rec = flight.last_record()
        assert breach_rec["reason"] == "slo_ttft_p99"
        assert breach_rec["count"] > warn_rec["count"]   # strict order
        # The EARLY dump carries the lead-up series and validates.
        from triton_dist_tpu.tools import trace_export
        for rec in (warn_rec, breach_rec):
            with open(rec["path"]) as f:
                chrome = json.load(f)
            hist = chrome["metadata"].get("history")
            assert hist and hist["series"], rec["reason"]
            assert "serving.queue_depth" in hist["series"]
            assert any(e.get("ph") == "C"
                       for e in chrome["traceEvents"])
            errors, _ = trace_export.validate(chrome)
            assert errors == [], (rec["reason"], errors)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Fleet: poll-fed health history + the cached-merge scrape contract.
# ---------------------------------------------------------------------------

class _FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def _mk_health(rid, q=0.0, occ=0.0, p99=None):
    h = {"replica_id": rid, "seq": 1, "uptime_s": 1.0,
         "queue_depth": q, "batch_occupancy": occ}
    if p99 is not None:
        h["rolling"] = {"ttft_p99_ms": p99}
    return {"health": h}


def test_fleet_view_poll_feeds_history_and_staleness_gaps():
    from triton_dist_tpu.obs.fleet import FleetView
    clock = _FakeClock()
    state = {"b_alive": True}

    def scrape(endpoints, req):
        out = []
        for ep in endpoints:
            if ep[1] == 2 and not state["b_alive"]:
                out.append({"error": "timed out",
                            "type": "TimeoutError"})
            else:
                out.append(_mk_health(f"r{ep[1]}", q=2.0, occ=0.5,
                                      p99=8.0))
        return out

    view = FleetView(["127.0.0.1:1", "127.0.0.1:2"], stale_s_=5.0,
                     down_s_=20.0, clock=clock, scrape=scrape)
    assert view.history()["fleet"]["series"] == {}   # empty until poll
    view.poll()
    clock.t += 1.0
    view.poll()
    h = view.history()
    fl = h["fleet"]["series"]
    # Fleet rollup: additive sums over reporting replicas per poll.
    assert [v for _, v in fl["queue_depth"]["points"]] == [4.0, 4.0]
    assert [v for _, v in fl["replicas_reporting"]["points"]] == \
        [2.0, 2.0]
    assert set(h["replicas"]) == {"r1", "r2"}
    r1 = h["replicas"]["r1"]["series"]
    assert len(r1["queue_depth"]["points"]) == 2
    assert r1["ttft_p99_ms"]["points"][-1][1] == 8.0
    # A replica that fails the poll gets NO new point (a sparkline gap
    # is a staleness signal, not a zero) while the healthy one keeps
    # advancing; the fleet rollup drops to one reporter.
    state["b_alive"] = False
    clock.t += 1.0
    view.poll()
    h = view.history()
    assert len(h["replicas"]["r2"]["series"]["queue_depth"]
               ["points"]) == 2               # stopped advancing
    assert len(h["replicas"]["r1"]["series"]["queue_depth"]
               ["points"]) == 3
    # Stale (not yet down): the last-good health still counts toward
    # the rollup — only a DOWN replica drops out of it.
    assert h["fleet"]["series"]["replicas_reporting"]["points"][-1][1] \
        == 2.0
    clock.t += 25.0                           # past down_s
    view.poll()
    h = view.history()
    assert h["fleet"]["series"]["replicas_reporting"]["points"][-1][1] \
        == 1.0
    assert len(h["replicas"]["r2"]["series"]["queue_depth"]
               ["points"]) == 2               # still frozen


def test_fleet_top_off_tick_issues_zero_history_scrapes():
    """The cached-merge contract (METRICS_EVERY): an off-tick refresh
    polls health but issues NO {"cmd": "history"} (or metrics)
    scrapes — it renders the cached copies."""
    from triton_dist_tpu.obs.fleet import FleetView
    from triton_dist_tpu.tools import fleet_top
    clock = _FakeClock()
    counts: dict = {}

    def scrape(endpoints, req):
        counts[req["cmd"]] = counts.get(req["cmd"], 0) + 1
        if req["cmd"] == "health":
            return [_mk_health(f"r{ep[1]}", q=1.0) for ep in endpoints]
        if req["cmd"] == "metrics":
            return [{"metrics": {"replica_id": f"r{ep[1]}",
                                 "counters": {}, "gauges": {},
                                 "histograms": {}}}
                    for ep in endpoints]
        assert req["cmd"] == "history"
        assert req["max_points"] == 32       # downsampled server-side
        return [{"history": {
            "epoch": 0.0, "maxlen": 8, "tick_s": 0.05,
            "series": {"serving.queue_depth":
                       {"points": [[1.0, 2.0]], "n": 1}},
            "warnings": [{"detector": "step",
                          "metric": "serving.queue_depth"}]}}
            for ep in endpoints]

    view = FleetView(["127.0.0.1:1", "127.0.0.1:2"], clock=clock,
                     scrape=scrape)
    state = fleet_top.fetch(view, with_metrics=True)
    assert counts == {"health": 1, "metrics": 1, "history": 1}
    assert set(state["remote_history"]) == {"r1", "r2"}
    # Off-tick: health only — merged and remote history come from the
    # cache, zero extra scrape rounds.
    state = fleet_top.fetch(view, with_metrics=False)
    assert counts == {"health": 2, "metrics": 1, "history": 1}
    assert set(state["remote_history"]) == {"r1", "r2"}
    screen = fleet_top.render(state)
    assert "history: queue" in screen        # poll-fed fleet sparkline
    assert "r1: q" in screen
    assert "! r1: history.warning step serving.queue_depth" in screen


# ---------------------------------------------------------------------------
# Dashboards + report rendering.
# ---------------------------------------------------------------------------

def test_top_render_history_panel():
    from triton_dist_tpu.tools import top
    snap = {"counters": {}, "gauges": {}, "histograms": {},
            "health": None, "requests": [],
            "history": {"epoch": 0.0, "maxlen": 8, "tick_s": 0.05,
                        "series": {"serving.queue_depth":
                                   {"points": [[float(i), float(i)]
                                               for i in range(8)],
                                    "n": 8}},
                        "warnings": [{"detector": "slope",
                                      "metric": "serving.queue_depth",
                                      "op": ">", "threshold": 0.5,
                                      "window_s": 30.0}]}}
    screen = top.render(snap)
    assert "history (sampled)" in screen
    assert "serving.queue_depth" in screen
    assert any(ch in screen for ch in "▁▂▃▄▅▆▇█")
    assert "! slope" in screen
    # Additive: a history-less snapshot renders no panel and no crash.
    snap["history"] = None
    assert "history (sampled)" not in top.render(snap)


def test_dashboards_once_live_with_history(tiny, monkeypatch, capsys):
    """End-to-end ``--once``: both dashboards against a live sampling
    server render the sparkline panels."""
    from triton_dist_tpu.tools import fleet_top, top
    monkeypatch.setenv("TDT_HISTORY", "1")
    monkeypatch.setenv("TDT_HISTORY_TICK_S", "0.05")
    model, params = tiny
    srv = ModelServer(_engine(model), params, port=0,
                      registry="private", replica_id="h-a").start()
    try:
        c = ChatClient(srv.host, srv.port, timeout=180)
        c.generate_ids([[1, 2, 3]], gen_len=3)
        _wait_until(
            lambda: (c.request({"cmd": "history"})["history"]
                     or {}).get("series"),
            what="sampled series")
        c.close()
        assert top.main(["--host", srv.host, "--port", str(srv.port),
                         "--once"]) == 0
        out = capsys.readouterr().out
        assert "history (sampled)" in out
        assert any(ch in out for ch in "▁▂▃▄▅▆▇█")
        assert fleet_top.main(
            ["--endpoints", f"{srv.host}:{srv.port}", "--once"]) == 0
        out = capsys.readouterr().out
        assert "h-a" in out
        assert "history: queue" in out       # poll-fed fleet rollup
    finally:
        srv.stop()


def test_report_history_section():
    from triton_dist_tpu.tools.report import (render_history,
                                              render_telemetry)
    assert render_history(None) == ""
    assert render_history({"series": {}}) == ""
    hist = {"epoch": 0.0, "maxlen": 8,
            "series": {"serving.queue_depth":
                       {"points": [[float(i), float(i * 2)]
                                   for i in range(6)], "n": 6}},
            "warnings": [{"detector": "step",
                          "metric": "serving.queue_depth", "op": ">",
                          "threshold": 1.5, "window_s": 1.0}]}
    md = render_history(hist)
    assert "#### history" in md
    assert "| serving.queue_depth | 6 |" in md
    assert any(ch in md for ch in "▁▂▃▄▅▆▇█")
    assert "⚠ history.warning: step detector on " \
           "`serving.queue_depth`" in md
    # Rides render_telemetry like the fleet/router sections.
    tel = render_telemetry({"counters": {}, "gauges": {},
                            "histograms": {}, "history": hist})
    assert "#### history" in tel


# ---------------------------------------------------------------------------
# bench_ops: the serving_history shape gate.
# ---------------------------------------------------------------------------

def test_check_history_wellformed():
    from triton_dist_tpu.tools.bench_ops import check_history_wellformed
    # Part didn't run (no sentinel): nothing to check.
    assert check_history_wellformed({}) == []
    good = {"serving_history_tokens_per_s": 100.0,
            "serving_history_on_vs_off": 0.97,
            "serving_history_ticks": 12,
            "serving_history_series": 5}
    assert check_history_wellformed(good) == []
    for key, bad in (("serving_history_on_vs_off", 0.0),
                     ("serving_history_on_vs_off", None),
                     ("serving_history_on_vs_off", True),
                     ("serving_history_ticks", 0),
                     ("serving_history_ticks", "many"),
                     ("serving_history_series", 0),
                     ("serving_history_series", None)):
        extras = dict(good)
        extras[key] = bad
        fails = check_history_wellformed(extras)
        assert fails and key in fails[0], (key, bad, fails)
