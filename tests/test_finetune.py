"""tdt-finetune CLI: HF checkpoint → train → orbax save → resume.

Drives the real console entry (``tools.finetune.main``) against a tiny
HF Qwen3 checkpoint written with ``save_pretrained``, a plain text
corpus, and the 8-device CPU mesh — the whole user journey the
reference cannot offer (it has no training path): load + shard HF
weights, overfit a corpus, save a resumable checkpoint, resume it.
"""

from __future__ import annotations

import numpy as np
import pytest

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def hf_dir(tmp_path_factory):
    torch = pytest.importorskip("torch")
    from transformers import Qwen3Config, Qwen3ForCausalLM
    cfg = Qwen3Config(
        hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=8, num_key_value_heads=8, head_dim=8,
        vocab_size=128, max_position_embeddings=128, rope_theta=1e6,
        rms_norm_eps=1e-6, tie_word_embeddings=False,
        attention_bias=False, attention_dropout=0.0)
    torch.manual_seed(0)
    hf = Qwen3ForCausalLM(cfg).eval()
    d = tmp_path_factory.mktemp("hf_ckpt")
    hf.save_pretrained(d, safe_serialization=True)
    return str(d)


def test_finetune_cli_end_to_end(hf_dir, tmp_path, capsys):
    from triton_dist_tpu.tools.finetune import main

    data = tmp_path / "corpus.txt"
    # A strongly repetitive corpus: a few steps must cut the loss.
    data.write_text("the quick brown fox jumps over the lazy dog. " * 200)
    out = tmp_path / "ckpt"

    last = main(["--model", hf_dir, "--data", str(data),
                 "--out", str(out), "--steps", "6", "--batch", "2",
                 "--seq", "32", "--lr", "1e-3", "--mode", "xla",
                 "--impl", "xla", "--log-every", "2"])
    logs = capsys.readouterr().out
    first = float(logs.split("loss ")[1].split()[0])
    assert np.isfinite(last) and last < first, (first, last)
    assert out.exists()

    # Resume: two more steps from the checkpoint keep improving and
    # start from (not above) where the saved run ended.
    last2 = main(["--model", hf_dir, "--data", str(data),
                  "--out", str(tmp_path / "ckpt2"), "--steps", "2",
                  "--batch", "2", "--seq", "32", "--lr", "1e-3",
                  "--mode", "xla", "--impl", "xla",
                  "--resume", str(out), "--log-every", "1"])
    assert np.isfinite(last2) and last2 < first


def test_finetune_cli_bin_shard(hf_dir, tmp_path):
    """--data *.bin routes through the memory-mapped TokenDataset
    (native shuffled-epoch batching) end-to-end."""
    from triton_dist_tpu.tools.data import pack_tokens
    from triton_dist_tpu.tools.finetune import main

    ids = (np.arange(4096) % 128).astype(np.int32)
    shard = pack_tokens(ids, str(tmp_path / "corpus.bin"))
    last = main(["--model", hf_dir, "--data", shard,
                 "--out", str(tmp_path / "ckpt"), "--steps", "3",
                 "--batch", "2", "--seq", "32", "--lr", "1e-3",
                 "--mode", "xla", "--impl", "xla", "--log-every", "1"])
    assert np.isfinite(last)
