"""Quick-tier CI gate for the static-analysis framework (ISSUE 9).

Three layers:

- the repo itself is clean under every registered pass (the
  acceptance gate — `python -m triton_dist_tpu.tools.tdt_check`
  exits 0);
- the ring-protocol model checker verifies every fused-family
  schedule for worlds 1..8 in both ring directions, and each of the
  five known-bad schedule mutants is caught with the RIGHT finding
  class and a nonzero driver exit code — a checker that passes
  everything is untested;
- one seeded drift per contract-lint class fires with a
  file:line-anchored finding.
"""

import json
import textwrap

import pytest

from triton_dist_tpu.analysis import (
    Finding, PASSES, exit_code, filter_suppressed, run_passes)
from triton_dist_tpu.analysis import ring_model as rm
from triton_dist_tpu.analysis import vmem as avmem
from triton_dist_tpu.analysis import (
    lint_env, lint_fallback, lint_metrics, lint_trace)
from triton_dist_tpu.tools import tdt_check


# ---------------------------------------------------------------------------
# The repo is clean (the CI gate)
# ---------------------------------------------------------------------------

def test_repo_is_clean_under_all_passes():
    findings = run_passes()
    assert findings == [], "\n".join(f.render() for f in findings)


def test_driver_main_exit_code_and_json(capsys):
    assert tdt_check.main([]) == 0
    assert tdt_check.main(["--json"]) == 0
    out = capsys.readouterr().out
    payload = json.loads(out[out.index("{"):])
    assert payload["errors"] == 0
    assert tdt_check.main(["--list"]) == 0
    listed = capsys.readouterr().out
    for name in PASSES:
        assert name in listed


def test_driver_rejects_unknown_pass():
    with pytest.raises(ValueError, match="unknown pass"):
        run_passes(names=["no-such-pass"])


def test_smoke_preflight_is_green():
    import tpu_smoke
    assert tpu_smoke.run_preflight() == 0


# ---------------------------------------------------------------------------
# Ring-protocol model checker: green on the real schedules...
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("world", range(1, 9))
@pytest.mark.parametrize("dirs", [1, 2])
def test_every_family_schedule_verifies(world, dirs):
    for trace in rm.family_traces(world, dirs):
        assert rm.check_trace(trace) == [], trace.name


# ...and each known-bad mutant is caught with the right class.

def _codes(trace):
    return {v.code for v in rm.check_trace(trace)}


@pytest.mark.parametrize("world,dirs", [(4, 2), (5, 2), (3, 1)])
def test_mutant_dropped_wait(world, dirs):
    t = rm.drop_first_wait(rm.ag_ring_trace(world, dirs))
    codes = _codes(t)
    assert "ring.race" in codes, codes           # read of in-flight chunk
    assert "ring.signal_wait_imbalance" in codes


@pytest.mark.parametrize("world,dirs", [(4, 2), (2, 1)])
def test_mutant_doubled_signal(world, dirs):
    codes = _codes(rm.double_signal(rm.ag_ring_trace(world, dirs)))
    assert codes == {"ring.signal_wait_imbalance"}, codes


@pytest.mark.parametrize("world,dirs", [(4, 2), (5, 1)])
def test_mutant_off_by_one_chunk(world, dirs):
    codes = _codes(rm.shift_consume(rm.ag_ring_trace(world, dirs)))
    assert "ring.coverage" in codes, codes


@pytest.mark.parametrize("world,dirs", [(4, 2), (3, 1), (8, 2)])
def test_mutant_swapped_direction(world, dirs):
    codes = _codes(rm.swap_direction(rm.ag_ring_trace(world, dirs)))
    assert "ring.deadlock" in codes, codes


def test_mutant_rs_off_by_one_reduction():
    codes = _codes(rm.gemm_rs_trace(5, 2, send_idx_shift=1))
    assert "ring.coverage" in codes, codes


def test_mutants_exit_nonzero_with_anchor():
    """Acceptance shape: every mutant → nonzero exit + file:line."""
    base = rm.ag_ring_trace(4, 2)
    mutants = [rm.drop_first_wait(base), rm.double_signal(base),
               rm.shift_consume(base), rm.swap_direction(base)]
    for t in mutants:
        findings = [Finding(code=v.code, message=v.detail,
                            file=t.anchor[0], line=t.anchor[1])
                    for v in rm.check_trace(t)]
        assert exit_code(findings) != 0, t.name
        assert findings[0].file and findings[0].file.endswith(".py")
        assert findings[0].line and findings[0].line > 0
        assert ":" in findings[0].anchor


def test_ring_pass_runs_real_schedule_code(monkeypatch):
    """The checker symbolically executes ring_chunk_schedule itself: a
    bug injected THERE (not in the mirror) must surface."""
    from triton_dist_tpu.ops import common as ops_common
    orig = ops_common.ring_chunk_schedule

    def broken(me, s, world, dirs):
        c, b, o = orig(me, s, world, dirs)
        return (c + 1) % world if world > 1 else c, b, o

    monkeypatch.setattr(ops_common, "ring_chunk_schedule", broken)
    rm._schedule_table.cache_clear()
    try:
        t = rm.ag_ring_trace(4, 2)
        assert rm.check_trace(t) != []
    finally:
        rm._schedule_table.cache_clear()


# ---------------------------------------------------------------------------
# VMEM-over-budget mutant: rejected statically, no compile invoked
# ---------------------------------------------------------------------------

def test_mutant_vmem_over_budget_rejected_statically():
    cfg = {"variant": "hbm", "block_m": 1024, "block_n": 2048}
    f = avmem.vet_candidate("ag_gemm", cfg, rows=8192, m=8192, k=8192,
                            n_loc=8192, itemsize=2, world=1)
    assert f is not None and f.code == "vmem.over_budget"
    assert f.file and f.line and exit_code([f]) != 0
    # and an in-budget config passes the same gate
    ok = avmem.vet_candidate("ag_gemm",
                             {"variant": "hbm", "block_m": 128,
                              "block_n": 128},
                             rows=1024, m=1024, k=1024, n_loc=1024,
                             itemsize=2, world=1)
    assert ok is None


def test_autotune_vet_skips_rejected_candidates_without_compiling():
    from triton_dist_tpu.tools import autotuner
    built = []

    def make_fn(**cfg):
        built.append(dict(cfg))
        return lambda: None

    res = autotuner.autotune(
        make_fn, [{"a": 1}, {"a": 2}, {"a": 3}], key=None, iters=1,
        warmup_iters=0,
        vet=lambda c: "too big" if c["a"] == 2 else None)
    assert {c["a"] for c in built} == {1, 3}   # a=2 never constructed
    assert res.config["a"] in (1, 3)
    with pytest.raises(ValueError, match="static vet"):
        autotuner.autotune(make_fn, [{"a": 2}], key=None, iters=1,
                           warmup_iters=0, vet=lambda c: "no")


def test_autotune_vet_blocks_stale_cached_winner(tmp_path, monkeypatch):
    """A persisted winner from a sweep that predates the vet (or a
    footprint-model fix) must be re-swept, not resurrected unvetted:
    the vet filters the candidate list BEFORE the cache consult, so
    the staleness membership check runs against the vetted list."""
    from triton_dist_tpu.tools import autotuner
    monkeypatch.setenv("TDT_AUTOTUNE_CACHE", str(tmp_path / "c.json"))
    built = []

    def make_fn(**cfg):
        built.append(dict(cfg))
        return lambda: None

    r1 = autotuner.autotune(make_fn, [{"a": 2}], key="stale-k",
                            iters=1, warmup_iters=0)
    assert r1.config == {"a": 2}
    autotuner.clear_cache()          # in-memory gone; disk hit remains
    built.clear()
    r2 = autotuner.autotune(
        make_fn, [{"a": 2}, {"a": 3}], key="stale-k", iters=1,
        warmup_iters=0,
        vet=lambda c: "over cap" if c["a"] == 2 else None)
    assert r2.config == {"a": 3}
    assert built == [{"a": 3}]       # the stale winner never compiled


def test_candidate_tables_fit_cap_all_worlds():
    assert avmem.sweep_candidate_tables() == []


def test_declared_footprint_agrees_with_config_generators():
    """The footprint model and the generators' feasibility filters are
    the same arithmetic: every candidate the generator emits (budget
    AND aggressive tiers) must score <= the hard cap the generator
    filters against."""
    from triton_dist_tpu.ops.allgather_gemm import ag_gemm_configs
    from triton_dist_tpu.ops.common import (DEFAULT_VMEM_BUDGET,
                                            HARD_FOOTPRINT_CAP)
    from triton_dist_tpu.tools.perf_model import declared_footprint
    m = k = n = 4096
    for world in (1, 2, 4, 8):
        rows, n_loc = m // world, n // world
        for cfg in ag_gemm_configs(m, rows, k, n_loc, 2,
                                   DEFAULT_VMEM_BUDGET):
            if cfg["variant"] == "hbm_kt":
                continue  # kt fallbacks are listed unconditionally
            fp = declared_footprint("ag_gemm", cfg, rows=rows, m=m,
                                    k=k, n_loc=n_loc, itemsize=2,
                                    world=world)
            assert fp <= HARD_FOOTPRINT_CAP, (world, cfg, fp)


# ---------------------------------------------------------------------------
# Seeded drift per lint class
# ---------------------------------------------------------------------------

def test_seeded_metric_drift(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text(textwrap.dedent("""
        import obs
        def f(op):
            obs.counter("totally.new_metric").inc()
            obs.gauge(f"comms.{op}.known_gauge").set(1)
    """))
    cat = tmp_path / "observability.md"
    cat.write_text(textwrap.dedent("""
        ## Metric catalog

        | metric | type | meaning |
        |---|---|---|
        | `comms.<op>.known_gauge` | gauge | fine |
        | `never.emitted_anywhere` | counter | stale |
    """))
    findings = lint_metrics.run(files=[src], catalog=cat)
    codes = {(f.code, f.line is not None and f.file is not None)
             for f in findings}
    assert ("lint.metric_undocumented", True) in codes
    assert ("lint.metric_dead", True) in codes
    assert len(findings) == 2 and exit_code(findings) != 0


def test_catalog_suffix_alternates_expand():
    """`x.a` / `.b` and `p50` / `_p99` style rows match both forms."""
    import pathlib
    cat = pathlib.Path(__file__).parents[1] / "docs" / "observability.md"
    pats = [p for _, cands in lint_metrics.catalog_patterns(cat)
            for p in cands]
    assert any(p.endswith("perfwatch.samples.xla") for p in pats)
    assert any(p.endswith("_p99_ms") and "rolling" in p for p in pats)


def test_seeded_env_drift(tmp_path, monkeypatch):
    src = tmp_path / "mod.py"
    src.write_text(textwrap.dedent("""
        import os
        def f():
            v = os.environ.get("TDT_TOTALLY_NEW_KNOB", "").strip()
            n = int(v) if v else 3
            direct = int(os.environ.get("TDT_MAX_WAITING", "64"))
            return n + direct
    """))
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "a.md").write_text("`TDT_MAX_WAITING` is documented.\n")
    findings = lint_env.run(files=[src], docs_dir=docs)
    by_code = {}
    for f in findings:
        by_code.setdefault(f.code, []).append(f)
    assert "lint.env_undocumented" in by_code
    assert "TDT_TOTALLY_NEW_KNOB" in by_code["lint.env_undocumented"][0].message
    # BOTH int-parse shapes fire: via tainted local AND direct
    knobs = {f.message.split()[4] for f in by_code["lint.env_int_parse"]}
    assert {"TDT_TOTALLY_NEW_KNOB", "TDT_MAX_WAITING"} <= knobs
    assert all(f.file and f.line for f in findings)
    assert exit_code(findings) != 0


def test_seeded_trace_imbalance(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text(textwrap.dedent("""
        from triton_dist_tpu.obs import trace
        def leaky():
            trace.begin("op.thing", "op")
            return 1   # no end
        def fine():
            trace.begin("op.other", "op")
            trace.end("op.other", "op")
        class Paired:
            def __enter__(self):
                trace.begin("op.paired", "op")
            def __exit__(self, *exc):
                trace.end("op.paired", "op")
    """))
    findings = lint_trace.run(files=[src])
    assert [f.code for f in findings] == ["lint.trace_unbalanced"]
    assert "leaky" in findings[0].message
    assert findings[0].file == str(src) and findings[0].line
    assert exit_code(findings) != 0


def test_seeded_fallback_drift():
    """Removing a DELEGATES entry re-exposes the contract violation,
    anchored at the delegate's def line in ops/."""
    delegates = dict(lint_fallback.DELEGATES)
    removed = delegates.pop("allgather_gemm.ag_gemm")
    assert removed == "ag_gemm"
    findings = lint_fallback.collect_findings(delegates=delegates)
    assert len(findings) == 1
    f = findings[0]
    assert f.code == "lint.fallback_uncovered"
    assert "allgather_gemm.ag_gemm" in f.message
    assert f.file.endswith("allgather_gemm.py") and f.line > 0
    assert exit_code(findings) != 0


# ---------------------------------------------------------------------------
# Suppression pragmas
# ---------------------------------------------------------------------------

def test_pragma_suppression(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text(
        "from triton_dist_tpu.obs import trace\n"
        "def hang_marker():\n"
        "    trace.begin('op.hang', 'op')"
        "  # tdt: ignore[lint.trace_unbalanced]\n")
    findings = filter_suppressed(lint_trace.run(files=[src]))
    assert findings == []
    # a pragma naming a DIFFERENT code does not suppress
    src.write_text(
        "from triton_dist_tpu.obs import trace\n"
        "def hang_marker():\n"
        "    trace.begin('op.hang', 'op')  # tdt: ignore[other.code]\n")
    assert len(filter_suppressed(lint_trace.run(files=[src]))) == 1
    # bare pragma suppresses anything
    src.write_text(
        "from triton_dist_tpu.obs import trace\n"
        "def hang_marker():\n"
        "    trace.begin('op.hang', 'op')  # tdt: ignore\n")
    assert filter_suppressed(lint_trace.run(files=[src])) == []


# ---------------------------------------------------------------------------
# Shim compatibility
# ---------------------------------------------------------------------------

def test_fallback_lint_shim_matches_pass():
    from triton_dist_tpu.tools import fallback_lint
    assert fallback_lint.missing_fallbacks() == [
        f.message for f in lint_fallback.collect_findings()]
    assert fallback_lint.DELEGATES is lint_fallback.DELEGATES
