"""Quick-tier CI gate for the whole-zoo protocol checkers (ISSUE 12).

Mirrors the mutation contract tests/test_tdt_check.py established for
the ring pass, now across the zoo:

- every new pass (a2a / p2p / flash-decode / protocol-coverage and
  the extended vmem comm-buffer sweep) is green on the repo for
  worlds 1..8 — with the a2a composed over call sequences 1..4 in
  BOTH buffering regimes — and the whole suite runs with zero Mosaic
  compiles (asserted by poisoning ``pallas_call``);
- each seeded mutant — dropped wait, doubled signal, swapped parity
  across calls, off-by-one merge contributor, unclaimed-semaphore
  kernel — produces its distinct finding code with a file:line anchor
  and a nonzero driver exit;
- the checkers execute the kernels' OWN schedule helpers (a bug
  injected there, not in the mirror, must surface);
- the ``--changed`` / comma-``--pass`` / ``--md-summary`` driver
  satellites behave.
"""

import json
import textwrap

import pytest

from triton_dist_tpu.analysis import (
    Finding, PASSES, exit_code, filter_suppressed, run_passes,
    select_passes_for, watch_match)
from triton_dist_tpu.analysis import a2a_model as am
from triton_dist_tpu.analysis import flash_model as fm
from triton_dist_tpu.analysis import lint_protocol as lp
from triton_dist_tpu.analysis import p2p_model as pm
from triton_dist_tpu.analysis import protocol_model as core
from triton_dist_tpu.analysis import vmem as avmem
from triton_dist_tpu.tools import tdt_check

NEW_PASSES = ("a2a-protocol", "p2p-protocol", "flash-decode-protocol",
              "protocol-coverage")


# ---------------------------------------------------------------------------
# The repo is clean under the new passes — and no Mosaic compile ever
# runs: the whole zoo is checked from Python.
# ---------------------------------------------------------------------------

def test_new_passes_registered_and_clean():
    for name in NEW_PASSES:
        assert name in PASSES, name
    findings = run_passes(names=list(NEW_PASSES) + ["vmem-budget"])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_zero_mosaic_compiles(monkeypatch):
    """The acceptance bar: the full pass suite never builds a kernel.
    Poison ``pallas_call`` — any compile attempt fails loudly."""
    from jax.experimental import pallas as pl

    def boom(*a, **k):   # pragma: no cover - must never run
        raise AssertionError("a static pass invoked pallas_call")

    monkeypatch.setattr(pl, "pallas_call", boom)
    assert run_passes() == []


@pytest.mark.parametrize("world", range(1, 9))
def test_a2a_every_counts_pattern_verifies(world):
    for pat, counts in am.counts_patterns(world).items():
        t = am.a2a_trace(world, counts, name=f"a2a[w{world} {pat}]")
        assert core.check_trace(t) == [], t.name
    t = am.a2a_trace(world, am.counts_patterns(world)["ragged"],
                     fp8_sideband=True)
    assert core.check_trace(t) == [], t.name


@pytest.mark.parametrize("world", [1, 2, 4, 5, 8])
@pytest.mark.parametrize("n_calls", [1, 2, 3, 4])
@pytest.mark.parametrize("buffering", ["fresh", "parity"])
def test_a2a_call_sequences_compose(world, n_calls, buffering):
    """Cross-call composition 1..4 verifies under BOTH regimes: the
    reference's call_count-parity re-expression AND the documented
    TPU collapse (fresh per-pallas_call semaphores,
    all_to_all.py:25-28)."""
    t = am.a2a_call_sequence(world, n_calls, buffering=buffering)
    assert core.check_trace(t) == [], t.name
    assert am.check_call_parity(t, buffering) == [], t.name


@pytest.mark.parametrize("world", range(1, 9))
def test_p2p_pipelines_verify(world):
    for deltas in pm.PIPELINES:
        t = pm.pipeline_trace(world, deltas)
        assert core.check_trace(t) == [], t.name


@pytest.mark.parametrize("world", range(1, 9))
def test_flash_combine_verifies(world):
    t = fm.combine_trace(world)
    assert core.check_trace(t) == [], t.name


# ---------------------------------------------------------------------------
# ...and each known-bad mutant is caught with the right class.
# ---------------------------------------------------------------------------

def _codes(trace):
    return {v.code for v in core.check_trace(trace)}


@pytest.mark.parametrize("world", [3, 4, 8])
def test_a2a_mutant_dropped_wait(world):
    t = am.a2a_trace(world, am.counts_patterns(world)["ragged"])
    codes = _codes(core.drop_first_wait(t, sem_kind="a2a"))
    assert "a2a.race" in codes, codes
    assert "a2a.signal_wait_imbalance" in codes


@pytest.mark.parametrize("world", [2, 5])
def test_a2a_mutant_doubled_signal(world):
    t = am.a2a_trace(world, am.counts_patterns(world)["full"])
    codes = _codes(core.double_signal(t, sem_kind="a2a"))
    assert codes == {"a2a.signal_wait_imbalance"}, codes


@pytest.mark.parametrize("world,call", [(4, 1), (8, 3)])
def test_a2a_mutant_swapped_parity_across_calls(world, call):
    """The double-buffer bug class: one call signals the OTHER
    buffer's slots. Distinct code, fires structurally even before the
    counting verdicts."""
    seq = am.a2a_call_sequence(world, 4, buffering="parity")
    mut = am.swap_call_parity(seq, call=call)
    parity = {v.code for v in am.check_call_parity(mut)}
    assert parity == {"a2a.call_parity"}, parity
    # the counting verdicts ALSO notice (receivers hang on the slot
    # that was never signalled)
    assert "a2a.deadlock" in _codes(mut)
    # ...and the unmutated sequence carries no parity violation
    assert am.check_call_parity(seq) == []


def test_a2a_mutant_fp8_sideband_dropped_wait():
    t = am.a2a_trace(4, am.counts_patterns(4)["ragged"],
                     fp8_sideband=True)
    codes = _codes(core.drop_first_wait(t, sem_kind="scale"))
    assert "a2a.race" in codes and "a2a.signal_wait_imbalance" in codes


def test_a2a_runs_real_schedule_code(monkeypatch):
    """The checker executes a2a_wait_src itself: a bug injected THERE
    (not in the mirror) must surface."""
    from triton_dist_tpu.ops import all_to_all as a2a_ops
    orig = a2a_ops.a2a_wait_src

    def broken(me, i, world):
        return orig(me, i + 1 if world > 2 else i, world)

    monkeypatch.setattr(a2a_ops, "a2a_wait_src", broken)
    for cache in (am._wait_order, am._send_order, am._live):
        cache.cache_clear()
    try:
        t = am.a2a_trace(4, am.counts_patterns(4)["full"])
        assert core.check_trace(t) != []
    finally:
        for cache in (am._wait_order, am._send_order, am._live):
            cache.cache_clear()


@pytest.mark.parametrize("world", [3, 5, 8])
def test_p2p_mutant_swapped_delta(world):
    t = pm.pipeline_trace(world, (1, -1))
    codes = _codes(pm.swap_delta(t, rank=0, stage=0))
    assert "p2p.signal_wait_imbalance" in codes, codes
    assert "p2p.deadlock" in codes


def test_p2p_mutant_dropped_wait():
    t = pm.pipeline_trace(4, (1,))
    codes = _codes(core.drop_first_wait(t, sem_kind="p2p"))
    assert "p2p.race" in codes and "p2p.signal_wait_imbalance" in codes


def test_p2p_runs_real_partner_code(monkeypatch):
    from triton_dist_tpu.ops import p2p as p2p_ops
    orig = p2p_ops.shift_partners

    def broken(me, delta, world):
        dst, src = orig(me, delta, world)
        return dst, dst   # wrong source partner

    monkeypatch.setattr(p2p_ops, "shift_partners", broken)
    pm._partners.cache_clear()
    try:
        t = pm.pipeline_trace(4, (1,))
        assert core.check_trace(t) != []
    finally:
        pm._partners.cache_clear()


@pytest.mark.parametrize("world", [3, 4, 8])
def test_flash_mutant_off_by_one_merge(world):
    """The silent-skew class: one contributor merged twice, another
    never — coverage exactly, no hang, no imbalance."""
    codes = _codes(fm.shift_merge_contributor(fm.combine_trace(world)))
    assert codes == {"flash.coverage"}, codes


def test_flash_mutant_dropped_wait_and_doubled_signal():
    t = fm.combine_trace(4)
    codes = _codes(core.drop_first_wait(t, sem_kind="fd"))
    assert "flash.race" in codes
    assert "flash.signal_wait_imbalance" in codes
    codes = _codes(core.double_signal(t, sem_kind="fd"))
    assert codes == {"flash.signal_wait_imbalance"}, codes


def test_mutants_exit_nonzero_with_anchor():
    """Acceptance shape: every zoo mutant → nonzero exit + file:line
    anchored at the kernel the trace mirrors."""
    cases = [
        (core.drop_first_wait(
            am.a2a_trace(4, am.counts_patterns(4)["full"]),
            sem_kind="a2a"), "all_to_all.py"),
        (am.swap_call_parity(
            am.a2a_call_sequence(4, 2, buffering="parity"), call=1),
         "all_to_all.py"),
        (pm.swap_delta(pm.pipeline_trace(4, (1,))), "p2p.py"),
        (fm.shift_merge_contributor(fm.combine_trace(4)),
         "flash_decode.py"),
    ]
    for trace, src in cases:
        viols = core.check_trace(trace)
        if trace.code_prefix == "a2a":
            viols = viols + am.check_call_parity(trace)
        findings = [Finding(code=v.code, message=v.detail,
                            file=trace.anchor[0], line=trace.anchor[1])
                    for v in viols]
        assert exit_code(findings) != 0, trace.name
        assert findings[0].file and findings[0].file.endswith(src)
        assert findings[0].line and findings[0].line > 0


def test_moe_rs_footprint_helpers_agree_with_entry():
    """The static vet prices the kernel's REAL tiling: the resolve
    helper reproduces the entry's clamp (budget-shrunk h_blk, floor
    128) and the footprint at the resolved block fits the budget
    whenever a >=128 block can."""
    from triton_dist_tpu.ops.moe_reduce_rs import (
        moe_rs_fused_footprint, moe_rs_resolve_h_blk)
    h_blk = moe_rs_resolve_h_blk(4096, 512, 128, 4096, 2048, 2,
                                 12 * 2**20)
    assert h_blk == 256     # 512 over budget; 256 lands exactly on it
    assert moe_rs_fused_footprint(128, 4096, h_blk, 2048, 2) \
        <= 12 * 2**20
    assert moe_rs_fused_footprint(128, 4096, 512, 2048, 2) \
        > 12 * 2**20
    # divisibility clamp: block_h that doesn't divide h halves first
    assert moe_rs_resolve_h_blk(384, 512, 128, 64, 64, 2,
                                12 * 2**20) == 128


def test_comm_buffer_sweep_clean_and_over_budget_mutant():
    assert avmem.sweep_comm_buffers() == []
    # an oversized slab config is refused statically, anchored at the
    # op's own config site (AllToAllContext), no compile
    f = avmem.vet_candidate("all_to_all",
                            {"capacity": 512, "h": 7168},
                            rows=0, itemsize=2, world=8)
    assert f is not None and f.code == "vmem.over_budget"
    assert f.file.endswith("all_to_all.py") and f.line > 0
    assert exit_code([f]) != 0
    # and an over-cap MoE-RS scratch (huge selection tiles)
    f = avmem.vet_candidate(
        "moe_reduce_rs",
        {"h": 4096, "i_loc": 4096, "block_m": 1024, "block_h": 512,
         "vmem_budget": 12 * 2**20},
        rows=8192, itemsize=2, world=1)
    assert f is not None and f.code == "vmem.over_budget"
    assert f.file.endswith("moe_reduce_rs.py") and f.line > 0


# ---------------------------------------------------------------------------
# protocol-coverage meta-lint
# ---------------------------------------------------------------------------

def _ops_dir(tmp_path, body):
    d = tmp_path / "ops"
    d.mkdir()
    (d / "__init__.py").write_text("")
    (d / "new_comm.py").write_text(textwrap.dedent(body))
    return d


SEM_KERNEL = """
    from jax.experimental.pallas import tpu as pltpu
    import triton_dist_tpu.language as dl

    def _kernel(x_ref, o_ref, send_sem, recv_sem):
        dl.remote_copy(x_ref, o_ref, 1, send_sem, recv_sem).start()

    SCRATCH = [pltpu.SemaphoreType.DMA((2,))]
"""


def test_unclaimed_semaphore_kernel_fires(tmp_path):
    d = _ops_dir(tmp_path, SEM_KERNEL)
    findings = lp.collect_findings(ops_dir=d, claims={}, backlog={},
                                   passes=PASSES)
    assert len(findings) == 1
    f = findings[0]
    assert f.code == "protocol.unclaimed_semaphore"
    assert f.file.endswith("new_comm.py") and f.line > 0
    assert "remote_copy" in f.message
    assert exit_code(findings) != 0


def test_claiming_a_registered_pass_clears(tmp_path):
    d = _ops_dir(tmp_path, SEM_KERNEL)
    assert lp.collect_findings(
        ops_dir=d, claims={"new_comm.py": "a2a-protocol"}, backlog={},
        passes=PASSES) == []
    # a backlog entry also silences — explicit, rationale'd debt
    assert lp.collect_findings(
        ops_dir=d, claims={}, backlog={"new_comm.py": "pending"},
        passes=PASSES) == []


def test_claim_naming_unregistered_pass_fires(tmp_path):
    d = _ops_dir(tmp_path, SEM_KERNEL)
    findings = lp.collect_findings(
        ops_dir=d, claims={"new_comm.py": "no-such-pass"}, backlog={},
        passes=PASSES)
    assert [f.code for f in findings] == ["protocol.unknown_pass"]


def test_stale_claim_fires_both_shapes(tmp_path):
    d = tmp_path / "ops"
    d.mkdir()
    (d / "__init__.py").write_text("")
    (d / "pure_math.py").write_text("def f(x):\n    return x + 1\n")
    findings = lp.collect_findings(
        ops_dir=d,
        claims={"pure_math.py": "a2a-protocol",
                "deleted_module.py": "a2a-protocol"},
        backlog={}, passes=PASSES)
    assert sorted(f.code for f in findings) == \
        ["protocol.stale_claim", "protocol.stale_claim"]


def test_docstring_mentions_do_not_count(tmp_path):
    d = _ops_dir(tmp_path, '''
    """This module merely DOCUMENTS pltpu.semaphore_signal and
    make_async_remote_copy usage elsewhere."""
    def f():
        return 0
    ''')
    assert lp.collect_findings(ops_dir=d, claims={}, backlog={},
                               passes=PASSES) == []


def test_unclaimed_finding_pragma_suppression(tmp_path):
    body = SEM_KERNEL.replace(
        "from jax.experimental.pallas import tpu as pltpu",
        "from jax.experimental.pallas import tpu as pltpu"
        "  # tdt: ignore[protocol.unclaimed_semaphore]")
    d = _ops_dir(tmp_path, body)
    findings = lp.collect_findings(ops_dir=d, claims={}, backlog={},
                                   passes=PASSES)
    # the finding anchors at the first primitive usage line, which is
    # the remote_copy call — a pragma elsewhere must NOT suppress
    assert len(filter_suppressed(findings)) == 1
    src = (d / "new_comm.py").read_text().splitlines()
    anchored = findings[0].line
    patched = "\n".join(
        line + "  # tdt: ignore[protocol.unclaimed_semaphore]"
        if i + 1 == anchored else line
        for i, line in enumerate(src))
    (d / "new_comm.py").write_text(patched + "\n")
    findings = lp.collect_findings(ops_dir=d, claims={}, backlog={},
                                   passes=PASSES)
    assert filter_suppressed(findings) == []


def test_repo_claims_are_wellformed():
    """Every CLAIMS entry names a registered pass; claim and backlog
    sets are disjoint; the three new kernels are claimed by the three
    new passes."""
    assert set(lp.CLAIMS) & set(lp.BACKLOG) == set()
    for mod, pass_name in lp.CLAIMS.items():
        assert pass_name in PASSES, (mod, pass_name)
    assert lp.CLAIMS["all_to_all.py"] == "a2a-protocol"
    assert lp.CLAIMS["p2p.py"] == "p2p-protocol"
    assert lp.CLAIMS["flash_decode.py"] == "flash-decode-protocol"


# ---------------------------------------------------------------------------
# Driver satellites: --pass comma lists, --changed, --md-summary
# ---------------------------------------------------------------------------

def test_driver_pass_comma_list(capsys):
    rc = tdt_check.main(
        ["--pass", "p2p-protocol,flash-decode-protocol",
         "--pass", "protocol-coverage", "--json"])
    assert rc == 0
    out = capsys.readouterr().out
    assert json.loads(out[out.index("{"):])["errors"] == 0


def test_watch_match_shapes():
    assert watch_match("triton_dist_tpu/ops/p2p.py",
                       "triton_dist_tpu/ops/p2p.py")
    assert watch_match("triton_dist_tpu/ops/new_kernel.py",
                       "triton_dist_tpu/ops/")
    assert not watch_match("docs/perf.md", "triton_dist_tpu/ops/")
    assert watch_match("docs/perf.md", "docs/*.md")


def test_select_passes_for_changed_files():
    names = select_passes_for(["triton_dist_tpu/ops/p2p.py"])
    assert "p2p-protocol" in names
    assert "protocol-coverage" in names      # watches all of ops/
    assert "a2a-protocol" not in names
    assert "ring-protocol" not in names
    # the shared core re-triggers every protocol pass
    names = select_passes_for(
        ["triton_dist_tpu/analysis/protocol_model.py"])
    for n in ("ring-protocol", "a2a-protocol", "p2p-protocol",
              "flash-decode-protocol"):
        assert n in names
    assert select_passes_for([]) == []
    assert select_passes_for(["README.md"]) == []


def test_select_passes_fleet_watches():
    """ISSUE 14 satellite: editing the fleet plane or its dashboard
    re-runs BOTH the metric-catalog pass (new fleet.* /
    serving.replica_* emissions must stay cataloged) and the
    annotation-coverage pass (a fleet-plane edit that touched the
    pump's read path must re-verify the device.step labels) under
    ``--changed``."""
    for path in ("triton_dist_tpu/obs/fleet.py",
                 "triton_dist_tpu/tools/fleet_top.py"):
        names = select_passes_for([path])
        assert "metric-catalog" in names, path
        assert "annotation-coverage" in names, path
        assert "ring-protocol" not in names, path


def test_select_passes_history_watches():
    """ISSUE 16 satellite: editing the history plane re-runs BOTH the
    metric-catalog pass (history.* emissions must stay cataloged) and
    the annotation-coverage pass (the sampler lives inside the pump's
    lifecycle) under ``--changed``."""
    names = select_passes_for(["triton_dist_tpu/obs/history.py"])
    assert "metric-catalog" in names
    assert "annotation-coverage" in names
    assert "ring-protocol" not in names


def test_driver_changed_scopes_to_diff(monkeypatch, capsys):
    monkeypatch.setattr(tdt_check, "changed_files",
                        lambda root=None: ["triton_dist_tpu/ops/p2p.py"])
    rc = tdt_check.main(["--changed"])
    assert rc == 0
    cap = capsys.readouterr()
    # status prose goes to STDERR so `--changed --json` output stays
    # machine-parseable
    assert "p2p-protocol" not in cap.err.split("skipped:")[-1]
    assert "ring-protocol" in cap.err.split("skipped:")[-1]
    assert "skipped" not in cap.out
    # nothing changed -> nothing to run, still exit 0 AND the output
    # contract holds (valid JSON, summary still written)
    monkeypatch.setattr(tdt_check, "changed_files",
                        lambda root=None: [])
    rc = tdt_check.main(["--changed", "--json"])
    assert rc == 0
    cap = capsys.readouterr()
    assert "no watched files changed" in cap.err
    assert json.loads(cap.out)["errors"] == 0


def test_driver_changed_excludes_explicit_pass(capsys):
    with pytest.raises(SystemExit):
        tdt_check.main(["--changed", "--pass", "ring-protocol"])
    capsys.readouterr()


def test_driver_md_summary(tmp_path, capsys):
    path = tmp_path / "summary.md"
    rc = tdt_check.main(["--pass", "protocol-coverage",
                         "--md-summary", str(path)])
    capsys.readouterr()
    assert rc == 0
    text = path.read_text()
    assert "## tdt-check" in text and "OK" in text
    # a red run renders the finding-code table
    f = Finding(code="a2a.call_parity", message="boom | pipe",
                file="x.py", line=3)
    md = tdt_check.render_md([f], n_passes=1)
    assert "| `a2a.call_parity` | error | `x.py:3` |" in md
    assert "\\|" in md


def test_fallback_shim_deprecation_warning():
    from triton_dist_tpu.tools import fallback_lint
    with pytest.warns(DeprecationWarning,
                      match="fallback-coverage"):
        assert fallback_lint.missing_fallbacks() == []
