"""Tests for the load-bearing hardware-window machinery: bench.py's
part orchestrator (abandon-don't-kill, stop-after-timeout, reason
labeling) and scripts/hw_watch.py's queue logic (retry-once,
evidence-commit cadence). These paths decide whether a rare tunnel
window yields evidence; they must not be exercised for the first time
ON the window."""

import importlib.util
import json
import pathlib

_ROOT = pathlib.Path(__file__).resolve().parent.parent


def _load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- bench orchestrator ------------------------------------------------------

def _run_children(monkeypatch, tmp_path, parts, deadlines, child_behavior):
    """Drive _run_parts_in_children with a stubbed child process."""
    # bench.py's import-time env defaults (compile cache dir, traceback
    # filtering) must not leak past this test (review r5j-3).
    for key in ("JAX_COMPILATION_CACHE_DIR", "JAX_TRACEBACK_FILTERING",
                "TDT_AUTOTUNE_CACHE"):
        monkeypatch.setenv(key, __import__("os").environ.get(key) or "")
    bench = _load("bench_t", _ROOT / "bench.py")
    monkeypatch.setenv("TDT_BENCH_PARTS", ",".join(parts))
    monkeypatch.setenv("TDT_BENCH_PROGRESS", str(tmp_path / "p.json"))
    monkeypatch.setattr(bench, "_PART_DEADLINE_S", deadlines)
    monkeypatch.setattr(bench, "_PART_DEADLINE_DEFAULT_S", 0.5)
    # Generous wall budget so only per-part deadlines matter.
    monkeypatch.setenv("TDT_BENCH_BUDGET_S", "600")
    bench._T0 = __import__("time").monotonic()

    class FakeChild:
        def __init__(self, name, tmp_progress):
            self.behavior = child_behavior(name)
            self.returncode = None
            if self.behavior == "ok":
                # A real child checkpoints metrics; emulate that.
                with open(tmp_progress, "w") as f:
                    json.dump({"ts": 1.0, "extras":
                               {f"{name}_pallas_ms": 1.0}}, f)

        def poll(self):
            if self.behavior == "ok":
                self.returncode = 0
                return 0
            return None  # hung forever

    import subprocess as sp

    def fake_popen(argv, env=None, **kw):
        name = env["TDT_BENCH_ONLY"]
        return FakeChild(name, env["TDT_BENCH_PROGRESS"])
    # bench imports subprocess inside the function, so patching the
    # global module object covers it; monkeypatch undoes on teardown.
    monkeypatch.setattr(sp, "Popen", fake_popen)
    extras = {}
    bench._run_parts_in_children(extras)
    return extras


def test_orchestrator_abandons_and_stops_with_reason(monkeypatch, tmp_path):
    """A part that blows its deadline is ABANDONED (never killed), the
    run stops there, and the reason says possible_wedge — while
    already-completed parts keep their metrics."""
    extras = _run_children(
        monkeypatch, tmp_path,
        parts=["ag_gemm", "gemm_rs", "gemm_ar"],
        deadlines={"gemm_rs": 0.5},
        child_behavior=lambda n: "ok" if n == "ag_gemm" else "hang")
    assert "ag_gemm_pallas_ms" in extras            # completed part kept
    assert extras["gemm_rs_timeout_s"] == 0         # round(0.5)
    assert extras["aborted_after"] == "gemm_rs"
    assert extras["aborted_reason"] == "possible_wedge"
    assert "gemm_ar_pallas_ms" not in extras        # never spawned


def test_orchestrator_completes_all_when_children_finish(monkeypatch,
                                                         tmp_path):
    extras = _run_children(
        monkeypatch, tmp_path,
        parts=["ag_gemm", "gemm_rs"],
        deadlines={},
        child_behavior=lambda n: "ok")
    assert "ag_gemm_pallas_ms" in extras and "gemm_rs_pallas_ms" in extras
    assert "aborted_after" not in extras


# -- watcher queue -----------------------------------------------------------

def _load_watch():
    return _load("hw_watch_t", _ROOT / "scripts" / "hw_watch.py")


def test_watcher_retries_abandoned_step_once(monkeypatch, tmp_path):
    """An abandoned step is re-queued exactly once at the back; the
    queue still drains; evidence is committed after every step."""
    w = _load_watch()
    monkeypatch.setattr(w, "LOG", str(tmp_path / "log"))
    events = []
    monkeypatch.setattr(w, "probe", lambda *a, **k: True)
    monkeypatch.setattr(w, "commit_evidence",
                        lambda: events.append("commit"))
    monkeypatch.setattr(w.time, "sleep", lambda s: None)

    fail_once = {"s2": 1}

    def fake_run_step(name, argv, deadline, env):
        events.append(name)
        if fail_once.get(name, 0):
            fail_once[name] -= 1
            return "abandoned"
        return "done"
    monkeypatch.setattr(w, "run_step", fake_run_step)
    monkeypatch.setattr(
        w, "QUEUE", [("s1", [], 1.0, {}), ("s2", [], 1.0, {}),
                     ("s3", [], 1.0, {})])
    monkeypatch.setattr(w, "ROOT", str(tmp_path))
    w.main()
    steps = [e for e in events if e != "commit"]
    assert steps == ["s1", "s2", "s3", "s2"]        # retried once, at back
    # evidence committed after every step + once at drain
    assert events.count("commit") == len(steps) + 1


def test_watcher_waits_out_wedge_between_steps(monkeypatch, tmp_path):
    """A wedged probe never consumes a queue step."""
    w = _load_watch()
    monkeypatch.setattr(w, "LOG", str(tmp_path / "log"))
    probes = iter([False, False, True, True])
    monkeypatch.setattr(w, "probe", lambda *a, **k: next(probes))
    sleeps = []
    monkeypatch.setattr(w.time, "sleep", lambda s: sleeps.append(s))
    ran = []
    monkeypatch.setattr(w, "run_step",
                        lambda n, *a: (ran.append(n), "done")[1])
    monkeypatch.setattr(w, "commit_evidence", lambda: None)
    monkeypatch.setattr(w, "QUEUE", [("a", [], 1.0, {}), ("b", [], 1.0, {})])
    monkeypatch.setattr(w, "ROOT", str(tmp_path))
    w.main()
    assert ran == ["a", "b"]
    assert sleeps.count(300.0) == 2                 # two wedged probes
