"""Stress tests: randomized shapes/data looped over the fused kernels to
catch synchronization bugs (reference test/stress/stress_test_ag_gemm.py,
SURVEY.md §4 — sync bugs show up as run-to-run nondeterminism or stale
reads, which randomized re-runs flush out)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow

from triton_dist_tpu.ops.allgather_gemm import (
    create_ag_gemm_context, ag_gemm)
from triton_dist_tpu.ops.gemm_reduce_scatter import (
    create_gemm_rs_context, gemm_rs)
from triton_dist_tpu.ops.all_to_all import (
    create_all_to_all_context, fast_all_to_all)

WORLD = 8


def test_stress_ag_gemm_random_shapes(mesh8):
    rng = np.random.RandomState(0)
    ctx = create_ag_gemm_context(mesh8, "tp")
    for it in range(4):
        m = WORLD * int(rng.choice([1, 2, 4]))
        k = int(rng.choice([32, 64]))
        n = WORLD * int(rng.choice([8, 16]))
        a = jax.device_put(
            jnp.asarray(rng.randn(m, k), jnp.float32),
            NamedSharding(mesh8, P("tp")))
        b = jax.device_put(
            jnp.asarray(rng.randn(k, n), jnp.float32),
            NamedSharding(mesh8, P(None, "tp")))
        fused = ag_gemm(a, b, ctx, impl="pallas")
        gold = ag_gemm(a, b, ctx, impl="xla")
        np.testing.assert_allclose(np.asarray(fused), np.asarray(gold),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"iter {it} m={m} k={k} n={n}")


def test_stress_gemm_rs_repeat(mesh8):
    """Same shape re-run with fresh data — stale-signal bugs reproduce as
    one iteration reading the previous iteration's tiles."""
    rng = np.random.RandomState(1)
    ctx = create_gemm_rs_context(mesh8, "tp")
    m, k, n = 16, 64, 32
    for it in range(4):
        a = jax.device_put(jnp.asarray(rng.randn(m, k), jnp.float32),
                           NamedSharding(mesh8, P(None, "tp")))
        b = jax.device_put(jnp.asarray(rng.randn(k, n), jnp.float32),
                           NamedSharding(mesh8, P("tp", None)))
        fused = gemm_rs(a, b, ctx, impl="pallas")
        gold = gemm_rs(a, b, ctx, impl="xla")
        np.testing.assert_allclose(np.asarray(fused), np.asarray(gold),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"iter {it}")


def test_stress_a2a_random_counts(mesh8):
    """Randomized live-row counts exercise the chunked-send guards."""
    rng = np.random.RandomState(2)
    cap, h = 16, 64
    ctx = create_all_to_all_context(mesh8, "tp", capacity=cap)
    for it in range(3):
        buf = jnp.asarray(rng.randn(WORLD * WORLD, cap, h), jnp.float32)
        counts = jnp.asarray(
            rng.randint(0, cap + 1, size=WORLD * WORLD), jnp.int32)
        bufs = jax.device_put(buf, NamedSharding(mesh8, P("tp")))
        cnts = jax.device_put(counts, NamedSharding(mesh8, P("tp")))
        rp, cp = fast_all_to_all(bufs, cnts, ctx, impl="pallas")
        rx, cx = fast_all_to_all(bufs, cnts, ctx, impl="xla")
        np.testing.assert_array_equal(np.asarray(cp), np.asarray(cx))
        rp, rx = np.asarray(rp), np.asarray(rx)
        cx = np.asarray(cx).reshape(WORLD, WORLD)
        for dst in range(WORLD):
            for src in range(WORLD):
                nlive = cx[dst, src]
                np.testing.assert_array_equal(
                    rp.reshape(WORLD, WORLD, cap, h)[dst, src, :nlive],
                    rx.reshape(WORLD, WORLD, cap, h)[dst, src, :nlive],
                    err_msg=f"iter {it} dst={dst} src={src}")


def test_stress_injection_options_accepted(mesh8):
    """for_correctness noise + straggler options must be accepted by
    AG / AG-GEMM / A2A and leave results exact (VERDICT r2 next 8;
    reference for_correctness allgather.py:74-79, stress_test_ag_gemm).
    In interpret mode the delays are no-ops (pl.delay is a hardware
    spin); tpu_smoke runs the same options compiled on the chip where
    they really skew the rank schedule."""
    from triton_dist_tpu.ops.allgather import (
        AllGatherMethod, create_allgather_context, all_gather)
    rng = np.random.RandomState(7)
    x = jax.device_put(jnp.asarray(rng.randn(WORLD * 4, 128), jnp.float32),
                       NamedSharding(mesh8, P("tp")))
    ctx = create_allgather_context(mesh8, "tp",
                                   method=AllGatherMethod.RING_BIDIR)
    ctx.straggler_option = (3, 2000)
    ctx.for_correctness = True
    got = all_gather(x, ctx, impl="pallas")
    np.testing.assert_allclose(np.asarray(got), np.asarray(x), rtol=0,
                               atol=0)

    agctx = create_ag_gemm_context(mesh8, "tp")
    agctx.straggler_option = (1, 2000)
    agctx.for_correctness = True
    a = jax.device_put(jnp.asarray(rng.randn(WORLD * 2, 64), jnp.float32),
                       NamedSharding(mesh8, P("tp")))
    b = jax.device_put(jnp.asarray(rng.randn(64, WORLD * 16), jnp.float32),
                       NamedSharding(mesh8, P(None, "tp")))
    fused = ag_gemm(a, b, agctx, impl="pallas")
    gold = ag_gemm(a, b, agctx, impl="xla")
    np.testing.assert_allclose(np.asarray(fused), np.asarray(gold),
                               rtol=1e-4, atol=1e-4)

    a2actx = create_all_to_all_context(mesh8, "tp", capacity=16)
    a2actx.straggler_option = (5, 2000)
    a2actx.for_correctness = True
    send = jax.device_put(
        jnp.asarray(rng.randn(WORLD * WORLD, 16, 128), jnp.float32),
        NamedSharding(mesh8, P("tp")))
    counts = jax.device_put(
        jnp.full((WORLD * WORLD,), 8, jnp.int32),
        NamedSharding(mesh8, P("tp")))
    got_buf, got_counts = fast_all_to_all(send, counts, a2actx,
                                          impl="pallas")
    ref_buf, ref_counts = fast_all_to_all(send, counts, a2actx,
                                          impl="xla")
    np.testing.assert_array_equal(np.asarray(got_counts),
                                  np.asarray(ref_counts))
    # Compare only live rows (capacity slabs beyond counts are garbage).
    gb = np.asarray(got_buf).reshape(WORLD, WORLD, 16, 128)
    rb = np.asarray(ref_buf).reshape(WORLD, WORLD, 16, 128)
    np.testing.assert_allclose(gb[:, :, :8], rb[:, :, :8], rtol=1e-5,
                               atol=1e-5)


def test_stress_flash_decode_random_kv_lens(mesh8):
    """Randomized PER-SEQUENCE kv lengths over the tiled split-KV decode
    (the reference's kv_length_ptr parity): boundary tiles (len not a
    t_blk multiple, len < one block, len == cache) all in one batch."""
    from triton_dist_tpu.ops.flash_decode import (
        create_flash_decode_context, gqa_fwd_batch_decode)
    rng = np.random.RandomState(11)
    b, hq, hkv, d, t = 4, 8, 2, 32, 128
    ctx = create_flash_decode_context(mesh8, "tp", variant="tiled",
                                      t_blk=32)
    for it in range(3):
        q = jnp.asarray(rng.randn(b, hq, d), jnp.float32)
        kc = jax.device_put(jnp.asarray(rng.randn(b, t, hkv, d),
                                        jnp.float32),
                            NamedSharding(mesh8, P(None, "tp")))
        vc = jax.device_put(jnp.asarray(rng.randn(b, t, hkv, d),
                                        jnp.float32),
                            NamedSharding(mesh8, P(None, "tp")))
        lens = jnp.asarray(
            [int(rng.randint(1, t + 1)) for _ in range(b)], jnp.int32)
        out = gqa_fwd_batch_decode(q, kc, vc, lens, ctx, impl="pallas")
        ref = gqa_fwd_batch_decode(q, kc, vc, lens, ctx, impl="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"iter {it} lens={lens}")


def test_stress_sp_attention_random_seq(mesh8):
    """Randomized sequence lengths through the ring SP attention
    (causal): the rotation/mask bookkeeping must hold at every s."""
    from triton_dist_tpu.ops.sp_attention import (
        create_sp_attention_context, sp_ag_attention)
    rng = np.random.RandomState(12)
    ctx = create_sp_attention_context(mesh8, "tp", causal=True)
    for it in range(3):
        s = WORLD * int(rng.choice([2, 4, 8]))
        b, hq, hkv, d = 2, 4, 2, 16
        sh = NamedSharding(mesh8, P(None, "tp"))
        q = jax.device_put(jnp.asarray(rng.randn(b, s, hq, d),
                                       jnp.float32), sh)
        k = jax.device_put(jnp.asarray(rng.randn(b, s, hkv, d),
                                       jnp.float32), sh)
        v = jax.device_put(jnp.asarray(rng.randn(b, s, hkv, d),
                                       jnp.float32), sh)
        out = sp_ag_attention(q, k, v, ctx, impl="ring")
        ref = sp_ag_attention(q, k, v, ctx, impl="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"iter {it} s={s}")
