"""Stress tests: randomized shapes/data looped over the fused kernels to
catch synchronization bugs (reference test/stress/stress_test_ag_gemm.py,
SURVEY.md §4 — sync bugs show up as run-to-run nondeterminism or stale
reads, which randomized re-runs flush out)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.ops.allgather_gemm import (
    create_ag_gemm_context, ag_gemm)
from triton_dist_tpu.ops.gemm_reduce_scatter import (
    create_gemm_rs_context, gemm_rs)
from triton_dist_tpu.ops.all_to_all import (
    create_all_to_all_context, fast_all_to_all)

WORLD = 8


def test_stress_ag_gemm_random_shapes(mesh8):
    rng = np.random.RandomState(0)
    ctx = create_ag_gemm_context(mesh8, "tp")
    for it in range(4):
        m = WORLD * int(rng.choice([1, 2, 4]))
        k = int(rng.choice([32, 64]))
        n = WORLD * int(rng.choice([8, 16]))
        a = jax.device_put(
            jnp.asarray(rng.randn(m, k), jnp.float32),
            NamedSharding(mesh8, P("tp")))
        b = jax.device_put(
            jnp.asarray(rng.randn(k, n), jnp.float32),
            NamedSharding(mesh8, P(None, "tp")))
        fused = ag_gemm(a, b, ctx, impl="pallas")
        gold = ag_gemm(a, b, ctx, impl="xla")
        np.testing.assert_allclose(np.asarray(fused), np.asarray(gold),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"iter {it} m={m} k={k} n={n}")


def test_stress_gemm_rs_repeat(mesh8):
    """Same shape re-run with fresh data — stale-signal bugs reproduce as
    one iteration reading the previous iteration's tiles."""
    rng = np.random.RandomState(1)
    ctx = create_gemm_rs_context(mesh8, "tp")
    m, k, n = 16, 64, 32
    for it in range(4):
        a = jax.device_put(jnp.asarray(rng.randn(m, k), jnp.float32),
                           NamedSharding(mesh8, P(None, "tp")))
        b = jax.device_put(jnp.asarray(rng.randn(k, n), jnp.float32),
                           NamedSharding(mesh8, P("tp", None)))
        fused = gemm_rs(a, b, ctx, impl="pallas")
        gold = gemm_rs(a, b, ctx, impl="xla")
        np.testing.assert_allclose(np.asarray(fused), np.asarray(gold),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"iter {it}")


def test_stress_a2a_random_counts(mesh8):
    """Randomized live-row counts exercise the chunked-send guards."""
    rng = np.random.RandomState(2)
    cap, h = 16, 64
    ctx = create_all_to_all_context(mesh8, "tp", capacity=cap)
    for it in range(3):
        buf = jnp.asarray(rng.randn(WORLD * WORLD, cap, h), jnp.float32)
        counts = jnp.asarray(
            rng.randint(0, cap + 1, size=WORLD * WORLD), jnp.int32)
        bufs = jax.device_put(buf, NamedSharding(mesh8, P("tp")))
        cnts = jax.device_put(counts, NamedSharding(mesh8, P("tp")))
        rp, cp = fast_all_to_all(bufs, cnts, ctx, impl="pallas")
        rx, cx = fast_all_to_all(bufs, cnts, ctx, impl="xla")
        np.testing.assert_array_equal(np.asarray(cp), np.asarray(cx))
        rp, rx = np.asarray(rp), np.asarray(rx)
        cx = np.asarray(cx).reshape(WORLD, WORLD)
        for dst in range(WORLD):
            for src in range(WORLD):
                nlive = cx[dst, src]
                np.testing.assert_array_equal(
                    rp.reshape(WORLD, WORLD, cap, h)[dst, src, :nlive],
                    rx.reshape(WORLD, WORLD, cap, h)[dst, src, :nlive],
                    err_msg=f"iter {it} dst={dst} src={src}")
