"""Model + engine e2e tests (reference test_tp_e2e.py — full Qwen3 fwd vs
torch eager with --check, test_e2e_inference.py (Engine),
test_ep_moe_inference.py; SURVEY.md §4) on the 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.models import (
    AutoLLM, DenseLLM, Engine, ModelConfig, Qwen3MoE)
from triton_dist_tpu.models.kv_cache import KVCacheManager

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow


def tiny_dense_cfg():
    return ModelConfig(hidden_size=64, intermediate_size=128,
                       num_hidden_layers=2, num_attention_heads=8,
                       num_key_value_heads=8, head_dim=8, vocab_size=128,
                       max_position_embeddings=64, dtype=jnp.float32)


def tiny_moe_cfg():
    return ModelConfig(hidden_size=64, moe_intermediate_size=64,
                       num_hidden_layers=2, num_attention_heads=8,
                       num_key_value_heads=8, head_dim=8, vocab_size=128,
                       max_position_embeddings=64, dtype=jnp.float32,
                       num_experts=8, num_experts_per_tok=2,
                       intermediate_size=0)


@pytest.fixture()
def dense(mesh8):
    return DenseLLM(tiny_dense_cfg(), mesh=mesh8, axis="tp")


def _caches(model, b, t):
    c = model.config
    kv = KVCacheManager(c.num_hidden_layers, b, t, c.num_key_value_heads,
                        c.head_dim, mesh=model.mesh, axis=model.axis,
                        dtype=c.dtype)
    return kv.init()


def test_dense_modes_agree(dense, key):
    b, s, t = 2, 4, 16
    params = dense.init(key)
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                             dense.config.vocab_size, jnp.int32)
    ref, _ = dense.forward(params, ids, _caches(dense, b, t), 0,
                           mode="xla_ar")
    for mode in ("xla", "ag_rs", "gemm_ar"):
        out, _ = dense.forward(params, ids, _caches(dense, b, t), 0,
                               mode=mode)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3, err_msg=mode)


def test_dense_decode_matches_prefill(dense, key):
    """Greedy decode step must match the last-position logits of a longer
    prefill (KV-cache correctness across modes)."""
    b, s, t = 2, 4, 16
    params = dense.init(key)
    ids = jax.random.randint(jax.random.PRNGKey(2), (b, s + 1), 0,
                             dense.config.vocab_size, jnp.int32)
    # full prefill of s+1 tokens
    full, _ = dense.forward(params, ids, _caches(dense, b, t), 0,
                            mode="xla_ar")
    # prefill s, then decode token s
    caches = _caches(dense, b, t)
    _, caches = dense.forward(params, ids[:, :s], caches, 0, mode="xla_ar")
    dec, _ = dense.forward(params, ids[:, s:], caches, s, mode="gemm_ar")
    np.testing.assert_allclose(np.asarray(dec[:, 0]),
                               np.asarray(full[:, -1]), rtol=2e-3,
                               atol=2e-3)


def test_moe_modes_agree(mesh8, key):
    b, s, t = 2, 4, 16
    model = Qwen3MoE(tiny_moe_cfg(), mesh=mesh8, axis="tp")
    params = model.init(key)
    ids = jax.random.randint(jax.random.PRNGKey(3), (b, s), 0,
                             model.config.vocab_size, jnp.int32)
    ref, _ = model.forward(params, ids, _caches(model, b, t), 0, mode="xla")
    out, _ = model.forward(params, ids, _caches(model, b, t), 0,
                           mode="ag_rs")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=3e-3,
                               atol=3e-3)


def test_engine_serve_greedy(dense, key):
    b, s, gen = 2, 4, 3
    params = dense.init(key)
    ids = jax.random.randint(jax.random.PRNGKey(4), (b, s), 0,
                             dense.config.vocab_size, jnp.int32)
    eng = Engine(dense, batch=b, max_seq=16, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    out = eng.serve(params, ids, gen)
    assert out.shape == (b, s + gen)
    # deterministic greedy
    out2 = Engine(dense, batch=b, max_seq=16, prefill_mode="xla_ar",
                  decode_mode="gemm_ar").serve(params, ids, gen)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    # tokens after prompt must match a teacher-forced forward over the
    # generated prefix (greedy consistency)
    full, _ = dense.forward(params, out[:, :-1],
                            _caches(dense, b, 16), 0, mode="xla_ar")
    expect = np.argmax(np.asarray(full)[:, s - 1:], axis=-1)
    np.testing.assert_array_equal(np.asarray(out[:, s:]), expect)


def test_hf_state_dict_load(mesh8):
    """HF-name-mapped weights drive the same forward as directly-built
    params (mapping correctness incl. the (out,in)→(in,out) transpose)."""
    cfg = tiny_dense_cfg()
    model = DenseLLM(cfg, mesh=mesh8, axis="tp")
    rng = np.random.RandomState(0)

    def w(*shape):
        return rng.randn(*shape).astype(np.float32) * 0.05

    h, d = cfg.hidden_size, cfg.head_dim
    nq = cfg.num_attention_heads * d
    nkv = cfg.num_key_value_heads * d
    state = {"model.embed_tokens.weight": w(cfg.vocab_size, h),
             "model.norm.weight": np.ones(h, np.float32),
             "lm_head.weight": w(cfg.vocab_size, h)}
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        state.update({
            p + "self_attn.q_proj.weight": w(nq, h),
            p + "self_attn.k_proj.weight": w(nkv, h),
            p + "self_attn.v_proj.weight": w(nkv, h),
            p + "self_attn.o_proj.weight": w(h, nq),
            p + "self_attn.q_norm.weight": np.ones(d, np.float32),
            p + "self_attn.k_norm.weight": np.ones(d, np.float32),
            p + "mlp.gate_proj.weight": w(cfg.intermediate_size, h),
            p + "mlp.up_proj.weight": w(cfg.intermediate_size, h),
            p + "mlp.down_proj.weight": w(h, cfg.intermediate_size),
            p + "input_layernorm.weight": np.ones(h, np.float32),
            p + "post_attention_layernorm.weight": np.ones(h, np.float32),
        })
    params = model.load_hf_state_dict(state)
    # direct-construction golden
    direct = {
        "embed": jnp.asarray(state["model.embed_tokens.weight"]),
        "final_norm": jnp.asarray(state["model.norm.weight"]),
        "lm_head": jnp.asarray(state["lm_head.weight"]),
        "layers": [],
    }
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        direct["layers"].append({
            "attn": {
                "w_q": jnp.asarray(state[p + "self_attn.q_proj.weight"].T),
                "w_k": jnp.asarray(state[p + "self_attn.k_proj.weight"].T),
                "w_v": jnp.asarray(state[p + "self_attn.v_proj.weight"].T),
                "w_o": jnp.asarray(state[p + "self_attn.o_proj.weight"].T),
                "q_norm": jnp.asarray(state[p + "self_attn.q_norm.weight"]),
                "k_norm": jnp.asarray(state[p + "self_attn.k_norm.weight"]),
            },
            "mlp": {
                "w_gate": jnp.asarray(state[p + "mlp.gate_proj.weight"].T),
                "w_up": jnp.asarray(state[p + "mlp.up_proj.weight"].T),
                "w_down": jnp.asarray(state[p + "mlp.down_proj.weight"].T),
            },
            "ln_attn": jnp.asarray(state[p + "input_layernorm.weight"]),
            "ln_mlp": jnp.asarray(
                state[p + "post_attention_layernorm.weight"]),
        })
    direct = model.shard_params(direct)
    ids = jnp.arange(8, dtype=jnp.int32).reshape(2, 4)
    out1, _ = model.forward(params, ids, _caches(model, 2, 16), 0,
                            mode="xla_ar")
    out2, _ = model.forward(direct, ids, _caches(model, 2, 16), 0,
                            mode="xla_ar")
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               rtol=1e-6, atol=1e-6)


def test_llama_style_checkpoint_load(mesh8, key):
    """Llama-3 / Seed-OSS-class dense checkpoints (no q/k-norm weights —
    reference AutoLLM maps Meta-Llama-3-70B and Seed-OSS-36B to DenseLLM,
    models/__init__.py:33-42) load and run."""
    import dataclasses
    cfg = dataclasses.replace(tiny_dense_cfg(), model_type="llama",
                              qk_norm=False)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp")
    rng = np.random.RandomState(1)

    def w(*shape):
        return rng.randn(*shape).astype(np.float32) * 0.05

    h, d = cfg.hidden_size, cfg.head_dim
    nq = cfg.num_attention_heads * d
    nkv = cfg.num_key_value_heads * d
    state = {"model.embed_tokens.weight": w(cfg.vocab_size, h),
             "model.norm.weight": np.ones(h, np.float32),
             "lm_head.weight": w(cfg.vocab_size, h)}
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        state.update({
            p + "self_attn.q_proj.weight": w(nq, h),
            p + "self_attn.k_proj.weight": w(nkv, h),
            p + "self_attn.v_proj.weight": w(nkv, h),
            p + "self_attn.o_proj.weight": w(h, nq),
            p + "mlp.gate_proj.weight": w(cfg.intermediate_size, h),
            p + "mlp.up_proj.weight": w(cfg.intermediate_size, h),
            p + "mlp.down_proj.weight": w(h, cfg.intermediate_size),
            p + "input_layernorm.weight": np.ones(h, np.float32),
            p + "post_attention_layernorm.weight": np.ones(h, np.float32),
        })
    params = model.load_hf_state_dict(state)  # no q_norm keys required
    assert "q_norm" not in params["layers"][0]["attn"]
    ids = jnp.arange(8, dtype=jnp.int32).reshape(2, 4)
    out, _ = model.forward(params, ids, _caches(model, 2, 16), 0,
                           mode="xla_ar")
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())


def test_model_config_qk_norm_by_model_type():
    base = {"hidden_size": 64, "num_hidden_layers": 1,
            "num_attention_heads": 4, "vocab_size": 100,
            "intermediate_size": 128}
    assert ModelConfig.from_hf_config({**base,
                                       "model_type": "qwen3"}).qk_norm
    assert not ModelConfig.from_hf_config({**base,
                                           "model_type": "llama"}).qk_norm


def test_autollm_build_dispatch(mesh8):
    assert isinstance(AutoLLM.build(tiny_dense_cfg(), mesh=mesh8), DenseLLM)
    assert isinstance(AutoLLM.build(tiny_moe_cfg(), mesh=mesh8), Qwen3MoE)


def test_model_config_from_hf_dict():
    cfg = ModelConfig.from_hf_config({
        "hidden_size": 128, "num_hidden_layers": 3,
        "num_attention_heads": 4, "num_key_value_heads": 2,
        "vocab_size": 1000, "intermediate_size": 256,
        "num_experts": 16, "num_experts_per_tok": 4,
        "moe_intermediate_size": 64, "model_type": "qwen3_moe"})
    assert cfg.is_moe and cfg.head_dim == 32 and cfg.num_experts == 16


def test_moe_ep_mode_matches_tp(mesh8, key):
    """Qwen3MoE under EP (expert-sharded + a2a dispatch) matches the TP
    model on the same weights — VERDICT r1 item 4 model gate."""
    b, s, t = 2, 4, 16
    tp = Qwen3MoE(tiny_moe_cfg(), mesh=mesh8, axis="tp")
    ep = Qwen3MoE(tiny_moe_cfg(), mesh=mesh8, axis="tp", moe_parallel="ep")
    params_tp = tp.init(key)
    params_ep = ep.init(key)  # same key → same host values, EP sharding
    ids = jax.random.randint(jax.random.PRNGKey(3), (b, s), 0,
                             tp.config.vocab_size, jnp.int32)
    ref, _ = tp.forward(params_tp, ids, _caches(tp, b, t), 0, mode="xla")
    out, _ = ep.forward(params_ep, ids, _caches(ep, b, t), 0, mode="ep")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=3e-3,
                               atol=3e-3)


def test_moe_ep_engine_serve(mesh8, key):
    """EP-mode Qwen3MoE through the Engine decode loop."""
    from triton_dist_tpu.models.engine import Engine
    ep = Qwen3MoE(tiny_moe_cfg(), mesh=mesh8, axis="tp", moe_parallel="ep")
    params = ep.init(key)
    ids = jax.random.randint(jax.random.PRNGKey(5), (2, 3), 0,
                             ep.config.vocab_size, jnp.int32)
    eng = Engine(ep, batch=2, max_seq=16, prefill_mode="ep",
                 decode_mode="ep")
    out = eng.serve(params, ids, gen_len=2)
    assert out.shape == (2, 5)
    tp = Qwen3MoE(tiny_moe_cfg(), mesh=mesh8, axis="tp")
    eng_tp = Engine(tp, batch=2, max_seq=16, prefill_mode="xla_ar",
                    decode_mode="xla_ar")
    out_tp = eng_tp.serve(tp.init(key), ids, gen_len=2)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out_tp))


def test_kv_cache_manager_contract(mesh8):
    """Offset bookkeeping + allocation shape/sharding contract
    (reference KV_Cache kv_cache.py: inc_offset, overflow guard)."""
    from triton_dist_tpu.models.kv_cache import KVCacheManager
    kv = KVCacheManager(2, 2, 8, 8, 4, mesh=mesh8, axis="tp",
                        dtype=jnp.float32)
    caches = kv.init()
    assert len(caches) == 2
    k0, v0 = caches[0]
    assert k0.shape == (2, 8, 8, 4) and v0.shape == (2, 8, 8, 4)
    assert kv.inc_offset(5) == 5
    assert kv.inc_offset(3) == 8      # exactly full is legal
    with pytest.raises(AssertionError):
        kv.inc_offset(1)              # overflow must be caught
    kv.reset()
    assert kv.offset == 0


def test_kv_cache_incremental_decode_matches_full(dense, key):
    """Token-by-token decode through the cache must equal one full
    forward over the same ids (cache write/read positions exact)."""
    b, s, t = 2, 6, 16
    params = dense.init(key)
    ids = jax.random.randint(jax.random.PRNGKey(3), (b, s), 0,
                             dense.config.vocab_size, jnp.int32)
    full, _ = dense.forward(params, ids, _caches(dense, b, t), 0,
                            mode="xla_ar")
    caches = _caches(dense, b, t)
    logits_steps = []
    for i in range(s):
        lg, caches = dense.forward(params, ids[:, i:i + 1], caches,
                                   jnp.int32(i), mode="xla_ar")
        logits_steps.append(lg)
    step_logits = jnp.concatenate(logits_steps, axis=1)
    np.testing.assert_allclose(np.asarray(step_logits),
                               np.asarray(full), rtol=2e-4, atol=2e-4)


def test_paged_kv_cache_matches_contiguous(mesh8, key, monkeypatch):
    """PagedKVCacheManager writes + paged decode == contiguous-cache
    decode, including slot reuse after free (vLLM-style paging over the
    SP flash-decode kernel)."""
    from triton_dist_tpu.models.kv_cache import PagedKVCacheManager
    from triton_dist_tpu.ops.flash_decode import (
        create_flash_decode_context, gqa_fwd_batch_decode,
        gqa_fwd_batch_decode_paged)
    from jax.sharding import NamedSharding, PartitionSpec as P

    w, b, hq, hkv, d, page, npg = 8, 2, 8, 4, 16, 4, 2
    mgr = PagedKVCacheManager(1, b, page, npg, hkv, d, mesh=mesh8,
                              axis="tp", dtype=jnp.float32,
                              slots_per_dev=3 * npg)
    # churn the allocator so tables are non-trivial: alloc, free, realloc
    mgr.alloc_seq(0)
    mgr.alloc_seq(1)
    mgr.free_seq(0)
    mgr.alloc_seq(0)
    t = mgr.max_seq
    ks = jax.random.normal(key, (b, t, hkv, d), jnp.float32)
    vs = jax.random.normal(jax.random.fold_in(key, 1), (b, t, hkv, d),
                           jnp.float32)
    pools = mgr.init()
    table = mgr.block_table()
    write = jax.jit(lambda p, k_, v_, pos, tb: mgr.write(
        p, 0, k_, v_, pos, tb))
    for pos in range(t):
        pools = write(pools, ks[:, pos], vs[:, pos], jnp.int32(pos), table)
        mgr.inc_offset(1)

    q = jax.random.normal(jax.random.fold_in(key, 2), (b, hq, d),
                          jnp.float32)
    ctx = create_flash_decode_context(mesh8, "tp")
    import dataclasses as dc
    kv_len = jnp.int32(t - 3)
    sh = NamedSharding(mesh8, P(None, "tp"))
    ref = gqa_fwd_batch_decode(
        q, jax.device_put(ks, sh), jax.device_put(vs, sh), kv_len, ctx,
        impl="xla")
    # The paged XLA golden (contiguous view rebuilt via table gathers)
    # must agree with the contiguous decode.
    got_xla = gqa_fwd_batch_decode_paged(q, pools[0][0], pools[0][1],
                                         mgr.block_table(), kv_len, ctx,
                                         impl="xla")
    np.testing.assert_allclose(np.asarray(got_xla), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
    # paged_variant="gathered" (the DEFAULT): table-gather view + the
    # dense tiled Pallas kernel must match too.
    got_g = gqa_fwd_batch_decode_paged(
        q, pools[0][0], pools[0][1], mgr.block_table(), kv_len, ctx)
    np.testing.assert_allclose(np.asarray(got_g), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
    # The DIRECT block-table-indirection Pallas kernel, now the opt-in
    # (default flipped to "gathered" until the direct kernel's on-chip
    # Mosaic compile hang is root-caused — ADVICE r5): its
    # interpret-mode numerics stay pinned where the interpreter
    # supports barrier semaphores (jax 0.4.x does not — the supported
    # paths above still fully validate there).
    try:
        got = gqa_fwd_batch_decode_paged(
            q, pools[0][0], pools[0][1], mgr.block_table(), kv_len,
            dc.replace(ctx, paged_variant="direct"))
    except NotImplementedError:
        got = None
    if got is not None:
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)
    # env override wins over the field: with an INVALID field value the
    # call only succeeds if the env value actually replaces it (the
    # validator rejects the resolved value otherwise), so this cannot
    # pass vacuously through the direct path.
    import pytest
    bad_ctx = dc.replace(ctx, paged_variant="bogus")
    with pytest.raises(ValueError, match="paged_variant"):
        gqa_fwd_batch_decode_paged(q, pools[0][0], pools[0][1],
                                   mgr.block_table(), kv_len, bad_ctx)
    monkeypatch.setenv("TDT_PAGED_VARIANT", "gathered")
    got_env = gqa_fwd_batch_decode_paged(
        q, pools[0][0], pools[0][1], mgr.block_table(), kv_len, bad_ctx)
    np.testing.assert_allclose(np.asarray(got_env), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_paged_kv_pool_exhaustion(mesh8):
    from triton_dist_tpu.models.kv_cache import PagedKVCacheManager
    mgr = PagedKVCacheManager(1, 3, 4, 2, 2, 8, mesh=mesh8, axis="tp",
                              slots_per_dev=4)  # room for 2 seqs only
    mgr.alloc_seq(0)
    mgr.alloc_seq(1)
    tops = mgr._top.copy()
    with pytest.raises(RuntimeError, match="exhausted"):
        mgr.alloc_seq(2)
    # All-or-nothing: the failed alloc must not leak pages (the first
    # Python implementation lost the already-popped devices' slots).
    np.testing.assert_array_equal(mgr._top, tops)
    mgr.free_seq(1)
    mgr.alloc_seq(2)  # freed slots are reusable


def test_paged_kv_native_python_parity(mesh8):
    """The C allocator (csrc/kvpool) and the Python fallback replay a
    randomized alloc/free trace bit-identically (stacks, tops, tables,
    owned flags)."""
    from triton_dist_tpu.models import kv_native
    from triton_dist_tpu.models.kv_cache import PagedKVCacheManager

    if not kv_native.have_native():
        pytest.skip("no native toolchain")

    def build():
        return PagedKVCacheManager(1, 8, 4, 2, 2, 8, mesh=mesh8,
                                   axis="tp", slots_per_dev=20)

    nat, py = build(), build()
    assert nat._lib is not None
    py._lib = None  # force the Python fallback on identical init state

    rng = np.random.RandomState(0)
    live = set()
    for _ in range(200):
        b = int(rng.randint(0, 8))
        for m in (nat, py):
            try:
                if b in live:
                    m.free_seq(b)
                else:
                    m.alloc_seq(b)
                ok = True
            except RuntimeError:
                ok = False
        live.symmetric_difference_update({b} if ok else set())
        np.testing.assert_array_equal(nat._stack, py._stack)
        np.testing.assert_array_equal(nat._top, py._top)
        np.testing.assert_array_equal(nat._table, py._table)
        np.testing.assert_array_equal(nat._owned, py._owned)


def test_paged_kv_alloc_many_rollback(mesh8):
    """Admission control is transactional: a request that cannot fully
    fit rolls back every row it touched."""
    from triton_dist_tpu.models.kv_cache import PagedKVCacheManager
    for force_py in (False, True):
        mgr = PagedKVCacheManager(1, 4, 4, 2, 2, 8, mesh=mesh8,
                                  axis="tp", slots_per_dev=6)  # 3 seqs
        if force_py:
            mgr._lib = None
        state = (mgr._stack.copy(), mgr._top.copy(), mgr._owned.copy())
        with pytest.raises(RuntimeError):
            mgr.alloc_many([0, 1, 2, 3])  # needs 8 pages, pool has 6
        # Transactional = same tops/ownership and same free SET per
        # device (rollback may reorder the stack, which is harmless).
        np.testing.assert_array_equal(mgr._top, state[1])
        np.testing.assert_array_equal(mgr._owned, state[2])
        for r in range(mgr.world):
            assert (set(mgr._stack[r, :mgr._top[r]])
                    == set(state[0][r, :state[1][r]]))
        mgr.alloc_many([0, 1, 2])  # exactly fits
        assert mgr._owned[:3].all() and not mgr._owned[3]


def test_checkpoint_roundtrip(mesh8, key, tmp_path):
    """Sharded params save/restore (orbax): restored arrays keep their
    shardings and drive an identical forward — capability absent in the
    reference (SURVEY §5 'Checkpoint/resume: none')."""
    from triton_dist_tpu.models.checkpoint import load_params, save_params
    dense = DenseLLM(tiny_dense_cfg(), mesh=mesh8, axis="tp")
    params = dense.init(key)
    ids = jnp.arange(8, dtype=jnp.int32).reshape(2, 4)
    ref, _ = dense.forward(params, ids, _caches(dense, 2, 16), 0,
                           mode="xla_ar")

    path = save_params(str(tmp_path / "ckpt"), params)
    restored = load_params(path, like=params)
    w0 = restored["layers"][0]["attn"]["w_q"]
    assert w0.sharding == params["layers"][0]["attn"]["w_q"].sharding
    out, _ = dense.forward(restored, ids, _caches(dense, 2, 16), 0,
                           mode="xla_ar")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def _hf_parity_case(mesh8, hf_model_cls, hf_cfg, model_type):
    """Shared HF-transformers parity check (the reference's test_tp_e2e
    --check against torch eager, test/nvidia/test_tp_e2e.py)."""
    import dataclasses
    import torch

    torch.manual_seed(0)
    hf = hf_model_cls(hf_cfg).eval()
    state = {k: v.detach().cpu().numpy().astype(np.float32)
             for k, v in hf.state_dict().items()}
    if "lm_head.weight" not in state:  # tied embeddings
        state["lm_head.weight"] = state["model.embed_tokens.weight"]

    cfg = ModelConfig.from_hf_config(
        {**hf_cfg.to_dict(), "model_type": model_type})
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = model.load_hf_state_dict(state)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(2, 8)).astype(np.int32)
    ours, _ = model.forward(params, jnp.asarray(ids),
                            _caches(model, 2, 16), 0, mode="xla_ar")
    with torch.no_grad():
        theirs = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=2e-3,
                               atol=2e-3)


def test_hf_transformers_parity_qwen3(mesh8):
    """Bit-level architecture parity vs the installed HF Qwen3 eager
    implementation — the external golden the self-consistency tests
    can't provide."""
    from transformers import Qwen3Config, Qwen3ForCausalLM
    hf_cfg = Qwen3Config(
        hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=8, num_key_value_heads=8, head_dim=8,
        vocab_size=128, max_position_embeddings=64, rope_theta=1e6,
        rms_norm_eps=1e-6, tie_word_embeddings=False,
        attention_bias=False, attention_dropout=0.0)
    _hf_parity_case(mesh8, Qwen3ForCausalLM, hf_cfg, "qwen3")


def test_hf_transformers_parity_llama(mesh8):
    """Same vs HF Llama (no qk-norm — the Llama-3/Seed-OSS dense
    class)."""
    from transformers import LlamaConfig, LlamaForCausalLM
    hf_cfg = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=8, num_key_value_heads=8,
        vocab_size=128, max_position_embeddings=64, rope_theta=1e6,
        rms_norm_eps=1e-6, tie_word_embeddings=False,
        attention_bias=False, attention_dropout=0.0, mlp_bias=False)
    _hf_parity_case(mesh8, LlamaForCausalLM, hf_cfg, "llama")


def test_hf_transformers_parity_qwen3_gqa(devices):
    """GQA grouping (hq != hkv) against HF on a 4-device mesh."""
    from jax.sharding import Mesh
    from transformers import Qwen3Config, Qwen3ForCausalLM
    mesh4 = Mesh(np.array(devices[:4]), ("tp",))
    hf_cfg = Qwen3Config(
        hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=8, num_key_value_heads=4, head_dim=8,
        vocab_size=128, max_position_embeddings=64, rope_theta=1e6,
        rms_norm_eps=1e-6, tie_word_embeddings=True,
        attention_bias=False, attention_dropout=0.0)
    _hf_parity_case(mesh4, Qwen3ForCausalLM, hf_cfg, "qwen3")


def test_hf_transformers_parity_qwen3_moe(devices):
    """MoE parity vs HF Qwen3Moe eager: router softmax/top-k norm,
    expert stacking, shared attention — external golden for the MoE
    stack."""
    import dataclasses
    import torch
    from jax.sharding import Mesh
    from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM

    mesh4 = Mesh(np.array(devices[:4]), ("tp",))
    hf_cfg = Qwen3MoeConfig(
        hidden_size=64, intermediate_size=128, moe_intermediate_size=32,
        num_hidden_layers=2, num_attention_heads=8,
        num_key_value_heads=4, head_dim=8, vocab_size=128,
        max_position_embeddings=64, rope_theta=1e6, rms_norm_eps=1e-6,
        tie_word_embeddings=False, attention_bias=False,
        attention_dropout=0.0, num_experts=4, num_experts_per_tok=2,
        norm_topk_prob=True, decoder_sparse_step=1,
        mlp_only_layers=[], router_aux_loss_coef=0.0,
        output_router_logits=False)
    torch.manual_seed(0)
    hf = Qwen3MoeForCausalLM(hf_cfg).eval()
    state = {k: v.detach().cpu().numpy().astype(np.float32)
             for k, v in hf.state_dict().items()}
    if "lm_head.weight" not in state:
        state["lm_head.weight"] = state["model.embed_tokens.weight"]

    cfg = ModelConfig.from_hf_config(
        {**hf_cfg.to_dict(), "model_type": "qwen3_moe"})
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    model = Qwen3MoE(cfg, mesh=mesh4, axis="tp")
    params = model.load_hf_state_dict(state)

    rng = np.random.RandomState(1)
    ids = rng.randint(0, cfg.vocab_size, size=(2, 8)).astype(np.int32)
    ours, _ = model.forward(params, jnp.asarray(ids),
                            _caches(model, 2, 16), 0, mode="xla")
    with torch.no_grad():
        theirs = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=3e-3,
                               atol=3e-3)


def test_hf_transformers_generation_parity(devices):
    """Greedy generation parity vs hf.generate — anchors the decode
    loop + KV cache + rope offsets externally, not just one forward."""
    import dataclasses
    import torch
    from jax.sharding import Mesh
    from transformers import Qwen3Config, Qwen3ForCausalLM

    mesh4 = Mesh(np.array(devices[:4]), ("tp",))
    hf_cfg = Qwen3Config(
        hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=8, num_key_value_heads=4, head_dim=8,
        vocab_size=128, max_position_embeddings=64, rope_theta=1e6,
        rms_norm_eps=1e-6, tie_word_embeddings=False,
        attention_bias=False, attention_dropout=0.0)
    torch.manual_seed(3)
    hf = Qwen3ForCausalLM(hf_cfg).eval()
    state = {k: v.detach().cpu().numpy().astype(np.float32)
             for k, v in hf.state_dict().items()}

    cfg = dataclasses.replace(
        ModelConfig.from_hf_config({**hf_cfg.to_dict(),
                                    "model_type": "qwen3"}),
        dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh4, axis="tp", impl="xla")
    params = model.load_hf_state_dict(state)

    ids = np.asarray([[7, 3, 11, 29]], np.int32)
    with torch.no_grad():
        ref = hf.generate(torch.tensor(ids.astype(np.int64)),
                          max_new_tokens=5, do_sample=False,
                          eos_token_id=None).numpy()
    ours = np.asarray(Engine(model, batch=1, max_seq=32).serve(
        params, jnp.asarray(ids), 5, stop_tokens=()))
    np.testing.assert_array_equal(ours, ref)
