"""Two-process `jax.distributed` CPU test (VERDICT r4 next-5).

Spawns two cooperating processes that each run
``tests/_multihost_worker.py``: `jax.distributed.initialize` via the
env-var path `runtime/dist.py::_maybe_multihost_init` reads, a
cross-process collective on the global 2x4-device mesh, and one
multi-host autotune round. This covers the DCN code path that no
single-process 8-device mesh touches — the reference's whole test spine
is multi-process launch (SURVEY.md §4, torchrun), and this is its
TPU-native equivalent.
"""

import os
import pathlib
import socket
import subprocess
import sys

import pytest

_WORKER = pathlib.Path(__file__).resolve().parent / "_multihost_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_mesh_collective_and_autotune(tmp_path):
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_COORDINATOR_ADDRESS",
                        "JAX_NUM_PROCESSES", "JAX_PROCESS_ID")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(_WORKER), str(pid), str(port),
             str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multihost workers timed out; partial: {outs}")
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    results = sorted(
        line for out in outs for line in out.splitlines()
        if line.startswith("RESULT"))
    assert len(results) == 2, outs
    # Same winner on both processes (the agreement contract), and the
    # cross-process psum saw all 8 shards.
    w0 = results[0].split("winner=")[1]
    w1 = results[1].split("winner=")[1]
    assert w0 == w1, results
    assert all("psum=8.0" in r for r in results)


def test_multihost_init_env_validation(monkeypatch):
    """Partial/garbled JAX_* multihost env fails with a clear
    configuration error, not a raw int()/JAX traceback (review r5d-3)."""
    from triton_dist_tpu.runtime import dist as tdist

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    with pytest.raises(RuntimeError, match="JAX_PROCESS_ID"):
        tdist._maybe_multihost_init()
    monkeypatch.setenv("JAX_NUM_PROCESSES", "two")
    monkeypatch.setenv("JAX_PROCESS_ID", "0")
    with pytest.raises(RuntimeError, match="num_processes"):
        tdist._maybe_multihost_init()
