"""Speculative decoding units (models/spec.py + Engine spec surface,
ISSUE 13).

The scheduler-level parity matrix lives in tests/test_scheduler.py;
this file pins the pieces in isolation: the n-gram drafter's lookup
semantics, the greedy acceptance rule, SpecConfig validation (greedy-
only, drafter requirements, the TDT_SPEC kill switch and TDT_SPEC_K
override), the serve() refusal (no silent ignore), the small-model
drafter's lockstep correctness (a target drafting for ITSELF must
accept everything — any rejection is a draft-cache desync), and the
chunked-prefill admission handing the drafter the right history.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
from triton_dist_tpu.models.spec import (NGramDrafter, SpecConfig,
                                         accept_greedy,
                                         draft_model_from_preset)
from triton_dist_tpu.serving import Scheduler


@pytest.fixture()
def tiny(mesh8, key):
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    return model, model.init(key)


def _solo(model, params, prompt, gen_len):
    eng = Engine(model, batch=1, max_seq=64, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    out = np.asarray(eng.serve(params, jnp.asarray([prompt], jnp.int32),
                               gen_len))[0].tolist()
    return out[len(prompt):]


# ---------------------------------------------------------------------------
# Pure logic: acceptance + the n-gram drafter.
# ---------------------------------------------------------------------------

def test_accept_greedy_rule():
    # full accept: every draft matches -> k accepted, k+1 emitted
    a, em = accept_greedy([3, 4, 5], np.asarray([3, 4, 5, 9]))
    assert (a, em) == (3, [3, 4, 5, 9])
    # first mismatch stops: the target's own token is the bonus
    a, em = accept_greedy([3, 7, 5], np.asarray([3, 4, 5, 9]))
    assert (a, em) == (1, [3, 4])
    # zero accept: exactly one token (the plain-step equivalent)
    a, em = accept_greedy([7], np.asarray([3, 9]))
    assert (a, em) == (0, [3])
    # empty draft: pure bonus
    a, em = accept_greedy([], np.asarray([3]))
    assert (a, em) == (0, [3])


def test_ngram_drafter_lookup_semantics():
    d = NGramDrafter(4, ngram_n=3)
    d.start_row(0, [1, 2, 3, 4, 1, 2, 3])
    # trailing [1,2,3] occurred at 0 with continuation [4,1,2,3]
    assert d.draft_batch([0], {0: 4}) == {0: [4, 1, 2, 3]}
    # kmax clamps the proposal
    assert d.draft_batch([0], {0: 2}) == {0: [4, 1]}
    assert d.draft_batch([0], {0: 0}) == {0: []}
    # most recent occurrence wins
    d.observe(0, [9, 1, 2, 3])
    assert d.draft_batch([0], {0: 3}) == {0: [9, 1, 2]}
    # falls back through shorter n-grams; no match -> empty
    d2 = NGramDrafter(4, ngram_n=3)
    d2.start_row(1, [5, 6, 7])
    assert d2.draft_batch([1], {1: 4}) == {1: []}
    d2.observe(1, [6])          # trailing [6]: seen at 1 -> cont [7]
    assert d2.draft_batch([1], {1: 4}) == {1: [7, 6]}
    # retirement clears state; a fresh admission starts clean
    d2.retire_row(1)
    d2.start_row(1, [8, 9])
    assert d2.draft_batch([1], {1: 4}) == {1: []}


# ---------------------------------------------------------------------------
# SpecConfig validation + env knobs.
# ---------------------------------------------------------------------------

def test_spec_config_validation(monkeypatch):
    assert SpecConfig().k == 4                  # DEFAULT_K
    monkeypatch.setenv("TDT_SPEC_K", "7")
    assert SpecConfig().k == 7                  # env override
    assert SpecConfig(k=2).k == 2               # explicit wins
    with pytest.raises(ValueError, match="k must be"):
        SpecConfig(k=0)
    with pytest.raises(ValueError, match="drafter"):
        SpecConfig(drafter="oracle")
    with pytest.raises(ValueError, match="draft_model"):
        SpecConfig(drafter="model")
    with pytest.raises(ValueError, match="ngram_n"):
        SpecConfig(ngram_n=0)


def test_spec_requires_greedy(tiny):
    model, _ = tiny
    with pytest.raises(ValueError, match="greedy"):
        Engine(model, batch=1, max_seq=64, prefill_mode="xla_ar",
               decode_mode="gemm_ar", temperature=0.7,
               spec=SpecConfig())


def test_tdt_spec_kill_switch(tiny, monkeypatch):
    """TDT_SPEC=0 disables speculation process-wide: the engine
    behaves exactly as spec=None (no spec state, serve() works)."""
    model, params = tiny
    monkeypatch.setenv("TDT_SPEC", "0")
    eng = Engine(model, batch=1, max_seq=64, prefill_mode="xla_ar",
                 decode_mode="gemm_ar", spec=SpecConfig())
    assert eng.spec is None
    sess = eng.stream_session(params)
    assert sess.spec is None
    out = np.asarray(eng.serve(params, jnp.asarray([[1, 2]], jnp.int32),
                               3))
    assert out.shape == (1, 5)


def test_serve_refuses_spec_engine(tiny):
    """Satellite: serve() must not silently ignore a SpecConfig — it
    refuses with a ValueError naming the restriction (the stream path
    is the spec surface); serve_ragged rides the same refusal."""
    model, params = tiny
    eng = Engine(model, batch=1, max_seq=64, prefill_mode="xla_ar",
                 decode_mode="gemm_ar", spec=SpecConfig())
    with pytest.raises(ValueError, match="stream path"):
        eng.serve(params, jnp.asarray([[1, 2]], jnp.int32), 4)
    with pytest.raises(ValueError, match="stream path"):
        eng.serve_ragged(params, [[1, 2], [3]], 4)
    # ... while the stream path serves it fine.
    res = eng.serve_stream(params, [[1, 2, 3]], 4)
    assert res[0][3:] == _solo(model, params, [1, 2, 3], 4)


def test_draft_model_from_preset(mesh8):
    m = draft_model_from_preset("qwen3-0.6b", mesh=mesh8)
    assert m.config.hidden_size == 1024
    with pytest.raises(ValueError, match="unknown preset"):
        draft_model_from_preset("qwen4-900b", mesh=mesh8)


# ---------------------------------------------------------------------------
# Model drafter: lockstep with the committed stream.
# ---------------------------------------------------------------------------

def test_model_drafter_self_draft_accepts_everything(tiny):
    """The target model drafting for ITSELF must reach accept rate 1.0
    — its drafts ARE the target's argmax, so any rejection means the
    drafter's KV cache desynced from the committed stream (the
    catch-up/scratch-rewind machinery is what this pins). Multi-token
    commits then retire rows mid-schedule like any burst."""
    from triton_dist_tpu import obs
    model, params = tiny
    spec = SpecConfig(k=3, drafter="model", draft_model=model,
                      draft_params=params)
    prompts = [[1, 2, 3], [9, 8], [4, 5, 6, 7]]
    reg = obs.enable(obs.Registry())
    try:
        eng = Engine(model, batch=2, max_seq=64, prefill_mode="xla_ar",
                     decode_mode="gemm_ar", spec=spec)
        sched = Scheduler(eng, params).start()
        try:
            reqs = [sched.submit(p, 7) for p in prompts]
            got = [r.result(timeout=180) for r in reqs]
        finally:
            sched.stop()
        for p, row in zip(prompts, got):
            assert row == _solo(model, params, p, 7), p
        snap = reg.snapshot()
        assert snap["gauges"]["serving.spec_accept_rate"] == 1.0
        assert snap["gauges"]["serving.spec_tokens_per_step"] > 1.0
    finally:
        obs.disable()


def test_model_drafter_distinct_model_stays_bit_identical(tiny, key):
    """A DIFFERENT (wrong-by-construction) draft model exercises the
    rejection path: outputs must still be bit-identical to spec-off —
    a bad drafter can only cost speed, never correctness."""
    model, params = tiny
    bad_params = model.init(jax.random.split(key)[0])   # different net
    spec = SpecConfig(k=3, drafter="model", draft_model=model,
                      draft_params=bad_params)
    eng = Engine(model, batch=2, max_seq=64, prefill_mode="xla_ar",
                 decode_mode="gemm_ar", spec=spec)
    prompts = [[1, 2, 3], [9, 8], [5, 6, 5, 6, 5]]
    res = eng.serve_stream(params, prompts, 6)
    for p, row in zip(prompts, res):
        assert row[len(p):] == _solo(model, params, p, 6), p


def test_spec_sp_nonpaged_family_bit_identical(mesh8, key):
    """The sp engine family WITHOUT paged pools (seq-sharded
    contiguous cache) bursts through forward_sp's per-row multi-token
    scatter + per-position flash-decode branch — bit-identical to
    spec-off via serve_stream."""
    from jax.sharding import Mesh
    devs = [d for d in mesh8.devices.flat]
    mesh = Mesh(np.array(devs).reshape(1, 8), ("tp", "sp"))
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=4,
                      num_key_value_heads=2, head_dim=16, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh, axis="tp", sp_axis="sp",
                     impl="xla", fwd_mode="sp")
    params = model.init(key)
    prompts = [[1, 2, 3], [5, 6, 5, 6, 5], [9, 8]]
    outs = {}
    for tag, spec in (("on", SpecConfig(k=3)), ("off", None)):
        eng = Engine(model, batch=2, max_seq=64, prefill_mode="sp",
                     decode_mode="sp", spec=spec)
        outs[tag] = eng.serve_stream(params, prompts, 6)
    assert outs["on"] == outs["off"]


def test_spec_with_chunked_prefill_admission(tiny):
    """Chunked admission (TDT_PREFILL_CHUNK path) + spec: the drafter
    is seeded at prefill COMPLETION with the full prompt, and outputs
    stay bit-identical."""
    model, params = tiny
    eng = Engine(model, batch=2, max_seq=64, prefill_mode="xla_ar",
                 decode_mode="gemm_ar", spec=SpecConfig(k=4))
    sched = Scheduler(eng, params, prefill_chunk=4).start()
    try:
        long_p = list(range(1, 15))          # 14 tokens -> 4 chunks
        short_p = [5, 9]
        r_long = sched.submit(long_p, 6)
        r_short = sched.submit(short_p, 6)
        assert r_long.result(timeout=180) == _solo(model, params,
                                                   long_p, 6)
        assert r_short.result(timeout=180) == _solo(model, params,
                                                    short_p, 6)
        assert eng._admit_chunk is not None  # the chunked path ran
    finally:
        sched.stop()
