"""Fused ops on 2-D meshes (VERDICT r2 next 10: "2-D mesh (tp x sp,
tp x ep) variants for every fused op" — round 2 only exercised 2-D
meshes in test_language).

Each op runs on ONE axis of a (tp=4, ep=2) mesh; correctness requires
``logical_device_id`` to translate axis-relative peers into global mesh
ids inside every remote DMA and barrier (a bug here silently corrupts
rank math on any real multi-dim topology, e.g. tp x sp serving or
tp x ep MoE)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow


def _put(mesh, x, spec):
    return jax.device_put(x, NamedSharding(mesh, spec))


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_allgather_2d(mesh4x2, axis):
    from triton_dist_tpu.ops.allgather import (
        AllGatherMethod, create_allgather_context, all_gather)
    w = mesh4x2.shape[axis]
    x = jnp.arange(w * 4 * 128, dtype=jnp.float32).reshape(w * 4, 128)
    xs = _put(mesh4x2, x, P(axis))
    for method in (AllGatherMethod.RING_1D, AllGatherMethod.RING_BIDIR,
                   AllGatherMethod.FULL_MESH_PUSH):
        ctx = create_allgather_context(mesh4x2, axis, method=method)
        got = all_gather(xs, ctx, impl="pallas")
        np.testing.assert_array_equal(np.asarray(got), np.asarray(x),
                                      err_msg=f"{axis}/{method}")


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_ag_gemm_2d(mesh4x2, axis, key):
    from triton_dist_tpu.ops.allgather_gemm import (
        create_ag_gemm_context, ag_gemm)
    w = mesh4x2.shape[axis]
    m, k, n = w * 8, 64, w * 32
    a = _put(mesh4x2, jax.random.normal(key, (m, k), jnp.float32) / 4,
             P(axis))
    b = _put(mesh4x2,
             jax.random.normal(jax.random.PRNGKey(1), (k, n),
                               jnp.float32) / 4, P(None, axis))
    ctx = create_ag_gemm_context(mesh4x2, axis)
    got = ag_gemm(a, b, ctx, impl="pallas")
    gold = ag_gemm(a, b, ctx, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(gold),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_gemm_rs_2d(mesh4x2, axis, key):
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)
    w = mesh4x2.shape[axis]
    m, k, n = w * 8, w * 16, 128
    a = _put(mesh4x2, jax.random.normal(key, (m, k), jnp.float32) / 4,
             P(None, axis))
    b = _put(mesh4x2,
             jax.random.normal(jax.random.PRNGKey(1), (k, n),
                               jnp.float32) / 4, P(axis))
    ctx = create_gemm_rs_context(mesh4x2, axis)
    got = gemm_rs(a, b, ctx, impl="pallas")
    gold = gemm_rs(a, b, ctx, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(gold),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_flash_decode_2d(mesh4x2, axis, key):
    from triton_dist_tpu.ops.flash_decode import (
        create_flash_decode_context, gqa_fwd_batch_decode)
    w = mesh4x2.shape[axis]
    b, hq, hkv, d, t = 2, 8, 2, 64, w * 64
    q = jax.random.normal(key, (b, hq, d), jnp.float32)
    kc = _put(mesh4x2, jax.random.normal(jax.random.PRNGKey(1),
                                         (b, t, hkv, d), jnp.float32),
              P(None, axis))
    vc = _put(mesh4x2, jax.random.normal(jax.random.PRNGKey(2),
                                         (b, t, hkv, d), jnp.float32),
              P(None, axis))
    ctx = create_flash_decode_context(mesh4x2, axis, variant="tiled",
                                      t_blk=32)
    got = gqa_fwd_batch_decode(q, kc, vc, jnp.int32(t - 5), ctx,
                               impl="pallas")
    gold = gqa_fwd_batch_decode(q, kc, vc, jnp.int32(t - 5), ctx,
                                impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(gold),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_sp_attention_fused_2d(mesh4x2, axis, key):
    from triton_dist_tpu.ops.sp_attention import (
        create_sp_attention_context, sp_ag_attention)
    w = mesh4x2.shape[axis]
    b, s, hq, hkv, d = 1, w * 128, 4, 2, 64
    q = _put(mesh4x2, jax.random.normal(key, (b, s, hq, d), jnp.float32),
             P(None, axis))
    k = _put(mesh4x2, jax.random.normal(jax.random.PRNGKey(1),
                                        (b, s, hkv, d), jnp.float32),
             P(None, axis))
    v = _put(mesh4x2, jax.random.normal(jax.random.PRNGKey(2),
                                        (b, s, hkv, d), jnp.float32),
             P(None, axis))
    ctx = create_sp_attention_context(mesh4x2, axis, causal=True)
    got = sp_ag_attention(q, k, v, ctx, impl="pallas")
    gold = sp_ag_attention(q, k, v, ctx, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(gold),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_all_to_all_2d(mesh4x2, axis):
    from triton_dist_tpu.ops.all_to_all import (
        create_all_to_all_context, fast_all_to_all)
    w = mesh4x2.shape[axis]
    cap = 16
    rng = np.random.RandomState(0)
    send = _put(mesh4x2,
                jnp.asarray(rng.randn(w * w, cap, 128), jnp.float32),
                P(axis))
    counts = _put(mesh4x2, jnp.full((w * w,), 8, jnp.int32), P(axis))
    ctx = create_all_to_all_context(mesh4x2, axis, capacity=cap)
    got_buf, got_counts = fast_all_to_all(send, counts, ctx,
                                          impl="pallas")
    ref_buf, ref_counts = fast_all_to_all(send, counts, ctx, impl="xla")
    np.testing.assert_array_equal(np.asarray(got_counts),
                                  np.asarray(ref_counts))
    gb = np.asarray(got_buf).reshape(w, w, cap, 128)
    rb = np.asarray(ref_buf).reshape(w, w, cap, 128)
    np.testing.assert_allclose(gb[:, :, :8], rb[:, :, :8], rtol=1e-5,
                               atol=1e-5)


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_reduce_scatter_2d(mesh4x2, axis, key):
    from triton_dist_tpu.ops.reduce_scatter import (
        ReduceScatterMethod, create_reduce_scatter_context, reduce_scatter)
    w = mesh4x2.shape[axis]
    x = jax.random.normal(key, (w, w * 8, 128), jnp.float32)
    ref = np.asarray(x, np.float64).sum(axis=0)
    for method in (ReduceScatterMethod.RING, ReduceScatterMethod.ONE_SHOT):
        ctx = create_reduce_scatter_context(mesh4x2, axis, method=method)
        got = reduce_scatter(x, ctx, impl="pallas")
        np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4,
                                   atol=1e-4, err_msg=f"{axis}/{method}")


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_allreduce_2d(mesh4x2, axis, key):
    from triton_dist_tpu.ops.allreduce import (
        AllReduceMethod, all_reduce, create_allreduce_context)
    w = mesh4x2.shape[axis]
    x = jax.random.normal(key, (w, 16, 128), jnp.float32)
    ref = np.asarray(x, np.float64).sum(axis=0)
    for method in (AllReduceMethod.ONE_SHOT, AllReduceMethod.TWO_SHOT,
                   AllReduceMethod.RECURSIVE_DOUBLING):
        ctx = create_allreduce_context(mesh4x2, axis, method=method)
        got = np.asarray(all_reduce(x, ctx, impl="pallas", stacked=True))
        for d in range(w):
            np.testing.assert_allclose(got[d], ref, rtol=1e-4, atol=1e-4,
                                       err_msg=f"{axis}/{method}/{d}")


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_gemm_ar_2d(mesh4x2, axis, key):
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_ar)
    w = mesh4x2.shape[axis]
    ka, kb = jax.random.split(key)
    a = (jax.random.normal(ka, (32, 16 * w)) / 4).astype(jnp.float32)
    b = (jax.random.normal(kb, (16 * w, 64)) / 4).astype(jnp.float32)
    a_s = _put(mesh4x2, a, P(None, axis))
    b_s = _put(mesh4x2, b, P(axis))
    ctx = create_gemm_rs_context(mesh4x2, axis)
    got = gemm_ar(a_s, b_s, ctx, impl="pallas")
    full = np.asarray(a, np.float64) @ np.asarray(b, np.float64)
    np.testing.assert_allclose(np.asarray(got), full, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("axis", ["tp", "ep"])
@pytest.mark.parametrize("impl", ["ring", "fused"])
def test_ag_group_gemm_2d(mesh4x2, axis, impl, key):
    from triton_dist_tpu.ops.group_gemm import (
        ag_group_gemm, create_ag_group_gemm_context)
    w = mesh4x2.shape[axis]
    rows, kdim, n, e = 4, 16, 32 * w, 4
    m = w * rows
    x = jax.random.normal(key, (m, kdim), jnp.float32)
    wt = jax.random.normal(jax.random.PRNGKey(5), (e, kdim, n), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(6), (m,), 0, e, jnp.int32)
    xs = _put(mesh4x2, x, P(axis))
    ws = _put(mesh4x2, wt, P(None, None, axis))
    ids_s = _put(mesh4x2, ids, P(axis))
    ctx = create_ag_group_gemm_context(mesh4x2, axis)
    if impl == "fused":
        ctx.block_m, ctx.block_n = 8, 16
    out = ag_group_gemm(xs, ws, ids_s, e, ctx, impl=impl)
    ref = np.stack([np.asarray(x[i]) @ np.asarray(wt[int(ids[i])])
                    for i in range(m)])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("axis", ["tp", "ep"])
@pytest.mark.parametrize("impl", ["ring", "fused"])
def test_moe_reduce_rs_2d(mesh4x2, axis, impl, key):
    from triton_dist_tpu.ops.moe_reduce_rs import (
        create_moe_rs_context, moe_reduce_rs)
    w = mesh4x2.shape[axis]
    rows, i, h, e, topk = 4, 16 * w, 16, 4, 2
    t = w * rows
    act = jax.random.normal(key, (t * topk, i), jnp.float32)
    wd = jax.random.normal(jax.random.PRNGKey(2), (e, i, h), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(3), (t * topk,), 0, e,
                             jnp.int32)
    wts = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(4), (t, topk)), axis=-1)
    ctx = create_moe_rs_context(mesh4x2, axis, num_experts=e, topk=topk)
    if impl == "fused":
        ctx.block_m, ctx.block_h = 8, 16
    act_s = _put(mesh4x2, act, P(None, axis))
    wd_s = _put(mesh4x2, wd, P(None, axis, None))
    out = moe_reduce_rs(act_s, wd_s, ids, wts, ctx, impl=impl)
    pair = np.stack([np.asarray(act[j]) @ np.asarray(wd[int(ids[j])])
                     for j in range(t * topk)]).reshape(t, topk, h)
    ref = (pair * np.asarray(wts)[..., None]).sum(1)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("axis", ["tp", "ep"])
@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_pp_shift_2d(mesh4x2, axis, impl, key):
    from triton_dist_tpu.ops.p2p import create_p2p_context, pp_shift
    w = mesh4x2.shape[axis]
    rows, f = 4, 128
    x = jax.random.normal(key, (w * rows, f), jnp.float32)
    xs = _put(mesh4x2, x, P(axis))
    ctx = create_p2p_context(mesh4x2, axis)
    out = pp_shift(xs, ctx, delta=1, impl=impl)
    ref = np.roll(np.asarray(x).reshape(w, rows, f), 1, axis=0)
    np.testing.assert_array_equal(
        np.asarray(out).reshape(w, rows, f), ref)


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_sp_ulysses_2d(mesh4x2, axis, key):
    """Ulysses a2a attention bound to one axis of a 2-D mesh."""
    from triton_dist_tpu.ops.sp_attention import (
        create_sp_attention_context, sp_ag_attention)
    w = mesh4x2.shape[axis]
    b, s, hq, hkv, d = 1, 8 * w, 4 * w, 2 * w, 16
    q = jax.random.normal(key, (b, s, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d),
                          jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d),
                          jnp.float32)
    ctx = create_sp_attention_context(mesh4x2, axis, causal=True)
    sh = NamedSharding(mesh4x2, P(None, axis))
    got = sp_ag_attention(jax.device_put(q, sh), jax.device_put(k, sh),
                          jax.device_put(v, sh), ctx, impl="ulysses")
    ref = sp_ag_attention(jax.device_put(q, sh), jax.device_put(k, sh),
                          jax.device_put(v, sh), ctx, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=3e-4, atol=3e-4)
