"""Fused ops on 2-D meshes (VERDICT r2 next 10: "2-D mesh (tp x sp,
tp x ep) variants for every fused op" — round 2 only exercised 2-D
meshes in test_language).

Each op runs on ONE axis of a (tp=4, ep=2) mesh; correctness requires
``logical_device_id`` to translate axis-relative peers into global mesh
ids inside every remote DMA and barrier (a bug here silently corrupts
rank math on any real multi-dim topology, e.g. tp x sp serving or
tp x ep MoE)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P


def _put(mesh, x, spec):
    return jax.device_put(x, NamedSharding(mesh, spec))


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_allgather_2d(mesh4x2, axis):
    from triton_dist_tpu.ops.allgather import (
        AllGatherMethod, create_allgather_context, all_gather)
    w = mesh4x2.shape[axis]
    x = jnp.arange(w * 4 * 128, dtype=jnp.float32).reshape(w * 4, 128)
    xs = _put(mesh4x2, x, P(axis))
    for method in (AllGatherMethod.RING_1D, AllGatherMethod.RING_BIDIR,
                   AllGatherMethod.FULL_MESH_PUSH):
        ctx = create_allgather_context(mesh4x2, axis, method=method)
        got = all_gather(xs, ctx, impl="pallas")
        np.testing.assert_array_equal(np.asarray(got), np.asarray(x),
                                      err_msg=f"{axis}/{method}")


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_ag_gemm_2d(mesh4x2, axis, key):
    from triton_dist_tpu.ops.allgather_gemm import (
        create_ag_gemm_context, ag_gemm)
    w = mesh4x2.shape[axis]
    m, k, n = w * 8, 64, w * 32
    a = _put(mesh4x2, jax.random.normal(key, (m, k), jnp.float32) / 4,
             P(axis))
    b = _put(mesh4x2,
             jax.random.normal(jax.random.PRNGKey(1), (k, n),
                               jnp.float32) / 4, P(None, axis))
    ctx = create_ag_gemm_context(mesh4x2, axis)
    got = ag_gemm(a, b, ctx, impl="pallas")
    gold = ag_gemm(a, b, ctx, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(gold),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_gemm_rs_2d(mesh4x2, axis, key):
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)
    w = mesh4x2.shape[axis]
    m, k, n = w * 8, w * 16, 128
    a = _put(mesh4x2, jax.random.normal(key, (m, k), jnp.float32) / 4,
             P(None, axis))
    b = _put(mesh4x2,
             jax.random.normal(jax.random.PRNGKey(1), (k, n),
                               jnp.float32) / 4, P(axis))
    ctx = create_gemm_rs_context(mesh4x2, axis)
    got = gemm_rs(a, b, ctx, impl="pallas")
    gold = gemm_rs(a, b, ctx, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(gold),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_flash_decode_2d(mesh4x2, axis, key):
    from triton_dist_tpu.ops.flash_decode import (
        create_flash_decode_context, gqa_fwd_batch_decode)
    w = mesh4x2.shape[axis]
    b, hq, hkv, d, t = 2, 8, 2, 64, w * 64
    q = jax.random.normal(key, (b, hq, d), jnp.float32)
    kc = _put(mesh4x2, jax.random.normal(jax.random.PRNGKey(1),
                                         (b, t, hkv, d), jnp.float32),
              P(None, axis))
    vc = _put(mesh4x2, jax.random.normal(jax.random.PRNGKey(2),
                                         (b, t, hkv, d), jnp.float32),
              P(None, axis))
    ctx = create_flash_decode_context(mesh4x2, axis, variant="tiled",
                                      t_blk=32)
    got = gqa_fwd_batch_decode(q, kc, vc, jnp.int32(t - 5), ctx,
                               impl="pallas")
    gold = gqa_fwd_batch_decode(q, kc, vc, jnp.int32(t - 5), ctx,
                                impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(gold),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_sp_attention_fused_2d(mesh4x2, axis, key):
    from triton_dist_tpu.ops.sp_attention import (
        create_sp_attention_context, sp_ag_attention)
    w = mesh4x2.shape[axis]
    b, s, hq, hkv, d = 1, w * 128, 4, 2, 64
    q = _put(mesh4x2, jax.random.normal(key, (b, s, hq, d), jnp.float32),
             P(None, axis))
    k = _put(mesh4x2, jax.random.normal(jax.random.PRNGKey(1),
                                        (b, s, hkv, d), jnp.float32),
             P(None, axis))
    v = _put(mesh4x2, jax.random.normal(jax.random.PRNGKey(2),
                                        (b, s, hkv, d), jnp.float32),
             P(None, axis))
    ctx = create_sp_attention_context(mesh4x2, axis, causal=True)
    got = sp_ag_attention(q, k, v, ctx, impl="pallas")
    gold = sp_ag_attention(q, k, v, ctx, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(gold),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("axis", ["tp", "ep"])
def test_all_to_all_2d(mesh4x2, axis):
    from triton_dist_tpu.ops.all_to_all import (
        create_all_to_all_context, fast_all_to_all)
    w = mesh4x2.shape[axis]
    cap = 16
    rng = np.random.RandomState(0)
    send = _put(mesh4x2,
                jnp.asarray(rng.randn(w * w, cap, 128), jnp.float32),
                P(axis))
    counts = _put(mesh4x2, jnp.full((w * w,), 8, jnp.int32), P(axis))
    ctx = create_all_to_all_context(mesh4x2, axis, capacity=cap)
    got_buf, got_counts = fast_all_to_all(send, counts, ctx,
                                          impl="pallas")
    ref_buf, ref_counts = fast_all_to_all(send, counts, ctx, impl="xla")
    np.testing.assert_array_equal(np.asarray(got_counts),
                                  np.asarray(ref_counts))
    gb = np.asarray(got_buf).reshape(w, w, cap, 128)
    rb = np.asarray(ref_buf).reshape(w, w, cap, 128)
    np.testing.assert_allclose(gb[:, :, :8], rb[:, :, :8], rtol=1e-5,
                               atol=1e-5)
