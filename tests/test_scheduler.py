"""Cross-request continuous batching (serving/scheduler.py, ISSUE 5).

Quick tier: the scheduler is pure Python orchestration over the proven
stream-session programs, and the xla-impl tiny model keeps every test
CPU-cheap. Covered here:

- equivalence: scheduler results are bit-identical (greedy) to
  per-request ``Engine.serve()`` for uniform, ragged, and over-batch
  workloads, including chunked prefill;
- fairness: a short request admitted while a long generation is
  mid-decode retires while the long one is still running, under ONE
  shared batch;
- backpressure: a full admission queue yields a structured
  ``queue_full`` reply and the server survives;
- observability: ``{"cmd": "metrics"}`` exposes queue_depth /
  batch_occupancy / ttft_ms / queue_wait_ms, and a trace dump from a
  loaded server shows admit/retire events interleaved;
- the ``gen_len`` clamp echo + counter, and the client ``timeout=``.

ISSUE 6 (paged-native scheduling + prefix caching) adds: greedy
bit-exactness with the prefix cache on vs off (shared / partial / no
overlap, uniform and ragged), oversubscribed pools running through the
shared-batch path, prefix + block-pool metrics through the metrics
command and tools/report.py, and an autouse leak audit asserting every
paged engine's block pool is fully returned after each scenario.

ISSUE 11 (mega decode in the shared batch) adds: greedy bit-identity
mega-vs-plain under ragged offsets, mid-decode admission/retirement,
oversubscribed paged pools, and prefix-cache warm hits; plus the
decode-path auto-selection policy unit tests (injected device.step.*
gauge values, both flip directions, the no-measurement default, and
the TDT_MEGA_AUTO opt-out).
"""

import json
import socket
import socketserver
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
from triton_dist_tpu.serving import (ChatClient, ModelServer, QueueFull,
                                     Scheduler, fanout)


@pytest.fixture()
def tiny(mesh8, key):
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    return model, model.init(key)


@pytest.fixture()
def paged_tiny(mesh8, key):
    """xla-impl sp model on a (tp=1, sp=8) grid — the paged engine
    family, cheap enough for the quick tier."""
    from jax.sharding import Mesh
    devs = [d for d in mesh8.devices.flat]
    mesh = Mesh(np.array(devs).reshape(1, 8), ("tp", "sp"))
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=4,
                      num_key_value_heads=2, head_dim=16, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh, axis="tp", sp_axis="sp",
                     impl="xla", fwd_mode="sp")
    return model, model.init(key)


#: Paged engines created this test session — the leak-audit fixture
#: below checks every one of them after each scenario.
_PAGED_ENGINES: list = []


@pytest.fixture(autouse=True)
def _block_pool_leak_audit():
    """ISSUE 6 satellite: after EVERY scenario in this file, each paged
    engine's block pool must be back to fully-returned state — zero
    active blocks, zero outstanding commitment, free + evictable
    covering the whole pool. A retired (or
    stop()-killed) request that strands blocks is a slow production
    OOM."""
    _PAGED_ENGINES.clear()
    yield
    for eng in _PAGED_ENGINES:
        a = eng.kv.block_audit()
        assert a["active"] == 0 and a["committed"] == 0, a
        assert a["free"] + a["evictable"] == a["total"], a
    _PAGED_ENGINES.clear()


def _engine(model, batch=2, max_seq=64):
    return Engine(model, batch=batch, max_seq=max_seq,
                  prefill_mode="xla_ar", decode_mode="gemm_ar")


def _paged_engine(model, batch=2, max_seq=64, page=4, slots=None,
                  prefix=True, decode_path=None):
    eng = Engine(model, batch=batch, max_seq=max_seq,
                 prefill_mode="sp", decode_mode="sp", paged=True,
                 page_size=page, prefix_cache=prefix,
                 kv_slots_per_dev=slots,
                 **({"decode_path": decode_path} if decode_path else {}))
    _PAGED_ENGINES.append(eng)
    return eng


def _solo_paged_golden(model, params, prompt, gen_len):
    """Golden for the sp-paged family: the plain tp engine on the same
    params (token-equal across families; accepts prompt lengths that
    don't divide the sp world)."""
    eng = Engine(model, batch=1, max_seq=64, prefill_mode="xla",
                 decode_mode="xla_ar")
    out = np.asarray(eng.serve(params, jnp.asarray([prompt], jnp.int32),
                               gen_len))[0].tolist()
    return out[len(prompt):]


def _solo(model, params, prompt, gen_len, stop=()):
    """Golden: the prompt served alone, trimmed to the exact-retire
    contract (generated tokens end at the first stop token)."""
    out = np.asarray(_engine(model, batch=1).serve(
        params, jnp.asarray([prompt], jnp.int32), gen_len,
        stop_tokens=stop))[0].tolist()
    gen = out[len(prompt):]
    for i, t in enumerate(gen):
        if t in set(stop):
            return gen[:i + 1]
    return gen


def _wait_until(pred, timeout=60.0, what="condition"):
    t0 = time.monotonic()
    while not pred():
        assert time.monotonic() - t0 < timeout, f"timed out on {what}"
        time.sleep(0.02)


# ---------------------------------------------------------------------------
# Equivalence: scheduler == per-request serve(), greedy.
# ---------------------------------------------------------------------------

def test_scheduler_matches_solo_serve(tiny):
    """Uniform, ragged, AND over-batch in one workload: 6 mixed-length
    prompts through a 2-row window, all submitted concurrently, each
    bit-identical to serving it alone."""
    model, params = tiny
    sched = Scheduler(_engine(model), params).start()
    try:
        prompts = [[1, 2, 3], [9, 8], [4, 5, 6, 7], [11], [23, 29],
                   [7, 7, 7]]
        reqs = [sched.submit(p, 5) for p in prompts]
        for p, r in zip(prompts, reqs):
            assert r.result(timeout=180) == _solo(model, params, p, 5)
    finally:
        sched.stop()


def test_scheduler_stop_tokens_exact_retire(tiny):
    """Per-request stop sets retire rows exactly at the stop token."""
    model, params = tiny
    probe = _solo(*tiny, [1, 2], 6)
    stop = (probe[1],)      # 2nd generated token of the first prompt
    sched = Scheduler(_engine(model), params).start()
    try:
        prompts = [[1, 2], [3, 4], [5, 6]]
        reqs = [sched.submit(p, 6, stop_tokens=stop) for p in prompts]
        for p, r in zip(prompts, reqs):
            want = _solo(model, params, p, 6, stop=stop)
            assert r.result(timeout=180) == want, (p, want)
    finally:
        sched.stop()


def test_scheduler_chunked_prefill_matches_solo(tiny):
    """Chunked admission (TDT_PREFILL_CHUNK path): a long prompt
    prefills in slices interleaved with decode steps and still decodes
    bit-identically; a second request rides along mid-prefill."""
    model, params = tiny
    eng = _engine(model)
    sched = Scheduler(eng, params, prefill_chunk=4).start()
    try:
        long_p = list(range(1, 15))          # 14 tokens → 4 chunks of 4
        short_p = [5, 9]
        r_long = sched.submit(long_p, 5)
        r_short = sched.submit(short_p, 5)
        assert r_long.result(timeout=180) == _solo(model, params,
                                                   long_p, 5)
        assert r_short.result(timeout=180) == _solo(model, params,
                                                    short_p, 5)
        assert eng._admit_chunk is not None  # the chunked path ran
    finally:
        sched.stop()


def test_server_scheduler_roundtrip_matches_solo(tiny):
    """The whole stack — socket protocol → scheduler → shared batch —
    returns per-request results equal to solo serving; the response
    echoes the effective gen_len."""
    model, params = tiny
    srv = ModelServer(_engine(model), params, port=0).start()
    try:
        prompts = [[1, 2, 3], [9, 8], [4, 5, 6, 7]]
        outs = fanout(srv.host, srv.port,
                      [{"prompt_ids": [p], "gen_len": 4}
                       for p in prompts], timeout=180)
        for p, o in zip(prompts, outs):
            assert o.get("gen_len") == 4, o
            assert o["tokens"][0] == _solo(model, params, p, 4)
        # multi-prompt request: one connection, rows still per-prompt
        c = ChatClient(srv.host, srv.port, timeout=180)
        r = c.generate_ids(prompts, gen_len=3)
        for p, row in zip(prompts, r["tokens"]):
            assert row == _solo(model, params, p, 3)
        c.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Fairness: no head-of-line blocking under one shared batch.
# ---------------------------------------------------------------------------

def test_short_request_retires_while_long_decodes(tiny):
    """ISSUE 5 acceptance: a short request admitted while a long
    generation is mid-decode completes while the long one is STILL
    decoding — the serialized-lock server could never do this."""
    model, params = tiny
    sched = Scheduler(_engine(model, batch=2), params).start()
    try:
        r_long = sched.submit([1, 2, 3], 55)
        # wait until the long generation is genuinely mid-decode
        _wait_until(lambda: len(r_long.tokens) >= 3, what="long decode")
        r_short = sched.submit([9, 8], 2)
        short_out = r_short.result(timeout=180)
        assert not r_long.done.is_set(), \
            "short request should retire while the long one decodes"
        assert short_out == _solo(model, params, [9, 8], 2)
        r_long.result(timeout=180)      # and the long one finishes too
    finally:
        sched.stop()


# ---------------------------------------------------------------------------
# Backpressure.
# ---------------------------------------------------------------------------

def test_scheduler_queue_full_raises(tiny):
    model, params = tiny
    sched = Scheduler(_engine(model, batch=1), params,
                      max_waiting=2).start()
    try:
        r_a = sched.submit([1, 2, 3], 50)
        # A must leave the queue (admitted into the one row) first so
        # the fill below is deterministic.
        _wait_until(lambda: sched.queue_depth() == 0, what="A admitted")
        r_b = sched.submit([4, 5], 4)           # queue slot 1
        r_c = sched.submit([6, 7], 4)           # queue slot 2 → full
        with pytest.raises(QueueFull):
            sched.submit([6], 2)
        # submit_many is atomic: a 2-prompt batch (which FITS capacity,
        # so it is retryable) into a full queue rejects BOTH — no
        # half-admitted client batch.
        with pytest.raises(QueueFull):
            sched.submit_many([[7], [8]], 2)
        # ... while a batch LARGER than capacity can never be admitted
        # and refuses as non-retryable ValueError instead.
        with pytest.raises(ValueError, match="split the batch"):
            sched.submit_many([[7], [8], [9]], 2)
        assert r_a.result(timeout=180) and r_b.result(timeout=180)
        assert r_c.result(timeout=180)
    finally:
        sched.stop()


def test_server_backpressure_structured_reply(tiny):
    """The protocol-level contract: a full queue answers a structured
    queue_full reply and the server keeps serving afterwards."""
    model, params = tiny
    srv = ModelServer(_engine(model, batch=1), params, port=0,
                      max_waiting=1).start()
    try:
        c = ChatClient(srv.host, srv.port, timeout=180)
        done: dict = {}

        def bg(name, prompt, gen):
            cc = ChatClient(srv.host, srv.port, timeout=180)
            done[name] = cc.generate_ids([prompt], gen_len=gen)
            cc.close()

        ta = threading.Thread(target=bg, args=("a", [1, 2, 3], 55),
                              daemon=True)
        ta.start()
        # wait until A occupies the row (metrics don't take any lock)
        _wait_until(lambda: c.request({"cmd": "metrics"})["metrics"]
                    ["gauges"].get("serving.batch_occupancy", 0) >= 1,
                    what="A occupying the batch")
        tb = threading.Thread(target=bg, args=("b", [4, 5], 40),
                              daemon=True)
        tb.start()
        _wait_until(lambda: c.request({"cmd": "metrics"})["metrics"]
                    ["gauges"].get("serving.queue_depth", 0) >= 1,
                    what="B queued")
        # The raw protocol reply is under test: opt out of the
        # client's sleep-and-retry-on-retry_after_ms (ISSUE 15).
        raw = ChatClient(srv.host, srv.port, timeout=180,
                         retry_shed=False)
        rej = raw.generate_ids([[6]], gen_len=2)
        raw.close()
        assert rej.get("type") == "queue_full", rej
        assert "max_waiting" in rej and "queue_depth" in rej
        # The backpressure hint rides the reply (rolling TPOT x queue
        # depth, clamped — docs/serving.md).
        assert isinstance(rej.get("retry_after_ms"), int)
        assert rej["retry_after_ms"] >= 25
        ta.join(timeout=180)
        tb.join(timeout=180)
        assert "tokens" in done["a"] and "tokens" in done["b"]
        ok = c.generate_ids([[5]], gen_len=2)   # server survives
        assert "tokens" in ok
        m = c.request({"cmd": "metrics"})["metrics"]
        assert m["counters"].get("server.backpressure_replies", 0) >= 1
        c.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Observability: metrics + trace acceptance.
# ---------------------------------------------------------------------------

def test_metrics_and_trace_show_batch_churn(tiny):
    """ISSUE 5 acceptance: metrics expose queue_depth /
    batch_occupancy / ttft_ms / queue_wait_ms, and a trace dump from a
    loaded server shows admit/retire instants interleaved — some
    request admitted between another's admit and retire."""
    model, params = tiny
    srv = ModelServer(_engine(model, batch=2), params, port=0).start()
    try:
        outs = fanout(srv.host, srv.port,
                      [{"prompt_ids": [[1 + i, 2 + i]], "gen_len": 6}
                       for i in range(5)], timeout=180)
        assert all("tokens" in o for o in outs), outs
        c = ChatClient(srv.host, srv.port, timeout=180)
        m = c.request({"cmd": "metrics"})["metrics"]
        assert "serving.queue_depth" in m["gauges"]
        assert "serving.batch_occupancy" in m["gauges"]
        assert m["histograms"]["serving.ttft_ms"]["count"] >= 5
        assert m["histograms"]["serving.queue_wait_ms"]["count"] >= 5
        assert m["counters"]["serving.admitted"] >= 5
        assert m["counters"]["serving.retired"] >= 5
        d = c.dump_trace(seconds=600)
        c.close()
        with open(d["dumped"]) as f:
            evs = json.load(f)["traceEvents"]
        admits = sorted((e["ts"], e["args"]["rid"]) for e in evs
                        if e["name"] == "serving.admit")
        retires = {e["args"]["rid"]: e["ts"] for e in evs
                   if e["name"] == "serving.retire"}
        assert len(admits) >= 5 and len(retires) >= 5
        # interleaving: some OTHER request was admitted inside another
        # request's admit→retire window (rows churn through the batch)
        assert any(ts_a < ts_b < retires[rid_a]
                   for ts_a, rid_a in admits
                   for ts_b, rid_b in admits
                   if rid_a != rid_b and rid_a in retires), \
            "no admission interleaved with a live request"
        # every admit instant carries the request's trace id
        tids = {e["args"].get("trace_id") for e in evs
                if e["name"] == "serving.admit"}
        assert all(tids) and len(tids) >= 5
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Satellites: gen_len clamp echo, client timeout, legacy path.
# ---------------------------------------------------------------------------

def test_gen_len_clamp_echo_and_counter(tiny):
    model, params = tiny
    srv = ModelServer(_engine(model, batch=1, max_seq=16), params,
                      port=0).start()
    try:
        c = ChatClient(srv.host, srv.port, timeout=180)
        r = c.generate_ids([[1, 2, 3]], gen_len=500)
        assert r["gen_len"] == 13            # max_seq 16 − prompt 3
        assert len(r["tokens"][0]) <= 13
        m = c.request({"cmd": "metrics"})["metrics"]
        assert m["counters"]["server.gen_len_clamped"] == 1
        r2 = c.generate_ids([[1, 2]], gen_len=4)   # unclamped echoes
        assert r2["gen_len"] == 4                  # the request as-is
        m = c.request({"cmd": "metrics"})["metrics"]
        assert m["counters"]["server.gen_len_clamped"] == 1
        c.close()
    finally:
        srv.stop()


def test_client_timeout_on_wedged_server():
    """A server that accepts but never answers must raise TimeoutError
    within the client timeout, not block forever (the satellite fix)."""
    class _Mute(socketserver.BaseRequestHandler):
        def handle(self):
            time.sleep(30)

    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _Mute)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        host, port = srv.server_address
        c = ChatClient(host, port, timeout=0.3)
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            c.request({"prompt_ids": [[1]], "gen_len": 1})
        assert time.monotonic() - t0 < 5.0
        # per-call override on a fresh connection
        c2 = ChatClient(host, port)
        with pytest.raises(TimeoutError):
            c2.request({"cmd": "metrics"}, timeout=0.2)
    finally:
        srv.shutdown()
        srv.server_close()


def test_scheduler_stop_unblocks_waiters(tiny):
    model, params = tiny
    sched = Scheduler(_engine(model, batch=1), params).start()
    r = sched.submit([1, 2, 3], 60)
    _wait_until(lambda: len(r.tokens) >= 1, what="decode started")
    sched.stop()
    with pytest.raises(RuntimeError, match="scheduler stopped"):
        r.result(timeout=30)
    with pytest.raises(RuntimeError, match="not running"):
        sched.submit([1], 1)


def test_scheduler_invalid_requests_fail_fast(tiny):
    model, params = tiny
    sched = Scheduler(_engine(model, batch=1, max_seq=16), params).start()
    try:
        with pytest.raises(ValueError, match="non-empty"):
            sched.submit([], 4)
        with pytest.raises(ValueError, match="max_seq"):
            sched.submit(list(range(1, 15)), 10)
        r = sched.submit([1, 2], 0)          # gen_len 0: trivially done
        assert r.result(timeout=5) == []
        out = sched.generate([1, 2, 3], 3)   # scheduler still healthy
        assert out == _solo(model, params, [1, 2, 3], 3)
    finally:
        sched.stop()


def test_pump_death_unblocks_and_stops_accepting(tiny, monkeypatch):
    """A pump thread that dies (even during SESSION CONSTRUCTION — an
    oversubscribed paged pool is legal for plain serve() yet asserts
    in a stream session) must fail every waiter and flip the scheduler
    to not-running, not leave handlers blocked on result() forever
    (review finding)."""
    model, params = tiny
    eng = _engine(model, batch=1)
    monkeypatch.setattr(
        eng, "stream_session",
        lambda p: (_ for _ in ()).throw(RuntimeError("pool exhausted")))
    sched = Scheduler(eng, params).start()
    try:
        r = sched.submit([1, 2], 4)
    except RuntimeError:
        pass                    # pump already died — submit refused
    else:
        with pytest.raises(RuntimeError, match="scheduler"):
            r.result(timeout=30)
    _wait_until(lambda: not sched._running, what="pump marked dead")
    with pytest.raises(RuntimeError, match="not running"):
        sched.submit([1], 1)
    sched.stop()


def test_oversized_batch_is_not_retryable_queue_full(tiny):
    """A single request with more prompts than max_waiting can NEVER
    be admitted — it must fail as a non-retryable error, not a
    'retry later' queue_full reply (review finding)."""
    model, params = tiny
    srv = ModelServer(_engine(model, batch=1), params, port=0,
                      max_waiting=2).start()
    try:
        c = ChatClient(srv.host, srv.port, timeout=180)
        r = c.generate_ids([[1], [2], [3]], gen_len=2)
        assert "error" in r and r.get("type") != "queue_full", r
        assert "split the batch" in r["error"]
        ok = c.generate_ids([[1], [2]], gen_len=2)  # fits → served
        assert "tokens" in ok
        c.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Paged-native scheduling + cross-request prefix caching (ISSUE 6).
# ---------------------------------------------------------------------------

def test_paged_prefix_cache_bit_exact(paged_tiny):
    """Tentpole acceptance: greedy outputs are bit-identical with the
    prefix cache enabled vs disabled, across shared-, partial-, and
    no-overlap prompts of mixed (ragged) lengths — and both match the
    solo golden, so they can't be identically wrong."""
    model, params = paged_tiny
    pre = list(range(1, 9))                 # 8 tokens = 2 full pages
    prompts = [pre + [20],                  # full shared prefix
               pre + [30, 31],              # ... ragged length
               pre[:4] + [40, 41],          # partial overlap (1 page)
               [50, 51, 52],                # no overlap
               pre + [60]]                  # another full hit
    outs = {}
    for flag in (True, False):
        sched = Scheduler(_paged_engine(model, prefix=flag),
                          params).start()
        try:
            reqs = [sched.submit(p, 5) for p in prompts]
            outs[flag] = [r.result(timeout=180) for r in reqs]
        finally:
            sched.stop()
    assert outs[True] == outs[False]
    for p, row in zip(prompts, outs[True]):
        assert row == _solo_paged_golden(model, params, p, 5), p


def test_paged_prefix_cache_uniform_prompts_bit_exact(paged_tiny):
    """Same acceptance, uniform lengths: every prompt shares the full
    preamble and the warm admissions demonstrably skipped prefill."""
    model, params = paged_tiny
    pre = list(range(3, 11))
    prompts = [pre + [t] for t in (21, 22, 23, 24)]
    eng = _paged_engine(model, prefix=True)
    sched = Scheduler(eng, params).start()
    try:
        reqs = [sched.submit(p, 4) for p in prompts]
        got = [r.result(timeout=180) for r in reqs]
    finally:
        sched.stop()
    for p, row in zip(prompts, got):
        assert row == _solo_paged_golden(model, params, p, 4), p
    st = eng.kv.prefix.stats()
    assert st["hit_blocks"] >= 6, st     # requests 2..4 each hit 2 blocks


def test_oversubscribed_pool_runs_shared_batch(paged_tiny):
    """ISSUE 6 acceptance: a paged engine whose pool CANNOT hold every
    row (the engine the old auto-detect sent to the serialized lock)
    runs through the shared-batch scheduler path — more concurrent
    requests than whole-row capacity, correct results, no fallback."""
    model, params = paged_tiny
    # batch=3 rows x 2 blocks/dev whole-row = 6; the pool has 5 slots
    # (all usable — the sentinel page rides outside the pool) ->
    # whole-row streaming could hold at most 2 lanes and the OLD
    # session refused to start at all.
    eng = _paged_engine(model, batch=3, slots=5)
    srv = ModelServer(eng, params, port=0).start()
    try:
        assert srv.scheduler is not None   # auto-detect: no fallback
        prompts = [[2 * i + 1, 2 * i + 2] for i in range(5)]
        outs = fanout(srv.host, srv.port,
                      [{"prompt_ids": [p], "gen_len": 6}
                       for p in prompts], timeout=180)
        for p, o in zip(prompts, outs):
            assert o["tokens"][0] == _solo_paged_golden(
                model, params, p, 6), (p, o)
        c = ChatClient(srv.host, srv.port, timeout=180)
        m = c.request({"cmd": "metrics"})["metrics"]
        c.close()
        assert m["counters"]["serving.admitted"] >= 5
        # block-pool occupancy gauges ride the same snapshot
        assert "kv.blocks_free" in m["gauges"]
        assert "kv.blocks_active" in m["gauges"]
    finally:
        srv.stop()


def test_oversubscribed_requests_wait_not_die(paged_tiny):
    """Block-granular backpressure: when the pool is too tight for two
    concurrent generations, the second request WAITS for the first
    row's eager block release instead of failing — and a request that
    could never fit fails fast as a non-retryable error."""
    model, params = paged_tiny
    eng = _paged_engine(model, batch=2, slots=1)  # 1 block/dev
    sched = Scheduler(eng, params).start()
    try:
        # Each needs 1 block on device 0 -> strictly one at a time.
        reqs = [sched.submit([7 + i, 8], 2) for i in range(3)]
        for i, r in enumerate(reqs):
            got = r.result(timeout=180)
            assert got == _solo_paged_golden(model, params,
                                             [7 + i, 8], 2)
        with pytest.raises(ValueError, match="never fit"):
            sched.submit([1, 2, 3, 4, 5], 4)   # 2 blocks on device 0
    finally:
        sched.stop()


def test_admission_upload_failure_releases_blocks(paged_tiny,
                                                  monkeypatch):
    """A failure in the block-table device upload during paged
    admission must release the row's just-allocated blocks and leave
    the lane clean for the next admission (review regression: the
    upload sat OUTSIDE _admit_paged's rollback window, so it stranded
    the blocks and every later admission into that row tripped the
    already-holds-blocks assert)."""
    model, params = paged_tiny
    eng = _paged_engine(model, batch=2)
    sched = Scheduler(eng, params).start()
    try:
        # Warm: session construction + one clean admission/retire
        # cycle consume their block_table() calls before we arm.
        golden = _solo_paged_golden(model, params, [1, 2, 3], 2)
        assert sched.submit([1, 2, 3], 2).result(timeout=180) == golden
        orig, armed = eng.kv.block_table, {"left": 1}

        def flaky():
            if armed["left"]:
                armed["left"] -= 1
                raise RuntimeError("injected device upload failure")
            return orig()

        monkeypatch.setattr(eng.kv, "block_table", flaky)
        with pytest.raises(RuntimeError, match="injected"):
            sched.submit([1, 2, 3], 2).result(timeout=180)
        # The degraded row's blocks came back: same prompt admits into
        # the same lane and matches the golden (the autouse leak audit
        # re-checks the pool after teardown).
        assert sched.submit([1, 2, 3], 2).result(timeout=180) == golden
    finally:
        sched.stop()


def test_paged_prefix_metrics_and_report(paged_tiny):
    """ISSUE 6 acceptance: serving.prefix_hit_rate /
    serving.prefill_tokens_saved and the kv.* block gauges are visible
    through {"cmd": "metrics"} and render in tools/report.py."""
    model, params = paged_tiny
    eng = _paged_engine(model, batch=2)
    srv = ModelServer(eng, params, port=0).start()
    try:
        pre = list(range(1, 9))
        outs = fanout(srv.host, srv.port,
                      [{"prompt_ids": [pre + [30 + i]], "gen_len": 3}
                       for i in range(4)], timeout=180)
        assert all("tokens" in o for o in outs), outs
        c = ChatClient(srv.host, srv.port, timeout=180)
        m = c.request({"cmd": "metrics"})["metrics"]
        c.close()
        assert m["counters"]["serving.prefill_tokens_saved"] >= 24
        assert m["gauges"]["serving.prefix_hit_rate"] > 0
        assert m["gauges"]["kv.blocks_cached"] >= 2  # preamble resident
        from triton_dist_tpu.tools.report import render_telemetry
        md = render_telemetry(m)
        assert "kv block pool" in md and "kv.blocks_free" in md
        assert "serving.prefix_hit_rate" in md
    finally:
        srv.stop()


def test_server_serialized_path_still_works(tiny):
    """scheduler=False keeps the pre-scheduler serialized route (now
    an explicit override only — mega engines schedule) intact, clamp
    echo included."""
    model, params = tiny
    srv = ModelServer(_engine(model, batch=1, max_seq=16), params,
                      port=0, scheduler=False).start()
    try:
        assert srv.scheduler is None
        c = ChatClient(srv.host, srv.port, timeout=180)
        r = c.generate_ids([[1, 2, 3]], gen_len=4)
        assert r["tokens"][0] == _solo(model, params, [1, 2, 3], 4)
        assert r["gen_len"] == 4
        r2 = c.generate_ids([[1, 2, 3]], gen_len=500)
        assert r2["gen_len"] == 13
        c.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# ISSUE 8: the serving SLO observatory through a live scheduler.
# ---------------------------------------------------------------------------

def test_response_timing_waterfall_sums_to_wall_time(tiny):
    """Acceptance: the attribution waterfall's segments partition the
    request's measured wall time — segment sum == total exactly (one
    clock, by construction), total within 5 ms of the server-measured
    latency (handler↔pump handoff is the only slack)."""
    model, params = tiny
    srv = ModelServer(_engine(model), params, port=0).start()
    try:
        c = ChatClient(srv.host, srv.port, timeout=180)
        c.generate_ids([[1, 2, 3]], gen_len=3)       # warm compiles
        r = c.generate_ids([[4, 5, 6]], gen_len=5)
        c.close()
        (t,) = r["timing"]
        seg = t["segments"]
        assert set(seg) == {"queue_wait_ms", "prefill_ms", "decode_ms"}
        assert sum(seg.values()) == pytest.approx(t["total_ms"],
                                                  abs=0.01)
        assert abs(t["total_ms"] - r["latency_ms"]) < 5.0, (t, r)
        assert t["tokens"] == len(r["tokens"][0]) == 5
        assert t["prompt_tokens"] == 3
        assert t["tpot_ms"] == pytest.approx(
            seg["decode_ms"] / 4, abs=0.01)
        assert t["trace_id"] == r["trace_id"]
    finally:
        srv.stop()


def test_request_stats_ring_newest_first(tiny):
    model, params = tiny
    srv = ModelServer(_engine(model), params, port=0).start()
    try:
        c = ChatClient(srv.host, srv.port, timeout=180)
        for i in range(3):
            c.generate_ids([[1 + i, 2, 3]], gen_len=2)
        stats = c.request({"cmd": "request_stats", "last": 2})
        all_stats = c.request({"cmd": "request_stats"})
        c.close()
        assert len(stats["requests"]) == 2
        assert len(all_stats["requests"]) == 3
        rids = [r["rid"] for r in all_stats["requests"]]
        assert rids == sorted(rids, reverse=True)    # newest first
        for r in all_stats["requests"]:
            assert sum(r["segments"].values()) == pytest.approx(
                r["total_ms"], abs=0.01)
    finally:
        srv.stop()


def test_waterfall_reports_prefix_savings(paged_tiny):
    """A warm shared-prefix admission's waterfall shows the skipped
    tokens (cached_tokens > 0) — the prefix-cache savings leg of the
    attribution story."""
    model, params = paged_tiny
    eng = _paged_engine(model, batch=2)
    srv = ModelServer(eng, params, port=0).start()
    try:
        pre = list(range(1, 9))                      # two full pages
        c = ChatClient(srv.host, srv.port, timeout=180)
        c.generate_ids([pre + [30]], gen_len=2)      # indexes preamble
        r = c.generate_ids([pre + [31]], gen_len=2)  # warm hit
        c.close()
        (t,) = r["timing"]
        assert t["cached_tokens"] >= 8, t
        assert t["prompt_tokens"] == 9
    finally:
        srv.stop()


def test_latency_regression_breaches_and_arms_recorder(tiny,
                                                       monkeypatch):
    """Acceptance: a latency regression (every TTFT 'violates' a
    deliberately impossible threshold — the CPU-tier stand-in for a
    fault-injected spike) drives a fast+slow burn breach through the
    LIVE scheduler, arms the flight recorder exactly once, and the
    dump validates as a Perfetto artifact."""
    import json as _json
    monkeypatch.setenv("TDT_SLO_TTFT_P99_MS", "0.001")
    from triton_dist_tpu.obs import flight, trace
    model, params = tiny
    srv = ModelServer(_engine(model), params, port=0).start()
    try:
        assert trace.enabled()                       # server default
        c = ChatClient(srv.host, srv.port, timeout=180)
        before = c.request({"cmd": "metrics"})["metrics"]
        b0 = before["counters"].get("serving.slo_breaches", 0)
        # Enough violating requests to clear the slow-window sample
        # floor (TDT_SLO_MIN_SAMPLES): a sustained regression, not a
        # single-request blip (which must NOT page — see below).
        outs = fanout(srv.host, srv.port,
                      [{"prompt_ids": [[1 + i, 2, 3]], "gen_len": 3}
                       for i in range(12)], timeout=180)
        assert all("tokens" in o for o in outs), outs
        # The metrics scrape forces a fresh evaluation.
        m = c.request({"cmd": "metrics"})["metrics"]
        assert m["counters"]["serving.slo_breaches"] == b0 + 1
        assert m["gauges"]["serving.slo_breached.ttft_p99"] == 1
        assert m["gauges"]["serving.slo_burn.ttft_p99"] > 1
        rec = flight.last_record()
        assert rec is not None and rec["reason"] == "slo_ttft_p99"
        dumps0 = rec["count"]
        # Sustained breach: another request + scrape, no second dump
        # (transition-gated), no second breach count.
        c.generate_ids([[4, 5, 6]], gen_len=3)
        m2 = c.request({"cmd": "metrics"})["metrics"]
        c.close()
        assert m2["counters"]["serving.slo_breaches"] == b0 + 1
        assert flight.last_record()["count"] == dumps0
        with open(rec["path"]) as f:
            chrome = _json.load(f)
        from triton_dist_tpu.tools import trace_export
        errors, _ = trace_export.validate(chrome)
        assert errors == [], errors
    finally:
        srv.stop()


def test_slo_no_false_positive_under_default_targets(tiny):
    """Default (generous) targets must never breach on healthy
    quick-tier traffic — the false-positive half of the acceptance
    bar."""
    model, params = tiny
    srv = ModelServer(_engine(model), params, port=0).start()
    try:
        c = ChatClient(srv.host, srv.port, timeout=180)
        b0 = c.request({"cmd": "metrics"})["metrics"]["counters"].get(
            "serving.slo_breaches", 0)    # registry is process-global
        outs = fanout(srv.host, srv.port,
                      [{"prompt_ids": [[1 + i, 2]], "gen_len": 4}
                       for i in range(4)], timeout=180)
        assert all("tokens" in o for o in outs), outs
        m = c.request({"cmd": "metrics"})["metrics"]
        c.close()
        assert m["counters"].get("serving.slo_breaches", 0) == b0
        for k, v in m["gauges"].items():
            if k.startswith("serving.slo_breached."):
                assert v == 0, k
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# ISSUE 11: mega decode in the shared batch + decode-path auto-selection.
# ---------------------------------------------------------------------------

def test_scheduler_mega_matches_plain_ragged_overbatch(tiny):
    """Tentpole acceptance (dense family): the mega one-program step
    pumped by the scheduler is greedily bit-identical to the plain
    path under ragged per-row offsets AND mid-decode
    admission/retirement — 6 mixed-length prompts through a 2-row
    window, so rows retire and re-admit while others decode."""
    model, params = tiny
    prompts = [[1, 2, 3], [9, 8], [4, 5, 6, 7], [11], [23, 29],
               [7, 7, 7]]
    outs = {}
    for path in ("mega", "plain"):
        eng = Engine(model, batch=2, max_seq=64, prefill_mode="xla_ar",
                     decode_mode="gemm_ar", decode_path=path)
        sched = Scheduler(eng, params).start()
        try:
            reqs = [sched.submit(p, 5) for p in prompts]
            outs[path] = [r.result(timeout=180) for r in reqs]
        finally:
            sched.stop()
    assert outs["mega"] == outs["plain"]
    for p, row in zip(prompts, outs["mega"]):
        assert row == _solo(model, params, p, 5), p


def test_scheduler_mega_paged_prefix_matches_plain(paged_tiny):
    """Tentpole acceptance (paged family): Engine(use_mega=True,
    paged=True) serves through the scheduler — per-row offsets against
    the paged pool's table lanes, prefix-cache WARM hits included —
    bit-identical to the plain paged scheduler path and to the solo
    golden."""
    model, params = paged_tiny
    pre = list(range(1, 9))                 # 8 tokens = 2 full pages
    prompts = [pre + [20],                  # cold (indexes the preamble)
               pre + [30, 31],              # warm full-prefix hit, ragged
               pre[:4] + [40, 41],          # partial overlap
               [50, 51, 52],                # no overlap
               pre + [60]]                  # another warm hit
    outs = {}
    hits = {}
    for path in ("mega", "plain"):
        eng = _paged_engine(model, decode_path=path)
        sched = Scheduler(eng, params).start()
        try:
            reqs = [sched.submit(p, 5) for p in prompts]
            outs[path] = [r.result(timeout=180) for r in reqs]
        finally:
            sched.stop()
        hits[path] = eng.kv.prefix.stats()["hit_blocks"]
    assert outs["mega"] == outs["plain"]
    assert hits["mega"] >= 4, hits          # the warm hits really hit
    for p, row in zip(prompts, outs["mega"]):
        assert row == _solo_paged_golden(model, params, p, 5), p


def test_scheduler_mega_oversubscribed_pool(paged_tiny):
    """The mega step streams an OVERSUBSCRIBED pool like the plain one:
    more concurrent requests than whole-row capacity, block-granular
    admission waits, correct results (the leak audit re-checks the
    pool after teardown)."""
    model, params = paged_tiny
    eng = _paged_engine(model, batch=3, slots=5, decode_path="mega")
    sched = Scheduler(eng, params).start()
    try:
        prompts = [[2 * i + 1, 2 * i + 2] for i in range(5)]
        reqs = [sched.submit(p, 6) for p in prompts]
        for p, r in zip(prompts, reqs):
            assert r.result(timeout=180) == _solo_paged_golden(
                model, params, p, 6), p
    finally:
        sched.stop()


def test_decode_path_auto_policy_unit(monkeypatch):
    """Auto-selection consumes MEASURED device.step.* gauges: both
    flip directions, the no-measurement default, provenance counters,
    and the TDT_MEGA_AUTO opt-out."""
    from triton_dist_tpu import obs
    from triton_dist_tpu.models.engine import DecodePathPolicy
    reg = obs.enable(obs.Registry())
    try:
        pol = DecodePathPolicy()
        # No measurement → the chip-prior default (mega), counted as
        # provenance "default".
        assert pol.decide() == "mega"
        # One-sided measurement is NOT a comparison → still default.
        reg.gauge("device.step.mega.total_ms").set(5.0)
        assert pol.decide() == "mega"
        snap = reg.snapshot()["counters"]
        assert snap["engine.decode_path.auto_source.default"] == 2
        # Both measured: slower mega → plain ...
        reg.gauge("device.step.plain.total_ms").set(2.0)
        assert pol.decide() == "plain"
        assert reg.snapshot()["gauges"]["serving.mega_selected"] == 0.0
        # ... and the other flip direction.
        reg.gauge("device.step.plain.total_ms").set(9.0)
        assert pol.decide() == "mega"
        snap = reg.snapshot()
        assert snap["counters"]["engine.decode_path.auto_mega"] == 3
        assert snap["counters"]["engine.decode_path.auto_plain"] == 1
        assert snap["counters"][
            "engine.decode_path.auto_source.measured"] == 2
        assert snap["gauges"]["serving.mega_selected"] == 1.0
        # Per-WINDOW normalization: a 4-iteration breach capture's
        # unioned plain total (9 ms / 4 windows = 2.25/step) must beat
        # a single-window 5 ms mega step — comparing raw unions would
        # pick mega.
        reg.gauge("device.step.plain.windows").set(4.0)
        assert pol.decide() == "plain"
        reg.gauge("device.step.plain.windows").set(1.0)
        # Probe beat: every PROBE_EVERY-th SAMPLABLE decision runs the
        # OTHER path (provenance "probe") so a live sampler can
        # measure or refresh it — without it, only the winning path's
        # gauge ever updates and the policy could never correct
        # itself. Doubly measurability-gated: no probes without a live
        # devprof sampler, and none for non-samplable decisions
        # (serve() resolved outside the pump would run a whole
        # generation on the probed path with nothing able to capture
        # it).
        kinds = [pol.decide(samplable=True)
                 for _ in range(DecodePathPolicy.PROBE_EVERY)]
        assert "plain" not in kinds, "probe fired with no sampler"
        from triton_dist_tpu.obs import devprof
        sampler = devprof.PumpSampler(every=10 ** 9, sync=True)
        kinds = [pol.decide()         # non-samplable: still no probe
                 for _ in range(DecodePathPolicy.PROBE_EVERY)]
        assert "plain" not in kinds, "probe fired for serve()-style call"
        kinds = [pol.decide(samplable=True)
                 for _ in range(DecodePathPolicy.PROBE_EVERY)]
        assert "plain" in kinds, "no probe fired in a full period"
        assert reg.snapshot()["counters"][
            "engine.decode_path.auto_source.probe"] >= 1
        del sampler
        # Env opt-out: auto resolves to plain regardless of gauges.
        monkeypatch.setenv("TDT_MEGA_AUTO", "0")
        off = DecodePathPolicy()
        reg.gauge("device.step.plain.total_ms").set(999.0)
        assert off.decide() == "plain"
        assert reg.snapshot()["counters"][
            "engine.decode_path.auto_source.env_off"] == 1
    finally:
        obs.disable()


def test_scheduler_auto_decode_path_serves(tiny):
    """Engine(decode_path="auto") through the scheduler: decisions are
    taken per pump iteration (provenance counted) and results stay
    bit-identical to solo serving whatever the policy picks."""
    from triton_dist_tpu import obs
    model, params = tiny
    eng = Engine(model, batch=2, max_seq=64, prefill_mode="xla_ar",
                 decode_mode="gemm_ar", decode_path="auto")
    reg = obs.enable(obs.Registry())
    try:
        sched = Scheduler(eng, params).start()
        try:
            prompts = [[1, 2, 3], [9, 8], [4, 5, 6, 7]]
            reqs = [sched.submit(p, 4) for p in prompts]
            got = [r.result(timeout=180) for r in reqs]
        finally:
            sched.stop()
        for p, row in zip(prompts, got):
            assert row == _solo(model, params, p, 4), p
        snap = reg.snapshot()["counters"]
        decisions = (snap.get("engine.decode_path.auto_mega", 0)
                     + snap.get("engine.decode_path.auto_plain", 0))
        assert decisions >= 1
        sources = [k for k in snap
                   if k.startswith("engine.decode_path.auto_source.")]
        assert sources, snap
    finally:
        obs.disable()


# ---------------------------------------------------------------------------
# ISSUE 13: speculative decoding in the shared batch.
# ---------------------------------------------------------------------------

def _spec_cfg(k=4, **kw):
    from triton_dist_tpu.models.spec import SpecConfig
    return SpecConfig(k=k, **kw)


def test_scheduler_spec_matches_plain_ragged_overbatch(tiny):
    """Tentpole acceptance (dense family): spec-on greedy outputs are
    bit-identical to spec-off across ragged mixed-length prompts AND
    mid-decode admission/retirement — 7 prompts through a 2-row
    window, so rows retire and re-admit while others burst; the
    repetitive prompt exercises real multi-token accepts."""
    model, params = tiny
    prompts = [[1, 2, 3], [9, 8], [4, 5, 6, 7], [11], [23, 29],
               [7, 7, 7], [5, 6, 5, 6, 5, 6, 5]]
    outs = {}
    for tag, spec in (("on", _spec_cfg()), ("off", None)):
        eng = Engine(model, batch=2, max_seq=64, prefill_mode="xla_ar",
                     decode_mode="gemm_ar", spec=spec)
        sched = Scheduler(eng, params).start()
        try:
            reqs = [sched.submit(p, 9) for p in prompts]
            outs[tag] = [r.result(timeout=180) for r in reqs]
        finally:
            sched.stop()
    assert outs["on"] == outs["off"]
    for p, row in zip(prompts, outs["on"]):
        assert row == _solo(model, params, p, 9), p


def test_scheduler_spec_paged_prefix_matches_plain(paged_tiny):
    """Tentpole acceptance (paged family): spec bursts against the
    paged pool's table lanes — prefix-cache WARM hits included — are
    bit-identical to spec-off and to the solo golden; the autouse leak
    audit re-checks both pools after teardown (multi-token commits +
    rejected-tail rollbacks must strand nothing)."""
    model, params = paged_tiny
    pre = list(range(1, 9))                 # 8 tokens = 2 full pages
    prompts = [pre + [20],                  # cold (indexes the preamble)
               pre + [30, 31],              # warm full-prefix hit, ragged
               pre[:4] + [40, 41],          # partial overlap
               [50, 51, 52],                # no overlap
               pre + [60]]                  # another warm hit
    outs = {}
    hits = {}
    for tag, spec in (("on", _spec_cfg()), ("off", None)):
        eng = Engine(model, batch=2, max_seq=64, prefill_mode="sp",
                     decode_mode="sp", paged=True, page_size=4,
                     prefix_cache=True, spec=spec)
        _PAGED_ENGINES.append(eng)
        sched = Scheduler(eng, params).start()
        try:
            reqs = [sched.submit(p, 6) for p in prompts]
            outs[tag] = [r.result(timeout=180) for r in reqs]
        finally:
            sched.stop()
        hits[tag] = eng.kv.prefix.stats()["hit_blocks"]
    assert outs["on"] == outs["off"]
    assert hits["on"] >= 4, hits            # the warm hits really hit
    for p, row in zip(prompts, outs["on"]):
        assert row == _solo_paged_golden(model, params, p, 6), p


def test_scheduler_spec_oversubscribed_pool(paged_tiny):
    """Spec bursts stream an OVERSUBSCRIBED pool: multi-block commits
    and rejected-tail rollbacks against a pool too small for every
    row, block-granular admission waits, correct results (the leak
    audit re-checks the pool after teardown)."""
    model, params = paged_tiny
    eng = Engine(model, batch=3, max_seq=64, prefill_mode="sp",
                 decode_mode="sp", paged=True, page_size=4,
                 kv_slots_per_dev=5, spec=_spec_cfg())
    _PAGED_ENGINES.append(eng)
    sched = Scheduler(eng, params).start()
    try:
        prompts = [[2 * i + 1, 2 * i + 2] for i in range(5)]
        reqs = [sched.submit(p, 6) for p in prompts]
        for p, r in zip(prompts, reqs):
            assert r.result(timeout=180) == _solo_paged_golden(
                model, params, p, 6), p
    finally:
        sched.stop()


def test_scheduler_spec_stop_tokens_retire_mid_burst(tiny):
    """A stop token landing MID-burst retires the row at that token
    and discards the burst's tail — the per-request stop contract is
    unchanged by variable tokens-per-step."""
    model, params = tiny
    probe = _solo(*tiny, [5, 6, 5, 6, 5, 6, 5], 9)
    stop = (probe[3],)          # 4th generated token
    prompts = [[5, 6, 5, 6, 5, 6, 5], [1, 2, 3], [9, 8]]
    outs = {}
    for tag, spec in (("on", _spec_cfg()), ("off", None)):
        eng = Engine(model, batch=2, max_seq=64, prefill_mode="xla_ar",
                     decode_mode="gemm_ar", spec=spec)
        sched = Scheduler(eng, params).start()
        try:
            reqs = [sched.submit(p, 9, stop_tokens=stop)
                    for p in prompts]
            outs[tag] = [r.result(timeout=180) for r in reqs]
        finally:
            sched.stop()
    assert outs["on"] == outs["off"]
    for p, row in zip(prompts, outs["on"]):
        assert row == _solo(model, params, p, 9, stop=stop), p


def test_spec_metrics_and_waterfall_through_server(tiny):
    """ISSUE 13 acceptance: serving.spec_accept_rate /
    serving.spec_tokens_per_step are visible through
    {"cmd": "metrics"}, the request waterfalls carry draft/verify
    segments through "timing" and request_stats, top.py renders the
    accept-rate gauge, and report.py's serving section carries the
    spec rows."""
    model, params = tiny
    eng = Engine(model, batch=2, max_seq=64, prefill_mode="xla_ar",
                 decode_mode="gemm_ar", spec=_spec_cfg())
    srv = ModelServer(eng, params, port=0).start()
    try:
        c = ChatClient(srv.host, srv.port, timeout=180)
        c.generate_ids([[5, 6, 5, 6, 5, 6, 5]], gen_len=9)  # warm
        r = c.generate_ids([[5, 6, 5, 6, 5, 6, 5]], gen_len=9)
        m = c.request({"cmd": "metrics"})["metrics"]
        stats = c.request({"cmd": "request_stats", "last": 1})
        c.close()
        assert m["counters"]["serving.spec_steps"] >= 1
        assert 0.0 <= m["gauges"]["serving.spec_accept_rate"] <= 1.0
        assert m["gauges"]["serving.spec_tokens_per_step"] >= 1.0
        assert "engine.spec_verify_ms" in m["histograms"]
        (t,) = r["timing"]
        assert t["spec"]["verify_ms"] >= 0.0
        assert t["spec"]["draft_ms"] >= 0.0
        assert stats["requests"][0]["spec"]["verify_ms"] >= 0.0
        # segments still partition exactly (spec is sub-attribution)
        assert sum(t["segments"].values()) == pytest.approx(
            t["total_ms"], abs=0.01)
        from triton_dist_tpu.tools.report import render_telemetry
        from triton_dist_tpu.tools.top import render
        assert "serving.spec_accept_rate" in render_telemetry(m)
        assert "accept" in render(m)
    finally:
        srv.stop()


def test_metrics_catalog_wellformed(tiny, monkeypatch):
    """CI satellite: every SLO/perfwatch metric in the documented
    catalog appears in a live {"cmd": "metrics"} snapshot after real
    traffic (+ a perfwatch sample/consult in the same process)."""
    import json as _json
    model, params = tiny
    srv = ModelServer(_engine(model), params, port=0).start()
    try:
        # Real traffic populates every rolling window (tpot needs a
        # multi-token request; pump/queue_wait/ttft come for free).
        outs = fanout(srv.host, srv.port,
                      [{"prompt_ids": [[1 + i, 2, 3]], "gen_len": 4}
                       for i in range(3)], timeout=180)
        assert all("tokens" in o for o in outs), outs
        # Perfwatch metrics need samples + a policy consult: feed the
        # process-shared watch and run one policy decision off a temp
        # floor table (the PR-3 cpu-forcing test hook).
        from triton_dist_tpu.obs import perfwatch, slo
        from triton_dist_tpu.resilience import router
        monkeypatch.setenv("TDT_PERFWATCH_MIN_SAMPLES", "2")
        for _ in range(3):
            perfwatch.record("catop", "fused", "b", 1.0)
            perfwatch.record("catop", "xla", "b", 2.0)
        floors = {"regression_floors": {"cpu": {"catop_vs_xla": 0.95}}}
        import tempfile
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            _json.dump(floors, f)
        monkeypatch.setenv("TDT_BASELINE_PATH", f.name)
        monkeypatch.setenv("TDT_BASELINE_ROUTING", "cpu")
        assert router.policy_reason("catop") is None   # live 2.0: fused
        c = ChatClient(srv.host, srv.port, timeout=180)
        m = c.request({"cmd": "metrics"})["metrics"]
        c.close()
        for name in slo.gauge_catalog():
            assert name in m["gauges"], name
        assert "serving.pump_iteration_ms" in m["histograms"]
        assert ("resilience.perfwatch.catop.live_ratio"
                in m["gauges"])
        assert ("resilience.perfwatch.samples.fused"
                in m["counters"])
        assert ("resilience.policy_source.live" in m["counters"])
    finally:
        srv.stop()
