"""Live perf-ratio watch (obs/perfwatch.py) + router integration
(ISSUE 8).

Quick tier, CPU-only: rolling-median arithmetic and the sample-count
gate are pure Python; the routing tests force the BASELINE policy
onto a temp floor table (``TDT_BASELINE_ROUTING=cpu`` +
``TDT_BASELINE_PATH``, the PR-3 test hook) and assert the
floor→live-median switchover through the
``resilience.policy_source.{live,floor}`` counters — the ISSUE 8
acceptance bar. The end-to-end test records real wall times through a
``@resilient``-decorated op, fused branch deliberately slowed, and
watches the router route it out once both branches cross
``TDT_PERFWATCH_MIN_SAMPLES``.
"""

import json
import time

import jax.numpy as jnp
import pytest

from triton_dist_tpu import obs
from triton_dist_tpu.obs import perfwatch
from triton_dist_tpu.resilience import router


def _feed(op, bucket, fused_ms, xla_ms, n):
    for _ in range(n):
        perfwatch.record(op, "fused", bucket, fused_ms)
        perfwatch.record(op, "xla", bucket, xla_ms)


# ---------------------------------------------------------------------------
# Rolling medians and the sample gate.
# ---------------------------------------------------------------------------

def test_ratio_needs_min_samples_on_both_branches(monkeypatch):
    monkeypatch.setenv("TDT_PERFWATCH_MIN_SAMPLES", "4")
    for _ in range(10):
        perfwatch.record("t_op", "fused", "b0", 2.0)
    assert perfwatch.ratio("t_op") is None        # no xla data at all
    for _ in range(3):
        perfwatch.record("t_op", "xla", "b0", 4.0)
    assert perfwatch.ratio("t_op") is None        # 3 < 4
    perfwatch.record("t_op", "xla", "b0", 4.0)
    assert perfwatch.ratio("t_op") == pytest.approx(2.0)


def test_ratio_is_median_of_per_bucket_ratios(monkeypatch):
    monkeypatch.setenv("TDT_PERFWATCH_MIN_SAMPLES", "2")
    _feed("t_op", "small", 1.0, 4.0, 3)           # ratio 4.0
    _feed("t_op", "large", 10.0, 5.0, 3)          # ratio 0.5
    _feed("t_op", "mid", 2.0, 4.0, 3)             # ratio 2.0
    assert perfwatch.ratio("t_op") == pytest.approx(2.0)
    assert perfwatch.ratio("t_op", bucket="large") == pytest.approx(0.5)
    # An unqualified bucket (one thin branch) never skews the median.
    perfwatch.record("t_op", "fused", "thin", 0.001)
    assert perfwatch.ratio("t_op") == pytest.approx(2.0)


def test_rolling_window_forgets_old_samples(monkeypatch):
    monkeypatch.setenv("TDT_PERFWATCH_MIN_SAMPLES", "4")
    _feed("t_op", "b0", 100.0, 1.0, 8)            # old regime: 0.01
    assert perfwatch.ratio("t_op") < 0.9
    # The deque holds DEFAULT_MAX_SAMPLES; a full window of new
    # samples displaces the old regime entirely.
    _feed("t_op", "b0", 1.0, 2.0, perfwatch.DEFAULT_MAX_SAMPLES)
    assert perfwatch.ratio("t_op") == pytest.approx(2.0)


def test_stats_and_gauge(monkeypatch):
    monkeypatch.setenv("TDT_PERFWATCH_MIN_SAMPLES", "2")
    reg = obs.Registry()
    obs.enable(reg)
    try:
        _feed("t_op", "b0", 2.0, 4.0, 3)
        st = perfwatch.stats()["t_op"]
        assert st["live_ratio"] == pytest.approx(2.0)
        assert st["fused_samples"] == 3 and st["xla_samples"] == 3
        snap = reg.snapshot()
        assert snap["gauges"][
            "resilience.perfwatch.t_op.live_ratio"] == pytest.approx(2.0)
        assert snap["counters"][
            "resilience.perfwatch.samples.fused"] == 3
    finally:
        obs.disable()


# ---------------------------------------------------------------------------
# Router policy: live median first, static floor fallback.
# ---------------------------------------------------------------------------

@pytest.fixture()
def cpu_policy(tmp_path, monkeypatch):
    """Force BASELINE routing onto a controlled cpu floor table."""
    floors = {"regression_floors": {"cpu": {
        "parityop_vs_xla": 0.95,      # parity floor: stays fused
        "slowop_vs_xla": 0.5,         # regression floor: routes to XLA
        "t_probed_vs_xla": 0.5,       # routes to XLA (probe test)
    }}}
    path = tmp_path / "BASELINE.json"
    path.write_text(json.dumps(floors))
    monkeypatch.setenv("TDT_BASELINE_PATH", str(path))
    monkeypatch.setenv("TDT_BASELINE_ROUTING", "cpu")
    monkeypatch.setenv("TDT_PERFWATCH_MIN_SAMPLES", "4")
    reg = obs.Registry()
    obs.enable(reg)
    yield reg
    obs.disable()


def _counters(reg):
    return reg.snapshot()["counters"]


def test_policy_switches_floor_to_live(cpu_policy):
    reg = cpu_policy
    # No live data: the static floor decides (parity → fused).
    assert router.policy_reason("parityop") is None
    c = _counters(reg)
    assert c["resilience.policy_source.floor"] == 1
    assert "resilience.policy_source.live" not in c
    # Cross the sample gate with live data saying "clearly slower":
    # the SAME op now routes out — the stale parity floor is overruled.
    _feed("parityop", "b0", 10.0, 1.0, 4)         # live ratio 0.1
    reason = router.policy_reason("parityop")
    assert reason is not None and "live" in reason
    c = _counters(reg)
    assert c["resilience.policy_source.live"] == 1
    assert c["resilience.parityop.policy_source.live"] == 1
    assert c["resilience.policy_source.floor"] == 1   # unchanged
    # decide() carries it through to the routing reason.
    assert router.decide("parityop", "nokey") == "policy"


def test_policy_live_rescues_floor_routed_op(cpu_policy):
    # The floor says route out (0.5 < 0.9)...
    assert router.policy_reason("slowop") is not None
    # ...but fresh measurements prove the kernel is fine now: the op
    # goes BACK to fused without a BASELINE redeploy.
    _feed("slowop", "b0", 1.0, 2.0, 4)            # live ratio 2.0
    assert router.policy_reason("slowop") is None
    c = _counters(cpu_policy)
    assert c["resilience.policy_source.live"] >= 1


def test_policy_routing_opt_out(cpu_policy, monkeypatch):
    monkeypatch.setenv("TDT_PERFWATCH_ROUTING", "0")
    _feed("parityop", "b0", 10.0, 1.0, 4)         # live says slow...
    # ...but routing is pinned to the floors: parity floor → fused.
    assert router.policy_reason("parityop") is None
    c = _counters(cpu_policy)
    assert "resilience.policy_source.live" not in c
    assert c["resilience.policy_source.floor"] == 1


def test_reset_router_clears_perfwatch(monkeypatch):
    monkeypatch.setenv("TDT_PERFWATCH_MIN_SAMPLES", "2")
    _feed("t_op", "b0", 1.0, 2.0, 3)
    assert perfwatch.ratio("t_op") is not None
    router.reset_router()
    assert perfwatch.ratio("t_op") is None


@router.resilient("t_probed")
def _probed_op(x, impl="pallas"):
    return x * 2


def test_policy_probe_keeps_fused_samples_fresh(cpu_policy, monkeypatch):
    """Review hardening: live routing must not be one-way sticky.
    Every Nth policy-routed call probes the fused branch (recording a
    fresh fused sample), so a routed-out op keeps gathering the data
    it needs to route back in."""
    monkeypatch.setenv("TDT_PERFWATCH_PROBE_EVERY", "2")
    monkeypatch.setenv("TDT_PERFWATCH_MIN_SAMPLES", "8")
    x = jnp.ones((2, 2), jnp.float32)
    for _ in range(4):                    # floor 0.5 → policy-routed
        _probed_op(x, impl="pallas")
    c = _counters(cpu_policy)
    assert c["resilience.t_probed.policy_probes"] == 2
    assert c["resilience.t_probed.fused_total"] == 2       # the probes
    assert c["resilience.t_probed.fallback.policy"] == 2   # the rest
    assert perfwatch.sample_count("t_probed", "fused") == 2
    assert perfwatch.sample_count("t_probed", "xla") == 2
    # With enough (here: hand-fed, deterministic) samples proving the
    # fused branch healthy, the live median overrules the floor and
    # the op is back on the fused path — the organic version of this
    # is exactly what the probes feed.
    _feed("t_probed", "m", 1.0, 2.0, 8)
    assert router.policy_reason("t_probed") is None
    # Probing honors the routing opt-out.
    monkeypatch.setenv("TDT_PERFWATCH_ROUTING", "0")
    perfwatch.reset()
    for _ in range(4):
        _probed_op(x, impl="pallas")
    assert perfwatch.sample_count("t_probed", "fused") == 0


def test_probe_never_runs_while_breaker_not_closed(cpu_policy,
                                                   monkeypatch):
    """decide() checks policy before the breaker, so a "policy" route
    can mask a breaker opened over real infra failures — probes must
    not re-enter the failing fused branch (nor steal the half-open
    slot)."""
    monkeypatch.setenv("TDT_PERFWATCH_PROBE_EVERY", "2")
    from triton_dist_tpu.resilience.breaker import OPEN, get_breaker
    br = get_breaker("t_probed")
    for _ in range(10):                   # past any threshold
        br.record_failure()
    assert br.state == OPEN
    x = jnp.ones((2, 2), jnp.float32)
    for _ in range(6):                    # floor 0.5 → policy-routed
        _probed_op(x, impl="pallas")
    c = _counters(cpu_policy)
    assert "resilience.t_probed.policy_probes" not in c
    assert "resilience.t_probed.fused_total" not in c
    assert perfwatch.sample_count("t_probed", "fused") == 0
    assert c["resilience.t_probed.fallback.policy"] == 6


# ---------------------------------------------------------------------------
# End to end through @resilient: measured wall times switch the route.
# ---------------------------------------------------------------------------

@router.resilient("t_slowfused")
def _slow_fused_op(x, impl="pallas"):
    if impl == "pallas":
        time.sleep(0.005)                 # the fused branch is slower
    return x + 1


def test_resilient_entries_record_and_reroute(cpu_policy, tmp_path,
                                              monkeypatch):
    """The acceptance scenario: @resilient entries record their own
    wall times; once both branches cross TDT_PERFWATCH_MIN_SAMPLES the
    router's next decision comes from the live median (policy_source
    counters prove the switch) and the slow fused branch routes to
    XLA."""
    floors = {"regression_floors": {"cpu": {"t_slowfused_vs_xla": 0.95}}}
    path = tmp_path / "B2.json"
    path.write_text(json.dumps(floors))
    monkeypatch.setenv("TDT_BASELINE_PATH", str(path))
    router._BASELINE_CACHE.clear()
    reg = cpu_policy
    x = jnp.ones((4, 4), jnp.float32)
    # Reference-branch calls (tests/bench are the xla sample source).
    for _ in range(4):
        _slow_fused_op(x, impl="xla")
    assert perfwatch.sample_count("t_slowfused", "xla") == 4
    # Fused calls: the parity floor keeps them fused while the live
    # data is thin...
    for _ in range(4):
        _slow_fused_op(x, impl="pallas")
    assert perfwatch.sample_count("t_slowfused", "fused") == 4
    c = _counters(reg)
    assert c["resilience.t_slowfused.policy_source.floor"] >= 1
    assert c.get("resilience.t_slowfused.fallbacks_total", 0) == 0
    # ...and the very next call consults the live median (~5 ms fused
    # vs ~µs xla → clearly slower) and routes to the reference path.
    _slow_fused_op(x, impl="pallas")
    c = _counters(reg)
    assert c["resilience.t_slowfused.policy_source.live"] >= 1
    assert c["resilience.t_slowfused.fallback.policy"] == 1
    # The routed call itself recorded another xla sample.
    assert perfwatch.sample_count("t_slowfused", "xla") == 5
