"""Worker process for tests/test_multihost.py — NOT collected by pytest.

One of two cooperating `jax.distributed` processes on CPU (gloo
collectives). Exercises the code paths no single-process 8-device mesh
can touch (VERDICT r4 next-5): ``runtime/dist.py::_maybe_multihost_init``
(driven by the JAX_COORDINATOR_ADDRESS/... env the TPU pod launcher
would set), a cross-process collective through the global mesh, and one
``tools/autotuner.py`` round whose multi-host agreement protocol
(worst-rank scores via ``process_allgather``, process-0 cache-hit
broadcast) must leave both processes with the same winner.

Reference analog: every reference test runs under torchrun with
NCCL/gloo process groups (SURVEY.md §4); this is the TPU-native spine's
DCN-path equivalent.
"""

import os
import sys


def main() -> None:
    pid, port, tmpdir = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(pid)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Exercise the device-kind-keyed disk cache path too (shared dir —
    # both processes see the same file, like a shared NFS home on a pod).
    os.environ["TDT_AUTOTUNE_CACHE"] = os.path.join(tmpdir, "autotune.json")

    import jax

    # BEFORE any backend init: the axon sitecustomize pins the tunneled
    # TPU platform otherwise, and jax.distributed would then block on
    # the (often wedged) tunnel instead of gloo/CPU.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from triton_dist_tpu.runtime import dist as tdist

    ctx = tdist.initialize_distributed()
    assert jax.process_count() == 2, jax.process_count()
    assert ctx.num_processes == 2
    assert ctx.world_size == 8, ctx.world_size
    mesh = ctx.mesh

    # -- 1. cross-process collective through the global mesh (DCN path).
    # Data lives sharded across BOTH processes; the psum must cross them.
    x = jax.device_put(
        jnp.arange(8, dtype=jnp.float32),
        NamedSharding(mesh, P("tp")))

    @jax.jit
    def total(v):
        return jnp.sum(v)

    s = float(total(x))
    assert s == 28.0, s

    # A shard_map psum over the mesh axis — the framework's collective
    # idiom (ops use this shape) across the process boundary.
    from jax import shard_map

    @jax.jit
    def allred(v):
        return shard_map(
            lambda t: jax.lax.psum(t, "tp"),
            mesh=mesh, in_specs=P("tp"), out_specs=P())(v)

    r = np.asarray(allred(jnp.ones((8,), jnp.float32)))
    assert float(r[0]) == 8.0, r

    # -- 1b. a HIERARCHICAL collective (VERDICT r4 next-5's literal
    # ask) on a 2-D ici x dcn mesh whose dcn axis spans the process
    # boundary — the exact pod topology ops/hierarchical.py is
    # designed for (ICI stage local, DCN stage cross-process).
    from triton_dist_tpu.ops import hierarchical as hier

    ctx2 = tdist.initialize_distributed(
        mesh_shape={"dcn": 2, "ici": 4})
    assert ctx2.mesh.shape == {"dcn": 2, "ici": 4}
    h = jax.device_put(
        jnp.arange(16, dtype=jnp.float32).reshape(8, 2),
        NamedSharding(ctx2.mesh, P(None)))  # replicated partials
    ar = np.asarray(hier.all_reduce_nd(h, ctx2.mesh, ("ici", "dcn")))
    np.testing.assert_allclose(
        ar, np.arange(16, dtype=np.float32).reshape(8, 2) * 8.0)
    ag_in = jax.device_put(
        jnp.arange(16, dtype=jnp.float32).reshape(8, 2),
        NamedSharding(ctx2.mesh, P(("dcn", "ici"))))
    ag = np.asarray(hier.all_gather_nd(ag_in, ctx2.mesh, ("ici", "dcn")))
    # Global (8, 2) sharded over all 8 devices -> gathered back,
    # replicated: the ICI stage collects the 4 local shards, the DCN
    # stage crosses the process boundary for the other host's half.
    np.testing.assert_allclose(
        ag, np.arange(16, dtype=np.float32).reshape(8, 2))

    # -- 1c. op-layer entry points on the cross-process mesh: the
    # context objects + shard_map plumbing of the fused-op API must
    # work when the tp axis spans processes (impl="xla" — the
    # XLA-collective path is what rides DCN; Pallas interpret mode is
    # single-process by construction).
    from triton_dist_tpu.ops.allgather_gemm import (
        create_ag_gemm_context, ag_gemm)
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)

    tdist.initialize_distributed()  # flat 8-way tp across both hosts
    fmesh = tdist.get_mesh()
    m, k, nn = 16, 32, 32
    # Row-graded A (row i is all i) so a misrouted/reordered chunk in
    # the cross-process gather/scatter produces WRONG values, not a
    # coincidental pass (review r5g-1): out[i, :] = i * k.
    a_mat = jnp.broadcast_to(
        jnp.arange(m, dtype=jnp.float32)[:, None], (m, k))

    def check_shards(arr):
        expect = np.broadcast_to(
            (np.arange(arr.shape[0], dtype=np.float32)
             * float(k))[:, None], arr.shape)
        assert arr.addressable_shards, "no local shards"
        for sh in arr.addressable_shards:
            np.testing.assert_allclose(np.asarray(sh.data),
                                       expect[sh.index])

    a_g = jax.device_put(a_mat, NamedSharding(fmesh, P("tp")))
    b_g = jax.device_put(jnp.ones((k, nn), jnp.float32),
                         NamedSharding(fmesh, P(None, "tp")))
    ctx_ag = create_ag_gemm_context(fmesh, "tp")
    check_shards(jax.block_until_ready(
        ag_gemm(a_g, b_g, ctx_ag, impl="xla")))

    a_r = jax.device_put(a_mat, NamedSharding(fmesh, P(None, "tp")))
    b_r = jax.device_put(jnp.ones((k, nn), jnp.float32),
                         NamedSharding(fmesh, P("tp")))
    ctx_rs = create_gemm_rs_context(fmesh, "tp")
    check_shards(jax.block_until_ready(
        gemm_rs(a_r, b_r, ctx_rs, impl="xla")))

    # -- 2. one autotune round: both processes must agree on the winner
    # even though their local timings differ.
    from triton_dist_tpu.tools.autotuner import autotune

    a64 = jnp.ones((64, 64), jnp.float32)
    a512 = jnp.ones((512, 512), jnp.float32)

    def make_fn(n):
        mat = a64 if n == 64 else a512
        f = jax.jit(lambda: (mat @ mat).sum())

        def run():
            return jax.block_until_ready(f())
        return run

    res = autotune(make_fn, [{"n": 512}, {"n": 64}], key="mh_test")
    # Second call must be served from the (agreed) cache.
    res2 = autotune(make_fn, [{"n": 512}, {"n": 64}], key="mh_test")
    assert res2.config == res.config
    print(f"RESULT pid={pid} winner={res.config['n']} psum={float(r[0])}",
          flush=True)


if __name__ == "__main__":
    main()
