"""Bench-shape VMEM-budget checks (VERDICT r2 next 10).

BENCH_r02's crash class — a default config whose declared VMEM scratch
cannot compile on the chip — must fail HERE, in CI on any host, not on
the chip. The gate asserts against HARD_FOOTPRINT_CAP (26 MB declared):
the library's comm kernels request a 64 MB Mosaic scoped-VMEM limit via
``comm_params`` and Mosaic's scoped accounting carries ~2.2x overhead
over declared buffers (measured round 5; constants in ops/common.py).
A kernel built WITHOUT ``comm_params`` keeps Mosaic's 16 MB default and
needs the tighter ``limit=`` argument. ``check_entry_vmem`` traces each
op's ``impl="pallas"`` entry at the exact bench.py shapes with
``jax.eval_shape`` (no execution) and asserts the static footprint of
every ``pallas_call`` it contains. World=1 (the bench environment) and
world=8 are both checked: round 2's failure was world=1-specific
(n_loc = N, the largest B panel).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu.testing.vmem import (
    VmemBudgetError, assert_vmem_within, check_entry_vmem)

bf16 = jnp.bfloat16


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("tp",))


@pytest.mark.parametrize("world", [1, 8])
def test_ag_gemm_bench_shape_fits(world):
    from triton_dist_tpu.ops.allgather_gemm import (
        create_ag_gemm_context, ag_gemm)
    mesh = _mesh(world)
    ctx = create_ag_gemm_context(mesh, "tp", interpret=True)
    m, k, n = 2048, 4096, 4096  # bench.py shape
    check_entry_vmem(
        lambda a, b: ag_gemm(a, b, ctx, impl="pallas"),
        jax.ShapeDtypeStruct((m, k), bf16),
        jax.ShapeDtypeStruct((k, n), bf16))


@pytest.mark.parametrize("world", [1, 8])
def test_gemm_rs_bench_shape_fits(world):
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)
    mesh = _mesh(world)
    ctx = create_gemm_rs_context(mesh, "tp", interpret=True)
    m, k, n = 2048, 4096, 4096
    check_entry_vmem(
        lambda a, b: gemm_rs(a, b, ctx, impl="pallas"),
        jax.ShapeDtypeStruct((m, k), bf16),
        jax.ShapeDtypeStruct((k, n), bf16))


@pytest.mark.parametrize("world", [1, 8])
def test_gemm_ar_bench_shape_fits(world):
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_ar)
    mesh = _mesh(world)
    ctx = create_gemm_rs_context(mesh, "tp", interpret=True)
    m, k, n = 128, 4096, 4096  # decode GEMM-AR bench shape
    check_entry_vmem(
        lambda a, b: gemm_ar(a, b, ctx, impl="pallas"),
        jax.ShapeDtypeStruct((m, k), bf16),
        jax.ShapeDtypeStruct((k, n), bf16))


@pytest.mark.parametrize("world", [1, 8])
def test_flash_decode_serving_shape_fits(world):
    from triton_dist_tpu.ops.flash_decode import (
        create_flash_decode_context, gqa_fwd_batch_decode)
    mesh = _mesh(world)
    ctx = create_flash_decode_context(mesh, "tp", interpret=True,
                                      variant="tiled", t_blk=512)
    b, hq, hkv, d, t = 8, 32, 8, 128, 8192  # bench.py serving shape
    check_entry_vmem(
        lambda q, kc, vc, n: gqa_fwd_batch_decode(q, kc, vc, n, ctx,
                                                  impl="pallas"),
        jax.ShapeDtypeStruct((b, hq, d), bf16),
        jax.ShapeDtypeStruct((b, t, hkv, d), bf16),
        jax.ShapeDtypeStruct((b, t, hkv, d), bf16),
        jax.ShapeDtypeStruct((), jnp.int32))


def test_sp_attention_fused_prefill_shape_fits():
    """The fused SP kernel streams q in resident groups, so ANY prefill
    shape must fit the budget — checked at a realistic distributed
    shape (16k positions over 8 ranks)."""
    from triton_dist_tpu.ops.sp_attention import (
        create_sp_attention_context, sp_ag_attention_fused)
    mesh = _mesh(8)
    ctx = create_sp_attention_context(mesh, "tp", causal=True,
                                      interpret=True)
    b, s, hq, hkv, d = 1, 16384, 8, 2, 128   # s_loc = 2048
    check_entry_vmem(
        lambda q, k, v: sp_ag_attention_fused(q, k, v, ctx),
        jax.ShapeDtypeStruct((b, s, hq, d), bf16),
        jax.ShapeDtypeStruct((b, s, hkv, d), bf16),
        jax.ShapeDtypeStruct((b, s, hkv, d), bf16))


def test_sp_attention_fused_bench_shape_fits():
    """THE bench.py sp_attn shape at world=1 (s_loc=4096, hq=16): q +
    state total ~50 MB — the q-group residency must bound what reaches
    VMEM (BENCH_r02's class; this shape failed the chip in round-3
    session 4)."""
    from triton_dist_tpu.ops.sp_attention import (
        create_sp_attention_context, sp_ag_attention_fused)
    mesh = _mesh(1)
    ctx = create_sp_attention_context(mesh, "tp", causal=True,
                                      interpret=True)
    b, s, hq, hkv, d = 1, 4096, 16, 8, 128
    check_entry_vmem(
        lambda q, k, v: sp_ag_attention_fused(q, k, v, ctx),
        jax.ShapeDtypeStruct((b, s, hq, d), bf16),
        jax.ShapeDtypeStruct((b, s, hkv, d), bf16),
        jax.ShapeDtypeStruct((b, s, hkv, d), bf16))


def test_train_step_bench_config_fits():
    """Trace the WHOLE fused train step (fwd + transpose-kernel bwd +
    optax update) at bench.py's train config and assert every
    pallas_call inside fits — forward gates alone miss the backward's
    transposed shapes (e.g. gemm_rs contractions over inter=8192)."""
    from triton_dist_tpu.models import DenseLLM, ModelConfig
    from triton_dist_tpu.models.train import make_train_step
    mesh = _mesh(1)   # the bench chip
    cfg = ModelConfig(hidden_size=2048, intermediate_size=8192,
                      num_hidden_layers=1,  # layers share kernel shapes
                      num_attention_heads=16, num_key_value_heads=8,
                      head_dim=128, vocab_size=32768,
                      max_position_embeddings=1024, dtype=bf16)
    model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="pallas",
                     fwd_mode="ag_rs")
    for layer in (model.attn, model.mlp):
        layer.ag_ctx.interpret = True
        layer.rs_ctx.interpret = True
    step, init_opt = make_train_step(model, mode="ag_rs", donate=False)
    params = jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0)))
    # Shape-only optimizer state: trace the STEP, not init_opt (which
    # device_puts concrete arrays).
    import optax
    opt_shapes = jax.eval_shape(lambda p: optax.adamw(1e-4).init(p),
                                params)
    batch = {"input_ids": jax.ShapeDtypeStruct((4, 512), jnp.int32)}
    check_entry_vmem(lambda p, o, bt: step(p, o, bt),
                     params, opt_shapes, batch)


def test_vmem_budget_catches_oversized_kernel():
    """The helper itself must detect an oversized kernel — the BENCH_r02
    config (16.5 MB of scratch on a 16 MB chip) reproduced in miniature."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(x_ref, o_ref, big):
        o_ref[:] = x_ref[:]

    def entry(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((128, 128), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((2, 2048, 4096), jnp.float32)],
            interpret=True,
        )(x)

    with pytest.raises(VmemBudgetError):
        with assert_vmem_within(16 * 1024 * 1024):
            jax.eval_shape(entry, jax.ShapeDtypeStruct((128, 128),
                                                       jnp.float32))


def test_vmem_budget_ignores_any_and_semaphores():
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(x_hbm, o_hbm, sem):
        pass

    def entry(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((8192, 8192), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA((8,))],
            interpret=True,
        )(x)

    # 256 MB operands in ANY (HBM) space must not trip the VMEM budget.
    with assert_vmem_within(16 * 1024 * 1024):
        jax.eval_shape(entry, jax.ShapeDtypeStruct((8192, 8192),
                                                   jnp.float32))


@pytest.mark.parametrize("world", [1, 8])
def test_ag_group_gemm_fused_bench_shape_fits(world):
    from triton_dist_tpu.ops.group_gemm import (
        create_ag_group_gemm_context, ag_group_gemm)
    mesh = _mesh(world)
    ctx = create_ag_group_gemm_context(mesh, "tp")
    ctx.interpret = True
    m, k, n, e = 2048, 4096, 4096, 8
    check_entry_vmem(
        lambda x, w, ids: ag_group_gemm(x, w, ids, e, ctx, impl="fused"),
        jax.ShapeDtypeStruct((m, k), bf16),
        jax.ShapeDtypeStruct((e, k, n), bf16),
        jax.ShapeDtypeStruct((m,), jnp.int32))


@pytest.mark.parametrize("world", [1, 8])
def test_moe_reduce_rs_fused_bench_shape_fits(world):
    from triton_dist_tpu.ops.moe_reduce_rs import (
        create_moe_rs_context, moe_reduce_rs)
    mesh = _mesh(world)
    t, topk, inter, hid, e = 2048, 2, 4096, 4096, 8
    ctx = create_moe_rs_context(mesh, "tp", num_experts=e, topk=topk)
    ctx.interpret = True
    check_entry_vmem(
        lambda a, w, ids, wts: moe_reduce_rs(a, w, ids, wts, ctx,
                                             impl="fused"),
        jax.ShapeDtypeStruct((t * topk, inter), bf16),
        jax.ShapeDtypeStruct((e, inter, hid), bf16),
        jax.ShapeDtypeStruct((t * topk,), jnp.int32),
        jax.ShapeDtypeStruct((t, topk), jnp.float32))


@pytest.mark.parametrize("world", [1, 8])
def test_ag_swiglu_bench_shape_fits(world):
    from triton_dist_tpu.ops.allgather_gemm import (
        create_ag_gemm_context, ag_swiglu)
    mesh = _mesh(world)
    ctx = create_ag_gemm_context(mesh, "tp", interpret=True)
    m, k = 2048, 4096
    # ag_swiglu takes the GLOBAL weight width (n_loc = n // world
    # inside). Gate (a) the exact width bench.py's tp_mlp runs at this
    # world (inter = 12288 // max(n,8) * n → per-chip 1536), and (b) a
    # 12288-global stress width (per-chip 12288 at world=1) so a config
    # that only fits scaled-down stand-ins cannot pass CI (review r3i:
    # the first version of this gate divided by world twice and tested
    # an 8x-smaller kernel than the bench runs).
    for n in (4096, 12288 // max(world, 8) * world,
              3072 * world, 12288):
        check_entry_vmem(
            lambda a, wg, wu: ag_swiglu(a, wg, wu, ctx, impl="pallas"),
            jax.ShapeDtypeStruct((m, k), bf16),
            jax.ShapeDtypeStruct((k, n), bf16),
            jax.ShapeDtypeStruct((k, n), bf16))


@pytest.mark.parametrize("world", [1, 8])
@pytest.mark.parametrize("dims", [
    ("8b", 4096, 4, 1, 128, 1536), ("32b", 5120, 8, 1, 128, 3200)])
def test_layer_bench_dims_fit(world, dims):
    """bench.py layer_8b/32b (Qwen3 per-chip TP8 slice, prefill M=2048
    + decode M=128): every Pallas kernel in the fused decoder-layer
    step must fit the chip budget at both worlds."""
    from triton_dist_tpu.layers import TPAttn, precompute_rope_cache
    from triton_dist_tpu.layers.tp_mlp import TPMLP
    tag, h, nq, nkv, d, inter = dims
    mesh = _mesh(world)
    nq, nkv, inter = nq * world, nkv * world, inter * world
    attn = TPAttn(h, nq, nkv, d, mesh=mesh, axis="tp", dtype=bf16)
    mlp = TPMLP(h, inter, mesh=mesh, axis="tp", dtype=bf16)
    rope = precompute_rope_cache(d, 512)
    pa = jax.eval_shape(attn.init, jax.random.PRNGKey(0))
    pm = jax.eval_shape(mlp.init, jax.random.PRNGKey(1))
    for phase, b, s, mode in (("prefill", 16, 128, "ag_rs"),
                              ("decode", 128, 1, "gemm_ar")):
        m = b * s
        pos = jnp.zeros((b, s), jnp.int32)
        offset = jnp.int32(0 if phase == "prefill" else 256)

        def f(x, pa, pm, kc, vc, mode=mode, pos=pos, offset=offset):
            a_out, _ = attn(pa, x, pos, rope, (kc, vc), offset, mode=mode)
            y = x + a_out
            return y + mlp(pm, y, mode=mode)
        check_entry_vmem(
            f, jax.ShapeDtypeStruct((m, h), bf16), pa, pm,
            jax.ShapeDtypeStruct((b, 512, nkv, d), bf16),
            jax.ShapeDtypeStruct((b, 512, nkv, d), bf16))


def test_ag_swiglu_configs_table():
    from triton_dist_tpu.ops.allgather_gemm import (
        ag_swiglu_configs, _swiglu_footprint)
    from triton_dist_tpu.ops.common import (DEFAULT_VMEM_BUDGET,
                                            HARD_FOOTPRINT_CAP)
    # Bench tp_mlp_big shape class: m=2048, w=1, k=4096, n_loc=3072.
    cfgs = ag_swiglu_configs(2048, 4096, 3072, 2)
    assert cfgs, "no swiglu configs at the bench shape"
    seen = set()
    budget_tier_ended = False
    for c in cfgs:
        bm, bn = c["block_m"], c["block_n"]
        assert 2048 % bm == 0 and 3072 % bn == 0, c
        fp = _swiglu_footprint(bm, bn, 4096, 2)
        assert fp <= HARD_FOOTPRINT_CAP, c
        if fp > DEFAULT_VMEM_BUDGET:
            budget_tier_ended = True
        else:
            # budget-tier entries must all precede aggressive ones
            assert not budget_tier_ended, cfgs
        assert (bm, bn) not in seen
        seen.add((bm, bn))
    # the sweep must have aggressive candidates to explore here
    assert budget_tier_ended, cfgs
    # tiny shard: no feasible kernel tiling -> empty table (entry then
    # composes from ag_gemm_multi), never an invalid config
    assert ag_swiglu_configs(8, 32, 32, 4) == []


@pytest.mark.slow
def test_deep_mega_bench_config_fits():
    """The 32-layer fused mega step at bench.py's deep TPU config: every
    pallas_call within the declared cap. Run offline after the round-5
    on-chip mega MosaicError (HTTP 500 during the deep compile): the
    static footprint is clean, so the failure class was Mosaic's old
    16 MB scoped limit (~2.2x overhead over declared — the same class
    that rejected the SP kernel), which comm_params' 64 MB request now
    covers."""
    from triton_dist_tpu.mega import MegaQwen3
    from triton_dist_tpu.models import DenseLLM, ModelConfig
    from triton_dist_tpu.models.kv_cache import KVCacheManager
    mesh = _mesh(1)
    cfg = ModelConfig(hidden_size=4096, intermediate_size=1536,
                      num_hidden_layers=32, num_attention_heads=4,
                      num_key_value_heads=1, head_dim=128,
                      vocab_size=32768, max_position_embeddings=512,
                      dtype=bf16)
    model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="pallas")
    for layer in (model.attn, model.mlp):
        layer.ag_ctx.interpret = True
        layer.rs_ctx.interpret = True
    kv = KVCacheManager(cfg.num_hidden_layers, 1,
                        cfg.max_position_embeddings,
                        cfg.num_key_value_heads, cfg.head_dim, mesh=mesh,
                        axis="tp", dtype=cfg.dtype)
    mega = MegaQwen3(model, decode_mode="gemm_ar")
    params = jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0)))
    caches = jax.eval_shape(kv.init)
    token = jax.ShapeDtypeStruct((1, 1), jnp.int32)
    check_entry_vmem(lambda p, t, c: mega.step(p, t, c, 4)[0],
                     params, token, caches)
