"""Disaggregated prefill/decode over KV-block streaming (ISSUE 18).

Quick tier. Covered here:

- the ACCEPTANCE scenario: a prefill replica streams a multi-block
  prompt's KV to a decode replica, the decode-side admission is
  bit-identical to unified greedy serving, and a WARM handoff (decode
  prefix cache already holding the chain) ships strictly fewer blocks
  than a cold one;
- both transport tiers: in-process (symm-mem ship path) and the
  length-prefixed wire verbs (``KVStreamSender`` over a real socket);
- the sever acceptance: ``chaos.sever_stream`` kills the prefill
  replica mid-stream → the router re-places on the decode replica,
  ZERO client errors, and the decode side counts the severed stream
  when purging its stale staging entry;
- the kvstream protocol model: clean schedules verify for every
  (n_blocks, held) shape, and the three mutation classes fail with
  DISTINCT finding codes (deadlock / signal_wait_imbalance /
  coverage);
- two-tier routing: ``parse_tiers``, health-advertised tier pickup,
  live ``router_retier`` under drain (sticky across health polls);
- satellites: ``tdt-check --changed`` selects the disagg watches, the
  regress gate (``check_disagg_wellformed``), and the dashboard
  surfaces (fleet_top tier column, report disagg section).
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
from triton_dist_tpu.serving import ChatClient, ModelServer, RouterServer
from triton_dist_tpu.serving import disagg as disagg_mod
from triton_dist_tpu.serving import kv_stream
from triton_dist_tpu.testing import chaos

PAGE = 4


@pytest.fixture()
def paged_tiny(mesh8, key):
    """xla-impl sp model on a (tp=1, sp=8) grid — the paged engine
    family (same recipe as tests/test_scheduler.py)."""
    from jax.sharding import Mesh
    devs = [d for d in mesh8.devices.flat]
    mesh = Mesh(np.array(devs).reshape(1, 8), ("tp", "sp"))
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=4,
                      num_key_value_heads=2, head_dim=16, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh, axis="tp", sp_axis="sp",
                     impl="xla", fwd_mode="sp")
    return model, model.init(key)


def _paged_server(tiny, rid, **kw):
    model, params = tiny
    eng = Engine(model, batch=2, max_seq=64, prefill_mode="sp",
                 decode_mode="sp", paged=True, page_size=PAGE,
                 prefix_cache=True)
    return ModelServer(eng, params, port=0, registry="private",
                       replica_id=rid, **kw).start()


def _golden(tiny, prompt, gen_len):
    """Unified greedy golden: the plain tp engine on the same params
    (token-equal across engine families, pinned by test_scheduler)."""
    model, params = tiny
    eng = Engine(model, batch=1, max_seq=64, prefill_mode="xla",
                 decode_mode="xla_ar")
    out = np.asarray(eng.serve(params, jnp.asarray([prompt], jnp.int32),
                               gen_len))[0].tolist()
    return out[len(prompt):]


def _counter(server, name):
    return server.registry.snapshot()["counters"].get(name, 0)


def _wait(pred, timeout=30.0, what="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        v = pred()
        if v:
            return v
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# Schedule helpers (the functions the model checker executes).
# ---------------------------------------------------------------------------

def test_schedule_helper_geometry():
    assert kv_stream.block_span(12, 4) == 3
    assert kv_stream.block_span(13, 4) == 4
    assert list(kv_stream.needed_blocks(3, 0)) == [0, 1, 2]
    assert list(kv_stream.needed_blocks(3, 2)) == [2]
    assert list(kv_stream.needed_blocks(3, 9)) == []
    assert kv_stream.ship_schedule(3, 0) == [(0, 0), (1, 1), (2, 2)]
    assert kv_stream.ship_schedule(3, 2) == [(2, 0)]
    assert kv_stream.ship_schedule(3, 3) == []


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    layers = [(rng.standard_normal((2, 4, 16), dtype=np.float32),
               rng.standard_normal((2, 4, 16), dtype=np.float32))]
    payload = kv_stream.pack_block(layers)
    back = kv_stream.unpack_block(payload, 1, (2, 4, 16))
    np.testing.assert_array_equal(back[0][0], layers[0][0])
    np.testing.assert_array_equal(back[0][1], layers[0][1])
    with pytest.raises(ValueError):
        kv_stream.unpack_block(payload[:-4], 1, (2, 4, 16))


# ---------------------------------------------------------------------------
# Acceptance: e2e handoff, bit-identical, warm dedup.
# ---------------------------------------------------------------------------

def test_disagg_e2e_bit_identical_and_warm_dedup(paged_tiny):
    """Cold handoff streams every block and the decode replica's
    decode-only admission reproduces unified greedy exactly; a warm
    handoff of the same prompt ships STRICTLY fewer blocks (the
    content-addressed dedup)."""
    prompt = list(range(1, 13))            # 3 full pages
    gen = 5
    want = _golden(paged_tiny, prompt, gen)
    p = _paged_server(paged_tiny, "dz-p", tier="prefill")
    d = _paged_server(paged_tiny, "dz-d", tier="decode")
    try:
        c = ChatClient(p.host, p.port, timeout=120)
        req = {"cmd": "disagg_prefill", "prompt_ids": prompt,
               "gen_len": gen,
               "decode_endpoint": f"{d.host}:{d.port}"}
        cold = c.request(dict(req))
        assert cold["tokens"][0] == want
        assert cold["disagg"]["decode"] == f"{d.host}:{d.port}"
        cold_shipped = _counter(p, "disagg.blocks_shipped")
        assert cold_shipped == 3           # every block streamed
        assert _counter(p, "disagg.handoffs") == 1
        assert _counter(p, "disagg.fallbacks") == 0
        assert _counter(d, "disagg.decode_admits") == 1
        assert _counter(d, "disagg.offers") == 1
        assert _counter(p, "disagg.ship_inproc") == 3

        warm = c.request(dict(req))
        assert warm["tokens"][0] == want
        warm_shipped = (_counter(p, "disagg.blocks_shipped")
                        - cold_shipped)
        # The decode replica's prefix cache now holds the chain: only
        # the always-ship tail block moves. Near-zero bytes, strictly
        # fewer than cold — the tentpole's dedup property.
        assert 0 < warm_shipped < cold_shipped
        assert warm_shipped == 1
        assert _counter(d, "disagg.blocks_deduped") == 2
        c.close()
    finally:
        p.stop()
        d.stop()


def test_disagg_wire_tier_bit_identical(paged_tiny):
    """With the in-process registration removed, the handoff takes the
    length-prefixed WIRE verbs over a real socket — and still matches
    unified greedy."""
    prompt = list(range(3, 11))            # 2 full pages
    gen = 4
    want = _golden(paged_tiny, prompt, gen)
    p = _paged_server(paged_tiny, "dw-p", tier="prefill")
    d = _paged_server(paged_tiny, "dw-d", tier="decode")
    try:
        disagg_mod.unregister_inproc(f"{d.host}:{d.port}")
        c = ChatClient(p.host, p.port, timeout=120)
        out = c.request({"cmd": "disagg_prefill", "prompt_ids": prompt,
                         "gen_len": gen,
                         "decode_endpoint": f"{d.host}:{d.port}"})
        assert out["tokens"][0] == want
        assert _counter(p, "disagg.ship_wire") == 2
        assert _counter(p, "disagg.ship_inproc") == 0
        assert _counter(d, "disagg.decode_admits") == 1
        assert _counter(d, "disagg.stream_bytes") > 0
        c.close()
    finally:
        p.stop()
        d.stop()


def test_disagg_short_prompt_no_handoff(paged_tiny):
    """gen_len == 1 (and stop-on-first) answers from the prefill
    replica — no stream, no decode involvement."""
    prompt = [1, 2, 3, 4]
    want = _golden(paged_tiny, prompt, 1)
    p = _paged_server(paged_tiny, "ds-p", tier="prefill")
    d = _paged_server(paged_tiny, "ds-d", tier="decode")
    try:
        c = ChatClient(p.host, p.port, timeout=120)
        out = c.request({"cmd": "disagg_prefill", "prompt_ids": prompt,
                         "gen_len": 1,
                         "decode_endpoint": f"{d.host}:{d.port}"})
        assert out["tokens"][0] == want
        assert _counter(p, "disagg.handoffs") == 0
        assert _counter(d, "disagg.offers") == 0
        c.close()
    finally:
        p.stop()
        d.stop()


def test_disagg_dead_decode_falls_back_locally(paged_tiny):
    """A dead decode endpoint NEVER surfaces to the client: the
    fallback contract re-serves the full request on the prefill
    replica (its prefix cache is still warm)."""
    prompt = list(range(1, 13))
    gen = 4
    want = _golden(paged_tiny, prompt, gen)
    p = _paged_server(paged_tiny, "df-p", tier="prefill")
    try:
        c = ChatClient(p.host, p.port, timeout=120)
        out = c.request({"cmd": "disagg_prefill", "prompt_ids": prompt,
                         "gen_len": gen,
                         "decode_endpoint": "127.0.0.1:9"})
        assert out["tokens"][0] == want
        assert out["disagg"] == {"fallback": True}
        assert _counter(p, "disagg.fallbacks") == 1
        assert _counter(p, "disagg.handoffs") == 0
        c.close()
    finally:
        p.stop()


# ---------------------------------------------------------------------------
# Two-tier routing.
# ---------------------------------------------------------------------------

def test_parse_tiers():
    from triton_dist_tpu.serving.router import parse_tiers
    assert parse_tiers("") == {}
    got = parse_tiers("prefill=127.0.0.1:81;decode=127.0.0.1:82")
    assert got == {("127.0.0.1", 81): "prefill",
                   ("127.0.0.1", 82): "decode"}
    with pytest.raises(ValueError):
        parse_tiers("turbo=127.0.0.1:81")
    with pytest.raises(ValueError):
        parse_tiers("prefill127.0.0.1:81")


def test_router_disagg_dispatch_and_retier(paged_tiny):
    """A tiered router sends single-prompt generates down the
    disagg_prefill path (prefill pool by TTFT burn, decode pool by
    TPOT burn), tokens bit-identical to unified greedy; a live
    ``router_retier`` survives subsequent health polls (the replica
    advertises its static tier, the override must not flap back)."""
    prompt = list(range(1, 13))
    gen = 4
    want = _golden(paged_tiny, prompt, gen)
    p = _paged_server(paged_tiny, "rt-p", tier="prefill")
    d = _paged_server(paged_tiny, "rt-d", tier="decode")
    eps = [(p.host, p.port), (d.host, d.port)]
    r = RouterServer(eps, registry="private", poll_s=0.05,
                     fleet_kwargs={"stale_s_": 0.5, "down_s_": 1.5,
                                   "timeout_s": 5.0}).start()
    try:
        # Tier pickup is health-advertised: wait for the poll.
        _wait(lambda: {row["tier"] for row in r.status()["replicas"]}
              == {"prefill", "decode"}, what="tier pickup")
        c = ChatClient(r.host, r.port, timeout=120)
        got = c.generate_ids([prompt], gen_len=gen)
        assert got["tokens"][0] == want
        assert got.get("disagg_route") or got.get("disagg")
        st = r.status()
        assert st["counters"].get("router.disagg_dispatches") == 1
        assert _counter(p, "disagg.handoffs") == 1
        assert _counter(d, "disagg.decode_admits") == 1

        # Live retier: decode → prefill under drain; sticky across
        # polls even though the replica still advertises "decode".
        resp = c.request({"cmd": "router_retier",
                          "endpoint": f"{d.host}:{d.port}",
                          "tier": "prefill"})
        assert resp["retiered"] == f"{d.host}:{d.port}"
        assert resp["tier"] == "prefill"
        time.sleep(0.2)                    # several poll cycles
        tiers = {row["replica_id"]: row["tier"]
                 for row in r.status()["replicas"]}
        assert tiers["rt-d"] == "prefill"
        assert st["counters"].get("router.retiers", 0) == 0  # pre-call
        assert r.status()["counters"]["router.retiers"] == 1

        # With no decode pool left, routing degrades to unified
        # placement — still correct tokens.
        got2 = c.generate_ids([prompt], gen_len=gen)
        assert got2["tokens"][0] == want
        c.close()
    finally:
        r.stop()
        p.stop()
        d.stop()


# ---------------------------------------------------------------------------
# Acceptance: sever mid-stream, zero client errors.
# ---------------------------------------------------------------------------

def test_sever_stream_zero_client_errors(paged_tiny, monkeypatch):
    """chaos.sever_stream kills the prefill replica after the first
    shipped block. The router's dispatch dies on the severed socket,
    tiered placement yields to the unified loop, and the DECODE
    replica serves the request in full — the client sees correct
    tokens, never an error. The decode side's half-received staging
    entry is purged as ``disagg.streams_severed`` on its next offer."""
    monkeypatch.setenv("TDT_KVSTREAM_STALE_S", "1")
    prompt = list(range(1, 13))
    gen = 4
    want = _golden(paged_tiny, prompt, gen)
    p = _paged_server(paged_tiny, "sv-p", tier="prefill")
    d = _paged_server(paged_tiny, "sv-d", tier="decode")
    eps = [(p.host, p.port), (d.host, d.port)]
    r = RouterServer(eps, registry="private", poll_s=0.05,
                     fleet_kwargs={"stale_s_": 0.5, "down_s_": 1.5,
                                   "timeout_s": 5.0}).start()
    try:
        _wait(lambda: {row["tier"] for row in r.status()["replicas"]}
              == {"prefill", "decode"}, what="tier pickup")
        with chaos.sever_stream(p, after_blocks=1) as cut:
            c = ChatClient(r.host, r.port, timeout=120)
            got = c.generate_ids([prompt], gen_len=gen)
            assert cut.fired.is_set()
            assert cut.blocks == 1
        # Zero client errors: the answer is the unified greedy tokens,
        # served by the surviving replica.
        assert got["tokens"][0] == want
        assert "error" not in got
        st = r.status()
        assert st["counters"].get("router.disagg_errors", 0) >= 1
        assert st["counters"].get("router.disagg_dispatches", 0) == 0
        # The decode side holds a half-received handoff; its next
        # offer purges the stale entry and counts the severed stream.
        assert len(d.disagg.staging) == 1
        time.sleep(1.1)                    # > TDT_KVSTREAM_STALE_S
        from triton_dist_tpu import obs
        with obs.scoped_registry(d.registry):
            d.disagg.handle("kv_offer",
                            {"handoff_id": "probe", "hashes": [],
                             "n_blocks": 1})
        assert _counter(d, "disagg.streams_severed") == 1
        c.close()
    finally:
        r.stop()
        p.stop()
        d.stop()


# ---------------------------------------------------------------------------
# Protocol model: clean verify + DISTINCT mutation codes.
# ---------------------------------------------------------------------------

def test_kvstream_model_clean():
    from triton_dist_tpu.analysis import kvstream_model
    assert kvstream_model.verify_kvstream() == []


def test_kvstream_mutations_distinct_codes():
    """Each mutation class fails with its OWN finding code — dropped
    signal deadlocks, double-ship leaves the semaphore unbalanced,
    dedup dropping a needed block breaks coverage. Pairwise-distinct
    signatures, so a regression names its failure class."""
    from triton_dist_tpu.analysis import kvstream_model as km
    from triton_dist_tpu.analysis.protocol_model import check_trace
    t = km.handoff_trace(4, 1)

    dropped = {v.code for v in check_trace(km.drop_signal(t))}
    doubled = {v.code for v in check_trace(km.double_ship(t))}
    deduped = {v.code for v in check_trace(km.dedup_drop_needed(4, 1))}

    assert "kvstream.deadlock" in dropped
    assert doubled == {"kvstream.signal_wait_imbalance"}
    assert deduped == {"kvstream.coverage"}
    # Signatures are pairwise distinct: coverage-only, imbalance-only,
    # and deadlock (absent from both others).
    assert "kvstream.deadlock" not in doubled | deduped
    assert "kvstream.coverage" not in dropped | doubled
    assert len({frozenset(dropped), frozenset(doubled),
                frozenset(deduped)}) == 3


def test_kvstream_claimed_and_changed_selection():
    """lint_protocol claims serving/kv_stream.py for kvstream-protocol
    (path-keyed CLAIM), and ``tdt-check --changed`` on any of the
    three disagg files selects the protocol pass plus the metric /
    annotation watches that pin them."""
    from triton_dist_tpu.analysis import select_passes_for
    from triton_dist_tpu.analysis.lint_protocol import CLAIMS, run
    assert CLAIMS["serving/kv_stream.py"] == "kvstream-protocol"
    assert run(None) == []                 # the claim verifies
    for f in ("triton_dist_tpu/serving/kv_stream.py",
              "triton_dist_tpu/serving/disagg.py",
              "triton_dist_tpu/analysis/kvstream_model.py"):
        sel = set(select_passes_for([f]))
        assert "kvstream-protocol" in sel, f
    sel = set(select_passes_for(["triton_dist_tpu/serving/disagg.py"]))
    assert {"metric-catalog", "annotation-coverage"} <= sel


# ---------------------------------------------------------------------------
# Satellites: regress gate + dashboards.
# ---------------------------------------------------------------------------

def test_check_disagg_wellformed_gate():
    from triton_dist_tpu.tools.bench_ops import check_disagg_wellformed
    good = {"serving_disagg_tokens_per_s": 10.0,
            "serving_disagg_vs_unified": 1.1,
            "serving_disagg_handoffs": 3,
            "serving_disagg_handoff_p50_ms": 12.0,
            "serving_disagg_dedup_ratio": 0.5}
    assert check_disagg_wellformed(good) == []
    assert check_disagg_wellformed({}) == []   # part not run: no-op
    bad = dict(good, serving_disagg_vs_unified=0.0)
    assert check_disagg_wellformed(bad)
    bad = dict(good, serving_disagg_handoffs=0)
    assert check_disagg_wellformed(bad)
    bad = dict(good, serving_disagg_dedup_ratio=1.5)
    assert check_disagg_wellformed(bad)


def test_fleet_top_tier_column(paged_tiny):
    from triton_dist_tpu.obs.fleet import FleetView
    from triton_dist_tpu.tools import fleet_top
    p = _paged_server(paged_tiny, "ft-p", tier="prefill")
    try:
        view = FleetView([(p.host, p.port)])
        screen = fleet_top.render({"replicas": view.poll(),
                                   "merged": None})
        assert "tier" in screen.splitlines()[2]
        assert "prefill" in screen
    finally:
        p.stop()


def test_report_disagg_section():
    from triton_dist_tpu.tools.report import render_disagg
    snap = {"counters": {"disagg.handoffs": 2,
                         "disagg.blocks_offered": 6,
                         "disagg.blocks_deduped": 3},
            "histograms": {"disagg.handoff_ms": {
                "count": 2, "sum": 30.0, "min": 10.0, "max": 20.0,
                "buckets": [[16.0, 1], [32.0, 2]]}}}
    out = render_disagg(snap)
    assert "#### disagg" in out
    assert "disagg.handoff_ms" in out
    assert "dedup ratio | 0.5" in out
    assert render_disagg({"counters": {}}) == ""
