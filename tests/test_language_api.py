"""Primitive-exhaustive device-language tests.

The analog of the reference's test/nvidia/test_nvshmem_api.py (962 LoC
exercising every libshmem_device primitive individually): every public
symbol of ``triton_dist_tpu.language`` and ``language.shmem`` gets at
least one kernel-level test here, beyond the protocol-shaped cases in
test_language.py (VERDICT r2 next 10 "primitive-exhaustive language/
tests").
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import triton_dist_tpu.language as dl
from triton_dist_tpu.language import shmem
from triton_dist_tpu.ops.common import comm_params, resolve_interpret

WORLD = 8


def _run(mesh, kernel, x, axis="tp", out_shape=None, scratch_shapes=(),
         collective_id=0, in_axes_spec=None, out_axes_spec=None):
    spec = in_axes_spec or P(axis)
    out_spec = out_axes_spec or spec
    out_shape = out_shape or jax.ShapeDtypeStruct(
        (x.shape[0] // mesh.shape[axis],) + x.shape[1:], x.dtype)

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh, in_specs=spec,
                       out_specs=out_spec, check_vma=False)
    def run(x):
        return pl.pallas_call(
            kernel,
            out_shape=out_shape,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=list(scratch_shapes),
            compiler_params=comm_params(collective_id),
            interpret=resolve_interpret(None),
        )(x)

    return run(x)


# --- identity / topology ---------------------------------------------------

def test_shmem_pe_queries(mesh8):
    """my_pe/n_pes/team_my_pe/team_n_pes (reference shmem identity API)."""
    def kernel(x_ref, o_ref):
        v = (shmem.my_pe("tp") * 1000 + shmem.n_pes("tp") * 100
             + shmem.team_my_pe("tp") * 10 + jnp.int32(0))
        o_ref[:] = jnp.full_like(o_ref, v + shmem.team_n_pes("tp"))

    x = jnp.zeros((WORLD * 8, 128), jnp.int32)
    got = np.asarray(_run(mesh8, kernel, x)).reshape(WORLD, 8, 128)
    for r in range(WORLD):
        assert (got[r] == r * 1000 + 800 + r * 10 + 8).all(), r


def test_multi_value_wait(mesh8):
    """notify(inc=k) accumulates; wait(k) consumes exactly k — split
    waits must drain a single accumulated signal."""
    def kernel(x_ref, o_ref, sem):
        dl.notify(sem, inc=5)
        dl.wait(sem, 3)               # consume 3 of the 5
        dl.wait(sem, 2)               # drain the rest
        o_ref[:] = jnp.full_like(o_ref, 52)

    x = jnp.zeros((WORLD * 8, 128), jnp.int32)
    got = _run(mesh8, kernel, x,
               scratch_shapes=[pltpu.SemaphoreType.REGULAR])
    assert (np.asarray(got) == 52).all()


def test_semaphore_read(mesh8):
    """semaphore_read observes without consuming (debug aid). The
    interpreter may not implement it — hardware-only then."""
    def kernel(x_ref, o_ref, sem):
        dl.notify(sem, inc=5)
        before = dl.semaphore_read(sem)
        dl.wait(sem, 5)
        o_ref[:] = jnp.full_like(o_ref, before)

    x = jnp.zeros((WORLD * 8, 128), jnp.int32)
    try:
        got = _run(mesh8, kernel, x,
                   scratch_shapes=[pltpu.SemaphoreType.REGULAR])
    except NotImplementedError:
        pytest.skip("semaphore_read unimplemented in interpret mode")
    assert (np.asarray(got) == 5).all()


def test_notify_wait_cross_rank_values(mesh8):
    """Remote notify with inc>1: every rank signals its right neighbor
    w+me times; neighbor waits for exactly that count."""
    def kernel(x_ref, o_ref, sem):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        dst = jax.lax.rem(me + 1, n)
        dl.notify(sem, peer=dst, inc=8 + dst, axis="tp")
        dl.wait(sem, 8 + me)
        o_ref[:] = jnp.full_like(o_ref, me)

    x = jnp.zeros((WORLD * 8, 128), jnp.int32)
    got = np.asarray(_run(
        mesh8, kernel, x,
        scratch_shapes=[pltpu.SemaphoreType.REGULAR])).reshape(WORLD, 8, 128)
    for r in range(WORLD):
        assert (got[r] == r).all()


# --- one-sided data movement -----------------------------------------------

def test_local_copy_roundtrip(mesh8):
    """dl.local_copy: async same-chip DMA through a scratch buffer."""
    def kernel(x_ref, o_ref, stage, sem):
        cp = dl.local_copy(x_ref, stage, sem)
        cp.start()
        cp.wait()
        o_ref[:] = stage[:] * 2.0

    x = jnp.arange(WORLD * 8 * 128, dtype=jnp.float32).reshape(-1, 128)
    got = _run(mesh8, kernel, x, scratch_shapes=[
        pltpu.VMEM((8, 128), jnp.float32), pltpu.SemaphoreType.DMA])
    np.testing.assert_allclose(np.asarray(got), np.asarray(x) * 2.0)


def test_remote_copy_full_exchange(mesh8):
    """Every rank puts its block to EVERY peer slot (the reference's
    putmem-to-all nvshmem case) with per-source semaphores."""
    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        o_ref[me] = x_ref[:]
        dl.barrier_all("tp")

        def put(p, _):
            peer = jax.lax.rem(me + p, n)
            dl.remote_copy(o_ref.at[me], o_ref.at[me], peer,
                           send_sem.at[peer], recv_sem.at[me],
                           axis="tp").start()
            return _
        jax.lax.fori_loop(1, n, put, None)

        def wait_one(p, _):
            src = jax.lax.rem(me - p + n, n)
            dl.remote_copy(o_ref.at[src], o_ref.at[src], me,
                           send_sem.at[src], recv_sem.at[src],
                           axis="tp").wait_recv()
            return _
        jax.lax.fori_loop(1, n, wait_one, None)

        def drain(p, _):
            peer = jax.lax.rem(me + p, n)
            dl.remote_copy(o_ref.at[me], o_ref.at[me], peer,
                           send_sem.at[peer], recv_sem.at[me],
                           axis="tp").wait_send()
            return _
        jax.lax.fori_loop(1, n, drain, None)

    x = (jnp.arange(WORLD)[:, None, None]
         * jnp.ones((WORLD, 8, 128))).astype(jnp.float32).reshape(-1, 128)
    out_shape = jax.ShapeDtypeStruct((WORLD, 8, 128), jnp.float32)
    got = _run(mesh8, kernel, x, out_shape=out_shape,
               scratch_shapes=[pltpu.SemaphoreType.DMA((WORLD,)),
                               pltpu.SemaphoreType.DMA((WORLD,))],
               out_axes_spec=P("tp"))
    got = np.asarray(got).reshape(WORLD, WORLD, 8, 128)
    for r in range(WORLD):
        for src in range(WORLD):
            assert (got[r, src] == src).all(), (r, src)


def test_putmem_block_blocking(mesh8):
    """shmem.putmem_block: the blocking put (send side complete on
    return)."""
    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = dl.rank("tp")
        dst = jax.lax.rem(me + 1, dl.num_ranks("tp"))
        cp = shmem.putmem_block(o_ref, x_ref, dst, send_sem, recv_sem)
        # putmem_block completes the SEND side; the receiver still
        # observes delivery via its recv semaphore (NVSHMEM contract).
        cp.wait_recv()

    x = (jnp.arange(WORLD)[:, None, None]
         * jnp.ones((WORLD, 8, 128))).astype(jnp.float32).reshape(-1, 128)
    got = np.asarray(_run(mesh8, kernel, x, scratch_shapes=[
        pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA(())])
    ).reshape(WORLD, 8, 128)
    for r in range(WORLD):
        assert (got[r] == (r - 1) % WORLD).all(), r


def test_putmem_signal_nbi_block_and_wait_until(mesh8):
    """putmem_signal_nbi: on TPU the recv semaphore IS the delivery
    signal (shmem.py docstring), so the receiver gates on wait_recv —
    the analog of the reference's putmem_signal + signal_wait_until."""
    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        dst = jax.lax.rem(me + 1, n)
        cp = shmem.putmem_signal_nbi_block(o_ref, x_ref, dst, send_sem,
                                           recv_sem, axis="tp")
        cp.wait_recv()
        cp.wait_send()
        o_ref[:] = o_ref[:] + 100.0

    x = (jnp.arange(WORLD)[:, None, None]
         * jnp.ones((WORLD, 8, 128))).astype(jnp.float32).reshape(-1, 128)
    got = np.asarray(_run(mesh8, kernel, x, scratch_shapes=[
        pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA(())])
    ).reshape(WORLD, 8, 128)
    for r in range(WORLD):
        assert (got[r] == (r - 1) % WORLD + 100.0).all(), r


def test_signal_op_add(mesh8):
    """shmem.signal_op: bare remote signal (SIGNAL_ADD), no data."""
    def kernel(x_ref, o_ref, flag):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        shmem.signal_op(flag, jax.lax.rem(me + 1, n), inc=4, axis="tp")
        shmem.signal_wait_until(flag, shmem.CMP_GE, 4)
        o_ref[:] = x_ref[:] + 1.0

    x = jnp.zeros((WORLD * 8, 128), jnp.float32)
    got = _run(mesh8, kernel, x,
               scratch_shapes=[pltpu.SemaphoreType.REGULAR])
    assert (np.asarray(got) == 1.0).all()


def test_fence_and_quiet(mesh8):
    """fence/quiet complete the send side of prior puts (reference
    libshmem_device.fence/quiet semantics)."""
    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        dst = jax.lax.rem(me + 1, n)
        cp = shmem.putmem_nbi_block(o_ref, x_ref, dst, send_sem, recv_sem,
                                    axis="tp")
        shmem.fence(cp)      # send-side ordering point
        shmem.quiet()        # vacuous quiet (no descriptors) is legal
        cp.wait_recv()
        o_ref[:] = o_ref[:] * 3.0

    x = (jnp.arange(WORLD)[:, None, None]
         * jnp.ones((WORLD, 8, 128))).astype(jnp.float32).reshape(-1, 128)
    got = np.asarray(_run(mesh8, kernel, x, scratch_shapes=[
        pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA(())])
    ).reshape(WORLD, 8, 128)
    for r in range(WORLD):
        assert (got[r] == 3.0 * ((r - 1) % WORLD)).all(), r


# --- barriers ---------------------------------------------------------------

def test_barrier_neighbors_ring_step(mesh8):
    """barrier_neighbors is sufficient to order ring-neighbor puts."""
    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        dst = jax.lax.rem(me + 1, n)
        cp = shmem.putmem_nbi_block(o_ref, x_ref, dst, send_sem, recv_sem,
                                    axis="tp")
        cp.wait()
        dl.barrier_neighbors("tp")
        o_ref[:] = o_ref[:] + 0.5

    x = (jnp.arange(WORLD)[:, None, None]
         * jnp.ones((WORLD, 8, 128))).astype(jnp.float32).reshape(-1, 128)
    got = np.asarray(_run(mesh8, kernel, x, scratch_shapes=[
        pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA(())],
        collective_id=2)).reshape(WORLD, 8, 128)
    for r in range(WORLD):
        assert (got[r] == (r - 1) % WORLD + 0.5).all(), r


def test_shmem_barrier_all_alias(mesh8):
    """shmem.barrier_all delegates to dl.barrier_all."""
    def kernel(x_ref, o_ref):
        shmem.barrier_all("tp")
        o_ref[:] = x_ref[:] + 7.0

    x = jnp.zeros((WORLD * 8, 128), jnp.float32)
    got = _run(mesh8, kernel, x, collective_id=3)
    assert (np.asarray(got) == 7.0).all()


# --- multi-axis meshes -------------------------------------------------------

@pytest.mark.parametrize("axis,other", [("tp", "ep"), ("ep", "tp")])
def test_put_ring_2d_mesh_both_axes(mesh4x2, axis, other):
    """Ring put along EITHER axis of a (tp=4, ep=2) mesh:
    logical_device_id must translate axis-relative peers to global ids
    (VERDICT r2 next 10 '2-D mesh variants')."""
    world = mesh4x2.shape[axis]
    mesh_axes = ("tp", "ep")

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = dl.rank(axis)
        dst = jax.lax.rem(me + 1, jnp.int32(world))
        cp = shmem.putmem_nbi_block(o_ref, x_ref, dst, send_sem, recv_sem,
                                    axis=axis, mesh_axes=mesh_axes)
        cp.wait()

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh4x2, in_specs=P(("tp", "ep")),
        out_specs=P(("tp", "ep")), check_vma=False)
    def run(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
            compiler_params=comm_params(0),
            interpret=resolve_interpret(None),
        )(x)

    # value = global device index
    x = (jnp.arange(WORLD)[:, None, None]
         * jnp.ones((WORLD, 8, 128))).astype(jnp.float32).reshape(-1, 128)
    got = np.asarray(run(x)).reshape(4, 2, 8, 128)
    for tp in range(4):
        for ep in range(2):
            if axis == "tp":
                src = ((tp - 1) % 4) * 2 + ep
            else:
                src = tp * 2 + (ep - 1) % 2
            assert (got[tp, ep] == src).all(), (axis, tp, ep)
