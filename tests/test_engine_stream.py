"""Continuous batching (Engine.serve_stream): streamed greedy results
must equal serving each prompt alone — admission into freed rows cannot
perturb the other rows' generations (beyond-reference; vLLM-style)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture()
def small_model(mesh8, key):
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    return model, model.init(key)


def solo(model, params, mesh8, prompt, gen_len, stop=()):
    eng = Engine(model, batch=1, max_seq=32, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    out = np.asarray(eng.serve(params, jnp.asarray([prompt], jnp.int32),
                               gen_len, stop_tokens=stop))[0]
    row = out.tolist()
    if stop:
        # serve() pads stopped rows with the stop token; trim to match
        # serve_stream's exact-retire contract.
        gen = row[len(prompt):]
        for i, t in enumerate(gen):
            if t in set(stop):
                gen = gen[:i + 1]
                break
        row = row[:len(prompt)] + gen
    return row


def test_stream_more_requests_than_rows(small_model, mesh8):
    model, params = small_model
    prompts = [[1, 2, 3], [9, 8], [4, 5, 6, 7], [11], [23, 29], [31]]
    gen_len = 5
    eng = Engine(model, batch=2, max_seq=32, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    got = eng.serve_stream(params, prompts, gen_len)
    assert len(got) == len(prompts)
    for prompt, row in zip(prompts, got):
        want = solo(model, params, mesh8, prompt, gen_len)
        assert row == want, (prompt, row, want)


def test_stream_stop_tokens_free_rows_early(small_model, mesh8):
    model, params = small_model
    # pick a stop token that actually occurs early for some prompt by
    # probing the solo generations
    prompts = [[1, 2], [3, 4], [5, 6], [7, 8]]
    gen_len = 6
    probe = [solo(model, params, mesh8, p, gen_len) for p in prompts]
    stop = (probe[0][len(prompts[0]) + 1],)  # 2nd generated tok of req 0
    eng = Engine(model, batch=2, max_seq=32, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    got = eng.serve_stream(params, prompts, gen_len, stop_tokens=stop)
    for prompt, row in zip(prompts, got):
        want = solo(model, params, mesh8, prompt, gen_len, stop=stop)
        assert row == want, (prompt, row, want)


def test_stream_single_row_window(small_model, mesh8):
    """batch=1 degenerates to sequential serving."""
    model, params = small_model
    prompts = [[2, 3, 5], [7]]
    eng = Engine(model, batch=1, max_seq=32, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    got = eng.serve_stream(params, prompts, 4)
    for prompt, row in zip(prompts, got):
        assert row == solo(model, params, mesh8, prompt, 4)


def test_stream_gen_len_zero_noop(small_model):
    model, params = small_model
    eng = Engine(model, batch=2, max_seq=32, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    assert eng.serve_stream(params, [[1, 2], [3]], 0) == [[1, 2], [3]]


@pytest.fixture()
def sp_model(mesh8, key):
    from jax.sharding import Mesh
    import numpy as _np
    devs = [d for d in mesh8.devices.flat]
    mesh = Mesh(_np.array(devs).reshape(1, 8), ("tp", "sp"))
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, head_dim=16, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh, axis="tp", sp_axis="sp",
                     impl="pallas", fwd_mode="sp")
    return model, model.init(key)


_SP_GOLDEN_CACHE: dict = {}


def _solo_sp(model, params, prompt, gen_len):
    # Golden: the plain tp engine on the same params — sp serving is
    # token-equal to it (test_sp_model.py::test_sp_paged_serving_matches)
    # and, unlike a solo sp engine, it accepts prompt lengths that
    # don't divide the sp world (the very case stream bucketing adds).
    # Cached across the paged parametrizations (paged-independent).
    key = (id(model), tuple(prompt), gen_len)
    if key not in _SP_GOLDEN_CACHE:
        eng = Engine(model, batch=1, max_seq=64, prefill_mode="xla",
                     decode_mode="xla_ar")
        _SP_GOLDEN_CACHE[key] = np.asarray(eng.serve(
            params, jnp.asarray([prompt], jnp.int32),
            gen_len))[0].tolist()
    return _SP_GOLDEN_CACHE[key]


@pytest.mark.parametrize("paged", [False, True])
def test_stream_sp_and_paged(sp_model, paged):
    """Continuous batching over the long-context engine families: the
    seq-sharded cache (per-row scatter through forward_sp) and the
    vLLM-style paged pools (block-granular admission prefills straight
    into the admitted row's pages; retired rows release eagerly)."""
    model, params = sp_model
    prompts = [[1, 2, 3], [9, 8], [4, 5, 6, 7], [11], [23, 29]]
    gen_len = 5
    eng = Engine(model, batch=2, max_seq=64, prefill_mode="sp",
                 decode_mode="sp", paged=paged, page_size=4)
    got = eng.serve_stream(params, prompts, gen_len)
    assert len(got) == len(prompts)
    for prompt, row in zip(prompts, got):
        want = _solo_sp(model, params, prompt, gen_len)
        assert row == want, (paged, prompt, row, want)


def test_stream_paged_fewer_requests_than_rows(sp_model):
    """n_req < batch (advisor r3, medium): lanes that are NEVER admitted
    still run the per-row KV write each decode step through their
    block-table lane. Under block-granular admission (ISSUE 6) those
    lanes point at the per-device SENTINEL block, so frozen writes are
    structurally harmless; the lone request must decode exactly as
    when served alone."""
    model, params = sp_model
    prompt = [4, 5, 6, 7]
    gen_len = 6
    eng = Engine(model, batch=3, max_seq=64, prefill_mode="sp",
                 decode_mode="sp", paged=True, page_size=4)
    got = eng.serve_stream(params, [prompt], gen_len)
    assert got[0] == _solo_sp(model, params, prompt, gen_len)


def test_stream_sampled_deterministic_per_seed(small_model):
    """Stochastic streaming is reproducible: same seed → same tokens
    (the engine key advances identically through admissions + steps)."""
    model, params = small_model
    prompts = [[1, 2], [3, 4, 5], [6]]
    outs = []
    for _ in range(2):
        eng = Engine(model, batch=2, max_seq=32, prefill_mode="xla_ar",
                     decode_mode="gemm_ar", temperature=0.8, top_k=8,
                     top_p=0.9, seed=13)
        outs.append(eng.serve_stream(params, prompts, 4))
    assert outs[0] == outs[1]


def test_stream_randomized_admission_fuzz(small_model, mesh8):
    """Seeded fuzz over the admission scheduler: random prompt lengths,
    a random stop token, 12 requests through 3 rows — every streamed
    row must equal its solo generation (reference stress_test_ag_gemm
    style: randomized loops catching sync bugs)."""
    model, params = small_model
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 64, size=int(n)).tolist()
               for n in rng.integers(1, 7, size=12)]
    stop = (int(rng.integers(1, 64)),)
    gen_len = int(rng.integers(2, 7))
    eng = Engine(model, batch=3, max_seq=32, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    got = eng.serve_stream(params, prompts, gen_len, stop_tokens=stop)
    for prompt, row in zip(prompts, got):
        want = solo(model, params, mesh8, prompt, gen_len, stop=stop)
        assert row == want, (prompt, row, want)


def test_stream_2d_tp_x_sp(mesh8, key):
    """Streaming over the 2-D tp×sp grid: heads tensor-parallel inside
    the sequence ring, per-row offsets through forward_sp."""
    from jax.sharding import Mesh
    import numpy as _np
    devs = [d for d in mesh8.devices.flat]
    mesh = Mesh(_np.array(devs).reshape(2, 4), ("tp", "sp"))
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, head_dim=16, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh, axis="tp", sp_axis="sp",
                     impl="pallas", fwd_mode="sp")
    params = model.init(key)
    prompts = [[1, 2, 3], [9, 8], [4, 5, 6, 7]]
    eng = Engine(model, batch=2, max_seq=64, prefill_mode="sp",
                 decode_mode="sp")
    got = eng.serve_stream(params, prompts, 4)
    golden = Engine(model, batch=1, max_seq=64, prefill_mode="xla_ar",
                    decode_mode="xla_ar")
    for prompt, row in zip(prompts, got):
        want = np.asarray(golden.serve(
            params, jnp.asarray([prompt], jnp.int32), 4))[0].tolist()
        assert row == want, (prompt, row, want)


@pytest.mark.parametrize("moe_parallel", ["tp", "ep"])
def test_stream_moe_model(mesh8, key, moe_parallel):
    """Per-row offsets thread through Qwen3MoE.forward — in BOTH MoE
    parallelizations (the EP dispatch/combine is token-level, so the
    per-row decode positions only touch the attention/cache path)."""
    from triton_dist_tpu.models import ModelConfig, Qwen3MoE
    cfg = ModelConfig(hidden_size=32, moe_intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32,
                      num_experts=8, num_experts_per_tok=2,
                      intermediate_size=0)
    model = Qwen3MoE(cfg, mesh=mesh8, axis="tp", impl="xla",
                     moe_parallel=moe_parallel)
    params = model.init(key)
    prompts = [[1, 2, 3], [9, 8], [4, 5]]
    eng = Engine(model, batch=2, max_seq=32, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    got = eng.serve_stream(params, prompts, 3)
    for prompt, row in zip(prompts, got):
        solo_eng = Engine(model, batch=1, max_seq=32,
                          prefill_mode="xla_ar", decode_mode="gemm_ar")
        want = np.asarray(solo_eng.serve(
            params, jnp.asarray([prompt], jnp.int32), 3))[0].tolist()
        assert row == want, (prompt, row, want)
