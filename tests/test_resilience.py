"""Resilience subsystem: watchdog, breaker, known-bad cache, routing.

Quick tier, CPU-only: every breaker/fallback/retry transition is
driven by deterministic fault injection (testing/faults.py), not wall
clocks or real hardware misbehavior. Fused ops run on a 1-device mesh
— world=1 compiles the kernels without the multi-device barrier
semaphore the container's jax 0.4.x interpreter cannot trace
(CHANGES.md PR 2 note), and the resilience machinery is world-size
agnostic.

The acceptance scenario (ISSUE 3): a deterministically injected
compile hang in one fused op (a) does not block other ops, (b) opens
that op's breaker and lands in the known-bad cache, (c) routes
subsequent calls to the XLA fallback with bit-identical numerics, and
(d) is visible in ``resilience.*`` metrics via ``{"cmd": "metrics"}``.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu import obs, resilience
from triton_dist_tpu.ops.allreduce import (all_reduce,
                                           create_allreduce_context)
from triton_dist_tpu.ops.gemm_reduce_scatter import (
    create_gemm_rs_context, gemm_rs)
from triton_dist_tpu.ops.p2p import create_p2p_context, pp_shift
from triton_dist_tpu.testing import faults


@pytest.fixture()
def mesh1(devices):
    """1-device mesh: compiles fused kernels eagerly on this jax
    (world=1 skips the barrier semaphore the 0.4.x interpreter cannot
    trace on multi-device CPU meshes)."""
    return Mesh(np.array(devices[:1]), ("tp",))


@pytest.fixture()
def registry():
    reg = obs.enable(obs.Registry())
    yield reg
    obs.disable()


def _counters():
    return obs.snapshot()["counters"]


def _gemm_rs_operands():
    a = (jnp.arange(256, dtype=jnp.float32).reshape(16, 16) / 7.0)
    b = (jnp.arange(256, dtype=jnp.float32).reshape(16, 16) / 11.0)
    return a, b


# ---------------------------------------------------------------------------
# Breaker state machine (pure, fake clock).
# ---------------------------------------------------------------------------

def test_breaker_state_machine():
    t = [0.0]
    b = resilience.CircuitBreaker("x", threshold=2, cooldown_s=10.0,
                                  clock=lambda: t[0])
    assert b.state == resilience.CLOSED and b.allow()
    b.record_failure()
    assert b.state == resilience.CLOSED      # below threshold
    b.record_failure()
    assert b.state == resilience.OPEN and not b.allow()
    t[0] = 9.9
    assert not b.allow()                     # cooldown not elapsed
    t[0] = 10.0
    assert b.allow()                         # half-open probe admitted
    assert b.state == resilience.HALF_OPEN
    assert not b.allow()                     # ONE probe: others fall back
    t[0] = 19.9
    assert not b.allow()
    t[0] = 20.0
    assert b.allow()                         # lost probe replaced
    b.record_failure()                       # probe failed → re-open
    assert b.state == resilience.OPEN and not b.allow()
    t[0] = 25.0
    assert not b.allow()                     # timer reset at re-open
    t[0] = 30.0
    assert b.allow() and b.state == resilience.HALF_OPEN
    b.record_success()                       # probe passed → closed
    assert b.state == resilience.CLOSED and b.allow()
    b.record_failure()
    b.record_success()                       # success resets the count
    b.record_failure()
    assert b.state == resilience.CLOSED


def test_breaker_metrics(registry):
    b = resilience.CircuitBreaker("metric_demo", threshold=1,
                                  cooldown_s=1000.0)
    b.record_failure()
    snap = obs.snapshot()
    assert snap["gauges"]["resilience.metric_demo.breaker_state"] == 1
    assert snap["counters"]["resilience.metric_demo.breaker_opens"] == 1


# ---------------------------------------------------------------------------
# Known-bad cache persistence.
# ---------------------------------------------------------------------------

def test_known_bad_cache_persists_across_processes(tmp_path):
    path = tmp_path / "kb.json"
    env = dict(os.environ, TDT_KNOWN_BAD_CACHE=str(path),
               JAX_PLATFORMS="cpu")
    code = ("from triton_dist_tpu.resilience import known_bad_cache; "
            "known_bad_cache().record('op1', 'cfg=1', 'devkind', 'why')")
    subprocess.run([sys.executable, "-c", code], env=env, check=True,
                   timeout=300)
    # A FRESH cache object (a different process's view) sees the entry.
    cache = resilience.KnownBadCache(str(path))
    key = resilience.known_bad_key("op1", "cfg=1", "devkind")
    assert key in cache
    assert cache.entries()[key]["reason"] == "why"
    # Writes merge rather than clobber.
    cache.record("op2", "cfg=2", "devkind", "also")
    reread = resilience.KnownBadCache(str(path))
    assert key in reread and len(reread) == 2
    # A corrupt file degrades to empty, never raises.
    path.write_text("{not json")
    assert len(resilience.KnownBadCache(str(path))) == 0


def test_known_bad_ttl_expires_entries(tmp_path, monkeypatch):
    path = tmp_path / "kb.json"
    cache = resilience.KnownBadCache(str(path))
    key = cache.record("op1", "cfg", "devk", "why")
    assert key in cache
    monkeypatch.setenv("TDT_KNOWN_BAD_TTL_S", "0.0001")
    import time
    time.sleep(0.01)
    assert key not in cache          # aged out of routing
    # Every view agrees with routing: len, entries, and the gauge.
    assert len(cache) == 0 and cache.entries() == {}
    monkeypatch.setenv("TDT_KNOWN_BAD_TTL_S", "3600")
    assert key in cache and len(cache) == 1


def test_trace_does_not_mark_key_compiled(mesh1, monkeypatch, registry):
    """A successful jit TRACE must not absorb the first-compile
    watchdog slot or close a half-open breaker — only a real eager
    execution proves the config safe."""
    monkeypatch.setenv("TDT_COMPILE_TIMEOUT_S", "0.3")
    resilience.reset_for_tests()
    xp = jnp.ones((1, 8, 128), jnp.float32)
    ctx = create_p2p_context(mesh1, "tp")
    # Trace-only touch of the config (no execution).
    jax.eval_shape(lambda x: pp_shift(x, ctx, impl="pallas"), xp)
    # The next EAGER call is still treated as the first compile: an
    # injected hang trips the watchdog rather than running unguarded.
    with faults.inject("compile_hang", op="pp_shift", hang_s=5.0):
        out = pp_shift(xp, ctx, impl="pallas")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(xp))
    assert _counters()["resilience.pp_shift.watchdog_trips"] == 1


# ---------------------------------------------------------------------------
# Routing policies.
# ---------------------------------------------------------------------------

def test_baseline_policy_routes_slow_ops_to_xla(mesh1, monkeypatch,
                                                tmp_path, registry):
    baseline = {"regression_floors": {"tpu": {"gemm_rs_vs_xla": 0.86}}}
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps(baseline))
    monkeypatch.setenv("TDT_BASELINE_PATH", str(p))
    monkeypatch.setenv("TDT_BASELINE_ROUTING", "tpu")
    resilience.reset_for_tests()

    a, b = _gemm_rs_operands()
    ctx = create_gemm_rs_context(mesh1, "tp")
    ref = gemm_rs(a, b, ctx, impl="xla")
    out = gemm_rs(a, b, ctx, impl="pallas")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    c = _counters()
    assert c["resilience.gemm_rs.fallback.policy"] == 1
    assert "resilience.gemm_rs.fused_total" not in c

    # An op with no BASELINE ratio is not policy-routed.
    xp = jnp.ones((1, 16, 16), jnp.float32)
    all_reduce(xp, create_allreduce_context(mesh1, "tp"), impl="pallas")
    c = _counters()
    assert c["resilience.allreduce.fused_total"] == 1
    assert "resilience.allreduce.fallbacks_total" not in c

    # The routing decision also bakes into jitted programs (trace time).
    jit_out = jax.jit(lambda x, w: gemm_rs(x, w, ctx, impl="pallas")
                      )(a, b)
    np.testing.assert_allclose(np.asarray(jit_out), np.asarray(ref),
                               rtol=1e-6)
    assert _counters()["resilience.gemm_rs.fallback.policy"] >= 2

    # TDT_FORCE_FUSED overrides the policy (bench/smoke/revalidation).
    monkeypatch.setenv("TDT_FORCE_FUSED", "1")
    out2 = gemm_rs(a, b, ctx, impl="pallas")
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(ref))
    assert _counters()["resilience.gemm_rs.fused_total"] == 1


def test_ratio_above_threshold_stays_fused(mesh1, monkeypatch, tmp_path,
                                           registry):
    # 0.95 is the r5 gemm_ar floor — a CI gate UNDER a measured 1.065x
    # win. The default 0.9 threshold's parity margin must keep such
    # floors fused (review r6d finding 1).
    baseline = {"regression_floors": {"tpu": {"gemm_rs_vs_xla": 0.95}}}
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps(baseline))
    monkeypatch.setenv("TDT_BASELINE_PATH", str(p))
    monkeypatch.setenv("TDT_BASELINE_ROUTING", "tpu")
    resilience.reset_for_tests()
    a, b = _gemm_rs_operands()
    ctx = create_gemm_rs_context(mesh1, "tp")
    gemm_rs(a, b, ctx, impl="pallas")
    c = _counters()
    assert c["resilience.gemm_rs.fused_total"] == 1
    assert "resilience.gemm_rs.fallbacks_total" not in c


# ---------------------------------------------------------------------------
# Fault-driven transitions.
# ---------------------------------------------------------------------------

def test_comm_error_falls_back_then_recovers(mesh1, monkeypatch,
                                             registry):
    monkeypatch.setenv("TDT_BREAKER_THRESHOLD", "3")
    resilience.reset_for_tests()
    xp = (jnp.arange(256, dtype=jnp.float32).reshape(1, 16, 16) / 3.0)
    ctx = create_allreduce_context(mesh1, "tp")
    ref = all_reduce(xp, ctx, impl="xla")
    with faults.inject("comm_error", op="allreduce", times=1):
        out = all_reduce(xp, ctx, impl="pallas")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    c = _counters()
    assert c["resilience.allreduce.fallback.error"] == 1
    assert resilience.get_breaker("allreduce").state == resilience.CLOSED
    # Next fused call succeeds and resets the failure count.
    out2 = all_reduce(xp, ctx, impl="pallas")
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(ref))
    assert _counters()["resilience.allreduce.fused_total"] >= 2


def test_breaker_half_open_recovery_via_ops(mesh1, monkeypatch,
                                            registry):
    """closed → open → half-open → closed through real op calls."""
    monkeypatch.setenv("TDT_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("TDT_BREAKER_COOLDOWN_S", "0")
    resilience.reset_for_tests()
    xp = jnp.ones((1, 16, 16), jnp.float32)
    ctx = create_allreduce_context(mesh1, "tp")
    with faults.inject("comm_error", op="allreduce", times=1):
        all_reduce(xp, ctx, impl="pallas")
    assert resilience.get_breaker("allreduce").state == resilience.OPEN
    # Cooldown 0: the next call is the half-open probe; it succeeds
    # (no fault active) and the breaker re-closes.
    all_reduce(xp, ctx, impl="pallas")
    assert resilience.get_breaker("allreduce").state == resilience.CLOSED


def test_real_watchdog_thread_trips_on_hang(mesh1, monkeypatch,
                                            registry):
    monkeypatch.setenv("TDT_COMPILE_TIMEOUT_S", "0.3")
    resilience.reset_for_tests()
    x = jnp.ones((1, 8, 128), jnp.float32)
    ctx = create_p2p_context(mesh1, "tp")
    ref = pp_shift(x, ctx, impl="xla")
    with faults.inject("compile_hang", op="pp_shift", hang_s=5.0):
        out = pp_shift(x, ctx, impl="pallas")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    c = _counters()
    assert c["resilience.watchdog.trips"] == 1
    assert c["resilience.pp_shift.watchdog_trips"] == 1
    assert c["resilience.pp_shift.fallback.watchdog"] == 1
    assert len(resilience.known_bad_cache()) == 1


def test_numeric_guard_catches_nan_payload(mesh1, monkeypatch,
                                           registry):
    monkeypatch.setenv("TDT_NUMERIC_GUARD", "1")
    resilience.reset_for_tests()
    xp = jnp.ones((1, 16, 16), jnp.float32)
    ctx = create_allreduce_context(mesh1, "tp")
    ref = all_reduce(xp, ctx, impl="xla")
    with faults.inject("nan_payload", op="allreduce", times=1):
        out = all_reduce(xp, ctx, impl="pallas")
    assert bool(jnp.isfinite(out).all())
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert _counters()["resilience.allreduce.fallback.nonfinite"] == 1


def test_force_fused_surfaces_infra_errors(mesh1, monkeypatch,
                                           registry):
    """Under TDT_FORCE_FUSED (bench/smoke) an infra failure must
    re-raise — never silently measure the XLA fallback — while still
    being recorded (breaker + counters + known-bad for trips)."""
    monkeypatch.setenv("TDT_FORCE_FUSED", "1")
    resilience.reset_for_tests()
    xp = jnp.ones((1, 16, 16), jnp.float32)
    ctx = create_allreduce_context(mesh1, "tp")
    with faults.inject("comm_error", op="allreduce", times=1):
        with pytest.raises(faults.InjectedFault):
            all_reduce(xp, ctx, impl="pallas")
    c = _counters()
    assert "resilience.allreduce.fallbacks_total" not in c
    with faults.inject("compile_timeout", op="allreduce", times=1):
        with pytest.raises(resilience.CompileTimeout):
            all_reduce(xp, ctx, impl="pallas")
    assert _counters()["resilience.allreduce.watchdog_trips"] == 1
    assert len(resilience.known_bad_cache()) == 1


def test_user_errors_propagate_not_swallowed(mesh1, registry):
    """API misuse must raise, never silently fall back to XLA."""
    from triton_dist_tpu.ops.allgather import (AllGatherMethod,
                                               all_gather,
                                               create_allgather_context)
    ctx = create_allgather_context(mesh1, "tp",
                                   method=AllGatherMethod.BROADCAST)
    x = jnp.ones((8, 128), jnp.float32)
    with pytest.raises(ValueError, match="one-to-all"):
        all_gather(x, ctx, impl="pallas")
    assert "resilience.allgather.fallbacks_total" not in _counters()


# ---------------------------------------------------------------------------
# dist-init retry (satellite: runtime/dist.py).
# ---------------------------------------------------------------------------

def test_dist_init_retries_with_backoff(monkeypatch, registry):
    from triton_dist_tpu.runtime.dist import _initialize_with_retry
    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda coordinator_address, num_processes, process_id:
        calls.append(coordinator_address))
    sleeps = []
    with faults.inject("dist_init", times=2):
        _initialize_with_retry("coord:1234", 2, 0, retries=5,
                               backoff_s=0.5, sleep=sleeps.append)
    assert calls == ["coord:1234"]          # succeeded on attempt 3
    assert sleeps == [0.5, 1.0]             # exponential backoff
    assert _counters()["resilience.dist_init.retries"] == 2


def test_dist_init_retries_exhaust(monkeypatch, registry):
    from triton_dist_tpu.runtime.dist import _initialize_with_retry
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: pytest.fail("must not be reached"))
    with faults.inject("dist_init", times=10):
        with pytest.raises(faults.InjectedFault):
            _initialize_with_retry("coord:1234", 2, 0, retries=2,
                                   backoff_s=0.0,
                                   sleep=lambda s: None)


def test_dist_init_idempotent_reentry(monkeypatch, registry):
    from triton_dist_tpu.runtime.dist import _initialize_with_retry

    def already(coordinator_address, num_processes, process_id):
        raise RuntimeError("jax.distributed is already initialized")

    monkeypatch.setattr(jax.distributed, "initialize", already)
    _initialize_with_retry("coord:1234", 2, 0, retries=0, backoff_s=0.0,
                           sleep=lambda s: None)  # returns quietly
    assert "resilience.dist_init.retries" not in _counters()


# ---------------------------------------------------------------------------
# Serving satellite: structured errors + metrics command.
# ---------------------------------------------------------------------------

def test_server_structured_error_keeps_serving(registry):
    from triton_dist_tpu.serving import ChatClient, ModelServer
    srv = ModelServer(object(), None, port=0).start()
    try:
        c = ChatClient(srv.host, srv.port)
        bad = c.request({"prompt_ids": "nonsense", "gen_len": 1})
        assert "error" in bad and "type" in bad
        # The connection and serve loop survive the failure.
        resp = c.request({"cmd": "metrics"})
        assert "metrics" in resp
        unknown = c.request({"cmd": "nope"})
        assert "error" in unknown
        c.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# The ISSUE 3 acceptance scenario, end to end.
# ---------------------------------------------------------------------------

def test_injected_compile_hang_acceptance(mesh1, monkeypatch, registry):
    from triton_dist_tpu.serving import ChatClient, ModelServer
    monkeypatch.setenv("TDT_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("TDT_BREAKER_COOLDOWN_S", "3600")
    resilience.reset_for_tests()

    a, b = _gemm_rs_operands()
    ctx = create_gemm_rs_context(mesh1, "tp")
    ref = gemm_rs(a, b, ctx, impl="xla")

    # One deterministic "compile hang" in gemm_rs's fused path.
    with faults.inject("compile_timeout", op="gemm_rs", times=1):
        out = gemm_rs(a, b, ctx, impl="pallas")
    # (c) the tripped call already returned the XLA fallback result,
    # bit-identical to the reference path.
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    # (b) the breaker is open and the config is in the known-bad cache.
    assert resilience.get_breaker("gemm_rs").state == resilience.OPEN
    cache = resilience.known_bad_cache()
    assert len(cache) == 1
    (entry,) = cache.entries().values()
    assert entry["op"] == "gemm_rs"
    assert "compile_timeout" in entry["reason"]

    # (c) subsequent calls route to XLA without re-entering the fused
    # path: same config hits the known-bad cache, a different shape
    # hits the open breaker.
    out2 = gemm_rs(a, b, ctx, impl="pallas")
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(ref))
    a32 = jnp.ones((32, 16), jnp.float32)
    ref32 = gemm_rs(a32, b, ctx, impl="xla")
    out32 = gemm_rs(a32, b, ctx, impl="pallas")
    np.testing.assert_array_equal(np.asarray(out32), np.asarray(ref32))
    c = _counters()
    assert c["resilience.gemm_rs.fallback.known_bad"] == 1
    assert c["resilience.gemm_rs.fallback.breaker"] == 1
    assert c["resilience.gemm_rs.fallbacks_total"] == 3

    # (a) other ops are unaffected: their fused paths still run.
    xp = jnp.ones((1, 16, 16), jnp.float32)
    all_reduce(xp, create_allreduce_context(mesh1, "tp"), impl="pallas")
    c = _counters()
    assert c["resilience.allreduce.fused_total"] == 1
    assert "resilience.allreduce.fallbacks_total" not in c

    # (d) everything above is visible through the server's metrics
    # command (same process-local registry the server snapshots).
    srv = ModelServer(object(), None, port=0).start()
    try:
        cl = ChatClient(srv.host, srv.port)
        snap = cl.request({"cmd": "metrics"})["metrics"]
        cl.close()
    finally:
        srv.stop()
    assert snap["counters"]["resilience.gemm_rs.fallbacks_total"] == 3
    assert snap["counters"]["resilience.watchdog.trips"] == 1
    assert snap["gauges"]["resilience.gemm_rs.breaker_state"] == 1
    assert snap["gauges"]["resilience.known_bad.size"] == 1

    # And the report renderer gives the resilience section a home.
    from triton_dist_tpu.tools.report import render_telemetry
    md = render_telemetry(snap)
    assert "#### resilience" in md and "OPEN" in md
