"""Device-time truth layer (obs/devprof.py, ISSUE 10).

Quick tier, CPU only. Covered here:

- the parser on GOLDEN inputs: a checked-in synthetic trace-event
  fixture with exact interval geometry yields the exact measured
  overlap (mirroring tests/test_trace.py's ``--overlap`` tests), and
  the same geometry hand-encoded as an XPlane proto yields the
  identical summary (pinning the protobuf wire decoder);
- a LIVE ``jax.profiler`` capture round-trip on CPU: capture an eager
  ``@resilient``-routed op → parse → nonzero ``device.<op>.*``, and a
  scheduler pump window (``TDT_DEVPROF_EVERY``) → nonzero
  ``device.step.*`` — no TPU required;
- the drift gauge against the dispatch-time model gauge;
- the breach-armed postmortem: an injected SLO breach through a live
  server leaves BOTH the host Perfetto flight dump and a parsed
  device-profile summary;
- ``group_profile``'s structured result + obs counters and the
  ``trace_files`` glob (tools/profiler.py satellite);
- the ``profile_export`` CLI (validate rc contract, summary, chrome
  conversion) and ``trace_export --merge-profile`` overlay;
- the ``annotation-coverage`` tdt-check pass incl. the strip-a-span
  mutation (``devprof.unlabeled``);
- ``bench_ops`` measured-overlap wellformedness + floor gates.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from triton_dist_tpu import obs
from triton_dist_tpu.obs import devprof, flight, trace
from triton_dist_tpu.tools.profiler import (annotate, group_profile,
                                            trace_files)

GOLDEN = os.path.join(os.path.dirname(__file__), "data",
                      "devprof_golden.trace.json")


# ---------------------------------------------------------------------------
# Golden geometry: exact measured overlap from a checked-in fixture.
# ---------------------------------------------------------------------------

def test_golden_fixture_exact_overlap():
    s = devprof.summarize(devprof.load_capture(GOLDEN))
    m = s["ops"]["ag_gemm"]
    assert m["total_ms"] == 1.0
    assert m["compute_ms"] == 0.6
    assert m["comm_ms"] == 0.8
    assert m["exposed_comm_ms"] == 0.4
    assert m["overlap_pct"] == 50.0            # 100·(1 − 400/800)
    assert s["unlabeled_ms"] == 0.5            # fusion.2 outside window
    # The host-side python event is not execution and counts nowhere.
    assert s["n_events"] == 3


def _enc_varint(x: int) -> bytes:
    out = b""
    while True:
        b7 = x & 0x7F
        x >>= 7
        if x:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def _field(fn: int, payload) -> bytes:
    if isinstance(payload, int):
        return _enc_varint(fn << 3 | 0) + _enc_varint(payload)
    if isinstance(payload, str):
        payload = payload.encode()
    return _enc_varint(fn << 3 | 2) + _enc_varint(len(payload)) + payload


def _xevent(mid, off_ps, dur_ps):
    return _field(1, mid) + _field(2, off_ps) + _field(3, dur_ps)


def test_xplane_wire_decoder_matches_golden_geometry():
    """The same interval geometry hand-encoded as an XSpace proto
    (XSpace→XPlane→XLine→XEvent with event_metadata names) parses to
    the identical summary — the wire decoder is pinned to the schema,
    not to whatever this jax build happens to emit."""
    def meta_entry(mid, name):
        return _field(4, _field(1, mid) + _field(2, _field(2, name)))
    host_plane = (_field(2, "/host:CPU")
                  + meta_entry(1, "device.ag_gemm.fused")
                  + _field(3, _field(3, 0)          # line ts_ns = 0
                           + _field(4, _xevent(1, 1_000_000_000,
                                               1_000_000_000))))
    dev_plane = (_field(2, "/device:TPU:0")
                 + meta_entry(1, "fusion.1")
                 + meta_entry(2, "all-gather-start.7")
                 + meta_entry(3, "fusion.2")
                 + _field(3, _field(3, 0)
                          + _field(4, _xevent(1, 1_000_000_000,
                                              600_000_000))
                          + _field(4, _xevent(3, 3_000_000_000,
                                              500_000_000)))
                 + _field(3, _field(3, 0)
                          + _field(4, _xevent(2, 1_200_000_000,
                                              800_000_000))))
    space = _field(1, host_plane) + _field(1, dev_plane)
    s = devprof.summarize(devprof.parse_xplane(space))
    assert s["ops"]["ag_gemm"] == devprof.summarize(
        devprof.load_capture(GOLDEN))["ops"]["ag_gemm"]
    assert s["unlabeled_ms"] == 0.5


def test_host_exec_spans_do_not_mask_device_comm():
    """Review regression: on a capture WITH a device plane, host-side
    Execute spans bracket dispatch, not device work — one covering a
    device comm interval must not count as compute and inflate the
    measured overlap (the exact fiction this tier exists to retire).
    Without a device plane (CPU backend) they remain the execution
    stand-in."""
    comm = {"name": "all-gather-start.1", "ts_us": 0.0, "dur_us": 1000.0,
            "pid": 2, "tid": 1, "device": True}
    host_exec = {"name": "TfrtCpuExecutable::Execute", "ts_us": 0.0,
                 "dur_us": 1000.0, "pid": 1, "tid": 1, "device": False}
    label = {"name": "device.ag_gemm.fused", "ts_us": 0.0,
             "dur_us": 1000.0, "pid": 1, "tid": 1, "device": False}
    m = devprof.summarize([label, comm, host_exec])["ops"]["ag_gemm"]
    assert m["compute_ms"] == 0.0          # host span ignored
    assert m["overlap_pct"] == 0.0         # comm fully exposed
    # CPU-shaped capture (no device plane): the host span IS the work.
    host_only = dict(host_exec)
    m2 = devprof.summarize([label, host_only])["ops"]["ag_gemm"]
    assert m2["compute_ms"] == 1.0


def test_unparseable_inputs_raise():
    with pytest.raises(ValueError):
        devprof.parse_xplane(b"")
    with pytest.raises(ValueError):
        devprof.load_capture("/nonexistent/path")


# ---------------------------------------------------------------------------
# Live CPU capture round-trip (eager op → device.<op>.* gauges).
# ---------------------------------------------------------------------------

def _capture_eager_op(tmp_path, mesh8):
    """One eager ag_gemm (@resilient-routed, so the router plants the
    device.ag_gemm.fused annotation) under a live jax.profiler
    capture. world=1: the multi-device interpret ring cannot trace
    ``get_barrier_semaphore`` on this jax (the pre-existing 0.4.37
    gap, see tests/test_ring_bidir.py) — the label/attribution path
    under test is identical."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.allgather_gemm import (ag_gemm,
                                                    create_ag_gemm_context)
    mesh = Mesh(np.array([d for d in mesh8.devices.flat][:1]), ("tp",))
    ctx = create_ag_gemm_context(mesh, "tp")
    a = jax.device_put(jnp.ones((64, 128), jnp.bfloat16),
                       NamedSharding(mesh, P("tp")))
    b = jax.device_put(jnp.ones((128, 128), jnp.bfloat16),
                       NamedSharding(mesh, P(None, "tp")))
    with group_profile("live_op", str(tmp_path)) as cap:
        jax.block_until_ready(ag_gemm(a, b, ctx, impl="pallas"))
    return cap


def test_live_capture_roundtrip_eager_op(tmp_path, mesh8):
    reg = obs.enable(obs.Registry())
    try:
        cap = _capture_eager_op(tmp_path / "prof", mesh8)
        assert cap.path == str(cap) and cap.name == "live_op"
        summary = devprof.parse_capture(cap)
        m = summary["ops"].get("ag_gemm")
        assert m is not None, summary["ops"]
        assert m["total_ms"] > 0
        assert m["compute_ms"] > 0      # TfrtCpuExecutable::Execute
        # world=1 on CPU: no real comm events → the honest marker
        # contract (overlap None), not a fictional 100%.
        assert m["overlap_pct"] is None or 0 <= m["overlap_pct"] <= 100
        devprof.publish(summary)
        g = reg.snapshot()["gauges"]
        assert g["device.ag_gemm.total_ms"] > 0
        assert g["device.ag_gemm.compute_ms"] > 0
        c = reg.snapshot()["counters"]
        assert c["profile.captures"] == 1
        assert c["profile.parsed"] == 1
    finally:
        obs.disable()


def test_live_capture_xplane_artifact_also_parses(tmp_path, mesh8):
    """The pb artifact of a REAL capture goes through the wire decoder
    (not just the JSON path) and attributes the same op."""
    import glob as _glob
    cap = _capture_eager_op(tmp_path / "prof", mesh8)
    pbs = _glob.glob(os.path.join(cap.path, "plugins/profile/*",
                                  "*.xplane.pb"))
    assert pbs, "jax wrote no xplane.pb artifact"
    with open(pbs[0], "rb") as f:
        events = devprof.parse_xplane(f.read())
    s = devprof.summarize(events)
    assert "ag_gemm" in s["ops"] and s["ops"]["ag_gemm"]["total_ms"] > 0


def test_group_profile_meta_and_trace_files(tmp_path):
    reg = obs.enable(obs.Registry())
    try:
        with group_profile("t2", str(tmp_path)) as cap:
            jnp.dot(jnp.ones((32, 32)),
                    jnp.ones((32, 32))).block_until_ready()
        meta = devprof.capture_meta(cap.path)
        assert meta["name"] == "t2" and meta["host"] == 0
        assert meta["t0_unix"] > 0
        files = trace_files("t2", str(tmp_path))
        assert files == sorted(files) and files
        # The glob walks the nested plugins/profile/<run>/ tree.
        assert any("plugins" in f for f in files)
        assert any(f.endswith("tdt_capture.json") for f in files)
        h = reg.snapshot()["histograms"]["profile.capture_ms"]
        assert h["count"] == 1 and h["sum"] > 0
    finally:
        obs.disable()


def test_group_profile_disabled_yields_none():
    with group_profile("off", "/nonexistent", enabled=False) as cap:
        assert cap is None


def test_drift_gauge_measured_minus_modeled():
    reg = obs.enable(obs.Registry())
    try:
        reg.gauge("comms.ag_gemm.overlap_pct").set(90.0)   # the model
        devprof.publish(devprof.summarize(devprof.load_capture(GOLDEN)))
        g = reg.snapshot()["gauges"]
        assert g["comms.ag_gemm.overlap_pct_measured"] == 50.0
        assert g["comms.ag_gemm.exposed_comm_ms_measured"] == 0.4
        assert g["comms.ag_gemm.overlap_drift_pct"] == -40.0
        assert g["device.unlabeled_ms"] == 0.5
    finally:
        obs.disable()


# ---------------------------------------------------------------------------
# Serving: continuous sampler + breach-armed postmortem.
# ---------------------------------------------------------------------------

def _tiny_engine(mesh8, key):
    from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = model.init(key)
    return Engine(model, batch=2, max_seq=64, prefill_mode="xla_ar",
                  decode_mode="gemm_ar"), params


def test_pump_sampler_feeds_device_step_gauges(mesh8, key):
    """TDT_DEVPROF_EVERY acceptance: a jax.profiler capture of a
    scheduler pump window parses into nonzero device.step.* gauges —
    on CPU, no TPU required."""
    from triton_dist_tpu.serving import Scheduler
    engine, params = _tiny_engine(mesh8, key)
    reg = obs.enable(obs.Registry())
    try:
        sampler = devprof.PumpSampler(every=3, sync=True)
        sched = Scheduler(engine, params,
                          devprof_sampler=sampler).start()
        try:
            toks = sched.generate([1, 2, 3], 8)
            assert len(toks) >= 1
        finally:
            sched.stop()
        last = devprof.last_profile()
        assert last is not None and last["reason"] == "sampler"
        step = last["summary"]["ops"].get("step")
        assert step is not None, last["summary"]
        assert step["total_ms"] > 0
        # Nested inside the whole-iteration window, the scheduler
        # brackets the shared decode step alone with the per-path
        # label — decode-only device time, no admission contamination
        # (the split Engine(decode_path="auto") arbitrates on).
        sub = last["summary"]["ops"].get("step.plain")
        assert sub is not None, last["summary"]
        assert 0 < sub["total_ms"] <= step["total_ms"]
        g = reg.snapshot()["gauges"]
        assert g["device.step.total_ms"] > 0
        assert g["device.step.plain.total_ms"] > 0
        assert g["device.step.plain.windows"] >= 1
        assert g.get("device.step.compute_ms", 0) >= 0
        assert reg.snapshot()["counters"]["profile.parsed"] >= 1
    finally:
        obs.disable()


def test_pump_sampler_attributes_mega_iterations_separately(mesh8, key):
    """ISSUE 11 satellite: a mega-engine scheduler's profiled pump
    iterations land in device.step.MEGA gauges, not blended into the
    plain window — the auto policy's measured inputs."""
    from triton_dist_tpu.serving import Scheduler
    from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh8, axis="tp", impl="xla")
    params = model.init(key)
    engine = Engine(model, batch=2, max_seq=64, prefill_mode="xla_ar",
                    decode_mode="gemm_ar", use_mega=True)
    reg = obs.enable(obs.Registry())
    try:
        sampler = devprof.PumpSampler(every=3, sync=True)
        sched = Scheduler(engine, params,
                          devprof_sampler=sampler).start()
        try:
            toks = sched.generate([1, 2, 3], 8)
            assert len(toks) >= 1
        finally:
            sched.stop()
        last = devprof.last_profile()
        assert last is not None
        ops = last["summary"]["ops"]
        assert "step.mega" in ops and ops["step.mega"]["total_ms"] > 0
        assert "step.plain" not in ops, ops
        # ... and the decode-only sub-window stays inside the
        # whole-iteration window.
        assert ops["step.mega"]["total_ms"] <= ops["step"]["total_ms"]
        g = reg.snapshot()["gauges"]
        assert g["device.step.mega.total_ms"] > 0
    finally:
        obs.disable()


def test_pump_sampler_off_by_default(mesh8, key):
    from triton_dist_tpu.serving import Scheduler
    engine, params = _tiny_engine(mesh8, key)
    sched = Scheduler(engine, params)
    assert sched.devprof is None       # both knobs unset (conftest)
    assert devprof.PumpSampler.from_env() is None


def test_breach_postmortem_has_dump_and_device_profile(mesh8, key,
                                                       monkeypatch):
    """Acceptance: an injected SLO breach produces a postmortem
    containing BOTH the host Perfetto flight dump AND a parsed
    device-profile summary (TDT_DEVPROF_ON_BREACH)."""
    from triton_dist_tpu.serving import Scheduler
    from triton_dist_tpu.obs import slo
    monkeypatch.setenv("TDT_SLO_MIN_SAMPLES", "1")
    engine, params = _tiny_engine(mesh8, key)
    reg = obs.enable(obs.Registry())
    trace.enable()
    try:
        trace.instant("serving.fake_event", "serving")
        sampler = devprof.PumpSampler(on_breach=2, sync=True)
        target = slo.SLOTarget("ttft", 0.99, 0.001)  # impossible: all violate
        sched = Scheduler(engine, params, slo_tracker=[target],
                          devprof_sampler=sampler).start()
        try:
            sched.generate([1, 2, 3], 4)
            # Force the burn evaluation now (the pump's own calls are
            # rate-limited): the breach transition dumps the flight
            # record AND arms the devprof capture.
            r = sched.slo.evaluate(force=True)
            assert r["burn"]["ttft_p99"]["breached"], r
            rec = flight.last_record()
            assert rec is not None and rec["reason"] == "slo_ttft_p99"
            # The next pump iterations run under the armed capture.
            sched.generate([4, 5, 6], 4)
        finally:
            sched.stop()
        last = devprof.last_profile()
        assert last is not None, "no device profile parsed post-breach"
        assert last["reason"] == "breach_slo_ttft_p99"
        assert last["summary"]["ops"]["step"]["total_ms"] > 0
        # BOTH artifacts: the Perfetto dump validates, the profile
        # summary rides the metrics payload's devprof key.
        with open(rec["path"]) as f:
            chrome = json.load(f)
        from triton_dist_tpu.tools import trace_export
        errors, _ = trace_export.validate(chrome)
        assert errors == [], errors
        st = devprof.stats()
        assert st["last_profile"] == last["path"]
        assert "step" in st["ops"]
    finally:
        trace.reset()
        obs.disable()


def test_arm_is_rate_limited():
    # Arming is consumer-gated: without a breach-configured sampler
    # alive, arm() is a no-op (a sampler-less process must not
    # advertise an armed capture forever).
    devprof.arm("ignored")
    assert devprof.armed_reason() is None
    sampler = devprof.PumpSampler(on_breach=1, sync=True)  # consumer
    devprof.arm("one")
    assert devprof._consume_arm() == "one"
    devprof.arm("two")                 # inside ARM_MIN_INTERVAL_S
    assert devprof._consume_arm() is None
    assert devprof.armed_reason() is None      # dropped, not queued
    del sampler


# ---------------------------------------------------------------------------
# profile_export CLI + trace_export --merge-profile.
# ---------------------------------------------------------------------------

def test_profile_export_validate_rc_contract(tmp_path, mesh8):
    from triton_dist_tpu.tools import profile_export
    cap = _capture_eager_op(tmp_path / "prof", mesh8)
    # Valid capture → rc 0 (dir form, like hw_watch points it at
    # TDT_DEVPROF_DIR).
    assert profile_export.main([str(tmp_path / "prof"),
                                "--validate"]) == 0
    # Unparseable capture → rc != 0.
    bad = tmp_path / "bad" / "plugins" / "profile" / "run1"
    bad.mkdir(parents=True)
    (bad / "host.trace.json").write_text("{not json")
    assert profile_export.main([str(tmp_path / "bad"),
                                "--validate"]) == 1
    # Empty dir: warning by default, failure under --require.
    empty = tmp_path / "empty"
    empty.mkdir()
    assert profile_export.main([str(empty), "--validate"]) == 0
    assert profile_export.main([str(empty), "--validate",
                                "--require"]) == 1
    # --summary emits machine-readable attribution.
    s, err = profile_export.validate_capture(str(cap))
    assert err is None and "ag_gemm" in s["ops"]
    # --chrome conversion is wall-clock anchored.
    out = tmp_path / "dev.json"
    assert profile_export.main([str(cap), "--chrome", str(out)]) == 0
    dev = json.loads(out.read_text())
    anchor_us = devprof.capture_meta(cap)["t0_unix"] * 1e6
    xs = [e["ts"] for e in dev["traceEvents"] if e.get("ph") == "X"]
    assert xs and all(t >= anchor_us for t in xs)


def test_merge_profile_overlays_on_one_clock(tmp_path, mesh8):
    from triton_dist_tpu.tools import profile_export, trace_export
    trace.enable()
    try:
        with trace.span("engine.decode_step", "engine"):
            pass
        host = trace_export.to_chrome(trace.collect())
    finally:
        trace.reset()
    cap = _capture_eager_op(tmp_path / "prof", mesh8)
    merged = trace_export.merge_profile(host, str(cap))
    pids = {e.get("pid") for e in merged["traceEvents"]}
    assert any(p is not None and p >= profile_export.DEVICE_PID_BASE
               for p in pids)
    names = {e.get("name") for e in merged["traceEvents"]}
    assert "device.ag_gemm.fused" in names       # the overlay rows
    assert "engine.decode_step" in names         # host events intact
    errors, _ = trace_export.validate(merged)
    assert errors == [], errors
    assert merged["metadata"]["merged_profiles"] == 1
    # Device timestamps sit on the tracer's wall-anchored clock: the
    # label window must land within the capture's wall-time span.
    lbl = [e for e in merged["traceEvents"]
           if e.get("name") == "device.ag_gemm.fused"
           and e.get("ph") == "X"]
    t0 = devprof.capture_meta(cap)["t0_unix"] * 1e6
    assert all(t0 <= e["ts"] <= t0 + 600e6 for e in lbl)


def test_merge_profile_cli(tmp_path, mesh8):
    from triton_dist_tpu.tools import trace_export
    trace.enable()
    try:
        trace.instant("serving.ping", "serving")
        host_path = tmp_path / "host.trace.json"
        trace_export.write_trace(
            trace_export.to_chrome(trace.collect()), str(host_path))
    finally:
        trace.reset()
    cap = _capture_eager_op(tmp_path / "prof", mesh8)
    out = tmp_path / "overlaid.json"
    rc = trace_export.main([str(host_path), "--merge-profile",
                            str(cap), "--out", str(out)])
    assert rc == 0
    merged = json.loads(out.read_text())
    assert any(str(e.get("name", "")).startswith("device.")
               for e in merged["traceEvents"])


# ---------------------------------------------------------------------------
# annotation-coverage pass (+ the strip-a-span mutation).
# ---------------------------------------------------------------------------

def test_annotation_coverage_repo_clean():
    from triton_dist_tpu.analysis import run_passes
    findings = run_passes(names=["annotation-coverage"])
    assert findings == [], [f.render() for f in findings]


def test_mutant_stripped_annotation_is_unlabeled(tmp_path):
    """Mutation test: strip the router's per-invocation annotation →
    the pass reports devprof.unlabeled with a file anchor."""
    from triton_dist_tpu.analysis import lint_annotations
    from triton_dist_tpu.resilience import router
    src = open(router.__file__.rstrip("c")).read()
    mut = src.replace("_op_annotation(op, impl, fallback_impl)",
                      "contextlib.nullcontext()")
    assert mut != src, "mutation site moved — update this test"
    p = tmp_path / "router.py"
    p.write_text(mut)
    findings = lint_annotations.check_router(p)
    assert [f.code for f in findings] == ["devprof.unlabeled"]
    assert findings[0].file == str(p) and findings[0].line
    # The clean source passes.
    p2 = tmp_path / "router_ok.py"
    p2.write_text(src)
    assert lint_annotations.check_router(p2) == []


def test_mutant_helper_without_device_prefix_is_unlabeled(tmp_path):
    """Renaming the label out of the device.* namespace is the same
    silent-misattribution bug as stripping the with — caught too."""
    from triton_dist_tpu.analysis import lint_annotations
    from triton_dist_tpu.resilience import router
    src = open(router.__file__.rstrip("c")).read()
    mut = src.replace('f"device.{op}.{branch}"', 'f"op.{op}.{branch}"')
    assert mut != src
    p = tmp_path / "router.py"
    p.write_text(mut)
    assert [f.code for f in lint_annotations.check_router(p)] \
        == ["devprof.unlabeled"]


def test_mutant_sampler_without_step_label(tmp_path):
    from triton_dist_tpu.analysis import lint_annotations
    dev_src = open(devprof.__file__.rstrip("c")).read()
    mut = dev_src.replace('STEP_LABEL = "device.step"',
                          'STEP_LABEL = "step"')
    assert mut != dev_src
    p = tmp_path / "devprof.py"
    p.write_text(mut)
    import triton_dist_tpu.serving.scheduler as sched_mod
    findings = lint_annotations.check_sampler(p, sched_mod.__file__)
    # The de-namespaced label ALSO breaks the per-path attribution
    # (step_label("mega") no longer yields device.step.mega), so both
    # finding classes fire.
    codes = [f.code for f in findings]
    assert "devprof.step_unlabeled" in codes, codes


def test_summarize_keeps_step_paths_separate():
    """The parser attributes device.step.mega / device.step.plain
    windows to SEPARATE ops (router device.<op>.<branch> labels still
    blend branches into one op) — the split the auto decode-path
    policy reads."""
    events = [
        {"name": "device.step.mega", "ts_us": 0.0, "dur_us": 100.0,
         "pid": 1, "tid": 1, "device": False},
        {"name": "fusion.a", "ts_us": 10.0, "dur_us": 40.0,
         "pid": 2, "tid": 1, "device": True},
        {"name": "device.step.plain", "ts_us": 200.0, "dur_us": 100.0,
         "pid": 1, "tid": 1, "device": False},
        {"name": "fusion.b", "ts_us": 210.0, "dur_us": 80.0,
         "pid": 2, "tid": 1, "device": True},
        {"name": "device.ag_gemm.fused", "ts_us": 400.0,
         "dur_us": 50.0, "pid": 1, "tid": 1, "device": False},
        {"name": "device.ag_gemm.xla", "ts_us": 500.0, "dur_us": 50.0,
         "pid": 1, "tid": 1, "device": False},
    ]
    ops = devprof.summarize(events)["ops"]
    assert set(ops) == {"step.mega", "step.plain", "ag_gemm"}
    assert ops["step.mega"]["compute_ms"] == pytest.approx(0.04)
    assert ops["step.plain"]["compute_ms"] == pytest.approx(0.08)
    assert devprof.step_label() == "device.step"
    assert devprof.step_label("mega") == "device.step.mega"


def test_mutant_step_label_blends(tmp_path):
    """Mutation test (ISSUE 11): collapse step_label(kind) back to the
    bare STEP_LABEL → the annotation-coverage pass reports
    devprof.step_path_blended (the auto policy would arbitrate on a
    blended device.step gauge)."""
    from triton_dist_tpu.analysis import lint_annotations
    dev_src = open(devprof.__file__.rstrip("c")).read()
    mut = dev_src.replace(
        'return f"{STEP_LABEL}.{kind}" if kind else STEP_LABEL',
        'return STEP_LABEL')
    assert mut != dev_src, "mutation site moved — update this test"
    p = tmp_path / "devprof.py"
    p.write_text(mut)
    import triton_dist_tpu.serving.scheduler as sched_mod
    findings = lint_annotations.check_sampler(p, sched_mod.__file__)
    assert [f.code for f in findings] == ["devprof.step_path_blended"]


def test_mutant_summarize_blends_step_paths(tmp_path):
    """Mutation test: a parser that regexes clean but BLENDS the step
    windows (two-segment rule stripped from _label_op) is caught by
    the behavioral check, not just pattern matching."""
    from triton_dist_tpu.analysis import lint_annotations
    dev_src = open(devprof.__file__.rstrip("c")).read()
    mut = dev_src.replace(
        'if parts[0] == "step" and len(parts) > 1 and parts[1]:',
        'if False:')
    assert mut != dev_src, "mutation site moved — update this test"
    p = tmp_path / "devprof.py"
    p.write_text(mut)
    import triton_dist_tpu.serving.scheduler as sched_mod
    findings = lint_annotations.check_sampler(p, sched_mod.__file__)
    assert [f.code for f in findings] == ["devprof.step_path_blended"]


def test_mutant_scheduler_without_kind(tmp_path):
    """Mutation test: a scheduler that stops bracketing the shared
    decode step with the per-path step_label annotation blends mega
    and plain decode time into the whole-iteration window."""
    from triton_dist_tpu.analysis import lint_annotations
    import triton_dist_tpu.serving.scheduler as sched_mod
    sched_src = open(sched_mod.__file__.rstrip("c")).read()
    mut = sched_src.replace("annotate(devprof.step_label(kind))",
                            "contextlib.nullcontext()")
    assert mut != sched_src, "mutation site moved — update this test"
    p = tmp_path / "scheduler.py"
    p.write_text(mut)
    findings = lint_annotations.check_sampler(
        devprof.__file__.rstrip("c"), p)
    assert [f.code for f in findings] == ["devprof.step_path_blended"]


# ---------------------------------------------------------------------------
# bench_ops: measured-overlap wellformedness + floors.
# ---------------------------------------------------------------------------

def test_overlap_wellformed_gate():
    from triton_dist_tpu.tools.bench_ops import (
        check_overlap_measured_wellformed)
    # Part didn't run → nothing demanded.
    assert check_overlap_measured_wellformed({}) == []
    # Ran + measured number → pass; malformed value → fail.
    ok = {"ag_gemm_pallas_ms": 1.0, "ag_gemm_overlap_pct_measured": 42.5}
    assert check_overlap_measured_wellformed(ok) == []
    bad = {"ag_gemm_pallas_ms": 1.0,
           "ag_gemm_overlap_pct_measured": 142.5}
    assert check_overlap_measured_wellformed(bad)
    # Ran + explicit marker → pass; ran + nothing → fail.
    marker = {"gemm_rs_pallas_ms": 1.0,
              "gemm_rs_overlap_requires_chip": True}
    assert check_overlap_measured_wellformed(marker) == []
    naked = {"gemm_ar_pallas_ms": 1.0}
    fails = check_overlap_measured_wellformed(naked)
    assert fails and "gemm_ar" in fails[0]


def test_measured_overlap_floor_gate(tmp_path):
    from triton_dist_tpu.tools.bench_ops import (
        check_measured_overlap_floors, load_measured_overlap_floors)
    baseline = tmp_path / "BASELINE.json"
    baseline.write_text(json.dumps({
        "regression_floors": {"tpu": {}, "cpu": {}},
        "measured_overlap_floors": {
            "tpu": {"ag_gemm_overlap_pct_measured": 5.0,
                    "_comment": "x"}, "cpu": {}}}))
    floors = load_measured_overlap_floors(str(baseline), "tpu")
    assert floors == {"ag_gemm_overlap_pct_measured": 5.0}
    assert check_measured_overlap_floors(
        {"ag_gemm_overlap_pct_measured": 12.0}, floors) == []
    assert check_measured_overlap_floors(
        {"ag_gemm_overlap_pct_measured": 2.0}, floors)
    # A marker-run (no measured key) passes the floor gate — the
    # wellformedness gate owns that contract.
    assert check_measured_overlap_floors(
        {"ag_gemm_overlap_requires_chip": True}, floors) == []
    # The shipped BASELINE.json carries the tpu-tier hook.
    from triton_dist_tpu.tools.bench_ops import _default_baseline_path
    shipped = load_measured_overlap_floors(_default_baseline_path(),
                                           "tpu")
    assert "ag_gemm_overlap_pct_measured" in shipped


def test_regress_from_file_gates_overlap(tmp_path):
    """End-to-end through run_regress: a checkpoint whose fused part
    ran without measured-overlap evidence fails the gate."""
    from triton_dist_tpu.tools import bench_ops
    extras = {"ag_gemm_vs_xla": 1.0, "gemm_rs_vs_xla": 1.0,
              "flash_decode_vs_xla": 1.0,
              "serving_sched_vs_serial": 5.0,
              "serving_prefix_ttft_vs_cold": 5.0,
              "serving_mega_vs_plain": 1.0,
              "serving_spec_vs_plain": 1.62,
              "serving_fleet_vs_single": 0.84,
              "serving_router_vs_direct": 0.9,
              "serving_history_on_vs_off": 0.97,
              "serving_disagg_vs_unified": 0.31,
              "ag_gemm_pallas_ms": 1.0, "baseline_anomaly": None}
    path = tmp_path / "ck.json"
    path.write_text(json.dumps({"extras": extras}))
    rc = bench_ops.run_regress(bench_ops._default_baseline_path(),
                               str(path), "cpu")
    assert rc == 1
    extras["ag_gemm_overlap_requires_chip"] = True
    path.write_text(json.dumps({"extras": extras}))
    rc = bench_ops.run_regress(bench_ops._default_baseline_path(),
                               str(path), "cpu")
    assert rc == 0


# ---------------------------------------------------------------------------
# CLI module entry (subprocess, no jax import needed in profile_export).
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_profile_export_module_entry(tmp_path):
    empty = tmp_path / "none"
    empty.mkdir()
    r = subprocess.run(
        [sys.executable, "-m", "triton_dist_tpu.tools.profile_export",
         str(empty), "--validate"],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0 and "no profile captures" in r.stdout
