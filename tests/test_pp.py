"""PP p2p tests (reference test/nvidia/test_pp.py: push/pull copy between
pp ranks + signal correctness, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.ops.p2p import create_p2p_context, pp_shift
from triton_dist_tpu.layers.p2p import CommOp, pipeline_forward


@pytest.mark.parametrize("impl", ["xla", "pallas"])
@pytest.mark.parametrize("delta", [1, -1])
def test_pp_shift(mesh8, impl, delta, key):
    world, rows, f = 8, 8, 128
    x = jax.random.normal(key, (world * rows, f), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh8, P("tp")))
    ctx = create_p2p_context(mesh8, "tp")
    out = pp_shift(xs, ctx, delta=delta, impl=impl)
    ref = np.roll(np.asarray(x).reshape(world, rows, f), delta, axis=0)
    np.testing.assert_array_equal(np.asarray(out).reshape(world, rows, f),
                                  ref)


def test_comm_op_ring(mesh8, key):
    world, rows, f = 8, 4, 128
    x = jax.random.normal(key, (world * rows, f), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh8, P("tp")))
    op = CommOp(num_buffers=2, mesh=mesh8, axis="tp", impl="xla")
    op.send(xs)
    got = op.recv()
    ref = np.roll(np.asarray(x).reshape(world, rows, f), 1, axis=0)
    np.testing.assert_array_equal(np.asarray(got).reshape(world, rows, f),
                                  ref)


def test_pipeline_forward(mesh8, key):
    """Stage i adds (i+1); a block passing all 8 stages gains 36."""
    world, rows, f = 8, 2, 8
    x = jnp.zeros((world * rows, f), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh8, P("tp")))

    def stage_fn(stage_idx, h):
        return h + (stage_idx + 1).astype(h.dtype)

    out = pipeline_forward(stage_fn, xs, mesh=mesh8, axis="tp")
    blocks = np.asarray(out).reshape(world, rows, f)
    # stage-0 block visited stages 0..7 in order: sum(1..8) = 36
    np.testing.assert_array_equal(blocks[0], np.full((rows, f), 36.0))
