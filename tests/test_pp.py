"""PP p2p tests (reference test/nvidia/test_pp.py: push/pull copy between
pp ranks + signal correctness, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.ops.p2p import create_p2p_context, pp_shift

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow
from triton_dist_tpu.layers.p2p import (CommOp, pipeline_forward,
                                        pipeline_schedule)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
@pytest.mark.parametrize("delta", [1, -1])
def test_pp_shift(mesh8, impl, delta, key):
    world, rows, f = 8, 8, 128
    x = jax.random.normal(key, (world * rows, f), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh8, P("tp")))
    ctx = create_p2p_context(mesh8, "tp")
    out = pp_shift(xs, ctx, delta=delta, impl=impl)
    ref = np.roll(np.asarray(x).reshape(world, rows, f), delta, axis=0)
    np.testing.assert_array_equal(np.asarray(out).reshape(world, rows, f),
                                  ref)


def test_comm_op_ring(mesh8, key):
    world, rows, f = 8, 4, 128
    x = jax.random.normal(key, (world * rows, f), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh8, P("tp")))
    op = CommOp(num_buffers=2, mesh=mesh8, axis="tp", impl="xla")
    op.send(xs)
    got = op.recv()
    ref = np.roll(np.asarray(x).reshape(world, rows, f), 1, axis=0)
    np.testing.assert_array_equal(np.asarray(got).reshape(world, rows, f),
                                  ref)


def test_pipeline_forward(mesh8, key):
    """Stage i adds (i+1); a block passing all 8 stages gains 36."""
    world, rows, f = 8, 2, 8
    x = jnp.zeros((world * rows, f), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh8, P("tp")))

    def stage_fn(stage_idx, h):
        return h + (stage_idx + 1).astype(h.dtype)

    out = pipeline_forward(stage_fn, xs, mesh=mesh8, axis="tp")
    blocks = np.asarray(out).reshape(world, rows, f)
    # stage-0 block visited stages 0..7 in order: sum(1..8) = 36
    np.testing.assert_array_equal(blocks[0], np.full((rows, f), 36.0))


@pytest.mark.parametrize("m", [1, 4, 11])
def test_pipeline_schedule(mesh8, key, m):
    """GPipe microbatch schedule == sequentially applying all stages to
    each microbatch."""
    world, rows, f = 8, 4, 16
    kp, kb, kx = jax.random.split(key, 3)
    ws = jax.random.normal(kp, (world, f, f), jnp.float32) / np.sqrt(f)
    bs = jax.random.normal(kb, (world, f), jnp.float32) * 0.1
    mb = jax.random.normal(kx, (m, rows, f), jnp.float32)

    params = {
        "w": jax.device_put(ws, NamedSharding(mesh8, P("tp"))),
        "b": jax.device_put(bs, NamedSharding(mesh8, P("tp"))),
    }

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    out = pipeline_schedule(stage_fn, params, mb, mesh=mesh8, axis="tp")

    ref = np.asarray(mb, np.float64)
    wsn, bsn = np.asarray(ws, np.float64), np.asarray(bs, np.float64)
    for s in range(world):
        ref = np.tanh(ref @ wsn[s] + bsn[s])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_pipeline_schedule_jit(mesh8, key):
    """The whole schedule must trace under jit (static shapes, masked
    fill/drain — no data-dependent Python control flow)."""
    world, rows, f, m = 8, 2, 8, 3
    params = {"w": jax.device_put(
        jax.random.normal(key, (world, f, f), jnp.float32) / np.sqrt(f),
        NamedSharding(mesh8, P("tp")))}
    mb = jax.random.normal(jax.random.fold_in(key, 1), (m, rows, f),
                           jnp.float32)

    def stage_fn(p, h):
        return h @ p["w"]

    g = jax.jit(lambda p, x: pipeline_schedule(stage_fn, p, x,
                                               mesh=mesh8, axis="tp"))
    out = g(params, mb)
    ref = np.asarray(mb, np.float64)
    for s in range(world):
        ref = ref @ np.asarray(params["w"], np.float64)[s]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-4)


def test_pipeline_schedule_grads(mesh8, key):
    """Pipeline-parallel TRAINING: the GPipe schedule differentiates
    (scan + ppermute carry native transpose rules) with stage-weight
    grads equal to running the stages sequentially — microbatching and
    the masked fill/drain must be invisible to the gradients."""
    world, rows, f, m = 8, 4, 16, 4
    params = {"w": jax.device_put(
        jax.random.normal(key, (world, f, f), jnp.float32) / np.sqrt(f),
        NamedSharding(mesh8, P("tp")))}
    mb = jax.random.normal(jax.random.fold_in(key, 2), (m, rows, f),
                           jnp.float32)

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"])

    def loss_pp(p, x):
        return jnp.sum(pipeline_schedule(stage_fn, p, x, mesh=mesh8,
                                         axis="tp") ** 2)

    def loss_seq(p, x):
        h = x
        for s in range(world):
            h = jnp.tanh(h @ p["w"][s])
        return jnp.sum(h ** 2)

    g_pp = jax.jit(jax.grad(loss_pp))(params, mb)
    g_seq = jax.jit(jax.grad(loss_seq))(params, mb)
    assert bool(jnp.isfinite(g_pp["w"]).all())
    np.testing.assert_allclose(np.asarray(g_pp["w"]),
                               np.asarray(g_seq["w"]),
                               rtol=2e-4, atol=1e-5)
