"""Device-primitive tests (reference analogs: test_distributed_wait.py,
test_nvshmem_api.py, test_common_ops.py — here runnable single-process via
Pallas TPU interpret mode on the 8-device CPU mesh)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import triton_dist_tpu.language as dl
from triton_dist_tpu.language import shmem
from triton_dist_tpu.ops.common import comm_params, resolve_interpret


def _run_1d(mesh, kernel, x, out_shape=None, scratch_shapes=(),
            collective_id=0):
    """shard_map a single-axis pallas kernel over the tp mesh."""
    out_shape = out_shape or jax.ShapeDtypeStruct(
        (x.shape[0] // mesh.shape["tp"],) + x.shape[1:], x.dtype)

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P("tp"),
                       out_specs=P("tp"), check_vma=False)
    def run(x):
        return pl.pallas_call(
            kernel,
            out_shape=out_shape,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=list(scratch_shapes),
            compiler_params=comm_params(collective_id),
            interpret=resolve_interpret(None),
        )(x)

    return run(x)


def test_rank_num_ranks(mesh8):
    def kernel(x_ref, o_ref):
        r = dl.rank("tp")
        n = dl.num_ranks("tp")
        o_ref[:] = jnp.full_like(o_ref, r * 100 + n)

    x = jnp.zeros((8 * 8, 128), jnp.int32)
    y = _run_1d(mesh8, kernel, x)
    got = np.asarray(y).reshape(8, 8, 128)
    for r in range(8):
        assert (got[r] == r * 100 + 8).all()


def test_put_ring(mesh8):
    """Each rank puts its block to its right neighbor — the minimal one-sided
    put+signal (reference test_nvshmem_api.py putmem_signal cases)."""
    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = dl.rank("tp")
        dst = jax.lax.rem(me + 1, dl.num_ranks("tp"))
        copy = shmem.putmem_nbi_block(o_ref, x_ref, dst, send_sem, recv_sem)
        copy.wait()

    x = (jnp.arange(8)[:, None, None] *
         jnp.ones((8, 8, 128))).astype(jnp.float32).reshape(64, 128)
    y = _run_1d(mesh8, kernel, x, scratch_shapes=[
        pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA(())])
    got = np.asarray(y).reshape(8, 8, 128)
    for r in range(8):
        assert (got[r] == (r - 1) % 8).all(), r


def test_notify_wait_ring(mesh8):
    """Signal right neighbor's semaphore, wait for left's — reference
    test_distributed_wait.py / test_wait_and_notify.py shape."""
    def kernel(x_ref, o_ref, sem):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        dst = jax.lax.rem(me + 1, n)
        dl.notify(sem, peer=dst, inc=3)
        dl.wait(sem, 3)
        o_ref[:] = x_ref[:] + 1.0

    x = jnp.zeros((64, 128), jnp.float32)
    y = _run_1d(mesh8, kernel, x,
                scratch_shapes=[pltpu.SemaphoreType.REGULAR])
    assert (np.asarray(y) == 1.0).all()


def test_barrier_all(mesh8):
    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        dst = jax.lax.rem(me + 1, n)
        copy = shmem.putmem_nbi_block(o_ref, x_ref, dst, send_sem, recv_sem)
        copy.wait()
        dl.barrier_all("tp")
        # after the barrier every rank's put has landed
        o_ref[:] = o_ref[:] * 2.0

    x = (jnp.arange(8)[:, None, None] *
         jnp.ones((8, 8, 128))).astype(jnp.float32).reshape(64, 128)
    y = _run_1d(mesh8, kernel, x, scratch_shapes=[
        pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA(())])
    got = np.asarray(y).reshape(8, 8, 128)
    for r in range(8):
        assert (got[r] == 2 * ((r - 1) % 8)).all()


def test_consume_token():
    assert dl.consume_token(5, None) == 5


def test_logical_device_id_2d(mesh4x2):
    """On a (tp=4, ep=2) mesh, notify along ep must translate to global
    logical ids (reference: team-relative→global PE translation)."""
    def kernel(x_ref, o_ref, sem):
        me = dl.rank("ep")
        n = dl.num_ranks("ep")
        dst = jax.lax.rem(me + 1, n)
        # mesh_axes intentionally omitted: auto-detected from the enclosing
        # mesh trace context
        dl.notify(sem, peer=dst, axis="ep")
        dl.wait(sem, 1)
        o_ref[:] = x_ref[:] + 10.0

    x = jnp.zeros((8 * 8, 128), jnp.float32)

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh4x2,
                       in_specs=P(("tp", "ep")),
                       out_specs=P(("tp", "ep")), check_vma=False)
    def run(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.REGULAR],
            compiler_params=comm_params(),
            interpret=resolve_interpret(None),
        )(x)

    y = run(x)
    assert (np.asarray(y) == 10.0).all()


def test_logical_device_id_3d(devices):
    """3-level mesh (the n-level hierarchical collectives' address
    space): ring notify along the MIDDLE axis must translate through
    both outer and inner coordinates (reference
    nvshmem_team_translate_pe over a 3-D team split)."""
    from jax.sharding import Mesh
    mesh = Mesh(np.array(devices).reshape(2, 2, 2), ("x", "y", "z"))

    def kernel(x_ref, o_ref, sem):
        me = dl.rank("y")
        n = dl.num_ranks("y")
        dst = jax.lax.rem(me + 1, n)
        dl.notify(sem, peer=dst, axis="y")
        dl.wait(sem, 1)
        o_ref[:] = x_ref[:] + 3.0

    x = jnp.zeros((8 * 8, 128), jnp.float32)

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=P(("x", "y", "z")),
                       out_specs=P(("x", "y", "z")), check_vma=False)
    def run(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.REGULAR],
            compiler_params=comm_params(),
            interpret=resolve_interpret(None),
        )(x)

    assert (np.asarray(run(x)) == 3.0).all()


def test_notify_accumulates_and_wait_decrements(mesh8):
    """notify(inc=k) accumulates; wait(v) consumes exactly v — the
    semaphore is a counter, not a flag (reference SIGNAL_OP add
    semantics + wait-until-eq)."""
    def kernel(x_ref, o_ref, sem):
        # (semaphore_read has no CPU-interpreter rule; completion of the
        # split waits IS the assertion — flag semantics would deadlock.)
        dl.notify(sem, inc=3)          # self-notify, accumulate
        dl.wait(sem, 2)                # consume 2 of 3
        dl.wait(sem, 1)                # drain the remaining 1
        o_ref[:] = x_ref[:] + 1.0

    x = jnp.zeros((8 * 8, 128), jnp.float32)

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh8, in_specs=P("tp"),
                       out_specs=P("tp"), check_vma=False)
    def run(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.REGULAR],
            compiler_params=comm_params(),
            interpret=resolve_interpret(None),
        )(x)

    y = np.asarray(run(x))
    assert (y == 1.0).all(), y[0, 0]


def test_remote_copy_sliced_rows(mesh8):
    """remote_copy over ROW SLICES of a larger buffer: each device
    pushes its top half into the right neighbor's bottom half
    (putmem_nbi_block with offsets, low_latency_all_to_all.py:52-99)."""
    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        right = jax.lax.rem(me + 1, n)
        o_ref[:8] = x_ref[:8]
        cp = dl.remote_copy(x_ref.at[pl.ds(0, 8)], o_ref.at[pl.ds(8, 8)],
                            right, send_sem, recv_sem, axis="tp")
        cp.start()
        cp.wait_recv()
        cp.wait_send()

    x = jnp.arange(8 * 16 * 128, dtype=jnp.float32).reshape(8 * 16, 128)

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh8, in_specs=P("tp"),
                       out_specs=P("tp"), check_vma=False)
    def run(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((16, 128), x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.DMA,
                            pltpu.SemaphoreType.DMA],
            compiler_params=comm_params(),
            interpret=resolve_interpret(None),
        )(x)

    y = np.asarray(run(x)).reshape(8, 16, 128)
    xs = np.asarray(x).reshape(8, 16, 128)
    for dev in range(8):
        left = (dev - 1) % 8
        np.testing.assert_array_equal(y[dev, :8], xs[dev, :8])
        np.testing.assert_array_equal(y[dev, 8:], xs[left, :8])
