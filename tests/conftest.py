"""Test configuration: a forced 8-device CPU mesh.

The reference can only test distributed code with real multi-GPU torchrun
(SURVEY.md §4). On TPU/JAX we get a single-process multi-device simulation:
8 virtual CPU devices + Pallas TPU interpret mode (which simulates remote
DMAs and semaphores), so the whole distributed test suite runs on any
machine.
"""

import os

# NOTE: on 1-core hosts the run is re-exec'd with the CPU-affinity shim by
# triton_dist_tpu.testing.shim_plugin (loaded via addopts) before capture
# starts — see runtime/cpu_shim.py for why.

# Must be set before the CPU backend is initialized.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The environment pins JAX_PLATFORMS=axon (a tunneled single real TPU chip).
# Tests run on the virtual CPU mesh instead; the benchmark (bench.py) is what
# runs on real TPU hardware.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def mesh8(devices):
    """1-D tp=8 mesh (the reference's default TP group of all ranks)."""
    from jax.sharding import Mesh
    return Mesh(np.array(devices), ("tp",))


@pytest.fixture()
def mesh4x2(devices):
    from jax.sharding import Mesh
    return Mesh(np.array(devices).reshape(4, 2), ("tp", "ep"))


@pytest.fixture()
def key():
    return jax.random.PRNGKey(0)


@pytest.fixture(autouse=True)
def _isolate_autotune_cache(monkeypatch):
    """Tests must not read or write the developer's persistent autotune
    cache (TDT_AUTOTUNE_CACHE); the disk-cache tests opt back in with
    their own tmp_path setenv."""
    monkeypatch.delenv("TDT_AUTOTUNE_CACHE", raising=False)


@pytest.fixture(autouse=True)
def _isolate_trace(monkeypatch, tmp_path):
    """Tracing state is process-global like the metrics registry:
    every test starts and ends with the tracer disabled and the flight
    recorder's dump history cleared, and dumps land in a per-test temp
    dir (never the developer's /tmp/tdt_trace)."""
    monkeypatch.delenv("TDT_TRACE", raising=False)
    monkeypatch.delenv("TDT_FLIGHT_SECONDS", raising=False)
    monkeypatch.setenv("TDT_TRACE_DIR", str(tmp_path / "traces"))
    # Device-profile captures isolate the same way: per-test artifact
    # dir, sampler knobs cleared, armed/last-profile state reset.
    monkeypatch.delenv("TDT_DEVPROF_EVERY", raising=False)
    monkeypatch.delenv("TDT_DEVPROF_ON_BREACH", raising=False)
    monkeypatch.setenv("TDT_DEVPROF_DIR", str(tmp_path / "devprof"))
    # The history sampler reads its knobs at scheduler construction;
    # a developer's TDT_HISTORY* must not leak a sampler (or
    # detectors) into tests that assert the off-by-default contract.
    for k in ("TDT_HISTORY", "TDT_HISTORY_LEN", "TDT_HISTORY_TICK_S",
              "TDT_HISTORY_DUMP_S", "TDT_HISTORY_SLOPE",
              "TDT_HISTORY_STEP"):
        monkeypatch.delenv(k, raising=False)
    from triton_dist_tpu.obs import devprof, flight, trace
    trace.reset()
    flight.reset()
    devprof.reset()
    yield
    trace.reset()
    flight.reset()
    devprof.reset()


@pytest.fixture(autouse=True)
def _isolate_observatory():
    """The SLO observatory's process-local rings (request-attribution
    waterfalls; the perfwatch sample windows reset through
    resilience.reset_for_tests below) start empty for every test, so
    one test's requests cannot leak into another's
    ``request_stats``."""
    from triton_dist_tpu.obs import attrib
    attrib.reset()
    yield
    attrib.reset()


@pytest.fixture(autouse=True)
def _isolate_resilience(monkeypatch, tmp_path):
    """Point the resilience known-bad cache at a per-test temp file
    (never the developer's ~/.cache) and reset all process-local
    resilience state (breakers, compiled-key set, fault plan) around
    each test, so a breaker tripped in one test cannot silently route
    another test's fused path to XLA."""
    monkeypatch.setenv("TDT_KNOWN_BAD_CACHE",
                       str(tmp_path / "known_bad.json"))
    # Defense in depth: a module imported by one test (bench.py sets
    # this for real runs) must not pin routing for every later test.
    monkeypatch.delenv("TDT_FORCE_FUSED", raising=False)
    from triton_dist_tpu import resilience
    from triton_dist_tpu.testing import faults
    resilience.reset_for_tests()
    faults.clear()
    yield
    resilience.reset_for_tests()
    faults.clear()
