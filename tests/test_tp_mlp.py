"""TP_MLP layer vs single-device golden (reference test/nvidia/test_tp_mlp.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.layers import TPMLP

#: Heavy interpret-mode numerics -> full tier only (quick tier: pytest -m 'not slow').
pytestmark = pytest.mark.slow

H, I, M = 64, 128, 16


def golden(params, x):
    wg = np.asarray(params["w_gate"], np.float32)
    wu = np.asarray(params["w_up"], np.float32)
    wd = np.asarray(params["w_down"], np.float32)
    xf = np.asarray(x, np.float32)
    gate = xf @ wg
    act = (gate / (1 + np.exp(-gate))) * (xf @ wu)
    return act.astype(np.float32) @ wd


@pytest.fixture()
def mlp(mesh8):
    return TPMLP(H, I, mesh=mesh8, dtype=jnp.float32)


@pytest.fixture()
def setup(mlp, key):
    params = mlp.init(key)
    x = jax.random.normal(jax.random.PRNGKey(7), (M, H), jnp.float32)
    return params, x, golden(params, x)


@pytest.mark.parametrize("mode", ["xla", "ag_rs", "xla_ar", "gemm_ar"])
def test_tp_mlp_modes(mlp, setup, mode):
    params, x, ref = setup
    out = mlp(params, x, mode=mode)
    np.testing.assert_allclose(np.asarray(out, np.float32), ref,
                               rtol=2e-4, atol=2e-4)


def test_modes_agree(mlp, setup):
    params, x, _ = setup
    a = mlp(params, x, mode="xla")
    b = mlp(params, x, mode="ag_rs")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
@pytest.mark.parametrize("m,h,i", [(16, 64, 128), (40, 72, 144)])
def test_tp_mlp_shape_dtype_sweep(mesh8, key, dtype, m, h, i):
    """Reference test_tp_mlp.py sweeps (M, dtype) per fwd mode; the
    second shape is deliberately non-tile-aligned (M=40, H=72)."""
    mlp = TPMLP(h, i, mesh=mesh8, dtype=dtype)
    params = mlp.init(key)
    x = jax.random.normal(jax.random.PRNGKey(9), (m, h), dtype)
    ref = golden(params, x)
    tol = 2e-4 if dtype == jnp.float32 else 8e-2
    for mode in ("xla", "ag_rs", "xla_ar", "gemm_ar"):
        out = mlp(params, x, mode=mode)
        assert out.dtype == dtype and out.shape == (m, h)
        np.testing.assert_allclose(np.asarray(out, np.float32), ref,
                                   rtol=tol, atol=tol * 8,
                                   err_msg=f"mode={mode}")


def test_tp_mlp_grads_fused_vs_xla(mesh8, key):
    """Layer-level grad parity: the fused ag_rs backward (transpose
    kernels, ops/autodiff.py) must match the xla-collective backward."""
    mlp = TPMLP(H, I, mesh=mesh8, dtype=jnp.float32)
    params = mlp.init(key)
    x = jax.random.normal(jax.random.PRNGKey(11), (M, H), jnp.float32)

    def loss(p, mode):
        y = mlp(p, x, mode=mode).astype(jnp.float32)
        return jnp.mean(y * y)

    g_ref = jax.grad(lambda p: loss(p, "xla"))(params)
    g_fused = jax.grad(lambda p: loss(p, "ag_rs"))(params)
    for name in g_ref:
        np.testing.assert_allclose(
            np.asarray(g_fused[name]), np.asarray(g_ref[name]),
            rtol=1e-4, atol=1e-4, err_msg=name)


def test_tp_mlp_set_fwd_roundtrip(mlp, setup):
    """set_fwd switches the default mode (reference TP_MLP.set_fwd)."""
    params, x, ref = setup
    mlp.set_fwd("gemm_ar")
    out = mlp(params, x)
    np.testing.assert_allclose(np.asarray(out, np.float32), ref,
                               rtol=2e-4, atol=2e-4)
    mlp.set_fwd("ag_rs")
