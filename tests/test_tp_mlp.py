"""TP_MLP layer vs single-device golden (reference test/nvidia/test_tp_mlp.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.layers import TPMLP

H, I, M = 64, 128, 16


def golden(params, x):
    wg = np.asarray(params["w_gate"], np.float32)
    wu = np.asarray(params["w_up"], np.float32)
    wd = np.asarray(params["w_down"], np.float32)
    xf = np.asarray(x, np.float32)
    gate = xf @ wg
    act = (gate / (1 + np.exp(-gate))) * (xf @ wu)
    return act.astype(np.float32) @ wd


@pytest.fixture()
def mlp(mesh8):
    return TPMLP(H, I, mesh=mesh8, dtype=jnp.float32)


@pytest.fixture()
def setup(mlp, key):
    params = mlp.init(key)
    x = jax.random.normal(jax.random.PRNGKey(7), (M, H), jnp.float32)
    return params, x, golden(params, x)


@pytest.mark.parametrize("mode", ["xla", "ag_rs", "xla_ar", "gemm_ar"])
def test_tp_mlp_modes(mlp, setup, mode):
    params, x, ref = setup
    out = mlp(params, x, mode=mode)
    np.testing.assert_allclose(np.asarray(out, np.float32), ref,
                               rtol=2e-4, atol=2e-4)


def test_modes_agree(mlp, setup):
    params, x, _ = setup
    a = mlp(params, x, mode="xla")
    b = mlp(params, x, mode="ag_rs")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)
