"""Block-granular paged-KV allocator + prefix-cache invariants
(ISSUE 6 satellite; models/kv_cache.py block substrate,
models/prefix_cache.py index).

Pure host-side state machines — no device programs — so the randomized
property tests run in the quick tier. The invariants checked after
EVERY operation:

- partition: each device's slots split disjointly into
  {free stack} ⊎ {referenced (ref > 0)} ⊎ {evictable (cached, ref 0)};
- refcount conservation: a slot's refcount equals the number of table
  lanes referencing it across allocated row blocks (the sentinel is a
  reserved physical page OUTSIDE the accounted pool — it never appears
  in the refcounts);
- write-block privacy (the COW discipline): the block holding any live
  row's next write position has refcount exactly 1 — indexed/shared
  blocks are immutable by construction, so the "copy" of copy-on-write
  is statically unreachable;
- commitment solvency: free + evictable always covers the decode
  blocks committed to live rows (no admission can starve a live row);
- leak-freedom: once every row retires, free + evictable equals the
  whole pool — a stranded block is a slow production OOM.
"""

import numpy as np
import pytest

from triton_dist_tpu.models.kv_cache import PagedKVCacheManager
from triton_dist_tpu.models.prefix_cache import PrefixCache


def _mgr(mesh8, batch=4, page=4, ppsd=4, slots=10):
    return PagedKVCacheManager(1, batch, page, ppsd, 2, 8, mesh=mesh8,
                               axis="tp", slots_per_dev=slots)


def _check_invariants(m, write_pos):
    """Full-state audit; ``write_pos[b]`` is row b's next write
    position (None for unoccupied rows)."""
    w, slots, ppsd = m.world, m.slots_per_dev, m.pages_per_seq_dev
    expected_ref = np.zeros((w, slots), np.int64)
    for b in range(m.batch):
        for j in range(int(m._row_blocks[b])):
            r, lp = j // ppsd, j % ppsd
            expected_ref[r, m._table[r, b, lp]] += 1
    np.testing.assert_array_equal(m._ref, expected_ref)
    for r in range(w):
        free = set(int(s) for s in m._stack[r, :m._top[r]])
        evict = set(m.prefix._evictable[r]) if m.prefix else set()
        refd = set(int(s) for s in np.nonzero(m._ref[r])[0])
        assert not free & evict and not free & refd and not evict & refd
        assert len(free) + len(evict) + len(refd) == slots, \
            (r, sorted(free), sorted(evict), sorted(refd))
        if m.prefix:
            # every evictable slot is indexed; index/slot maps agree
            for s in evict:
                assert m.prefix.is_indexed(r, s)
    avail = m.available_per_dev()
    assert (avail >= m._committed).all(), (avail, m._committed)
    # COW discipline: any block a row can WRITE is privately owned.
    for b, pos in enumerate(write_pos):
        if pos is None:
            continue
        j = pos // m.page_size
        if j < int(m._row_blocks[b]):
            r, lp = j // ppsd, j % ppsd
            assert m._ref[r, m._table[r, b, lp]] == 1, (b, pos)
    a = m.block_audit()
    assert a["active"] >= 0 and a["free"] + a["evictable"] + \
        a["active"] == a["total"]


def test_block_pool_randomized_interleavings(mesh8):
    """Randomized admit(fork-shared prefixes)/decode/retire
    interleavings — plus ISSUE 13's speculative ops: k-token COMMITS
    (multi-block ensure_position growth in one call) and rejected-tail
    ROLLBACKS (rollback_position restoring consumed commitments) —
    never double-free or leak (the satellite's property test). Prompts
    draw from a few shared families so admissions fork off cached
    prefixes; the pool is tight enough that the free stacks run dry
    and LRU eviction engages."""
    m = _mgr(mesh8, batch=4, page=4, ppsd=4, slots=10)
    m.stream_setup(prefix_cache=True)
    rng = np.random.default_rng(11)
    families = [list(rng.integers(1, 64, size=16)) for _ in range(3)]
    live: dict = {}          # row -> {pos, end}
    for step in range(600):
        op = rng.choice(["admit", "decode", "retire", "spec_commit",
                         "spec_rollback"])
        free = [b for b in range(m.batch) if b not in live]
        if op == "admit" and free:
            b = int(rng.choice(free))
            fam = families[int(rng.integers(len(families)))]
            pl = int(rng.integers(1, 14))
            gen = int(rng.integers(1, 8))
            prompt = fam[:pl]
            if not m.can_admit(pl, gen):
                continue
            cached = m.admit_row(b, prompt, gen_budget=gen)
            assert cached % m.page_size == 0 and cached < pl + \
                m.page_size
            m.register_prefix(b, prompt)
            live[b] = {"pos": pl, "end": pl + gen - 1}
        elif op == "decode" and live:
            b = int(rng.choice(list(live)))
            st = live[b]
            if st["pos"] < st["end"]:
                m.ensure_position(b, st["pos"])
                st["pos"] += 1
        elif op == "spec_commit" and live:
            # A speculative burst: ensure positions for up to k drafts
            # in ONE call (multi-block growth), accept a prefix, roll
            # the rejected tail back — commitment bookkeeping must
            # survive any interleaving with admissions/retirements.
            b = int(rng.choice(list(live)))
            st = live[b]
            room = st["end"] - st["pos"]
            if room <= 0:
                continue
            k = int(rng.integers(1, min(room, 9) + 1))
            m.ensure_position(b, st["pos"] + k - 1)
            accepted = int(rng.integers(0, k + 1))
            if accepted < k:
                m.rollback_position(b, st["pos"] + accepted - 1
                                    if st["pos"] + accepted > 0 else 0)
            st["pos"] += accepted
        elif op == "spec_rollback" and live:
            # Degenerate rewind: everything past the current committed
            # position rolls back (a fully-rejected burst).
            b = int(rng.choice(list(live)))
            st = live[b]
            if st["pos"] > 0:
                m.rollback_position(b, st["pos"] - 1)
        elif op == "retire" and live:
            b = int(rng.choice(list(live)))
            m.release_row(b)
            del live[b]
        _check_invariants(
            m, [live[b]["pos"] if b in live else None
                for b in range(m.batch)])
    for b in list(live):
        m.release_row(b)
    a = m.block_audit()
    assert a["active"] == 0 and a["committed"] == 0
    assert a["free"] + a["evictable"] == a["total"]


def test_prefix_fork_shares_slots(mesh8):
    """Two admissions forking from one preamble reference the SAME
    physical slots for the shared full blocks (refcount 2), and both
    retire without returning a still-shared slot to the free stack."""
    m = _mgr(mesh8, batch=2, page=4, ppsd=4, slots=12)
    m.stream_setup(prefix_cache=True)
    pre = list(range(1, 13))            # 3 full blocks
    cached = m.admit_row(0, pre + [20], gen_budget=2)
    assert cached == 0                   # cold
    m.register_prefix(0, pre + [20])
    cached = m.admit_row(1, pre + [30], gen_budget=2)
    assert cached == 12                  # all 3 preamble blocks shared
    ppsd = m.pages_per_seq_dev
    for j in range(3):
        r, lp = j // ppsd, j % ppsd
        assert m._table[r, 0, lp] == m._table[r, 1, lp]
        assert m._ref[r, m._table[r, 0, lp]] == 2
    m.release_row(0)
    for j in range(3):                   # row 1 still holds the prefix
        r, lp = j // ppsd, j % ppsd
        assert m._ref[r, m._table[r, 1, lp]] == 1
    m.release_row(1)
    a = m.block_audit()
    assert a["active"] == 0
    assert a["evictable"] == 3           # the indexed prefix stays cached


def test_lru_eviction_order_and_reclaim(mesh8):
    """Eviction takes the LEAST recently released indexed block first,
    drops it from the index (a later probe misses), and hands its slot
    to the allocator; blocks referenced by live rows are never
    evicted."""
    m = _mgr(mesh8, batch=3, page=4, ppsd=8, slots=8)
    m.stream_setup(prefix_cache=True)   # all 8 usable (sentinel outside)
    # 10 tokens = 2 full blocks + a partial tail, so BOTH full blocks
    # are probe-eligible (an exact-multiple prompt always recomputes
    # its last block and would cap the probe at n_full - 1).
    a_p, b_p = list(range(1, 11)), list(range(11, 21))
    m.admit_row(0, a_p, gen_budget=1)
    m.register_prefix(0, a_p)
    m.release_row(0)                    # A's 2 indexed blocks -> evictable
    m.admit_row(0, b_p, gen_budget=1)
    m.register_prefix(0, b_p)
    m.release_row(0)                    # LRU order now: A, then B
    assert m.prefix_probe(a_p) == 2 and m.prefix_probe(b_p) == 2
    # Claim B live so only A is evictable, then exhaust the stack.
    assert m.admit_row(1, b_p, gen_budget=1) == 8
    free_now = int(m._top[0])
    m.admit_row(2, list(range(21, 21 + 4 * free_now + 2)),
                gen_budget=1)           # forces one eviction
    assert m._evicted_total == 1
    assert m.prefix_probe(a_p) < 2      # A lost its LRU block (block 0)
    assert m.prefix_probe(b_p) == 2     # B untouched: live-referenced
    m.release_row(1)
    m.release_row(2)
    a = m.block_audit()
    assert a["active"] == 0 and a["free"] + a["evictable"] == a["total"]


def test_admission_rollback_on_exhaustion(mesh8):
    """A failed admission (pool short) is all-or-nothing: hit refs roll
    back, lanes return to the sentinel, and nothing leaks."""
    m = _mgr(mesh8, batch=2, page=4, ppsd=8, slots=4)
    m.stream_setup(prefix_cache=True)   # all 4 usable
    pre = list(range(1, 9))             # 2 blocks
    m.admit_row(0, pre, gen_budget=1)   # wait: 8 % 4 == 0 -> last block
    m.register_prefix(0, pre)           # recomputed; 1 block indexed
    before = m.block_audit()
    with pytest.raises(RuntimeError, match="exhausted"):
        m.admit_row(1, pre + list(range(31, 40)), gen_budget=8)
    assert m.block_audit() == before
    assert (m._table[:, 1, :] == m._sentinel[:, None]).all()
    m.release_row(0)


def test_commitment_blocks_starvation(mesh8):
    """Admission control counts live rows' UNallocated decode tails:
    a second request that would eat the first row's committed blocks
    is refused until the first retires."""
    m = _mgr(mesh8, batch=2, page=4, ppsd=8, slots=6)   # 6 usable
    m.stream_setup(prefix_cache=False)
    # Row 0: prompt 4 (1 block now) + budget 17 -> commits 4 more
    # (positions 4..20 span blocks 1..5... ceil(20/4)=5 blocks total).
    assert m.can_admit(4, 17)
    m.admit_row(0, [1, 2, 3, 4], gen_budget=17)
    assert int(m._committed[0]) == 4
    assert not m.can_admit(4, 4)        # needs 2, only 1 uncommitted
    assert m.can_admit(1, 1)            # needs 1 -> fits
    # The committed row can always grow to its budget: G=17 decode
    # steps write positions 4..19 (the last token is sampled from the
    # step that writes position L+G-2).
    for pos in range(4, 20):
        m.ensure_position(0, pos)
    assert int(m._committed[0]) == 0
    m.release_row(0)
    assert m.can_admit(4, 4)


def test_spec_multiblock_growth_and_rollback_restores_commitment(mesh8):
    """ISSUE 13: one ensure_position call may cross several page
    boundaries (a k-token burst), consuming the row's commitment per
    allocated block; rolling the rejected tail back frees the blocks
    AND restores exactly the consumed commitments — so a later
    admission still cannot starve the row's remaining budget, and a
    rollback can never mint commitment growth never consumed."""
    m = _mgr(mesh8, batch=2, page=4, ppsd=8, slots=8)
    m.stream_setup(prefix_cache=False)
    m.admit_row(0, [1, 2, 3, 4], gen_budget=17)   # 1 block + 4 committed
    assert int(m._committed[0]) == 4
    # Burst crosses 3 page boundaries at once: positions 4..15.
    assert m.ensure_position(0, 15)
    assert int(m._row_blocks[0]) == 4
    assert int(m._committed[0]) == 1              # 3 consumed
    _check_invariants(m, [16])
    # Reject back to position 6 (keep blocks 0..1): 2 blocks return,
    # their commitments restored.
    assert m.rollback_position(0, 6)
    assert int(m._row_blocks[0]) == 2
    assert int(m._committed[0]) == 3
    assert (m._table[:, 0, 2:] == m._sentinel[:, None]).all()
    _check_invariants(m, [7])
    # No-op rollback: nothing past the kept position.
    assert not m.rollback_position(0, 7)
    # The row can still grow to its full budget after the rewind.
    for pos in range(7, 20):
        m.ensure_position(0, pos)
    assert int(m._committed[0]) == 0
    # Growth PAST the commitment (no budget left) must not let a
    # rollback mint new commitment: grow one uncommitted block, roll
    # it back, committed stays 0.
    m.ensure_position(0, 20)
    assert int(m._committed[0]) == 0
    m.rollback_position(0, 19)
    assert int(m._committed[0]) == 0
    m.release_row(0)
    a = m.block_audit()
    assert a["active"] == 0 and a["committed"] == 0
    assert a["free"] + a["evictable"] == a["total"]


def test_fits_pool_and_never_admissible(mesh8):
    m = _mgr(mesh8, batch=2, page=4, ppsd=8, slots=2)   # 2 usable
    m.stream_setup(prefix_cache=True)
    assert m.fits_pool(4, 4)            # 2 blocks
    assert not m.fits_pool(8, 4)        # 3 blocks > 2 usable
    assert m.can_admit(4, 4)


def test_full_capacity_request_fits(mesh8):
    """The sentinel must not steal request capacity: a request whose
    worst case needs EVERY accounted slot on every device (batch=1
    default-sized pool, prompt + gen == max_seq) is servable — the
    sentinel page rides outside the pool."""
    m = _mgr(mesh8, batch=1, page=4, ppsd=2, slots=2)   # default sizing
    m.stream_setup(prefix_cache=True)   # max_seq = 4 * 2 * 8 = 64
    assert m.fits_pool(32, 32)          # 2 blocks on every device
    assert m.can_admit(32, 32)
    m.admit_row(0, list(range(1, 33)), gen_budget=32)
    for pos in range(32, 63):           # decode writes [L, L+G-1)
        m.ensure_position(0, pos)
    _check_invariants(m, [63])
    m.release_row(0)
    a = m.block_audit()
    assert a["active"] == 0 and a["free"] + a["evictable"] == a["total"]


def test_prefix_cache_hash_chain_semantics():
    """Index-level contract: hashes chain (a prefix match is exact),
    only full blocks hash, first writer wins, claim/release round-trip
    keeps the LRU consistent."""
    pc = PrefixCache(world=2, page_size=4)
    a = pc.block_hashes([1, 2, 3, 4, 5, 6, 7, 8, 9])
    b = pc.block_hashes([1, 2, 3, 4, 5, 6, 99])
    assert len(a) == 2 and len(b) == 1      # partial tails don't hash
    assert a[0] == b[0] and a[1] != b[0]
    assert pc.probe(a) == 0
    assert pc.register(a[0], 0, 3)
    assert not pc.register(a[0], 0, 4)      # first writer wins (hash)
    assert not pc.register(a[1], 0, 3)      # ... and slot
    assert pc.probe(a) == 1 and pc.probe(b) == 1
    assert pc.lookup(a) == [(0, 3)]
    pc.release(0, 3)
    assert pc.evictable_count(0) == 1
    pc.claim(0, 3)
    assert pc.evictable_count(0) == 0 and pc.probe(b) == 1
    pc.release(0, 3)
    assert pc.evict_lru(0) == 3
    assert pc.probe(a) == 0 and pc.evict_lru(0) is None
    assert pc.stats()["evictions"] == 1
