"""Fault-tolerant replica router (serving/router.py, ISSUE 15).

Quick tier. Covered here:

- basic routing: greedy tokens through the router are bit-identical
  to a direct replica's, responses carry ``replica`` + ``trace_id``
  and NO ``failovers`` key on the clean path; ``router_status`` /
  ``metrics`` verbs;
- the ACCEPTANCE scenario: three replicas, one killed mid-traffic-
  window → zero failed client requests, every in-flight request
  re-dispatched (``failovers >= 1`` observed), the victim marked
  ``down`` within the configured age, a validated flight dump, and
  ONE trace ID spanning both the dead and the answering replica;
- wedged-replica handling: requests fail over on the dispatch
  deadline while the victim's health verb stays live (the breaker —
  not liveness — catches it), the breaker opens, and the half-open
  probe re-closes it after recovery;
- fleet-level load shed: every replica draining/saturated → one
  structured ``queue_full`` with a ``retry_after_ms`` hint;
- graceful drain: the server ``drain`` verb + scheduler in-flight
  accounting, and live ``router_remove``/``router_add``;
- client fault-awareness (satellites): multi-endpoint ChatClient and
  ``fanout`` skip dead endpoints with a single retry on the next;
  ``retry_after_ms`` is honored with one sleep-and-retry;
- the regress gate (``check_router_wellformed``) and the dashboard
  surfaces (``fleet_top.render_router``, ``report.render_router``).
"""

import json
import socket
import socketserver
import threading
import time

import jax.numpy as jnp
import pytest

from triton_dist_tpu.serving import ChatClient, ModelServer, RouterServer
from triton_dist_tpu.serving.client import fanout
from triton_dist_tpu.testing import chaos


@pytest.fixture(scope="module")
def tiny():
    import jax
    import numpy as np
    from jax.sharding import Mesh
    from triton_dist_tpu.models import DenseLLM, ModelConfig
    mesh = Mesh(np.array(jax.devices()), ("tp",))
    cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=4, vocab_size=64,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="xla")
    return model, model.init(jax.random.PRNGKey(0))


def _server(tiny, rid, **kw):
    from triton_dist_tpu.models import Engine
    model, params = tiny
    eng = Engine(model, batch=2, max_seq=64, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    return ModelServer(eng, params, port=0, registry="private",
                       replica_id=rid, **kw).start()


def _router(eps, **kw):
    kw.setdefault("registry", "private")
    kw.setdefault("poll_s", 0.05)
    kw.setdefault("fleet_kwargs", {"stale_s_": 0.5, "down_s_": 1.5,
                                   "timeout_s": 2.0})
    return RouterServer(eps, **kw).start()


def _wait(pred, timeout=30.0, what="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        v = pred()
        if v:
            return v
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


def _dead_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# Basic routing.
# ---------------------------------------------------------------------------

def test_router_roundtrip_matches_direct_and_status(tiny):
    s0 = _server(tiny, "rt-a")
    s1 = _server(tiny, "rt-b")
    eps = [(s0.host, s0.port), (s1.host, s1.port)]
    r = _router(eps)
    try:
        direct = ChatClient(s0.host, s0.port, timeout=60)
        want = direct.generate_ids([[1, 2, 3]], gen_len=4)
        direct.close()
        c = ChatClient(r.host, r.port, timeout=60)
        got = c.generate_ids([[1, 2, 3]], gen_len=4)
        # Greedy replay-idempotence: any replica produces the same
        # tokens — the property failover's re-dispatch rests on.
        assert got["tokens"] == want["tokens"]
        assert got.get("trace_id")
        assert got.get("replica") in (f"{s0.host}:{s0.port}",
                                      f"{s1.host}:{s1.port}")
        assert "failovers" not in got       # clean path
        st = c.request({"cmd": "router_status"})["router"]
        assert len(st["replicas"]) == 2
        for row in st["replicas"]:
            assert row["status"] == "live"
            assert row["breaker"] == "closed"
            assert row["inflight"] == 0
            assert not row["draining"]
        assert sum(st["placements"].values()) >= 1
        m = c.request({"cmd": "metrics"})["metrics"]
        assert m["counters"]["router.requests"] >= 1
        assert m["router"]["replicas"]
        # generation without prompt_ids is a structured error
        bad = c.request({"x": 1})
        assert bad.get("type") == "ValueError"
        c.close()
    finally:
        r.stop()
        s0.stop()
        s1.stop()


# ---------------------------------------------------------------------------
# The acceptance scenario: kill one of three mid-window.
# ---------------------------------------------------------------------------

def test_kill_one_of_three_zero_client_failures(tiny):
    """ISSUE 15 acceptance: 3 replicas, one killed mid-window → zero
    failed client requests, in-flight requests re-dispatched
    (failovers >= 1), down within the configured age, a validated
    flight dump, and one trace ID spanning both replicas."""
    from triton_dist_tpu.obs import trace
    from triton_dist_tpu.tools import trace_export
    srvs = [_server(tiny, f"kill-{i}") for i in range(3)]
    eps = [(s.host, s.port) for s in srvs]
    down_s = 1.5
    r = _router(eps)
    rc = ChatClient(r.host, r.port, timeout=120)
    try:
        reqs = [{"prompt_ids": [[(i % 7) + 1, (i % 5) + 2]],
                 "gen_len": 60} for i in range(9)]
        # Warm all replicas' compiles before the timed window.
        fanout(endpoints=eps,
               requests=[dict(q, gen_len=2) for q in reqs])

        window: dict = {}
        base_admits = [s.registry.snapshot()["counters"]
                       .get("serving.admitted", 0) for s in srvs]

        def traffic():
            window["outs"] = fanout(r.host, r.port, requests=reqs)

        th = threading.Thread(target=traffic, daemon=True)
        th.start()

        def busy_victim():
            # A replica with an in-flight dispatch that its pump has
            # ADMITTED: killing pre-admission is legal (the router
            # still fails over) but leaves no victim-side admit
            # instant for the trace-stitching assertion below.
            rows = rc.request({"cmd": "router_status"}
                              )["router"]["replicas"]
            for i, row in enumerate(rows):
                admitted = (srvs[i].registry.snapshot()["counters"]
                            .get("serving.admitted", 0))
                if row["inflight"] > 0 and admitted > base_admits[i]:
                    return (i, row["endpoint"])
            return None

        victim_idx, victim_ep = _wait(busy_victim,
                                      what="in-flight on a replica")
        t_kill = time.monotonic()
        chaos.kill_replica(srvs[victim_idx])
        th.join(timeout=120)
        outs = window["outs"]

        # ZERO failed client requests — the acceptance bar.
        assert all("tokens" in o for o in outs), outs
        # At least one request actually failed over.
        hops = [o for o in outs if o.get("failovers")]
        assert hops, outs
        hop = hops[0]
        assert hop["failovers"] >= 1
        assert hop["replica"] != victim_ep   # answered elsewhere

        # Down within the configured age (+ poll slack).
        def victim_down():
            rows = rc.request({"cmd": "router_status"}
                              )["router"]["replicas"]
            st = {x["endpoint"]: x["status"] for x in rows}
            return st.get(victim_ep) == "down"
        _wait(victim_down, timeout=down_s + 10.0, what="victim down")
        assert time.monotonic() - t_kill < down_s + 10.0

        # The kill left an automatic flight dump (breaker open /
        # replica_down) — and it validates.
        stats = trace.stats()
        auto = stats.get("last_flight_record")
        assert auto, stats
        with open(auto) as f:
            errors, _warn = trace_export.validate(json.load(f))
        assert not errors, errors

        # One trace ID spans both replicas: the failover request's ID
        # tags the victim's admission, the router's failover instant,
        # and the survivor's admission/retire. (Fresh cmd dump = the
        # full current window; in-process replicas share the ring.)
        dump = rc.dump_trace()["dumped"]
        with open(dump) as f:
            evs = json.load(f)["traceEvents"]

        def story_of(h):
            return [e for e in evs
                    if (e.get("args") or {}).get("trace_id")
                    == h["trace_id"]]

        def admit_replicas(st):
            return {(e.get("args") or {}).get("replica")
                    for e in st if e["name"] == "serving.admit"}

        story = story_of(hop)
        assert any(e["name"] == "router.failover" for e in story)
        # A failed-over request whose VICTIM-side admission happened
        # (the kill can legally race ahead of the victim's pump, in
        # which case that hop has only the survivor's admit) — pick
        # any hop whose story spans both replicas; with several
        # requests in flight at the kill, at least one was admitted
        # on the victim before dying.
        spanning = [h for h in hops
                    if len(admit_replicas(story_of(h))) >= 2]
        assert spanning, [story_of(h) for h in hops]
        # The fleet kept serving afterwards.
        ok = rc.generate_ids([[9, 8]], gen_len=3)
        assert "tokens" in ok
        m = rc.request({"cmd": "metrics"})["metrics"]["counters"]
        assert m.get("router.failovers", 0) >= 1
        assert m.get("router.dispatch_errors", 0) >= 1
    finally:
        rc.close()
        r.stop()
        for s in srvs:
            s.stop()


# ---------------------------------------------------------------------------
# Wedged replica: dispatch deadline + breaker, not liveness.
# ---------------------------------------------------------------------------

def test_wedged_replica_fails_over_breaker_opens_then_recovers(tiny):
    s0 = _server(tiny, "wg-a")
    s1 = _server(tiny, "wg-b")
    eps = [(s0.host, s0.port), (s1.host, s1.port)]
    r = _router(eps, try_timeout_s=0.5, retries=3, backoff_ms=10,
                breaker_threshold=2, breaker_cooldown_s=0.3)
    c = ChatClient(r.host, r.port, timeout=120)
    try:
        # Warm BOTH replicas' compiled programs directly (not through
        # the router): each Engine jits its own step, and the first
        # generation's XLA compile can exceed the deliberately tight
        # 0.5 s dispatch deadline this test gives the router — which
        # would open both breakers before anything is wedged.
        for s in (s0, s1):
            w = ChatClient(s.host, s.port, timeout=120)
            try:
                assert "tokens" in w.generate_ids([[1, 2]], gen_len=2)
            finally:
                w.close()
        # Find where the router places, then wedge THAT replica.
        first = c.generate_ids([[1, 2]], gen_len=2)
        assert "tokens" in first
        by_label = {f"{s.host}:{s.port}": s for s in (s0, s1)}
        victim = by_label[first["replica"]]
        survivor = s1 if victim is s0 else s0

        def victim_row():
            rows = c.request({"cmd": "router_status"}
                             )["router"]["replicas"]
            return {x["endpoint"]: x for x in rows}[
                f"{victim.host}:{victim.port}"]

        with chaos.wedge_pump(victim.scheduler):
            # With the healthy sibling still attached, every request
            # SUCCEEDS — a wedged dispatch times out and fails over
            # (health-gated placement may also route around the
            # victim outright once its queue gauge rises; either way
            # the client never sees the wedge).
            for i in range(3):
                assert "tokens" in c.generate_ids(
                    [[i + 1, i + 2]], gen_len=2)
            # Isolate the victim (remove the survivor) so dispatches
            # MUST hit the wedge: the per-attempt deadline trips, the
            # breaker opens after `breaker_threshold` timeouts, and
            # the exhausted request degrades structurally — while the
            # victim's health verb keeps answering (status live: the
            # failure class liveness checks cannot catch).
            c.request({"cmd": "router_remove",
                       "endpoint": f"{survivor.host}:{survivor.port}"})
            # (The breaker may ALREADY be open here if the loop above
            # sent `breaker_threshold` dispatches into the wedge —
            # then this request sheds without a dispatch; either way
            # the reply is structured and the breaker ends open.)
            resp = c.generate_ids([[9, 9]], gen_len=2)
            assert resp.get("type") == "no_healthy_replicas", resp
            row = victim_row()
            assert row["breaker"] == "open", row
            assert row["status"] == "live", row
            # The wedge was exercised through the dispatch deadline:
            # the breaker needed `breaker_threshold` recorded
            # timeouts to open.
            m = c.request({"cmd": "metrics"})["metrics"]["counters"]
            assert m.get("router.dispatch_errors", 0) >= 2
        # Recovery: release the wedge; the half-open probe dispatch
        # must re-close the breaker.
        _wait(lambda: victim.scheduler.inflight() == 0,
              what="wedge drained")
        time.sleep(0.35)        # past breaker_cooldown_s
        resp = _wait(
            lambda: (lambda o: o if "tokens" in o else None)(
                c.generate_ids([[7, 7]], gen_len=2)),
            what="probe success via recovered replica")
        assert resp["replica"] == f"{victim.host}:{victim.port}"
        rows = c.request({"cmd": "router_status"}
                         )["router"]["replicas"]
        assert [x["breaker"] for x in rows] == ["closed"]
    finally:
        c.close()
        r.stop()
        s0.stop()
        s1.stop()


# ---------------------------------------------------------------------------
# Fleet-level shed + drain.
# ---------------------------------------------------------------------------

def test_all_replicas_draining_sheds_fleet_queue_full(tiny):
    srv = _server(tiny, "shed-a")
    r = _router([(srv.host, srv.port)])
    try:
        c = ChatClient(r.host, r.port, timeout=60, retry_shed=False)
        assert "tokens" in c.generate_ids([[1, 2]], gen_len=2)
        # Server-side drain: the replica answers {"type": "draining"}.
        drc = ChatClient(srv.host, srv.port, timeout=60)
        d = drc.request({"cmd": "drain"})
        assert d["draining"] is True
        drc.close()
        resp = c.generate_ids([[3, 4]], gen_len=2)
        assert resp.get("type") == "queue_full", resp
        assert resp.get("scope") == "fleet"
        assert isinstance(resp.get("retry_after_ms"), int)
        assert resp["retry_after_ms"] >= 25
        m = c.request({"cmd": "metrics"})["metrics"]["counters"]
        assert m.get("router.shed", 0) >= 1
        assert m.get("router.replica_sheds", 0) >= 1
        c.close()
    finally:
        r.stop()
        srv.stop()


def test_server_drain_verb_inflight_accounting_and_resume(tiny):
    srv = _server(tiny, "drain-a")
    try:
        c = ChatClient(srv.host, srv.port, timeout=60,
                       retry_shed=False)
        assert "tokens" in c.generate_ids([[1, 2]], gen_len=2)
        assert srv.scheduler.inflight() == 0

        got: dict = {}

        def bg():
            cc = ChatClient(srv.host, srv.port, timeout=60)
            got["resp"] = cc.generate_ids([[1, 2, 3]], gen_len=40)
            cc.close()

        th = threading.Thread(target=bg, daemon=True)
        th.start()
        _wait(lambda: srv.scheduler.inflight() >= 1,
              what="request in flight")
        d = c.request({"cmd": "drain"})
        assert d["draining"] is True and d["inflight"] >= 1
        # New work refuses with the draining type + hint...
        rej = c.generate_ids([[5, 6]], gen_len=2)
        assert rej.get("type") == "draining", rej
        assert isinstance(rej.get("retry_after_ms"), int)
        # ...while health advertises the drain (routers stop placing).
        assert c.health().get("draining") is True
        # In-flight work finishes; wait_s polls it to zero.
        d2 = c.request({"cmd": "drain", "wait_s": 60})
        assert d2["drained"] is True and d2["inflight"] == 0
        th.join(timeout=60)
        assert "tokens" in got["resp"]
        # Resume: admissions work again.
        d3 = c.request({"cmd": "drain", "resume": True})
        assert d3["draining"] is False
        assert "tokens" in c.generate_ids([[7, 8]], gen_len=2)
        assert c.health().get("draining") is None
        c.close()
    finally:
        srv.stop()


def test_router_remove_waits_for_inflight_then_add_restores(tiny):
    srv = _server(tiny, "rm-a")
    r = _router([(srv.host, srv.port)])
    c = ChatClient(r.host, r.port, timeout=120, retry_shed=False)
    try:
        assert "tokens" in c.generate_ids([[1, 2]], gen_len=2)
        got: dict = {}

        def bg():
            cc = ChatClient(r.host, r.port, timeout=120)
            got["resp"] = cc.generate_ids([[1, 2, 3]], gen_len=40)
            cc.close()

        th = threading.Thread(target=bg, daemon=True)
        th.start()
        _wait(lambda: any(
            x["inflight"] > 0 for x in c.request(
                {"cmd": "router_status"})["router"]["replicas"]),
            what="in-flight through the router")
        # Graceful remove: waits for the router's in-flight dispatch.
        rm = c.request({"cmd": "router_remove",
                        "endpoint": f"{srv.host}:{srv.port}",
                        "wait_s": 60})
        assert rm["removed"] == f"{srv.host}:{srv.port}"
        assert rm["drained"] is True and rm["inflight"] == 0
        th.join(timeout=60)
        assert "tokens" in got["resp"]     # the in-flight one finished
        # Empty fleet: structured no_healthy_replicas, not a hang.
        resp = c.generate_ids([[5, 5]], gen_len=2)
        assert resp.get("type") == "no_healthy_replicas", resp
        assert isinstance(resp.get("retry_after_ms"), int)
        # Live add restores service.
        add = c.request({"cmd": "router_add",
                         "endpoint": f"{srv.host}:{srv.port}"})
        assert add["replicas"] == 1
        assert "tokens" in c.generate_ids([[6, 6]], gen_len=2)
        m = c.request({"cmd": "metrics"})["metrics"]["counters"]
        assert m.get("router.replicas_removed") == 1
        assert m.get("router.replicas_added") == 1
    finally:
        c.close()
        r.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# Client fault-awareness (satellites).
# ---------------------------------------------------------------------------

def test_multi_endpoint_client_skips_dead_and_retries_next(tiny):
    srv = _server(tiny, "skip-a")
    dead = ("127.0.0.1", _dead_port())
    c = ChatClient(endpoints=[dead, (srv.host, srv.port)], timeout=60)
    try:
        # Round-robin starts on the dead endpoint: the failure is
        # retried once on the next — the caller never sees it.
        for _ in range(4):
            assert "tokens" in c.generate_ids([[1, 2]], gen_len=2)
        # ... and the dead endpoint is skipped (marked bad), so ALL
        # requests landed on the live replica.
        h = c.health(endpoint=(srv.host, srv.port))
        assert h["counters"]["server.requests"] >= 4
    finally:
        c.close()
        srv.stop()


def test_fanout_retries_slot_on_next_endpoint(tiny):
    srv = _server(tiny, "fan-a")
    dead = ("127.0.0.1", _dead_port())
    outs = fanout(endpoints=[dead, (srv.host, srv.port)],
                  requests=[{"prompt_ids": [[i + 1, 2]], "gen_len": 2}
                            for i in range(4)], timeout=60)
    try:
        assert all("tokens" in o for o in outs), outs
        # Pinned mode (the FleetView scrape contract) keeps the old
        # exact slot→endpoint behavior: dead slots error.
        outs_pinned = fanout(
            endpoints=[dead, (srv.host, srv.port)],
            requests=[{"cmd": "health"}, {"cmd": "health"}],
            timeout=5, retry_next=False)
        assert "error" in outs_pinned[0]
        assert "health" in outs_pinned[1]
    finally:
        srv.stop()


def _stub_server(reply_fn):
    """Tiny protocol stub: one JSON line in → ``reply_fn(req, server)``
    out (return a dict, the bytes b"" to close the connection mid-
    reply-less, or a raw bytes payload for torn-reply injection)."""
    class _H(socketserver.StreamRequestHandler):
        def handle(self):
            for line in self.rfile:
                if not line.strip():
                    continue
                self.server.hits += 1
                out = reply_fn(json.loads(line), self.server)
                if isinstance(out, dict):
                    out = (json.dumps(out) + "\n").encode()
                if out:
                    self.wfile.write(out)
                    self.wfile.flush()
                if getattr(self.server, "close_after", False):
                    return          # sever the connection
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _H)
    srv.daemon_threads = True
    srv.hits = 0
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def test_client_fails_over_on_torn_reply(tiny):
    """Review regression: a replica severed mid-write leaves a torn
    JSON line — a ValueError, not an OSError — and the multi-endpoint
    client must treat it like any other endpoint death: mark bad,
    retry once on the next endpoint."""
    def torn(req, server):
        server.close_after = True
        return b'{"tokens": [[1, 2'          # cut mid-reply
    broken = _stub_server(torn)
    srv = _server(tiny, "torn-b")
    try:
        c = ChatClient(endpoints=[broken.server_address,
                                  (srv.host, srv.port)], timeout=60)
        resp = c.generate_ids([[1, 2]], gen_len=2)
        assert "tokens" in resp, resp        # failed over, no raise
        # ... and the torn endpoint is now skipped.
        assert "tokens" in c.generate_ids([[3, 4]], gen_len=2)
        assert broken.hits == 1
        c.close()
    finally:
        broken.shutdown()
        broken.server_close()
        srv.stop()


def test_shed_retry_fails_over_when_endpoint_dies_in_the_sleep(tiny):
    """Review regression: the retry_after_ms sleep-and-retry round
    trip carries the same dead-endpoint failover contract as the
    first attempt — a replica dying during the backpressure sleep
    costs the one retry, not a raw socket error."""
    shedder = _stub_server(
        lambda req, s: {"error": "full", "type": "queue_full",
                        "retry_after_ms": 30})
    dead = ("127.0.0.1", _dead_port())
    srv = _server(tiny, "shed-die-b")
    try:
        # Round-robin: attempt 1 → shedder (queue_full + hint), sleep,
        # retry → the DEAD endpoint → must fail over to the live one
        # inside the retry round trip, not raise.
        c = ChatClient(endpoints=[shedder.server_address, dead,
                                  (srv.host, srv.port)], timeout=60)
        resp = c.generate_ids([[1, 2]], gen_len=2)
        assert "tokens" in resp, resp
        c.close()
    finally:
        shedder.shutdown()
        shedder.server_close()
        srv.stop()


def test_router_fails_over_replica_fault_reply_passes_client_fault():
    """Review regression: an error reply that is a REPLICA fault
    (engine failure — anything outside the ValueError client-mistake
    class) must fail over and count against the breaker; the
    request's own ValueError passes through unchanged."""
    broken = _stub_server(
        lambda req, s: {"error": "device lost", "type": "RuntimeError"}
        if "prompt_ids" in req else {"health": {"replica_id": "bx"}})
    healthy = _stub_server(
        lambda req, s: {"tokens": [[9]], "gen_len": 1}
        if "prompt_ids" in req else {"health": {"replica_id": "hx"}})
    r = _router([broken.server_address, healthy.server_address],
                retries=2, backoff_ms=5)
    try:
        c = ChatClient(r.host, r.port, timeout=60, retry_shed=False)
        resp = c.generate_ids([[1, 2]], gen_len=2)
        assert resp.get("tokens") == [[9]], resp
        assert resp.get("failovers") == 1        # RuntimeError hopped
        rows = c.request({"cmd": "router_status"})["router"]["replicas"]
        by_ep = {x["endpoint"]: x for x in rows}
        b_ep = "%s:%s" % broken.server_address
        assert by_ep[b_ep]["breaker"] != "closed" \
            or c.request({"cmd": "metrics"})["metrics"]["counters"][
                "router.dispatch_errors"] >= 1
        # A ValueError reply (the request's own fault) passes through
        # from whichever replica produced it — no failover.
        vbad = _stub_server(
            lambda req, s: {"error": "bad prompt", "type": "ValueError"}
            if "prompt_ids" in req else {"health": {"replica_id": "v"}})
        r2 = _router([vbad.server_address])
        c2 = ChatClient(r2.host, r2.port, timeout=60, retry_shed=False)
        resp2 = c2.generate_ids([[1]], gen_len=1)
        assert resp2.get("type") == "ValueError", resp2
        assert "failovers" not in resp2
        assert vbad.hits >= 1
        c2.close()
        r2.stop()
        vbad.shutdown()
        vbad.server_close()
        c.close()
    finally:
        r.stop()
        for s in (broken, healthy):
            s.shutdown()
            s.server_close()


class _ShedOnce(socketserver.StreamRequestHandler):
    def handle(self):
        for line in self.rfile:
            if not line.strip():
                continue
            self.server.hits += 1
            if self.server.hits == 1:
                resp = {"error": "full", "type": "queue_full",
                        "retry_after_ms": 40}
            else:
                resp = {"tokens": [[5]], "gen_len": 1}
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()


def _shed_server():
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _ShedOnce)
    srv.daemon_threads = True
    srv.hits = 0
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def test_client_honors_retry_after_ms_once():
    srv = _shed_server()
    try:
        c = ChatClient(*srv.server_address, timeout=60)
        t0 = time.monotonic()
        resp = c.generate_ids([[1]], gen_len=1)
        took = time.monotonic() - t0
        assert resp.get("tokens") == [[5]]       # retried through
        assert took >= 0.04                      # honored the hint
        assert srv.hits == 2                     # exactly one retry
        c.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_client_skips_retry_when_budget_too_small():
    srv = _shed_server()
    try:
        # hint (40ms) >= timeout budget (0.02s): no sleep-retry; the
        # raw shed reply comes back.
        c = ChatClient(*srv.server_address, timeout=0.02)
        resp = c.generate_ids([[1]], gen_len=1)
        assert resp.get("type") == "queue_full"
        assert srv.hits == 1
        c.close()
    finally:
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# Regress gate + dashboards.
# ---------------------------------------------------------------------------

def test_check_router_wellformed_gate():
    from triton_dist_tpu.tools.bench_ops import check_router_wellformed
    assert check_router_wellformed({}) == []        # part didn't run
    ok = {"serving_router_tokens_per_s": 800.0,
          "serving_router_vs_direct": 0.88,
          "serving_router_kill_client_errors": 0,
          "serving_router_failovers": 4,
          "serving_router_down_detect_s": 2.9,
          "serving_router_down_s": 3.0}
    assert check_router_wellformed(ok) == []
    for bad in (None, "x", True, 0.0, -1.0):
        fails = check_router_wellformed(
            dict(ok, serving_router_vs_direct=bad))
        assert fails and "vs_direct" in fails[0], bad
    fails = check_router_wellformed(
        dict(ok, serving_router_kill_client_errors=2))
    assert fails and "client-visible" in fails[0]
    for bad in (None, 0, True):
        fails = check_router_wellformed(
            dict(ok, serving_router_failovers=bad))
        assert fails and "failover" in fails[0], bad
    # Within the mechanism's inherent poll lag passes...
    assert check_router_wellformed(
        dict(ok, serving_router_down_detect_s=3.4)) == []
    # ...a miss past the bounded slack fails.
    fails = check_router_wellformed(
        dict(ok, serving_router_down_detect_s=6.0))
    assert fails and "detection deadline" in fails[0]
    fails = check_router_wellformed(
        dict(ok, serving_router_down_detect_s=None))
    assert fails
    gone = {"serving_router_tokens_per_s": 800.0}
    assert len(check_router_wellformed(gone)) == 4


def test_fleet_top_render_router_pure():
    from triton_dist_tpu.tools.fleet_top import render_router
    status = {
        "uptime_s": 12.5,
        "replicas": [
            {"endpoint": "127.0.0.1:1", "replica_id": "r0",
             "status": "live", "age_s": 0.1, "score": 0.9,
             "breaker": "closed", "inflight": 2, "draining": False},
            {"endpoint": "127.0.0.1:2", "replica_id": "r1",
             "status": "down", "age_s": 40.0, "score": None,
             "breaker": "open", "inflight": 0, "draining": True},
        ],
        "placements": {"127.0.0.1:1": 10, "127.0.0.1:2": 3},
        "counters": {"router.requests": 13, "router.failovers": 2,
                     "router.shed": 1},
    }
    screen = render_router(status)
    assert "r0" in screen and "r1" in screen
    assert "open" in screen and "closed" in screen
    assert "failovers 2" in screen
    assert "shed 1" in screen
    # degraded fetch renders too
    assert "no replicas" in render_router({"replicas": []})


def test_fleet_top_router_live_and_report_section(tiny, capsys):
    from triton_dist_tpu.tools import fleet_top, report
    srv = _server(tiny, "dash-a")
    r = _router([(srv.host, srv.port)])
    try:
        c = ChatClient(r.host, r.port, timeout=60)
        assert "tokens" in c.generate_ids([[1, 2]], gen_len=2)
        status = fleet_top.fetch_router(f"{r.host}:{r.port}")
        assert status["replicas"][0]["status"] == "live"
        rc = fleet_top.main(["--router", f"{r.host}:{r.port}",
                             "--once"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "tdt router" in out and "dash-a" in out

        # report.py renders the same payload as the "router" section.
        status["failover_sample"] = {"trace_id": "t-1", "failovers": 1,
                                     "replica": "x:1", "timing": None}
        md = report.render_router(status)
        assert "#### router" in md and "dash-a" in md
        assert "trace_id=t-1" in md
        assert report.render_router(None) == ""
        full = report.render_telemetry({"counters": {}, "gauges": {},
                                        "histograms": {},
                                        "router": status})
        assert "#### router" in full
        c.close()
    finally:
        r.stop()
        srv.stop()
