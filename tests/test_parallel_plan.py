"""Parallelism planner (parallel/plan.py): config + chips → layout."""

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models import ModelConfig
from triton_dist_tpu.parallel import Plan, plan_parallelism


def _dense_32b():
    return ModelConfig(hidden_size=5120, intermediate_size=27648,
                       num_hidden_layers=64, num_attention_heads=64,
                       num_key_value_heads=8, head_dim=128,
                       vocab_size=151936)


def test_dense_32b_takes_tp8():
    p = plan_parallelism(_dense_32b(), 8)
    assert (p.tp, p.sp, p.ep, p.dp) == (8, 1, 1, 1)
    assert p.decode_mode == "gemm_ar" and p.moe_parallel is None
    assert any("GiB params/chip" in r for r in p.reasons)


def test_moe_spreads_experts_first():
    moe = ModelConfig(hidden_size=2048, intermediate_size=0,
                      moe_intermediate_size=768, num_hidden_layers=48,
                      num_attention_heads=32, num_key_value_heads=4,
                      head_dim=128, vocab_size=151936, num_experts=128,
                      num_experts_per_tok=8)
    p = plan_parallelism(moe, 16)
    assert p.ep == 16 and p.moe_parallel == "ep"


def test_long_context_spends_leftover_on_sp():
    small = ModelConfig(hidden_size=1024, intermediate_size=2048,
                        num_hidden_layers=8, num_attention_heads=16,
                        num_key_value_heads=2, head_dim=64,
                        vocab_size=32000)
    p = plan_parallelism(small, 8, max_seq=65536)
    assert p.sp > 1 and p.prefill_mode == p.decode_mode == "sp"
    assert p.tp * p.sp * p.ep * p.dp <= 8


def test_small_model_leftover_is_dp():
    small = ModelConfig(hidden_size=256, intermediate_size=512,
                        num_hidden_layers=2, num_attention_heads=8,
                        num_key_value_heads=2, head_dim=32,
                        vocab_size=1024)
    p = plan_parallelism(small, 8, max_seq=1024)
    assert p.dp > 1
    assert p.tp * p.sp * p.ep * p.dp <= 8


def test_plan_mesh_builds_and_runs(mesh8):
    devs = [d for d in mesh8.devices.flat]
    p = Plan(tp=2, sp=1, ep=1, dp=4)
    m = p.mesh(devs)
    assert m.axis_names == ("dp", "tp") and m.shape["dp"] == 4
    # the mesh is usable for a real computation
    from jax.sharding import NamedSharding, PartitionSpec as P
    x = jax.device_put(jnp.ones((8, 16)),
                       NamedSharding(m, P("dp", None)))
    assert float(x.sum()) == 128.0


def test_divisibility_is_respected():
    odd = ModelConfig(hidden_size=768, intermediate_size=1536,
                      num_hidden_layers=4, num_attention_heads=12,
                      num_key_value_heads=3, head_dim=64,
                      vocab_size=32000)
    p = plan_parallelism(odd, 8)
    assert odd.num_key_value_heads % p.tp == 0   # tp=1 or 3
    assert odd.intermediate_size % p.tp == 0


def test_tp_never_violates_kv_heads_on_awkward_chip_counts():
    # review r3j finding 1: kv=8 on 6 chips must NOT pick tp=3
    big = ModelConfig(hidden_size=5120, intermediate_size=27648,
                      num_hidden_layers=64, num_attention_heads=64,
                      num_key_value_heads=8, head_dim=128,
                      vocab_size=151936)
    p = plan_parallelism(big, 6)
    assert big.num_key_value_heads % p.tp == 0
    assert big.intermediate_size % p.tp == 0


def test_oversized_model_with_odd_caps_warns():
    # review r3j finding 2: odd tp_cap must still grow (3 divides 3)
    # or warn — never silently return an over-HBM plan.
    huge = ModelConfig(hidden_size=8192, intermediate_size=24576,
                       num_hidden_layers=80, num_attention_heads=64,
                       num_key_value_heads=3, head_dim=128,
                       vocab_size=151936)
    p = plan_parallelism(huge, 8)
    assert p.tp == 3   # the only legal shard > 1
    assert any("WARNING" in r for r in p.reasons) or         (sum(1 for r in p.reasons if "params/chip" in r) == 1)


def test_unused_chips_are_reported():
    # review r3j finding 4: 128 experts on 12 chips → ep=4? divisors of
    # 128 ≤ 12 → 8; 12//8 = 1 → 4 idle chips must be REPORTED.
    moe = ModelConfig(hidden_size=2048, intermediate_size=0,
                      moe_intermediate_size=768, num_hidden_layers=48,
                      num_attention_heads=32, num_key_value_heads=4,
                      head_dim=128, vocab_size=151936, num_experts=128,
                      num_experts_per_tok=8)
    p = plan_parallelism(moe, 12)
    used = p.tp * p.sp * p.ep * p.dp
    if used < 12:
        assert any("unused" in r for r in p.reasons)
