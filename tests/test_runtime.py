"""Runtime tests (reference analog: test/nvidia/test_utils.py — but runnable
single-process, see conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import triton_dist_tpu as tdt
from triton_dist_tpu.runtime import (
    assert_allclose, local_shard, perf_func, symm_tensor)


def test_initialize_default(devices):
    ctx = tdt.initialize_distributed()
    assert ctx.world_size == 8
    assert ctx.axis_names == ("tp",)
    assert ctx.axis_size("tp") == 8
    tdt.finalize_distributed()
    with pytest.raises(RuntimeError):
        tdt.get_context()


def test_initialize_2d(devices):
    ctx = tdt.initialize_distributed({"dp": 2, "tp": 4})
    assert ctx.axis_size("dp") == 2
    assert ctx.axis_size("tp") == 4
    assert tdt.get_mesh().shape["tp"] == 4
    tdt.finalize_distributed()


def test_initialize_bad_shape(devices):
    with pytest.raises(ValueError):
        tdt.initialize_distributed({"tp": 3})


def test_symm_tensor(mesh8):
    buf = symm_tensor((4, 128), jnp.float32, mesh8, axis="tp")
    assert buf.shape == (8, 4, 128)
    # one addressable shard of local shape per device
    shards = buf.addressable_shards
    assert len(shards) == 8
    assert shards[0].data.shape == (1, 4, 128)
    assert local_shard(buf, 3).shape == (4, 128)


def test_perf_func():
    f = jax.jit(lambda: jnp.ones((64, 64)) * 2)
    out, ms = perf_func(lambda: f(), iters=3, warmup_iters=1)
    assert ms > 0
    assert float(out[0, 0]) == 2.0


def test_assert_allclose():
    a = np.ones((4, 4))
    assert_allclose(a, a + 1e-4)
    with pytest.raises(AssertionError):
        assert_allclose(a, a + 1.0)
    with pytest.raises(AssertionError):
        assert_allclose(a, np.ones((2, 2)))


def test_perturb_input_distinct_in_leaf_dtype():
    """The perturbation step must be representable in the LEAF's dtype —
    bf16's eps is 2^-7; a fixed 1e-4 step would round to exactly 1.0
    and silently reintroduce the tunnel-dedup bug (bench methodology,
    docs/perf.md)."""
    from triton_dist_tpu.runtime.utils import perturb_input
    tree = {"bf": jnp.ones((4,), jnp.bfloat16),
            "f32": jnp.ones((4,), jnp.float32),
            "ints": jnp.ones((4,), jnp.int32)}
    seen_bf, seen_f32 = set(), set()
    for i in range(1, 6):
        out = perturb_input(tree, i)
        seen_bf.add(float(out["bf"][0]))
        seen_f32.add(float(out["f32"][0]))
        # int leaves pass through untouched
        np.testing.assert_array_equal(np.asarray(out["ints"]),
                                      np.asarray(tree["ints"]))
    assert len(seen_bf) == 5, seen_bf      # distinct at every counter
    assert len(seen_f32) == 5
    assert all(v != 1.0 for v in seen_bf)  # never rounds back to 1.0


def test_perf_func_chained_measures_real_work():
    """Off-tunnel: the chained slope returns a positive per-step ms and
    the chain actually advances (step applied n2 times)."""
    from triton_dist_tpu.runtime.utils import perf_func_chained
    calls = [0]

    @jax.jit
    def step(x):
        return x * 1.0000001

    def counted(x):
        calls[0] += 1
        return step(x)

    ms = perf_func_chained(counted, jnp.ones((8, 8)), iters=(2, 6))
    assert ms > 0
    assert calls[0] >= 7   # warmup + n2 chain


class TestTopology:
    def test_describe_topology_mocked_coords(self):
        from triton_dist_tpu.runtime.topology import describe_topology

        class FakeDev:
            def __init__(self, coords, proc):
                self.platform = "tpu"
                self.device_kind = "TPU v5 lite"
                self.coords = coords
                self.process_index = proc

        devs = [FakeDev((x, y, 0), x // 2) for x in range(4)
                for y in range(2)]
        info = describe_topology(devs)
        assert info["n_devices"] == 8
        assert info["torus_extent"] == (4, 2, 1)
        assert info["coords_contiguous"] is True
        assert info["n_hosts"] == 2

    def test_describe_topology_cpu_no_coords(self):
        from triton_dist_tpu.runtime.topology import describe_topology
        info = describe_topology()
        assert info["platform"] == "cpu"
        assert "torus_extent" not in info

    def test_grid_cpu_falls_back_to_reshape(self):
        import numpy as np
        from triton_dist_tpu.runtime.topology import topology_aware_grid
        devs = np.array(jax.devices())
        grid = topology_aware_grid(devs, (2, 4))
        assert grid.shape == (2, 4)
        assert list(grid.ravel()) == list(devs)   # order preserved

    def test_grid_tpu_routes_through_mesh_utils(self, monkeypatch):
        """TPU device grids must go through mesh_utils (torus-aware
        placement); a mesh_utils failure must fall back, not raise."""
        import numpy as np
        from triton_dist_tpu.runtime import topology
        from jax.experimental import mesh_utils

        calls = []

        def spy(shape, devices=None):
            calls.append(shape)
            return np.array(devices).reshape(shape)

        monkeypatch.setattr(mesh_utils, "create_device_mesh", spy)

        class FakeTpu:
            platform = "tpu"

        # len must match jax.devices() for the TPU path to engage
        devs = np.array([FakeTpu() for _ in jax.devices()])
        grid = topology.topology_aware_grid(devs, (len(devs),))
        assert calls == [(len(devs),)]
        assert grid.shape == (len(devs),)

        def boom(shape, devices=None):
            raise RuntimeError("no topology info")

        monkeypatch.setattr(mesh_utils, "create_device_mesh", boom)
        grid = topology.topology_aware_grid(devs, (len(devs),))
        assert grid.shape == (len(devs),)   # reshape fallback
