"""Runtime tests (reference analog: test/nvidia/test_utils.py — but runnable
single-process, see conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import triton_dist_tpu as tdt
from triton_dist_tpu.runtime import (
    assert_allclose, local_shard, perf_func, symm_tensor)


def test_initialize_default(devices):
    ctx = tdt.initialize_distributed()
    assert ctx.world_size == 8
    assert ctx.axis_names == ("tp",)
    assert ctx.axis_size("tp") == 8
    tdt.finalize_distributed()
    with pytest.raises(RuntimeError):
        tdt.get_context()


def test_initialize_2d(devices):
    ctx = tdt.initialize_distributed({"dp": 2, "tp": 4})
    assert ctx.axis_size("dp") == 2
    assert ctx.axis_size("tp") == 4
    assert tdt.get_mesh().shape["tp"] == 4
    tdt.finalize_distributed()


def test_initialize_bad_shape(devices):
    with pytest.raises(ValueError):
        tdt.initialize_distributed({"tp": 3})


def test_symm_tensor(mesh8):
    buf = symm_tensor((4, 128), jnp.float32, mesh8, axis="tp")
    assert buf.shape == (8, 4, 128)
    # one addressable shard of local shape per device
    shards = buf.addressable_shards
    assert len(shards) == 8
    assert shards[0].data.shape == (1, 4, 128)
    assert local_shard(buf, 3).shape == (4, 128)


def test_perf_func():
    f = jax.jit(lambda: jnp.ones((64, 64)) * 2)
    out, ms = perf_func(lambda: f(), iters=3, warmup_iters=1)
    assert ms > 0
    assert float(out[0, 0]) == 2.0


def test_assert_allclose():
    a = np.ones((4, 4))
    assert_allclose(a, a + 1e-4)
    with pytest.raises(AssertionError):
        assert_allclose(a, a + 1.0)
    with pytest.raises(AssertionError):
        assert_allclose(a, np.ones((2, 2)))


def test_perturb_input_distinct_in_leaf_dtype():
    """The perturbation step must be representable in the LEAF's dtype —
    bf16's eps is 2^-7; a fixed 1e-4 step would round to exactly 1.0
    and silently reintroduce the tunnel-dedup bug (bench methodology,
    docs/perf.md)."""
    from triton_dist_tpu.runtime.utils import perturb_input
    tree = {"bf": jnp.ones((4,), jnp.bfloat16),
            "f32": jnp.ones((4,), jnp.float32),
            "ints": jnp.ones((4,), jnp.int32)}
    seen_bf, seen_f32 = set(), set()
    for i in range(1, 6):
        out = perturb_input(tree, i)
        seen_bf.add(float(out["bf"][0]))
        seen_f32.add(float(out["f32"][0]))
        # int leaves pass through untouched
        np.testing.assert_array_equal(np.asarray(out["ints"]),
                                      np.asarray(tree["ints"]))
    assert len(seen_bf) == 5, seen_bf      # distinct at every counter
    assert len(seen_f32) == 5
    assert all(v != 1.0 for v in seen_bf)  # never rounds back to 1.0


def test_perf_func_chained_measures_real_work():
    """Off-tunnel: the chained slope returns a positive per-step ms and
    the chain actually advances (step applied n2 times)."""
    from triton_dist_tpu.runtime.utils import perf_func_chained
    calls = [0]

    @jax.jit
    def step(x):
        return x * 1.0000001

    def counted(x):
        calls[0] += 1
        return step(x)

    ms = perf_func_chained(counted, jnp.ones((8, 8)), iters=(2, 6))
    assert ms > 0
    assert calls[0] >= 7   # warmup + n2 chain
