"""Sequence-parallel attention for long-context prefill.

TPU-native redesign of the reference's SP AG-attention
(python/triton_dist/kernels/nvidia/sp_ag_attention_inter_node.py: KV
allgather producer :115-257 overlapped with a flash-attn consumer waiting
per-KV-shard signals :259-499; intra-node zigzag variant
sp_ag_attention_intra_node.py) — plus **ring attention**, which the
reference lacks (SURVEY.md §5 flags it as the ICI-natural extension): on a
torus each ppermute hop rides one neighbor link, KV is never materialized
in full, and the online-softmax merge makes the schedule exact.

Three implementations:

- ``impl="ring"``  — ring attention: rotate the KV shard w-1 times; each
  step folds one shard into the running (m, l, acc) online-softmax state
  while the next shard is in flight (collective matmul schedule — XLA
  overlaps the ppermute with the einsums).
- ``impl="xla"``   — AG-KV golden: one ``all_gather`` of KV + a single
  masked attention pass (the reference's semantic baseline).
- ``impl="pallas"``— AG-KV with the fused Pallas ring all-gather
  (ops/allgather) producing KV, then the same local pass; the analog of
  the reference's copy-engine-AG + consumer split.

Causal masking uses global positions (query block r holds positions
``r*S_loc + [0, S_loc)``), so all variants are exact for causal and full
attention. Load imbalance of causal ring attention is noted: the zigzag
batch reorder of the intra-node reference variant is a host-side
permutation of the sequence dimension, exposed as ``zigzag_reorder`` /
``zigzag_restore`` helpers.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.ops.allgather import (
    AllGatherContext, create_allgather_context, all_gather)

_NEG = -1e30


@dataclasses.dataclass
class SpAttentionContext:
    """Analog of ``create_sp_ag_attention_context``
    (sp_ag_attention_inter_node.py): axis + AG workspace config."""
    mesh: Mesh
    axis: str = "sp"
    causal: bool = True
    interpret: bool | None = None

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]


def create_sp_attention_context(mesh: Mesh | None = None, axis: str = "sp",
                                causal: bool = True,
                                interpret: bool | None = None
                                ) -> SpAttentionContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return SpAttentionContext(mesh=mesh, axis=axis, causal=causal,
                              interpret=interpret)


def _chunk_scores(q, k, q_first, k_first, causal: bool):
    """Masked scores of one (Q block, KV block) pair.

    q: (B, K, G, Sq, D) fp32; k: (B, T, K, D); returns (B, K, G, Sq, T).
    """
    d = q.shape[-1]
    scores = jnp.einsum("bkgsd,btkd->bkgst", q,
                        k.astype(jnp.float32)) * (d ** -0.5)
    if causal:
        sq, t = scores.shape[-2], scores.shape[-1]
        q_pos = q_first + jnp.arange(sq)[:, None]
        k_pos = k_first + jnp.arange(t)[None, :]
        scores = jnp.where(q_pos >= k_pos, scores, _NEG)
    return scores


def _online_update(state, scores, v):
    """Fold one KV block into the (m, l, acc) online-softmax state."""
    m, l, acc = state
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    p = jnp.exp(scores - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l = l * corr + jnp.sum(p, axis=-1)
    acc = acc * corr[..., None] + jnp.einsum(
        "bkgst,btkd->bkgsd", p, v.astype(jnp.float32))
    return m_new, l, acc


def sp_ag_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    ctx: SpAttentionContext | None = None,
                    impl: str = "ring") -> jax.Array:
    """Sequence-parallel (self-)attention (functional entry, reference
    ``fused_sp_ag_attn_inter_node`` sp_ag_attention_inter_node.py:504).

    Args:
      q: (B, S, Hq, D), S sequence-sharded over ``ctx.axis``.
      k/v: (B, S, Hkv, D), sharded the same way.
    Returns:
      (B, S, Hq, D) outputs, sequence-sharded like q.
    """
    ctx = ctx or create_sp_attention_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    causal = ctx.causal
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    groups = hq // hkv
    assert s % world == 0
    s_loc = s // world

    def finish(state, qs_dtype):
        m, l, acc = state
        out = acc / jnp.maximum(l, 1e-20)[..., None]
        # (B, K, G, S, D) → (B, S, Hq, D)
        return out.transpose(0, 3, 1, 2, 4).reshape(
            b, s_loc, hq, d).astype(qs_dtype)

    def local_q(qs):
        # (B, S_loc, Hq, D) → (B, K, G, S_loc, D) fp32
        return qs.reshape(b, s_loc, hkv, groups, d
                          ).transpose(0, 2, 3, 1, 4).astype(jnp.float32)

    def ag_body(qs, ks, vs):
        me = lax.axis_index(axis)
        kg = lax.all_gather(ks, axis, axis=1, tiled=True)
        vg = lax.all_gather(vs, axis, axis=1, tiled=True)
        qf = local_q(qs)
        scores = _chunk_scores(qf, kg, me * s_loc, 0, causal)
        m = jnp.max(scores, axis=-1)
        p = jnp.exp(scores - m[..., None])
        l = jnp.sum(p, axis=-1)
        acc = jnp.einsum("bkgst,btkd->bkgsd", p, vg.astype(jnp.float32))
        return finish((m, l, acc), qs.dtype)

    def ring_body(qs, ks, vs):
        me = lax.axis_index(axis)
        qf = local_q(qs)
        perm = [(i, (i + 1) % world) for i in range(world)]
        state = (jnp.full((b, hkv, groups, s_loc), _NEG, jnp.float32),
                 jnp.zeros((b, hkv, groups, s_loc), jnp.float32),
                 jnp.zeros((b, hkv, groups, s_loc, d), jnp.float32))

        def step(i, carry):
            state, kc, vc = carry
            src = lax.rem(me - i + world, world)
            # Next hop first — XLA overlaps it with this step's einsums.
            kn = lax.ppermute(kc, axis, perm)
            vn = lax.ppermute(vc, axis, perm)
            scores = _chunk_scores(qf, kc, me * s_loc, src * s_loc, causal)
            state = _online_update(state, scores, vc)
            return state, kn, vn

        state, kc, vc = lax.fori_loop(0, world - 1, step, (state, ks, vs))
        src = lax.rem(me - (world - 1) + world, world)
        scores = _chunk_scores(qf, kc, me * s_loc, src * s_loc, causal)
        state = _online_update(state, scores, vc)
        return finish(state, qs.dtype)

    if impl in ("xla", "ring") or world == 1:
        body = ag_body if (impl == "xla" or world == 1) else ring_body
        f = jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(None, axis), P(None, axis), P(None, axis)),
            out_specs=P(None, axis), check_vma=False)
        return f(q, k, v)

    if impl == "pallas":
        # Fused Pallas ring AG of KV (the copy-engine producer analog),
        # then one local masked pass.
        ag_ctx = create_allgather_context(mesh, axis,
                                          interpret=ctx.interpret)
        # Flatten KV to 2-D row-sharded layout for the AG kernel.
        kf = k.transpose(1, 0, 2, 3).reshape(s, b * hkv * d)
        vf = v.transpose(1, 0, 2, 3).reshape(s, b * hkv * d)
        kg = all_gather(kf, ag_ctx, impl="pallas")
        vg = all_gather(vf, ag_ctx, impl="pallas")
        kg = kg.reshape(s, b, hkv, d).transpose(1, 0, 2, 3)
        vg = vg.reshape(s, b, hkv, d).transpose(1, 0, 2, 3)

        def body(qs, kgs, vgs):
            me = lax.axis_index(axis)
            qf = local_q(qs)
            scores = _chunk_scores(qf, kgs, me * s_loc, 0, causal)
            m = jnp.max(scores, axis=-1)
            p = jnp.exp(scores - m[..., None])
            l = jnp.sum(p, axis=-1)
            acc = jnp.einsum("bkgst,btkd->bkgsd", p,
                             vgs.astype(jnp.float32))
            return finish((m, l, acc), qs.dtype)

        f = jax.shard_map(body, mesh=mesh,
                          in_specs=(P(None, axis), P(), P()),
                          out_specs=P(None, axis), check_vma=False)
        return f(q, kg, vg)

    raise ValueError(f"unknown impl {impl!r}")


def zigzag_reorder(x: jax.Array, world: int, seq_axis: int = 1) -> jax.Array:
    """Zigzag sequence permutation for causal load balance (the reference's
    intra-node zigzag batch schedule, sp_ag_attention_intra_node.py):
    shard r gets chunks (r, 2w-1-r) so early and late positions pair up."""
    s = x.shape[seq_axis]
    assert s % (2 * world) == 0
    c = s // (2 * world)
    idx = []
    for r in range(world):
        idx.extend(range(r * c, (r + 1) * c))
        idx.extend(range((2 * world - 1 - r) * c, (2 * world - r) * c))
    return jnp.take(x, jnp.array(idx), axis=seq_axis)


def zigzag_restore(x: jax.Array, world: int, seq_axis: int = 1) -> jax.Array:
    """Inverse of :func:`zigzag_reorder`."""
    s = x.shape[seq_axis]
    c = s // (2 * world)
    idx = []
    for r in range(world):
        idx.extend(range(r * c, (r + 1) * c))
        idx.extend(range((2 * world - 1 - r) * c, (2 * world - r) * c))
    inv = [0] * s
    for new, old in enumerate(
            [i for blk in idx for i in ([blk] if isinstance(blk, int) else blk)]):
        inv[old] = new
    return jnp.take(x, jnp.array(inv), axis=seq_axis)
