"""Sequence-parallel attention for long-context prefill.

TPU-native redesign of the reference's SP AG-attention
(python/triton_dist/kernels/nvidia/sp_ag_attention_inter_node.py: KV
allgather producer :115-257 overlapped with a flash-attn consumer waiting
per-KV-shard signals :259-499; intra-node zigzag variant
sp_ag_attention_intra_node.py) — plus **ring attention**, which the
reference lacks (SURVEY.md §5 flags it as the ICI-natural extension): on a
torus each ppermute hop rides one neighbor link, KV is never materialized
in full, and the online-softmax merge makes the schedule exact.

Five implementations:

- ``impl="ring"``  — ring attention: rotate the KV shard w-1 times; each
  step folds one shard into the running (m, l, acc) online-softmax state
  while the next shard is in flight (collective matmul schedule — XLA
  overlaps the ppermute with the einsums).
- ``impl="ulysses"`` — all-to-all head parallelism (DeepSpeed-Ulysses
  style; also absent in the reference): trade the sequence sharding for
  a head sharding, one exact full-sequence pass on the local heads,
  trade back. Needs heads divisible by the world size.
- ``impl="xla"``   — AG-KV golden: one ``all_gather`` of KV + a single
  masked attention pass (the reference's semantic baseline).
- ``impl="pallas"``— ONE fused kernel: in-kernel ring AG of KV chunks
  (per-chunk recv semaphores — the reference's per-shard ``dl.wait``)
  feeding a tiled flash loop that streams KV subtiles from the HBM
  workspace (``_sp_fused_kernel``; reference
  sp_ag_attention_inter_node.py:259-499).
- ``impl="ag_pallas"`` — two-step: fused Pallas ring all-gather
  (ops/allgather) producing KV, then one local masked pass; the analog
  of the reference's copy-engine-AG + consumer split.

Causal masking uses global positions (query block r holds positions
``r*S_loc + [0, S_loc)``), so all variants are exact for causal and full
attention. Load imbalance of causal ring attention is noted: the zigzag
batch reorder of the intra-node reference variant is a host-side
permutation of the sequence dimension, exposed as ``zigzag_reorder`` /
``zigzag_restore`` helpers.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.resilience import resilient
from triton_dist_tpu.ops.allgather import (
    AllGatherContext, create_allgather_context, all_gather)
from triton_dist_tpu.ops.common import (
    any_spec,
    comm_params,
    nestable_shard_map,
    resolve_interpret,
    sync_interpret)

_NEG = -1e30


@dataclasses.dataclass
class SpAttentionContext:
    """Analog of ``create_sp_ag_attention_context``
    (sp_ag_attention_inter_node.py): axis + AG workspace config.

    ``head_axis``: optional second mesh axis sharding the HEAD dim (2-D
    tp×sp attention — heads tensor-parallel, sequence ring-parallel).
    Supported by the xla/ring impls, whose per-head math is independent;
    the ulysses and fused-Pallas impls require ``head_axis=None``.
    """
    mesh: Mesh
    axis: str = "sp"
    causal: bool = True
    interpret: bool | None = None
    head_axis: str | None = None
    # VMEM budget for the fused kernel's resident q-group + state
    # (bytes): the wrapper sizes the slab group so q_buf + (m, l, acc)
    # + the fixed KV tiles/output stage fit (BENCH_r02 class: an
    # over-budget residency must never reach the compiler).
    vmem_budget: int = 10 * 1024 * 1024

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]


def create_sp_attention_context(mesh: Mesh | None = None, axis: str = "sp",
                                causal: bool = True,
                                interpret: bool | None = None,
                                head_axis: str | None = None
                                ) -> SpAttentionContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return SpAttentionContext(mesh=mesh, axis=axis, causal=causal,
                              interpret=interpret, head_axis=head_axis)


def _chunk_scores(q, k, q_first, k_first, causal: bool, kv_live=None):
    """Masked scores of one (Q block, KV block) pair.

    q: (B, K, G, Sq, D); k: (B, T, K, D); returns (B, K, G, Sq, T) fp32.
    When q and k share a dtype the dot runs in it (MXU-native; the f32
    accumulation makes scores bit-identical to an upcast-first dot);
    precision-mismatched inputs keep the exact f32 path (casting q
    down would silently change results — review r4b-4).
    ``kv_live``: global number of live KV positions — KV block entries
    at or past it are masked (cache-aware chunked prefill, where the
    KV blocks come from a partially-filled cache).
    """
    d = q.shape[-1]
    dt = k.dtype if q.dtype == k.dtype else jnp.float32
    scores = jnp.einsum("bkgsd,btkd->bkgst", q.astype(dt), k.astype(dt),
                        preferred_element_type=jnp.float32) * (d ** -0.5)
    sq, t = scores.shape[-2], scores.shape[-1]
    k_pos = k_first + jnp.arange(t)[None, :]
    mask = jnp.ones((sq, t), bool)
    if causal:
        q_pos = q_first + jnp.arange(sq)[:, None]
        mask = q_pos >= k_pos
    if kv_live is not None:
        mask = mask & (k_pos < kv_live)
    return jnp.where(mask, scores, _NEG)


def _online_update(state, scores, v):
    """Fold one KV block into the (m, l, acc) online-softmax state.
    The PV product runs in v's dtype (f32 accumulation) — standard
    flash practice; exact for f32 caches."""
    m, l, acc = state
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    p = jnp.exp(scores - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l = l * corr + jnp.sum(p, axis=-1)
    acc = acc * corr[..., None] + jnp.einsum(
        "bkgst,btkd->bkgsd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return m_new, l, acc


def _sp_fused_kernel(q_hbm, k_ref, v_ref, o_hbm, kw_hbm, vw_hbm, q_buf,
                     k_sub, v_sub, m_buf, l_buf, acc_buf, o_stage,
                     copy_sem, q_sem, ks_sem, vs_sem, o_sem, send_sem,
                     recv_sem, *, axis: str, world: int, batch: int,
                     s_loc: int, hkv: int, groups: int, d: int,
                     sq_blk: int, t_sub: int, causal: bool, n_res: int):
    """Fused SP prefill attention: in-kernel ring AG of KV chunks feeding
    a tiled flash loop.

    TPU shape of the reference's fused consumer
    (sp_ag_attention_inter_node.py:259-499: flash-attn blocks that
    ``dl.wait`` per-KV-shard signals while copy engines run the AG): the
    per-shard signal wait becomes the chunk ``wait_recv`` at the top of
    each ring step; the copy-engine producer becomes the in-kernel remote
    DMA forwarding the freshest chunk while the MXU consumes it; the
    flash inner loop streams (B, t_sub, K, D) KV subtiles from the HBM
    workspace through double-buffered VMEM and updates per-(q-tile)
    online-softmax state.

    Causal skip: chunks whose positions all exceed every local query
    position contribute nothing and skip compute entirely (they are
    still forwarded — peers need them), mirroring the reference's
    early-exit blocks.

    VMEM discipline: q lives in HBM pre-slabbed and is processed in
    GROUPS of ``n_res`` slabs — each group's q + fp32 (m, l, acc) state
    are VMEM-resident, sized to the budget by the wrapper (the bench
    prefill shape put ~50 MB of q+state against the 16 MB chip —
    BENCH_r02's class). The KV ring runs ONCE, during group 0 (its
    forwarding fills the HBM workspace); later groups re-consume the
    landed chunks with no further communication. K/V inputs, the AG
    workspace and the output stay in HBM (outputs drain through a
    double-buffered stage), so sequence length is unbounded
    (tests/test_vmem_budget.py checks 16k/8-rank AND the bench shape).
    """
    me = lax.axis_index(axis)
    right = lax.rem(me + 1, world)
    n_sub = s_loc // t_sub
    n_q = s_loc // sq_blk
    n_slabs = n_q * hkv
    scale = d ** -0.5

    # local chunk → workspace slot me (HBM→HBM)
    for ref, hbm, sem_i in ((k_ref, kw_hbm, 0), (v_ref, vw_hbm, 1)):
        cp = pltpu.make_async_copy(ref, hbm.at[me], copy_sem.at[sem_i])
        cp.start()
    for sem_i, (ref, hbm) in enumerate(((k_ref, kw_hbm), (v_ref, vw_hbm))):
        pltpu.make_async_copy(ref, hbm.at[me], copy_sem.at[sem_i]).wait()
    if world > 1:
        dl.barrier_all(axis)

    def chunk_copy(idx):
        return [dl.remote_copy(hbm.at[idx], hbm.at[idx], right,
                               send_sem.at[idx, i], recv_sem.at[idx, i],
                               axis=axis)
                for i, hbm in enumerate((kw_hbm, vw_hbm))]

    def k_dma(slot, src, j):
        return pltpu.make_async_copy(
            kw_hbm.at[src, :, pl.ds(j * t_sub, t_sub)], k_sub.at[slot],
            ks_sem.at[slot])

    def v_dma(slot, src, j):
        return pltpu.make_async_copy(
            vw_hbm.at[src, :, pl.ds(j * t_sub, t_sub)], v_sub.at[slot],
            vs_sem.at[slot])

    # Row-folded q tiles: head h of q-tile i is a (B, sq_blk·G, D) slab —
    # every value in the flash inner loop stays ≤3-D with B as the single
    # dot batch dim (Mosaic: one-batch-dim matmuls, no 5-D relayouts).
    # q arrives PRE-SLABBED as (n_q·hkv, B, rows, D) in HBM — the
    # (seq, head) → slab permutation runs in XLA outside the kernel, so
    # the kernel never reshapes (the in-kernel middle-dim reshape was
    # the one construct the proven-compiling flash-decode kernels don't
    # use).
    rows = sq_blk * groups

    def consume_chunk(src, slabs):
        """Fold chunk ``src`` (already in the HBM workspace) into the
        resident group's online state, streaming KV subtiles through
        VMEM.

        The (m, l, acc) state lives in VMEM *scratch refs* indexed by a
        static leading (group-local slab) index and mutated in place —
        round 2's ``dynamic_slice_in_dim`` loop-carried state failed
        Mosaic (VERDICT r2 weak 3), and a pytree-of-tiles fori_loop
        carry blows the VMEM stack (the compiler double-buffers the
        whole carry). The two-batch-dim einsums are unrolled over the
        KV-head dim so each dot keeps only B as the batch dim (same fix
        as ops/flash_decode._qk_scores) with the (sq, G) query dims
        folded into rows.
        """
        k_dma(0, src, 0).start()
        v_dma(0, src, 0).start()

        # Per-row query position for the causal mask: row r of a slab is
        # query (r // G) of the tile.
        row_q = jnp.arange(rows)[:, None] // groups       # (rows, 1)

        def sub_step(j, _):
            slot = lax.rem(j, 2)

            @pl.when(j + 1 < n_sub)
            def _():
                k_dma(lax.rem(j + 1, 2), src, j + 1).start()
                v_dma(lax.rem(j + 1, 2), src, j + 1).start()
            k_dma(slot, src, j).wait()
            v_dma(slot, src, j).wait()
            k_first = src * s_loc + j * t_sub
            ktile = k_sub[slot]                   # (B, t_sub, K, D)
            vtile = v_sub[slot]

            for li, gidx in enumerate(slabs):     # static slab loop
                i, h = divmod(gidx, hkv)
                # MXU-native dtype dots when q matches KV (bf16 matmul
                # is up to 3x f32 on TPU; the f32 accumulate keeps
                # scores bit-identical to an upcast-first dot); a
                # mismatched q keeps the exact f32 path (r4b-4).
                dt = (k_sub.dtype if q_buf.dtype == k_sub.dtype
                      else jnp.float32)
                kt = ktile[:, :, h, :].astype(dt)
                vt = vtile[:, :, h, :].astype(dt)
                s_blk = lax.dot_general(
                    q_buf[li].astype(dt), kt,
                    (((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32) * scale
                if causal:
                    q_pos = me * s_loc + i * sq_blk + row_q
                    k_pos = k_first + jnp.arange(t_sub)[None, :]
                    s_blk = jnp.where((q_pos >= k_pos)[None],
                                      s_blk, _NEG)
                mi, li_, ai = m_buf[li], l_buf[li], acc_buf[li]
                m_new = jnp.maximum(mi, jnp.max(s_blk, axis=-1))
                p = jnp.exp(s_blk - m_new[..., None])
                corr = jnp.exp(mi - m_new)
                pv = lax.dot_general(
                    p.astype(vt.dtype), vt, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)
                m_buf[li] = m_new
                l_buf[li] = li_ * corr + jnp.sum(p, axis=-1)
                acc_buf[li] = ai * corr[..., None] + pv
            return _

        lax.fori_loop(0, n_sub, sub_step, None)

    def o_dma(slot, gidx):
        # Slab-shaped output: one contiguous (B, rows, D) block per
        # (q-tile, head) — the un-permute back to (B, S, H, D) runs in
        # XLA outside the kernel.
        return pltpu.make_async_copy(
            o_stage.at[slot], o_hbm.at[gidx], o_sem.at[slot])

    n_groups = -(-n_slabs // n_res)
    for g in range(n_groups):                     # static group loop
        slabs = list(range(g * n_res, min((g + 1) * n_res, n_slabs)))
        glen = len(slabs)
        # One contiguous DMA loads the group's q slabs.
        qcp = pltpu.make_async_copy(
            q_hbm.at[pl.ds(g * n_res, glen)], q_buf.at[pl.ds(0, glen)],
            q_sem)
        qcp.start()
        qcp.wait()
        for li in range(glen):
            m_buf[li] = jnp.full((batch, rows), _NEG, jnp.float32)
            l_buf[li] = jnp.zeros((batch, rows), jnp.float32)
            acc_buf[li] = jnp.zeros((batch, rows, d), jnp.float32)

        if g == 0:
            # Group 0 drives the ring: forward each chunk while
            # consuming it; afterwards the whole gathered KV sits in
            # this device's workspace for the later groups.
            def ring_step(s, _):
                cur = lax.rem(me - s + world, world)
                nxt = lax.rem(me - s - 1 + world, world)
                if world > 1:
                    @pl.when(s < world - 1)
                    def _():
                        for c in chunk_copy(cur):
                            c.start()   # forward current chunk (ICI)
                if causal:
                    # Chunks strictly in the future contribute nothing.
                    @pl.when(cur <= me)
                    def _():
                        consume_chunk(cur, slabs)
                else:
                    consume_chunk(cur, slabs)
                if world > 1:
                    @pl.when(s < world - 1)
                    def _():
                        for c in chunk_copy(nxt):
                            c.wait_recv()   # next chunk must have landed
                return _

            lax.fori_loop(0, world, ring_step, None)

            if world > 1:
                def drain(s, _):
                    for c in chunk_copy(lax.rem(me - s + world, world)):
                        c.wait_send()
                    return _
                lax.fori_loop(0, world - 1, drain, None)
        else:
            # Later groups: every chunk already landed — no copies.
            def replay_step(s, _):
                cur = lax.rem(me - s + world, world)
                if causal:
                    @pl.when(cur <= me)
                    def _():
                        consume_chunk(cur, slabs)
                else:
                    consume_chunk(cur, slabs)
                return _

            lax.fori_loop(0, world, replay_step, None)

        for li, gidx in enumerate(slabs):
            out = acc_buf[li] / jnp.maximum(l_buf[li], 1e-20)[..., None]
            slot = li % 2
            if li >= 2:
                o_dma(slot, slabs[li - 2]).wait()
            o_stage[slot] = out.astype(o_stage.dtype)
            o_dma(slot, gidx).start()
        for li in range(max(0, glen - 2), glen):
            o_dma(li % 2, slabs[li]).wait()


def sp_ag_attention_fused(q: jax.Array, k: jax.Array, v: jax.Array,
                          ctx: SpAttentionContext | None = None,
                          sq_blk: int = 128, t_sub: int = 128) -> jax.Array:
    """Single fused Pallas kernel for SP prefill attention — ``impl=
    "pallas"`` of :func:`sp_ag_attention` routes here. See
    :func:`_sp_fused_kernel`."""
    ctx = ctx or create_sp_attention_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    groups = hq // hkv
    assert s % world == 0
    s_loc = s // world
    t_sub = min(t_sub, s_loc)
    while s_loc % t_sub:
        t_sub //= 2
    sq_blk = min(sq_blk, s_loc)
    while s_loc % sq_blk:
        sq_blk //= 2
    interpret = resolve_interpret(ctx.interpret)

    n_q = s_loc // sq_blk
    rows = sq_blk * groups
    n_slabs = n_q * hkv

    # Size the resident q-group to the VMEM budget (the bench prefill
    # shape put ~50 MB of q+state on a 16 MB chip — BENCH_r02's class).
    item = q.dtype.itemsize
    fixed = (2 * 2 * b * t_sub * hkv * d * k.dtype.itemsize   # k/v tiles
             + 2 * b * rows * d * item)                       # o stage
    per_slab = b * rows * (d * 4 + 8        # acc + m + l (fp32)
                           + d * item)      # q_buf slab
    n_res = max(1, min(n_slabs,
                       (ctx.vmem_budget - fixed) // per_slab))

    kernel = functools.partial(
        _sp_fused_kernel, axis=axis, world=world, batch=b, s_loc=s_loc,
        hkv=hkv, groups=groups, d=d, sq_blk=sq_blk, t_sub=t_sub,
        causal=ctx.causal, n_res=n_res)

    def body(qs, ks, vs):
        # (B, S_loc, Hq, D) → (n_q·hkv, B, sq_blk·G, D): slab s = (i, h)
        # holds q-tile i of kv-head h with (seq, group) folded into rows.
        # This permutation (and its inverse on the output) runs in XLA so
        # the kernel body needs no reshapes at all.
        qp = qs.reshape(b, n_q, sq_blk, hkv, groups, d)
        qp = qp.transpose(1, 3, 0, 2, 4, 5).reshape(n_slabs, b, rows, d)
        out, *_ = pl.pallas_call(
            kernel,
            out_shape=(jax.ShapeDtypeStruct((n_slabs, b, rows, d),
                                            q.dtype),
                       jax.ShapeDtypeStruct((world, b, s_loc, hkv, d),
                                            k.dtype),
                       jax.ShapeDtypeStruct((world, b, s_loc, hkv, d),
                                            v.dtype)),
            in_specs=[any_spec(), any_spec(), any_spec()],
            out_specs=(any_spec(), any_spec(), any_spec()),
            scratch_shapes=[
                pltpu.VMEM((n_res, b, rows, d), q.dtype),
                pltpu.VMEM((2, b, t_sub, hkv, d), k.dtype),
                pltpu.VMEM((2, b, t_sub, hkv, d), v.dtype),
                pltpu.VMEM((n_res, b, rows), jnp.float32),
                pltpu.VMEM((n_res, b, rows), jnp.float32),
                pltpu.VMEM((n_res, b, rows, d), jnp.float32),
                pltpu.VMEM((2, b, rows, d), q.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA(()),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((world, 2)),
                pltpu.SemaphoreType.DMA((world, 2)),
            ],
            # comm_params raises Mosaic's scoped-VMEM limit to
            # common.VMEM_LIMIT_BYTES: the default 16 MB cap rejected
            # this kernel's round-5 on-chip compile at 16.14 MB scoped
            # for ~7.4 MB of declared scratch (see the constants in
            # ops/common.py for the measured overhead factor).
            compiler_params=comm_params(collective_id=6, world=world),
            interpret=interpret,
        )(qp, ks, vs)
        out = out.reshape(n_q, hkv, b, sq_blk, groups, d)
        return out.transpose(2, 0, 3, 1, 4, 5).reshape(b, s_loc, hq, d)

    f = nestable_shard_map(body, mesh=mesh,
                      in_specs=(P(None, axis),) * 3,
                      out_specs=P(None, axis), check_vma=False)
    return sync_interpret(f(q, k, v), interpret)


@resilient("sp_attention", fused_impls=("pallas", "ag_pallas"))
def sp_ag_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    ctx: SpAttentionContext | None = None,
                    impl: str = "ring", q_offset=0,
                    kv_len=None) -> jax.Array:
    """Sequence-parallel (self-)attention (functional entry, reference
    ``fused_sp_ag_attn_inter_node`` sp_ag_attention_inter_node.py:504).

    Args:
      q: (B, S, Hq, D), S sequence-sharded over ``ctx.axis``.
      k/v: (B, T, Hkv, D), sharded the same way. T may EXCEED S
        (cache-aware chunked prefill: k/v are the full sequence-sharded
        cache, q is one chunk).
      q_offset: global position of q's first row (chunk base; 0 for
        whole-sequence prefill). ring/xla impls only.
      kv_len: number of live KV positions (<= T); positions beyond are
        masked. Default: all of T.
    Returns:
      (B, S, Hq, D) outputs, sequence-sharded like q.
    """
    ctx = ctx or create_sp_attention_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    causal = ctx.causal
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    groups = hq // hkv
    assert s % world == 0
    s_loc = s // world
    t = k.shape[1]
    assert t % world == 0
    t_loc = t // world
    chunked = (kv_len is not None or t != s
               or not (isinstance(q_offset, int) and q_offset == 0))
    if chunked:
        assert impl in ("xla", "ring"), (
            f"q_offset/kv_len (chunked prefill) support impl 'ring' and "
            f"'xla', not {impl!r}")
    q_offset = jnp.asarray(q_offset, jnp.int32)
    kv_len = jnp.asarray(t if kv_len is None else kv_len, jnp.int32)

    def finish(state, qs_dtype):
        m, l, acc = state
        out = acc / jnp.maximum(l, 1e-20)[..., None]
        # (B, K, G, S, D) → (B, S, hq_l, D) — hq_l is the LOCAL head
        # count (= Hq/|head_axis| under 2-D tp×sp sharding).
        kl, gl = out.shape[1], out.shape[2]
        return out.transpose(0, 3, 1, 2, 4).reshape(
            b, s_loc, kl * gl, d).astype(qs_dtype)

    def local_q(qs, hkv_l):
        # (B, S_loc, hq_l, D) → (B, K, G, S_loc, D); dtype preserved —
        # the scores dot runs MXU-native in the KV dtype.
        return qs.reshape(b, s_loc, hkv_l, qs.shape[2] // hkv_l, d
                          ).transpose(0, 2, 3, 1, 4)

    def ag_body(qs, ks, vs):
        me = lax.axis_index(axis)
        kg = lax.all_gather(ks, axis, axis=1, tiled=True)
        vg = lax.all_gather(vs, axis, axis=1, tiled=True)
        qf = local_q(qs, ks.shape[2])
        scores = _chunk_scores(qf, kg, q_offset + me * s_loc, 0, causal,
                               kv_live=kv_len)
        m = jnp.max(scores, axis=-1)
        p = jnp.exp(scores - m[..., None])
        l = jnp.sum(p, axis=-1)
        acc = jnp.einsum("bkgst,btkd->bkgsd", p.astype(vg.dtype), vg,
                         preferred_element_type=jnp.float32)
        return finish((m, l, acc), qs.dtype)

    def ring_body(qs, ks, vs):
        me = lax.axis_index(axis)
        hkv_l, gl = ks.shape[2], qs.shape[2] // ks.shape[2]
        qf = local_q(qs, hkv_l)
        perm = [(i, (i + 1) % world) for i in range(world)]
        state = (jnp.full((b, hkv_l, gl, s_loc), _NEG, jnp.float32),
                 jnp.zeros((b, hkv_l, gl, s_loc), jnp.float32),
                 jnp.zeros((b, hkv_l, gl, s_loc, d), jnp.float32))

        def step(i, carry):
            state, kc, vc = carry
            src = lax.rem(me - i + world, world)
            # Next hop first — XLA overlaps it with this step's einsums.
            kn = lax.ppermute(kc, axis, perm)
            vn = lax.ppermute(vc, axis, perm)
            scores = _chunk_scores(qf, kc, q_offset + me * s_loc,
                                   src * t_loc, causal, kv_live=kv_len)
            state = _online_update(state, scores, vc)
            return state, kn, vn

        state, kc, vc = lax.fori_loop(0, world - 1, step, (state, ks, vs))
        src = lax.rem(me - (world - 1) + world, world)
        scores = _chunk_scores(qf, kc, q_offset + me * s_loc,
                               src * t_loc, causal, kv_live=kv_len)
        state = _online_update(state, scores, vc)
        return finish(state, qs.dtype)

    if impl in ("xla", "ring"):
        body = ag_body if (impl == "xla" or world == 1) else ring_body
        # Optional 2-D sharding: heads split over ctx.head_axis on top
        # of the sequence split — the per-(kv-head, group) math never
        # mixes heads, so the same bodies run on the head-local slice.
        spec = P(None, axis, ctx.head_axis, None)
        f = nestable_shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=spec, check_vma=False)
        return f(q, k, v)
    assert ctx.head_axis is None, (
        f"impl={impl!r} does not support head_axis (use 'ring' or 'xla')")

    if impl == "ulysses":
        # All-to-all head parallelism (DeepSpeed-Ulysses style; absent in
        # the reference — SURVEY.md §2.9 "CP/Ulysses: Absent"): exchange
        # the sequence sharding for a head sharding, run full-sequence
        # attention on the local head subset, exchange back. Four
        # all-to-alls (q/k/v in, out back), each moving S_loc*H/w
        # elements per device — less traffic than AG-KV when heads are
        # plentiful, and every score is computed exactly once (no
        # online-softmax merges).
        assert hkv % world == 0 and hq % world == 0, (
            f"ulysses needs heads divisible by world: hq={hq}, "
            f"hkv={hkv}, world={world}")

        def ulysses_body(qs, ks, vs):
            # (B, S_loc, H, D) -> (B, S, H/w, D): split heads, gather seq.
            # Contiguous head split keeps GQA groups aligned (q head
            # h = k*groups + g, so Hq/w q-heads pair with Hkv/w kv-heads).
            qh = lax.all_to_all(qs, axis, split_axis=2, concat_axis=1,
                                tiled=True)
            kh = lax.all_to_all(ks, axis, split_axis=2, concat_axis=1,
                                tiled=True)
            vh = lax.all_to_all(vs, axis, split_axis=2, concat_axis=1,
                                tiled=True)
            hkv_loc = hkv // world
            qf = qh.reshape(b, s, hkv_loc, groups, d
                            ).transpose(0, 2, 3, 1, 4)
            scores = _chunk_scores(qf, kh, 0, 0, causal)
            m = jnp.max(scores, axis=-1)
            p = jnp.exp(scores - m[..., None])
            l = jnp.sum(p, axis=-1)
            acc = jnp.einsum("bkgst,btkd->bkgsd", p.astype(vh.dtype), vh,
                             preferred_element_type=jnp.float32)
            out = (acc / jnp.maximum(l, 1e-20)[..., None]
                   ).transpose(0, 3, 1, 2, 4).reshape(
                       b, s, hq // world, d).astype(qs.dtype)
            # (B, S, H/w, D) -> (B, S_loc, H, D): split seq, gather heads.
            return lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        f = nestable_shard_map(
            ulysses_body, mesh=mesh,
            in_specs=(P(None, axis), P(None, axis), P(None, axis)),
            out_specs=P(None, axis), check_vma=False)
        return f(q, k, v)

    if impl == "pallas":
        # Single fused kernel: in-kernel ring AG + tiled flash consumer.
        return sp_ag_attention_fused(q, k, v, ctx)

    if impl == "ag_pallas":
        # Two-step: fused Pallas ring AG of KV (the copy-engine producer
        # analog), then one local masked pass.
        ag_ctx = create_allgather_context(mesh, axis,
                                          interpret=ctx.interpret)
        # Flatten KV to 2-D row-sharded layout for the AG kernel.
        kf = k.transpose(1, 0, 2, 3).reshape(s, b * hkv * d)
        vf = v.transpose(1, 0, 2, 3).reshape(s, b * hkv * d)
        kg = all_gather(kf, ag_ctx, impl="pallas")
        vg = all_gather(vf, ag_ctx, impl="pallas")
        kg = kg.reshape(s, b, hkv, d).transpose(1, 0, 2, 3)
        vg = vg.reshape(s, b, hkv, d).transpose(1, 0, 2, 3)

        def body(qs, kgs, vgs):
            me = lax.axis_index(axis)
            qf = local_q(qs, hkv)
            scores = _chunk_scores(qf, kgs, me * s_loc, 0, causal)
            m = jnp.max(scores, axis=-1)
            p = jnp.exp(scores - m[..., None])
            l = jnp.sum(p, axis=-1)
            acc = jnp.einsum("bkgst,btkd->bkgsd", p.astype(vgs.dtype),
                             vgs, preferred_element_type=jnp.float32)
            return finish((m, l, acc), qs.dtype)

        f = nestable_shard_map(body, mesh=mesh,
                          in_specs=(P(None, axis), P(), P()),
                          out_specs=P(None, axis), check_vma=False)
        return f(q, kg, vg)

    raise ValueError(f"unknown impl {impl!r}")


def zigzag_reorder(x: jax.Array, world: int, seq_axis: int = 1) -> jax.Array:
    """Zigzag sequence permutation for causal load balance (the reference's
    intra-node zigzag batch schedule, sp_ag_attention_intra_node.py):
    shard r gets chunks (r, 2w-1-r) so early and late positions pair up."""
    s = x.shape[seq_axis]
    assert s % (2 * world) == 0
    c = s // (2 * world)
    idx = []
    for r in range(world):
        idx.extend(range(r * c, (r + 1) * c))
        idx.extend(range((2 * world - 1 - r) * c, (2 * world - r) * c))
    return jnp.take(x, jnp.array(idx), axis=seq_axis)


def zigzag_restore(x: jax.Array, world: int, seq_axis: int = 1) -> jax.Array:
    """Inverse of :func:`zigzag_reorder`."""
    s = x.shape[seq_axis]
    c = s // (2 * world)
    idx = []
    for r in range(world):
        idx.extend(range(r * c, (r + 1) * c))
        idx.extend(range((2 * world - 1 - r) * c, (2 * world - r) * c))
    inv = [0] * s
    for new, old in enumerate(
            [i for blk in idx for i in ([blk] if isinstance(blk, int) else blk)]):
        inv[old] = new
    return jnp.take(x, jnp.array(inv), axis=seq_axis)
