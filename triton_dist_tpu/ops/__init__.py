"""Overlapping kernel library (reference L5: python/triton_dist/kernels/).

Every op follows the reference's API shape: a ``create_*_context`` builder
that allocates persistent workspaces/configs, plus a functional entry point
(e.g. ``ag_gemm``, ``gemm_rs``, ``all_reduce``, ``fast_all_to_all``).

Each op has (at least) two implementations:

- ``impl="xla"``  — shard_map + ``jax.lax`` collectives. Always correct;
  XLA's async collective scheduler provides coarse overlap. This is also
  the golden baseline, like the reference's torch/NCCL goldens.
- ``impl="pallas"`` — fused Pallas kernel with explicit remote DMA /
  semaphore overlap (compiled on TPU, interpreted on CPU meshes).

Resilience contract (docs/resilience.md): every public entry here
wears the ``@resilient`` decorator, registering its ``impl="xla"``
branch as the always-available escape hatch — the router diverts
known-bad configs, BASELINE-measured slow regimes, and open-breaker
ops to it, and retries fused infra failures on it with bit-identical
numerics. ``tools/fallback_lint.py`` (quick tier) rejects any new
entry that ships without one.
"""
