"""Overlapping kernel library (reference L5: python/triton_dist/kernels/).

Every op follows the reference's API shape: a ``create_*_context`` builder
that allocates persistent workspaces/configs, plus a functional entry point
(e.g. ``ag_gemm``, ``gemm_rs``, ``all_reduce``, ``fast_all_to_all``).

Each op has (at least) two implementations:

- ``impl="xla"``  — shard_map + ``jax.lax`` collectives. Always correct;
  XLA's async collective scheduler provides coarse overlap. This is also
  the golden baseline, like the reference's torch/NCCL goldens.
- ``impl="pallas"`` — fused Pallas kernel with explicit remote DMA /
  semaphore overlap (compiled on TPU, interpreted on CPU meshes).
"""
