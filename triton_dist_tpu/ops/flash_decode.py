"""Distributed flash-decode: split-KV GQA decode with cross-rank
partial-softmax combine.

TPU-native redesign of the reference's distributed flash-decode
(python/triton_dist/kernels/nvidia/flash_decode.py: split-KV batch decode
kernels :130-393, intra-rank combine :393-482, **inter-rank combine**
merging (m, l, acc) partial softmax states through symmetric buffers
:482-566; host wrappers :763-1130; scaling claim 1→32 GPUs README.md:203).

Design: the KV cache is sequence-sharded over the SP axis. Each device
computes an *unnormalized* flash partial over its shard:

    m = max_t s_t,   l = Σ_t e^{s_t - m},   a = Σ_t e^{s_t - m} v_t

and the cross-rank combine is the associative log-sum-exp merge

    out = Σ_r a_r e^{m_r - m*} / Σ_r l_r e^{m_r - m*},  m* = max_r m_r.

``impl="xla"``: partials via one batched einsum; merge via ``pmax`` +
``psum`` (3 scalar-sized collectives — the reference needs a second
kernel + symmetric buffers for the same merge).
``impl="pallas"``: one kernel per device — computes its partial, pushes
(a, l, m) to every peer by remote DMA (the symmetric-buffer exchange,
flash_decode.py:482-566), waits, and merges locally.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.ops.common import comm_params, resolve_interpret, sync_interpret

_NEG = -1e30


@dataclasses.dataclass
class FlashDecodeContext:
    """Analog of the reference's flash-decode context/workspace
    (flash_decode.py:763-850): axis + combine buffers (kernel-owned)."""
    mesh: Mesh
    axis: str = "sp"
    interpret: bool | None = None

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]


def create_flash_decode_context(mesh: Mesh | None = None, axis: str = "sp",
                                interpret: bool | None = None
                                ) -> FlashDecodeContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return FlashDecodeContext(mesh=mesh, axis=axis, interpret=interpret)


def _local_partials(q, k, v, first_pos, kv_len, groups: int):
    """Unnormalized flash partial over one KV shard.

    q: (B, Hq, D); k/v: (B, T, Hkv, D); positions of the shard are
    ``first_pos + [0, T)``; only positions < ``kv_len`` are live.
    Returns a (B, K, G, D), l (B, K, G), m (B, K, G) in fp32.
    """
    b, hq, d = q.shape
    t, hkv = k.shape[1], k.shape[2]
    qg = q.reshape(b, hkv, groups, d).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    scores = jnp.einsum("bkgd,btkd->bkgt", qg, kf) * (d ** -0.5)
    live = (first_pos + jnp.arange(t)) < kv_len              # (T,)
    scores = jnp.where(live[None, None, None, :], scores, _NEG)
    m = jnp.max(scores, axis=-1)
    p = jnp.exp(scores - m[..., None]) * live[None, None, None, :]
    l = jnp.sum(p, axis=-1)
    a = jnp.einsum("bkgt,btkd->bkgd", p, v.astype(jnp.float32))
    return a, l, m


def _merge(a, l, m):
    """Merge per-rank partials stacked on the leading axis (w, B, K, G, ...)."""
    m_star = jnp.max(m, axis=0, keepdims=True)
    scale = jnp.exp(m - m_star)
    num = jnp.sum(a * scale[..., None], axis=0)
    den = jnp.sum(l * scale, axis=0)
    return num / jnp.maximum(den, 1e-20)[..., None]


def _decode_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, abuf, lbuf, mbuf,
                   send_sem, recv_sem, *, axis: str, world: int,
                   groups: int, t_loc: int):
    """Single-program distributed decode: local partial → full-mesh push of
    (a, l, m) into per-rank slots of the combine buffers → wait → merge.

    The combine buffers are the analog of the reference's symmetric
    reduce buffers (flash_decode.py:482-566); `abuf[r]` holds rank r's
    partial after the exchange.
    """
    me = lax.axis_index(axis)
    kv_len = len_ref[0]
    a, l, m = _local_partials(q_ref[:], k_ref[:], v_ref[:],
                              me * t_loc, kv_len, groups)
    abuf[me] = a
    lbuf[me] = l
    mbuf[me] = m
    if world > 1:
        # Peers' buffers must exist before remote writes land.
        dl.barrier_all(axis)

        def copies(p):
            peer = lax.rem(me + p, world)
            return [dl.remote_copy(ref.at[me], ref.at[me], peer,
                                   send_sem.at[peer, i], recv_sem.at[me, i],
                                   axis=axis)
                    for i, ref in enumerate((abuf, lbuf, mbuf))]

        def send(p, _):
            for c in copies(p):
                c.start()
            return _
        lax.fori_loop(1, world, send, None)

        def wait(p, _):
            src = lax.rem(me - p + world, world)
            for i, ref in enumerate((abuf, lbuf, mbuf)):
                dl.remote_copy(ref.at[src], ref.at[src], me,
                               send_sem.at[src, i], recv_sem.at[src, i],
                               axis=axis).wait_recv()
            return _
        lax.fori_loop(1, world, wait, None)

        def drain(p, _):
            for c in copies(p):
                c.wait_send()
            return _
        lax.fori_loop(1, world, drain, None)

    out = _merge(abuf[:], lbuf[:], mbuf[:])
    b = q_ref.shape[0]
    o_ref[:] = out.reshape(b, -1, out.shape[-1]).astype(o_ref.dtype)


def gqa_fwd_batch_decode(q: jax.Array, cache_k: jax.Array,
                         cache_v: jax.Array, kv_len: jax.Array,
                         ctx: FlashDecodeContext | None = None,
                         impl: str = "pallas") -> jax.Array:
    """Decode-time GQA over a sequence-sharded KV cache (functional entry,
    reference ``gqa_fwd_batch_decode`` flash_decode.py:763).

    Args:
      q: (B, Hq, D) current-step queries, replicated over the SP axis.
      cache_k/cache_v: (B, T, Hkv, D) with T sequence-sharded over
        ``ctx.axis`` (each device holds T/w positions).
      kv_len: scalar int32 — number of live positions (decode offset + 1).
    Returns:
      (B, Hq, D) attention outputs, replicated.
    """
    ctx = ctx or create_flash_decode_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    b, hq, d = q.shape
    t, hkv = cache_k.shape[1], cache_k.shape[2]
    assert t % world == 0
    t_loc = t // world
    groups = hq // hkv
    kv_len = jnp.asarray(kv_len, jnp.int32)

    if impl == "xla" or world == 1:
        def body(qs, ks, vs, n):
            me = lax.axis_index(axis)
            a, l, m = _local_partials(qs, ks, vs, me * t_loc, n[0], groups)
            m_star = lax.pmax(m, axis)
            scale = jnp.exp(m - m_star)
            num = lax.psum(a * scale[..., None], axis)
            den = lax.psum(l * scale, axis)
            out = num / jnp.maximum(den, 1e-20)[..., None]
            return out.reshape(b, hq, d).astype(qs.dtype)

        f = jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(None, axis), P(None, axis), P()),
            out_specs=P(), check_vma=False)
        return f(q, cache_k, cache_v, kv_len.reshape(1))

    interpret = resolve_interpret(ctx.interpret)
    kernel = functools.partial(_decode_kernel, axis=axis, world=world,
                               groups=groups, t_loc=t_loc)

    def body(qs, ks, vs, n):
        out, *_ = pl.pallas_call(
            kernel,
            out_shape=(jax.ShapeDtypeStruct((b, hq, d), q.dtype),
                       jax.ShapeDtypeStruct((world, b, hkv, groups, d),
                                            jnp.float32),
                       jax.ShapeDtypeStruct((world, b, hkv, groups),
                                            jnp.float32),
                       jax.ShapeDtypeStruct((world, b, hkv, groups),
                                            jnp.float32)),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3 +
                     [pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 4),
            scratch_shapes=[pltpu.SemaphoreType.DMA((world, 3)),
                            pltpu.SemaphoreType.DMA((world, 3))],
            compiler_params=comm_params(collective_id=7, world=world),
            interpret=interpret,
        )(qs, ks, vs, n)
        return out

    f = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(None, axis), P(None, axis), P()),
        out_specs=P(), check_vma=False)
    return sync_interpret(f(q, cache_k, cache_v, kv_len.reshape(1)),
                          interpret)
