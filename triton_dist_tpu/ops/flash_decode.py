"""Distributed flash-decode: tiled split-KV GQA decode with paged KV and
cross-rank partial-softmax combine.

TPU-native redesign of the reference's distributed flash-decode
(python/triton_dist/kernels/nvidia/flash_decode.py: split-KV batch decode
kernels :130-393 with paged KV via block_table/page_size :136,:203,
persistent variant :587, intra-rank combine :393-482, **inter-rank
combine** merging (m, l, acc) partial softmax states through symmetric
buffers :482-566; host wrappers :763-1130; scaling claim 1→32 GPUs
README.md:203).

Design: the KV cache is sequence-sharded over the SP axis. Each device
computes an *unnormalized* flash partial over its shard:

    m = max_t s_t,   l = Σ_t e^{s_t - m},   a = Σ_t e^{s_t - m} v_t

and the cross-rank combine is the associative log-sum-exp merge

    out = Σ_r a_r e^{m_r - m*} / Σ_r l_r e^{m_r - m*},  m* = max_r m_r.

Local-partial variants (``FlashDecodeContext.variant``):
  * ``tiled``  — the real kernel: KV stays in HBM; (B, t_blk, D) tiles
    per KV head stream through double-buffered VMEM feeding an
    online-softmax loop. Never materializes (B, K, G, T) scores, so
    T ≥ 64k per device fits. The single long-running kernel is the
    analog of the reference's persistent variant (:587); the tile DMA
    pipeline replaces its split-KV grid.
  * ``einsum`` — whole-shard scores in one batched einsum; lowest
    latency for short caches that fit VMEM.
  * ``auto``   — picks by KV-shard byte size.

Paged KV (``gqa_fwd_batch_decode_paged``): the cache is a physical page
pool (P, page_size, Hkv, D); ``block_table[b, i]`` maps sequence b's
i-th logical page to a pool slot (reference block_table/page_table
indirection, flash_decode.py:136,:203). Tiles are DMA'd page-by-page via
the table — t_blk == page_size.

``impl="xla"``: partials via one batched einsum; merge via ``pmax`` +
``psum`` (3 scalar-sized collectives — the reference needs a second
kernel + symmetric buffers for the same merge).
``impl="pallas"``: one kernel per device — computes its partial, pushes
(a, l, m) to every peer by remote DMA (the symmetric-buffer exchange,
flash_decode.py:482-566), waits, and merges locally.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.resilience import resilient
from triton_dist_tpu.ops.common import (
    any_spec,
    comm_params,
    nestable_shard_map,
    resolve_interpret,
    sync_interpret)

_NEG = -1e30


@dataclasses.dataclass
class FlashDecodeContext:
    """Analog of the reference's flash-decode context/workspace
    (flash_decode.py:763-850): axis + combine buffers (kernel-owned)."""
    mesh: Mesh
    axis: str = "sp"
    interpret: bool | None = None
    # Local-partial variant: "tiled" | "einsum" | "auto" (by shard bytes).
    variant: str = "auto"
    # KV positions per VMEM tile for the tiled variant (dense path);
    # auto-shrunk so the two double-buffered (B, t_blk, Hkv, D) K/V tiles
    # fit ``vmem_budget`` (BENCH_r02 class: an infeasible tile size must
    # never reach the compiler — tests/test_vmem_budget.py).
    t_blk: int = 512
    vmem_budget: int = 10 * 1024 * 1024
    # Byte threshold for auto: einsum below (shard fits VMEM comfortably).
    einsum_max_bytes: int = 4 * 1024 * 1024
    # Paged-KV kernel path: "direct" streams pages into the tiled
    # kernel via block-table indirection (one DMA per batch row per
    # tile); "gathered" reconstructs the contiguous per-device KV view
    # with an XLA gather and runs the PROVEN dense tiled kernel.
    # DEFAULT is "gathered" (ADVICE r5 medium): the direct kernel's
    # round-5 on-chip Mosaic compile hang (tpu_smoke_r5_bulk.log:
    # flash_decode/paged, >40 min) is still un-root-caused, and a
    # production paged server must not wedge by default. "direct" is
    # the opt-in — via this field or the TDT_PAGED_VARIANT env var,
    # which overrides the field so a deployment can flip paths without
    # code changes — until the hang is fixed. (Its smoke-queue canary
    # is retired: docs/resilience.md "Retired canary".)
    paged_variant: str = "gathered"

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]

    def resolve_variant(self, shard_bytes: int) -> str:
        if self.variant != "auto":
            return self.variant
        return "einsum" if shard_bytes <= self.einsum_max_bytes else "tiled"


def create_flash_decode_context(mesh: Mesh | None = None, axis: str = "sp",
                                interpret: bool | None = None,
                                variant: str = "auto",
                                t_blk: int = 512) -> FlashDecodeContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return FlashDecodeContext(mesh=mesh, axis=axis, interpret=interpret,
                              variant=variant, t_blk=t_blk)


def _qk_scores(qg, kt):
    """(B, K, G, D) x (B, T, K, D) -> (B, K, G, T) scores.

    Mosaic's ``tpu.matmul`` supports at most ONE batch dimension
    (VERDICT r2: the two-batch-dim ``bkgd,btkd->bkgt`` einsum fails to
    compile), so the KV-head dimension is unrolled as a static Python
    loop — each per-head dot keeps only B as the batch dim.
    """
    hkv = qg.shape[1]
    outs = [lax.dot_general(qg[:, h], kt[:, :, h],
                            (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)
            for h in range(hkv)]
    return jnp.stack(outs, axis=1)


def _pv_accum(p, vt):
    """(B, K, G, T) x (B, T, K, D) -> (B, K, G, D), one batch dim per dot
    (same Mosaic constraint as :func:`_qk_scores`)."""
    hkv = p.shape[1]
    outs = [lax.dot_general(p[:, h], vt[:, :, h],
                            (((2,), (1,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)
            for h in range(hkv)]
    return jnp.stack(outs, axis=1)


def _local_partials(q, k, v, first_pos, kv_len, groups: int,
                    mosaic: bool = False):
    """Unnormalized flash partial over one KV shard (einsum variant).

    q: (B, Hq, D); k/v: (B, T, Hkv, D); positions of the shard are
    ``first_pos + [0, T)``; only positions < ``kv_len`` are live.
    ``kv_len`` is PER-BATCH (B,) — the reference loads
    ``kv_length_ptr + bid`` per sequence (flash_decode.py:182); a
    scalar is broadcast. Returns a (B, K, G, D), l (B, K, G),
    m (B, K, G) in fp32. ``mosaic=True`` routes the contractions
    through the per-head single-batch-dim dots (required inside Pallas
    kernels).
    """
    b, hq, d = q.shape
    t, hkv = k.shape[1], k.shape[2]
    # QK in the cache dtype when q matches it (MXU-native; f32
    # accumulation makes the scores bit-identical to an upcast-first
    # dot); precision-mismatched callers keep the exact f32 path —
    # see the tiled kernel (review r4b-4).
    dt = k.dtype if q.dtype == k.dtype else jnp.float32
    qg = q.reshape(b, hkv, groups, d).astype(dt)
    kc = k.astype(dt)
    if mosaic:
        scores = _qk_scores(qg, kc) * (d ** -0.5)
    else:
        scores = jnp.einsum("bkgd,btkd->bkgt", qg, kc,
                            preferred_element_type=jnp.float32
                            ) * (d ** -0.5)
    lens = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (b,))
    live = (first_pos + jnp.arange(t))[None, :] < lens[:, None]  # (B, T)
    live4 = live[:, None, None, :]
    scores = jnp.where(live4, scores, _NEG)
    m = jnp.max(scores, axis=-1)
    p = jnp.exp(scores - m[..., None]) * live4
    l = jnp.sum(p, axis=-1)
    pv_in = p.astype(dt)   # PV in the compute dtype, f32 accumulate
    vc = v.astype(dt)
    if mosaic:
        a = _pv_accum(pv_in, vc)
    else:
        a = jnp.einsum("bkgt,btkd->bkgd", pv_in, vc,
                       preferred_element_type=jnp.float32)
    return a, l, m


def _merge(a, l, m):
    """Merge per-rank partials stacked on the leading axis (w, B, K, G, ...)."""
    m_star = jnp.max(m, axis=0, keepdims=True)
    scale = jnp.exp(m - m_star)
    num = jnp.sum(a * scale[..., None], axis=0)
    den = jnp.sum(l * scale, axis=0)
    return num / jnp.maximum(den, 1e-20)[..., None]


def combine_peer(me, p, world: int):
    """Peer targeted at combine send position ``p`` (1..world-1).
    Exposed for symbolic execution — the flash-decode-protocol model
    checker (analysis/flash_model.py) executes this with concrete
    ranks; ``_exchange_and_merge`` calls it with traced values so the
    checker and the kernel cannot drift apart."""
    return lax.rem(me + p, world)


def combine_src(me, p, world: int):
    """Source waited on at combine wait position ``p`` (1..world-1) —
    the left-rotation mirror of :func:`combine_peer`."""
    return lax.rem(me - p + world, world)


def _exchange_and_merge(abuf, lbuf, mbuf, send_sem, recv_sem, o_ref, *,
                        axis: str, world: int):
    """Full-mesh push of this rank's (a, l, m) partial into every peer's
    combine-buffer slot, wait for all peers, then merge locally — the
    symmetric-buffer exchange of the reference's inter-rank combine
    (flash_decode.py:482-566)."""
    me = lax.axis_index(axis)
    if world > 1:
        # Peers' buffers must exist before remote writes land.
        dl.barrier_all(axis)

        def copies(p):
            peer = combine_peer(me, p, world)
            return [dl.remote_copy(ref.at[me], ref.at[me], peer,
                                   send_sem.at[peer, i], recv_sem.at[me, i],
                                   axis=axis)
                    for i, ref in enumerate((abuf, lbuf, mbuf))]

        def send(p, _):
            for c in copies(p):
                c.start()
            return _
        lax.fori_loop(1, world, send, None)

        def wait(p, _):
            src = combine_src(me, p, world)
            for i, ref in enumerate((abuf, lbuf, mbuf)):
                dl.remote_copy(ref.at[src], ref.at[src], me,
                               send_sem.at[src, i], recv_sem.at[src, i],
                               axis=axis).wait_recv()
            return _
        lax.fori_loop(1, world, wait, None)

        def drain(p, _):
            for c in copies(p):
                c.wait_send()
            return _
        lax.fori_loop(1, world, drain, None)

    out = _merge(abuf[:], lbuf[:], mbuf[:])
    b = out.shape[0]
    o_ref[:] = out.reshape(b, -1, out.shape[-1]).astype(o_ref.dtype)


def _decode_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, abuf, lbuf, mbuf,
                   send_sem, recv_sem, *, axis: str, world: int,
                   groups: int, t_loc: int):
    """Einsum-variant distributed decode: whole-shard partial in VMEM →
    cross-rank combine. Lowest latency for short caches."""
    me = lax.axis_index(axis)
    # (B,) per-sequence lengths — SMEM loads must be scalar (Mosaic),
    # so unroll the small batch dim.
    kv_len = jnp.stack([len_ref[i] for i in range(q_ref.shape[0])])
    a, l, m = _local_partials(q_ref[:], k_ref[:], v_ref[:],
                              me * t_loc, kv_len, groups, mosaic=True)
    abuf[me] = a
    lbuf[me] = l
    mbuf[me] = m
    _exchange_and_merge(abuf, lbuf, mbuf, send_sem, recv_sem, o_ref,
                        axis=axis, world=world)


def _tiled_decode_kernel(q_ref, len_ref, table_ref, k_hbm, v_hbm, o_ref,
                         abuf, lbuf, mbuf, k_tile, v_tile, k_sem, v_sem,
                         send_sem, recv_sem, *, axis: str, world: int,
                         batch: int, hkv: int, groups: int, d: int,
                         t_loc: int, t_blk: int, paged: bool):
    """Tiled split-KV partial: stream (B, t_blk, D) K/V tiles per KV head
    through double-buffered VMEM with an online-softmax carry, then the
    cross-rank combine.

    The KV refs live in HBM (``pl.ANY``):
      dense: (B, T_loc, Hkv, D); tile DMA slices rows [ts, ts+t_blk).
      paged: pool (P, page_size, Hkv, D) + ``table_ref`` (B, n_pages)
        int32 in SMEM; tile i of sequence b reads pool[table[b, i]]
        (reference block_table indirection, flash_decode.py:136,:203).

    Per-tile trip count is *dynamic* (ceil of the live positions in this
    rank's shard), so ranks whose shard lies past ``kv_len`` skip all
    DMAs and compute — the split-KV early-exit of the reference's
    persistent kernel (:587).
    """
    me = lax.axis_index(axis)
    scale = d ** -0.5

    # Per-sequence lengths (reference kv_length_ptr + bid,
    # flash_decode.py:182); the DMA trip count covers the longest live
    # row, per-row tails are masked per tile below. SMEM loads must be
    # scalar (Mosaic), so unroll the small batch dim.
    lens = jnp.stack([len_ref[i] for i in range(batch)])
    kv_max = jnp.max(lens)
    first_pos = me * t_loc
    live_here = jnp.clip(kv_max - first_pos, 0, t_loc)
    n_tiles = lax.div(live_here + t_blk - 1, t_blk)

    def paged_dma(hbm, tile, sem, slot, ti, b):
        # Paged: each sequence's tile lives on its own page → one DMA
        # per batch row (block_table indirection).
        page = table_ref[b, ti]
        return pltpu.make_async_copy(hbm.at[page, :, :, :],
                                     tile.at[slot, b], sem.at[slot, b])

    def dense_dma(hbm, tile, sem, slot, ti):
        # Dense cache: the whole (B, t_blk, Hkv, D) tile is one strided
        # DMA — 2 descriptors per tile instead of 2*B (B=8 serving
        # batches were paying 16 issue latencies per tile).
        return pltpu.make_async_copy(
            hbm.at[:, pl.ds(ti * t_blk, t_blk), :, :], tile.at[slot],
            sem.at[slot, 0])

    _kv = ((k_hbm, k_tile, k_sem), (v_hbm, v_tile, v_sem))

    def tile_dmas(slot, ti):
        if paged:
            return [paged_dma(*refs, slot, ti, b)
                    for refs in _kv for b in range(batch)]
        return [dense_dma(*refs, slot, ti) for refs in _kv]

    def start_tile(slot, ti):
        for dma in tile_dmas(slot, ti):
            dma.start()

    def wait_tile(slot, ti):
        for dma in tile_dmas(slot, ti):
            dma.wait()

    @pl.when(n_tiles > 0)
    def _():
        start_tile(0, 0)

    def tile_step(ti, carry):
        m_run, l_run, acc = carry
        slot = lax.rem(ti, 2)

        @pl.when(ti + 1 < n_tiles)
        def _():
            start_tile(lax.rem(ti + 1, 2), ti + 1)
        wait_tile(slot, ti)

        # Dots run in the CACHE dtype when q matches it (MXU-native: a
        # bf16 matmul is up to 3x an f32 one on TPU and skips two
        # full-tile f32 conversions per step; bf16->f32 upcast before
        # the dot would produce bit-identical scores anyway since the
        # accumulation is f32 either way — r4, targeting the 0.90x
        # bench line). A precision-MISMATCHED caller (e.g. f32 q over a
        # bf16 cache) keeps the exact f32 path: casting q down would
        # silently change results (review r4b-4).
        dt = k_tile.dtype if q_ref.dtype == k_tile.dtype else jnp.float32
        kt = k_tile[slot].astype(dt)            # (B, t_blk, Hkv, D)
        vt = v_tile[slot].astype(dt)
        q = q_ref[:].reshape(batch, hkv, groups, d).astype(dt)
        # (B, K, G, D) x (B, t_blk, K, D) -> (B, K, G, t_blk); per-head
        # dots keep Mosaic's one-batch-dim matmul constraint.
        scores = _qk_scores(q, kt) * scale
        pos = first_pos + ti * t_blk + jnp.arange(t_blk)
        live = pos[None, :] < lens[:, None]                  # (B, t_blk)
        live4 = live[:, None, None, :]
        scores = jnp.where(live4, scores, _NEG)

        m_new = jnp.maximum(m_run, jnp.max(scores, axis=-1))
        alpha = jnp.exp(m_run - m_new)
        p = jnp.exp(scores - m_new[..., None]) * live4
        l_new = l_run * alpha + jnp.sum(p, axis=-1)
        # PV in the cache dtype with f32 accumulation (standard flash
        # practice; p in [0,1] loses <0.5% per element to bf16 and the
        # f32 accumulate keeps the sum exact). No-op for f32 caches.
        pv = _pv_accum(p.astype(vt.dtype), vt)
        acc_new = acc * alpha[..., None] + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((batch, hkv, groups), _NEG, jnp.float32)
    l0 = jnp.zeros((batch, hkv, groups), jnp.float32)
    a0 = jnp.zeros((batch, hkv, groups, d), jnp.float32)
    m_f, l_f, a_f = lax.fori_loop(0, n_tiles, tile_step, (m0, l0, a0))

    abuf[me] = a_f
    lbuf[me] = l_f
    mbuf[me] = m_f
    _exchange_and_merge(abuf, lbuf, mbuf, send_sem, recv_sem, o_ref,
                        axis=axis, world=world)


def _combine_shapes(world, b, hkv, groups, d):
    return (jax.ShapeDtypeStruct((world, b, hkv, groups, d), jnp.float32),
            jax.ShapeDtypeStruct((world, b, hkv, groups), jnp.float32),
            jax.ShapeDtypeStruct((world, b, hkv, groups), jnp.float32))


@resilient("flash_decode")
def gqa_fwd_batch_decode(q: jax.Array, cache_k: jax.Array,
                         cache_v: jax.Array, kv_len: jax.Array,
                         ctx: FlashDecodeContext | None = None,
                         impl: str = "pallas") -> jax.Array:
    """Decode-time GQA over a sequence-sharded KV cache (functional entry,
    reference ``gqa_fwd_batch_decode`` flash_decode.py:763).

    Args:
      q: (B, Hq, D) current-step queries, replicated over the SP axis.
      cache_k/cache_v: (B, T, Hkv, D) with T sequence-sharded over
        ``ctx.axis`` (each device holds T/w positions).
      kv_len: int32 number of live positions (decode offset + 1) —
        scalar, or PER-SEQUENCE (B,) like the reference's kv_length
        array (flash_decode.py:182).
    Returns:
      (B, Hq, D) attention outputs, replicated.
    """
    ctx = ctx or create_flash_decode_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    b, hq, d = q.shape
    t, hkv = cache_k.shape[1], cache_k.shape[2]
    assert t % world == 0
    t_loc = t // world
    groups = hq // hkv
    kv_len = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (b,))

    if impl == "xla":
        def body(qs, ks, vs, n):
            me = lax.axis_index(axis)
            a, l, m = _local_partials(qs, ks, vs, me * t_loc, n, groups)
            m_star = lax.pmax(m, axis)
            sc = jnp.exp(m - m_star)
            num = lax.psum(a * sc[..., None], axis)
            den = lax.psum(l * sc, axis)
            out = num / jnp.maximum(den, 1e-20)[..., None]
            return out.reshape(b, hq, d).astype(qs.dtype)

        f = nestable_shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(None, axis), P(None, axis), P()),
            out_specs=P(), check_vma=False)
        return f(q, cache_k, cache_v, kv_len)

    interpret = resolve_interpret(ctx.interpret)
    shard_bytes = t_loc * hkv * d * cache_k.dtype.itemsize * b
    variant = ctx.resolve_variant(shard_bytes)

    if variant == "einsum":
        kernel = functools.partial(_decode_kernel, axis=axis, world=world,
                                   groups=groups, t_loc=t_loc)

        def body(qs, ks, vs, n):
            out, *_ = pl.pallas_call(
                kernel,
                out_shape=(jax.ShapeDtypeStruct((b, hq, d), q.dtype),)
                + _combine_shapes(world, b, hkv, groups, d),
                in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3 +
                         [pl.BlockSpec(memory_space=pltpu.SMEM)],
                out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 4),
                scratch_shapes=[pltpu.SemaphoreType.DMA((world, 3)),
                                pltpu.SemaphoreType.DMA((world, 3))],
                compiler_params=comm_params(collective_id=7, world=world),
                interpret=interpret,
            )(qs, ks, vs, n)
            return out

        f = nestable_shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(None, axis), P(None, axis), P()),
            out_specs=P(), check_vma=False)
        return sync_interpret(f(q, cache_k, cache_v, kv_len),
                              interpret)

    # tiled variant: KV stays in HBM, dummy 1x1 table (dense addressing).
    def _div_leq(cap: int) -> int:
        # Largest divisor of t_loc <= cap — tile slicing and the
        # liveness mask both assume t_blk | t_loc.
        cap = max(min(cap, t_loc), 1)
        while t_loc % cap:
            cap -= 1
        return cap

    t_blk = _div_leq(ctx.t_blk)
    # 4 tiles (K+V, double-buffered) must fit the VMEM budget.
    per_pos = 4 * b * hkv * d * cache_k.dtype.itemsize
    while t_blk > 8 and t_blk * per_pos > ctx.vmem_budget:
        t_blk = _div_leq(t_blk // 2)
    kernel = functools.partial(
        _tiled_decode_kernel, axis=axis, world=world, batch=b, hkv=hkv,
        groups=groups, d=d, t_loc=t_loc, t_blk=t_blk, paged=False)

    def body(qs, n, ks, vs):
        table = jnp.zeros((1, 1), jnp.int32)
        out, *_ = pl.pallas_call(
            kernel,
            out_shape=(jax.ShapeDtypeStruct((b, hq, d), q.dtype),)
            + _combine_shapes(world, b, hkv, groups, d),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pltpu.SMEM),
                      any_spec(),
                      any_spec()],
            out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 4),
            scratch_shapes=[
                pltpu.VMEM((2, b, t_blk, hkv, d), cache_k.dtype),
                pltpu.VMEM((2, b, t_blk, hkv, d), cache_v.dtype),
                # Dense path: one whole-tile DMA per slot — only sem
                # [slot, 0] is used (paged keeps per-batch sems).
                pltpu.SemaphoreType.DMA((2, 1)),
                pltpu.SemaphoreType.DMA((2, 1)),
                pltpu.SemaphoreType.DMA((world, 3)),
                pltpu.SemaphoreType.DMA((world, 3))],
            compiler_params=comm_params(collective_id=7, world=world),
            interpret=interpret,
        )(qs, n, table, ks, vs)
        return out

    f = nestable_shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P(None, axis), P(None, axis)),
        out_specs=P(), check_vma=False)
    return sync_interpret(f(q, kv_len, cache_k, cache_v), interpret)


@resilient("flash_decode_paged", env_keys=("TDT_PAGED_VARIANT",))
def gqa_fwd_batch_decode_paged(q: jax.Array, pool_k: jax.Array,
                               pool_v: jax.Array, block_table: jax.Array,
                               kv_len: jax.Array,
                               ctx: FlashDecodeContext | None = None,
                               impl: str = "pallas") -> jax.Array:
    """Paged-KV distributed decode (reference paged split-KV kernels,
    flash_decode.py:130-393 block_table/page_size :136,:203).

    Sharding contract: device r of the SP axis backs global positions
    [r*t_loc, (r+1)*t_loc) of every sequence, t_loc = n_pages*page_size.

    Args:
      q: (B, Hq, D) replicated.
      pool_k/pool_v: (w*P_loc, page_size, Hkv, D) physical page pools,
        dim 0 sharded over ``ctx.axis`` — each device owns P_loc slots.
      block_table: (w, B, n_pages) int32, dim 0 sharded — device r's
        table maps its logical page i of sequence b to a *local* slot id
        in [0, P_loc).
      kv_len: int32 global live length — scalar or per-sequence (B,).
    Returns:
      (B, Hq, D) replicated.
    """
    ctx = ctx or create_flash_decode_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    b, hq, d = q.shape
    page_size, hkv = pool_k.shape[1], pool_k.shape[2]
    assert block_table.shape[0] == world and block_table.shape[1] == b
    n_pages = block_table.shape[2]
    groups = hq // hkv
    t_loc = n_pages * page_size
    kv_len = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (b,))

    import os
    paged_variant = os.environ.get("TDT_PAGED_VARIANT",
                                   ctx.paged_variant)
    if paged_variant not in ("direct", "gathered"):
        # A typo here would silently run the direct path — the exact
        # compile-hang the override exists to dodge.
        raise ValueError(
            f"paged_variant {paged_variant!r} (field or "
            "TDT_PAGED_VARIANT) must be 'direct' or 'gathered'")
    if impl == "xla" or paged_variant == "gathered":
        # Reconstruct the contiguous (B, T, Hkv, D) view via table
        # gathers (position → slot is the allocator's map), then run
        # the contiguous decode. For impl="xla" this is the golden /
        # fast CPU-mesh path, like the other ops' xla impls; for
        # paged_variant="gathered" the dense TILED Pallas kernel
        # consumes the gathered view — the proven-on-chip path that
        # sidesteps the direct kernel's block-table indirection (see
        # FlashDecodeContext.paged_variant).
        from triton_dist_tpu.models.kv_cache import PagedKVCacheManager
        ck = PagedKVCacheManager.gathered_view(pool_k, block_table,
                                               world)  # (B, T, ...)
        cv = PagedKVCacheManager.gathered_view(pool_v, block_table,
                                               world)
        sh = jax.sharding.NamedSharding(mesh, P(None, axis))
        return gqa_fwd_batch_decode(
            q, jax.lax.with_sharding_constraint(ck, sh),
            jax.lax.with_sharding_constraint(cv, sh), kv_len, ctx,
            impl=impl)

    interpret = resolve_interpret(ctx.interpret)

    kernel = functools.partial(
        _tiled_decode_kernel, axis=axis, world=world, batch=b, hkv=hkv,
        groups=groups, d=d, t_loc=t_loc, t_blk=page_size, paged=True)

    def body(qs, n, table, ks, vs):
        out, *_ = pl.pallas_call(
            kernel,
            out_shape=(jax.ShapeDtypeStruct((b, hq, d), q.dtype),)
            + _combine_shapes(world, b, hkv, groups, d),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pltpu.SMEM),
                      any_spec(),
                      any_spec()],
            out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 4),
            scratch_shapes=[
                pltpu.VMEM((2, b, page_size, hkv, d), pool_k.dtype),
                pltpu.VMEM((2, b, page_size, hkv, d), pool_v.dtype),
                pltpu.SemaphoreType.DMA((2, b)),
                pltpu.SemaphoreType.DMA((2, b)),
                pltpu.SemaphoreType.DMA((world, 3)),
                pltpu.SemaphoreType.DMA((world, 3))],
            compiler_params=comm_params(collective_id=7, world=world),
            interpret=interpret,
        )(qs, n, table.reshape(b, n_pages), ks, vs)
        return out

    f = nestable_shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis), P(axis)),
        out_specs=P(), check_vma=False)
    return sync_interpret(
        f(q, kv_len, block_table, pool_k, pool_v), interpret)
