"""MoE routing / token-alignment utilities.

TPU-native redesign of the reference's MoE host utilities
(python/triton_dist/kernels/nvidia/moe_utils.py, csrc/lib/moe_utils.cu:61
``moe_ag_scatter_align_block_size_kernel``, :195 topk-reduce kernel, and the
EP preprocess path ep_a2a_layer.py:119-139: bincount of expert indices →
splits → recv offsets).

The reference aligns token→expert assignments to GEMM block boundaries so a
grouped GEMM can consume them; the TPU equivalent is sorting tokens by
expert and handing ``group_sizes`` to ``jax.lax.ragged_dot`` — XLA's native
grouped-GEMM primitive that tiles directly onto the MXU. Dynamic token
counts become static-shape tensors via fixed per-peer capacity plus masks
(SURVEY.md §7 "Dynamic shapes in EP": the reference also uses MAX_M
buffers, so parity holds).

Everything here is pure jnp — traced under jit, no host sync (the
reference needs a CUDA kernel + cpu pinned-memory roundtrip for the same
job, ep_a2a.py:244-310).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def topk_routing(router_logits: jax.Array, topk: int,
                 norm_topk_prob: bool = True):
    """Softmax→top-k gating (the Qwen3-MoE recipe, models/qwen_moe.py:50-80).

    Args:
      router_logits: (T, E) float logits.
      topk: experts per token.
      norm_topk_prob: renormalize the selected probabilities to sum to 1.

    Returns:
      (weights (T, topk) float32, indices (T, topk) int32)
    """
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    weights, indices = lax.top_k(probs, topk)
    if norm_topk_prob:
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return weights, indices.astype(jnp.int32)


def live_slot_mask(counts: jax.Array, world: int,
                   capacity: int) -> jax.Array:
    """(world, capacity) bool: slot s of slab p is live iff
    ``s < counts[p]``.

    One definition of "live" for the a2a slab layout, shared by the
    dispatch unpack (layers/ep_a2a.py) and the a2a VJP's cotangent
    masking (ops/autodiff.py) — the Pallas exchange leaves dead slots
    stale, and both sides must zero the same set of rows.
    """
    slot = lax.broadcasted_iota(jnp.int32, (world, capacity), 1)
    return slot < counts[:, None]


def bincount(indices: jax.Array, length: int) -> jax.Array:
    """Static-length bincount (reference device ``bincount`` ep_a2a.py:310,
    used for per-expert splits)."""
    one = jnp.zeros((length,), jnp.int32)
    return one.at[indices.reshape(-1)].add(1, mode="drop")


def dispatch_layout(exp_indices: jax.Array, num_experts: int, world: int,
                    capacity: int):
    """Compute the rank-major dispatch layout for EP all-to-all.

    The analog of the reference's send-request generation + recv-offset
    computation (ep_a2a_layer.py:119-139, ep_a2a.py:244) — but fully traced
    and static-shape: each (token, k) pair is assigned a slot
    ``(dest_rank, position)`` where ``position`` is the pair's ordinal among
    all pairs routed to ``dest_rank`` (stable, token-major). Pairs beyond
    ``capacity`` are dropped (marked invalid), like capacity-factor MoE.

    Args:
      exp_indices: (T, K) int32 global expert ids.
      num_experts: total experts E; experts_per_rank = E // world.
      world: EP world size.
      capacity: max pairs a rank may send to one peer.

    Returns dict of:
      dest        (T, K) int32 destination rank per pair
      pos         (T, K) int32 slot within the destination slab
      valid       (T, K) bool  pair kept (not capacity-dropped)
      send_counts (world,) int32 pairs actually sent per destination
      local_expert(T, K) int32 expert id local to the destination rank
    """
    epr = num_experts // world
    t, k = exp_indices.shape
    flat = exp_indices.reshape(-1)
    dest = flat // epr
    # position of pair i within its destination slab = number of earlier
    # pairs with the same destination (stable token-major order, matching
    # the reference's start/end-indices send requests).
    onehot = jax.nn.one_hot(dest, world, dtype=jnp.int32)      # (TK, world)
    pos = (jnp.cumsum(onehot, axis=0) - onehot)                # exclusive
    pos = jnp.take_along_axis(pos, dest[:, None], axis=1)[:, 0]
    valid = pos < capacity
    send_counts = jnp.sum(onehot * valid[:, None].astype(jnp.int32), axis=0)
    return {
        "dest": dest.reshape(t, k),
        "pos": pos.reshape(t, k),
        "valid": valid.reshape(t, k),
        "send_counts": send_counts.astype(jnp.int32),
        "local_expert": (flat % epr).reshape(t, k).astype(jnp.int32),
    }


def scatter_to_slabs(x: jax.Array, meta: dict, world: int, capacity: int,
                     extra: dict | None = None):
    """Scatter per-token payloads into the (world, capacity, ...) send
    buffer described by ``meta`` (from :func:`dispatch_layout`).

    ``x``: (T, H) token payloads, expanded to one row per (token, k) pair.
    ``extra``: name → (T, K) int32 side-band values scattered alongside
    (local expert id, source slot id ... the reference packs these into the
    same nvshmem send_buf rows, ep_a2a.py:37-150).

    Returns (send_buf (world, capacity, H), extras {name: (world, capacity)}).
    Invalid / unused slots are zero.
    """
    t, k = meta["dest"].shape
    h = x.shape[-1]
    dest = meta["dest"].reshape(-1)
    pos = meta["pos"].reshape(-1)
    valid = meta["valid"].reshape(-1)
    # Route dropped pairs to an out-of-range slot; mode="drop" discards them.
    slot = jnp.where(valid, dest * capacity + pos, world * capacity)
    rows = jnp.repeat(x, k, axis=0)                             # (TK, H)
    buf = jnp.zeros((world * capacity, h), x.dtype)
    buf = buf.at[slot].set(rows, mode="drop")
    extras_out = {}
    for name, val in (extra or {}).items():
        e = jnp.zeros((world * capacity,), val.dtype)
        extras_out[name] = e.at[slot].set(val.reshape(-1), mode="drop"
                                          ).reshape(world, capacity)
    return buf.reshape(world, capacity, h), extras_out


def sort_by_group(values: jax.Array, group_ids: jax.Array, num_groups: int):
    """Stable-sort rows by group id → (sorted values, group_sizes, unsort).

    The TPU-native ``moe_ag_scatter_align_block_size`` (csrc moe_utils.cu:61):
    instead of padding token blocks to GEMM tiles, sorting + ``group_sizes``
    feeds ``lax.ragged_dot`` which handles expert-boundary tiling natively.

    ``group_ids`` may contain ``num_groups`` (sentinel for invalid rows);
    those sort to the end and are excluded from ``group_sizes``.
    """
    order = jnp.argsort(group_ids, stable=True)
    sizes = bincount(jnp.minimum(group_ids, num_groups), num_groups)
    unsort = jnp.argsort(order, stable=True)
    return values[order], sizes, unsort


def moe_align_block_size(expert_ids, num_experts: int, block_size: int):
    """Host-side grouped-GEMM tile plan (reference
    ``moe_ag_scatter_align_block_size`` csrc/lib/moe_utils.cu:61 + CPU
    swizzle threadblock_swizzle_ag_moe.cc): stable expert-sorted order,
    per-expert counts, tile-padded offsets, and the block→expert map an
    explicit tiled grouped-GEMM kernel iterates. Native C++ via ctypes
    (csrc/moe/moe_align.cc) with a numpy fallback.

    Returns dict(sorted_order, expert_counts, padded_offsets,
    block_expert) — numpy arrays (host planning, like the reference).
    """
    import numpy as np
    ids = np.ascontiguousarray(np.asarray(expert_ids).reshape(-1), np.int32)
    if ids.size and (ids.min() < 0 or ids.max() > num_experts):
        raise ValueError(
            f"expert ids must lie in [0, {num_experts}] "
            f"(== {num_experts} is the invalid sentinel); got "
            f"[{ids.min()}, {ids.max()}]")
    n = ids.shape[0]
    lib = _moe_native()
    if lib is not None:
        import ctypes
        cap = n + num_experts
        order = np.empty(n, np.int32)
        counts = np.empty(num_experts, np.int32)
        offsets = np.empty(num_experts + 1, np.int32)
        blocks = np.empty(cap, np.int32)
        p = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        nb = lib.tdt_moe_align_block_size(
            n, p(ids), num_experts, block_size, p(order), p(counts),
            p(offsets), p(blocks), cap)
        assert nb >= 0, f"tdt_moe_align_block_size failed (rc={nb})"
        return {"sorted_order": order, "expert_counts": counts,
                "padded_offsets": offsets, "block_expert": blocks[:nb]}
    # numpy fallback (bit-identical; tests assert so)
    order = np.argsort(ids, kind="stable").astype(np.int32)
    counts = np.bincount(ids[ids < num_experts],
                         minlength=num_experts).astype(np.int32)
    nblk = -(-counts // block_size)
    offsets = np.zeros(num_experts + 1, np.int32)
    offsets[1:] = np.cumsum(nblk * block_size)
    block_expert = np.repeat(np.arange(num_experts, dtype=np.int32), nblk)
    return {"sorted_order": order, "expert_counts": counts,
            "padded_offsets": offsets, "block_expert": block_expert}


_MOE_LIB = None
_MOE_TRIED = False


def _moe_native():
    global _MOE_LIB, _MOE_TRIED
    if _MOE_TRIED:
        return _MOE_LIB
    _MOE_TRIED = True
    import ctypes
    import os
    import subprocess
    src = os.path.abspath(os.path.join(
        os.path.dirname(__file__), "..", "csrc", "moe", "moe_align.cc"))
    so = os.path.join(os.path.dirname(src), "libtdtmoe.so")
    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            subprocess.run(["g++", "-shared", "-fPIC", "-O2", "-o", so, src],
                           check=True, capture_output=True)
        lib = ctypes.CDLL(so)
        lib.tdt_moe_align_block_size.restype = ctypes.c_int32
        _MOE_LIB = lib
    except (OSError, subprocess.CalledProcessError):
        _MOE_LIB = None
    return _MOE_LIB


def topk_reduce(per_pair_out: jax.Array, weights: jax.Array) -> jax.Array:
    """Weighted sum over the top-k expert outputs per token (reference
    topk-reduce kernel, csrc/lib/moe_utils.cu:195).

    per_pair_out: (T, K, H); weights: (T, K) → (T, H).
    """
    w = weights.astype(jnp.float32)[..., None]
    return jnp.sum(per_pair_out.astype(jnp.float32) * w, axis=1
                   ).astype(per_pair_out.dtype)
