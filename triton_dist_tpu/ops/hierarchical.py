"""Two-level (hierarchical) collectives over ICI + DCN mesh axes.

TPU-native redesign of the reference's 2D intra/inter-node collectives
(python/triton_dist/kernels/nvidia/reduce_scatter.py:506-673: per-node
staging buffers + intra-node ring + inter-node ring;
low_latency_allgather.py 2d/3d multinode variants; SURVEY.md §7
"Cross-host (DCN) one-sided ops ... the reference's 2D ring intra/inter
split is the right template").

On a multi-host TPU pod the mesh has a fast axis (ICI, within the slice)
and a slow axis (DCN, across hosts). The two-level schedule does the
bandwidth-heavy stage on ICI and moves only the reduced/partial data over
DCN:

- all_gather_2d:     AG over ICI first (big payload on fast links), then
                     AG the ICI-gathered blocks over DCN.
- reduce_scatter_2d: RS over ICI first (reduces payload by the ICI world
                     size before it touches DCN), then RS over DCN.
- all_reduce_2d:     RS(ici) → AR(dcn) → AG(ici): the DCN stage carries
                     1/w_ici of the data.

These compose the per-axis ``lax`` collectives so XLA emits them on the
right transport; the fused Pallas per-axis kernels (ops/allgather,
ops/reduce_scatter) slot in per-axis when explicit overlap is wanted.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from triton_dist_tpu.ops.common import nestable_shard_map


@dataclasses.dataclass
class HierCollectiveContext:
    """Axis naming: ``inner`` = fast transport (ICI), ``outer`` = slow
    (DCN) — the reference's intra-node / inter-node split."""
    mesh: Mesh
    inner: str = "ici"
    outer: str = "dcn"

    @property
    def inner_size(self) -> int:
        return self.mesh.shape[self.inner]

    @property
    def outer_size(self) -> int:
        return self.mesh.shape[self.outer]


def create_hier_context(mesh: Mesh | None = None, inner: str = "ici",
                        outer: str = "dcn") -> HierCollectiveContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return HierCollectiveContext(mesh=mesh, inner=inner, outer=outer)


def all_gather_2d(x: jax.Array, ctx: HierCollectiveContext) -> jax.Array:
    """Gather dim-0 shards across both axes: ICI stage then DCN stage
    (reference 2D AG: intra-node ring + inter-node ring,
    low_latency_allgather.py 2d variants)."""
    return all_gather_nd(x, ctx.mesh, (ctx.inner, ctx.outer))


def reduce_scatter_2d(x: jax.Array, ctx: HierCollectiveContext) -> jax.Array:
    """Reduce-scatter replicated-per-device partials down to 2D shards:
    ICI RS first so DCN carries 1/w_ici of the bytes (reference
    ``reduce_scatter_2d_op`` reduce_scatter.py:857).

    Note the resulting dim-0 sharding is *inner-major*
    (``P((inner, outer))``): scattering over ICI first fixes the coarse
    block per ICI rank, the DCN stage subdivides it — the transpose of
    the AG layout, exactly like the reference's 2D RS whose per-node
    staging leaves node-interleaved segments.
    """
    return reduce_scatter_nd(x, ctx.mesh, (ctx.inner, ctx.outer))


def all_reduce_2d(x: jax.Array, ctx: HierCollectiveContext) -> jax.Array:
    """AllReduce via RS(ici) → AR(dcn) → AG(ici): minimum DCN traffic
    (the reference's double-tree/2D AR role, allreduce.py:1101)."""
    return all_reduce_nd(x, ctx.mesh, (ctx.inner, ctx.outer))


# --- n-level generalization (reference 2d/3d multinode variants,
# low_latency_allgather.py:48-780: intra-numa / inter-numa / inter-node).
# A TPU pod exposes the same laddered topology — e.g. a 3D mesh with two
# ICI dimensions plus DCN — so the schedule generalizes: run each stage on
# the fastest remaining transport while the payload (AG) is still small,
# or so the payload is maximally reduced (RS) before touching slower
# links. ``axes`` is ordered fastest → slowest.


def all_to_all_2d(x: jax.Array, ctx: HierCollectiveContext) -> jax.Array:
    """Two-level all-to-all for EP dispatch across ICI + DCN (the
    reference's inter-node EP domain — DeepEP-style: tutorial 04 /
    low_latency_all_to_all.py run flat; multinode batching is the win).

    ``x``: (w*rows, F) per device — w destination chunks in global rank
    order (rank g = outer*w_inner + inner, the mesh's row-major order).
    Equivalent permutation to a flat ``lax.all_to_all`` over both axes
    (tests assert bit-equality), but the slow (DCN) hop moves ONE large
    (w_inner*rows) block per outer peer instead of w_inner separate
    chunks — fewer, larger inter-node messages, then the fine-grained
    chunk exchange rides ICI.
    """
    w_in, w_out = ctx.inner_size, ctx.outer_size
    spec = P((ctx.outer, ctx.inner))

    def body(xs):
        rows = xs.shape[0] // (w_in * w_out)
        y = lax.all_to_all(xs, ctx.outer, split_axis=0, concat_axis=0,
                           tiled=True)
        t = y.reshape(w_out, w_in, rows, *xs.shape[1:])
        z = t.transpose(1, 0, 2, *range(3, t.ndim)).reshape(
            w_in * w_out * rows, *xs.shape[1:])
        z = lax.all_to_all(z, ctx.inner, split_axis=0, concat_axis=0,
                           tiled=True)
        u = z.reshape(w_in, w_out, rows, *xs.shape[1:]).transpose(
            1, 0, 2, *range(3, t.ndim))
        return u.reshape(w_out * w_in * rows, *xs.shape[1:])

    f = nestable_shard_map(body, mesh=ctx.mesh, in_specs=spec,
                           out_specs=spec, check_vma=False)
    return f(x)


def all_gather_nd(x: jax.Array, mesh: Mesh,
                  axes: tuple[str, ...]) -> jax.Array:
    """Gather dim-0 shards across every axis in ``axes`` (fastest first):
    stage k gathers the stage-(k-1) result over the next-slower transport,
    so each link class carries its payload exactly once (reference 3d AG
    low_latency_allgather.py:617-780)."""
    def body(xs):
        for ax in axes:
            xs = lax.all_gather(xs, ax, tiled=True)
        return xs
    f = nestable_shard_map(body, mesh=mesh, in_specs=P(tuple(reversed(axes))),
                      out_specs=P(), check_vma=False)
    return f(x)


def reduce_scatter_nd(x: jax.Array, mesh: Mesh,
                      axes: tuple[str, ...]) -> jax.Array:
    """Reduce-scatter replicated partials over every axis, fastest first,
    so each slower transport carries payload already divided by the faster
    world sizes. Resulting dim-0 layout is fastest-major
    (``P(axes)``) — the n-level analog of :func:`reduce_scatter_2d`'s
    inner-major note."""
    def body(xs):
        for ax in axes:
            xs = lax.psum_scatter(xs, ax, scatter_dimension=0, tiled=True)
        return xs
    f = nestable_shard_map(body, mesh=mesh, in_specs=P(),
                      out_specs=P(tuple(axes)), check_vma=False)
    return f(x)


def all_reduce_nd(x: jax.Array, mesh: Mesh,
                  axes: tuple[str, ...]) -> jax.Array:
    """AllReduce as RS down the ladder (fastest first), one AR on the
    slowest link over 1/prod(faster worlds) of the data, then AG back up
    (slowest-remaining first) — the n-level extension of
    :func:`all_reduce_2d`'s minimum-slow-traffic schedule."""
    *fast, slow = axes
    def body(xs):
        for ax in fast:
            xs = lax.psum_scatter(xs, ax, scatter_dimension=0, tiled=True)
        xs = lax.psum(xs, slow)
        for ax in reversed(fast):
            xs = lax.all_gather(xs, ax, tiled=True)
        return xs
    f = nestable_shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_vma=False)
    return f(x)
