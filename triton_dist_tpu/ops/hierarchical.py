"""Two-level (hierarchical) collectives over ICI + DCN mesh axes.

TPU-native redesign of the reference's 2D intra/inter-node collectives
(python/triton_dist/kernels/nvidia/reduce_scatter.py:506-673: per-node
staging buffers + intra-node ring + inter-node ring;
low_latency_allgather.py 2d/3d multinode variants; SURVEY.md §7
"Cross-host (DCN) one-sided ops ... the reference's 2D ring intra/inter
split is the right template").

On a multi-host TPU pod the mesh has a fast axis (ICI, within the slice)
and a slow axis (DCN, across hosts). The two-level schedule does the
bandwidth-heavy stage on ICI and moves only the reduced/partial data over
DCN:

- all_gather_2d:     AG over ICI first (big payload on fast links), then
                     AG the ICI-gathered blocks over DCN.
- reduce_scatter_2d: RS over ICI first (reduces payload by the ICI world
                     size before it touches DCN), then RS over DCN.
- all_reduce_2d:     RS(ici) → AR(dcn) → AG(ici): the DCN stage carries
                     1/w_ici of the data.

These compose the per-axis ``lax`` collectives so XLA emits them on the
right transport; the fused Pallas per-axis kernels (ops/allgather,
ops/reduce_scatter) slot in per-axis when explicit overlap is wanted.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


@dataclasses.dataclass
class HierCollectiveContext:
    """Axis naming: ``inner`` = fast transport (ICI), ``outer`` = slow
    (DCN) — the reference's intra-node / inter-node split."""
    mesh: Mesh
    inner: str = "ici"
    outer: str = "dcn"

    @property
    def inner_size(self) -> int:
        return self.mesh.shape[self.inner]

    @property
    def outer_size(self) -> int:
        return self.mesh.shape[self.outer]


def create_hier_context(mesh: Mesh | None = None, inner: str = "ici",
                        outer: str = "dcn") -> HierCollectiveContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return HierCollectiveContext(mesh=mesh, inner=inner, outer=outer)


def _spec2(ctx):
    # data sharded jointly over (outer, inner) on dim 0
    return P((ctx.outer, ctx.inner))


def all_gather_2d(x: jax.Array, ctx: HierCollectiveContext) -> jax.Array:
    """Gather dim-0 shards across both axes: ICI stage then DCN stage
    (reference 2D AG: intra-node ring + inter-node ring,
    low_latency_allgather.py 2d variants)."""
    def body(xs):
        g_in = lax.all_gather(xs, ctx.inner, tiled=True)
        return lax.all_gather(g_in, ctx.outer, tiled=True)
    f = jax.shard_map(body, mesh=ctx.mesh, in_specs=_spec2(ctx),
                      out_specs=P(), check_vma=False)
    return f(x)


def reduce_scatter_2d(x: jax.Array, ctx: HierCollectiveContext) -> jax.Array:
    """Reduce-scatter replicated-per-device partials down to 2D shards:
    ICI RS first so DCN carries 1/w_ici of the bytes (reference
    ``reduce_scatter_2d_op`` reduce_scatter.py:857).

    Note the resulting dim-0 sharding is *inner-major*
    (``P((inner, outer))``): scattering over ICI first fixes the coarse
    block per ICI rank, the DCN stage subdivides it — the transpose of
    the AG layout, exactly like the reference's 2D RS whose per-node
    staging leaves node-interleaved segments.
    """
    def body(xs):
        part = lax.psum_scatter(xs, ctx.inner, scatter_dimension=0,
                                tiled=True)
        return lax.psum_scatter(part, ctx.outer, scatter_dimension=0,
                                tiled=True)
    f = jax.shard_map(body, mesh=ctx.mesh, in_specs=P(),
                      out_specs=P((ctx.inner, ctx.outer)),
                      check_vma=False)
    return f(x)


def all_reduce_2d(x: jax.Array, ctx: HierCollectiveContext) -> jax.Array:
    """AllReduce via RS(ici) → AR(dcn) → AG(ici): minimum DCN traffic
    (the reference's double-tree/2D AR role, allreduce.py:1101)."""
    def body(xs):
        part = lax.psum_scatter(xs, ctx.inner, scatter_dimension=0,
                                tiled=True)
        part = lax.psum(part, ctx.outer)
        return lax.all_gather(part, ctx.inner, tiled=True)
    f = jax.shard_map(body, mesh=ctx.mesh, in_specs=P(), out_specs=P(),
                      check_vma=False)
    return f(x)
