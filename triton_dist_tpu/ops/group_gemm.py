"""Grouped (per-expert) GEMM building blocks for MoE.

TPU-native redesign of the reference's AG-MoE grouped GEMM
(python/triton_dist/kernels/nvidia/allgather_group_gemm.py:608
``ag_group_gemm``: AllGather + group GEMM whose tile schedule follows the
token→expert alignment from csrc/lib/moe_utils.cu:61) and the expert
compute inside MoE-RS (moe_reduce_rs.py:167 gather-grouped GEMM producer).

On TPU the token→block alignment machinery collapses into
``jax.lax.ragged_dot``: tokens sorted by expert + ``group_sizes`` is the
native grouped-GEMM form XLA tiles onto the MXU (see ops/moe_utils.py
``sort_by_group``). What remains of the reference's design is the
*overlap*: the ring variant interleaves ``ppermute`` hops of the token
shards with per-chunk ragged dots so ICI transfers ride under MXU work —
the collective-matmul schedule XLA's latency-hiding scheduler can overlap
(the analog of the reference's producer-AG + consumer-group-GEMM split).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.resilience import resilient
from triton_dist_tpu.ops.common import (
    DEFAULT_VMEM_BUDGET,
    any_spec,
    comm_params,
    nestable_shard_map,
    resolve_interpret,
    round_up,
    sync_interpret)
from triton_dist_tpu.ops.moe_utils import sort_by_group


def grouped_matmul(tokens: jax.Array, w: jax.Array, expert_ids: jax.Array,
                   num_experts: int, acc_dtype=jnp.float32) -> jax.Array:
    """out[i] = tokens[i] @ w[expert_ids[i]] with static shapes.

    Sort-by-expert + ``ragged_dot`` + unsort (the whole
    ``moe_ag_scatter_align_block_size`` pipeline in three ops). Rows with
    ``expert_ids == num_experts`` (invalid/padding) produce garbage rows
    that callers must mask — they are routed through the LAST expert's
    (``num_experts - 1``) weights.
    """
    sorted_tokens, group_sizes, unsort = sort_by_group(
        tokens, expert_ids, num_experts)
    # ragged_dot requires sum(group_sizes) == rows; padding rows (sentinel
    # group) are folded into the last real group, so they run through
    # expert num_experts-1's weights — masked by callers via `valid`.
    pad = tokens.shape[0] - jnp.sum(group_sizes)
    group_sizes = group_sizes.at[num_experts - 1].add(pad)
    out = lax.ragged_dot(
        sorted_tokens, w, group_sizes,
        preferred_element_type=acc_dtype).astype(tokens.dtype)
    return out[unsort]


def grouped_expert_ffn(tokens: jax.Array, w_gate: jax.Array, w_up: jax.Array,
                       w_down: jax.Array, expert_ids: jax.Array,
                       num_experts: int) -> jax.Array:
    """Per-expert SwiGLU FFN over a flat token list (the expert compute of
    Qwen3-MoE, reference models/qwen_moe.py:50-108).

    w_gate/w_up: (E, H, I), w_down: (E, I, H); expert_ids: (T,) int32 with
    ``num_experts`` as the invalid sentinel.
    """
    sorted_tokens, group_sizes, unsort = sort_by_group(
        tokens, expert_ids, num_experts)
    pad = tokens.shape[0] - jnp.sum(group_sizes)
    group_sizes = group_sizes.at[num_experts - 1].add(pad)
    gate = lax.ragged_dot(sorted_tokens, w_gate, group_sizes,
                          preferred_element_type=jnp.float32)
    up = lax.ragged_dot(sorted_tokens, w_up, group_sizes,
                        preferred_element_type=jnp.float32)
    act = (jax.nn.silu(gate) * up).astype(tokens.dtype)
    down = lax.ragged_dot(act, w_down, group_sizes,
                          preferred_element_type=jnp.float32)
    return down.astype(tokens.dtype)[unsort]


def align_tokens_for_tiles(tokens: jax.Array, ids: jax.Array,
                           num_experts: int, m_blk: int):
    """Tile-align tokens by expert (traced; static shapes).

    The TPU analog of the reference's token→tile alignment
    (``moe_ag_scatter_align_block_size`` csrc/lib/moe_utils.cu:61 +
    threadblock_swizzle_ag_moe): rows are expert-sorted and each expert
    group is padded to an ``m_blk`` boundary, so every (m_blk, K) tile of
    the padded layout touches EXACTLY ONE expert — the schedule the fused
    kernel iterates.

    Returns:
      padded: (M_pad, K) expert-sorted, group-padded tokens (pad rows 0).
      tile_experts: (M_pad // m_blk,) int32 expert of each tile.
      dest: (M,) int32 — padded row index of each original row (invalid
        rows, ``ids == num_experts``, collide into the trailing trash
        tile and must be masked by callers).
    """
    m, k = tokens.shape
    e = num_experts
    # Worst case: every group padded by m_blk-1, plus one trash tile.
    m_pad = round_up(m + e * (m_blk - 1), m_blk) + m_blk
    valid = ids < e
    eids = jnp.clip(ids, 0, e - 1)
    sizes = jnp.sum(
        jax.nn.one_hot(jnp.where(valid, eids, e), e + 1, dtype=jnp.int32),
        axis=0)[:e]                                    # live rows per expert
    gs_pad = ((sizes + m_blk - 1) // m_blk) * m_blk
    offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            jnp.cumsum(sizes)[:-1]])
    offs_pad = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(gs_pad)[:-1]])
    order = jnp.argsort(jnp.where(valid, eids, e), stable=True)
    e_sorted = eids[order]
    valid_sorted = valid[order]
    rank_in_group = jnp.arange(m, dtype=jnp.int32) - offs[e_sorted]
    dest_sorted = jnp.where(valid_sorted,
                            offs_pad[e_sorted] + rank_in_group,
                            m_pad - 1)                 # trash slot
    padded = jnp.zeros((m_pad, k), tokens.dtype).at[dest_sorted].set(
        tokens[order])
    dest = jnp.zeros((m,), jnp.int32).at[order].set(dest_sorted)
    tile_starts = jnp.arange(m_pad // m_blk, dtype=jnp.int32) * m_blk
    tile_experts = jnp.clip(
        jnp.searchsorted(jnp.cumsum(gs_pad), tile_starts, side="right"),
        0, e - 1).astype(jnp.int32)
    return padded, tile_experts, dest


def _ag_group_gemm_kernel(x_hbm, te_ref, w_hbm, ag_hbm, c_hbm, a_tile,
                          b_panel, c_stage, copy_sem, a_sem, b_sem, c_sem,
                          send_sem, recv_sem, *, axis: str, world: int,
                          m_pad: int, k: int, n_loc: int, m_blk: int,
                          n_blk: int, acc_dtype):
    """Fused ring-AG + grouped GEMM over the tile-aligned schedule.

    One Pallas kernel per device (VERDICT r2 next 7: the answer to the
    reference's fused producer/consumer, allgather_group_gemm.py:608):
    the ring AG of aligned token chunks runs during the first N-block
    (chunk-boundary ``wait_recv`` ≙ the reference's per-rank signal
    wait); every (m_blk, K) A tile belongs to a single expert, whose
    (K, n_blk) B panel stays resident until the expert RUN ends — the
    sorted schedule makes panel reloads O(#experts), not O(#tiles).
    """
    me = lax.axis_index(axis)
    right = lax.rem(me + 1, world)
    m_tiles = m_pad // m_blk
    n_blocks = n_loc // n_blk
    per_nb = world * m_tiles
    total = n_blocks * per_nb

    cp = pltpu.make_async_copy(
        x_hbm, ag_hbm.at[pl.ds(me * m_pad, m_pad), :], copy_sem)
    cp.start()
    cp.wait()
    if world > 1:
        dl.barrier_all(axis)

    def chunk_idx(i):
        return lax.rem(me - lax.rem(i, per_nb) // m_tiles + world, world)

    def tile_of(i):
        return chunk_idx(i) * m_tiles + lax.rem(i, m_tiles)

    def row_of(i):
        return chunk_idx(i) * m_pad + lax.rem(i, m_tiles) * m_blk

    def chunk_copy(idx):
        return dl.remote_copy(
            ag_hbm.at[pl.ds(idx * m_pad, m_pad), :],
            ag_hbm.at[pl.ds(idx * m_pad, m_pad), :],
            right, send_sem.at[idx], recv_sem.at[idx], axis=axis)

    def a_dma(slot, i):
        return pltpu.make_async_copy(
            ag_hbm.at[pl.ds(row_of(i), m_blk), :], a_tile.at[slot],
            a_sem.at[slot])

    def b_dma(slot, i):
        e = te_ref[tile_of(i)]
        return pltpu.make_async_copy(
            w_hbm.at[e, :, pl.ds((i // per_nb) * n_blk, n_blk)],
            b_panel.at[slot], b_sem.at[slot])

    def need_b(i):
        # Panel reloads happen at N-block starts and expert-run
        # boundaries only (the point of the aligned schedule).
        prev = jnp.maximum(i - 1, 0)
        return (lax.rem(i, per_nb) == 0) | (
            te_ref[tile_of(i)] != te_ref[tile_of(prev)])

    def c_dma(slot, i):
        return pltpu.make_async_copy(
            c_stage.at[slot],
            c_hbm.at[pl.ds(row_of(i), m_blk),
                     pl.ds((i // per_nb) * n_blk, n_blk)],
            c_sem.at[slot])

    def ring_advance(i):
        if world == 1:
            return

        @pl.when((i < per_nb) & (lax.rem(i, m_tiles) == 0))
        def _():
            s = i // m_tiles

            @pl.when(s > 0)
            def _():
                chunk_copy(chunk_idx(i)).wait_recv()

            @pl.when(s < world - 1)
            def _():
                chunk_copy(chunk_idx(i)).start()

    ring_advance(0)
    a_dma(0, 0).start()
    b_dma(0, 0).start()

    def step(i, cur):
        """``cur`` carries the slot holding tile i-1's panel; reloads
        alternate slots, and the NEXT reload is prefetched one tile
        ahead (the expert schedule is known in te_ref), so panel
        fetches ride under the current run's dots instead of stalling
        the MXU (code-review r3b finding 4)."""
        slot = lax.rem(i, 2)
        ring_advance(i + 1)

        @pl.when(i + 1 < total)
        def _():
            a_dma(lax.rem(i + 1, 2), i + 1).start()

        nb_i = need_b(i)

        @pl.when(nb_i)
        def _():
            b_dma(1 - cur, i).wait()
        cur = jnp.where(nb_i, 1 - cur, cur)

        @pl.when((i + 1 < total) & need_b(i + 1))
        def _():
            b_dma(1 - cur, i + 1).start()   # prefetch next panel

        a_dma(slot, i).wait()
        out = jnp.dot(a_tile[slot], b_panel[cur],
                      preferred_element_type=acc_dtype)

        @pl.when(i >= 2)
        def _():
            c_dma(slot, i - 2).wait()
        c_stage[slot] = out.astype(c_stage.dtype)
        c_dma(slot, i).start()
        return cur

    lax.fori_loop(0, total, step, jnp.int32(1))
    for i_last in range(max(0, total - 2), total):
        c_dma(i_last % 2, i_last).wait()

    if world > 1:
        def drain(s, _):
            chunk_copy(lax.rem(me - s + world, world)).wait_send()
            return _
        lax.fori_loop(0, world - 1, drain, None)


@dataclasses.dataclass
class AGGroupGEMMContext:
    """Analog of ``create_ag_group_gemm_context``
    (allgather_group_gemm.py): mesh/axis + schedule choice."""
    mesh: Mesh
    axis: str = "tp"
    ring: bool = True   # ring-overlap schedule vs one-shot AG
    interpret: bool | None = None
    # Tile sizes for the fused Pallas kernel (impl="fused").
    block_m: int = 128
    block_n: int = 512
    vmem_budget: int = DEFAULT_VMEM_BUDGET

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]


def create_ag_group_gemm_context(mesh: Mesh | None = None, axis: str = "tp",
                                 ring: bool = True) -> AGGroupGEMMContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return AGGroupGEMMContext(mesh=mesh, axis=axis, ring=ring)


#: impl="auto" winners keyed by problem shape (in-process; the autotuner
#: adds the cross-run disk cache).
_IMPL_TUNED: dict = {}


@resilient("ag_group_gemm", fused_impls=("fused", "auto"))
def ag_group_gemm(x: jax.Array, w: jax.Array, expert_ids: jax.Array,
                  num_experts: int, ctx: AGGroupGEMMContext | None = None,
                  impl: str = "ring") -> jax.Array:
    """C = group_gemm(allgather(x), w) — TP-MoE first projection
    (reference ``ag_group_gemm`` allgather_group_gemm.py:608).

    Args:
      x: (M, K) row-sharded over ``ctx.axis``; one expert id per row.
      w: (E, K, N) with N column-sharded over ``ctx.axis``.
      expert_ids: (M,) int32 row→expert, row-sharded like x.
    Returns:
      (M, N/world) per device — full gathered M rows against the local
      N-shard, column-sharded overall.

    ``impl="ring"``: w-1 ``ppermute`` hops; chunk s's ragged dot runs
    while chunk s+1 is in flight (collective matmul — the overlap the
    reference gets from its producer/consumer split).
    ``impl="fused"``: ONE Pallas kernel — in-kernel ring AG of
    tile-aligned expert-sorted chunks feeding tiled MXU dots
    (:func:`_ag_group_gemm_kernel`; the reference's fused design,
    allgather_group_gemm.py:608).
    ``impl="xla"``: one-shot all-gather golden.
    ``impl="auto"``: measure ring vs fused once per shape (autotuner,
    disk-cached across processes) and use the winner — the r3 chip
    measurement had fused ahead (1.224 vs 1.344 ms at bench shape),
    but the winner is shape-dependent.
    """
    ctx = ctx or create_ag_group_gemm_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    m, k = x.shape
    assert w.ndim == 3 and w.shape[1] == k

    if impl == "auto":
        shape_key = (m, k, w.shape[0], w.shape[2], str(x.dtype), world)
        tune_key = f"ag_gg_impl:{shape_key}"
        choice = _IMPL_TUNED.get(shape_key)
        if choice is None and not isinstance(x, jax.core.Tracer):
            from triton_dist_tpu.tools.autotuner import autotune
            from triton_dist_tpu.runtime.utils import make_perturbed_runner

            def make_fn(impl):
                fn = jax.jit(lambda xv: ag_group_gemm(
                    xv, w, expert_ids, num_experts, ctx, impl=impl))
                return make_perturbed_runner(fn, x)

            res = autotune(make_fn, [{"impl": "ring"}, {"impl": "fused"}],
                           key=tune_key, iters=8, warmup_iters=2)
            choice = _IMPL_TUNED[shape_key] = res.config["impl"]
        elif choice is None:
            # Traced: a prior run's disk-cached winner still counts —
            # single-controller only, warns once on a miss (ADVICE r4;
            # see consult_disk_for_trace).
            from triton_dist_tpu.tools.autotuner import (
                consult_disk_for_trace)
            hit = consult_disk_for_trace(tune_key)
            if hit is not None:
                choice = _IMPL_TUNED[shape_key] = hit.config["impl"]
        impl = choice or "ring"   # no sweep, no cache: ring default

    if impl == "fused":
        return _ag_group_gemm_fused(x, w, expert_ids, num_experts, ctx)

    def oneshot(xs, ids, ws):
        ag = lax.all_gather(xs, axis, tiled=True)
        ag_ids = lax.all_gather(ids, axis, tiled=True)
        return grouped_matmul(ag, ws, ag_ids, num_experts)

    def ring(xs, ids, ws):
        me = lax.axis_index(axis)
        rows = xs.shape[0]
        out = jnp.zeros((rows * world, ws.shape[-1]), xs.dtype)

        def step(s, carry):
            out, cur_x, cur_ids = carry
            src = lax.rem(me - s + world, world)
            # Launch the next hop first so XLA can overlap it with the dot.
            perm = [(i, (i + 1) % world) for i in range(world)]
            nxt_x = lax.ppermute(cur_x, axis, perm)
            nxt_ids = lax.ppermute(cur_ids, axis, perm)
            chunk_out = grouped_matmul(cur_x, ws, cur_ids, num_experts)
            out = lax.dynamic_update_slice(out, chunk_out,
                                           (src * rows, jnp.int32(0)))
            return out, nxt_x, nxt_ids

        out, last_x, last_ids = lax.fori_loop(
            0, world - 1, step, (out, xs, ids))
        src = lax.rem(me - (world - 1) + world, world)
        chunk_out = grouped_matmul(last_x, ws, last_ids, num_experts)
        out = lax.dynamic_update_slice(out, chunk_out,
                                       (src * rows, jnp.int32(0)))
        return out

    body = oneshot if (impl == "xla" or world == 1) else ring
    f = nestable_shard_map(body, mesh=mesh,
                      in_specs=(P(axis), P(axis), P(None, None, axis)),
                      out_specs=P(None, axis), check_vma=False)
    return f(x, expert_ids, w)


def _ag_group_gemm_fused(x, w, expert_ids, num_experts, ctx):
    """Entry for the fused Pallas AG + grouped-GEMM kernel."""
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    m, k = x.shape
    e, _, n = w.shape
    n_loc = n // world
    m_loc = m // world
    interpret = resolve_interpret(ctx.interpret)

    # m_blk need not divide m_loc — the alignment pass pads per group.
    m_blk = ctx.block_m
    m_pad = round_up(m_loc + num_experts * (m_blk - 1), m_blk) + m_blk
    n_blk = ctx.block_n
    while n_blk > n_loc or n_loc % n_blk:
        n_blk //= 2
    n_blk = max(n_blk, 1)
    # 2 B panels (double-buffered prefetch) + A tiles + C stages must
    # fit the budget.
    item = x.dtype.itemsize
    while n_blk > 128 and (2 * k * n_blk + 2 * m_blk * k
                           + 2 * m_blk * n_blk) * item > ctx.vmem_budget:
        n_blk //= 2

    kernel = functools.partial(
        _ag_group_gemm_kernel, axis=axis, world=world, m_pad=m_pad, k=k,
        n_loc=n_loc, m_blk=m_blk, n_blk=n_blk, acc_dtype=jnp.float32)

    def body(xs, ids_s, ws):
        padded, tile_e, dest = align_tokens_for_tiles(
            xs, ids_s, num_experts, m_blk)
        tile_e_all = lax.all_gather(tile_e, axis, tiled=True)
        dest_all = lax.all_gather(dest, axis, tiled=True)
        _, cpad = pl.pallas_call(
            kernel,
            out_shape=(jax.ShapeDtypeStruct((world * m_pad, k), x.dtype),
                       jax.ShapeDtypeStruct((world * m_pad, n_loc),
                                            x.dtype)),
            in_specs=[any_spec(),
                      pl.BlockSpec(memory_space=pltpu.SMEM),
                      any_spec()],
            out_specs=(any_spec(), any_spec()),
            scratch_shapes=[
                pltpu.VMEM((2, m_blk, k), x.dtype),
                pltpu.VMEM((2, k, n_blk), x.dtype),
                pltpu.VMEM((2, m_blk, n_blk), x.dtype),
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((world,)),
                pltpu.SemaphoreType.DMA((world,)),
            ],
            compiler_params=comm_params(collective_id=8, world=world),
            interpret=interpret,
        )(padded, tile_e_all, ws)
        # Unsort: global row j lives at chunk(j)*m_pad + dest_all[j].
        rows = (jnp.arange(world * m_loc) // m_loc) * m_pad + dest_all
        return cpad[rows]

    f = nestable_shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), P(axis), P(None, None, axis)),
        out_specs=P(None, axis), check_vma=False)
    return sync_interpret(f(x, expert_ids, w), interpret)
