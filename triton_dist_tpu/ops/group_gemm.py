"""Grouped (per-expert) GEMM building blocks for MoE.

TPU-native redesign of the reference's AG-MoE grouped GEMM
(python/triton_dist/kernels/nvidia/allgather_group_gemm.py:608
``ag_group_gemm``: AllGather + group GEMM whose tile schedule follows the
token→expert alignment from csrc/lib/moe_utils.cu:61) and the expert
compute inside MoE-RS (moe_reduce_rs.py:167 gather-grouped GEMM producer).

On TPU the token→block alignment machinery collapses into
``jax.lax.ragged_dot``: tokens sorted by expert + ``group_sizes`` is the
native grouped-GEMM form XLA tiles onto the MXU (see ops/moe_utils.py
``sort_by_group``). What remains of the reference's design is the
*overlap*: the ring variant interleaves ``ppermute`` hops of the token
shards with per-chunk ragged dots so ICI transfers ride under MXU work —
the collective-matmul schedule XLA's latency-hiding scheduler can overlap
(the analog of the reference's producer-AG + consumer-group-GEMM split).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.ops.moe_utils import sort_by_group


def grouped_matmul(tokens: jax.Array, w: jax.Array, expert_ids: jax.Array,
                   num_experts: int, acc_dtype=jnp.float32) -> jax.Array:
    """out[i] = tokens[i] @ w[expert_ids[i]] with static shapes.

    Sort-by-expert + ``ragged_dot`` + unsort (the whole
    ``moe_ag_scatter_align_block_size`` pipeline in three ops). Rows with
    ``expert_ids == num_experts`` (invalid/padding) produce garbage rows
    that callers must mask — they are routed through the LAST expert's
    (``num_experts - 1``) weights.
    """
    sorted_tokens, group_sizes, unsort = sort_by_group(
        tokens, expert_ids, num_experts)
    # ragged_dot requires sum(group_sizes) == rows; padding rows (sentinel
    # group) are folded into the last real group, so they run through
    # expert num_experts-1's weights — masked by callers via `valid`.
    pad = tokens.shape[0] - jnp.sum(group_sizes)
    group_sizes = group_sizes.at[num_experts - 1].add(pad)
    out = lax.ragged_dot(
        sorted_tokens, w, group_sizes,
        preferred_element_type=acc_dtype).astype(tokens.dtype)
    return out[unsort]


def grouped_expert_ffn(tokens: jax.Array, w_gate: jax.Array, w_up: jax.Array,
                       w_down: jax.Array, expert_ids: jax.Array,
                       num_experts: int) -> jax.Array:
    """Per-expert SwiGLU FFN over a flat token list (the expert compute of
    Qwen3-MoE, reference models/qwen_moe.py:50-108).

    w_gate/w_up: (E, H, I), w_down: (E, I, H); expert_ids: (T,) int32 with
    ``num_experts`` as the invalid sentinel.
    """
    sorted_tokens, group_sizes, unsort = sort_by_group(
        tokens, expert_ids, num_experts)
    pad = tokens.shape[0] - jnp.sum(group_sizes)
    group_sizes = group_sizes.at[num_experts - 1].add(pad)
    gate = lax.ragged_dot(sorted_tokens, w_gate, group_sizes,
                          preferred_element_type=jnp.float32)
    up = lax.ragged_dot(sorted_tokens, w_up, group_sizes,
                        preferred_element_type=jnp.float32)
    act = (jax.nn.silu(gate) * up).astype(tokens.dtype)
    down = lax.ragged_dot(act, w_down, group_sizes,
                          preferred_element_type=jnp.float32)
    return down.astype(tokens.dtype)[unsort]


@dataclasses.dataclass
class AGGroupGEMMContext:
    """Analog of ``create_ag_group_gemm_context``
    (allgather_group_gemm.py): mesh/axis + schedule choice."""
    mesh: Mesh
    axis: str = "tp"
    ring: bool = True   # ring-overlap schedule vs one-shot AG

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]


def create_ag_group_gemm_context(mesh: Mesh | None = None, axis: str = "tp",
                                 ring: bool = True) -> AGGroupGEMMContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return AGGroupGEMMContext(mesh=mesh, axis=axis, ring=ring)


def ag_group_gemm(x: jax.Array, w: jax.Array, expert_ids: jax.Array,
                  num_experts: int, ctx: AGGroupGEMMContext | None = None,
                  impl: str = "ring") -> jax.Array:
    """C = group_gemm(allgather(x), w) — TP-MoE first projection
    (reference ``ag_group_gemm`` allgather_group_gemm.py:608).

    Args:
      x: (M, K) row-sharded over ``ctx.axis``; one expert id per row.
      w: (E, K, N) with N column-sharded over ``ctx.axis``.
      expert_ids: (M,) int32 row→expert, row-sharded like x.
    Returns:
      (M, N/world) per device — full gathered M rows against the local
      N-shard, column-sharded overall.

    ``impl="ring"``: w-1 ``ppermute`` hops; chunk s's ragged dot runs
    while chunk s+1 is in flight (collective matmul — the overlap the
    reference gets from its producer/consumer split).
    ``impl="xla"``: one-shot all-gather golden.
    """
    ctx = ctx or create_ag_group_gemm_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    m, k = x.shape
    assert w.ndim == 3 and w.shape[1] == k

    def oneshot(xs, ids, ws):
        ag = lax.all_gather(xs, axis, tiled=True)
        ag_ids = lax.all_gather(ids, axis, tiled=True)
        return grouped_matmul(ag, ws, ag_ids, num_experts)

    def ring(xs, ids, ws):
        me = lax.axis_index(axis)
        rows = xs.shape[0]
        out = jnp.zeros((rows * world, ws.shape[-1]), xs.dtype)

        def step(s, carry):
            out, cur_x, cur_ids = carry
            src = lax.rem(me - s + world, world)
            # Launch the next hop first so XLA can overlap it with the dot.
            perm = [(i, (i + 1) % world) for i in range(world)]
            nxt_x = lax.ppermute(cur_x, axis, perm)
            nxt_ids = lax.ppermute(cur_ids, axis, perm)
            chunk_out = grouped_matmul(cur_x, ws, cur_ids, num_experts)
            out = lax.dynamic_update_slice(out, chunk_out,
                                           (src * rows, jnp.int32(0)))
            return out, nxt_x, nxt_ids

        out, last_x, last_ids = lax.fori_loop(
            0, world - 1, step, (out, xs, ids))
        src = lax.rem(me - (world - 1) + world, world)
        chunk_out = grouped_matmul(last_x, ws, last_ids, num_experts)
        out = lax.dynamic_update_slice(out, chunk_out,
                                       (src * rows, jnp.int32(0)))
        return out

    body = oneshot if (impl == "xla" or world == 1) else ring
    f = jax.shard_map(body, mesh=mesh,
                      in_specs=(P(axis), P(axis), P(None, None, axis)),
                      out_specs=P(None, axis), check_vma=False)
    return f(x, expert_ids, w)
