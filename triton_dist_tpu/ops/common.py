"""Shared helpers for the kernel library (reference
python/triton_dist/kernels/nvidia/common_ops.py — barriers, signal ops —
plus the per-op boilerplate every kernel repeats)."""

from __future__ import annotations

import functools

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu import obs
from triton_dist_tpu.obs import record_comm  # noqa: F401  (op entries)
from triton_dist_tpu.runtime.platform import default_interpret


# -- jax version compat -----------------------------------------------------
# The library targets the current jax API
# (jax.sharding.get_abstract_mesh/AxisType, pltpu.CompilerParams /
# InterpretParams); jax 0.4.x has no abstract-mesh tracking and spells
# the params pltpu.TPUCompilerParams. These helpers keep one compat
# site per concept instead of hasattr checks at each use. (The
# jax.shard_map check_vma→check_rep alias lives in the package
# __init__ — tests and examples call it directly too.)

def _abstract_mesh():
    """Current thread's AbstractMesh, or None when this jax either has
    no tracking (0.4.x) or reports an empty context."""
    gam = getattr(jax.sharding, "get_abstract_mesh", None)
    if gam is None:
        return None
    am = gam()
    if am is None or getattr(am, "empty", True):
        return None
    return am


def _manual_axis_flags(am) -> list[bool]:
    """Per-axis is-Manual flags of an AbstractMesh; [] when this jax
    does not expose axis types."""
    axis_types = getattr(am, "axis_types", None)
    manual = getattr(jax.sharding, "AxisType", None)
    if axis_types is None or manual is None:
        return []
    return [t == manual.Manual for t in axis_types]


# NOTE: jax.shard_map itself always exists here — the package __init__
# installs a check_vma→check_rep translating alias on jax 0.4.x before
# this module can load — so call sites use jax.shard_map directly.


def resolve_interpret(interpret: bool | None):
    """Auto-select interpret mode: compiled on TPU, interpreted elsewhere.

    Interpreted kernels simulate remote DMA + semaphores on a multi-device
    CPU mesh — the framework's single-process distributed test mode.

    With ``TDT_DETECT_RACES=1`` the interpreter's vector-clock race
    detector is enabled: missing semaphore waits in kernel signal
    protocols are reported as data races. This is the framework's race
    sanitizer — the reference has no equivalent (SURVEY.md §5 "no custom
    sanitizer"; it relies on sleep-injection + stress runs).
    """
    import os
    if interpret is None:
        interpret = default_interpret()
    if interpret:
        # A *mixed* mesh context — some axes already Manual (an enclosing
        # user shard_map, e.g. a DP wrap) while this op's axis is still
        # Auto — means the op's own shard_map will nest, which the
        # interpreter cannot lower (io_callback trips an XLA
        # sharding-validation CHECK). All-Manual (called from inside a
        # kernel-level shard_map body) and empty (host) contexts are the
        # normal working paths.
        am = _abstract_mesh()
        if am is not None:
            manual = _manual_axis_flags(am)
            if any(manual) and not all(manual):
                raise NotImplementedError(
                    "interpret-mode Pallas cannot run nested inside an "
                    "outer manual shard_map. Under DP composition on the "
                    "CPU simulator use impl='xla'; compiled TPU mode is "
                    "the path for nested fused kernels.")
        from triton_dist_tpu.runtime.interpret_compat import (
            patch_interpreter_spin)
        patch_interpreter_spin()
        interpret_params = getattr(pltpu, "InterpretParams", None)
        if interpret_params is None:
            # jax 0.4.x: no TPU-interpret parameter object (and no race
            # detector) — plain interpret mode is the best available.
            return True
        return interpret_params(
            detect_races=bool(os.environ.get("TDT_DETECT_RACES")))
    return False


def sync_interpret(out, interpret) -> object:
    """Block on eager interpret-mode results before returning.

    JAX dispatches asynchronously: an interpreted multi-device kernel may
    still be executing (its device programs + io_callbacks occupying CPU
    client pool threads) when the caller dispatches follow-on
    computations into the same pool — on low-core hosts the queued work
    can starve the in-flight kernel's device programs: a resource
    deadlock (observed: TP_Attn xla-then-ag_rs hang). Compiled TPU
    kernels don't need this; under jit tracing outputs are Tracers and
    are passed through untouched.
    """
    if not interpret:
        return out
    leaves = jax.tree_util.tree_leaves(out)
    if any(isinstance(leaf, jax.core.Tracer) for leaf in leaves):
        return out
    return jax.block_until_ready(out)


#: Mosaic scoped-VMEM limit requested for every comm kernel. Mosaic's
#: default cap is 16 MB, but a v5e core has 128 MB of physical VMEM
#: (public TPU flash kernels run with vmem_limit_bytes up to 128 MB);
#: the round-5 on-chip compile of the fused SP kernel was rejected at
#: 16.14 MB scoped for ~7.4 MB of declared scratch. 64 MB absorbs that
#: overhead for every budget-sized shape while leaving headroom for
#: XLA's own scoped uses.
VMEM_LIMIT_BYTES = 64 * 1024 * 1024

#: Ceiling on a kernel's DECLARED scratch footprint. Mosaic's scoped
#: accounting carries roughly 2.2x of window/staging overhead on top of
#: the declared buffers (measured round-5: 16.14 MB scoped for ~7.4 MB
#: declared), so declared footprints up to ~26 MB compile under
#: :data:`VMEM_LIMIT_BYTES`. Config tables list over-soft-budget
#: "aggressive tier" entries up to this cap for the autotuner; the
#: per-op clamps reject anything beyond it so an uncompilable config
#: never reaches Mosaic (BENCH_r02).
HARD_FOOTPRINT_CAP = 26 * 1024 * 1024

#: Soft VMEM budget the fused ops' "auto" tile choice and default-path
#: clamps target. Back to the PROVEN 12 MB (ADVICE r5 medium 2): the
#: round-5 default path compiled on chip under 12 MB, and the 24 MB
#: raise that round introduced was never revalidated there — an
#: unproven default is the BENCH_r02 crash class waiting to recur. The
#: larger declared footprints the raise was after are still reachable,
#: but only through paths with per-config compile-failure isolation:
#: autotune sweeps and tuned winners run against
#: :data:`TUNED_VMEM_BUDGET` / :data:`HARD_FOOTPRINT_CAP` (the sweep
#: scores a config that fails to compile as inf instead of crashing).
DEFAULT_VMEM_BUDGET = 12 * 1024 * 1024

#: Budget-tier boundary for AUTOTUNE candidate tables (the round-5
#: value DEFAULT_VMEM_BUDGET briefly held): 24 MB declared x the
#: measured ~2.2x scoped overhead ~= 53 MB, under the 64 MB
#: :data:`VMEM_LIMIT_BYTES` with margin. Only swept / trust_blocks
#: paths — which carry per-config failure isolation — use it; the
#: default path keeps :data:`DEFAULT_VMEM_BUDGET` until
#: ``smoke_revalidate`` passes these shapes on chip.
TUNED_VMEM_BUDGET = 24 * 1024 * 1024
assert DEFAULT_VMEM_BUDGET < TUNED_VMEM_BUDGET < HARD_FOOTPRINT_CAP


def cap_config_tiers(budget_cfgs, aggressive_cfgs, n_budget: int = 5,
                     n_aggressive: int = 4):
    """Prune an autotune config table for sweep tractability: each
    entry costs a ~30 s cold Mosaic compile on chip, so keep the
    ``n_budget`` best in-budget entries and ``n_aggressive`` best
    aggressive (over-soft-budget) entries. Both lists are generated
    best-first (larger block_n = fewer A re-reads, then larger
    block_m), so a prefix of each preserves the heuristic ranking.
    Callers pass the tiers as separate lists — tier membership is
    decided once, at generation (review r5l finding 2: re-deriving it
    in a closure invited drift), and fallback variants a downstream
    clamp depends on (hbm_kt) must be appended by the caller OUTSIDE
    the cap so pruning can never remove them (r5l finding 1)."""
    return budget_cfgs[:n_budget] + aggressive_cfgs[:n_aggressive]


def record_overlap(op: str, cost, world: int | None = None,
                   dirs: int | None = None) -> None:
    """Per-op overlap gauges from a :class:`tools.perf_model
    .FusedGemmCost` breakdown: ``comms.<op>.overlap_pct`` (hidden
    fraction of the ring communication under the chosen tile schedule —
    the BASELINE.md >=90% north-star metric, previously only derivable
    by hand from bench ingredients) and ``comms.<op>.exposed_comm_ms``.

    Model-derived from the tile-loop timing structure at DISPATCH time
    (trace time under jit, like ``record_comm``), not a trace
    decomposition — bench.py's ``comms.<op>.overlap_pct`` extras carry
    the measured counterpart on chip. At world=1 there is no
    communication to expose, so the gauge reads 100.

    With event tracing on and ``world``/``dirs`` passed, the ring
    schedule additionally lands on the timeline as per-chunk
    begin/end events (``comms.<op>.compute`` / ``comms.<op>.comm``
    tracks) so ``tools/trace_export.py --overlap`` reconstructs
    overlap from the trace's interval geometry rather than from this
    gauge (docs/observability.md "Tracing")."""
    from triton_dist_tpu.obs import trace as _trace
    if obs.enabled():
        obs.gauge(f"comms.{op}.overlap_pct").set(cost.overlap_pct)
        obs.gauge(f"comms.{op}.exposed_comm_ms").set(
            cost.exposed_comm_ms)
    if _trace.enabled() and world is not None and world > 1:
        _trace.ring_schedule_events(
            op, world=world, dirs=dirs if dirs is not None else 1,
            compute_ms=cost.compute_ms, comm_ms=cost.comm_ms)


def comm_params(collective_id: int | None = 0,
                vmem_limit_bytes: int | None = None,
                world: int | None = None) -> pltpu.CompilerParams:
    """CompilerParams for kernels that communicate: side effects must be kept
    (DMA-only kernels would be DCE'd) and a collective_id is required for the
    global barrier semaphore.

    At ``world == 1`` kernels skip ``dl.barrier_all`` so no barrier semaphore
    exists — Mosaic then rejects a ``collective_id`` ("has to be unspecified
    ... when not using a custom barrier").

    ``vmem_limit_bytes`` defaults to :data:`VMEM_LIMIT_BYTES`; pass an
    explicit value only to tighten it for a specific kernel."""
    kwargs = dict(has_side_effects=True)
    if world != 1 and collective_id is not None:
        kwargs["collective_id"] = collective_id
    limit = (VMEM_LIMIT_BYTES if vmem_limit_bytes is None
             else vmem_limit_bytes)
    kwargs["vmem_limit_bytes"] = limit
    if obs.enabled():
        # Requested-vs-declared VMEM gauges (docs/observability.md):
        # the scoped limit each comm kernel asks Mosaic for, next to
        # the declared-footprint budget/cap the tile choosers target —
        # the pair whose confusion ADVICE r5 flagged.
        obs.gauge("vmem.scoped_limit_bytes").set(limit)
        obs.gauge("vmem.declared_budget_bytes").set(DEFAULT_VMEM_BUDGET)
        obs.gauge("vmem.declared_cap_bytes").set(HARD_FOOTPRINT_CAP)
    params_cls = getattr(pltpu, "CompilerParams", None)
    if params_cls is None:
        # jax 0.4.x name; it also lacks some fields (has_side_effects)
        # — drop what it cannot carry rather than TypeError the whole
        # kernel build.
        import dataclasses
        params_cls = pltpu.TPUCompilerParams
        known = {f.name for f in dataclasses.fields(params_cls)}
        kwargs = {k: v for k, v in kwargs.items() if k in known}
    return params_cls(**kwargs)


def maybe_straggle(straggler_option, axis: str, interpret=False) -> None:
    """Spin one rank before it starts communicating
    (reference ``straggler_option`` / ``_run_straggler``,
    allreduce.py:137): correctness must not depend on rank arrival
    order. ``pl.delay`` is a hardware spin — skipped in interpret mode,
    where the interpreter's own thread scheduling provides the skew."""
    if straggler_option is None or interpret:
        return
    from jax import lax
    rank, cycles = straggler_option

    @pl.when(lax.axis_index(axis) == rank)
    def _():
        pl.delay(cycles)


def maybe_noise(for_correctness: bool, axis: str, world: int,
                salt: int = 0, base_cycles: int = 512,
                interpret=False) -> None:
    """Per-rank pseudo-random delay for correctness-debug runs
    (reference ``for_correctness`` sleep injection, allgather.py:74-79,
    allgather_gemm.py:507-508): shakes the rank schedule so stale-signal
    / missing-wait bugs reproduce instead of hiding behind lockstep
    timing. Deterministic per (rank, salt) so failures replay."""
    if not for_correctness or interpret or world <= 1:
        return
    from jax import lax
    me = lax.axis_index(axis)
    for r in range(world):
        amt = ((r * 2654435761 + salt * 40503) >> 7) % 8 + 1

        @pl.when(me == r)
        def _(amt=amt):
            pl.delay(base_cycles * amt)


# -- bidirectional ring scheduling ------------------------------------------
# ICI links are full duplex, so a ring collective can run both directions
# at once: chunks travel the SHORTER way round and the hop count halves
# (ops/allgather.py RING_BIDIR documents the win for the plain
# collective). These helpers give the fused GEMM kernels the same
# schedule: a rank-rotated consumption order that starts at the local
# chunk and then alternates between arrivals from the left (forward
# ring) and the right (backward ring).

def resolve_ring_dirs(ring_dirs: int = 0) -> int:
    """Ring direction count for the fused comm-GEMM schedules.

    ``2`` = bidirectional (default), ``1`` = the unidirectional
    proven-on-chip fallback. ``0`` consults ``TDT_RING_DIRS`` (so the
    round-5-measured schedule stays selectable without code changes)
    and falls back to 2.
    """
    if ring_dirs not in (0, 1, 2):
        raise ValueError(f"ring_dirs must be 0 (auto), 1 or 2: {ring_dirs}")
    if ring_dirs:
        return ring_dirs
    env = obs.env_int("TDT_RING_DIRS", 2)
    if env not in (1, 2):
        raise ValueError(f"TDT_RING_DIRS must be 1 or 2: {env!r}")
    return env


def ring_hop_counts(world: int, dirs: int) -> tuple[int, int]:
    """(forward, backward) hop counts of the ring schedule. Odd worlds
    split the w-1 travelling chunks as ceil/floor; world <= 2 has no
    shorter way round, so bidir degenerates to the unidirectional ring
    (same split as ``ops/allgather._ring_ag_kernel``)."""
    if world <= 1:
        return 0, 0
    if dirs == 1 or world == 2:
        return world - 1, 0
    n_bwd = (world - 1) // 2
    return (world - 1) - n_bwd, n_bwd


def ring_chunk_schedule(me, s, world: int, dirs: int):
    """Chunk consumed at position ``s`` of the rank-rotated schedule.

    dirs=1: chunk ``me - s`` (all forward — today's proven order).
    dirs=2: own chunk first, then alternating arrivals from the left
    (forward ring: me-1, me-2, ...) and the right (backward ring: me+1,
    me+2, ...); even worlds end with a forward-only tail because the
    backward ring carries floor((w-1)/2) chunks.

    Returns ``(chunk, is_bwd, offset)``: ``offset`` is the hop count
    from the chunk's origin rank to this rank along its travel
    direction (0 for the local chunk). ``me``/``s`` may be traced;
    ``world``/``dirs`` are static.
    """
    import jax.numpy as jnp
    from jax import lax
    if dirs == 1 or world <= 2:
        off = jnp.asarray(s, jnp.int32)
        chunk = lax.rem(me - off + world, world)
        return chunk, jnp.zeros((), jnp.bool_), off
    s = jnp.asarray(s, jnp.int32)
    n_bwd = (world - 1) // 2
    in_alt = s <= 2 * n_bwd
    is_bwd = in_alt & (lax.rem(s, 2) == 0) & (s > 0)
    off = jnp.where(in_alt, jnp.where(is_bwd, s // 2, (s + 1) // 2),
                    s - n_bwd)
    chunk = lax.rem(jnp.where(is_bwd, me + off, me - off) + world, world)
    return chunk, is_bwd, off


def vmem_spec(block_shape=None, index_map=None):
    return pl.BlockSpec(block_shape, index_map, memory_space=pltpu.VMEM)


def any_spec():
    return pl.BlockSpec(memory_space=pl.ANY)


def cdiv(a: int, b: int) -> int:
    return -(-a // b)


def pow2_round(n: int) -> int:
    """Smallest power of two >= ``n`` (0 stays 0)."""
    n = int(n)
    if n <= 0:
        return 0
    return 1 << (n - 1).bit_length()


def shape_bucket(*arrays) -> str:
    """Power-of-two-rounded shape signature of an op call's array
    operands, e.g. ``"2048x4096:bfloat16,4096x4096:bfloat16"`` — the
    pooling key for the live perf-ratio watch (``obs.perfwatch``).
    Coarser than the resilience config key on purpose: a serving
    process sees few distinct shapes but many calls, and nearby shapes
    share a performance regime, while a 64x size difference never
    pools."""
    return ",".join(
        "x".join(str(pow2_round(d)) for d in a.shape) + f":{a.dtype}"
        for a in arrays
        if hasattr(a, "shape") and hasattr(a, "dtype"))


def round_up(a: int, b: int) -> int:
    return cdiv(a, b) * b


@functools.cache
def min_tile(dtype) -> tuple[int, int]:
    """Minimum TPU tile (sublane, lane) for ``dtype`` — layout constraint for
    block shapes (pallas_guide: Tiling Constraints)."""
    import jax.numpy as jnp
    dtype = jnp.dtype(dtype)
    sublane = {4: 8, 2: 16, 1: 32}[dtype.itemsize]
    return (sublane, 128)


def nestable_shard_map(fn, *, mesh=None, in_specs, out_specs,
                       check_vma: bool = False):
    """``jax.shard_map`` for op entry points, callable inside an enclosing
    shard_map.

    When an op runs under an outer manual region — e.g. the user wraps a
    whole model step in ``shard_map(..., axis_names={"dp"})`` for data
    parallelism and the op communicates along "tp" inside it — the inner
    shard_map must reuse the context's AbstractMesh (passing the concrete
    mesh raises a context-mismatch error). Inside the nested region every
    mesh axis is manual, so ``language.logical_device_id`` sees the outer
    (dp) coordinate via ``lax.axis_index`` and remote DMAs stay within the
    dp slice — every fused op composes with outer DP/FSDP axes,
    parallelism the reference delegates to torchrun replication
    (SURVEY.md §2.9 "DP: not a subsystem").
    """
    am = _abstract_mesh()
    if am is not None and any(_manual_axis_flags(am)):
        mesh = am
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=check_vma)


def shard_map_1d(fn, mesh, axis: str = "tp"):
    """Wrap ``fn`` in a shard_map over a single mesh axis with everything
    sharded on its leading dim. Convenience for op entry points."""
    from jax.sharding import PartitionSpec as P
    spec = P(axis)
    return nestable_shard_map(fn, mesh=mesh, in_specs=spec, out_specs=spec)
