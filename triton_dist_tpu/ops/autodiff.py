"""Custom VJPs for the fused TP ops — training through the Pallas path.

The reference never needs this (it is inference-only; SURVEY §2.9), and
its unfused torch autograd could not see it anyway. On TPU the fused
pair is self-transposed:

  AG-GEMM forward   C = allgather(A) @ B      (row-sharded → col-sharded)
  its dA            = reduce_scatter(dC @ Bᵀ)  — exactly GEMM-RS
  GEMM-RS forward   C = reduce_scatter(A @ B)  (col-sharded → row-sharded)
  its dA            = allgather(dC) @ Bᵀ       — exactly AG-GEMM

so the backward of each fused kernel IS the other fused kernel, and a
training step in ``mode="ag_rs"`` runs compute-communication overlap in
both directions. The weight grads (dB = Aᵀ @ dC) contract over the
gathered dim; they are plain local/sharded dots that XLA schedules (a
sharding constraint pins the layout, XLA inserts the gather where one
is needed).

``gemm_ar`` (decode TP, C replicated) backs both grads with purely
local dots — no collective at all in its backward.

Usage: the wrappers are forward-identical to the entries in
``allgather_gemm`` / ``gemm_reduce_scatter`` (they call them), so they
can be substituted anywhere; differentiation only changes what
``jax.grad`` does. ``models.train.make_train_step(mode="ag_rs")``
routes through them.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.ops import all_to_all as _a2a
from triton_dist_tpu.ops import allgather_gemm as _ag
from triton_dist_tpu.ops import gemm_reduce_scatter as _rs
from triton_dist_tpu.ops.common import nestable_shard_map


def _constrain(x, mesh, spec):
    return lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _paired_ctx(src, create_fn, **over):
    """Build the transpose op's context from the forward context.

    Shape-independent knobs carry over (autotune, vmem_budget, debug
    injection where the target has them); the block hints do NOT — the
    backward contracts over different dims, so forward tile sizes would
    be wrong there (each entry re-resolves/clamps per shape anyway).
    """
    dst = create_fn(src.mesh, src.axis, acc_dtype=src.acc_dtype,
                    interpret=src.interpret)
    shared = {"autotune", "vmem_budget", "straggler_option",
              "for_correctness"}
    for f in dataclasses.fields(dst):
        if f.name in shared and hasattr(src, f.name):
            over.setdefault(f.name, getattr(src, f.name))
    return dataclasses.replace(dst, **over)


# -- AG-GEMM (multi-B: the QKV / gate-up shared-gather form) --------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def ag_gemm_multi(a, bs, ctx, impl="pallas"):
    """Differentiable ``allgather_gemm.ag_gemm_multi`` (no
    ``return_gathered`` support — grads need the plain output list)."""
    if ctx.return_gathered:  # not assert: wrong grads if stripped by -O
        raise ValueError("autodiff needs return_gathered=False")
    return tuple(_ag.ag_gemm_multi(a, list(bs), ctx, impl))


def _ag_fwd(a, bs, ctx, impl):
    # Keep bs in its original container: the bwd cotangents must come
    # back in the same pytree structure the caller passed (list/tuple).
    return ag_gemm_multi(a, bs, ctx, impl), (a, bs)


def _ag_bwd(ctx, impl, res, dcs):
    a, bs = res
    rs_ctx = _paired_ctx(ctx, _rs.create_gemm_rs_context)
    # dA = Σ_i RS(dC_i @ B_iᵀ): each term is one fused GEMM-RS kernel
    # (the transpose of this op), accumulated in the input's sharding.
    da = None
    for b, dc in zip(bs, dcs):
        term = _rs.gemm_rs(dc, b.T, rs_ctx, impl=impl)
        da = term if da is None else da + term
    da = _constrain(da.astype(a.dtype), ctx.mesh, P(ctx.axis, None))
    # dB_i = Aᵀ @ dC_i: A's rows (the contraction dim) are sharded, so
    # XLA contracts locally and psums the (K, N_loc) partials — no
    # re-gather of A is required for a col-sharded result.
    dbs = [
        _constrain(jnp.dot(a.T, dc,
                           preferred_element_type=ctx.acc_dtype
                           ).astype(b.dtype),
                   ctx.mesh, P(None, ctx.axis))
        for b, dc in zip(bs, dcs)]
    return da, type(bs)(dbs)


ag_gemm_multi.defvjp(_ag_fwd, _ag_bwd)


def ag_gemm(a, b, ctx, impl="pallas"):
    """Differentiable ``allgather_gemm.ag_gemm``."""
    return ag_gemm_multi(a, (b,), ctx, impl)[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ag_swiglu(a, w_gate, w_up, ctx, impl="pallas"):
    """Differentiable ``allgather_gemm.ag_swiglu`` (fused
    AG + dual GEMM + SwiGLU). Backward recomputes gate/up with one
    fused AG-GEMM pass (standard remat trade: the forward never stored
    them — that is the point of the fusion), then routes dA through the
    fused GEMM-RS transposes exactly like :func:`ag_gemm_multi`'s
    backward."""
    return _ag.ag_swiglu(a, w_gate, w_up, ctx, impl)


def _swiglu_fwd(a, w_gate, w_up, ctx, impl):
    return ag_swiglu(a, w_gate, w_up, ctx, impl), (a, w_gate, w_up)


def _swiglu_bwd(ctx, impl, res, dact):
    a, wg, wu = res
    g, u = _ag.ag_gemm_multi(a, [wg, wu], ctx, impl)   # remat
    g32 = g.astype(jnp.float32)
    u32 = u.astype(jnp.float32)
    d32 = dact.astype(jnp.float32)
    s = jax.nn.sigmoid(g32)
    dg = (d32 * u32 * (s + g32 * s * (1.0 - s))).astype(a.dtype)
    du = (d32 * g32 * s).astype(a.dtype)
    rs_ctx = _paired_ctx(ctx, _rs.create_gemm_rs_context)
    da = (_rs.gemm_rs(dg, wg.T, rs_ctx, impl=impl)
          + _rs.gemm_rs(du, wu.T, rs_ctx, impl=impl))
    da = _constrain(da.astype(a.dtype), ctx.mesh, P(ctx.axis, None))
    dwg = _constrain(jnp.dot(a.T, dg,
                             preferred_element_type=ctx.acc_dtype
                             ).astype(wg.dtype),
                     ctx.mesh, P(None, ctx.axis))
    dwu = _constrain(jnp.dot(a.T, du,
                             preferred_element_type=ctx.acc_dtype
                             ).astype(wu.dtype),
                     ctx.mesh, P(None, ctx.axis))
    return da, dwg, dwu


ag_swiglu.defvjp(_swiglu_fwd, _swiglu_bwd)


# -- GEMM-RS --------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def gemm_rs(a, b, ctx, impl="pallas"):
    """Differentiable ``gemm_reduce_scatter.gemm_rs``."""
    return _rs.gemm_rs(a, b, ctx, impl=impl)


def _rs_fwd(a, b, ctx, impl):
    return gemm_rs(a, b, ctx, impl), (a, b)


def _rs_bwd(ctx, impl, res, dc):
    a, b = res
    ag_ctx = _paired_ctx(ctx, _ag.create_ag_gemm_context,
                         return_gathered=True)
    # dA = AG(dC) @ Bᵀ — one fused AG-GEMM kernel (the transpose of
    # this op); Bᵀ is column-sharded exactly as AG-GEMM wants. The
    # kernel's internal gather is opaque to XLA, so ask it to RETURN
    # the gathered dC (the field exists for exactly this reuse,
    # reference tp_attn workspace sharing) instead of gathering twice.
    da, dc_gathered = _ag.ag_gemm(dc, b.T, ag_ctx, impl=impl)
    da = _constrain(da.astype(a.dtype), ctx.mesh, P(None, ctx.axis))
    # dB = Aᵀ @ AG(dC): every device holds a full dC block in
    # ``dc_gathered`` ((w·M, N), P(axis)) and its own K-columns of A,
    # so the weight grad is one comm-free local dot per device.
    db = nestable_shard_map(
        lambda a_l, g_l: jnp.dot(
            a_l.T, g_l, preferred_element_type=ctx.acc_dtype
        ).astype(b.dtype),
        mesh=ctx.mesh,
        in_specs=(P(None, ctx.axis), P(ctx.axis, None)),
        out_specs=P(ctx.axis, None), check_vma=False)(a, dc_gathered)
    return da, db


gemm_rs.defvjp(_rs_fwd, _rs_bwd)


# -- EP AllToAll ----------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fast_all_to_all(send_buf, send_counts, ctx, impl="pallas"):
    """Differentiable ``all_to_all.fast_all_to_all``.

    The exchange transposes the (rank, slab) matrix — recv slab j on
    rank i is send slab i of rank j — so its adjoint is the SAME
    exchange run on the cotangents, with the forward's ``recv_counts``
    as the send counts (send back exactly what was received). With
    this one rule the whole EP dispatch → experts → combine pipeline
    differentiates (layers/ep_a2a.py: everything else is jnp).
    """
    return _a2a.fast_all_to_all(send_buf, send_counts, ctx, impl)


def _a2a_fwd(send_buf, send_counts, ctx, impl):
    recv_buf, recv_counts = fast_all_to_all(send_buf, send_counts, ctx,
                                            impl)
    return (recv_buf, recv_counts), (recv_counts, send_counts.shape)


def _a2a_bwd(ctx, impl, res, cot):
    recv_counts, counts_shape = res
    d_recv, _ = cot  # counts are int32 → their cotangent is float0
    d_send, back_counts = _a2a.fast_all_to_all(d_recv, recv_counts, ctx,
                                               impl)
    # The Pallas exchange leaves slots past each slab's live count
    # STALE; a cotangent is mathematically zero there, and any NaN
    # would poison upstream weight-grad accumulations (0-primal ×
    # NaN-cotangent), so mask here — in the rule, not in callers.
    from triton_dist_tpu.ops.moe_utils import live_slot_mask

    def mask(buf, counts):
        live = live_slot_mask(counts, buf.shape[0], buf.shape[1])
        return jnp.where(live[..., None], buf, 0)

    d_send = nestable_shard_map(
        mask, mesh=ctx.mesh, in_specs=(P(ctx.axis), P(ctx.axis)),
        out_specs=P(ctx.axis), check_vma=False)(d_send, back_counts)
    d_counts = np.zeros(counts_shape, jax.dtypes.float0)
    return d_send, d_counts


fast_all_to_all.defvjp(_a2a_fwd, _a2a_bwd)


# -- GEMM-AR (decode TP: C replicated) ------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def gemm_ar(a, b, ctx, impl="pallas"):
    """Differentiable ``gemm_reduce_scatter.gemm_ar``."""
    return _rs.gemm_ar(a, b, ctx, impl=impl)


def _ar_fwd(a, b, ctx, impl):
    return gemm_ar(a, b, ctx, impl), (a, b)


def _ar_bwd(ctx, impl, res, dc):
    a, b = res
    # dC is replicated, so both grads are comm-free local dots:
    # dA[:, k_loc] = dC @ Bᵀ[:, k_loc];  dB[k_loc, :] = Aᵀ[k_loc, :] @ dC.
    da = _constrain(jnp.dot(dc, b.T,
                            preferred_element_type=ctx.acc_dtype
                            ).astype(a.dtype),
                    ctx.mesh, P(None, ctx.axis))
    db = _constrain(jnp.dot(a.T, dc,
                            preferred_element_type=ctx.acc_dtype
                            ).astype(b.dtype),
                    ctx.mesh, P(ctx.axis, None))
    return da, db


gemm_ar.defvjp(_ar_fwd, _ar_bwd)
