"""Low-latency AllToAll for expert-parallel dispatch/combine.

TPU-native redesign of the reference's LL AllToAll
(python/triton_dist/kernels/nvidia/low_latency_all_to_all.py: single kernel
doing per-peer ``putmem_nbi_block`` of tokens + splits with
``putmem_signal`` / ``signal_wait_until`` :36-120, context + host entry
``fast_all_to_all`` :127-258) and the train-style dispatch/combine
(ep_a2a.py:37-244).

Data model (static shapes — SURVEY.md §7 "Dynamic shapes in EP"): each
device holds a rank-major send buffer ``(world, capacity, H)`` where slab
``p`` carries the ``send_counts[p]`` rows destined for rank ``p``. The
exchange transposes slabs: after the op, recv slab ``j`` holds the rows
rank ``j`` sent here.

The Pallas path sends each slab in row chunks and only transmits the
chunks that contain live rows — the TPU analog of the reference sending
exactly ``splits[expert]`` tokens per peer rather than the whole MAX_M
buffer. Chunk arrival is signalled per (src, chunk) DMA semaphore
(putmem_signal ≙ remote copy's recv semaphore). Counts are exchanged
first via a (tiny) XLA all-to-all — the analog of the reference's splits
pre-exchange (`get_ag_splits_and_recv_offset_for_dispatch`,
ep_a2a.py:244).

The reference double-buffers by call parity (low_latency_all_to_all.py:
140-143) because its symmetric buffers persist across calls; on TPU each
``pallas_call`` owns its buffers and semaphores start/finish at zero, so
the parity protocol collapses — documented design decision.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.resilience import resilient
from triton_dist_tpu.ops.common import (
    cdiv,
    comm_params,
    maybe_noise,
    maybe_straggle,
    nestable_shard_map,
    record_comm,
    resolve_interpret,
    sync_interpret)


def _default_chunk_rows(capacity: int, itemsize: int = 2) -> int:
    """Largest divisor of ``capacity`` that is ≤128 and sublane-tile-
    aligned for the element width: native tiles are (8/16/32, 128) rows
    for 4/2/1-byte elements, so 1-byte wires (the fp8 path's int8
    transport) only take 32-row-aligned chunk offsets. Falls back to the
    full slab (offset 0 — trivially aligned) when no divisor fits."""
    aligned = {4: (128, 64, 32, 16, 8), 2: (128, 64, 32, 16),
               1: (128, 64, 32)}.get(itemsize, (128, 64, 32))
    for c in aligned:
        if capacity % c == 0:
            return c
    return capacity


@dataclasses.dataclass
class AllToAllContext:
    """Analog of the reference's ``create_all_to_all_context``
    (low_latency_all_to_all.py:127): capacity and chunking config; the
    symmetric send/recv buffers and signal arrays live in the kernel."""
    mesh: Mesh
    axis: str = "ep"
    capacity: int = 128          # max rows per (src, dst) pair
    chunk_rows: int | None = None
    interpret: bool | None = None
    # Correctness-debug injection (reference for_correctness sleeps /
    # straggler_option, low_latency_all_to_all.py): see ops/common.py.
    straggler_option: tuple[int, int] | None = None
    for_correctness: bool = False

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]

    def resolve_chunk(self, itemsize: int = 2) -> int:
        return self.chunk_rows or _default_chunk_rows(self.capacity,
                                                      itemsize)


def create_all_to_all_context(mesh: Mesh | None = None, axis: str = "ep",
                              capacity: int = 128,
                              chunk_rows: int | None = None,
                              interpret: bool | None = None
                              ) -> AllToAllContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return AllToAllContext(mesh=mesh, axis=axis, capacity=capacity,
                           chunk_rows=chunk_rows, interpret=interpret)


# ---------------------------------------------------------------------------
# Schedule helpers — exposed for symbolic execution (the a2a-protocol
# model checker, analysis/a2a_model.py, executes THESE with concrete
# (rank, position) values, exactly as the ring checker executes
# ``ring_chunk_schedule``). The kernel calls the same functions with
# traced values, so checker and kernel cannot drift apart.
# ---------------------------------------------------------------------------

def a2a_send_peer(me, i, world: int):
    """Peer targeted at send position ``i`` (1..world-1): rank-rotated
    right so no two senders hammer one receiver in lockstep (the
    reference staggers per-peer putmem the same way)."""
    return lax.rem(me + i, world)


def a2a_wait_src(me, i, world: int):
    """Source waited on at wait position ``i`` (1..world-1): the
    left-rotation mirror of :func:`a2a_send_peer` — rank me waits
    first on the peer that targeted it first."""
    return lax.rem(me - i + world, world)


def a2a_live_chunks(count, chunk: int):
    """Chunks actually transmitted for a slab with ``count`` live rows
    (``cdiv``; trailing dead rows of a slab never ride the wire)."""
    return lax.div(count + (chunk - 1), chunk)


def a2a_footprint(world: int, capacity: int, h: int,
                  itemsize: int = 2) -> int:
    """Declared VMEM bytes of one ``fast_all_to_all`` dispatch: the
    (world, capacity, H) send slab input + same-shape recv output both
    live whole in VMEM (counts are SMEM; the per-(slab, chunk) DMA
    semaphore arrays are not VMEM). Consumed by the static
    ``vmem-budget`` sweep (analysis/vmem.py)."""
    return 2 * world * capacity * h * itemsize


def _xla_a2a(mesh: Mesh, axis: str, arr: jax.Array) -> jax.Array:
    """Slab-transposing XLA all-to-all on the leading dim — the one
    sideband exchange pattern (counts, scales, expert ids) written once
    (code-review r3e finding 3)."""
    def body(a):
        return lax.all_to_all(a, axis, split_axis=0, concat_axis=0,
                              tiled=True)
    return nestable_shard_map(body, mesh=mesh, in_specs=(P(axis),),
                              out_specs=P(axis), check_vma=False)(arr)


def _a2a_kernel(send_counts_ref, recv_counts_ref, send_ref, recv_ref,
                send_sem, recv_sem, *, axis: str, world: int, capacity: int,
                chunk: int, straggler_option=None, for_correctness=False,
                interp=False):
    """Per-device body: push live chunks of each slab to its peer.

    Per peer p: ``n = cdiv(send_counts[p], chunk)`` chunk DMAs
    ``send[p, c*chunk : (c+1)*chunk] → peer_p.recv[me, ...]`` (reference
    ``putmem_nbi_block`` per expert range, low_latency_all_to_all.py:52-99).
    Then wait ``cdiv(recv_counts[j], chunk)`` arrivals per source j
    (reference ``signal_wait_until`` :108-118). Per-(slab, chunk)
    semaphore slots — no FIFO assumption across chunks.
    """
    me = lax.axis_index(axis)
    n_chunks = capacity // chunk

    # Self slab: plain VMEM copy, no DMA (reference skips rank==me too).
    recv_ref[me] = send_ref[me]
    if world == 1:
        return
    # Peers' recv buffers must exist before remote writes land.
    dl.barrier_all(axis)
    maybe_straggle(straggler_option, axis, interp)
    maybe_noise(for_correctness, axis, world, salt=6, interpret=interp)

    def chunk_copy(p, c):
        # dst slab on peer p is indexed by *our* rank; semaphore slot
        # (me→slab, c) on the receiver.
        return dl.remote_copy(
            send_ref.at[p, pl.ds(c * chunk, chunk), :],
            recv_ref.at[me, pl.ds(c * chunk, chunk), :],
            p, send_sem.at[p, c], recv_sem.at[me, c], axis=axis)

    def send_to(i, _):
        p = a2a_send_peer(me, i, world)
        live = a2a_live_chunks(send_counts_ref[p], chunk)

        def one(c, _):
            @pl.when(c < live)
            def _():
                chunk_copy(p, c).start()
            return _
        lax.fori_loop(0, n_chunks, one, None)
        return _

    lax.fori_loop(1, world, send_to, None)

    def wait_from(i, _):
        j = a2a_wait_src(me, i, world)
        live = a2a_live_chunks(recv_counts_ref[j], chunk)

        def one(c, _):
            @pl.when(c < live)
            def _():
                # Mirror descriptor for the incoming DMA from j.
                dl.remote_copy(
                    send_ref.at[j, pl.ds(c * chunk, chunk), :],
                    recv_ref.at[j, pl.ds(c * chunk, chunk), :],
                    me, send_sem.at[j, c], recv_sem.at[j, c],
                    axis=axis).wait_recv()
            return _
        lax.fori_loop(0, n_chunks, one, None)
        return _

    lax.fori_loop(1, world, wait_from, None)

    def drain(i, _):
        p = a2a_send_peer(me, i, world)
        live = a2a_live_chunks(send_counts_ref[p], chunk)

        def one(c, _):
            @pl.when(c < live)
            def _():
                chunk_copy(p, c).wait_send()
            return _
        lax.fori_loop(0, n_chunks, one, None)
        return _

    lax.fori_loop(1, world, drain, None)


@resilient("all_to_all")
def fast_all_to_all(send_buf: jax.Array, send_counts: jax.Array,
                    ctx: AllToAllContext | None = None,
                    impl: str = "pallas"):
    """Exchange rank-major slabs (functional entry, reference
    ``fast_all_to_all`` low_latency_all_to_all.py:198).

    Args:
      send_buf: (world, capacity, H) per device — slab p goes to rank p.
        Sharded as the *local* buffer of each device (global shape
        (world*world, capacity, H) with leading dim sharded).
      send_counts: (world,) int32 per device (global (world*world,)).

    Returns:
      (recv_buf, recv_counts) with the same layouts; recv slab j came from
      rank j. Rows past ``recv_counts[j]`` in a slab are undefined (the
      reference leaves stale data there too — consumers mask by splits).
    """
    ctx = ctx or create_all_to_all_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    record_comm("all_to_all", send_buf)
    capacity = ctx.capacity
    chunk = ctx.resolve_chunk(send_buf.dtype.itemsize)
    assert capacity % chunk == 0
    assert send_buf.shape[0] == world * world and send_buf.shape[1] == capacity

    if impl == "xla" or world == 1:
        return (_xla_a2a(mesh, axis, send_buf),
                _xla_a2a(mesh, axis, send_counts))

    interpret = resolve_interpret(ctx.interpret)
    kernel = functools.partial(_a2a_kernel, axis=axis, world=world,
                               capacity=capacity, chunk=chunk,
                               straggler_option=ctx.straggler_option,
                               for_correctness=ctx.for_correctness,
                               interp=bool(interpret))
    n_chunks = capacity // chunk

    def body(buf, counts, rcounts):
        recv = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(buf.shape, buf.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.DMA((world, n_chunks)),
                            pltpu.SemaphoreType.DMA((world, n_chunks))],
            compiler_params=comm_params(collective_id=6, world=world),
            interpret=interpret,
        )(counts, rcounts, buf)
        return recv

    def outer(buf, counts):
        rcounts = lax.all_to_all(counts, axis, split_axis=0, concat_axis=0,
                                 tiled=True)
        return body(buf, counts, rcounts), rcounts

    f = nestable_shard_map(outer, mesh=mesh, in_specs=(P(axis), P(axis)),
                      out_specs=(P(axis), P(axis)), check_vma=False)
    return sync_interpret(f(send_buf, send_counts), interpret)


# ---------------------------------------------------------------------------
# FP8-quantized dispatch (the reference's headline LL-a2a configuration:
# 128 tok/rank, hidden 7168, **fp8** + per-token scales — README.md:97,
# low_latency_all_to_all.py:60-99 sends tokens as fp8 blocks and their
# scales via a separate putmem_signal channel).
# ---------------------------------------------------------------------------

_FP8_MAX = 448.0        # float8_e4m3fn finite max


def quantize_fp8_rows(x: jax.Array):
    """Per-row symmetric fp8(e4m3) quantization.

    Returns (q, scales): ``q = fp8(x / scale)`` with
    ``scale = max|row| / 448`` broadcast per leading-row, f32 scales of
    shape ``x.shape[:-1]``. Rows of zeros get scale 1 (exact zeros)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.where(amax > 0, amax / _FP8_MAX, 1.0)
    q = (x.astype(jnp.float32) / scale[..., None]
         ).astype(jnp.float8_e4m3fn)
    return q, scale


def dequantize_fp8_rows(q: jax.Array, scale: jax.Array,
                        dtype=jnp.bfloat16) -> jax.Array:
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fast_all_to_all_fp8(send_buf: jax.Array, send_counts: jax.Array,
                        ctx: AllToAllContext | None = None,
                        impl: str = "pallas"):
    """LL AllToAll at fp8 wire precision: 2x (bf16) / 4x (f32) less ICI
    traffic for the token payload.

    Tokens are row-quantized to float8_e4m3fn, BITCAST to int8 for
    transport (the exchange kernel then only ever moves bytes — no
    Mosaic fp8 arithmetic on the hot path; chunk offsets are 32-row
    aligned for the 1-byte tile via ``resolve_chunk(itemsize=1)``), and
    dequantized with the exchanged scales on arrival. Scales ride the
    sideband XLA all-to-all, the analog of the reference's separate
    scale channel with its own ``putmem_signal``
    (low_latency_all_to_all.py:60-99).

    Inference-only: differentiating through the quantizer is
    meaningless; a jax.grad over this op raises a pointed error instead
    of the opaque bitcast one (use ``wire_dtype=None`` to train).

    Args/returns: as :func:`fast_all_to_all`, plus the received scales
    are folded back in — the result is dequantized to ``send_buf.dtype``.
    Rows past ``recv_counts[j]`` remain undefined.
    """
    ctx = ctx or create_all_to_all_context()
    out_dtype = send_buf.dtype
    q, scale = quantize_fp8_rows(send_buf)
    wire = lax.bitcast_convert_type(q, jnp.int8)
    recv_wire, recv_counts = fast_all_to_all(wire, send_counts, ctx,
                                             impl=impl)
    recv_scale = _xla_a2a(ctx.mesh, ctx.axis, scale)
    recv_q = lax.bitcast_convert_type(recv_wire, jnp.float8_e4m3fn)
    return dequantize_fp8_rows(recv_q, recv_scale, out_dtype), recv_counts


def _fp8_fwd(send_buf, send_counts, ctx, impl):
    return fast_all_to_all_fp8(send_buf, send_counts, ctx, impl), None


def _fp8_bwd(ctx, impl, res, cots):
    raise NotImplementedError(
        "fast_all_to_all_fp8 / wire_dtype='fp8' is inference-only: the "
        "fp8 wire quantizer has no useful gradient. Train with the "
        "plain wire (wire_dtype=None; ops.autodiff.fast_all_to_all).")


fast_all_to_all_fp8.defvjp(_fp8_fwd, _fp8_bwd)
