"""Low-latency AllToAll for expert-parallel dispatch/combine.

TPU-native redesign of the reference's LL AllToAll
(python/triton_dist/kernels/nvidia/low_latency_all_to_all.py: single kernel
doing per-peer ``putmem_nbi_block`` of tokens + splits with
``putmem_signal`` / ``signal_wait_until`` :36-120, context + host entry
``fast_all_to_all`` :127-258) and the train-style dispatch/combine
(ep_a2a.py:37-244).

Data model (static shapes — SURVEY.md §7 "Dynamic shapes in EP"): each
device holds a rank-major send buffer ``(world, capacity, H)`` where slab
``p`` carries the ``send_counts[p]`` rows destined for rank ``p``. The
exchange transposes slabs: after the op, recv slab ``j`` holds the rows
rank ``j`` sent here.

The Pallas path sends each slab in row chunks and only transmits the
chunks that contain live rows — the TPU analog of the reference sending
exactly ``splits[expert]`` tokens per peer rather than the whole MAX_M
buffer. Chunk arrival is signalled per (src, chunk) DMA semaphore
(putmem_signal ≙ remote copy's recv semaphore). Counts are exchanged
first via a (tiny) XLA all-to-all — the analog of the reference's splits
pre-exchange (`get_ag_splits_and_recv_offset_for_dispatch`,
ep_a2a.py:244).

The reference double-buffers by call parity (low_latency_all_to_all.py:
140-143) because its symmetric buffers persist across calls; on TPU each
``pallas_call`` owns its buffers and semaphores start/finish at zero, so
the parity protocol collapses — documented design decision.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.ops.common import (
    cdiv,
    comm_params,
    maybe_noise,
    maybe_straggle,
    nestable_shard_map,
    resolve_interpret,
    sync_interpret)


def _default_chunk_rows(capacity: int) -> int:
    """Largest divisor of ``capacity`` that is ≤128 and sublane-aligned
    (8). Falls back to the full slab when capacity is small/odd."""
    for c in (128, 64, 32, 16, 8):
        if capacity % c == 0:
            return c
    return capacity


@dataclasses.dataclass
class AllToAllContext:
    """Analog of the reference's ``create_all_to_all_context``
    (low_latency_all_to_all.py:127): capacity and chunking config; the
    symmetric send/recv buffers and signal arrays live in the kernel."""
    mesh: Mesh
    axis: str = "ep"
    capacity: int = 128          # max rows per (src, dst) pair
    chunk_rows: int | None = None
    interpret: bool | None = None
    # Correctness-debug injection (reference for_correctness sleeps /
    # straggler_option, low_latency_all_to_all.py): see ops/common.py.
    straggler_option: tuple[int, int] | None = None
    for_correctness: bool = False

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]

    def resolve_chunk(self) -> int:
        return self.chunk_rows or _default_chunk_rows(self.capacity)


def create_all_to_all_context(mesh: Mesh | None = None, axis: str = "ep",
                              capacity: int = 128,
                              chunk_rows: int | None = None,
                              interpret: bool | None = None
                              ) -> AllToAllContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return AllToAllContext(mesh=mesh, axis=axis, capacity=capacity,
                           chunk_rows=chunk_rows, interpret=interpret)


def _a2a_kernel(send_counts_ref, recv_counts_ref, send_ref, recv_ref,
                send_sem, recv_sem, *, axis: str, world: int, capacity: int,
                chunk: int, straggler_option=None, for_correctness=False,
                interp=False):
    """Per-device body: push live chunks of each slab to its peer.

    Per peer p: ``n = cdiv(send_counts[p], chunk)`` chunk DMAs
    ``send[p, c*chunk : (c+1)*chunk] → peer_p.recv[me, ...]`` (reference
    ``putmem_nbi_block`` per expert range, low_latency_all_to_all.py:52-99).
    Then wait ``cdiv(recv_counts[j], chunk)`` arrivals per source j
    (reference ``signal_wait_until`` :108-118). Per-(slab, chunk)
    semaphore slots — no FIFO assumption across chunks.
    """
    me = lax.axis_index(axis)
    n_chunks = capacity // chunk

    # Self slab: plain VMEM copy, no DMA (reference skips rank==me too).
    recv_ref[me] = send_ref[me]
    if world == 1:
        return
    # Peers' recv buffers must exist before remote writes land.
    dl.barrier_all(axis)
    maybe_straggle(straggler_option, axis, interp)
    maybe_noise(for_correctness, axis, world, salt=6, interpret=interp)

    def chunk_copy(p, c):
        # dst slab on peer p is indexed by *our* rank; semaphore slot
        # (me→slab, c) on the receiver.
        return dl.remote_copy(
            send_ref.at[p, pl.ds(c * chunk, chunk), :],
            recv_ref.at[me, pl.ds(c * chunk, chunk), :],
            p, send_sem.at[p, c], recv_sem.at[me, c], axis=axis)

    def send_to(i, _):
        p = lax.rem(me + i, world)
        live = cdiv_dyn(send_counts_ref[p], chunk)

        def one(c, _):
            @pl.when(c < live)
            def _():
                chunk_copy(p, c).start()
            return _
        lax.fori_loop(0, n_chunks, one, None)
        return _

    def cdiv_dyn(a, b):
        return lax.div(a + (b - 1), b)

    lax.fori_loop(1, world, send_to, None)

    def wait_from(i, _):
        j = lax.rem(me - i + world, world)
        live = cdiv_dyn(recv_counts_ref[j], chunk)

        def one(c, _):
            @pl.when(c < live)
            def _():
                # Mirror descriptor for the incoming DMA from j.
                dl.remote_copy(
                    send_ref.at[j, pl.ds(c * chunk, chunk), :],
                    recv_ref.at[j, pl.ds(c * chunk, chunk), :],
                    me, send_sem.at[j, c], recv_sem.at[j, c],
                    axis=axis).wait_recv()
            return _
        lax.fori_loop(0, n_chunks, one, None)
        return _

    lax.fori_loop(1, world, wait_from, None)

    def drain(i, _):
        p = lax.rem(me + i, world)
        live = cdiv_dyn(send_counts_ref[p], chunk)

        def one(c, _):
            @pl.when(c < live)
            def _():
                chunk_copy(p, c).wait_send()
            return _
        lax.fori_loop(0, n_chunks, one, None)
        return _

    lax.fori_loop(1, world, drain, None)


def fast_all_to_all(send_buf: jax.Array, send_counts: jax.Array,
                    ctx: AllToAllContext | None = None,
                    impl: str = "pallas"):
    """Exchange rank-major slabs (functional entry, reference
    ``fast_all_to_all`` low_latency_all_to_all.py:198).

    Args:
      send_buf: (world, capacity, H) per device — slab p goes to rank p.
        Sharded as the *local* buffer of each device (global shape
        (world*world, capacity, H) with leading dim sharded).
      send_counts: (world,) int32 per device (global (world*world,)).

    Returns:
      (recv_buf, recv_counts) with the same layouts; recv slab j came from
      rank j. Rows past ``recv_counts[j]`` in a slab are undefined (the
      reference leaves stale data there too — consumers mask by splits).
    """
    ctx = ctx or create_all_to_all_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    capacity = ctx.capacity
    chunk = ctx.resolve_chunk()
    assert capacity % chunk == 0
    assert send_buf.shape[0] == world * world and send_buf.shape[1] == capacity

    if impl == "xla" or world == 1:
        def body(buf, counts):
            rb = lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                                tiled=True)
            rc = lax.all_to_all(counts, axis, split_axis=0, concat_axis=0,
                                tiled=True)
            return rb, rc
        f = nestable_shard_map(body, mesh=mesh, in_specs=(P(axis), P(axis)),
                          out_specs=(P(axis), P(axis)), check_vma=False)
        return f(send_buf, send_counts)

    interpret = resolve_interpret(ctx.interpret)
    kernel = functools.partial(_a2a_kernel, axis=axis, world=world,
                               capacity=capacity, chunk=chunk,
                               straggler_option=ctx.straggler_option,
                               for_correctness=ctx.for_correctness,
                               interp=bool(interpret))
    n_chunks = capacity // chunk

    def body(buf, counts, rcounts):
        recv = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(buf.shape, buf.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.DMA((world, n_chunks)),
                            pltpu.SemaphoreType.DMA((world, n_chunks))],
            compiler_params=comm_params(collective_id=6, world=world),
            interpret=interpret,
        )(counts, rcounts, buf)
        return recv

    def outer(buf, counts):
        rcounts = lax.all_to_all(counts, axis, split_axis=0, concat_axis=0,
                                 tiled=True)
        return body(buf, counts, rcounts), rcounts

    f = nestable_shard_map(outer, mesh=mesh, in_specs=(P(axis), P(axis)),
                      out_specs=(P(axis), P(axis)), check_vma=False)
    return sync_interpret(f(send_buf, send_counts), interpret)
