"""AllReduce variants over the ICI mesh.

TPU-native redesign of the reference's standalone AllReduce
(python/triton_dist/kernels/nvidia/allreduce.py: 6 device algorithms
:214-683, auto method-by-size :1101, dispatcher ``all_reduce`` :1129,
straggler injection ``_run_straggler`` :137).

Method mapping (reference → TPU):

- one-shot push / one-shot TMA   → ``ONE_SHOT``: every device pushes its
  full buffer to all peers' staging slots; each reduces locally. One hop,
  latency-optimal.
- two-shot push                  → ``TWO_SHOT``: ring reduce-scatter then
  ring all-gather inside one kernel; bandwidth-optimal.
- double-tree                    → ``RECURSIVE_DOUBLING``: log-depth
  XOR-partner exchange (the same latency class; tree topologies
  themselves don't map to ICI neighbor links).
- one/two-shot multimem (NVLS)   → no ICI multicast exists; the XLA
  ``psum`` path is the hardware-tuned equivalent. Documented gap.

Straggler injection (reference allreduce.py:137) is supported via
``straggler_option=(rank, cycles)`` — that rank spins ``pl.delay`` before
communicating, to expose missing waits under stress tests.
"""

from __future__ import annotations

import dataclasses
import enum
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.resilience import resilient
from triton_dist_tpu.ops.common import (
    comm_params,
    nestable_shard_map,
    record_comm,
    resolve_interpret,
    sync_interpret)


class AllReduceMethod(enum.Enum):
    AUTO = "auto"
    ONE_SHOT = "one_shot"
    TWO_SHOT = "two_shot"
    # Log-depth exchange (the latency class of the reference's
    # double-tree, allreduce.py:214-683 double-tree rows): requires a
    # power-of-two world.
    RECURSIVE_DOUBLING = "recursive_doubling"


def get_auto_allreduce_method(world_size: int, nbytes: int,
                              spec=None) -> AllReduceMethod:
    """Perf-model-driven selection (reference allreduce.py:1101-1127
    picks from measured bandwidth models): one-shot's single full-buffer
    exchange wins at small payloads; the two-shot RS+AG decomposition
    moves 2·nbytes/w per link instead of (w-1)·nbytes and wins once
    bandwidth-bound."""
    from triton_dist_tpu.tools.perf_model import estimate_all_reduce_time_ms
    if world_size <= 2:
        return AllReduceMethod.ONE_SHOT
    t_one = estimate_all_reduce_time_ms(nbytes, world_size, spec,
                                        method="one_shot")
    t_two = estimate_all_reduce_time_ms(nbytes, world_size, spec,
                                        method="two_shot")
    return (AllReduceMethod.ONE_SHOT if t_one <= t_two
            else AllReduceMethod.TWO_SHOT)


@dataclasses.dataclass
class AllReduceContext:
    mesh: Mesh
    axis: str = "tp"
    method: AllReduceMethod = AllReduceMethod.AUTO
    interpret: bool | None = None
    # (rank, delay_cycles) — that rank delays before communicating
    # (reference straggler_option / _run_straggler, allreduce.py:137).
    straggler_option: tuple[int, int] | None = None

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]


def create_allreduce_context(mesh: Mesh | None = None, axis: str = "tp",
                             method: AllReduceMethod = AllReduceMethod.AUTO,
                             interpret: bool | None = None,
                             straggler_option=None) -> AllReduceContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return AllReduceContext(mesh=mesh, axis=axis, method=method,
                            interpret=interpret,
                            straggler_option=straggler_option)


def _maybe_straggle(straggler_option, axis):
    if straggler_option is None:
        return
    rank, cycles = straggler_option

    @pl.when(lax.axis_index(axis) == rank)
    def _():
        pl.delay(cycles)


def _one_shot_ar_kernel(x_ref, o_ref, stage_ref, send_sem, recv_sem, *,
                        axis: str, world: int, straggler_option=None):
    """Push my full buffer to every peer's stage[me]; sum all stages
    (reference one-shot push kernel, allreduce.py:214-300)."""
    me = lax.axis_index(axis)
    stage_ref[me] = x_ref[:]
    if world == 1:
        o_ref[:] = x_ref[:]
        return
    _maybe_straggle(straggler_option, axis)
    dl.barrier_all(axis)

    def send(p, _):
        peer = lax.rem(me + p, world)
        dl.remote_copy(x_ref, stage_ref.at[me], peer,
                       send_sem.at[peer], recv_sem.at[me], axis=axis).start()
        return _

    lax.fori_loop(1, world, send, None)

    def wait_recv(p, _):
        src = lax.rem(me - p + world, world)
        dl.remote_copy(x_ref, stage_ref.at[src], me,
                       send_sem.at[src], recv_sem.at[src],
                       axis=axis).wait_recv()
        return _

    lax.fori_loop(1, world, wait_recv, None)

    acc = stage_ref[0]
    for p in range(1, world):
        acc = acc + stage_ref[p]
    o_ref[:] = acc

    def wait_send(p, _):
        peer = lax.rem(me + p, world)
        dl.remote_copy(x_ref, stage_ref.at[me], peer,
                       send_sem.at[peer], recv_sem.at[me],
                       axis=axis).wait_send()
        return _

    lax.fori_loop(1, world, wait_send, None)


def _recursive_doubling_ar_kernel(x_ref, o_ref, send_stage, recv_stage,
                                  send_sem, recv_sem, *, axis: str,
                                  world: int, straggler_option=None):
    """Log-depth allreduce: step j exchanges the running partial with
    partner ``me XOR 2^j`` and adds — log2(w) hops of the full buffer.

    The TPU answer to the reference's double-tree kernels (log-latency
    class, allreduce.py:214-683): on a torus the XOR partner at step j is
    2^j links away, so total traffic matches one-shot but the incast is
    pairwise (2 flows/link) instead of (w-1)-way. The exchange is
    symmetric: both partners use step-slot j, so one descriptor serves
    start (my push), wait_recv (partner's delivery into my stage) and
    wait_send (my push drained)."""
    me = lax.axis_index(axis)
    o_ref[:] = x_ref[:]
    if world == 1:
        return
    n_steps = world.bit_length() - 1
    _maybe_straggle(straggler_option, axis)
    dl.barrier_all(axis)

    cps = []
    for j in range(n_steps):                 # static log2(w) unroll
        partner = jnp.bitwise_xor(me, 1 << j)
        send_stage[j] = o_ref[:]
        cp = dl.remote_copy(send_stage.at[j], recv_stage.at[j], partner,
                            send_sem.at[j], recv_sem.at[j], axis=axis)
        cp.start()
        cp.wait_recv()                       # partner's partial landed
        o_ref[:] = o_ref[:] + recv_stage[j]
        cps.append(cp)
    for cp in cps:
        cp.wait_send()


def _two_shot_ar_kernel(x_ref, o_ref, send_buf, recv_buf, send_sem, recv_sem,
                        ag_send_sem, ag_recv_sem, *, axis: str, world: int,
                        rows: int, straggler_option=None):
    """Ring reduce-scatter + ring all-gather in one kernel (reference
    two-shot push, allreduce.py:301-430). Bandwidth-optimal: each element
    crosses each link twice. Per-step buffers/semaphores — see
    _ring_rs_kernel for why reuse races."""
    me = lax.axis_index(axis)
    right = lax.rem(me + 1, world)

    if world == 1:
        o_ref[:] = x_ref[:]
        return
    _maybe_straggle(straggler_option, axis)
    dl.barrier_all(axis)

    # Phase 1: ring reduce-scatter of my (M, N) into my chunk [me].
    def rs_copy(s):
        return dl.remote_copy(send_buf.at[s], recv_buf.at[s], right,
                              send_sem.at[s], recv_sem.at[s], axis=axis)

    def rs_step(s, _):
        send_idx = lax.rem(me - s - 1 + world, world)

        @pl.when(s == 0)
        def _():
            send_buf[s] = x_ref[pl.ds(send_idx * rows, rows), :]

        @pl.when(s > 0)
        def _():
            send_buf[s] = (recv_buf[jnp.maximum(s - 1, 0)] +
                           x_ref[pl.ds(send_idx * rows, rows), :])

        rs_copy(s).start()
        rs_copy(s).wait_recv()
        return _

    lax.fori_loop(0, world - 1, rs_step, None)
    o_ref[pl.ds(me * rows, rows), :] = (recv_buf[world - 2] +
                                        x_ref[pl.ds(me * rows, rows), :])

    # Phase 2: ring all-gather of the reduced chunks (per-chunk semaphores;
    # o_ref chunk slots are naturally distinct so no staging needed).
    def ag_copy(idx):
        return dl.remote_copy(
            o_ref.at[pl.ds(idx * rows, rows), :],
            o_ref.at[pl.ds(idx * rows, rows), :],
            right, ag_send_sem.at[idx], ag_recv_sem.at[idx], axis=axis)

    def ag_step(s, _):
        ag_copy(lax.rem(me - s + world, world)).start()
        ag_copy(lax.rem(me - s - 1 + world, world)).wait_recv()
        return _

    lax.fori_loop(0, world - 1, ag_step, None)

    def drain(s, _):
        rs_copy(s).wait_send()
        ag_copy(lax.rem(me - s + world, world)).wait_send()
        return _

    lax.fori_loop(0, world - 1, drain, None)


@resilient("allreduce")
def all_reduce(x: jax.Array, ctx: AllReduceContext | None = None,
               impl: str = "pallas", stacked: bool = False) -> jax.Array:
    """Sum per-device partials; every device receives the total.

    Input: (w, M, N) sharded on dim 0 (one partial per device). Output:
    (M, N) replicated — or (w, M, N) stacked copies with ``stacked=True``.
    Dispatcher analog of reference ``all_reduce`` (allreduce.py:1129).
    """
    ctx = ctx or create_allreduce_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    record_comm("allreduce", x)
    assert x.shape[0] == world, (x.shape, world)
    m, n = x.shape[1], x.shape[2]
    method = ctx.method
    if method is AllReduceMethod.AUTO:
        method = get_auto_allreduce_method(world, m * n * x.dtype.itemsize)
    if method is AllReduceMethod.TWO_SHOT and m % world != 0:
        method = AllReduceMethod.ONE_SHOT
    if (method is AllReduceMethod.RECURSIVE_DOUBLING
            and world & (world - 1)):
        method = AllReduceMethod.ONE_SHOT    # needs power-of-two world

    out_spec = P(axis) if stacked else P()

    if impl == "xla":
        def body(xs):
            r = lax.psum(xs[0], axis)
            return r[None] if stacked else r
        f = nestable_shard_map(body, mesh=mesh, in_specs=P(axis),
                          out_specs=out_spec, check_vma=False)
        return f(x)

    interpret = resolve_interpret(ctx.interpret)

    if method is AllReduceMethod.ONE_SHOT:
        kernel = functools.partial(_one_shot_ar_kernel, axis=axis,
                                   world=world,
                                   straggler_option=ctx.straggler_option)
        scratch = [pltpu.VMEM((world, m, n), x.dtype),
                   pltpu.SemaphoreType.DMA((world,)),
                   pltpu.SemaphoreType.DMA((world,))]
    elif method is AllReduceMethod.RECURSIVE_DOUBLING:
        n_steps = max(world.bit_length() - 1, 1)
        kernel = functools.partial(
            _recursive_doubling_ar_kernel, axis=axis, world=world,
            straggler_option=ctx.straggler_option)
        scratch = [pltpu.VMEM((n_steps, m, n), x.dtype),
                   pltpu.VMEM((n_steps, m, n), x.dtype),
                   pltpu.SemaphoreType.DMA((n_steps,)),
                   pltpu.SemaphoreType.DMA((n_steps,))]
    else:
        rows = m // world
        kernel = functools.partial(_two_shot_ar_kernel, axis=axis,
                                   world=world, rows=rows,
                                   straggler_option=ctx.straggler_option)
        scratch = [pltpu.VMEM((world - 1, rows, n), x.dtype),
                   pltpu.VMEM((world - 1, rows, n), x.dtype),
                   pltpu.SemaphoreType.DMA((world - 1,)),
                   pltpu.SemaphoreType.DMA((world - 1,)),
                   pltpu.SemaphoreType.DMA((world,)),
                   pltpu.SemaphoreType.DMA((world,))]

    def body(xs):
        r = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=scratch,
            compiler_params=comm_params(collective_id=3, world=world),
            interpret=interpret,
        )(xs[0])
        return r[None] if stacked else r

    f = nestable_shard_map(body, mesh=mesh, in_specs=P(axis),
                      out_specs=out_spec, check_vma=False)
    return sync_interpret(f(x), interpret)
