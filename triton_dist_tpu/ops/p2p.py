"""Point-to-point pipeline-parallel transfers.

TPU-native redesign of the reference's PP p2p kernels
(python/triton_dist/kernels/nvidia/p2p.py: ``p2p_copy_kernel`` push :31 /
pull :54 — one-sided copies between pp ranks' symmetric buffers, with
per-rank set/wait signals).

On an ICI mesh a pipeline hop is a neighbor transfer:

- ``impl="xla"``    — ``lax.ppermute`` shift along the pp axis (XLA
  schedules it asynchronously; this is the idiomatic path).
- ``impl="pallas"`` — explicit remote DMA kernel: each device pushes its
  buffer to the next stage and waits the incoming DMA's recv semaphore
  (the signal set/wait protocol of the reference collapses into the DMA
  semaphore pair).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.resilience import resilient
from triton_dist_tpu.ops.common import (
    comm_params,
    nestable_shard_map,
    resolve_interpret,
    sync_interpret)


@dataclasses.dataclass
class P2PContext:
    mesh: Mesh
    axis: str = "pp"
    interpret: bool | None = None

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]


def create_p2p_context(mesh: Mesh | None = None, axis: str = "pp",
                       interpret: bool | None = None) -> P2PContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return P2PContext(mesh=mesh, axis=axis, interpret=interpret)


def shift_partners(me, delta: int, world: int):
    """(dst, src) of one pipeline hop: push to ``me+delta``, receive
    from ``me-delta``. Exposed for symbolic execution — the
    p2p-protocol model checker (analysis/p2p_model.py) executes this
    with concrete ranks, exactly as the ring checker executes
    ``ring_chunk_schedule``; the kernel calls it with traced values so
    the two cannot drift apart."""
    span = (abs(delta) // world + 1) * world    # keep lax.rem args >= 0
    return (lax.rem(me + delta + span, world),
            lax.rem(me - delta + span, world))


def _shift_kernel(x_ref, o_ref, send_sem, recv_sem, *, axis: str,
                  world: int, delta: int):
    """Push local buffer to rank (me+delta); receive from (me-delta)."""
    me = lax.axis_index(axis)
    dst, src = shift_partners(me, delta, world)
    dl.barrier_all(axis)
    dl.remote_copy(x_ref.at[:], o_ref.at[:], dst, send_sem, recv_sem,
                   axis=axis).start()
    # Mirror descriptor: wait for the DMA arriving from src.
    dl.remote_copy(x_ref.at[:], o_ref.at[:], me, send_sem, recv_sem,
                   axis=axis).wait_recv()
    dl.remote_copy(x_ref.at[:], o_ref.at[:], dst, send_sem, recv_sem,
                   axis=axis).wait_send()


@resilient("pp_shift")
def pp_shift(x: jax.Array, ctx: P2PContext | None = None, delta: int = 1,
             impl: str = "pallas") -> jax.Array:
    """Shift per-stage activations one pipeline hop (functional entry;
    reference ``p2p_copy_kernel`` push, p2p.py:31).

    Args:
      x: (stages, ...) with the leading dim sharded over the pp axis —
        each stage's activation block.
      delta: +1 forward (stage i → i+1), -1 backward.
    Returns:
      same layout; stage i now holds what stage i-delta had. The wrap
      entry (stage 0 for delta=+1) carries stage w-1's buffer — pipeline
      schedulers treat it as the bubble slot.
    """
    ctx = ctx or create_p2p_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    if world == 1:
        return x

    if impl == "xla":
        perm = [(i, (i + delta) % world) for i in range(world)]

        def body(xs):
            return lax.ppermute(xs, axis, perm)
        return nestable_shard_map(body, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis), check_vma=False)(x)

    interpret = resolve_interpret(ctx.interpret)
    kernel = functools.partial(_shift_kernel, axis=axis, world=world,
                               delta=delta)

    def body(xs):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(xs.shape, xs.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.DMA,
                            pltpu.SemaphoreType.DMA],
            compiler_params=comm_params(collective_id=8, world=world),
            interpret=interpret,
        )(xs)

    out = nestable_shard_map(body, mesh=mesh, in_specs=P(axis),
                        out_specs=P(axis), check_vma=False)(x)
    return sync_interpret(out, interpret)
