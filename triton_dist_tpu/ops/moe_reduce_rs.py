"""Fused MoE second-projection + topk-reduce + ReduceScatter.

TPU-native redesign of the reference's MoE-RS
(python/triton_dist/kernels/nvidia/moe_reduce_rs.py: grouped GEMM producer
gathering rows by top-k assignment :167, topk-reduce kernels :293/:380,
dispatcher ``moe_reduce_rs`` :546).

Math: per device, activations ``act`` (T*topk, I/w) hold one row per
(token, k) pair against the local intermediate shard; ``w_down``
(E, I/w, H). The op computes the per-pair down-projection (grouped GEMM),
reduces over top-k with routing weights, and reduce-scatters the
rank-partial sums so each device ends with its T/w token rows.

``impl="ring"`` is the overlapped schedule: the ring reduce-scatter is
interleaved with per-row-block grouped dots — block c's MXU work happens
at the step its accumulator passes through this rank, so every ICI hop
rides under compute (the reference's producer GEMM + ring-reduce consumer
split, moe_reduce_rs.py:380-546, re-expressed as a collective matmul).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.ops.group_gemm import grouped_matmul
from triton_dist_tpu.ops.moe_utils import topk_reduce


@dataclasses.dataclass
class MoEReduceRSContext:
    """Analog of ``create_moe_rs_context`` (moe_reduce_rs.py): mesh/axis +
    topology; workspaces collapse into the traced program."""
    mesh: Mesh
    axis: str = "tp"
    num_experts: int = 8
    topk: int = 2

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]


def create_moe_rs_context(mesh: Mesh | None = None, axis: str = "tp",
                          num_experts: int = 8, topk: int = 2
                          ) -> MoEReduceRSContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return MoEReduceRSContext(mesh=mesh, axis=axis, num_experts=num_experts,
                              topk=topk)


def moe_reduce_rs(act: jax.Array, w_down: jax.Array, expert_ids: jax.Array,
                  weights: jax.Array, ctx: MoEReduceRSContext,
                  impl: str = "ring") -> jax.Array:
    """out = reduce_scatter( topk_reduce( grouped_gemm(act, w_down) ) ).

    Args:
      act: (T*topk, I) with I sharded over ``ctx.axis`` (each device holds
        its I/w slice of every pair row).
      w_down: (E, I, H), I sharded the same way.
      expert_ids: (T*topk,) int32, replicated.
      weights: (T, topk) routing weights, replicated.
    Returns:
      (T/w, H) row-sharded token outputs (reference ``moe_reduce_rs``
      :546 returns the same layout).
    """
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    tk = act.shape[0]
    t, topk = weights.shape
    assert tk == t * topk
    assert t % world == 0
    rows = t // world
    n_exp = ctx.num_experts

    def pair_down(a_shard, wd, ids):
        """(T*topk, I/w) → per-token rank-partial (T, H)."""
        partial = grouped_matmul(a_shard, wd, ids, n_exp)
        return topk_reduce(partial.reshape(t, topk, -1), weights)

    def oneshot(a_shard, wd, ids, wts):
        del wts
        tok = pair_down(a_shard, wd, ids)
        return lax.psum_scatter(tok, axis, scatter_dimension=0, tiled=True)

    def ring(a_shard, wd, ids, wts):
        me = lax.axis_index(axis)
        h = wd.shape[-1]
        perm = [(i, (i + 1) % world) for i in range(world)]

        def block_partial(c):
            """Rank-partial down-proj of token row block c ((T/w, H))."""
            sl_act = lax.dynamic_slice_in_dim(
                a_shard.reshape(t, topk, -1), c * rows, rows, 0
            ).reshape(rows * topk, -1)
            sl_ids = lax.dynamic_slice_in_dim(
                ids.reshape(t, topk), c * rows, rows, 0).reshape(-1)
            sl_w = lax.dynamic_slice_in_dim(wts, c * rows, rows, 0)
            part = grouped_matmul(sl_act, wd, sl_ids, n_exp)
            return topk_reduce(part.reshape(rows, topk, h), sl_w)

        def step(s, acc):
            c = lax.rem(me + world - 1 - s, world)
            nxt = lax.ppermute(acc, axis, perm)  # overlaps the dots below
            mine = block_partial(c).astype(jnp.float32)
            return jnp.where(s == 0, mine, nxt + mine)

        acc = lax.fori_loop(0, world, step,
                            jnp.zeros((rows, h), jnp.float32))
        return acc.astype(act.dtype)

    body = oneshot if (impl == "xla" or world == 1) else ring
    f = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(None, axis), P(None, axis, None), P(), P()),
        out_specs=P(axis), check_vma=False)
    return f(act, w_down, expert_ids, weights)
