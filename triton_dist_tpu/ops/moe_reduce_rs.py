"""Fused MoE second-projection + topk-reduce + ReduceScatter.

TPU-native redesign of the reference's MoE-RS
(python/triton_dist/kernels/nvidia/moe_reduce_rs.py: grouped GEMM producer
gathering rows by top-k assignment :167, topk-reduce kernels :293/:380,
dispatcher ``moe_reduce_rs`` :546).

Math: per device, activations ``act`` (T*topk, I/w) hold one row per
(token, k) pair against the local intermediate shard; ``w_down``
(E, I/w, H). The op computes the per-pair down-projection (grouped GEMM),
reduces over top-k with routing weights, and reduce-scatters the
rank-partial sums so each device ends with its T/w token rows.

``impl="ring"`` is the overlapped schedule: the ring reduce-scatter is
interleaved with per-row-block grouped dots — block c's MXU work happens
at the step its accumulator passes through this rank, so every ICI hop
rides under compute (the reference's producer GEMM + ring-reduce consumer
split, moe_reduce_rs.py:380-546, re-expressed as a collective matmul).

Why ring is the TPU default (VERDICT r3 next-8, measured on chip r3:
fused 3.191 ms vs ring 2.217 ms at T=2048, topk=2, I=4096, H=4096):

* **MXU occupancy.** The fused kernel folds the topk scatter-reduce
  into a second MXU dot against a (rows, m_blk) selection tile — the
  only scatter-free formulation a TPU kernel has (strided VPU scatter
  adds would serialize). That dot costs ``rows / I_loc`` extra FLOPs
  relative to the down-projection itself (~50% at serving shapes where
  T ≈ I), plus expert-alignment padding (~1.25x at T*topk=4096, E=8,
  m_blk=128). The ring instead lets XLA run the grouped GEMM as
  ``ragged_dot`` (dense MXU tiles over expert-sorted rows) and the
  topk-reduce as a segment-sum at full VPU width — no selection matmul,
  no per-tile padding.
* **Comm volume is identical** ((w-1)/w · T·H per device either way),
  and the ring's ppermute hop rides under the next block's dots just
  like the fused kernel's remote DMA — there is no overlap the fused
  form adds that the ring lacks.
* The GPU reference wins with its fused form because CUDA atomics make
  the scatter-reduce free and its grouped GEMM reads gathered rows at
  full bandwidth (moe_reduce_rs.py:167-380); neither property holds on
  TPU. Hence: ring default, fused kept and selectable — ``impl="auto"``
  measures both once per shape (tools/autotuner, disk-cached) and picks
  the winner, so shapes where ``rows << I_loc`` (deep EP slicing) can
  still choose the fused kernel.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.resilience import resilient
from triton_dist_tpu.ops.common import (
    DEFAULT_VMEM_BUDGET,
    any_spec,
    comm_params,
    nestable_shard_map,
    resolve_interpret,
    sync_interpret)
from triton_dist_tpu.ops.group_gemm import (
    align_tokens_for_tiles, grouped_matmul)
from triton_dist_tpu.ops.moe_utils import topk_reduce


def _moe_rs_fused_kernel(act_hbm, w_hbm, sel_hbm, te_ref, o_hbm, send_hbm,
                         recv_hbm, a_tile, b_panel, sel_tile, acc, r_tile,
                         c_stage, a_sem, b_sem, s_sem, r_sem, c_sem,
                         send_sem, recv_sem, *, axis: str, world: int,
                         rows: int, m_pad: int, i_loc: int, h: int,
                         m_blk: int, h_blk: int):
    """Single-kernel MoE second-projection + topk-reduce + ring RS.

    The TPU answer to the reference's fused producer/reducer
    (moe_reduce_rs.py:167-546, VERDICT r2 next 7 second half): per ring
    step the kernel computes one token-chunk's rank-partial — streaming
    expert-aligned (m_blk, I_loc) pair tiles through VMEM, one full-K
    dot per tile with the expert's resident (I_loc, h_blk) down-proj
    panel — and folds the topk scatter-reduce into a second small MXU
    dot against a precomputed (rows, m_blk) routing-weight selection
    tile (≈ rows/I_loc extra FLOPs, no in-kernel scatter). The reduced
    chunk rides the ring under the next chunk's compute, exactly the
    GEMM-RS schedule.
    """
    me = lax.axis_index(axis)
    right = lax.rem(me + 1, world)
    m_tiles = m_pad // m_blk
    n_blocks = h // h_blk
    per = n_blocks * m_tiles

    def rs_copy(s):
        return dl.remote_copy(send_hbm.at[s], recv_hbm.at[s], right,
                              send_sem.at[s], recv_sem.at[s], axis=axis)

    def chunk_gemm(chunk, s, dst):
        def tile_of(i):
            return chunk * m_tiles + lax.rem(i, m_tiles)

        def a_dma(slot, i):
            row0 = chunk * m_pad + lax.rem(i, m_tiles) * m_blk
            return pltpu.make_async_copy(
                act_hbm.at[pl.ds(row0, m_blk), :], a_tile.at[slot],
                a_sem.at[slot])

        def sel_dma(slot, i):
            return pltpu.make_async_copy(
                sel_hbm.at[tile_of(i)], sel_tile.at[slot], s_sem.at[slot])

        def b_dma(slot, i):
            e = te_ref[tile_of(i)]
            return pltpu.make_async_copy(
                w_hbm.at[e, :, pl.ds((i // m_tiles) * h_blk, h_blk)],
                b_panel.at[slot], b_sem.at[slot])

        def need_b(i):
            prev = jnp.maximum(i - 1, 0)
            return (lax.rem(i, m_tiles) == 0) | (
                te_ref[tile_of(i)] != te_ref[tile_of(prev)])

        def r_dma(nb):
            return pltpu.make_async_copy(
                recv_hbm.at[jnp.maximum(s - 1, 0), :,
                            pl.ds(nb * h_blk, h_blk)],
                r_tile, r_sem)

        def c_dma(nb):
            return pltpu.make_async_copy(
                c_stage, dst.at[:, pl.ds(nb * h_blk, h_blk)], c_sem)

        a_dma(0, 0).start()
        sel_dma(0, 0).start()
        b_dma(0, 0).start()

        def istep(i, cur):
            # ``cur`` = slot of the current B panel; the next reload is
            # prefetched one tile ahead so panel fetches overlap dots
            # (code-review r3b finding 4).
            slot = lax.rem(i, 2)
            nb = i // m_tiles

            @pl.when(i + 1 < per)
            def _():
                a_dma(lax.rem(i + 1, 2), i + 1).start()
                sel_dma(lax.rem(i + 1, 2), i + 1).start()

            @pl.when((lax.rem(i, m_tiles) == 0) & (s > 0))
            def _():
                r_dma(nb).start()   # travelling partial for this h-block

            nb_i = need_b(i)

            @pl.when(nb_i)
            def _():
                b_dma(1 - cur, i).wait()
            cur = jnp.where(nb_i, 1 - cur, cur)

            @pl.when((i + 1 < per) & need_b(i + 1))
            def _():
                b_dma(1 - cur, i + 1).start()   # prefetch next panel

            a_dma(slot, i).wait()
            sel_dma(slot, i).wait()
            pair_out = jnp.dot(a_tile[slot], b_panel[cur],
                               preferred_element_type=jnp.float32)
            contrib = jnp.dot(sel_tile[slot], pair_out,
                              preferred_element_type=jnp.float32)

            @pl.when(lax.rem(i, m_tiles) == 0)
            def _():
                acc[:] = contrib

            @pl.when(lax.rem(i, m_tiles) > 0)
            def _():
                acc[:] = acc[:] + contrib

            @pl.when(lax.rem(i, m_tiles) == m_tiles - 1)
            def _():
                @pl.when(nb > 0)
                def _():
                    c_dma(nb - 1).wait()

                @pl.when(s > 0)
                def _():
                    r_dma(nb).wait()
                    c_stage[:] = (acc[:] + r_tile[:].astype(jnp.float32)
                                  ).astype(c_stage.dtype)

                @pl.when(s == 0)
                def _():
                    c_stage[:] = acc[:].astype(c_stage.dtype)
                c_dma(nb).start()
            return cur

        lax.fori_loop(0, per, istep, jnp.int32(1))
        c_dma(n_blocks - 1).wait()

    if world == 1:
        chunk_gemm(jnp.int32(0), jnp.int32(0), o_hbm)
        return

    dl.barrier_all(axis)

    def rs_step(s, _):
        send_idx = lax.rem(me - s - 1 + world, world)

        @pl.when(s > 0)
        def _():
            rs_copy(jnp.maximum(s - 1, 0)).wait_recv()
        chunk_gemm(send_idx, s, send_hbm.at[s])
        rs_copy(s).start()
        return _

    lax.fori_loop(0, world - 1, rs_step, None)
    rs_copy(world - 2).wait_recv()
    chunk_gemm(me, jnp.int32(world - 1), o_hbm)

    def drain(s, _):
        rs_copy(s).wait_send()
        return _

    lax.fori_loop(0, world - 1, drain, None)


def moe_rs_fused_footprint(m_blk: int, i_loc: int, h_blk: int,
                           rows: int, itemsize: int) -> int:
    """Declared VMEM bytes of the fused kernel's scratch at one tile
    config: double-buffered (m_blk, I_loc) pair tiles + (I_loc, h_blk)
    down-proj panels, f32 selection tiles and accumulator, and the
    travelling-partial / output stages. This is the exact expression
    the kernel entry clamps ``h_blk`` against and the static
    ``vmem-budget`` sweep (analysis/vmem.py) vets — one formula, two
    consumers, so they cannot drift."""
    return ((2 * m_blk * i_loc + 2 * i_loc * h_blk) * itemsize
            + 4 * (2 * rows * m_blk + rows * h_blk)
            + 2 * rows * h_blk * itemsize)


def moe_rs_resolve_h_blk(h: int, block_h: int, m_blk: int, i_loc: int,
                         rows: int, itemsize: int, budget: int) -> int:
    """The h-block the fused kernel will actually run: ``block_h``
    halved until it divides ``h``, then halved (floor 128) until the
    declared footprint fits ``budget`` — mirrored by the static sweep
    so the vet prices the kernel's real tiling, not the requested
    one."""
    h_blk = block_h
    while h_blk > h or h % h_blk:
        h_blk //= 2
    h_blk = max(h_blk, 1)
    while h_blk > 128 and moe_rs_fused_footprint(
            m_blk, i_loc, h_blk, rows, itemsize) > budget:
        h_blk //= 2
    return h_blk


@dataclasses.dataclass
class MoEReduceRSContext:
    """Analog of ``create_moe_rs_context`` (moe_reduce_rs.py): mesh/axis +
    topology; workspaces collapse into the traced program."""
    mesh: Mesh
    axis: str = "tp"
    num_experts: int = 8
    topk: int = 2
    interpret: bool | None = None
    # Tile sizes for the fused Pallas kernel (impl="fused").
    block_m: int = 128
    block_h: int = 512
    vmem_budget: int = DEFAULT_VMEM_BUDGET

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]


def create_moe_rs_context(mesh: Mesh | None = None, axis: str = "tp",
                          num_experts: int = 8, topk: int = 2
                          ) -> MoEReduceRSContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return MoEReduceRSContext(mesh=mesh, axis=axis, num_experts=num_experts,
                              topk=topk)


#: impl="auto" winners keyed by problem shape (in-process; the autotuner
#: adds the cross-run disk cache).
_IMPL_TUNED: dict = {}


@resilient("moe_reduce_rs", fused_impls=("fused", "auto"))
def moe_reduce_rs(act: jax.Array, w_down: jax.Array, expert_ids: jax.Array,
                  weights: jax.Array, ctx: MoEReduceRSContext,
                  impl: str = "ring") -> jax.Array:
    """out = reduce_scatter( topk_reduce( grouped_gemm(act, w_down) ) ).

    Args:
      act: (T*topk, I) with I sharded over ``ctx.axis`` (each device holds
        its I/w slice of every pair row).
      w_down: (E, I, H), I sharded the same way.
      expert_ids: (T*topk,) int32, replicated.
      weights: (T, topk) routing weights, replicated.
      impl: "ring" (default; see module docstring for why) | "fused" |
        "xla" | "auto" (measure ring vs fused once per shape, cached).
    Returns:
      (T/w, H) row-sharded token outputs (reference ``moe_reduce_rs``
      :546 returns the same layout).
    """
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    tk = act.shape[0]
    t, topk = weights.shape
    assert tk == t * topk
    assert t % world == 0
    rows = t // world
    n_exp = ctx.num_experts

    def pair_down(a_shard, wd, ids):
        """(T*topk, I/w) → per-token rank-partial (T, H)."""
        partial = grouped_matmul(a_shard, wd, ids, n_exp)
        return topk_reduce(partial.reshape(t, topk, -1), weights)

    def oneshot(a_shard, wd, ids, wts):
        del wts
        tok = pair_down(a_shard, wd, ids)
        return lax.psum_scatter(tok, axis, scatter_dimension=0, tiled=True)

    def ring(a_shard, wd, ids, wts):
        me = lax.axis_index(axis)
        h = wd.shape[-1]
        perm = [(i, (i + 1) % world) for i in range(world)]

        def block_partial(c):
            """Rank-partial down-proj of token row block c ((T/w, H))."""
            sl_act = lax.dynamic_slice_in_dim(
                a_shard.reshape(t, topk, -1), c * rows, rows, 0
            ).reshape(rows * topk, -1)
            sl_ids = lax.dynamic_slice_in_dim(
                ids.reshape(t, topk), c * rows, rows, 0).reshape(-1)
            sl_w = lax.dynamic_slice_in_dim(wts, c * rows, rows, 0)
            part = grouped_matmul(sl_act, wd, sl_ids, n_exp)
            return topk_reduce(part.reshape(rows, topk, h), sl_w)

        def step(s, acc):
            c = lax.rem(me + world - 1 - s, world)
            nxt = lax.ppermute(acc, axis, perm)  # overlaps the dots below
            mine = block_partial(c).astype(jnp.float32)
            return jnp.where(s == 0, mine, nxt + mine)

        acc = lax.fori_loop(0, world, step,
                            jnp.zeros((rows, h), jnp.float32))
        return acc.astype(act.dtype)

    if impl == "auto":
        shape_key = (t, topk, act.shape[1], w_down.shape[-1], n_exp, world)
        tune_key = f"moe_rs_impl:{shape_key}"
        choice = _IMPL_TUNED.get(shape_key)
        if choice is None and not isinstance(act, jax.core.Tracer):
            from triton_dist_tpu.tools.autotuner import autotune
            from triton_dist_tpu.runtime.utils import make_perturbed_runner

            def make_fn(impl):
                fn = jax.jit(lambda a: moe_reduce_rs(
                    a, w_down, expert_ids, weights, ctx, impl=impl))
                return make_perturbed_runner(fn, act)

            res = autotune(make_fn, [{"impl": "ring"}, {"impl": "fused"}],
                           key=tune_key, iters=8, warmup_iters=2)
            choice = _IMPL_TUNED[shape_key] = res.config["impl"]
        elif choice is None:
            # Traced call (no eager sweep possible): a prior run's
            # winner in the autotuner's disk cache still counts — the
            # docstring's "measured once per shape, disk-cached"
            # promise must hold under jit too (review r4b-5).
            from triton_dist_tpu.tools.autotuner import (
                consult_disk_for_trace)
            hit = consult_disk_for_trace(tune_key)
            if hit is not None:
                choice = _IMPL_TUNED[shape_key] = hit.config["impl"]
        impl = choice or "ring"   # no sweep, no cache: ring default

    if impl == "fused":
        return _moe_rs_fused(act, w_down, expert_ids, weights, ctx)

    body = oneshot if (impl == "xla" or world == 1) else ring
    f = nestable_shard_map(
        body, mesh=mesh,
        in_specs=(P(None, axis), P(None, axis, None), P(), P()),
        out_specs=P(axis), check_vma=False)
    return f(act, w_down, expert_ids, weights)


def _moe_rs_fused(act, w_down, expert_ids, weights, ctx):
    """Entry for :func:`_moe_rs_fused_kernel`: builds the expert-aligned
    pair layout and the per-tile routing-weight selection tensors
    (traced; the analog of the reference's gather_a_ptrs + topk-reduce
    planning, moe_reduce_rs.py:167-380), then runs the single kernel."""
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    t, topk = weights.shape
    rows = t // world
    n_exp = ctx.num_experts
    m_blk = ctx.block_m
    pairs = rows * topk
    from triton_dist_tpu.ops.common import round_up
    m_pad = round_up(pairs + n_exp * (m_blk - 1), m_blk) + m_blk
    m_tiles = m_pad // m_blk
    interpret = resolve_interpret(ctx.interpret)

    def body(a_shard, wd, ids, wts):
        i_loc = a_shard.shape[1]
        h = wd.shape[-1]
        item = a_shard.dtype.itemsize
        h_blk = moe_rs_resolve_h_blk(h, ctx.block_h, m_blk, i_loc,
                                     rows, item, ctx.vmem_budget)

        # Per token-chunk alignment (identical on every device: ids and
        # weights are replicated; only the I-slice of act differs).
        a_chunks = a_shard.reshape(world, pairs, i_loc)
        id_chunks = ids.reshape(world, pairs)
        padded, tile_e, dest = jax.vmap(
            lambda av, iv: align_tokens_for_tiles(av, iv, n_exp, m_blk)
        )(a_chunks, id_chunks)
        padded_all = padded.reshape(world * m_pad, i_loc)
        te_all = tile_e.reshape(world * m_tiles)

        # Selection tensors: sel[tile, tok, col] = routing weight of the
        # pair that landed at aligned position (tile, col), for its
        # token row within the chunk; 0 elsewhere.
        p_idx = jnp.arange(pairs)
        chunk_idx = jnp.arange(world)[:, None]
        tile_idx = chunk_idx * m_tiles + dest // m_blk       # (w, pairs)
        col_idx = dest % m_blk
        tok_idx = jnp.broadcast_to(p_idx // topk, (world, pairs))
        w_vals = wts.reshape(world, rows, topk).reshape(world, pairs)
        sel = jnp.zeros((world * m_tiles, rows, m_blk), jnp.float32)
        sel = sel.at[tile_idx.ravel(), tok_idx.ravel(),
                     col_idx.ravel()].add(w_vals.ravel())

        kernel = functools.partial(
            _moe_rs_fused_kernel, axis=axis, world=world, rows=rows,
            m_pad=m_pad, i_loc=i_loc, h=h, m_blk=m_blk, h_blk=h_blk)

        out, *_ = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((rows, h), act.dtype),
                jax.ShapeDtypeStruct((max(world - 1, 1), rows, h),
                                     act.dtype),
                jax.ShapeDtypeStruct((max(world - 1, 1), rows, h),
                                     act.dtype)),
            in_specs=[any_spec(), any_spec(), any_spec(),
                      pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=(any_spec(),) * 3,
            scratch_shapes=[
                pltpu.VMEM((2, m_blk, i_loc), act.dtype),
                pltpu.VMEM((2, i_loc, h_blk), act.dtype),
                pltpu.VMEM((2, rows, m_blk), jnp.float32),
                pltpu.VMEM((rows, h_blk), jnp.float32),
                pltpu.VMEM((rows, h_blk), act.dtype),
                pltpu.VMEM((rows, h_blk), act.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA((max(world - 1, 1),)),
                pltpu.SemaphoreType.DMA((max(world - 1, 1),)),
            ],
            compiler_params=comm_params(collective_id=9, world=world),
            interpret=interpret,
        )(padded_all, wd, sel, te_all)
        return out

    f = nestable_shard_map(
        body, mesh=mesh,
        in_specs=(P(None, axis), P(None, axis, None), P(), P()),
        out_specs=P(axis), check_vma=False)
    return sync_interpret(f(act, w_down, expert_ids, weights), interpret)
