"""AllGather variants over the ICI mesh.

TPU-native redesign of the reference's copy-engine AllGather family
(python/triton_dist/kernels/nvidia/allgather.py: ``AllGatherMethod`` enum
:46-73, per-variant producers :81-370, device put kernels :380-470).

The reference picks among full-mesh push/pull, 1-D ring, 2-D numa ring and
broadcast based on NVLink topology. On a TPU torus the natural methods are:

- ``RING_1D``     — neighbor ring over the mesh axis; each hop rides one ICI
  link. Bandwidth-optimal for large payloads.
- ``RING_BIDIR``  — both ring directions at once (ICI links are full
  duplex): halves the number of steps. The analog of the reference's 2-D
  ring exploiting extra links.
- ``FULL_MESH_PUSH`` — every device puts its shard directly to all peers;
  minimizes latency for small payloads (analog of reference full-mesh
  push, allgather.py:81-170).
- ``AUTO``        — size-based choice (analog of
  ``get_auto_all_gather_method``, allgather.py:46-73).

Implementations: ``impl="xla"`` lowers to ``jax.lax.all_gather`` (golden /
fallback); ``impl="pallas"`` is the explicit remote-DMA kernel.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.resilience import resilient
from triton_dist_tpu.ops.common import (
    comm_params,
    maybe_noise,
    maybe_straggle,
    nestable_shard_map,
    record_comm,
    resolve_interpret,
    sync_interpret)


class AllGatherMethod(enum.Enum):
    AUTO = "auto"
    RING_1D = "ring_1d"
    RING_BIDIR = "ring_bidir"
    FULL_MESH_PUSH = "full_mesh_push"
    # One source rank pushes its buffer to every peer (reference
    # low_latency_allgather.py broadcast variants :48-210).
    BROADCAST = "broadcast"


# LL (flag-in-data) packet mapping: the reference's low-latency AG packs
# an 8-byte flag into each 16-byte data quantum so the receiver can spin
# on the DATA buffer instead of a separate signal
# (low_latency_allgather.py:531-549 _pack_ll_block/_recv_ll_block) — an
# artifact of NVLink writes carrying no completion signal. On TPU the
# transport signals the receiver's DMA semaphore ON DELIVERY of each
# remote copy, so every `impl="pallas"` method here already has LL
# semantics: the per-chunk recv-semaphore wait IS the flag spin, with no
# bandwidth tax and no two-pass packing. The 2d/3d multinode variants
# (:48-780) map to ops/hierarchical.all_gather_2d (ICI x DCN two-level).


def get_auto_all_gather_method(world_size: int, nbytes_per_rank: int,
                               spec=None) -> AllGatherMethod:
    """Perf-model-driven method choice (reference
    get_auto_all_gather_method allgather.py:46-73 picks from probed
    bandwidth models, comm_perf_model.py:94-116): full-mesh push wins
    when its single-launch latency beats the ring's per-step fixed
    costs; the bidirectional ring wins once payloads are
    bandwidth-bound (through-traffic makes full-mesh scale as w·w/4
    hops)."""
    from triton_dist_tpu.tools.perf_model import (
        estimate_all_gather_time_ms, estimate_full_mesh_push_time_ms)
    if world_size <= 2:
        return AllGatherMethod.FULL_MESH_PUSH
    t_fm = estimate_full_mesh_push_time_ms(nbytes_per_rank, world_size,
                                           spec)
    t_ring = estimate_all_gather_time_ms(nbytes_per_rank, world_size,
                                         spec, bidir=True)
    return (AllGatherMethod.FULL_MESH_PUSH if t_fm <= t_ring
            else AllGatherMethod.RING_BIDIR)


@dataclasses.dataclass
class AllGatherContext:
    """Per-op context (reference ``create_ag_context`` pattern: the reference
    allocates symmetric workspaces here; on TPU the kernel's output buffer
    *is* the symmetric workspace, so the context carries config only)."""
    mesh: Mesh
    axis: str = "tp"
    method: AllGatherMethod = AllGatherMethod.AUTO
    interpret: bool | None = None
    # Correctness-debug injection (reference for_correctness sleeps
    # allgather.py:74-79 and straggler_option): see ops/common.py.
    straggler_option: tuple[int, int] | None = None
    for_correctness: bool = False

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]

    def resolve_method(self, nbytes_per_rank: int) -> AllGatherMethod:
        if self.method is AllGatherMethod.AUTO:
            return get_auto_all_gather_method(self.world_size,
                                              nbytes_per_rank)
        return self.method


def create_allgather_context(mesh: Mesh | None = None, axis: str = "tp",
                             method: AllGatherMethod = AllGatherMethod.AUTO,
                             interpret: bool | None = None) -> AllGatherContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return AllGatherContext(mesh=mesh, axis=axis, method=method,
                            interpret=interpret)


# ---------------------------------------------------------------------------
# Pallas kernels (per-device bodies under shard_map)
# ---------------------------------------------------------------------------

def _ring_ag_kernel(x_ref, o_ref, send_sem, recv_sem, *, axis: str,
                    world: int, rows: int, bidir: bool,
                    straggler_option=None, for_correctness=False,
                    interp=False):
    """Ring all-gather. Unidirectional: w-1 hops to the right.
    Bidirectional: chunks travel the shorter way round; ceil((w-1)/2) steps.

    Analog of the reference's ring copy chain (allgather.py:232-370) with
    the copy engine replaced by in-kernel remote DMA (SURVEY.md §5:
    copy-engine AG ≙ RDMA inside the kernel)."""
    me = lax.axis_index(axis)
    right = lax.rem(me + 1, world)
    left = lax.rem(me - 1 + world, world)

    o_ref[pl.ds(me * rows, rows), :] = x_ref[:]
    if world == 1:
        return
    # Peers must have written their own chunk (and exist) before remote
    # writes into their o_ref land.
    dl.barrier_all(axis)
    maybe_straggle(straggler_option, axis, interp)
    maybe_noise(for_correctness, axis, world, salt=1, interpret=interp)

    n_fwd = (world - 1 + 1) // 2 if bidir else world - 1
    n_bwd = (world - 1) - n_fwd if bidir else 0

    # Semaphore slots are PER CHUNK, not per step: delivery is not assumed
    # FIFO, and a fast upstream neighbor may run several steps ahead. With
    # one reused semaphore its later-chunk signal could satisfy an earlier
    # wait and we would forward a not-yet-arrived region (the reference
    # avoids the same race with per-(rank,segment) flags, allgather.py
    # set_ready/wait protocol).
    def chunk_copy(idx, peer, direction):
        return dl.remote_copy(
            o_ref.at[pl.ds(idx * rows, rows), :],
            o_ref.at[pl.ds(idx * rows, rows), :],
            peer, send_sem.at[idx], recv_sem.at[direction, idx], axis=axis)

    def step(s, _):
        fwd_idx = lax.rem(me - s + world, world)
        fwd_recv = lax.rem(me - s - 1 + world, world)

        # Start both directions before waiting on either: the two copies
        # ride opposite (full-duplex) ICI links concurrently.
        @pl.when(s < n_fwd)
        def _():
            chunk_copy(fwd_idx, right, 0).start()

        if bidir:
            bwd_idx = lax.rem(me + s, world)
            bwd_recv = lax.rem(me + s + 1, world)

            @pl.when(s < n_bwd)
            def _():
                chunk_copy(bwd_idx, left, 1).start()

            @pl.when(s < n_bwd)
            def _():
                # wait for the chunk arriving from the RIGHT (it travels
                # leftwards); it is next step's bwd send.
                chunk_copy(bwd_recv, left, 1).wait_recv()

        @pl.when(s < n_fwd)
        def _():
            # chunk arriving from the LEFT; next step's fwd send.
            chunk_copy(fwd_recv, right, 0).wait_recv()
        return _

    lax.fori_loop(0, max(n_fwd, n_bwd), step, None)

    # Drain send completions so the kernel does not retire with DMAs in
    # flight.
    def drain(s, _):
        @pl.when(s < n_fwd)
        def _():
            chunk_copy(lax.rem(me - s + world, world), right, 0).wait_send()
        if bidir:
            @pl.when(s < n_bwd)
            def _():
                chunk_copy(lax.rem(me + s, world), left, 1).wait_send()
        return _

    lax.fori_loop(0, max(n_fwd, n_bwd), drain, None)


def _broadcast_kernel(x_ref, o_ref, send_sem, recv_sem, *, axis: str,
                      world: int, root: int):
    """Root pushes its full buffer to every peer (reference LL-AG
    broadcast, low_latency_allgather.py:48-210). Non-root ranks just
    wait for delivery on their recv semaphore (the LL flag analog)."""
    me = lax.axis_index(axis)

    @pl.when(me == root)
    def _():
        o_ref[...] = x_ref[...]
    if world == 1:
        return
    dl.barrier_all(axis)

    def copy_to(peer):
        return dl.remote_copy(o_ref, o_ref, peer, send_sem.at[peer],
                              recv_sem, axis=axis)

    @pl.when(me == root)
    def _():
        def send(p, _):
            peer = lax.rem(root + p, world)
            copy_to(peer).start()
            return _
        lax.fori_loop(1, world, send, None)

        def drain(p, _):
            copy_to(lax.rem(root + p, world)).wait_send()
            return _
        lax.fori_loop(1, world, drain, None)

    @pl.when(me != root)
    def _():
        copy_to(me).wait_recv()


def _full_mesh_push_kernel(x_ref, o_ref, send_sem, recv_sem, *, axis: str,
                           world: int, rows: int, straggler_option=None,
                           for_correctness=False, interp=False):
    """Every device puts its chunk to all peers (reference full-mesh push,
    allgather.py:81-170). Latency-optimal: one hop, w-1 concurrent DMAs."""
    me = lax.axis_index(axis)
    o_ref[pl.ds(me * rows, rows), :] = x_ref[:]
    if world == 1:
        return
    dl.barrier_all(axis)
    maybe_straggle(straggler_option, axis, interp)
    maybe_noise(for_correctness, axis, world, salt=2, interpret=interp)

    def send(p, _):
        peer = lax.rem(me + p, world)
        dl.remote_copy(
            o_ref.at[pl.ds(me * rows, rows), :],
            o_ref.at[pl.ds(me * rows, rows), :],
            peer, send_sem.at[peer], recv_sem.at[me], axis=axis).start()
        return _

    lax.fori_loop(1, world, send, None)

    def wait_one(p, _):
        src = lax.rem(me - p + world, world)
        # Mirror descriptor: wait for the copy that src issued into our
        # recv_sem[src] slot (standard Pallas pattern for waiting on an
        # incoming remote DMA).
        dl.remote_copy(
            o_ref.at[pl.ds(src * rows, rows), :],
            o_ref.at[pl.ds(src * rows, rows), :],
            me, send_sem.at[src], recv_sem.at[src], axis=axis).wait_recv()
        return _

    lax.fori_loop(1, world, wait_one, None)

    def wait_send(p, _):
        peer = lax.rem(me + p, world)
        dl.remote_copy(
            o_ref.at[pl.ds(me * rows, rows), :],
            o_ref.at[pl.ds(me * rows, rows), :],
            peer, send_sem.at[peer], recv_sem.at[me], axis=axis).wait_send()
        return _

    lax.fori_loop(1, world, wait_send, None)


# ---------------------------------------------------------------------------
# Functional entry
# ---------------------------------------------------------------------------

@resilient("allgather")
def all_gather(x: jax.Array, ctx: AllGatherContext | None = None,
               impl: str = "pallas", stacked: bool = False) -> jax.Array:
    """Gather ``x`` (sharded on dim 0 over ``ctx.axis``) onto every device.

    Functional entry (reference ``cp_engine_producer_all_gather_*`` host
    wrappers). Returns the gathered array, replicated — or, with
    ``stacked=True``, with a leading per-device dim (w, M, N) so tests can
    check every device's copy.
    """
    ctx = ctx or create_allgather_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    record_comm("allgather", x)
    assert x.shape[0] % world == 0, (x.shape, world)
    rows = x.shape[0] // world
    method = ctx.resolve_method(
        rows * x.dtype.itemsize * math.prod(x.shape[1:]))

    out_spec = P(axis) if stacked else P()

    if impl == "xla":
        def body(xs):
            g = lax.all_gather(xs, axis, tiled=True)
            return g
        f = nestable_shard_map(body, mesh=mesh, in_specs=P(axis),
                          out_specs=out_spec, check_vma=False)
        return f(x)

    interpret = resolve_interpret(ctx.interpret)

    if method is AllGatherMethod.BROADCAST:
        raise ValueError(
            "BROADCAST is one-to-all, not an all-gather — call "
            "ops.allgather.broadcast(x, root, ctx) instead")

    inject = dict(straggler_option=ctx.straggler_option,
                  for_correctness=ctx.for_correctness,
                  interp=bool(interpret))
    if method in (AllGatherMethod.RING_1D, AllGatherMethod.RING_BIDIR):
        kernel = functools.partial(
            _ring_ag_kernel, axis=axis, world=world, rows=rows,
            bidir=method is AllGatherMethod.RING_BIDIR, **inject)
        scratch = [pltpu.SemaphoreType.DMA((world,)),
                   pltpu.SemaphoreType.DMA((2, world))]
    else:
        kernel = functools.partial(
            _full_mesh_push_kernel, axis=axis, world=world, rows=rows,
            **inject)
        scratch = [pltpu.SemaphoreType.DMA((world,)),
                   pltpu.SemaphoreType.DMA((world,))]

    def body(xs):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=scratch,
            compiler_params=comm_params(collective_id=1, world=world),
            interpret=interpret,
        )(xs)

    f = nestable_shard_map(body, mesh=mesh, in_specs=P(axis),
                      out_specs=out_spec, check_vma=False)
    return sync_interpret(f(x), interpret)


@resilient("broadcast")
def broadcast(x: jax.Array, root: int = 0,
              ctx: AllGatherContext | None = None,
              impl: str = "pallas") -> jax.Array:
    """Rank ``root``'s row-chunk of ``x`` on every device (reference
    LL-AG broadcast variants, low_latency_allgather.py:48-210).

    Args:
      x: (w·M, N) row-sharded over ``ctx.axis`` — chunk r is rank r's
        buffer.
    Returns:
      (M, N) — the root's chunk, replicated.
    """
    ctx = ctx or create_allgather_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    record_comm("broadcast", x)
    assert x.shape[0] % world == 0
    if not 0 <= root < world:
        raise ValueError(f"root {root} out of range for world {world}")
    rows = x.shape[0] // world

    if impl == "xla":
        def body(xs):
            src = jnp.zeros((world,), x.dtype).at[root].set(1).reshape(
                (world,) + (1,) * (x.ndim - 1)).astype(x.dtype)
            return lax.psum(xs * src[lax.axis_index(axis)], axis)
        f = nestable_shard_map(body, mesh=mesh, in_specs=P(axis),
                          out_specs=P(), check_vma=False)
        return f(x)

    interpret = resolve_interpret(ctx.interpret)
    kernel = functools.partial(_broadcast_kernel, axis=axis, world=world,
                               root=root)

    def body(xs):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((rows,) + x.shape[1:], x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.DMA((world,)),
                            pltpu.SemaphoreType.DMA],
            compiler_params=comm_params(collective_id=1, world=world),
            interpret=interpret,
        )(xs)

    f = nestable_shard_map(body, mesh=mesh, in_specs=P(axis),
                      out_specs=P(), check_vma=False)
    return sync_interpret(f(x), interpret)
